"""Headline benchmark: ResNet-50 v1 training throughput (img/s).

Baseline (BASELINE.md, docs/faq/perf.md:214-217 of the reference):
MXNet 1.2 ResNet-50 fp32 training on one V100, batch 128 = 363.69 img/s.
Secondary (docs/faq/perf.md:155,171): ResNet-50 *scoring*, V100 fp16,
batch 32 = 2085.51 img/s — measured here as `extra.score_*`.

TPU-native configuration (see PERF.md for the trace-driven derivation):
  - layout NHWC: channels ride the 128-lane minor dim; no layout
    transposes around convs (vs ~11% slower NCHW, measured)
  - mixed precision via ShardedTrainer(compute_dtype="bfloat16"):
    weights/activations bf16 on the MXU, fp32 master params, fp32 BN
    statistics, fp32 softmax inner (measured 1.9x vs fp32)
  - one fused XLA program per step (fwd+bwd+SGD update) built by
    parallel.ShardedTrainer; synthetic data staged on-device, like the
    reference's `--benchmark 1` mode (image-classification/common/fit.py)

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", "extra"}.
"""
import json
import os
import time

import numpy as np

BASELINE_IMG_S = 363.69
SCORE_BASELINE_FP16 = 2085.51
# env overrides exist for CI smoke only; the driver runs the defaults
BATCH = int(os.environ.get("MXTPU_BENCH_BATCH", 128))
SCORE_BATCH = int(os.environ.get("MXTPU_BENCH_SCORE_BATCH", 32))
IMG = int(os.environ.get("MXTPU_BENCH_IMG", 224))
STEPS = int(os.environ.get("MXTPU_BENCH_STEPS", 50))


def _apply_platform_override():
    """MXTPU_BENCH_PLATFORM=cpu pins the backend via jax.config (for CI
    smoke runs — the env-var spelling can still race plugin discovery
    on machines with a configured accelerator tunnel)."""
    plat = os.environ.get("MXTPU_BENCH_PLATFORM")
    if plat:
        import jax
        jax.config.update("jax_platforms", plat)


def _probe_devices(timeout_s=180):
    """Probe + recovery (the recorded metric must be a real measurement
    or a clean error, never a hang — and round 3 proved one failed
    probe shouldn't be the end: recover, then retry).

    Each probe runs in a FRESH interpreter: a PJRT init that timed out
    leaves this process's jax wedged on the init lock, so an in-process
    retry can never succeed. Between attempts, reap stale framework
    processes that may be blocking the device lease (tools/kill_stale.py,
    the reference kill-mxnet.py role) and back off — relay-side lease
    wedges clear with time, not force.
    """
    import subprocess
    import sys
    retries = int(os.environ.get("MXTPU_BENCH_PROBE_RETRIES", 3))
    waits = (45, 90, 180)
    plat = os.environ.get("MXTPU_BENCH_PLATFORM")
    pin = ("import jax; jax.config.update('jax_platforms', %r); " % plat
           if plat else "")
    code = (pin + "from mxnet_tpu.base import probe_devices; import sys; "
            "d, e = probe_devices(%d); "
            "sys.stderr.write('' if d else str(e)); "
            "sys.exit(0 if d else 1)" % timeout_s)
    err = "?"
    here = os.path.dirname(os.path.abspath(__file__))
    for attempt in range(max(retries, 1)):
        try:
            # belt over the in-child deadline: if the child itself wedges
            # (e.g. PJRT init stuck in a C call holding the GIL so even
            # interpreter shutdown hangs), reap it here
            r = subprocess.run([sys.executable, "-c", code], cwd=here,
                               capture_output=True, text=True,
                               timeout=timeout_s + 60)
        except subprocess.TimeoutExpired:
            err = "probe child wedged past %ds" % (timeout_s + 60)
        else:
            if r.returncode == 0:
                # do the PARENT's backend init under the same deadline:
                # this process hasn't attempted init yet, so the probe
                # both guards and performs it (a wedge in the window
                # after the child's clean exit would otherwise hang the
                # unguarded jax.devices() below)
                from mxnet_tpu.base import probe_devices
                devs, perr = probe_devices(timeout_s)
                if devs is not None:
                    return True
                raise SystemExit(
                    "bench: probe child ok but parent init failed (%s)"
                    % perr)
            err = ((r.stderr or "").strip().splitlines() or ["?"])[-1]
        if attempt + 1 >= max(retries, 1):
            break
        sys.stderr.write("bench: probe %d failed (%s); cleaning stale "
                         "processes and retrying\n" % (attempt + 1, err))
        ks = subprocess.run([sys.executable,
                             os.path.join(here, "tools", "kill_stale.py"),
                             "--kill"], capture_output=True, text=True)
        for line in (ks.stdout + ks.stderr).splitlines():
            sys.stderr.write("bench:   kill_stale: %s\n" % line)
        time.sleep(waits[min(attempt, len(waits) - 1)])
    raise SystemExit("bench: device backend unreachable after %d probes "
                     "(%s)" % (max(retries, 1), err))


def main():
    _apply_platform_override()
    _probe_devices()
    import jax
    jax.config.update("jax_default_matmul_precision", "bfloat16")
    import mxnet_tpu as mx
    from mxnet_tpu import gluon
    from mxnet_tpu.gluon.model_zoo import vision
    from mxnet_tpu.parallel import make_mesh, ShardedTrainer

    n_dev = len(jax.devices())
    mesh = make_mesh({"dp": n_dev})

    net = vision.resnet50_v1(classes=1000, layout="NHWC")
    # materialize parameters WITHOUT an eager forward (which would
    # trigger ~180 separate accelerator compiles over the device link):
    # symbolic shape inference + deferred-init finish. Prefer the host
    # CPU backend for the initializer ops when it exists (it is absent
    # under JAX_PLATFORMS=axon/tpu-only configurations).
    import contextlib
    try:
        mat_ctx = jax.default_device(jax.local_devices(backend="cpu")[0])
    except Exception:
        mat_ctx = contextlib.nullcontext()
    with mat_ctx:
        net.initialize()
        net.infer_shape(mx.nd.zeros((1, IMG, IMG, 3)))
        for p in net.collect_params().values():
            p._finish_deferred_init()

    loss = gluon.loss.SoftmaxCrossEntropyLoss()
    st = ShardedTrainer(net, lambda o, l: loss(o, l), "sgd",
                        {"learning_rate": 0.1, "momentum": 0.9},
                        mesh=mesh, compute_dtype="bfloat16")

    rng = np.random.RandomState(0)
    # stage the synthetic batch on-device ONCE (the input pipeline's job;
    # re-uploading 77MB per step would measure the host link, not the TPU)
    sh = st._batch_sharding()
    x = jax.device_put(rng.randn(BATCH, IMG, IMG, 3).astype("float32"), sh)
    y = jax.device_put((rng.rand(BATCH) * 1000).astype("float32"), sh)

    # ALL timed steps run inside ONE jitted lax.scan (step_many): one
    # dispatch per window, forced by fetching the losses to host —
    # device_get is the only reliable fence on remote/tunneled backends
    # (block_until_ready can return before remote execution completes).
    unroll = int(os.environ.get("MXTPU_BENCH_UNROLL", 10))

    def run_window(n):
        losses = st.step_many(x, y, n_steps=n, unroll=min(unroll, n))
        out = np.asarray(jax.device_get(losses._data))
        assert np.isfinite(out).all(), "non-finite loss in bench window"
        return out

    run_window(STEPS)  # compile + warm (same shape/unroll as timed run)
    t0 = time.perf_counter()
    run_window(STEPS)
    dt = time.perf_counter() - t0
    img_s = BATCH * STEPS / dt

    # secondary: inference scoring at the reference's benchmark_score.py
    # config (batch 32), bf16 like the V100 fp16 row
    import jax.numpy as jnp
    params = {k: (v.astype(jnp.bfloat16) if v.ndim >= 2 else v)
              for k, v in st.params.items()}
    aux = dict(st._aux)
    from mxnet_tpu.graph import build_graph_fn
    out_sym = net(mx.sym.var("data"))
    score_fn, _, _, _ = build_graph_fn(out_sym._entries, "predict")

    @jax.jit
    def score(params, aux, xb):
        outs, _ = score_fn({**params, "data": xb.astype(jnp.bfloat16)}, aux)
        return outs[0]

    xs = jax.device_put(
        rng.randn(SCORE_BATCH, IMG, IMG, 3).astype("float32"))
    n_score = 30

    @jax.jit
    def score_window(params, aux, xb):
        # n_score forwards in one program; each iteration perturbs the
        # input by a function of the previous logits so XLA cannot
        # collapse the loop, mirroring a feed of distinct batches
        def body(i, carry):
            xb, acc = carry
            out = score(params, aux, xb)
            return (xb + out.mean().astype(xb.dtype) * 1e-12,
                    acc + out.astype(jnp.float32).mean())
        _, acc = jax.lax.fori_loop(0, n_score, body, (xb, jnp.float32(0)))
        return acc

    np.asarray(jax.device_get(score_window(params, aux, xs)))  # compile
    t0 = time.perf_counter()
    np.asarray(jax.device_get(score_window(params, aux, xs)))
    sdt = time.perf_counter() - t0
    score_img_s = SCORE_BATCH * n_score / sdt

    print(json.dumps({
        "metric": "resnet50_v1_train_throughput_b%d" % BATCH,
        "value": round(img_s, 2), "unit": "img/s",
        "vs_baseline": round(img_s / BASELINE_IMG_S, 3),
        "extra": {
            "score_b%d_img_s" % SCORE_BATCH: round(score_img_s, 2),
            "score_vs_v100_fp16": round(score_img_s / SCORE_BASELINE_FP16,
                                        3),
        }}))


if __name__ == "__main__":
    main()
