"""Headline benchmark: ResNet-50 v1 training throughput (img/s).

Baseline (BASELINE.md, docs/faq/perf.md:214-217 of the reference):
MXNet 1.2 ResNet-50 fp32 training on one V100, batch 128 = 363.69 img/s.

This runs the same workload TPU-natively: one fused XLA program per step
(forward+backward+SGD update) built by parallel.ShardedTrainer on however
many local devices exist (one real TPU chip under the driver). Synthetic
data, like the reference's `--benchmark 1` mode
(example/image-classification/common/fit.py).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""
import json
import time

import numpy as np

BASELINE_IMG_S = 363.69
BATCH = 128
IMG = 224
WARMUP = 3
STEPS = 10


def main():
    import jax
    # MXU-native conv/matmul passes (industry-standard bf16 training
    # numerics; params/BN stats stay fp32)
    jax.config.update("jax_default_matmul_precision", "bfloat16")
    import mxnet_tpu as mx
    from mxnet_tpu import gluon
    from mxnet_tpu.gluon.model_zoo import vision
    from mxnet_tpu.parallel import make_mesh, ShardedTrainer

    n_dev = len(jax.devices())
    mesh = make_mesh({"dp": n_dev})

    net = vision.resnet50_v1(classes=1000)
    net.initialize()
    net(mx.nd.zeros((1, 3, IMG, IMG)))  # materialize shapes

    loss = gluon.loss.SoftmaxCrossEntropyLoss()
    st = ShardedTrainer(net, lambda o, l: loss(o, l), "sgd",
                        {"learning_rate": 0.1, "momentum": 0.9},
                        mesh=mesh)

    rng = np.random.RandomState(0)
    # stage the synthetic batch on-device ONCE (the input pipeline's job;
    # re-uploading 77MB per step would measure the host link, not the TPU)
    sh = st._batch_sharding()
    x = jax.device_put(rng.randn(BATCH, 3, IMG, IMG).astype("float32"), sh)
    y = jax.device_put((rng.rand(BATCH) * 1000).astype("float32"), sh)

    for _ in range(WARMUP):
        st.step(x, y).wait_to_read()
    t0 = time.perf_counter()
    for _ in range(STEPS):
        l = st.step(x, y)
    l.wait_to_read()
    dt = time.perf_counter() - t0

    img_s = BATCH * STEPS / dt
    print(json.dumps({"metric": "resnet50_v1_train_throughput_b%d" % BATCH,
                      "value": round(img_s, 2), "unit": "img/s",
                      "vs_baseline": round(img_s / BASELINE_IMG_S, 3)}))


if __name__ == "__main__":
    main()
