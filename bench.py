"""Headline benchmark: ResNet-50 v1 training throughput (img/s).

Baseline (BASELINE.md, docs/faq/perf.md:214-217 of the reference):
MXNet 1.2 ResNet-50 fp32 training on one V100, batch 128 = 363.69 img/s.
Secondary (docs/faq/perf.md:155,171): ResNet-50 *scoring*, V100 fp16,
batch 32 = 2085.51 img/s — measured here as `extra.score_*`.

TPU-native configuration (see PERF.md for the trace-driven derivation):
  - layout NHWC: channels ride the 128-lane minor dim; no layout
    transposes around convs (vs ~11% slower NCHW, measured)
  - mixed precision via ShardedTrainer(compute_dtype="bfloat16"):
    weights/activations bf16 on the MXU, fp32 master params, fp32 BN
    statistics, fp32 softmax inner (measured 1.9x vs fp32)
  - one fused XLA program per step (fwd+bwd+SGD update) built by
    parallel.ShardedTrainer; synthetic data staged on-device, like the
    reference's `--benchmark 1` mode (image-classification/common/fit.py)

Prints a best-so-far JSON line after every ladder rung; the LAST
{-prefixed stdout line is the result:
{"metric", "value", "unit", "vs_baseline", "extra"} — with
extra.ladder recording each rung's img/s or failure status.
"""
import json
import os
import sys
import time

import numpy as np

BASELINE_IMG_S = 363.69
SCORE_BASELINE_FP16 = 2085.51
INCEPTION_BASELINE = 253.68   # docs/faq/perf.md:216, V100 b128
ALEXNET_BASELINE = 2994.32    # docs/faq/perf.md:212, V100 b256
# env overrides exist for CI smoke only; the driver runs the defaults
BATCH = int(os.environ.get("MXTPU_BENCH_BATCH", 128))
SCORE_BATCH = int(os.environ.get("MXTPU_BENCH_SCORE_BATCH", 32))
IMG = int(os.environ.get("MXTPU_BENCH_IMG", 224))
STEPS = int(os.environ.get("MXTPU_BENCH_STEPS", 50))
UNROLL = int(os.environ.get("MXTPU_BENCH_UNROLL", 10))


def _flag(name, default="1"):
    return os.environ.get(name, default) not in ("0", "false")


# device-lease bookkeeping for the BENCH record (ISSUE 7): a failed
# round must be diagnosable from the record alone — how many probes it
# took, whether a stale lease was taken over, and who held it
_LEASE = None
_PROBE_INFO = {"probes": 0, "takeovers": 0, "lease_holder": None}


def _acquire_device_lease():
    """The probe path owns device acquisition now: a cooperative
    on-disk lease (resilience/lease.py) with hard-timeout takeover
    replaces the old skip-and-pray kill_stale ladder. A wedged previous
    holder (stale heartbeat) is reclaimed — SIGTERM→SIGKILL with grace,
    no --force — while a LIVE holder with a fresh heartbeat becomes a
    clean diagnosable exit instead of 35 minutes of doomed retries."""
    global _LEASE
    from mxnet_tpu.resilience.lease import DeviceLease, LeaseHeld
    if os.environ.get("MXTPU_LEASE", "") in ("0", "false"):
        return None      # explicit opt-out; bench otherwise ALWAYS
        # leases — even a cpu-pinned run wants measurement exclusivity
    if _LEASE is not None and _LEASE.held():
        return _LEASE
    lease = DeviceLease(what="bench")
    try:
        lease.acquire()      # MXTPU_LEASE_ACQUIRE_S bounds the wait
    except LeaseHeld as err:
        _PROBE_INFO["lease_holder"] = err.holder
        raise SystemExit("bench: %s" % err)
    _LEASE = lease
    import atexit
    atexit.register(lease.release)
    _PROBE_INFO["takeovers"] = lease.takeovers
    if lease.taken_over_from:
        # the party that mattered: who was wedged on the device before
        # this run reclaimed it (trim to the diagnosable fields)
        _PROBE_INFO["lease_holder"] = {
            k: lease.taken_over_from.get(k)
            for k in ("pid", "host", "what", "cmdline", "heartbeat")}
    else:
        _PROBE_INFO["lease_holder"] = lease.state().get("holder")
    return lease


def _apply_platform_override():
    """MXTPU_BENCH_PLATFORM=cpu pins the backend via jax.config (for CI
    smoke runs — the env-var spelling can still race plugin discovery
    on machines with a configured accelerator tunnel)."""
    plat = os.environ.get("MXTPU_BENCH_PLATFORM")
    if plat:
        import jax
        jax.config.update("jax_platforms", plat)


def _probe_devices(timeout_s=180, parent_init=True, retries=None):
    """Probe + recovery (the recorded metric must be a real measurement
    or a clean error, never a hang — and round 3 proved one failed
    probe shouldn't be the end: recover, then retry).

    Each probe runs in a FRESH interpreter: a PJRT init that timed out
    leaves this process's jax wedged on the init lock, so an in-process
    retry can never succeed. The probe loop first ACQUIRES the host
    device lease (stale holders are taken over — resilience/lease.py;
    a live fresh holder is a clean diagnosable exit). Between failed
    attempts, reap stale framework processes that may still be blocking
    the PJRT pool (tools/kill_stale.py, now lease-aware) and back off —
    relay-side lease wedges clear with time, not force.
    """
    import subprocess
    _acquire_device_lease()
    # 6 probes spanning ~35 min by default: relay-lease wedges clear
    # with time (round 4 evidence), so a short probe burst undersamples
    # (callers with a CPU fallback pass a smaller retries)
    if retries is None:
        retries = int(os.environ.get("MXTPU_BENCH_PROBE_RETRIES", 6))
    waits = (60, 120, 240, 480, 600, 600)
    plat = os.environ.get("MXTPU_BENCH_PLATFORM")
    pin = ("import jax; jax.config.update('jax_platforms', %r); " % plat
           if plat else "")
    # the child probes through the health watchdog: a trip reports the
    # typed DeviceUnreachable WITH the lease-holder + /proc diagnostics
    # on stderr, so the failure record names the culprit
    code = (pin + "import sys\n"
            "from mxnet_tpu.resilience.watchdog import (HealthWatchdog, "
            "DeviceUnreachable)\n"
            "try:\n"
            "    d = HealthWatchdog(init_timeout_s=%d).init_devices()\n"
            "except DeviceUnreachable as e:\n"
            "    sys.stderr.write(str(e))\n"
            "    sys.exit(1)\n"
            "sys.stdout.write(d[0].platform)\n" % timeout_s)
    err = "?"
    here = os.path.dirname(os.path.abspath(__file__))
    for attempt in range(max(retries, 1)):
        _PROBE_INFO["probes"] += 1
        try:
            # belt over the in-child deadline: if the child itself wedges
            # (e.g. PJRT init stuck in a C call holding the GIL so even
            # interpreter shutdown hangs), reap it here
            r = subprocess.run([sys.executable, "-c", code], cwd=here,
                               capture_output=True, text=True,
                               timeout=timeout_s + 60)
        except subprocess.TimeoutExpired:
            err = "probe child wedged past %ds" % (timeout_s + 60)
        else:
            if r.returncode == 0:
                # the child reports its backend platform on stdout so
                # the caller can notice a TPU-less (cpu-only) host
                plat = (r.stdout or "").strip() or "unknown"
                if not parent_init:
                    # ladder mode: measurement runs in child processes,
                    # and a parent that inits PJRT would HOLD the device
                    # lease for the whole ladder, blocking every rung
                    # child's init (kill_stale.py's holder model)
                    return plat
                # do the PARENT's backend init under the same deadline:
                # this process hasn't attempted init yet, so the probe
                # both guards and performs it (a wedge in the window
                # after the child's clean exit would otherwise hang the
                # unguarded jax.devices() below)
                from mxnet_tpu.base import probe_devices
                devs, perr = probe_devices(timeout_s)
                if devs is not None:
                    return plat
                raise SystemExit(
                    "bench: probe child ok but parent init failed (%s)"
                    % perr)
            err = ((r.stderr or "").strip().splitlines() or ["?"])[-1]
        if attempt + 1 >= max(retries, 1):
            break
        sys.stderr.write("bench: probe %d failed (%s); cleaning stale "
                         "processes and retrying\n" % (attempt + 1, err))
        ks = subprocess.run([sys.executable,
                             os.path.join(here, "tools", "kill_stale.py"),
                             "--kill"], capture_output=True, text=True)
        for line in (ks.stdout + ks.stderr).splitlines():
            sys.stderr.write("bench:   kill_stale: %s\n" % line)
        time.sleep(waits[min(attempt, len(waits) - 1)])
    # attach environment diagnostics to the failure record so the
    # post-mortem does not need a live session
    try:
        dg = subprocess.run([sys.executable,
                             os.path.join(here, "tools", "diagnose.py")],
                            capture_output=True, text=True, timeout=120)
        for line in (dg.stdout + dg.stderr).splitlines()[-15:]:
            sys.stderr.write("bench:   diagnose: %s\n" % line)
    except Exception as e:  # diagnostics must never mask the verdict
        sys.stderr.write("bench:   diagnose failed: %s\n" % e)
    raise SystemExit("bench: device backend unreachable after %d probes "
                     "(%s)" % (max(retries, 1), err))


def _materialize(net, img, nhwc=True):
    """Finish deferred param init WITHOUT an eager forward (which would
    trigger ~180 separate accelerator compiles over the device link):
    symbolic shape inference + deferred-init finish. Prefer the host
    CPU backend for the initializer ops when it exists (it is absent
    under JAX_PLATFORMS=axon/tpu-only configurations)."""
    import contextlib
    import jax
    import mxnet_tpu as mx
    try:
        mat_ctx = jax.default_device(jax.local_devices(backend="cpu")[0])
    except Exception:
        mat_ctx = contextlib.nullcontext()
    with mat_ctx:
        net.initialize()
        shp = (1, img, img, 3) if nhwc else (1, 3, img, img)
        net.infer_shape(mx.nd.zeros(shp))
        for p in net.collect_params().values():
            p._finish_deferred_init()


def _train_tput(ctor, batch, img, steps, unroll, lr=0.1,
                flops_per_img=None, **trainer_kw):
    """Train throughput of one model: ALL timed steps run inside ONE
    jitted lax.scan (step_many) — one dispatch per window, fenced by
    fetching the losses to host; device_get is the only reliable fence
    on remote/tunneled backends (block_until_ready can return before
    remote execution completes)."""
    import jax
    from mxnet_tpu import gluon
    from mxnet_tpu.parallel import make_mesh, ShardedTrainer

    mesh = make_mesh({"dp": len(jax.devices())})
    net = ctor()
    _materialize(net, img)
    loss = gluon.loss.SoftmaxCrossEntropyLoss()
    st = ShardedTrainer(net, lambda o, l: loss(o, l), "sgd",
                        {"learning_rate": lr, "momentum": 0.9},
                        mesh=mesh, compute_dtype="bfloat16",
                        **trainer_kw)
    rng = np.random.RandomState(0)
    # stage the synthetic batch on-device ONCE (the input pipeline's
    # job; re-uploading per step would measure the host link, not the
    # TPU — the reference's --benchmark 1 mode does the same)
    sh = st._batch_sharding()
    x = jax.device_put(rng.randn(batch, img, img, 3).astype("float32"),
                       sh)
    y = jax.device_put((rng.rand(batch) * 1000).astype("float32"), sh)

    def run_window(n):
        losses = st.step_many(x, y, n_steps=n, unroll=min(unroll, n))
        out = np.asarray(jax.device_get(losses._data))
        assert np.isfinite(out).all(), "non-finite loss in bench window"
        return out

    # numerics accounting (ISSUE 10): the in-graph guard records one ok
    # flag per step; a silently-skipping run must be visible in the
    # BENCH record, not post a fake throughput number
    from mxnet_tpu.resilience import numerics as _numerics

    run_window(steps)  # compile + warm (same shape/unroll as timed run)
    _numerics.drain_flags()
    t0 = time.perf_counter()
    run_window(steps)
    dt = time.perf_counter() - t0
    guard = _numerics.drain_flags()     # timed window's verdicts
    st.bench_skipped_steps = guard["skipped_steps"]
    st.bench_anomalies = guard["anomalies"]
    if flops_per_img:
        # charge the timed window's analytic model FLOPs (fwd+bwd) to
        # the goodput counter and derive the headline MFU — step_many's
        # scanned window never dispatches per-step costed programs, so
        # the fused step only self-charges its optimizer phase
        from mxnet_tpu.observability import goodput as _goodput
        flops = float(flops_per_img) * batch * steps
        if _goodput.enabled():
            _goodput.note_flops(flops, n_dispatches=steps)
        st.bench_mfu = _goodput.mfu_value(flops, dt, source="bench")
    return batch * steps / dt, st


def _score_tput(score_fn, tree, xs, batch, n_score=30):
    """Inference throughput: n_score forwards in ONE jitted fori_loop;
    each iteration perturbs the input by a function of the previous
    logits so XLA cannot collapse the loop. The weights ride as jit
    ARGUMENTS (a pytree), not closure constants — closure capture would
    embed ~25M params into the jaxpr and pin their current (possibly
    host) placement into the compiled module."""
    import jax
    import jax.numpy as jnp

    @jax.jit
    def window(tree, xb):
        def body(i, carry):
            xb, acc = carry
            out = score_fn(tree, xb)
            return (xb + out.mean().astype(xb.dtype) * 1e-12,
                    acc + out.astype(jnp.float32).mean())
        _, acc = jax.lax.fori_loop(0, n_score, body,
                                   (xb, jnp.float32(0)))
        return acc

    np.asarray(jax.device_get(window(tree, xs)))  # compile
    t0 = time.perf_counter()
    np.asarray(jax.device_get(window(tree, xs)))
    return batch * n_score / (time.perf_counter() - t0)


def _extra_metrics(rng, t_start):
    """Secondary BASELINE.md rows (docs/faq/perf.md:155,212-216):
    inception-v3 train b128, alexnet train b256, int8 resnet50
    scoring. Each is fenced in try/except so one failure can't cost
    the others, and a soft deadline keeps extras from eating a driver
    timeout that would lose the already-computed headline."""
    import jax
    import mxnet_tpu as mx
    from mxnet_tpu.gluon.model_zoo import vision
    extras = {}
    steps = int(os.environ.get("MXTPU_BENCH_EXTRA_STEPS", 20))
    budget = float(os.environ.get("MXTPU_BENCH_BUDGET_S", 1200))

    def over_budget(name):
        if time.perf_counter() - t_start > budget:
            extras[name + "_skipped"] = "time budget (%ds) spent" % budget
            return True
        return False
    # size overrides exist for CI smoke only; the driver runs defaults
    inc_batch = int(os.environ.get("MXTPU_BENCH_INCEPTION_BATCH", BATCH))
    alex_batch = int(os.environ.get("MXTPU_BENCH_ALEX_BATCH", 256))

    def inception():
        # Inception-v3 train, b128 @299^2 (V100 baseline 253.68; the
        # 299^2 input is structural: the v3 tail pools an 8x8 map)
        r, _ = _train_tput(
            lambda: vision.inception_v3(classes=1000, layout="NHWC"),
            inc_batch, 299, steps, 5)
        extras["inception_v3_train_b%d_img_s" % inc_batch] = round(r, 2)
        extras["inception_v3_vs_v100"] = round(r / INCEPTION_BASELINE,
                                               3)

    def alexnet():
        # AlexNet train, b256 (V100 baseline 2994.32 at batch 16x16);
        # small lr: no BN anywhere, lr=0.1 diverges within the window
        r, _ = _train_tput(
            lambda: vision.alexnet(classes=1000, layout="NHWC"),
            alex_batch, 224, steps, 5, lr=1e-3)
        extras["alexnet_train_b%d_img_s" % alex_batch] = round(r, 2)
        extras["alexnet_vs_v100"] = round(r / ALEXNET_BASELINE, 3)

    def int8_score():
        # int8-quantized resnet50 scoring, b32 (the int8 subsystem's
        # one unmeasured perf story; fp16 V100 score row = 2085.51)
        net = vision.resnet50_v1(classes=1000)  # NCHW: quantizer's form
        _materialize(net, IMG, nhwc=False)
        out = net(mx.sym.var("data"))
        aux_names = set(out.list_auxiliary_states())
        args = {p.name: p.data() for p in net.collect_params().values()
                if p.name not in aux_names}
        auxs = {p.name: p.data() for p in net.collect_params().values()
                if p.name in aux_names}
        calib = rng.randn(SCORE_BATCH, 3, IMG, IMG).astype("float32")

        from mxnet_tpu.io import NDArrayIter
        from mxnet_tpu.contrib.quantization import quantize_model
        qsym, qargs, qauxs = quantize_model(
            out, args, auxs,
            calib_data=NDArrayIter(calib, batch_size=SCORE_BATCH),
            calib_mode="naive", quantize_mode="full", label_names=None)
        from mxnet_tpu.graph import build_graph_fn
        qfn, _, _, _ = build_graph_fn(qsym._entries, "predict")
        # weights were materialized on the host backend: re-stage them
        # on the accelerator so the jit doesn't mix device commitments
        dev = jax.devices()[0]
        qa = {k: jax.device_put(v._data, dev) for k, v in qargs.items()}
        qx = {k: jax.device_put(v._data, dev) for k, v in qauxs.items()}

        def score_fn(tree, xb):
            a, x_ = tree
            outs, _ = qfn({**a, "data": xb}, x_)
            return outs[0]

        xs = jax.device_put(calib, dev)
        r = _score_tput(score_fn, (qa, qx), xs, SCORE_BATCH)
        extras["int8_resnet50_score_b%d_img_s" % SCORE_BATCH] = round(r, 2)
        extras["int8_score_vs_v100_fp16"] = round(
            r / SCORE_BASELINE_FP16, 3)

    for name, fn in (("inception_v3", inception), ("alexnet", alexnet),
                     ("int8_score", int8_score)):
        if over_budget(name):
            continue
        try:
            fn()
        except Exception as e:  # noqa: BLE001 -- recorded, not fatal
            extras[name + "_error"] = str(e)[:200]
    return extras


def _rungs():
    """Escalation ladder for the headline measurement. Round-5 lesson:
    with the tunnel UP, the full-size program (50-step scan, unroll=10)
    can still wedge in the server-side compile RPC indefinitely — so a
    single in-process measurement can record nothing at all. Rungs run
    smallest-first in separate deadline-fenced child processes: the
    first secures *a* chip number cheaply, later ones upgrade it. CI
    size overrides apply inside each rung (min with the rung's cap).
    """
    deadlines = [float(x) for x in os.environ.get(
        "MXTPU_BENCH_DEADLINES", "900,900,1500,2400").split(",")
        if x.strip()]
    if len(deadlines) == 3:
        # pre-round-5 spelling (secure,mid,full): keep its semantics —
        # the score rung borrows secure's fence rather than silently
        # shifting mid/full to looser bounds
        deadlines = [deadlines[0]] + deadlines
    specs = [
        # (name, steps, unroll, score?, extras?) — round-5 chip lesson:
        # the rung that bundled the train upgrade WITH the score compile
        # wedged and took the lease with it, so train-upgrade and score
        # are now separate rungs (score reuses the secure-size train
        # program, which the persistent compile cache makes nearly free)
        ("secure", min(8, STEPS), 1, False, False),
        ("score", min(8, STEPS), 1, True, False),
        ("mid", STEPS, min(2, UNROLL), False, False),
        ("full", STEPS, UNROLL, True, True),
    ]
    while len(deadlines) < len(specs):  # a short list bounds the rest
        deadlines.append(deadlines[-1] if deadlines else 900.0)
    rungs = [s + (d,) for s, d in zip(specs, deadlines)]
    if not _flag("MXTPU_BENCH_SCORE"):
        # with scoring masked off, the score rung would be an exact
        # duplicate of secure — don't spend a chip-window child on it
        # (deadlines are zipped first so the others keep their slots)
        rungs = [r for r in rungs if r[0] != "score"]
    return rungs


def fence_child(p, graces=None):
    """Reap a deadline-struck child with SIGINT -> SIGTERM -> SIGKILL
    escalation: the clean KeyboardInterrupt unwind closes the PJRT
    client and releases the device lease, where a blunt kill wedges it
    (PERF.md §9). Shared by the bench rungs and tools/probe_loop.py.
    Returns (stdout_so_far, signal_name|'unreaped') — output the child
    printed before wedging is real and must be kept. stdout is always
    str: TimeoutExpired.stdout is bytes even under text=True, so it is
    decoded here — both callers can strip/concatenate without a
    TypeError in exactly the wedge scenario they exist to survive."""
    import signal
    import subprocess

    def _text(b):
        return b.decode("utf-8", "replace") if isinstance(b, bytes) else b

    graces = graces or ((signal.SIGINT, 120), (signal.SIGTERM, 30),
                        (signal.SIGKILL, 30))
    out = None
    for sig, grace in graces:
        p.send_signal(sig)
        try:
            got, _ = p.communicate(timeout=grace)
            return (_text(got) if got is not None else out,
                    signal.Signals(sig).name)
        except subprocess.TimeoutExpired as e:
            if e.stdout is not None:
                out = _text(e.stdout)
            continue
    return out, "unreaped"


def _run_rung(name, steps, unr, score, extras, deadline):
    """One ladder rung in a fresh interpreter. Returns (result|None,
    status). On deadline the child is reaped via fence_child (SIGINT
    first; escalating only if it is stuck in a C call)."""
    import subprocess
    import sys
    env = dict(os.environ)
    # a caller's explicit SCORE=0/EXTRAS=0 wins over the rung spec
    score &= _flag("MXTPU_BENCH_SCORE")
    extras &= _flag("MXTPU_BENCH_EXTRAS")
    env.update(MXTPU_BENCH_CHILD="1", MXTPU_BENCH_STEPS=str(steps),
               MXTPU_BENCH_UNROLL=str(unr),
               MXTPU_BENCH_SCORE="1" if score else "0",
               MXTPU_BENCH_EXTRAS="1" if extras else "0")
    here = os.path.dirname(os.path.abspath(__file__))
    p = subprocess.Popen([sys.executable, os.path.abspath(__file__)],
                         cwd=here, env=env, stdout=subprocess.PIPE,
                         stderr=sys.stderr, text=True)
    out, timed_out = "", False
    try:
        out, _ = p.communicate(timeout=deadline)
    except subprocess.TimeoutExpired as e:
        timed_out = True
        fenced, _sig = fence_child(p)
        if fenced is not None:
            out = fenced
        elif isinstance(e.stdout, bytes):
            out = e.stdout.decode("utf-8", "replace")
        else:
            out = e.stdout or ""

    def parse():
        text = out or ""  # always str: fence_child decodes
        lines = [l for l in text.splitlines()
                 if l.startswith("{")]
        if not lines:
            return None
        try:
            return json.loads(lines[-1])
        except ValueError:
            return None

    if timed_out:
        # the child may have finished the measurement and printed its
        # line BEFORE wedging in teardown — that result is real; keep
        # it (the caller still stops escalating: the lease is suspect)
        return parse(), "timeout after %ds" % deadline
    r = parse()
    if p.returncode != 0 or r is None:
        return None, "rc=%s" % p.returncode
    return r, "ok"


def _enable_compile_cache():
    """Persistent XLA compile cache shared by every child interpreter
    (and by later bench runs on this host). Through the dev tunnel a
    large-program compile is both slow (~minutes) and the lease-wedge
    trigger (round-5 chip log), so reusing executables across rungs and
    across runs is the single best de-risking lever. Backends whose
    PJRT client can't serialize executables just log a warning and
    compile as before. MXTPU_XLA_CACHE=0 disables."""
    default = "/tmp/mxtpu_xla_cache_%d" % os.getuid()
    d = os.environ.get("MXTPU_XLA_CACHE", default)
    if not d or d == "0":
        return
    if d == default:
        # the default lives in world-writable /tmp: refuse a directory
        # we don't own with 0700 (someone else could pre-create it and
        # plant serialized executables); an explicit MXTPU_XLA_CACHE
        # path is the operator's own responsibility
        try:
            os.makedirs(d, mode=0o700, exist_ok=True)
            if os.path.islink(d):  # lstat, not stat: a foreign symlink
                return             # to a dir we own passes the checks
            st = os.lstat(d)
            if st.st_uid != os.getuid() or (st.st_mode & 0o022):
                return
        except OSError:
            return
    os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", d)


def _fallback_to_cpu():
    """TPU-less (or wedged-tunnel) host: retarget the measurement at
    the CPU backend instead of dying with a traceback — the perf
    record must exist and parse on every host, and its `platform`
    field says what was actually measured. The workload shrinks to
    CPU-feasible sizes unless the caller pinned its own; a ResNet-50
    b128 50-step scan on CPU would blow every rung deadline."""
    global BATCH, IMG, STEPS, UNROLL
    # drop a wedged accelerator pin for this process and the rung
    # children, then pin the retry explicitly (the ISSUE's
    # JAX_PLATFORMS='' retry, made deterministic)
    os.environ["JAX_PLATFORMS"] = ""
    os.environ["MXTPU_BENCH_PLATFORM"] = "cpu"
    # the CI-smoke sizes (tests/test_bench_smoke.py): measured to fit a
    # rung deadline on CPU — 224px resnet50 does NOT, at any batch size
    for var, small in (("MXTPU_BENCH_BATCH", "8"),
                       ("MXTPU_BENCH_IMG", "32"),
                       ("MXTPU_BENCH_STEPS", "2"),
                       ("MXTPU_BENCH_UNROLL", "1"),
                       ("MXTPU_BENCH_SCORE", "0"),
                       ("MXTPU_BENCH_EXTRAS", "0")):
        os.environ.setdefault(var, small)
    BATCH = int(os.environ["MXTPU_BENCH_BATCH"])
    IMG = int(os.environ["MXTPU_BENCH_IMG"])
    STEPS = int(os.environ["MXTPU_BENCH_STEPS"])
    UNROLL = int(os.environ["MXTPU_BENCH_UNROLL"])
    _apply_platform_override()


def main():
    _enable_compile_cache()
    if os.environ.get("MXTPU_BENCH_CHILD"):
        return _measure_main()
    _apply_platform_override()
    ladder_mode = _flag("MXTPU_BENCH_LADDER")
    # with the CPU fallback armed, cut the probe burst short: two
    # wedged 180s probes are evidence enough when a working backend
    # is one env var away (an explicit platform pin disarms it)
    fallback_ok = _flag("MXTPU_BENCH_CPU_FALLBACK") and \
        not os.environ.get("MXTPU_BENCH_PLATFORM")
    # an explicit probe budget wins over the fallback's short burst:
    # on hosts whose relay wedges clear after N probes, giving up at 2
    # would record a misleading CPU number when the chip was reachable
    short_burst = 2 if fallback_ok and \
        "MXTPU_BENCH_PROBE_RETRIES" not in os.environ else None
    try:
        plat = _probe_devices(parent_init=not ladder_mode,
                              retries=short_burst)
    except SystemExit as err:
        if not fallback_ok:
            raise
        sys.stderr.write("bench: %s; falling back to the CPU backend\n"
                         % err)
        _fallback_to_cpu()
        if _LEASE is None:
            # the SystemExit was a live holder owning the lease
            # (LeaseHeld): the CPU fallback doesn't need the device —
            # don't wait out a SECOND acquire timeout just to die again
            os.environ["MXTPU_LEASE"] = "0"
        _probe_devices(parent_init=not ladder_mode)
    else:
        if plat == "cpu" and fallback_ok:
            # the backend came up but there is no accelerator: the
            # default-size ladder would blow every rung deadline on
            # CPU — shrink so a TPU-less host still records a number
            sys.stderr.write("bench: cpu-only backend; shrinking to "
                             "CPU-feasible sizes\n")
            _fallback_to_cpu()
    if not ladder_mode:
        return _measure_main()
    best, extra, ladder = None, {}, {}

    def emit():
        rec = dict(best)
        # probe/lease outcome ride every emitted record: a failed or
        # degraded round is diagnosable from the BENCH json alone
        rec["extra"] = dict(extra, ladder=dict(ladder), **_PROBE_INFO)
        print(json.dumps(rec), flush=True)

    for name, steps, unr, score, extras, deadline in _rungs():
        r, status = _run_rung(name, steps, unr, score, extras, deadline)
        ladder[name] = (r["value"] if status == "ok"
                        else status if r is None
                        else "%s (%s)" % (r["value"], status))
        if r is not None:
            extra.update(r.get("extra") or {})
            # a later rung ran the higher-fidelity configuration:
            # its number replaces the quick secure estimate even when
            # lower (the headline must describe the documented config)
            best = r
            # best-so-far line NOW: if the driver's own timeout fires
            # mid-ladder, the last complete line printed still stands
            emit()
        if "timeout" in status:
            # a wedged (even if reaped) holder means the lease is
            # suspect; bigger programs won't fare better — stop
            break
    if best is None:
        raise SystemExit("bench: all ladder rungs failed: %s" % ladder)
    # final line carries the COMPLETE ladder record, including any
    # failure entry from a rung that came after the last success
    emit()


def _numerics_overhead_pct(steps=150, warmup=30):
    """Happy-path cost of the training numerics guard on the fused
    update path (the ISSUE-10 acceptance number): time a small gluon
    Trainer step loop with MXTPU_NUMERICS on vs off and report the
    overhead percentage. Small on purpose — a dispatch-bound loop is
    the WORST case for the guard (one extra fused reduce + select per
    group, plus the host-side flag drain), so the recorded number
    upper-bounds the big-model cost. MXTPU_BENCH_NUMERICS_PROBE=0
    skips it."""
    import mxnet_tpu as mx
    from mxnet_tpu import optimizer as opt
    from mxnet_tpu.resilience import numerics as _numerics

    rng = np.random.RandomState(0)
    shapes = [(64, 64)] * 6 + [(64,)] * 6

    def loop(env_on):
        os.environ["MXTPU_NUMERICS"] = "1" if env_on else "0"
        try:
            ws = [mx.nd.array(rng.randn(*s).astype("float32"))
                  for s in shapes]
            gs = [mx.nd.array(rng.randn(*s).astype("float32"))
                  for s in shapes]
            upd = opt.get_updater(opt.create("sgd", learning_rate=1e-6,
                                             momentum=0.9))
            idx = list(range(len(ws)))
            for _ in range(warmup):
                upd.update_all(idx, gs, ws)
            _numerics.drain_flags()
            import jax
            jax.block_until_ready([w._data for w in ws])
            t0 = time.perf_counter()
            for _ in range(steps):
                upd.update_all(idx, gs, ws)
                _numerics.drain_flags()    # the guard's host-side cost
            jax.block_until_ready([w._data for w in ws])
            return time.perf_counter() - t0
        finally:
            os.environ.pop("MXTPU_NUMERICS", None)
    prev = os.environ.get("MXTPU_NUMERICS")
    try:
        # interleaved min-of-5: single reps on a busy CI core are
        # noise-dominated (±5% observed); alternating the modes cancels
        # slow drift and the minimum is the least-perturbed run of each
        t_on, t_off = [], []
        for _ in range(5):
            t_off.append(loop(False))
            t_on.append(loop(True))
        t_off, t_on = min(t_off), min(t_on)
    finally:
        if prev is not None:
            os.environ["MXTPU_NUMERICS"] = prev
    return round(100.0 * (t_on - t_off) / t_off, 2)


def _ledger_mb():
    """HBM-ledger resident MiB at call time (0.0 when the plane is
    off): the BENCH record's model-footprint field."""
    from mxnet_tpu.observability import memory as _memory
    return _memory.total_bytes() / (1024.0 * 1024.0)


def _memledger_overhead_pct(steps=120, warmup=20):
    """Happy-path cost of the HBM-ledger/goodput plane (the ISSUE-17
    acceptance number): time a dispatch-bound fused-step loop with
    MXTPU_MEMLEDGER on vs off and report the overhead percentage. The
    plane's per-dispatch cost is an oom_guard enter/exit, a cost-table
    lookup, and two counter bumps — so a tiny one-dispatch-per-call
    loop upper-bounds the big-model cost exactly like the numerics
    probe above. MXTPU_BENCH_MEMLEDGER_PROBE=0 skips it."""
    import mxnet_tpu as mx
    from mxnet_tpu import optimizer as opt
    from mxnet_tpu.parallel import fused_step as _fstep

    rng = np.random.RandomState(0)
    shapes = [(64, 64)] * 6 + [(64,)] * 6

    def loop(env_on):
        os.environ["MXTPU_MEMLEDGER"] = "1" if env_on else "0"
        try:
            ws = [mx.nd.array(rng.randn(*s).astype("float32"))
                  for s in shapes]
            gs = [mx.nd.array(rng.randn(*s).astype("float32"))
                  for s in shapes]
            upd = opt.get_updater(opt.create("sgd", learning_rate=1e-6,
                                             momentum=0.9))
            idx = list(range(len(ws)))
            for _ in range(warmup):
                if not _fstep.try_step(upd, idx, gs, ws):
                    raise RuntimeError("fused step refused — the "
                                       "memledger probe measures its "
                                       "dispatch wrapper")
            import jax
            jax.block_until_ready([w._data for w in ws])
            t0 = time.perf_counter()
            for _ in range(steps):
                _fstep.try_step(upd, idx, gs, ws)
            jax.block_until_ready([w._data for w in ws])
            return time.perf_counter() - t0
        finally:
            os.environ.pop("MXTPU_MEMLEDGER", None)
    prev = os.environ.get("MXTPU_MEMLEDGER")
    try:
        # interleaved min-of-5, same rationale as the numerics probe
        t_on, t_off = [], []
        for _ in range(5):
            t_off.append(loop(False))
            t_on.append(loop(True))
        t_off, t_on = min(t_off), min(t_on)
    finally:
        if prev is not None:
            os.environ["MXTPU_MEMLEDGER"] = prev
    return round(100.0 * (t_on - t_off) / t_off, 2)


def _measure_main():
    t_start = time.perf_counter()
    _apply_platform_override()
    import jax
    jax.config.update("jax_default_matmul_precision", "bfloat16")
    import mxnet_tpu as mx
    from mxnet_tpu.gluon.model_zoo import vision
    from mxnet_tpu.graph import build_graph_fn

    rng = np.random.RandomState(0)
    unroll = int(os.environ.get("MXTPU_BENCH_UNROLL", 10))
    img_s, st = _train_tput(
        lambda: vision.resnet50_v1(classes=1000, layout="NHWC"),
        BATCH, IMG, STEPS, unroll,
        # resnet50 @224 fwd ~4.089 GFLOP/img, train ~3x fwd (the same
        # accounting tools/mfu_probe.py documents); conv FLOPs scale
        # with spatial area, so shrunk-IMG CI rungs scale the constant
        # instead of posting a fantasy MFU
        flops_per_img=3 * 4.089e9 * (IMG / 224.0) ** 2)
    net = st._net

    extra = {}
    if _flag("MXTPU_BENCH_SCORE"):
        # secondary: inference scoring at the reference's
        # benchmark_score.py config (batch 32), bf16 like the V100
        # fp16 row
        import jax.numpy as jnp
        params = {k: (v.astype(jnp.bfloat16) if v.ndim >= 2 else v)
                  for k, v in st.params.items()}
        aux = dict(st._aux)
        out_sym = net(mx.sym.var("data"))
        score_fn, _, _, _ = build_graph_fn(out_sym._entries, "predict")

        def fp_score(tree, xb):
            p, a = tree
            outs, _ = score_fn({**p, "data": xb.astype(jnp.bfloat16)},
                               a)
            return outs[0]

        xs = jax.device_put(
            rng.randn(SCORE_BATCH, IMG, IMG, 3).astype("float32"))
        score_img_s = _score_tput(fp_score, (params, aux), xs,
                                  SCORE_BATCH)
        extra.update({
            "score_b%d_img_s" % SCORE_BATCH: round(score_img_s, 2),
            "score_vs_v100_fp16": round(
                score_img_s / SCORE_BASELINE_FP16, 3),
        })
    if _flag("MXTPU_BENCH_EXTRAS"):
        extra.update(_extra_metrics(rng, t_start))
    if _flag("MXTPU_BENCH_NUMERICS_PROBE") and STEPS >= 10:
        # CI smoke runs (shrunk MXTPU_BENCH_STEPS) skip the probe: its
        # number is only meaningful — and only recorded — on the
        # driver's default-size runs
        try:
            extra["numerics_overhead_pct"] = _numerics_overhead_pct()
        except Exception as e:  # noqa: BLE001 — recorded, not fatal
            extra["numerics_overhead_error"] = str(e)[:200]
    if _flag("MXTPU_BENCH_MEMLEDGER_PROBE") and STEPS >= 10:
        try:
            extra["memledger_overhead_pct"] = _memledger_overhead_pct()
        except Exception as e:  # noqa: BLE001 — recorded, not fatal
            extra["memledger_overhead_error"] = str(e)[:200]
    if _PROBE_INFO["probes"]:
        # non-ladder parent measured in-process: its record carries the
        # probe/lease outcome directly (rung children never probe —
        # the ladder parent merges _PROBE_INFO at emit instead)
        extra.update(_PROBE_INFO)

    print(json.dumps({
        "metric": "resnet50_v1_train_throughput_b%d" % BATCH,
        "value": round(img_s, 2), "unit": "img/s",
        "vs_baseline": round(img_s / BASELINE_IMG_S, 3),
        # what the number was measured on: a CPU-fallback record must
        # never be mistaken for a chip measurement
        "platform": jax.default_backend(),
        # numerics-guard verdicts over the TIMED window (ISSUE 10): a
        # throughput number from silently-skipped steps is a fake —
        # tools/perf_gate.py --max-skipped-steps turns these into a CI
        # failure
        "skipped_steps": int(getattr(st, "bench_skipped_steps", 0)),
        "anomalies": int(getattr(st, "bench_anomalies", 0)),
        # fused-step provenance (docs/performance.md "Fused train step
        # & ZeRO-1"): the measured loop is the one-program-per-step
        # ShardedTrainer path; zero1 records whether optimizer state
        # was ZeRO-1-sharded over dp (MXTPU_ZERO1) for this number
        "fused_step": True,
        "zero1": bool(getattr(st, "_shard_opt", False)),
        # goodput/memory plane (docs/observability.md "Goodput & MFU" /
        # "Memory ledger"): model-FLOPs utilization of the timed window
        # against the platform's peak, and the HBM ledger's resident
        # bytes at record time — 0.0 with MXTPU_MEMLEDGER=0
        "mfu": round(float(getattr(st, "bench_mfu", 0.0)), 4),
        "hbm_mb": round(_ledger_mb(), 2),
        "extra": extra}))


if __name__ == "__main__":
    main()
