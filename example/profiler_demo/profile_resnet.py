"""Profiler walkthrough (reference: example/profiler/profiler_executor.py
— configure, run a model under the profiler, dump Chrome-trace JSON).

Produces <output>.json loadable in chrome://tracing / perfetto, plus the
aggregate per-scope table.

Usage: python profile_resnet.py [--steps 5] [--cpu]
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--steps", type=int, default=5)
    p.add_argument("--batch-size", type=int, default=8)
    p.add_argument("--output", default="profile_resnet")
    p.add_argument("--cpu", action="store_true")
    args = p.parse_args()
    if args.cpu:
        os.environ.setdefault(
            "XLA_FLAGS", "--xla_force_host_platform_device_count=8")
        import jax
        jax.config.update("jax_platforms", "cpu")
    import mxnet_tpu as mx
    from mxnet_tpu import gluon, autograd, profiler
    from mxnet_tpu.gluon.model_zoo import vision

    net = vision.resnet18_v1(classes=10)
    net.initialize()
    net(mx.nd.zeros((1, 3, 32, 32)))
    net.hybridize()
    loss = gluon.loss.SoftmaxCrossEntropyLoss()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.05})

    profiler.set_config(filename=args.output, profile_all=True)
    profiler.set_state("run")
    rng = np.random.RandomState(0)
    for step in range(args.steps):
        x = mx.nd.array(rng.randn(args.batch_size, 3, 32,
                                  32).astype("float32"))
        y = mx.nd.array((np.arange(args.batch_size) % 10)
                        .astype("float32"))
        with profiler.Task("train_step"):
            with autograd.record():
                l = loss(net(x), y)
            l.backward()
            trainer.step(args.batch_size)
            l.wait_to_read()
    path = profiler.dump()
    print("trace written:", path, "(%d bytes)" % os.path.getsize(path))
    print(profiler.dumps())
    assert os.path.getsize(path) > 0
    return path


if __name__ == "__main__":
    main()
