"""Variational autoencoder (reference: example/vae — MLP VAE on MNIST).

Proves stochastic layers under autograd: the encoder emits (mu,
log-var), the reparameterization draws eps through mx.random inside
the recorded graph, and the loss is reconstruction + analytic KL. On
synthetic 'digits' (shared class prototypes + noise, no dataset
download). Success = ELBO improves AND the decoder reconstructs
held-out samples better than the best constant predictor.

Usage: python vae_mnist.py [--epochs 15] [--cpu]
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np

D = 64          # observation dim
Z = 8           # latent dim


def make_data(rng, protos, n, noise=0.25):
    y = rng.randint(0, 10, n)
    X = protos[y] + rng.randn(n, D).astype("float32") * noise
    return 1.0 / (1.0 + np.exp(-X))          # squash into (0,1)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=15)
    ap.add_argument("--batch", type=int, default=128)
    ap.add_argument("--hidden", type=int, default=64)
    ap.add_argument("--train-size", type=int, default=4096)
    ap.add_argument("--cpu", action="store_true")
    args = ap.parse_args()
    if args.cpu:
        import jax
        jax.config.update("jax_platforms", "cpu")

    import mxnet_tpu as mx
    from mxnet_tpu import nd, autograd, gluon
    from mxnet_tpu.gluon import nn

    rng = np.random.RandomState(0)
    protos = rng.randn(10, D).astype("float32") * 2.0
    Xtr = make_data(rng, protos, args.train_size)
    Xte = make_data(rng, protos, 512)

    class VAE(gluon.HybridBlock):
        def __init__(self, **kw):
            super().__init__(**kw)
            with self.name_scope():
                self.enc = nn.Dense(args.hidden, activation="relu")
                self.mu = nn.Dense(Z)
                self.logvar = nn.Dense(Z)
                self.dec1 = nn.Dense(args.hidden, activation="relu")
                self.dec2 = nn.Dense(D)

        def hybrid_forward(self, F, x):
            h = self.enc(x)
            mu, logvar = self.mu(h), self.logvar(h)
            eps = F.random.normal(shape=(x.shape[0], Z)) \
                if hasattr(F, "random") else F.random_normal(
                    shape=(x.shape[0], Z))
            z = mu + F.exp(0.5 * logvar) * eps
            logits = self.dec2(self.dec1(z))
            return logits, mu, logvar

    net = VAE()
    net.initialize(mx.init.Xavier())
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 2e-3})

    def elbo_terms(x):
        logits, mu, logvar = net(x)
        # bernoulli reconstruction via stable log-sigmoid forms
        rec = nd.sum(nd.relu(logits) - logits * x +
                     nd.log(1 + nd.exp(-nd.abs(logits))), axis=1)
        kl = -0.5 * nd.sum(1 + logvar - mu * mu - nd.exp(logvar), axis=1)
        return rec, kl

    B = args.batch
    first = None
    for epoch in range(args.epochs):
        perm = rng.permutation(len(Xtr))
        tot = 0.0
        for b in range(len(Xtr) // B):
            x = nd.array(Xtr[perm[b * B:(b + 1) * B]])
            with autograd.record():
                rec, kl = elbo_terms(x)
                loss = nd.mean(rec + kl)
            loss.backward()
            trainer.step(B)
            tot += float(loss.asnumpy())
        tot /= len(Xtr) // B
        first = first if first is not None else tot
        print("epoch %2d  -ELBO %.3f" % (epoch, tot))

    # reconstruction error on held-out data vs best-constant baseline
    logits, _, _ = net(nd.array(Xte))
    recon = 1.0 / (1.0 + np.exp(-logits.asnumpy()))
    mse = float(np.mean((recon - Xte) ** 2))
    base = float(np.mean((Xte.mean(0, keepdims=True) - Xte) ** 2))
    print("recon mse %.5f vs constant-baseline %.5f" % (mse, base))
    assert mse < 0.5 * base, "VAE reconstructions no better than mean"
    print("VAE_OK")


if __name__ == "__main__":
    main()
