"""Training memory cost and rematerialization (reference:
example/memcost — inspecting a symbol's training memory with the
mirror/recompute option, src/executor mirror pass).

The reference trades compute for activation memory with
MXNET_BACKWARD_DO_MIRROR; the TPU-native lever is `jax.checkpoint`
(ShardedTrainer(remat=True)). This demo makes the trade measurable
WITHOUT hardware: XLA's compiled-program memory analysis reports the
temp (activation) allocation of the full train step, and remat must
shrink it on a deep MLP while producing identical numerics.

Usage: python memory_cost.py [--cpu]
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--layers", type=int, default=24)
    ap.add_argument("--width", type=int, default=512)
    ap.add_argument("--batch", type=int, default=256)
    ap.add_argument("--cpu", action="store_true")
    args = ap.parse_args()
    if args.cpu:
        import jax
        jax.config.update("jax_platforms", "cpu")
    import jax
    import jax.numpy as jnp

    L, D, B = args.layers, args.width, args.batch
    rng = np.random.RandomState(0)
    Ws = [jnp.asarray(rng.randn(D, D).astype("float32") / np.sqrt(D))
          for _ in range(L)]
    x = jnp.asarray(rng.randn(B, D).astype("float32"))
    y = jnp.asarray(rng.randn(B, D).astype("float32"))

    def block(h, W):
        return jnp.tanh(h @ W)

    def loss_plain(Ws, x):
        h = x
        for W in Ws:
            h = block(h, W)
        return jnp.mean((h - y) ** 2)

    def loss_remat(Ws, x):
        h = x
        ck = jax.checkpoint(block)
        for W in Ws:
            h = ck(h, W)
        return jnp.mean((h - y) ** 2)

    # one jit/lower/compile per variant, reused by every probe below
    jits = {name: jax.jit(jax.grad(fn))
            for name, fn in [("plain", loss_plain), ("remat", loss_remat)]}
    lowered = {k: v.lower(Ws, x) for k, v in jits.items()}
    compiled = {k: v.compile() for k, v in lowered.items()}

    # the structural trade, visible in the lowered program BEFORE the
    # backend optimizes: remat re-traces every block's forward inside
    # the backward (2x the tanh ops, +L recompute matmuls), which is
    # exactly what frees the activation buffers between fwd and bwd
    def op_counts(name):
        txt = lowered[name].as_text()
        return txt.count("dot_general"), txt.count("tanh")

    (d0, t0), (d1, t1) = op_counts("plain"), op_counts("remat")
    print("lowered-program ops: plain %d dots / %d tanh; "
          "remat %d dots / %d tanh" % (d0, t0, d1, t1))
    assert t1 >= 2 * t0 and d1 >= d0 + L - 1, \
        "remat did not re-trace the forward inside the backward"

    # the memory side, as the backend reports it (NOTE: the CPU
    # backend's buffer model CSEs recomputation back out and does not
    # track HBM-style activation liveness — the byte savings are a TPU
    # property; tools/mfu_probe.py measures the b256 remat rows on the
    # chip, PERF.md)
    for name in ("plain", "remat"):
        m = compiled[name].memory_analysis()
        print("  %s: peak %.1f MiB (backend=%s)"
              % (name, m.peak_memory_in_bytes / 2**20,
                 jax.default_backend()))

    # identical numerics: remat recomputes, it does not approximate
    g1 = jits["plain"](Ws, x)
    g2 = jits["remat"](Ws, x)
    err = max(float(jnp.abs(a - b).max()) for a, b in zip(g1, g2))
    print("max grad difference plain-vs-remat: %.2e" % err)
    assert err < 1e-5, "remat changed numerics"

    # the same lever exposed through the framework:
    # ShardedTrainer(remat=True) wraps the whole traced net step
    print("framework hook: ShardedTrainer(..., remat=True) "
          "(parallel/data_parallel.py)")
    print("MEMCOST_OK")


if __name__ == "__main__":
    main()
