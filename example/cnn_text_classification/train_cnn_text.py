"""Multi-width CNN text classifier (reference:
example/cnn_text_classification/ — the Kim-2014 architecture:
embedding -> parallel conv filters of widths 3/4/5 -> max-over-time
pooling -> concat -> dropout -> FC).

Synthetic task: token sequences over a 50-word vocabulary are positive
iff they contain the trigram (7, 3, 11) anywhere — exactly the pattern
a width-3 filter bank can detect. Asserts held-out accuracy.

Usage: python train_cnn_text.py [--epochs 6] [--cpu]
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))  # run from a source checkout

import numpy as np

VOCAB, SEQ, TRIGRAM = 50, 20, (7, 3, 11)


def make_dataset(rng, n):
    x = rng.randint(0, VOCAB, size=(n, SEQ))
    y = np.zeros((n,), np.float32)
    pos = rng.rand(n) < 0.5
    for i in np.where(pos)[0]:
        at = rng.randint(0, SEQ - 3)
        x[i, at:at + 3] = TRIGRAM
        y[i] = 1.0
    # kill accidental positives in negatives
    for i in np.where(~pos)[0]:
        for t in range(SEQ - 2):
            if tuple(x[i, t:t + 3]) == TRIGRAM:
                x[i, t] = (x[i, t] + 1) % VOCAB
    return x.astype(np.float32), y


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=6)
    p.add_argument("--batch-size", type=int, default=32)
    p.add_argument("--n", type=int, default=2048)
    p.add_argument("--cpu", action="store_true")
    args = p.parse_args()
    if args.cpu:
        os.environ.setdefault(
            "XLA_FLAGS", "--xla_force_host_platform_device_count=8")
        import jax
        jax.config.update("jax_platforms", "cpu")
    import mxnet_tpu as mx
    from mxnet_tpu import gluon, autograd
    from mxnet_tpu.gluon import nn

    emb_dim, n_filter = 16, 24

    class TextCNN(gluon.Block):
        def __init__(self):
            super().__init__()
            with self.name_scope():
                self.embed = nn.Embedding(VOCAB, emb_dim)
                self.convs = [nn.Conv2D(n_filter, (w, emb_dim))
                              for w in (3, 4, 5)]
                for i, c in enumerate(self.convs):
                    setattr(self, "conv%d" % i, c)
                self.drop = nn.Dropout(0.3)
                self.out = nn.Dense(2)

        def forward(self, tokens):
            e = self.embed(tokens)            # (B, T, E)
            e = e.expand_dims(1)              # (B, 1, T, E)
            pooled = []
            for conv in self.convs:
                h = mx.nd.relu(conv(e))       # (B, F, T-w+1, 1)
                pooled.append(mx.nd.max(h, axis=(2, 3)))  # over time
            h = mx.nd.concat(*pooled, dim=1)
            return self.out(self.drop(h))

    net = TextCNN()
    net.initialize(mx.initializer.Xavier())
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 2e-3})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

    rng = np.random.RandomState(0)
    Xtr, ytr = make_dataset(rng, args.n)
    Xte, yte = make_dataset(rng, 512)

    bs = args.batch_size
    for epoch in range(args.epochs):
        perm = rng.permutation(len(Xtr))
        tot = 0.0
        for i in range(0, len(Xtr) - bs + 1, bs):
            idx = perm[i:i + bs]
            x = mx.nd.array(Xtr[idx])
            y = mx.nd.array(ytr[idx])
            with autograd.record():
                l = loss_fn(net(x), y)
            l.backward()
            trainer.step(bs)
            tot += float(l.mean().asscalar())
        pred = net(mx.nd.array(Xte)).asnumpy().argmax(1)
        acc = float((pred == yte).mean())
        print("epoch %d loss %.4f test-acc %.3f"
              % (epoch, tot / (len(Xtr) // bs), acc))
    assert acc > 0.85, "text CNN did not learn the trigram"
    print("final test-acc %.3f" % acc)


if __name__ == "__main__":
    main()
