"""Noise-contrastive estimation vs full softmax (reference:
example/nce-loss/toy_nce.py, nce.py).

Word-prediction over a toy skip-gram corpus where the output vocabulary
is large relative to the model: the full-softmax head pays O(V) per
step, the NCE head scores only the true class plus k sampled noise
classes against a binary logistic objective (the reference's
nce_loss(): Embedding of [label|noise] -> broadcast_mul with the hidden
state -> sum -> LogisticRegressionOutput). Built on the Module/symbol
API like the reference; shows NCE reaching comparable accuracy while
touching k+1 << V output rows per example.

Usage: python toy_nce.py [--epochs 12] [--cpu]
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np


def make_corpus(rng, vocab, n):
    """Deterministic-ish bigram structure: w -> (3w+1)%V or (3w+2)%V."""
    ctx = rng.randint(0, vocab, size=n).astype("float32")
    nxt = ((3 * ctx + 1 + rng.randint(0, 2, size=n)) %
           vocab).astype("float32")
    return ctx, nxt


def build_nce_symbol(mx, vocab, dim, k):
    """Shared input embedding; output scored against 1 true + k noise
    classes through a logistic head (reference nce.py:27)."""
    data = mx.sym.Variable("data")                  # (N,) context word
    cand = mx.sym.Variable("cand_label")            # (N, k+1) classes
    lbl = mx.sym.Variable("binary_label")           # (N, k+1) 1/0
    embed_w = mx.sym.Variable("embed_weight", shape=(vocab, dim))
    out_w = mx.sym.Variable("nce_weight", shape=(vocab, dim))
    h = mx.sym.Embedding(data, weight=embed_w, input_dim=vocab,
                         output_dim=dim, name="ctx_embed")
    cand_e = mx.sym.Embedding(cand, weight=out_w, input_dim=vocab,
                              output_dim=dim, name="cand_embed")
    h = mx.sym.Reshape(h, shape=(-1, 1, dim))
    scores = mx.sym.sum(mx.sym.broadcast_mul(h, cand_e), axis=2)
    return mx.sym.LogisticRegressionOutput(scores, lbl, name="nce")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=12)
    ap.add_argument("--batch", type=int, default=64)
    ap.add_argument("--vocab", type=int, default=200)
    ap.add_argument("--dim", type=int, default=32)
    ap.add_argument("--neg", type=int, default=8, help="noise samples k")
    ap.add_argument("--train-size", type=int, default=8192)
    ap.add_argument("--cpu", action="store_true")
    args = ap.parse_args()
    if args.cpu:
        import jax
        jax.config.update("jax_platforms", "cpu")

    import mxnet_tpu as mx
    from mxnet_tpu import nd

    rng = np.random.RandomState(5)
    V, D, K = args.vocab, args.dim, args.neg
    ctx, nxt = make_corpus(rng, V, args.train_size)

    sym = build_nce_symbol(mx, V, D, K)
    mod = mx.mod.Module(sym, data_names=("data",),
                        label_names=("cand_label", "binary_label"),
                        context=mx.cpu())

    # candidates: column 0 = the true class, then k noise draws
    cand = np.zeros((len(ctx), K + 1), "float32")
    cand[:, 0] = nxt
    cand[:, 1:] = rng.randint(0, V, size=(len(ctx), K))
    binary = np.zeros_like(cand)
    binary[:, 0] = 1.0

    it = mx.io.NDArrayIter(
        {"data": ctx},
        {"cand_label": cand, "binary_label": binary},
        batch_size=args.batch, shuffle=True)
    mod.fit(it, num_epoch=args.epochs, optimizer="adam",
            optimizer_params=(("learning_rate", 5e-3),),
            eval_metric=mx.metric.Loss())

    # rank the TRUE next word among all V via the learned embeddings
    argp, auxp = mod.get_params()
    emb = argp["embed_weight"].asnumpy()
    out = argp["nce_weight"].asnumpy()
    test_ctx, test_nxt = make_corpus(rng, V, 1024)
    scores = emb[test_ctx.astype(int)] @ out.T          # (N, V)
    top2 = np.argsort(-scores, axis=1)[:, :2]
    acc = np.mean([t in row for t, row in
                   zip(test_nxt.astype(int), top2)])
    print("top-2 accuracy over full vocab: %.3f (chance %.4f)"
          % (acc, 2.0 / V))
    assert acc > 0.5, "NCE head failed to learn the bigram structure"
    print("NCE_OK")


if __name__ == "__main__":
    main()
