"""Custom numpy operator (reference: example/numpy-ops/custom_softmax.py
— the classic CustomOp tutorial: a softmax output layer written in
numpy, registered through mx.operator, trained in a real network).

Here CustomOp callbacks run via jax.pure_callback with a custom_vjp
(mxnet_tpu/operator.py), so the numpy code participates in jitted
graphs and autograd.

Usage: python custom_softmax.py [--epochs 5] [--cpu]
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np


def define_op():
    import mxnet_tpu as mx

    class Softmax(mx.operator.CustomOp):
        def forward(self, is_train, req, in_data, out_data, aux):
            x = in_data[0].asnumpy()
            y = np.exp(x - x.max(axis=1, keepdims=True))
            y /= y.sum(axis=1, keepdims=True)
            self.assign(out_data[0], req[0], mx.nd.array(y))

        def backward(self, req, out_grad, in_data, out_data, in_grad,
                     aux):
            l = in_data[1].asnumpy().ravel().astype(np.int64)
            y = np.array(out_data[0].asnumpy(), copy=True)
            y[np.arange(l.shape[0]), l] -= 1.0
            self.assign(in_grad[0], req[0], mx.nd.array(y))

    @mx.operator.register("example_softmax")
    class SoftmaxProp(mx.operator.CustomOpProp):
        def __init__(self):
            super().__init__(need_top_grad=False)

        def list_arguments(self):
            return ["data", "label"]

        def list_outputs(self):
            return ["output"]

        def infer_shape(self, in_shape):
            data_shape = in_shape[0]
            label_shape = (in_shape[0][0],)
            return [data_shape, label_shape], [data_shape], []

        def create_operator(self, ctx, shapes, dtypes):
            return Softmax()

    return Softmax


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=5)
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--cpu", action="store_true")
    args = p.parse_args()
    if args.cpu:
        os.environ.setdefault(
            "XLA_FLAGS", "--xla_force_host_platform_device_count=8")
        import jax
        jax.config.update("jax_platforms", "cpu")
    import mxnet_tpu as mx

    define_op()

    # two-moons-ish synthetic 10-class problem
    rng = np.random.RandomState(0)
    n = 2048
    centers = rng.randn(10, 16) * 2.5
    labels = rng.randint(0, 10, n)
    data = (centers[labels] + rng.randn(n, 16)).astype("float32")

    net = mx.sym.var("data")
    net = mx.sym.FullyConnected(net, num_hidden=64, name="fc1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=10, name="fc2")
    net = mx.sym.Custom(net, mx.sym.var("softmax_label"),
                        op_type="example_softmax", name="softmax")

    mod = mx.mod.Module(net, label_names=("softmax_label",))
    train = mx.io.NDArrayIter(data, labels.astype("float32"),
                              args.batch_size, shuffle=True,
                              label_name="softmax_label")
    mod.fit(train, num_epoch=args.epochs,
            optimizer_params={"learning_rate": 0.1},
            eval_metric="acc",
            batch_end_callback=mx.callback.Speedometer(
                args.batch_size, 20))
    score = mod.score(train, mx.metric.Accuracy())
    acc = dict(score)["accuracy"]
    print("final train accuracy %.3f" % acc)
    assert acc > 0.9, "custom softmax network failed to learn"
    return acc


if __name__ == "__main__":
    main()
