"""ResNet on CIFAR-10 with Gluon (reference: example/gluon/image_classification.py).

Real CIFAR-10 if the binary batches are under --data-dir, else synthetic.

Usage: python train_cifar10.py [--model resnet20ish] [--epochs 2] [--cpu]
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))  # run from a source checkout

import numpy as np


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--model", default="resnet18_v1")
    p.add_argument("--epochs", type=int, default=2)
    p.add_argument("--batch-size", type=int, default=128)
    p.add_argument("--lr", type=float, default=0.1)
    p.add_argument("--data-dir",
                   default=os.path.join("~", ".mxnet", "datasets",
                                        "cifar10"))
    p.add_argument("--cpu", action="store_true")
    p.add_argument("--hybridize", action="store_true", default=True)
    args = p.parse_args()
    if args.cpu:
        os.environ.setdefault(
            "XLA_FLAGS", "--xla_force_host_platform_device_count=8")
        import jax
        jax.config.update("jax_platforms", "cpu")
    import mxnet_tpu as mx
    from mxnet_tpu import gluon, autograd
    from mxnet_tpu.gluon.data import ArrayDataset, DataLoader
    from mxnet_tpu.gluon.model_zoo import vision

    try:
        from mxnet_tpu.gluon.data.vision import CIFAR10
        train = CIFAR10(root=args.data_dir, train=True)
        x = train._data.asnumpy().transpose(0, 3, 1, 2) / 255.0
        y = train._label
        print("using real CIFAR-10")
    except RuntimeError:
        print("CIFAR-10 not found; synthetic data")
        # learnable stand-in: class = (spatial pattern, color channel)
        rng = np.random.RandomState(0)
        n = 2048
        y = rng.randint(0, 10, n)
        x = np.zeros((n, 3, 32, 32), "float32")
        xs = np.arange(32)
        for i in range(n):
            c = y[i]
            ang = (c % 5) * np.pi / 5
            g = np.cos(ang) * xs[None, :] + np.sin(ang) * xs[:, None]
            pat = (np.sin(2 * np.pi * g / 6) > 0).astype("float32")
            x[i, c // 5] = pat
            x[i] += rng.randn(3, 32, 32) * 0.15
        y = y.astype("float32")

    loader = DataLoader(ArrayDataset(x.astype("float32"),
                                     y.astype("float32")),
                        batch_size=args.batch_size, shuffle=True,
                        last_batch="discard")
    net = vision.get_model(args.model, classes=10)
    net.initialize(mx.initializer.Xavier())
    if args.hybridize:
        net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": args.lr, "momentum": 0.9,
                             "wd": 1e-4})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    for epoch in range(args.epochs):
        total, correct, lsum, n = 0, 0, 0.0, 0
        for xb, yb in loader:
            with autograd.record():
                out = net(xb)
                loss = loss_fn(out, yb)
            loss.backward()
            trainer.step(xb.shape[0])
            lsum += float(loss.mean().asscalar())
            n += 1
            pred = out.argmax(axis=1).asnumpy()
            correct += (pred == yb.asnumpy()).sum()
            total += xb.shape[0]
        acc = correct / total
        print("epoch %d loss %.4f acc %.3f" % (epoch, lsum / n, acc))
        if epoch == 0:
            first_acc = acc
    assert acc >= first_acc and acc > 0.25, \
        "no learning signal: acc %.3f (epoch0 %.3f)" % (acc, first_acc)
    print("CIFAR_EXAMPLE_OK")


if __name__ == "__main__":
    main()
