"""Sorting as seq2seq with a bidirectional LSTM (reference:
example/bi-lstm-sort — sort a sequence of symbols by reading it both
directions and emitting per-position outputs).

Proves bidirectional fused RNN support end-to-end: the model reads a
sequence of tokens and must output, at position i, the i-th smallest
element — impossible from a causal pass alone, so accuracy > chance
requires the backward direction to work.

Usage: python sort_lstm.py [--epochs 12] [--cpu]
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np

V = 16          # token alphabet
T = 8           # sequence length


def make_data(rng, n):
    X = rng.randint(0, V, size=(n, T)).astype("float32")
    Y = np.sort(X, axis=1).astype("float32")
    return X, Y


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=12)
    ap.add_argument("--batch", type=int, default=64)
    ap.add_argument("--hidden", type=int, default=64)
    ap.add_argument("--train-size", type=int, default=4096)
    ap.add_argument("--threshold", type=float, default=0.85)
    ap.add_argument("--cpu", action="store_true")
    args = ap.parse_args()
    if args.cpu:
        import jax
        jax.config.update("jax_platforms", "cpu")

    import mxnet_tpu as mx
    from mxnet_tpu import nd, autograd, gluon
    from mxnet_tpu.gluon import nn

    rng = np.random.RandomState(0)
    Xtr, Ytr = make_data(rng, args.train_size)
    Xte, Yte = make_data(rng, 512)

    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Embedding(V, 32),
                gluon.rnn.LSTM(args.hidden, layout="NTC",
                               bidirectional=True),
                nn.Dense(V, flatten=False))
    net.initialize(mx.init.Xavier())
    net(nd.array(Xtr[:2]))
    net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 3e-3})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

    B = args.batch
    for epoch in range(args.epochs):
        perm = rng.permutation(len(Xtr))
        tot = 0.0
        for b in range(len(Xtr) // B):
            idx = perm[b * B:(b + 1) * B]
            x, y = nd.array(Xtr[idx]), nd.array(Ytr[idx])
            with autograd.record():
                loss = loss_fn(net(x), y)
            loss.backward()
            trainer.step(B)
            tot += float(nd.mean(loss).asnumpy())
        print("epoch %2d loss %.4f" % (epoch, tot / (len(Xtr) // B)))

    pred = net(nd.array(Xte)).asnumpy().argmax(-1)
    tok_acc = (pred == Yte).mean()
    print("per-position accuracy: %.3f" % tok_acc)
    assert tok_acc > args.threshold, "bi-LSTM failed to learn sorting"
    print("BI_LSTM_SORT_OK")


if __name__ == "__main__":
    main()
