"""Context-parallel transformer LM: ring attention (sp) + MoE experts (ep).

The reference (2018) handles long sequences with bucketing + truncated
BPTT (SURVEY.md §5.7) and has no sequence/expert parallelism. This
example is the TPU-native upgrade path: a decoder-only LM whose

- attention runs as `parallel.ring_attention` — the sequence axis is
  sharded over the mesh; K/V blocks rotate via ppermute, so per-device
  memory is O(T/n) and contexts larger than one chip's HBM train fine;
- FFN is `parallel.moe_ffn` — experts sharded over the same mesh axis,
  tokens routed top-2 with fixed capacity through two all_to_alls.

The whole train step (fwd + bwd + adam) jits into ONE XLA program over
the mesh; gradients of the shard_map collectives are themselves
collectives.

Usage: python train_transformer.py [--steps 60] [--cpu] [--no-moe]
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np


def make_corpus(rng, vocab, n):
    toks = [0]
    for _ in range(n):
        toks.append((toks[-1] * 7 + rng.randint(0, 3)) % vocab)
    return np.asarray(toks, "int32")


def init_params(rng, vocab, D, H, L, E, Hff):
    p = {"embed": rng.randn(vocab, D) * 0.05,
         "pos": rng.randn(4096, D) * 0.02}
    for i in range(L):
        p["l%d_ln1_g" % i] = np.ones(D)
        p["l%d_ln1_b" % i] = np.zeros(D)
        p["l%d_qkv" % i] = rng.randn(D, 3 * D) * (0.5 / np.sqrt(D))
        p["l%d_out" % i] = rng.randn(D, D) * (0.5 / np.sqrt(D))
        p["l%d_ln2_g" % i] = np.ones(D)
        p["l%d_ln2_b" % i] = np.zeros(D)
        p["l%d_gate" % i] = rng.randn(D, E) * 0.1
        p["l%d_w1" % i] = rng.randn(E, D, Hff) * (0.5 / np.sqrt(D))
        p["l%d_b1" % i] = np.zeros((E, Hff))
        p["l%d_w2" % i] = rng.randn(E, Hff, D) * (0.5 / np.sqrt(Hff))
        p["l%d_b2" % i] = np.zeros((E, D))
    return {k: np.asarray(v, "float32") for k, v in p.items()}


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--steps", type=int, default=60)
    p.add_argument("--batch", type=int, default=4)
    p.add_argument("--seq", type=int, default=64)
    p.add_argument("--dim", type=int, default=32)
    p.add_argument("--heads", type=int, default=4)
    p.add_argument("--layers", type=int, default=2)
    p.add_argument("--experts", type=int, default=8)
    p.add_argument("--vocab", type=int, default=50)
    p.add_argument("--lr", type=float, default=3e-3)
    p.add_argument("--no-moe", action="store_true",
                   help="dense FFN instead of expert-parallel MoE")
    p.add_argument("--cpu", action="store_true")
    args = p.parse_args()
    if args.cpu:
        os.environ.setdefault(
            "XLA_FLAGS", "--xla_force_host_platform_device_count=8")
        import jax
        jax.config.update("jax_platforms", "cpu")
    import jax
    import jax.numpy as jnp
    from mxnet_tpu.parallel import (make_mesh, shard_on, replicated,
                                    ring_attention, moe_ffn)
    from mxnet_tpu.parallel.data_parallel import adam_init, adam_update

    mesh = make_mesh({"sp": len(jax.devices())})
    n_dev = mesh.shape["sp"]
    B, T, D, H = args.batch, args.seq, args.dim, args.heads
    L, E, V = args.layers, args.experts, args.vocab
    assert T % n_dev == 0 and E % n_dev == 0
    Dh, Hff = D // H, D * 4
    use_moe = not args.no_moe

    rng = np.random.RandomState(0)
    corpus = make_corpus(rng, V, 200000)
    params = init_params(rng, V, D, H, L, E, Hff)

    def ln(x, g, b):
        mu = x.mean(-1, keepdims=True)
        var = ((x - mu) ** 2).mean(-1, keepdims=True)
        return (x - mu) / jnp.sqrt(var + 1e-5) * g + b

    def forward(params, tokens):
        # tokens (B, T) sharded on T
        x = params["embed"][tokens] + params["pos"][:T][None]
        aux_tot = jnp.float32(0)
        for i in range(L):
            h = ln(x, params["l%d_ln1_g" % i], params["l%d_ln1_b" % i])
            qkv = h @ params["l%d_qkv" % i]                  # (B,T,3D)
            q, k, v = jnp.split(qkv, 3, axis=-1)
            # (B,T,D) -> (B,H,T,Dh); T stays sharded over 'sp'
            sh = lambda t: t.reshape(B, T, H, Dh).transpose(0, 2, 1, 3)
            att = ring_attention(sh(q), sh(k), sh(v), mesh, "sp",
                                 causal=True)
            att = att.transpose(0, 2, 1, 3).reshape(B, T, D)
            x = x + att @ params["l%d_out" % i]
            h = ln(x, params["l%d_ln2_g" % i], params["l%d_ln2_b" % i])
            if use_moe:
                # (B,T,D) -> (T*B, D): T-major keeps token dim sharded
                toks = h.transpose(1, 0, 2).reshape(T * B, D)
                y, aux = moe_ffn(toks, params["l%d_gate" % i],
                                 params["l%d_w1" % i], params["l%d_b1" % i],
                                 params["l%d_w2" % i], params["l%d_b2" % i],
                                 mesh, "sp", top_k=2, capacity_factor=2.0)
                y = y.reshape(T, B, D).transpose(1, 0, 2)
                aux_tot = aux_tot + aux
            else:
                e0 = jax.nn.relu(
                    jnp.einsum("btd,edh->bteh", h,
                               params["l%d_w1" % i][:1])
                    + params["l%d_b1" % i][0])
                y = (jnp.einsum("bteh,ehd->btd", e0,
                                params["l%d_w2" % i][:1])
                     + params["l%d_b2" % i][0])
                y = y[:, :, :]
            x = x + y
        logits = x @ params["embed"].T                        # (B,T,V)
        return logits, aux_tot / max(L, 1)

    def loss_fn(params, tokens, targets):
        logits, aux = forward(params, tokens)
        lp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        nll = -jnp.take_along_axis(lp, targets[..., None],
                                   axis=-1).mean()
        return nll + 0.01 * aux, nll

    tok_sh = shard_on(mesh, "sp", 1, 2)
    rep = replicated(mesh)
    opt_state = adam_init(params)

    @jax.jit
    def step(params, opt_state, tokens, targets):
        (_, nll), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params, tokens, targets)
        params, opt_state = adam_update(params, grads, opt_state,
                                        lr=args.lr)
        return params, opt_state, nll

    params = {k: jax.device_put(jnp.asarray(v), rep)
              for k, v in params.items()}
    opt_state = jax.tree.map(lambda v: jax.device_put(v, rep), opt_state)

    first = last = None
    for it in range(args.steps):
        starts = rng.randint(0, len(corpus) - T - 1, B)
        batch = np.stack([corpus[s:s + T] for s in starts])
        targ = np.stack([corpus[s + 1:s + T + 1] for s in starts])
        params, opt_state, nll = step(
            params, opt_state,
            jax.device_put(jnp.asarray(batch), tok_sh),
            jax.device_put(jnp.asarray(targ), tok_sh))
        nll = float(np.asarray(jax.device_get(nll)))
        first, last = (nll if first is None else first), nll
        if it % 10 == 0 or it == args.steps - 1:
            print("step %4d  nll %.4f  ppl %.2f" % (it, nll, np.exp(nll)))
    print("final nll %.4f (from %.4f)%s"
          % (last, first, "  [moe]" if use_moe else "  [dense]"))
    assert last < first, "LM did not learn"
    return last


if __name__ == "__main__":
    main()
