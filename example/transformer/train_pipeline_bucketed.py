"""Bucketed transformer LM over a GPipe pipeline (pp) + data parallel (dp).

The reference trains variable-length sequence models through
BucketingModule (python/mxnet/module/bucketing_module.py): batches are
grouped into length buckets and each bucket gets its own bound
executor over shared parameters. This example is the same idea wired
through the TPU-native stack:

- every length bucket compiles its own XLA program (one jit cache entry
  per bucket, exactly the BucketingModule contract);
- the decoder layer stack runs through `parallel.pipeline_apply` — L
  identical stages laid out over the 'pp' mesh axis, activations hopping
  stage-to-stage via ppermute with GPipe microbatching;
- the batch axis is simultaneously sharded over 'dp'.

Usage: python train_pipeline_bucketed.py [--steps 40] [--cpu]
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np


def make_corpus(rng, vocab, n):
    toks = [0]
    for _ in range(n):
        toks.append((toks[-1] * 7 + rng.randint(0, 3)) % vocab)
    return np.asarray(toks, "int32")


def bucketed_batches(corpus, rng, buckets, batch, n):
    """Sample (bucket_len, tokens, targets) batches — variable-length
    sequences routed to the tightest bucket (BucketSentenceIter role)."""
    for _ in range(n):
        true_len = int(rng.randint(buckets[0] // 2, buckets[-1]))
        blen = next(b for b in buckets if b >= true_len)
        starts = rng.randint(0, len(corpus) - blen - 1, size=batch)
        toks = np.stack([corpus[s:s + blen] for s in starts])
        tgts = np.stack([corpus[s + 1:s + blen + 1] for s in starts])
        yield blen, toks, tgts


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=40)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--dim", type=int, default=32)
    ap.add_argument("--heads", type=int, default=4)
    ap.add_argument("--vocab", type=int, default=50)
    ap.add_argument("--lr", type=float, default=3e-3)
    ap.add_argument("--buckets", type=int, nargs="+", default=[16, 32])
    ap.add_argument("--cpu", action="store_true")
    args = ap.parse_args()
    if args.cpu:
        os.environ.setdefault(
            "XLA_FLAGS", "--xla_force_host_platform_device_count=8")
        import jax
        jax.config.update("jax_platforms", "cpu")
    import jax
    import jax.numpy as jnp
    from mxnet_tpu.parallel import (make_mesh, shard_on, pipeline_apply)
    import jax.tree_util as jtu

    n_dev = len(jax.devices())
    pp = 4 if n_dev % 4 == 0 else n_dev
    mesh = make_mesh({"dp": n_dev // pp, "pp": pp})
    B, D, H, V = args.batch, args.dim, args.heads, args.vocab
    L = pp                      # one decoder layer per pipeline stage
    Dh, Hff = D // H, D * 4

    rng = np.random.RandomState(0)
    corpus = make_corpus(rng, V, 100000)

    # embedding/head replicated; per-stage decoder params stacked on a
    # leading L axis that pipeline_apply shards over 'pp'
    params = {
        "embed": np.asarray(rng.randn(V, D) * 0.05, "float32"),
        "pos": np.asarray(rng.randn(args.buckets[-1], D) * 0.02, "float32"),
        "stages": {
            "ln1_g": np.ones((L, D), "float32"),
            "ln1_b": np.zeros((L, D), "float32"),
            "qkv": np.asarray(rng.randn(L, D, 3 * D) * (0.5 / np.sqrt(D)),
                              "float32"),
            "out": np.asarray(rng.randn(L, D, D) * (0.5 / np.sqrt(D)),
                              "float32"),
            "ln2_g": np.ones((L, D), "float32"),
            "ln2_b": np.zeros((L, D), "float32"),
            "w1": np.asarray(rng.randn(L, D, Hff) * (0.5 / np.sqrt(D)),
                             "float32"),
            "b1": np.zeros((L, Hff), "float32"),
            "w2": np.asarray(rng.randn(L, Hff, D) * (0.5 / np.sqrt(Hff)),
                             "float32"),
            "b2": np.zeros((L, D), "float32"),
        },
    }

    def ln(x, g, b):
        mu = x.mean(-1, keepdims=True)
        var = ((x - mu) ** 2).mean(-1, keepdims=True)
        return (x - mu) / jnp.sqrt(var + 1e-5) * g + b

    def decoder_stage(sp, x):
        """One pre-norm decoder layer; shape-preserving, so the same
        program runs on every pipeline stage."""
        b, t, d = x.shape
        h = ln(x, sp["ln1_g"], sp["ln1_b"])
        q, k, v = jnp.split(h @ sp["qkv"], 3, axis=-1)
        split = lambda z: z.reshape(b, t, H, Dh).transpose(0, 2, 1, 3)
        s = jnp.einsum("bhqd,bhkd->bhqk", split(q), split(k)) / np.sqrt(Dh)
        mask = jnp.tril(jnp.ones((t, t), bool))
        s = jnp.where(mask, s, -1e30)
        att = jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(s, axis=-1),
                         split(v))
        x = x + att.transpose(0, 2, 1, 3).reshape(b, t, d) @ sp["out"]
        h = ln(x, sp["ln2_g"], sp["ln2_b"])
        return x + jax.nn.relu(h @ sp["w1"] + sp["b1"]) @ sp["w2"]

    def loss_fn(params, tokens, targets):
        T = tokens.shape[1]
        x = params["embed"][tokens] + params["pos"][:T][None]
        x = pipeline_apply(decoder_stage, params["stages"], x, mesh,
                           axis_name="pp")
        logits = x @ params["embed"].T
        lp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        return -jnp.take_along_axis(lp, targets[..., None], axis=-1).mean()

    # pytree adam (the flat-dict helper in parallel.data_parallel serves
    # ShardedTrainer; stage params here are a nested tree)
    zeros = lambda t: jtu.tree_map(jnp.zeros_like, t)
    opt_state = {"m": zeros(params), "v": zeros(params),
                 "t": jnp.zeros((), jnp.int32)}

    def adam(params, grads, st, lr, b1=0.9, b2=0.999, eps=1e-8):
        t = st["t"] + 1
        m = jtu.tree_map(lambda m, g: b1 * m + (1 - b1) * g,
                         st["m"], grads)
        v = jtu.tree_map(lambda v, g: b2 * v + (1 - b2) * g * g,
                         st["v"], grads)
        scale = lr * jnp.sqrt(1 - b2 ** t) / (1 - b1 ** t)
        params = jtu.tree_map(
            lambda p, m, v: p - scale * m / (jnp.sqrt(v) + eps),
            params, m, v)
        return params, {"m": m, "v": v, "t": t}

    @jax.jit     # one cache entry per bucket length — bucketing contract
    def step(params, opt_state, tokens, targets):
        nll, grads = jax.value_and_grad(loss_fn)(params, tokens, targets)
        params, opt_state = adam(params, grads, opt_state, lr=args.lr)
        return params, opt_state, nll

    tok_sh = shard_on(mesh, "dp", 0, 2)
    first = last = None
    per_bucket = {}
    for i, (blen, toks, tgts) in enumerate(bucketed_batches(
            corpus, rng, sorted(args.buckets), B, args.steps)):
        toks = jax.device_put(jnp.asarray(toks), tok_sh)
        tgts = jax.device_put(jnp.asarray(tgts), tok_sh)
        params, opt_state, nll = step(params, opt_state, toks, tgts)
        nll = float(nll)
        per_bucket.setdefault(blen, []).append(nll)
        first = first if first is not None else nll
        last = nll
        if i % 10 == 0:
            print("step %3d bucket %3d nll %.4f" % (i, blen, nll))
    print("buckets trained:", {k: len(v) for k, v in
                               sorted(per_bucket.items())})
    print("first nll %.4f -> last %.4f" % (first, last))
    assert last < first, "no learning"
    print("PIPELINE_BUCKETED_OK")


if __name__ == "__main__":
    main()
