"""Post-training int8 quantization (reference: example/quantization/
imagenet_gen_qsym.py + python/mxnet/contrib/quantization.py:412).

Quantizes a ResNet-18, calibrates activation ranges (min-max or
KL-entropy) on a calibration batch, and compares fp32 vs int8 top-1
agreement and latency on synthetic data.

Usage: python quantize_resnet.py [--calib-mode entropy] [--cpu]
"""
import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--batch-size", type=int, default=16)
    p.add_argument("--calib-mode", default="naive",
                   choices=["naive", "entropy", "none"])
    p.add_argument("--image-size", type=int, default=32)
    p.add_argument("--cpu", action="store_true")
    args = p.parse_args()
    if args.cpu:
        os.environ.setdefault(
            "XLA_FLAGS", "--xla_force_host_platform_device_count=8")
        import jax
        jax.config.update("jax_platforms", "cpu")
    import mxnet_tpu as mx
    from mxnet_tpu import nd
    from mxnet_tpu.gluon.model_zoo import vision

    rng = np.random.RandomState(0)
    S = args.image_size
    net = vision.resnet18_v1(classes=10)
    net.initialize()
    xcal = rng.randn(args.batch_size, 3, S, S).astype("float32")
    net(mx.nd.array(xcal))

    data = mx.sym.var("data")
    out = net(data)
    arg_names = set(out.list_arguments())
    params = {p_.name: p_.data()
              for p_ in net.collect_params().values()}
    arg_params = {k: v for k, v in params.items() if k in arg_names}
    aux_params = {k: v for k, v in params.items() if k not in arg_names}

    calib = mx.io.NDArrayIter(
        xcal, np.zeros((xcal.shape[0],), "float32"),
        batch_size=args.batch_size, label_name="softmax_label")
    qsym, qargs, qauxs = mx.contrib.quantization.quantize_model(
        out, arg_params, aux_params, calib_data=calib,
        calib_mode=args.calib_mode, quantize_mode="full",
        label_names=None)

    xtest = rng.randn(args.batch_size, 3, S, S).astype("float32")

    def scorer(s, a, au):
        ex = s.bind(None, args={**a, "data": nd.array(xtest)},
                    aux_states=dict(au), grad_req="null")

        def run():
            return ex.forward(is_train=False)[0].asnumpy()
        return run

    run_fp32 = scorer(out, arg_params, aux_params)
    run_int8 = scorer(qsym, qargs, qauxs)
    ref, got = run_fp32(), run_int8()
    agree = float((ref.argmax(1) == got.argmax(1)).mean())

    for run in (run_fp32, run_int8):  # warm both compiled programs
        run()
    t0 = time.perf_counter(); [run_fp32() for _ in range(5)]
    t_fp32 = (time.perf_counter() - t0) / 5
    t0 = time.perf_counter(); [run_int8() for _ in range(5)]
    t_int8 = (time.perf_counter() - t0) / 5

    print("calib_mode=%s  top-1 agreement fp32 vs int8: %.3f"
          % (args.calib_mode, agree))
    print("latency b%d: fp32 %.2f ms  int8 %.2f ms"
          % (args.batch_size, t_fp32 * 1e3, t_int8 * 1e3))
    if args.calib_mode == "naive":
        # KL-entropy thresholds assume peaked real-data histograms;
        # on this synthetic gaussian demo only min-max is a hard gate
        assert agree >= 0.7, "int8 model diverged from fp32"
    return agree


if __name__ == "__main__":
    main()
