"""Multi-task training: one trunk, two supervised heads (reference:
example/multi-task/example_multi_task.py).

The reference trains MNIST digit classification and a second task from
one shared trunk by Grouping two SoftmaxOutputs and feeding a
two-label iterator. Same structure here on synthetic 'digits': task 1
predicts the class (10-way), task 2 predicts class parity (2-way) —
the heads share all trunk features, and the Module API drives the
grouped symbol with two labels and a per-task metric.

Usage: python multi_task.py [--epochs 8] [--cpu]
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np


def make_digits(rng, protos, n, noise=0.35):
    """Samples around shared 10-class prototypes in 64-d."""
    y = rng.randint(0, 10, size=n)
    X = protos[y] + rng.randn(n, 64).astype("float32") * noise
    return X, y.astype("float32"), (y % 2).astype("float32")


def build_network(mx):
    data = mx.sym.Variable("data")
    h = mx.sym.Activation(mx.sym.FullyConnected(data, num_hidden=128),
                          act_type="relu")
    h = mx.sym.Activation(mx.sym.FullyConnected(h, num_hidden=64),
                          act_type="relu")
    digit = mx.sym.SoftmaxOutput(
        mx.sym.FullyConnected(h, num_hidden=10),
        mx.sym.Variable("digit_label"), name="digit")
    parity = mx.sym.SoftmaxOutput(
        mx.sym.FullyConnected(h, num_hidden=2),
        mx.sym.Variable("parity_label"), name="parity")
    return mx.sym.Group([digit, parity])


class MultiAccuracy(object):
    """Per-head accuracy over a Group's outputs (reference uses a custom
    Multi_Accuracy EvalMetric; the shape is the same)."""

    def __init__(self, names):
        self.names = names
        self.reset()

    def reset(self):
        self.hits = [0] * len(self.names)
        self.total = 0

    def update(self, labels, preds):
        for i, (l, p) in enumerate(zip(labels, preds)):
            self.hits[i] += int(
                (p.asnumpy().argmax(1) == l.asnumpy()).sum())
        self.total += labels[0].shape[0]

    def get_name_value(self):
        return [(n, h / max(self.total, 1))
                for n, h in zip(self.names, self.hits)]


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=8)
    ap.add_argument("--batch", type=int, default=64)
    ap.add_argument("--train-size", type=int, default=4096)
    ap.add_argument("--cpu", action="store_true")
    args = ap.parse_args()
    if args.cpu:
        import jax
        jax.config.update("jax_platforms", "cpu")

    import mxnet_tpu as mx

    rng = np.random.RandomState(4)
    protos = rng.randn(10, 64).astype("float32")
    X, y_digit, y_parity = make_digits(rng, protos, args.train_size)

    mod = mx.mod.Module(build_network(mx), data_names=("data",),
                        label_names=("digit_label", "parity_label"),
                        context=mx.cpu())
    it = mx.io.NDArrayIter(
        {"data": X},
        {"digit_label": y_digit, "parity_label": y_parity},
        batch_size=args.batch, shuffle=True)
    mod.fit(it, num_epoch=args.epochs, optimizer="adam",
            optimizer_params=(("learning_rate", 2e-3),))

    # joint evaluation with a per-head metric
    Xt, yt_d, yt_p = make_digits(rng, protos, 1024)
    mod.forward(mx.io.DataBatch(data=[mx.nd.array(Xt)]), is_train=False)
    digit_out, parity_out = mod.get_outputs()
    metric = MultiAccuracy(["digit_acc", "parity_acc"])
    metric.update([mx.nd.array(yt_d), mx.nd.array(yt_p)],
                  [digit_out, parity_out])
    results = dict(metric.get_name_value())
    print("digit acc %.3f  parity acc %.3f"
          % (results["digit_acc"], results["parity_acc"]))
    assert results["digit_acc"] > 0.9 and results["parity_acc"] > 0.9
    print("MULTI_TASK_OK")


if __name__ == "__main__":
    main()
