"""REINFORCE policy gradient (reference: example/reinforcement-learning
— A3C/DQN on gym; this is the dependency-free core capability).

A 5x5 gridworld (start corner, goal corner, step cost): the agent
samples actions from a learned softmax policy, gets Monte-Carlo
returns, and ascends the policy gradient through autograd — proving
sampling + log-prob losses + per-episode variable-length credit
assignment on the eager path.

Usage: python reinforce_gridworld.py [--episodes 400] [--cpu]
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np

SIZE = 5
ACTIONS = [(-1, 0), (1, 0), (0, -1), (0, 1)]
MAX_STEPS = 30


def run_episode(policy_logits_fn, rng):
    """Roll one episode; returns (states, actions, rewards)."""
    pos = (0, 0)
    states, actions, rewards = [], [], []
    for _ in range(MAX_STEPS):
        s = np.zeros((SIZE, SIZE), "float32")
        s[pos] = 1.0
        logits = policy_logits_fn(s.reshape(1, -1))[0]
        p = np.exp(logits - logits.max())
        p /= p.sum()
        a = rng.choice(4, p=p)
        dr, dc = ACTIONS[a]
        nxt = (min(max(pos[0] + dr, 0), SIZE - 1),
               min(max(pos[1] + dc, 0), SIZE - 1))
        done = nxt == (SIZE - 1, SIZE - 1)
        states.append(s.reshape(-1))
        actions.append(a)
        rewards.append(10.0 if done else -1.0)
        pos = nxt
        if done:
            break
    return states, actions, rewards


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--episodes", type=int, default=400)
    ap.add_argument("--gamma", type=float, default=0.97)
    ap.add_argument("--lr", type=float, default=1e-2)
    ap.add_argument("--cpu", action="store_true")
    args = ap.parse_args()
    if args.cpu:
        import jax
        jax.config.update("jax_platforms", "cpu")

    import mxnet_tpu as mx
    from mxnet_tpu import nd, autograd, gluon
    from mxnet_tpu.gluon import nn

    rng = np.random.RandomState(0)
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(32, activation="relu"), nn.Dense(4))
    net.initialize(mx.init.Xavier())
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": args.lr})

    def logits_np(s):
        return net(nd.array(s)).asnumpy()

    lengths = []
    for ep in range(args.episodes):
        states, actions, rewards = run_episode(logits_np, rng)
        # discounted returns, normalized as the baseline
        G, g = [], 0.0
        for r in reversed(rewards):
            g = r + args.gamma * g
            G.append(g)
        G = np.asarray(G[::-1], "float32")
        G = (G - G.mean()) / (G.std() + 1e-6)
        S = nd.array(np.stack(states))
        A = np.asarray(actions)
        with autograd.record():
            logits = net(S)
            logp = nd.log_softmax(logits, axis=-1)
            chosen = nd.pick(logp, nd.array(A.astype("float32")), axis=1)
            loss = -nd.sum(chosen * nd.array(G)) / len(A)
        loss.backward()
        trainer.step(1)
        lengths.append(len(actions))
        if ep % 50 == 0:
            print("episode %4d  mean length (last 50): %.1f"
                  % (ep, np.mean(lengths[-50:])))

    early = np.mean(lengths[:50])
    late = np.mean(lengths[-50:])
    print("mean episode length: first50 %.1f -> last50 %.1f (optimal 8)"
          % (early, late))
    assert late < 0.6 * early and late < 14, "policy did not improve"
    print("REINFORCE_OK")


if __name__ == "__main__":
    main()
