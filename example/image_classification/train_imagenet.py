"""ImageNet-style training on record files — the reference's flagship
example (example/image-classification/train_imagenet.py + common/fit.py).

Input: an ImageNet .rec (pack with tools/im2rec or the reference's
im2rec) via the threaded mx.io.ImageRecordIter; or --benchmark 1 for
synthetic data (reference common/fit.py benchmark mode).

TPU configuration: NHWC layout + bf16 mixed precision + one fused XLA
program per step (see PERF.md). The input pipeline (C++ record loader ->
N decode threads -> prefetch queue) runs on host cores concurrently with
the device step.

Usage:
  python train_imagenet.py --benchmark 1                 # synthetic
  python train_imagenet.py --data-train train.rec        # real records
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))
import time

import numpy as np


def parse_args():
    p = argparse.ArgumentParser()
    p.add_argument("--network", default="resnet50_v1")
    p.add_argument("--batch-size", type=int, default=128)
    p.add_argument("--image-shape", default="3,224,224")
    p.add_argument("--num-classes", type=int, default=1000)
    p.add_argument("--num-epochs", type=int, default=1)
    p.add_argument("--max-batches", type=int, default=0,
                   help="stop an epoch early (0 = full epoch)")
    p.add_argument("--lr", type=float, default=0.1)
    p.add_argument("--momentum", type=float, default=0.9)
    p.add_argument("--wd", type=float, default=1e-4)
    p.add_argument("--data-train", default=None, help=".rec file")
    p.add_argument("--preprocess-threads", type=int, default=8)
    p.add_argument("--benchmark", type=int, default=0)
    p.add_argument("--layout", default="NHWC", choices=["NCHW", "NHWC"])
    p.add_argument("--dtype", default="bfloat16",
                   choices=["float32", "bfloat16"])
    p.add_argument("--cpu", action="store_true")
    return p.parse_args()


def main():
    args = parse_args()
    if args.cpu:
        os.environ.setdefault(
            "XLA_FLAGS", "--xla_force_host_platform_device_count=8")
        import jax
        jax.config.update("jax_platforms", "cpu")
    import jax
    jax.config.update("jax_default_matmul_precision", "bfloat16")
    import mxnet_tpu as mx
    from mxnet_tpu import gluon
    from mxnet_tpu.gluon.model_zoo import vision
    from mxnet_tpu.parallel import make_mesh, ShardedTrainer

    c, h, w = map(int, args.image_shape.split(","))
    nhwc = args.layout == "NHWC"
    net = getattr(vision, args.network)(classes=args.num_classes,
                                        layout=args.layout)
    import contextlib
    try:
        mat_ctx = jax.default_device(jax.local_devices(backend="cpu")[0])
    except Exception:
        mat_ctx = contextlib.nullcontext()
    with mat_ctx:
        net.initialize()
        shape = (1, h, w, c) if nhwc else (1, c, h, w)
        net.infer_shape(mx.nd.zeros(shape))
        for p in net.collect_params().values():
            p._finish_deferred_init()

    loss = gluon.loss.SoftmaxCrossEntropyLoss()
    st = ShardedTrainer(
        net, lambda o, l: loss(o, l), "sgd",
        {"learning_rate": args.lr, "momentum": args.momentum,
         "wd": args.wd},
        mesh=make_mesh({"dp": len(jax.devices())}),
        compute_dtype=None if args.dtype == "float32" else args.dtype)

    if args.benchmark or not args.data_train:
        rng = np.random.RandomState(0)
        bshape = (args.batch_size, h, w, c) if nhwc \
            else (args.batch_size, c, h, w)
        x = rng.randn(*bshape).astype("float32")
        y = (rng.rand(args.batch_size) * args.num_classes).astype("f")
        batches = [(x, y)] * (args.max_batches or 50)

        def epoch_iter():
            return iter(batches)
    else:
        it = mx.io.ImageRecordIter(
            path_imgrec=args.data_train, data_shape=(c, h, w),
            batch_size=args.batch_size, shuffle=True, rand_crop=True,
            rand_mirror=True, layout=args.layout,
            preprocess_threads=args.preprocess_threads,
            round_batch=False)

        def epoch_iter():
            it.reset()
            return ((b.data[0], b.label[0]) for b in it)

    for epoch in range(args.num_epochs):
        t0 = time.perf_counter()
        n, last = 0, None
        for i, (xb, yb) in enumerate(epoch_iter()):
            last = st.step(xb, yb)
            n += args.batch_size
            if args.max_batches and i + 1 >= args.max_batches:
                break
        last.wait_to_read()
        dt = time.perf_counter() - t0
        print("epoch %d: %.1f img/s, loss %.4f"
              % (epoch, n / dt, float(last.asnumpy())))
    st.copy_params_to_net()


if __name__ == "__main__":
    main()
