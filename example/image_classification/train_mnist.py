"""LeNet on MNIST via the Module API — the reference's canonical first
example (reference: example/image-classification/train_mnist.py).

Runs on real MNIST if the idx files are under --data-dir, otherwise on
synthetic data (the reference's `--benchmark 1` random-data mode,
example/image-classification/common/fit.py).

Usage: python train_mnist.py [--epochs 3] [--batch-size 64] [--cpu]
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))  # run from a source checkout

import numpy as np


def lenet(num_classes=10):
    import mxnet_tpu as mx
    data = mx.sym.var("data")
    c1 = mx.sym.Convolution(data, kernel=(5, 5), num_filter=20)
    a1 = mx.sym.Activation(c1, act_type="tanh")
    p1 = mx.sym.Pooling(a1, pool_type="max", kernel=(2, 2), stride=(2, 2))
    c2 = mx.sym.Convolution(p1, kernel=(5, 5), num_filter=50)
    a2 = mx.sym.Activation(c2, act_type="tanh")
    p2 = mx.sym.Pooling(a2, pool_type="max", kernel=(2, 2), stride=(2, 2))
    f = mx.sym.Flatten(p2)
    fc1 = mx.sym.FullyConnected(f, num_hidden=500)
    a3 = mx.sym.Activation(fc1, act_type="tanh")
    fc2 = mx.sym.FullyConnected(a3, num_hidden=num_classes)
    return mx.sym.SoftmaxOutput(fc2, name="softmax")


def get_data(args):
    import mxnet_tpu as mx
    try:
        from mxnet_tpu.gluon.data.vision import MNIST
        train = MNIST(root=args.data_dir, train=True)
        val = MNIST(root=args.data_dir, train=False)
        xt = train._data.asnumpy().transpose(0, 3, 1, 2) / 255.0
        xv = val._data.asnumpy().transpose(0, 3, 1, 2) / 255.0
        yt, yv = train._label, val._label
        print("using real MNIST from", args.data_dir)
    except RuntimeError:
        print("MNIST files not found; using synthetic data "
              "(--benchmark mode)")
        rng = np.random.RandomState(0)
        # learnable stand-in: 10 spatial pattern classes (bars /
        # checkers / blobs), shift-jittered + noise
        n = 2400
        yt = rng.randint(0, 10, n)
        xt = np.zeros((n, 1, 28, 28), "float32")
        xs = np.arange(28)
        for i in range(n):
            c = int(yt[i])
            if c < 4:
                ang = c * np.pi / 4
                g = np.cos(ang) * xs[None, :] + np.sin(ang) * xs[:, None]
                img = (np.sin(2 * np.pi * g / 6) > 0).astype("float32")
            elif c < 7:
                k = [2, 4, 7][c - 4]
                img = ((xs[None, :] // k + xs[:, None] // k) % 2
                       ).astype("float32")
            else:
                r = [4, 8, 12][c - 7]
                cx, cy = rng.randint(9, 19, 2)
                d2 = (xs[None, :] - cx) ** 2 + (xs[:, None] - cy) ** 2
                img = (d2 < r * r).astype("float32")
            sh = rng.randint(-3, 4, 2)
            img = np.roll(np.roll(img, sh[0], 0), sh[1], 1)
            xt[i, 0] = img + rng.randn(28, 28) * 0.25
        yt = yt.astype("float32")
        xv, yv = xt[:500], yt[:500]
    train_iter = mx.io.NDArrayIter(xt.astype("float32"), yt,
                                   args.batch_size, shuffle=True)
    val_iter = mx.io.NDArrayIter(xv.astype("float32"), yv,
                                 args.batch_size)
    return train_iter, val_iter


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=6)
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--lr", type=float, default=0.05)
    p.add_argument("--data-dir",
                   default=os.path.join("~", ".mxnet", "datasets",
                                        "mnist"))
    p.add_argument("--cpu", action="store_true",
                   help="force the CPU backend")
    args = p.parse_args()
    if args.cpu:
        os.environ.setdefault(
            "XLA_FLAGS", "--xla_force_host_platform_device_count=8")
        import jax
        jax.config.update("jax_platforms", "cpu")
    import mxnet_tpu as mx

    np.random.seed(1)   # NDArrayIter(shuffle=True) draws from the
    #                       global numpy RNG — pin it for reproducibility
    train_iter, val_iter = get_data(args)
    mod = mx.mod.Module(lenet(), label_names=["softmax_label"])
    mod.fit(train_iter, eval_data=val_iter, num_epoch=args.epochs,
            optimizer="sgd",
            optimizer_params={"learning_rate": args.lr, "momentum": 0.9},
            batch_end_callback=mx.callback.Speedometer(args.batch_size,
                                                       20))
    score = dict(mod.score(val_iter, "acc"))
    print("final accuracy:", score)
    assert score["accuracy"] > 0.8, "LeNet failed to learn: %s" % score
    print("MNIST_EXAMPLE_OK")


if __name__ == "__main__":
    main()
