"""Sequence tagging / NER (reference: example/named_entity_recognition
— bi-LSTM tagger with padded variable-length sentences).

Proves variable-length sequence tagging: a bi-LSTM emits a tag per
token, sentences are padded to a fixed length, and the loss/metric are
masked by true sequence length (SequenceMask semantics). The synthetic
grammar embeds multi-token 'entities' whose tags (B/I/O) depend on
context, so per-token memorization cannot solve it.

Usage: python ner_tagger.py [--epochs 12] [--cpu]
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np

V = 40          # word vocab: 0=pad, 1..9 triggers, rest filler
TAGS = 3        # O, B-ENT, I-ENT
T = 12


def make_sentences(rng, n):
    """A 'trigger' word starts an entity: triggers 1-5 bind the next
    token, triggers 6-9 the next two — the continuation tokens are
    ordinary filler words, so the tag is decidable only from context
    (and the trigger word fully determines it)."""
    X = np.zeros((n, T), "float32")
    Y = np.zeros((n, T), "float32")
    L = np.zeros((n,), "float32")
    for i in range(n):
        ln = rng.randint(6, T + 1)
        L[i] = ln
        t = 0
        while t < ln:
            if rng.rand() < 0.25 and t + 3 < ln:
                trig = rng.randint(1, 10)
                body = 1 if trig <= 5 else 2
                X[i, t] = trig
                Y[i, t] = 1                           # B-ENT
                for k in range(1, body + 1):
                    X[i, t + k] = rng.randint(10, V)
                    Y[i, t + k] = 2                   # I-ENT
                t += body + 1
            else:
                X[i, t] = rng.randint(10, V)
                Y[i, t] = 0                           # O
                t += 1
    return X, Y, L


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=12)
    ap.add_argument("--batch", type=int, default=64)
    ap.add_argument("--train-size", type=int, default=4096)
    ap.add_argument("--cpu", action="store_true")
    args = ap.parse_args()
    if args.cpu:
        import jax
        jax.config.update("jax_platforms", "cpu")

    import mxnet_tpu as mx
    from mxnet_tpu import nd, autograd, gluon
    from mxnet_tpu.gluon import nn

    rng = np.random.RandomState(0)
    Xtr, Ytr, Ltr = make_sentences(rng, args.train_size)
    Xte, Yte, Lte = make_sentences(rng, 512)

    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Embedding(V, 32),
                gluon.rnn.LSTM(48, layout="NTC", bidirectional=True),
                nn.Dense(TAGS, flatten=False))
    net.initialize(mx.init.Xavier())
    net(nd.array(Xtr[:2]))
    net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 3e-3})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

    def mask(lengths):
        return (np.arange(T)[None, :] < lengths[:, None]).astype("float32")

    B = args.batch
    for epoch in range(args.epochs):
        perm = rng.permutation(len(Xtr))
        tot = 0.0
        for b in range(len(Xtr) // B):
            idx = perm[b * B:(b + 1) * B]
            x, y = nd.array(Xtr[idx]), nd.array(Ytr[idx])
            m = nd.array(mask(Ltr[idx]))
            with autograd.record():
                # per-token loss, masked to the true lengths
                loss = loss_fn(net(x), y, m.expand_dims(-1))
                loss = nd.sum(loss) / nd.sum(m)
            loss.backward()
            trainer.step(B)
            tot += float(loss.asnumpy())
        print("epoch %2d masked loss %.4f" % (epoch, tot / (len(Xtr) // B)))

    pred = net(nd.array(Xte)).asnumpy().argmax(-1)
    m = mask(Lte).astype(bool)
    tag_acc = (pred == Yte)[m].mean()
    ent_mask = m & (Yte > 0)
    ent_acc = (pred == Yte)[ent_mask].mean()
    print("token acc %.3f  entity-token acc %.3f" % (tag_acc, ent_acc))
    assert tag_acc > 0.95 and ent_acc > 0.9, "tagger failed"
    print("NER_OK")


if __name__ == "__main__":
    main()
