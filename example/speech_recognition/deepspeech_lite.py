"""Speech recognition: conv frontend + BiLSTM + CTC (reference:
example/speech_recognition — a DeepSpeech-style acoustic model).

The full pipeline on synthetic speech: each 'word' is a sequence of
'phonemes', each phoneme renders as a band-limited tone burst in a
spectrogram (with speaker-rate jitter); the model is Conv2D frequency
feature extraction -> bidirectional LSTM over time -> per-frame class
logits -> contrib.CTCLoss, decoded greedy. The same architecture shape
as the reference's acoustic model, scaled to run on this VM.

Usage: python deepspeech_lite.py [--epochs 12] [--cpu]
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np

N_PHONE = 6          # classes 1..6; 0 = CTC blank
FREQ = 16            # spectrogram bins
T = 24               # frames
MAX_LEN = 3          # phonemes per word


def render_word(rng, phones):
    """Each phoneme excites a distinct frequency band for 2-4 frames,
    with silence gaps — alignment is unknown, which is CTC's job."""
    spec = rng.randn(T, FREQ).astype("float32") * 0.15
    t = rng.randint(0, 3)
    for p in phones:
        t += rng.randint(1, 3)
        dur = rng.randint(2, 5)
        band = slice(2 * (p - 1), 2 * (p - 1) + 3)
        for _ in range(dur):
            if t >= T:
                break
            spec[t, band] += 1.0 + 0.2 * rng.randn()
            t += 1
    return spec


def make_dataset(rng, n):
    X = np.zeros((n, 1, T, FREQ), "float32")
    Y = np.zeros((n, MAX_LEN), "float32")
    for i in range(n):
        k = rng.randint(1, MAX_LEN + 1)
        phones = rng.randint(1, N_PHONE + 1, size=k)
        X[i, 0] = render_word(rng, phones)
        Y[i, :k] = phones
    return X, Y


def greedy_decode(logits):
    path = logits.argmax(-1)
    out = []
    for seq in path:
        prev, dec = -1, []
        for c in seq:
            if c != prev and c != 0:
                dec.append(int(c))
            prev = c
        out.append(dec)
    return out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=14)
    ap.add_argument("--batch", type=int, default=32)
    ap.add_argument("--hidden", type=int, default=64)
    ap.add_argument("--train-size", type=int, default=512)
    ap.add_argument("--lr", type=float, default=5e-3)
    ap.add_argument("--loss-only", action="store_true",
                    help="smoke mode: assert loss collapse, not decode "
                         "accuracy (short runs sit in the all-blank "
                         "plateau)")
    ap.add_argument("--cpu", action="store_true")
    args = ap.parse_args()
    if args.cpu:
        import jax
        jax.config.update("jax_platforms", "cpu")

    import mxnet_tpu as mx
    from mxnet_tpu import nd, autograd, gluon
    from mxnet_tpu.gluon import nn

    rng = np.random.RandomState(11)
    Xtr, Ytr = make_dataset(rng, args.train_size)
    Xte, Yte = make_dataset(rng, 128)

    class Acoustic(gluon.HybridBlock):
        def __init__(self, **kw):
            super().__init__(**kw)
            with self.name_scope():
                self.conv = nn.Conv2D(8, (3, 3), padding=(1, 1),
                                      activation="relu")
                self.lstm = gluon.rnn.LSTM(args.hidden, layout="NTC",
                                           bidirectional=True)
                self.head = nn.Dense(N_PHONE + 1, flatten=False)

        def hybrid_forward(self, F, x):
            h = self.conv(x)                         # (N, C, T, F)
            h = F.transpose(h, axes=(0, 2, 1, 3))    # (N, T, C, F)
            h = F.reshape(h, shape=(0, 0, -1))       # (N, T, C*F)
            return self.head(self.lstm(h))           # (N, T, classes)

    net = Acoustic()
    net.initialize(mx.init.Xavier())
    net(nd.array(Xtr[:2]))
    net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": args.lr})

    B = args.batch
    n_batches = len(Xtr) // B
    first_loss = None
    for epoch in range(args.epochs):
        perm = rng.permutation(len(Xtr))
        tot = 0.0
        for b in range(n_batches):
            idx = perm[b * B:(b + 1) * B]
            x, y = nd.array(Xtr[idx]), nd.array(Ytr[idx])
            with autograd.record():
                logits = net(x)
                loss = nd.mean(nd.contrib.CTCLoss(
                    nd.transpose(logits, axes=(1, 0, 2)), y))
            loss.backward()
            trainer.step(B)
            tot += float(loss.asnumpy())
        tot /= n_batches
        first_loss = first_loss if first_loss is not None else tot
        print("epoch %2d  ctc loss %.4f" % (epoch, tot))

    logits = net(nd.array(Xte)).asnumpy()
    decoded = greedy_decode(logits)
    hits = sum(dec == [int(v) for v in truth if v > 0]
               for dec, truth in zip(decoded, Yte))
    acc = hits / len(Yte)
    print("exact-word accuracy: %.3f" % acc)
    if args.loss_only:
        assert tot < 0.5 * first_loss, "CTC loss did not collapse"
    else:
        assert acc > 0.6, "acoustic model failed"
    print("SPEECH_OK")


if __name__ == "__main__":
    main()
