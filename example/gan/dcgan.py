"""DCGAN (reference: example/gan/dcgan.py) — Gluon generator/discriminator
pair with alternating updates.

Trains on a synthetic two-moons-in-pixel-space dataset by default so the
example is self-contained; point --mnist at an idx file for the real
thing. TPU-native notes: both nets hybridize to single XLA programs; the
two optimizer steps stay separate (G and D alternate, as in the
reference).

Usage: python dcgan.py [--steps 200] [--cpu]
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np


def build_generator(nz, ngf=32):
    from mxnet_tpu.gluon import nn
    net = nn.HybridSequential(prefix="gen_")
    with net.name_scope():
        # nz -> 4x4 -> 8x8 -> 16x16 -> 32x32
        net.add(nn.Dense(ngf * 4 * 4 * 4, use_bias=False),
                nn.BatchNorm(), nn.Activation("relu"),
                nn.HybridLambda(
                    lambda F, x: F.reshape(x, shape=(-1, ngf * 4, 4, 4))))
        net.add(nn.Conv2DTranspose(ngf * 2, 4, strides=2, padding=1,
                                   use_bias=False),
                nn.BatchNorm(), nn.Activation("relu"))
        net.add(nn.Conv2DTranspose(ngf, 4, strides=2, padding=1,
                                   use_bias=False),
                nn.BatchNorm(), nn.Activation("relu"))
        net.add(nn.Conv2DTranspose(1, 4, strides=2, padding=1),
                nn.Activation("tanh"))
    return net


def build_discriminator(ndf=32):
    from mxnet_tpu.gluon import nn
    net = nn.HybridSequential(prefix="disc_")
    with net.name_scope():
        net.add(nn.Conv2D(ndf, 4, strides=2, padding=1),
                nn.LeakyReLU(0.2))
        net.add(nn.Conv2D(ndf * 2, 4, strides=2, padding=1,
                          use_bias=False),
                nn.BatchNorm(), nn.LeakyReLU(0.2))
        net.add(nn.Conv2D(ndf * 4, 4, strides=2, padding=1,
                          use_bias=False),
                nn.BatchNorm(), nn.LeakyReLU(0.2))
        net.add(nn.Flatten(), nn.Dense(1))
    return net


def synthetic_batch(rng, n):
    """32x32 'images': soft blobs at class-dependent positions."""
    yy, xx = np.mgrid[0:32, 0:32] / 31.0
    out = np.empty((n, 1, 32, 32), "float32")
    for i in range(n):
        cx, cy = rng.rand(2) * 0.6 + 0.2
        r = 0.08 + rng.rand() * 0.08
        out[i, 0] = np.exp(-((xx - cx) ** 2 + (yy - cy) ** 2) / (2 * r * r))
    return out * 2 - 1  # tanh range


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--steps", type=int, default=200)
    p.add_argument("--batch-size", type=int, default=32)
    p.add_argument("--nz", type=int, default=16)
    p.add_argument("--lr", type=float, default=2e-4)
    p.add_argument("--cpu", action="store_true")
    args = p.parse_args()
    if args.cpu:
        os.environ.setdefault(
            "XLA_FLAGS", "--xla_force_host_platform_device_count=8")
        import jax
        jax.config.update("jax_platforms", "cpu")
    import mxnet_tpu as mx
    from mxnet_tpu import gluon, autograd

    rng = np.random.RandomState(0)
    gen = build_generator(args.nz)
    disc = build_discriminator()
    gen.initialize(mx.init.Normal(0.02))
    disc.initialize(mx.init.Normal(0.02))
    gen(mx.nd.zeros((1, args.nz)))
    disc(mx.nd.zeros((1, 1, 32, 32)))
    gen.hybridize()
    disc.hybridize()

    bce = gluon.loss.SigmoidBinaryCrossEntropyLoss()
    opt = {"learning_rate": args.lr, "beta1": 0.5}
    trainer_g = gluon.Trainer(gen.collect_params(), "adam", opt)
    trainer_d = gluon.Trainer(disc.collect_params(), "adam", opt)

    B = args.batch_size
    ones = mx.nd.ones((B,))
    zeros = mx.nd.zeros((B,))
    d_hist, g_hist = [], []
    for step in range(args.steps):
        real = mx.nd.array(synthetic_batch(rng, B))
        noise = mx.nd.array(rng.randn(B, args.nz).astype("float32"))
        # D step: real -> 1, fake -> 0
        with autograd.record():
            out_real = disc(real).reshape((-1,))
            fake = gen(noise)
            out_fake = disc(fake.detach()).reshape((-1,))
            loss_d = bce(out_real, ones) + bce(out_fake, zeros)
        loss_d.backward()
        trainer_d.step(B)
        # G step: fool D
        with autograd.record():
            out = disc(gen(noise)).reshape((-1,))
            loss_g = bce(out, ones)
        loss_g.backward()
        trainer_g.step(B)
        d_hist.append(float(loss_d.mean().asscalar()))
        g_hist.append(float(loss_g.mean().asscalar()))
        if step % 20 == 0 or step == args.steps - 1:
            print("step %4d  loss_D %.4f  loss_G %.4f"
                  % (step, d_hist[-1], g_hist[-1]))
    # a working GAN keeps D near equilibrium (not collapsed to 0)
    print("final loss_D %.4f loss_G %.4f" % (d_hist[-1], g_hist[-1]))
    return d_hist, g_hist


if __name__ == "__main__":
    main()
