"""Dense-Sparse-Dense training (reference: example/dsd — Han et al.:
train dense, prune to a sparse mask and retrain, then release the mask
and retrain dense).

Proves the weight-masking workflow: magnitude pruning computed from
trained weights, the mask enforced through the sparse phase by zeroing
masked gradients after backward (set_data on the live parameters), and
a final dense phase recovering accuracy at equal-or-better loss than
the first dense pass.

Usage: python dsd_train.py [--epochs-per-phase 4] [--cpu]
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np


def make_digits(rng, protos, n, noise=0.9):
    y = rng.randint(0, 10, n)
    X = protos[y] + rng.randn(n, protos.shape[1]).astype("float32") * noise
    return X.astype("float32"), y.astype("float32")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs-per-phase", type=int, default=4)
    ap.add_argument("--batch", type=int, default=128)
    ap.add_argument("--sparsity", type=float, default=0.95)
    ap.add_argument("--train-size", type=int, default=4096)
    ap.add_argument("--cpu", action="store_true")
    args = ap.parse_args()
    if args.cpu:
        import jax
        jax.config.update("jax_platforms", "cpu")

    import mxnet_tpu as mx
    from mxnet_tpu import nd, autograd, gluon
    from mxnet_tpu.gluon import nn

    rng = np.random.RandomState(0)
    protos = rng.randn(10, 64).astype("float32")
    Xtr, ytr = make_digits(rng, protos, args.train_size)
    Xte, yte = make_digits(rng, protos, 1024)

    net = nn.Sequential()
    with net.name_scope():
        net.add(nn.Dense(128, activation="relu"), nn.Dense(10))
    net.initialize(mx.init.Xavier())
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 2e-3})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

    def run_phase(masks):
        B = args.batch
        for _ in range(args.epochs_per_phase):
            perm = rng.permutation(len(Xtr))
            for b in range(len(Xtr) // B):
                idx = perm[b * B:(b + 1) * B]
                x, y = nd.array(Xtr[idx]), nd.array(ytr[idx])
                with autograd.record():
                    loss = loss_fn(net(x), y)
                loss.backward()
                trainer.step(B)
                if masks:
                    # re-apply the pruning mask: pruned weights stay 0
                    # through the sparse phase (reference dsd semantics)
                    for p, m in masks.items():
                        p.set_data(p.data() * m)

    def accuracy():
        pred = net(nd.array(Xte)).asnumpy().argmax(1)
        return float((pred == yte).mean())

    # phase 1: dense
    run_phase(None)
    acc_dense = accuracy()

    # prune: drop the smallest |w| per weight matrix
    masks = {}
    kept = total = 0
    for p in net.collect_params().values():
        if p.name.endswith("_weight"):
            w = p.data().asnumpy()
            thr = np.quantile(np.abs(w), args.sparsity)
            m = (np.abs(w) > thr).astype("float32")
            masks[p] = nd.array(m)
            p.set_data(p.data() * masks[p])
            kept += int(m.sum())
            total += m.size
    acc_pruned = accuracy()

    # phase 2: sparse retrain under the mask
    run_phase(masks)
    acc_sparse = accuracy()
    # the mask must actually be sparse at the end of the phase
    w0 = list(masks)[0].data().asnumpy()
    frac_zero = float((w0 == 0).mean())

    # phase 3: dense retrain (mask released)
    run_phase(None)
    acc_final = accuracy()

    print("dense %.3f -> pruned(%.0f%% zeros) %.3f -> sparse-retrain "
          "%.3f -> dense-retrain %.3f"
          % (acc_dense, 100 * (1 - kept / total), acc_pruned,
             acc_sparse, acc_final))
    assert frac_zero > args.sparsity - 0.1, "mask not enforced"
    assert acc_sparse > acc_pruned - 0.02, "sparse retrain regressed"
    assert acc_final >= acc_dense - 0.02, "DSD lost accuracy"
    print("DSD_OK")


if __name__ == "__main__":
    main()
