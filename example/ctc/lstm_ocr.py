"""LSTM + CTC sequence recognition (reference: example/ctc/lstm_ocr_train.py).

The reference trains an LSTM OCR model on generated captchas with
`sym.contrib.ctc_loss` wrapped in MakeLoss. Same capability here on
synthetic data that needs no image assets: each sample is a (T, F)
frame sequence rendering a variable-length digit string (one noisy
frame burst per digit, variable gaps), the model is a gluon LSTM over
frames + per-frame classifier, the loss is `nd.contrib.CTCLoss`
(blank=0, labels padded with 0 — the reference's 'first' convention),
and decoding is greedy best-path collapse. Reports exact-sequence
accuracy.

Usage: python lstm_ocr.py [--epochs 10] [--cpu]
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np

N_CLASSES = 11          # blank + digits 1..10 (digit d -> class d)
MAX_LABEL = 4


def render_sequence(rng, digits, T, F):
    """Each digit emits 2-3 frames carrying a (noisy) one-hot pattern;
    random silent gaps in between — a CTC-alignment problem by design."""
    frames = np.zeros((T, F), "float32")
    t = rng.randint(0, 2)
    for d in digits:
        t += rng.randint(1, 3)          # gap
        for _ in range(rng.randint(2, 4)):
            if t >= T:
                break
            frames[t, d - 1] = 1.0
            t += 1
    frames += rng.randn(T, F).astype("float32") * 0.1
    return frames


def make_dataset(rng, n, T, F):
    X = np.zeros((n, T, F), "float32")
    Y = np.zeros((n, MAX_LABEL), "float32")       # 0-padded labels
    for i in range(n):
        k = rng.randint(1, MAX_LABEL + 1)
        digits = rng.randint(1, N_CLASSES, size=k)
        X[i] = render_sequence(rng, digits, T, F)
        Y[i, :k] = digits
    return X, Y


def greedy_decode(logits):
    """Best-path: argmax per frame, collapse repeats, drop blanks."""
    path = logits.argmax(-1)
    out = []
    for seq in path:
        prev, dec = -1, []
        for c in seq:
            if c != prev and c != 0:
                dec.append(int(c))
            prev = c
        out.append(dec)
    return out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=20)
    ap.add_argument("--batch", type=int, default=32)
    ap.add_argument("--hidden", type=int, default=64)
    ap.add_argument("--seq", type=int, default=20)
    ap.add_argument("--train-size", type=int, default=512)
    ap.add_argument("--lr", type=float, default=5e-3)
    ap.add_argument("--threshold", type=float, default=0.6,
                    help="required exact-sequence accuracy")
    ap.add_argument("--loss-only", action="store_true",
                    help="smoke mode: assert the CTC loss collapsed "
                         "instead of decoding accuracy (short runs sit "
                         "in the all-blank plateau before alignment "
                         "snaps in around epoch ~14)")
    ap.add_argument("--cpu", action="store_true")
    args = ap.parse_args()
    if args.cpu:
        import jax
        jax.config.update("jax_platforms", "cpu")

    import mxnet_tpu as mx
    from mxnet_tpu import nd, autograd, gluon

    rng = np.random.RandomState(7)
    T, F = args.seq, N_CLASSES - 1
    Xtr, Ytr = make_dataset(rng, args.train_size, T, F)
    Xte, Yte = make_dataset(rng, 256, T, F)

    net = gluon.nn.HybridSequential()
    with net.name_scope():
        net.add(gluon.rnn.LSTM(args.hidden, layout="NTC"),
                gluon.nn.Dense(N_CLASSES, flatten=False))
    net.initialize(mx.init.Xavier())
    net(nd.array(Xtr[:2]))        # materialize deferred shapes eagerly
    net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": args.lr})

    n_batches = len(Xtr) // args.batch
    first_loss = None
    for epoch in range(args.epochs):
        perm = rng.permutation(len(Xtr))
        tot = 0.0
        for b in range(n_batches):
            idx = perm[b * args.batch:(b + 1) * args.batch]
            x = nd.array(Xtr[idx])
            y = nd.array(Ytr[idx])
            with autograd.record():
                logits = net(x)                       # (N, T, C)
                # CTCLoss wants (T, N, C)
                loss = nd.contrib.CTCLoss(
                    nd.transpose(logits, axes=(1, 0, 2)), y)
                total = nd.mean(loss)
            total.backward()
            trainer.step(args.batch)
            tot += float(total.asnumpy())
        print("epoch %2d  ctc loss %.4f" % (epoch, tot / n_batches))
        first_loss = first_loss if first_loss is not None \
            else tot / n_batches

    logits = net(nd.array(Xte)).asnumpy()
    decoded = greedy_decode(logits)
    hits = sum(dec == [int(v) for v in truth if v > 0]
               for dec, truth in zip(decoded, Yte))
    acc = hits / len(Yte)
    print("exact-sequence accuracy: %.3f" % acc)
    if args.loss_only:
        final = tot / n_batches
        assert final < 0.5 * first_loss, \
            "CTC loss did not collapse (%.2f -> %.2f)" % (first_loss, final)
    else:
        assert acc > args.threshold, "CTC failed to learn alignment"
    print("CTC_OCR_OK")


if __name__ == "__main__":
    main()
