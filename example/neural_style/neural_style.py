"""Neural style by input-gradient optimization (reference:
example/neural-style/ — optimize the IMAGE, not the weights: content
loss on deep features + style loss on Gram matrices, gradients taken
w.r.t. the input pixels).

Uses a small fixed (random, frozen) conv feature extractor as the
"VGG": layers conv1/conv2 give style Grams, conv3 gives content. The
canvas starts from noise and is optimized with Adam on its pixels via
`autograd` (x.attach_grad(); backward to the input). Asserts the total
loss drops by >80%.

Usage: python neural_style.py [--steps 60] [--cpu]
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))  # run from a source checkout

import numpy as np


def make_images(rng, size=32):
    # content: a centered bright square; style: diagonal stripes
    content = np.zeros((1, 3, size, size), np.float32)
    content[:, :, 8:24, 8:24] = 1.0
    yy, xx = np.mgrid[0:size, 0:size]
    stripes = (((yy + xx) // 4) % 2).astype(np.float32)
    style = np.broadcast_to(stripes, (1, 3, size, size)).copy()
    return content, style


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--steps", type=int, default=60)
    p.add_argument("--cpu", action="store_true")
    p.add_argument("--style-weight", type=float, default=10.0)
    args = p.parse_args()
    if args.cpu:
        os.environ.setdefault(
            "XLA_FLAGS", "--xla_force_host_platform_device_count=8")
        import jax
        jax.config.update("jax_platforms", "cpu")
    import mxnet_tpu as mx
    from mxnet_tpu import autograd, nd
    from mxnet_tpu.gluon import nn
    from mxnet_tpu import gluon

    class Features(gluon.Block):
        """Frozen random conv stack standing in for VGG features."""

        def __init__(self):
            super().__init__()
            with self.name_scope():
                self.c1 = nn.Conv2D(8, 3, padding=1)
                self.c2 = nn.Conv2D(16, 3, strides=2, padding=1)
                self.c3 = nn.Conv2D(32, 3, strides=2, padding=1)

        def forward(self, x):
            f1 = mx.nd.relu(self.c1(x))
            f2 = mx.nd.relu(self.c2(f1))
            f3 = mx.nd.relu(self.c3(f2))
            return f1, f2, f3

    feat = Features()
    feat.initialize(mx.initializer.Xavier(magnitude=1.0))

    def gram(f):
        b, c, h, w = f.shape
        m = f.reshape((c, h * w))
        return nd.dot(m, m.T) / (c * h * w)

    rng = np.random.RandomState(0)
    content_img, style_img = make_images(rng)
    cf = feat(nd.array(content_img))[2]            # content target
    sg = [gram(f) for f in feat(nd.array(style_img))[:2]]  # style targets

    canvas = nd.array(rng.rand(*content_img.shape).astype("float32"))
    canvas.attach_grad()
    # Adam moments for the pixel tensor (the reference uses its own
    # lr-scheduled SGD on the image; Adam converges faster at toy size)
    m = np.zeros(canvas.shape, np.float32)
    v = np.zeros(canvas.shape, np.float32)
    lr, b1, b2, eps = 0.05, 0.9, 0.999, 1e-8

    def total_loss():
        f1, f2, f3 = feat(canvas)
        closs = mx.nd.mean(mx.nd.square(f3 - cf))
        sloss = sum(mx.nd.sum(mx.nd.square(gram(f) - g))
                    for f, g in zip((f1, f2), sg))
        return closs + args.style_weight * sloss

    first = None
    for step in range(args.steps):
        with autograd.record():
            l = total_loss()
        l.backward()
        g = canvas.grad.asnumpy()
        m = b1 * m + (1 - b1) * g
        v = b2 * v + (1 - b2) * g * g
        mh = m / (1 - b1 ** (step + 1))
        vh = v / (1 - b2 ** (step + 1))
        new = canvas.asnumpy() - lr * mh / (np.sqrt(vh) + eps)
        canvas = nd.array(np.clip(new, 0.0, 1.0))
        canvas.attach_grad()
        cur = float(l.asscalar())
        if first is None:
            first = cur
        if step % 15 == 0:
            print("step %d loss %.5f" % (step, cur))
    print("loss %.5f -> %.5f" % (first, cur))
    assert cur < first * 0.2, "input optimization did not converge"
    print("final loss %.5f" % cur)


if __name__ == "__main__":
    main()
