"""Capsule network with dynamic routing (reference: example/capsnet —
CapsNet on MNIST, Sabour et al. routing-by-agreement).

Proves an iterative routing algorithm running inside autograd: primary
capsules come from a conv stem, digit capsules are computed by 3
rounds of routing-by-agreement (softmax coupling over logits updated
by prediction-output dot products), the class score is the capsule
length, and the loss is the reference's margin loss. Runs on the
procedural 10-class pattern set.

Usage: python capsnet.py [--epochs 6] [--cpu]
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np

SIZE = 16
N_CLASS = 10


def make_images(rng, n):
    X = np.zeros((n, 1, SIZE, SIZE), "float32")
    y = rng.randint(0, N_CLASS, n)
    xs = np.arange(SIZE)
    for i in range(n):
        c = y[i]
        if c < 4:
            ang = c * np.pi / 4
            g = np.cos(ang) * xs[None, :] + np.sin(ang) * xs[:, None]
            img = (np.sin(2 * np.pi * g / 5) > 0).astype("float32")
        elif c < 7:
            k = [2, 3, 5][c - 4]
            img = ((xs[None, :] // k + xs[:, None] // k) % 2
                   ).astype("float32")
        else:
            r = [3, 5, 7][c - 7]
            d2 = ((xs[None, :] - SIZE // 2) ** 2
                  + (xs[:, None] - SIZE // 2) ** 2)
            img = (d2 < r * r).astype("float32")
        X[i, 0] = img + rng.randn(SIZE, SIZE) * 0.2
    return X, y.astype("float32")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=6)
    ap.add_argument("--batch", type=int, default=50)
    ap.add_argument("--routing", type=int, default=3)
    ap.add_argument("--train-size", type=int, default=2000)
    ap.add_argument("--cpu", action="store_true")
    args = ap.parse_args()
    if args.cpu:
        import jax
        jax.config.update("jax_platforms", "cpu")

    import mxnet_tpu as mx
    from mxnet_tpu import nd, autograd, gluon
    from mxnet_tpu.gluon import nn

    D_PRIM, D_DIGIT = 4, 8
    N_PRIM_CH = 4

    class CapsNet(gluon.Block):
        def __init__(self, **kw):
            super().__init__(**kw)
            with self.name_scope():
                # BN keeps capsule magnitudes O(1): squash(s) ~ s|s| for
                # small s, so unnormalized stacks vanish to zero output
                self.stem = nn.Sequential()
                self.stem.add(nn.Conv2D(16, 5, strides=2, padding=2),
                              nn.BatchNorm(), nn.Activation("relu"))
                self.prim = nn.Sequential()
                self.prim.add(nn.Conv2D(N_PRIM_CH * D_PRIM, 3, strides=2,
                                        padding=1), nn.BatchNorm())
                # transform u_i -> u_hat_{j|i}, one matrix per (i-type, j)
                self.W = self.params.get(
                    "routing_weight",
                    shape=(1, N_PRIM_CH * 4 * 4, N_CLASS, D_DIGIT,
                           D_PRIM),
                    init=mx.init.Xavier())

        @staticmethod
        def squash(s, axis):
            n2 = nd.sum(s * s, axis=axis, keepdims=True)
            return s * (n2 / (1 + n2)) / nd.sqrt(n2 + 1e-8)

        def forward(self, x):
            b = x.shape[0]
            h = self.prim(self.stem(x))          # (B, C*Dp, 4, 4)
            u = h.reshape((b, N_PRIM_CH, D_PRIM, -1))
            u = nd.transpose(u, axes=(0, 1, 3, 2)).reshape(
                (b, -1, D_PRIM))
            u = self.squash(u, axis=2)           # (B, P, Dp)
            W = self.W.data()                    # (1, P, J, Dd, Dp)
            # u_hat[b,p,j,:] = W[p,j] @ u[b,p]
            u_ = u.expand_dims(2).expand_dims(-1)       # (B,P,1,Dp,1)
            u_hat = nd.sum(W * nd.transpose(u_, axes=(0, 1, 2, 4, 3)),
                           axis=-1)                      # (B,P,J,Dd)
            # routing-by-agreement (logits held out of the grad path,
            # as in the reference implementation)
            logits = nd.zeros((b, u_hat.shape[1], N_CLASS))
            for it in range(args.routing):
                c = nd.softmax(logits, axis=2)           # (B,P,J)
                s = nd.sum(c.expand_dims(-1) * u_hat, axis=1)  # (B,J,Dd)
                v = self.squash(s, axis=2)
                if it < args.routing - 1:
                    agree = nd.sum(u_hat * v.expand_dims(1), axis=-1)
                    logits = logits + agree.detach()
            return nd.sqrt(nd.sum(v * v, axis=2) + 1e-8)   # (B, J)

    def margin_loss(lengths, y):
        oh = nd.one_hot(y, depth=N_CLASS)
        pos = nd.relu(0.9 - lengths) ** 2
        neg = nd.relu(lengths - 0.1) ** 2
        return nd.mean(nd.sum(oh * pos + 0.5 * (1 - oh) * neg, axis=1))

    rng = np.random.RandomState(0)
    Xtr, ytr = make_images(rng, args.train_size)
    Xte, yte = make_images(rng, 500)
    net = CapsNet()
    net.initialize()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 2e-3})

    B = args.batch
    for epoch in range(args.epochs):
        perm = rng.permutation(len(Xtr))
        tot = 0.0
        for b in range(len(Xtr) // B):
            idx = perm[b * B:(b + 1) * B]
            x, y = nd.array(Xtr[idx]), nd.array(ytr[idx])
            with autograd.record():
                loss = margin_loss(net(x), y)
            loss.backward()
            trainer.step(B)
            tot += float(loss.asnumpy())
        print("epoch %2d margin loss %.4f" % (epoch, tot / (len(Xtr) // B)))

    preds = []
    for b in range(len(Xte) // B):
        preds.append(net(nd.array(Xte[b * B:(b + 1) * B])
                         ).asnumpy().argmax(1))
    acc = (np.concatenate(preds) == yte[:len(preds) * B]).mean()
    print("test accuracy: %.3f" % acc)
    assert acc > 0.85, "capsnet failed to train"
    print("CAPSNET_OK")


if __name__ == "__main__":
    main()
