"""Deep Embedded Clustering (reference: example/deep-embedded-clustering
— Xie et al.: autoencoder pretrain, then cluster-assignment hardening
with a self-training target distribution).

The full DEC loop: (1) pretrain an autoencoder; (2) initialize
centroids from the code space; (3) alternate computing Student-t soft
assignments q, the sharpened target p = q^2/f normalized, and
minimizing KL(p || q) through the encoder. Success = unsupervised
cluster accuracy (best 1:1 label matching) far above chance and
improved by the DEC phase over raw k-means-style init.

Usage: python dec.py [--cpu]
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np


def cluster_accuracy(assign, labels, k):
    """Best one-to-one matching accuracy (greedy over the k x k
    contingency table — exact enough at k=4)."""
    table = np.zeros((k, k))
    for a, l in zip(assign, labels.astype(int)):
        table[a, l] += 1
    total, used_r, used_c = 0, set(), set()
    for _ in range(k):
        r, c = np.unravel_index(
            np.argmax(np.where(
                np.isin(np.arange(k), list(used_r))[:, None]
                | np.isin(np.arange(k), list(used_c))[None, :],
                -1, table)), (k, k))
        total += table[r, c]
        used_r.add(int(r))
        used_c.add(int(c))
    return total / len(assign)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--pretrain-epochs", type=int, default=15)
    ap.add_argument("--dec-iters", type=int, default=60)
    ap.add_argument("--batch", type=int, default=256)
    ap.add_argument("--clusters", type=int, default=4)
    ap.add_argument("--cpu", action="store_true")
    args = ap.parse_args()
    if args.cpu:
        import jax
        jax.config.update("jax_platforms", "cpu")

    import mxnet_tpu as mx
    from mxnet_tpu import nd, autograd, gluon
    from mxnet_tpu.gluon import nn

    rng = np.random.RandomState(0)
    K, D, Z = args.clusters, 32, 4
    protos = rng.randn(K, D).astype("float32") * 1.6
    n = 2048
    y = rng.randint(0, K, n)
    X = (protos[y] + rng.randn(n, D).astype("float32") * 2.0)

    enc = nn.Sequential()
    with enc.name_scope():
        enc.add(nn.Dense(32, activation="relu"), nn.Dense(Z))
    dec = nn.Sequential()
    with dec.name_scope():
        dec.add(nn.Dense(32, activation="relu"), nn.Dense(D))
    enc.initialize(mx.init.Xavier())
    dec.initialize(mx.init.Xavier())
    t_enc = gluon.Trainer(enc.collect_params(), "adam",
                          {"learning_rate": 2e-3})
    t_dec = gluon.Trainer(dec.collect_params(), "adam",
                          {"learning_rate": 2e-3})
    l2 = gluon.loss.L2Loss()

    # phase 1: autoencoder pretrain
    B = args.batch
    for epoch in range(args.pretrain_epochs):
        perm = rng.permutation(n)
        for b in range(n // B):
            xb = nd.array(X[perm[b * B:(b + 1) * B]])
            with autograd.record():
                loss = l2(dec(enc(xb)), xb)
            loss.backward()
            t_enc.step(B)
            t_dec.step(B)

    # phase 2: centroids from code space (k-means++-lite: farthest-point
    # seeds + a few Lloyd iterations)
    codes = enc(nd.array(X)).asnumpy()
    cents = [codes[rng.randint(n)]]
    for _ in range(K - 1):
        d2 = np.min([((codes - c) ** 2).sum(1) for c in cents], axis=0)
        cents.append(codes[np.argmax(d2)])
    cents = np.stack(cents)
    for _ in range(10):
        a = ((codes[:, None] - cents[None]) ** 2).sum(-1).argmin(1)
        cents = np.stack([codes[a == k].mean(0) if (a == k).any()
                          else cents[k] for k in range(K)])
    acc_init = cluster_accuracy(a, y, K)

    # phase 3: DEC self-training — KL(p || q) through the encoder
    mu = nd.array(cents.astype("float32"))
    mu.attach_grad()
    for it in range(args.dec_iters):
        idx = rng.permutation(n)[:B]
        xb = nd.array(X[idx])
        with autograd.record():
            z = enc(xb)                                   # (B, Z)
            d2 = nd.sum((z.expand_dims(1) - mu.expand_dims(0)) ** 2,
                        axis=2)
            q = 1.0 / (1.0 + d2)                          # Student-t, v=1
            q = q / nd.sum(q, axis=1, keepdims=True)
            qd = q.detach().asnumpy()
            p = qd ** 2 / qd.sum(0, keepdims=True)
            p = nd.array(p / p.sum(1, keepdims=True))
            loss = nd.mean(nd.sum(p * (nd.log(p + 1e-9)
                                       - nd.log(q + 1e-9)), axis=1))
        loss.backward()
        t_enc.step(B)
        mu -= 1e-2 * mu.grad   # grad_req='write': fresh each backward

    codes = enc(nd.array(X)).asnumpy()
    a2 = ((codes[:, None] - mu.asnumpy()[None]) ** 2).sum(-1).argmin(1)
    acc_dec = cluster_accuracy(a2, y, K)
    print("cluster accuracy: after pretrain+kmeans %.3f -> after DEC %.3f"
          % (acc_init, acc_dec))
    assert acc_dec > 0.85 and acc_dec >= acc_init - 0.02, \
        "DEC failed to produce clean clusters"
    print("DEC_OK")


if __name__ == "__main__":
    main()
