"""Wide-and-deep-style training with row_sparse embedding exchange.

Reference: example/sparse/ (wide_deep, matrix_factorization) — the
pattern where a huge embedding table lives in the kvstore and each step
only the rows touched by the batch move: `row_sparse_pull` the batch's
rows, compute, push a RowSparseNDArray gradient back. Memory and wire
bytes scale with rows-per-batch, not table size (SURVEY hard-part (b)).

Synthetic CTR-style task: each sample has `NNZ` categorical ids out of
`VOCAB` plus a dense feature vector; label = whether the sum of the true
(hidden) id weights is positive.
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np


def main():
    p = argparse.ArgumentParser()
    # defaults sized so each row gets enough visits to learn (~10
    # SGD touches/row): vocab 2k x 200 batches reaches ~0.8 accuracy
    p.add_argument("--vocab", type=int, default=2000)
    p.add_argument("--dim", type=int, default=16)
    p.add_argument("--nnz", type=int, default=8)
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--num-batches", type=int, default=200)
    p.add_argument("--lr", type=float, default=0.5)
    p.add_argument("--kv-store", default="local")
    p.add_argument("--cpu", action="store_true")
    args = p.parse_args()
    if args.cpu:
        os.environ.setdefault(
            "XLA_FLAGS", "--xla_force_host_platform_device_count=8")
        import jax
        jax.config.update("jax_platforms", "cpu")

    import mxnet_tpu as mx
    from mxnet_tpu import nd, autograd
    from mxnet_tpu.ndarray.sparse import RowSparseNDArray

    rng = np.random.RandomState(0)
    true_w = rng.randn(args.vocab).astype("float32")

    # embedding table lives in the kvstore; dense tower is a local param
    kv = mx.kv.create(args.kv_store)
    kv.init("embed", nd.array(
        rng.randn(args.vocab, args.dim).astype("float32") * 0.05))
    dense_w = nd.array(rng.randn(args.dim).astype("float32") * 0.1)
    dense_w.attach_grad()

    correct = total = 0
    for step in range(args.num_batches):
        ids = rng.randint(0, args.vocab,
                          (args.batch_size, args.nnz)).astype("int32")
        y = (true_w[ids].sum(1) > 0).astype("float32")

        uniq, inv = np.unique(ids, return_inverse=True)
        # pull ONLY the touched rows (never the vocab-sized table)
        rows = RowSparseNDArray(nd.zeros((len(uniq), args.dim)),
                                nd.array(uniq),
                                (args.vocab, args.dim))
        kv.row_sparse_pull("embed", out=rows,
                           row_ids=nd.array(uniq))
        emb = rows.data  # (n_uniq, dim)
        emb.attach_grad()

        with autograd.record():
            gathered = nd.take(emb, nd.array(
                inv.reshape(args.batch_size, args.nnz).astype("float32")))
            pooled = nd.sum(gathered, axis=1)       # (B, dim)
            logit = nd.sum(pooled * dense_w.reshape((1, -1)), axis=1)
            loss = nd.mean(nd.log(1 + nd.exp(-(
                (nd.array(y) * 2 - 1) * logit))))
        loss.backward()

        pred = (logit.asnumpy() > 0).astype("float32")
        correct += (pred == y).sum()
        total += len(y)

        # push the sparse embedding gradient: rows touched only
        kv.push("embed", RowSparseNDArray(
            nd.array(-args.lr * emb.grad.asnumpy()
                     + np.asarray(rows.data._data)),
            nd.array(uniq), (args.vocab, args.dim)))
        dense_w -= args.lr * dense_w.grad
        dense_w.grad[:] = 0

        if (step + 1) % 20 == 0:
            print("step %d: accuracy %.3f" % (step + 1, correct / total))
            correct = total = 0


if __name__ == "__main__":
    main()
