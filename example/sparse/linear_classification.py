"""Sparse linear classification over libsvm data.

Reference: example/sparse/linear_classification.py — logistic regression
on CSR batches where both the data-weight product AND the weight
gradient are sparse computations (tensor/dot-inl.h DotCsrDnsDns /
DotCsrTDnsDns). The gradient of w is X^T (p - y): a csr-transpose dot —
O(nnz) work per step, never densifying X.

Runs on a generated synthetic libsvm file by default; pass --data to use
a real one (e.g. the reference's kdda/avazu downloads).
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))
import tempfile

import numpy as np


def _make_synthetic_libsvm(path, n=512, dim=100, nnz=10, seed=0):
    rng = np.random.RandomState(seed)
    true_w = rng.randn(dim)
    with open(path, "w") as f:
        for _ in range(n):
            idx = np.sort(rng.choice(dim, nnz, replace=False))
            val = rng.randn(nnz)
            y = int(np.dot(val, true_w[idx]) > 0)
            f.write("%d %s\n" % (y, " ".join(
                "%d:%.4f" % (i, v) for i, v in zip(idx, val))))


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--data", default=None, help="libsvm file")
    p.add_argument("--dim", type=int, default=100)
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--epochs", type=int, default=10)
    p.add_argument("--lr", type=float, default=0.5)
    p.add_argument("--cpu", action="store_true")
    args = p.parse_args()
    if args.cpu:
        os.environ.setdefault(
            "XLA_FLAGS", "--xla_force_host_platform_device_count=8")
        import jax
        jax.config.update("jax_platforms", "cpu")

    import mxnet_tpu as mx
    from mxnet_tpu import nd
    from mxnet_tpu.ndarray import sparse as sp

    if args.data is None:
        args.data = os.path.join(tempfile.gettempdir(),
                                 "mxtpu_synth.libsvm")
        _make_synthetic_libsvm(args.data, dim=args.dim)

    w = nd.zeros((args.dim, 1))
    b = nd.zeros((1,))
    it = mx.io.LibSVMIter(data_libsvm=args.data, data_shape=(args.dim,),
                          batch_size=args.batch_size, round_batch=False)
    for epoch in range(args.epochs):
        it.reset()
        correct = total = 0
        for batch in it:
            X = batch.data[0]                       # CSRNDArray
            y = batch.label[0].asnumpy().reshape(-1, 1)
            logits = sp.dot(X, w).asnumpy() + float(b.asnumpy()[0])
            prob = 1.0 / (1.0 + np.exp(-logits))
            grad_out = nd.array((prob - y) / len(y))
            gw = sp.dot(X, grad_out, transpose_a=True)  # O(nnz) grad
            w -= args.lr * gw
            b -= args.lr * float(grad_out.asnumpy().sum())
            correct += int(((logits > 0) == (y > 0.5)).sum())
            total += len(y)
        if (epoch + 1) % 2 == 0:
            print("epoch %d: accuracy %.3f" % (epoch + 1,
                                               correct / total))


if __name__ == "__main__":
    main()
