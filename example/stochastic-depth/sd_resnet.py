"""Stochastic depth (reference: example/stochastic-depth — residual
blocks randomly skipped during training, kept at inference with
survival-probability scaling).

Proves mode-dependent stochastic architecture: each residual block
draws a Bernoulli survival gate inside autograd.record() (training) but
runs deterministically scaled at inference — the train/predict-mode
plumbing the reference implements with mx.sym.uniform + custom blocks.

Usage: python sd_resnet.py [--epochs 8] [--cpu]
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np


def make_images(rng, n, size=16):
    """10 classes of oriented-bar/checker/blob patterns (same family as
    tests/train) at 16x16."""
    X = np.zeros((n, 1, size, size), "float32")
    y = rng.randint(0, 10, n)
    xs = np.arange(size)
    for i in range(n):
        c = y[i]
        if c < 4:
            ang = c * np.pi / 4
            g = np.cos(ang) * xs[None, :] + np.sin(ang) * xs[:, None]
            img = (np.sin(2 * np.pi * g / 5) > 0).astype("float32")
        elif c < 7:
            k = [2, 3, 5][c - 4]
            img = ((xs[None, :] // k + xs[:, None] // k) % 2
                   ).astype("float32")
        else:
            r = [3, 5, 7][c - 7]
            d2 = ((xs[None, :] - size // 2) ** 2
                  + (xs[:, None] - size // 2) ** 2)
            img = (d2 < r * r).astype("float32")
        X[i, 0] = img + rng.randn(size, size) * 0.25
    return X, y.astype("float32")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=8)
    ap.add_argument("--batch", type=int, default=100)
    ap.add_argument("--blocks", type=int, default=6)
    ap.add_argument("--death-rate", type=float, default=0.3)
    ap.add_argument("--train-size", type=int, default=3000)
    ap.add_argument("--cpu", action="store_true")
    args = ap.parse_args()
    if args.cpu:
        import jax
        jax.config.update("jax_platforms", "cpu")

    import mxnet_tpu as mx
    from mxnet_tpu import nd, autograd, gluon
    from mxnet_tpu.gluon import nn

    class SDBlock(gluon.Block):
        """Residual block skipped with prob `death_rate` in training;
        output scaled by survival prob at inference (reference
        sd_module.py semantics). Uses Block (not Hybrid): the gate is
        drawn per batch on the eager path."""

        def __init__(self, channels, death_rate, **kw):
            super().__init__(**kw)
            self.death_rate = death_rate
            with self.name_scope():
                self.body = nn.Sequential()
                self.body.add(nn.Conv2D(channels, 3, padding=1),
                              nn.BatchNorm(),
                              nn.Activation("relu"),
                              nn.Conv2D(channels, 3, padding=1),
                              nn.BatchNorm())

        def forward(self, x):
            if autograd.is_training():
                if float(np.random.rand()) < self.death_rate:
                    return x                  # block dies this batch
                return nd.relu(x + self.body(x))
            return nd.relu(x + (1 - self.death_rate) * self.body(x))

    net = gluon.nn.Sequential()
    with net.name_scope():
        net.add(nn.Conv2D(16, 3, padding=1), nn.BatchNorm(),
                nn.Activation("relu"))
        for _ in range(args.blocks):
            net.add(SDBlock(16, args.death_rate))
        net.add(nn.GlobalAvgPool2D(), nn.Dense(10))

    rng = np.random.RandomState(0)
    Xtr, ytr = make_images(rng, args.train_size)
    Xte, yte = make_images(rng, 600)
    net.initialize(mx.init.Xavier())
    net(nd.array(Xtr[:2]))   # predict-mode pass runs EVERY block's body,
    #                          materializing deferred shapes before any
    #                          training batch can skip a dead block
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 2e-3})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

    B = args.batch
    for epoch in range(args.epochs):
        perm = rng.permutation(len(Xtr))
        tot = 0.0
        for b in range(len(Xtr) // B):
            idx = perm[b * B:(b + 1) * B]
            x, y = nd.array(Xtr[idx]), nd.array(ytr[idx])
            with autograd.record():
                loss = loss_fn(net(x), y)
            loss.backward()
            trainer.step(B)
            tot += float(nd.mean(loss).asnumpy())
        print("epoch %2d loss %.4f" % (epoch, tot / (len(Xtr) // B)))

    preds = []
    for b in range(len(Xte) // B):
        preds.append(net(nd.array(Xte[b * B:(b + 1) * B])
                         ).asnumpy().argmax(1))
    acc = (np.concatenate(preds) == yte[:len(preds) * B]).mean()
    print("test accuracy: %.3f" % acc)
    assert acc > 0.85, "stochastic-depth net failed to train"
    print("STOCHASTIC_DEPTH_OK")


if __name__ == "__main__":
    main()
