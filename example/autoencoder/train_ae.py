"""Stacked autoencoder (reference: example/autoencoder/autoencoder.py —
dense encoder/decoder trained on reconstruction, used there as the
front-end for deep embedded clustering).

Self-contained: trains on synthetic clustered data; reports
reconstruction MSE and a cluster-separation score of the code layer
(the property the reference's DEC pipeline relies on).

Usage: python train_ae.py [--epochs 20] [--cpu]
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=20)
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--n", type=int, default=1024)
    p.add_argument("--input-dim", type=int, default=32)
    p.add_argument("--code-dim", type=int, default=4)
    p.add_argument("--lr", type=float, default=1e-3)
    p.add_argument("--cpu", action="store_true")
    args = p.parse_args()
    if args.cpu:
        os.environ.setdefault(
            "XLA_FLAGS", "--xla_force_host_platform_device_count=8")
        import jax
        jax.config.update("jax_platforms", "cpu")
    import mxnet_tpu as mx
    from mxnet_tpu import gluon, autograd
    from mxnet_tpu.gluon import nn

    rng = np.random.RandomState(0)
    # 4 gaussian clusters embedded in input_dim dims
    centers = rng.randn(4, args.input_dim) * 3
    labels = rng.randint(0, 4, args.n)
    data = (centers[labels]
            + rng.randn(args.n, args.input_dim) * 0.5).astype("float32")

    net = nn.HybridSequential(prefix="ae_")
    with net.name_scope():
        enc = nn.HybridSequential(prefix="enc_")
        with enc.name_scope():
            enc.add(nn.Dense(64, activation="relu"),
                    nn.Dense(args.code_dim))
        dec = nn.HybridSequential(prefix="dec_")
        with dec.name_scope():
            dec.add(nn.Dense(64, activation="relu"),
                    nn.Dense(args.input_dim))
        net.add(enc, dec)
    net.initialize(mx.init.Xavier())
    net(mx.nd.zeros((1, args.input_dim)))
    net.hybridize()

    l2 = gluon.loss.L2Loss()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": args.lr})
    loader = gluon.data.DataLoader(
        gluon.data.ArrayDataset(data, data), batch_size=args.batch_size,
        shuffle=True)
    first = last = None
    for epoch in range(args.epochs):
        tot, cnt = 0.0, 0
        for xb, yb in loader:
            with autograd.record():
                loss = l2(net(xb), yb)
            loss.backward()
            trainer.step(xb.shape[0])
            tot += float(loss.mean().asscalar()) * xb.shape[0]
            cnt += xb.shape[0]
        mse = tot / cnt
        if first is None:
            first = mse
        last = mse
        if epoch % 5 == 0 or epoch == args.epochs - 1:
            print("epoch %3d  recon-mse %.5f" % (epoch, mse))

    # cluster separation in code space: between/within distance ratio
    codes = enc(mx.nd.array(data)).asnumpy()
    mu = np.stack([codes[labels == k].mean(0) for k in range(4)])
    within = np.mean([np.linalg.norm(codes[labels == k] - mu[k], axis=1).mean()
                      for k in range(4)])
    between = np.mean([np.linalg.norm(mu[i] - mu[j])
                       for i in range(4) for j in range(i + 1, 4)])
    print("final recon-mse %.5f (from %.5f); code separation %.2f"
          % (last, first, between / max(within, 1e-9)))
    assert last < first, "reconstruction did not improve"
    return last, between / max(within, 1e-9)


if __name__ == "__main__":
    main()
