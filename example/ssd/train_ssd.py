"""Minimal SSD-style detector on synthetic boxes
(reference: example/ssd/ — MultiBoxPrior/Target/Detection pipeline,
SURVEY.md N5d).

A tiny conv backbone predicts class scores + box offsets per anchor;
targets come from contrib.MultiBoxTarget; detection decodes + NMS via
contrib.MultiBoxDetection. Synthetic scenes contain one bright square on
a dark background.

Usage: python train_ssd.py [--steps 60] [--cpu]
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))  # run from a source checkout

import numpy as np


def make_scene(rng, size=32):
    img = np.zeros((3, size, size), np.float32)
    w = rng.randint(8, 16)
    x0 = rng.randint(0, size - w)
    y0 = rng.randint(0, size - w)
    img[:, y0:y0 + w, x0:x0 + w] = 1.0
    box = np.array([0, x0 / size, y0 / size, (x0 + w) / size,
                    (y0 + w) / size], np.float32)
    return img, box


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--steps", type=int, default=60)
    p.add_argument("--batch-size", type=int, default=8)
    p.add_argument("--cpu", action="store_true")
    args = p.parse_args()
    if args.cpu:
        os.environ.setdefault(
            "XLA_FLAGS", "--xla_force_host_platform_device_count=8")
        import jax
        jax.config.update("jax_platforms", "cpu")
    import mxnet_tpu as mx
    from mxnet_tpu import gluon, autograd
    from mxnet_tpu.gluon import nn

    num_classes = 1  # square vs background
    sizes = (0.3, 0.45)
    n_anchor_per_pos = len(sizes)

    class TinySSD(gluon.Block):
        def __init__(self):
            super().__init__()
            with self.name_scope():
                self.backbone = nn.Sequential()
                self.backbone.add(
                    nn.Conv2D(16, 3, padding=1, activation="relu"),
                    nn.MaxPool2D(2),
                    nn.Conv2D(32, 3, padding=1, activation="relu"),
                    nn.MaxPool2D(2))  # 32 -> 8x8 feature map
                self.cls_head = nn.Conv2D(
                    n_anchor_per_pos * (num_classes + 1), 3, padding=1)
                self.box_head = nn.Conv2D(n_anchor_per_pos * 4, 3,
                                          padding=1)

        def forward(self, x):
            feat = self.backbone(x)
            anchors = mx.nd.contrib.MultiBoxPrior(feat, sizes=sizes,
                                                  ratios=(1.0,))
            B = x.shape[0]
            cls = self.cls_head(feat)  # (B, A*(C+1), H, W)
            cls = cls.transpose((0, 2, 3, 1)).reshape(
                (B, -1, num_classes + 1))
            cls = cls.transpose((0, 2, 1))  # (B, C+1, N)
            box = self.box_head(feat).transpose((0, 2, 3, 1)) \
                .reshape((B, -1))
            return anchors, cls, box

    net = TinySSD()
    net.initialize(mx.initializer.Xavier())
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 5e-3})
    cls_loss = gluon.loss.SoftmaxCrossEntropyLoss(axis=1)
    box_loss = gluon.loss.HuberLoss()

    rng = np.random.RandomState(0)
    for step in range(args.steps):
        imgs, boxes = zip(*[make_scene(rng)
                            for _ in range(args.batch_size)])
        x = mx.nd.array(np.stack(imgs))
        label = mx.nd.array(np.stack(boxes)[:, None, :])  # (B,1,5)
        with autograd.record():
            anchors, cls, box = net(x)
            bt, bm, ct = mx.nd.contrib.MultiBoxTarget(anchors, label,
                                                      cls)
            l = cls_loss(cls, ct) + box_loss(box * bm, bt * bm)
        l.backward()
        trainer.step(args.batch_size)
        if step % 10 == 0:
            print("step %d loss %.4f" % (step,
                                         float(l.mean().asscalar())))

    # detect on one scene
    img, box = make_scene(rng)
    anchors, cls, boxp = net(mx.nd.array(img[None]))
    probs = mx.nd.softmax(cls, axis=1)
    det = mx.nd.contrib.MultiBoxDetection(probs, boxp, anchors,
                                          nms_threshold=0.45).asnumpy()
    best = det[0][det[0, :, 1].argmax()]
    print("GT box:", box[1:], "-> detected:", best[2:6],
          "score %.2f" % best[1])


if __name__ == "__main__":
    main()
