"""Minimal SSD-style detector trained from the detection input path
(reference: example/ssd/ — MultiBoxPrior/Target/Detection pipeline fed
by ImageDetIter over a detection record file, SURVEY.md N5d/N10;
python/mxnet/image/detection.py:625, src/io/iter_image_det_recordio.cc).

The example packs synthetic scenes (one bright square on a dark
background) into a real .rec with per-image detection labels, then
trains end-to-end from mx.image.ImageDetIter: decode -> label-aware
augmentation (random mirror) -> fixed-shape padded labels ->
MultiBoxTarget -> losses.

Usage: python train_ssd.py [--steps 60] [--cpu]
"""
import argparse
import os
import sys
import tempfile

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))  # run from a source checkout

import numpy as np


def make_scene(rng, size=32):
    """HWC uint8 image + normalized [cls, x1, y1, x2, y2] box."""
    img = np.zeros((size, size, 3), np.uint8)
    w = rng.randint(8, 16)
    x0 = rng.randint(0, size - w)
    y0 = rng.randint(0, size - w)
    img[y0:y0 + w, x0:x0 + w, :] = 255
    box = np.array([0, x0 / size, y0 / size, (x0 + w) / size,
                    (y0 + w) / size], np.float32)
    return img, box


def build_det_record(mx, path, n_images, rng, size=32):
    """Pack scenes into an indexed .rec whose headers carry detection
    labels [header_w=2, obj_w=5, cls, x1, y1, x2, y2] — the det-record
    format ImageDetIter consumes (iter_image_det_recordio.cc role)."""
    from mxnet_tpu import recordio
    rec = recordio.MXIndexedRecordIO(path + ".idx", path + ".rec", "w")
    boxes = []
    for i in range(n_images):
        img, box = make_scene(rng, size)
        label = np.concatenate([[2, 5], box]).astype(np.float32)
        hdr = recordio.IRHeader(0, label, i, 0)
        rec.write_idx(i, recordio.pack_img(hdr, img, quality=95))
        boxes.append(box)
    rec.close()
    return path + ".rec", boxes


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--steps", type=int, default=60)
    p.add_argument("--batch-size", type=int, default=8)
    p.add_argument("--cpu", action="store_true")
    args = p.parse_args()
    if args.cpu:
        os.environ.setdefault(
            "XLA_FLAGS", "--xla_force_host_platform_device_count=8")
        import jax
        jax.config.update("jax_platforms", "cpu")
    import mxnet_tpu as mx
    from mxnet_tpu import gluon, autograd
    from mxnet_tpu.gluon import nn

    num_classes = 1  # square vs background
    sizes = (0.3, 0.45)
    n_anchor_per_pos = len(sizes)

    class TinySSD(gluon.Block):
        def __init__(self):
            super().__init__()
            with self.name_scope():
                self.backbone = nn.Sequential()
                self.backbone.add(
                    nn.Conv2D(16, 3, padding=1, activation="relu"),
                    nn.MaxPool2D(2),
                    nn.Conv2D(32, 3, padding=1, activation="relu"),
                    nn.MaxPool2D(2))  # 32 -> 8x8 feature map
                self.cls_head = nn.Conv2D(
                    n_anchor_per_pos * (num_classes + 1), 3, padding=1)
                self.box_head = nn.Conv2D(n_anchor_per_pos * 4, 3,
                                          padding=1)

        def forward(self, x):
            feat = self.backbone(x)
            anchors = mx.nd.contrib.MultiBoxPrior(feat, sizes=sizes,
                                                  ratios=(1.0,))
            B = x.shape[0]
            cls = self.cls_head(feat)  # (B, A*(C+1), H, W)
            cls = cls.transpose((0, 2, 3, 1)).reshape(
                (B, -1, num_classes + 1))
            cls = cls.transpose((0, 2, 1))  # (B, C+1, N)
            box = self.box_head(feat).transpose((0, 2, 3, 1)) \
                .reshape((B, -1))
            return anchors, cls, box

    net = TinySSD()
    net.initialize(mx.initializer.Xavier())
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 5e-3})
    cls_loss = gluon.loss.SoftmaxCrossEntropyLoss(axis=1)
    box_loss = gluon.loss.HuberLoss()

    # the detection input path: det .rec -> ImageDetIter batches
    rng = np.random.RandomState(0)
    tmpdir = tempfile.mkdtemp(prefix="ssd_rec_")
    rec_path, _ = build_det_record(
        mx, os.path.join(tmpdir, "scenes"), 4 * args.batch_size, rng)
    det_iter = mx.image.ImageDetIter(
        batch_size=args.batch_size, data_shape=(3, 32, 32),
        path_imgrec=rec_path, shuffle=True, rand_mirror=True)

    step = 0
    while step < args.steps:
        det_iter.reset()
        for batch in det_iter:
            if step >= args.steps:
                break
            x = batch.data[0] / 255.0
            label = batch.label[0]  # (B, max_obj, 5), -1-padded
            with autograd.record():
                anchors, cls, box = net(x)
                bt, bm, ct = mx.nd.contrib.MultiBoxTarget(anchors, label,
                                                          cls)
                l = cls_loss(cls, ct) + box_loss(box * bm, bt * bm)
            l.backward()
            trainer.step(args.batch_size)
            if step % 10 == 0:
                print("step %d loss %.4f" % (step,
                                             float(l.mean().asscalar())))
            step += 1

    # detect on one scene
    img, box = make_scene(rng)
    img = img.transpose(2, 0, 1).astype(np.float32) / 255.0
    anchors, cls, boxp = net(mx.nd.array(img[None]))
    probs = mx.nd.softmax(cls, axis=1)
    det = mx.nd.contrib.MultiBoxDetection(probs, boxp, anchors,
                                          nms_threshold=0.45).asnumpy()
    best = det[0][det[0, :, 1].argmax()]
    print("GT box:", box[1:], "-> detected:", best[2:6],
          "score %.2f" % best[1])


if __name__ == "__main__":
    main()
