"""Data+tensor parallel ResNet training over a device mesh — the
TPU-native counterpart of the reference's multi-GPU
example/image-classification (dist_device_sync) path.

On hardware this runs over real chips; with --cpu it demonstrates the
same program on an 8-device virtual mesh.

Usage: python sharded_resnet.py [--dp 4 --tp 2] [--cpu]
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))  # run from a source checkout

import numpy as np


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--dp", type=int, default=4)
    p.add_argument("--tp", type=int, default=2)
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--steps", type=int, default=20)
    p.add_argument("--cpu", action="store_true")
    args = p.parse_args()
    if args.cpu:
        os.environ.setdefault(
            "XLA_FLAGS", "--xla_force_host_platform_device_count=%d"
            % (args.dp * args.tp))
        import jax
        jax.config.update("jax_platforms", "cpu")
    import mxnet_tpu as mx
    from mxnet_tpu import gluon
    from mxnet_tpu.gluon.model_zoo import vision
    from mxnet_tpu.parallel import (make_mesh, ShardedTrainer,
                                    PartitionSpec)

    mesh = make_mesh({"dp": args.dp, "tp": args.tp})
    net = vision.resnet18_v1(classes=10)
    net.initialize(mx.initializer.Xavier())
    net(mx.nd.zeros((2, 3, 32, 32)))  # materialize shapes
    loss = gluon.loss.SoftmaxCrossEntropyLoss()
    st = ShardedTrainer(
        net, lambda o, l: loss(o, l), "sgd",
        {"learning_rate": 0.1, "momentum": 0.9}, mesh=mesh,
        param_rules=[(r"dense0_weight", PartitionSpec(None, "tp"))])

    rng = np.random.RandomState(0)
    x = rng.rand(args.batch_size, 3, 32, 32).astype("float32")
    y = rng.randint(0, 10, args.batch_size).astype("float32")
    for step in range(args.steps):
        l = st.step(x, y)
        if step % 5 == 0:
            print("step %d loss %.4f" % (step, float(l.asscalar())))
    st.copy_params_to_net()
    print("done; params synced back to the gluon net")


if __name__ == "__main__":
    main()
