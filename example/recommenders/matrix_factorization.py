"""Matrix factorization recommender (reference:
example/recommenders/demo1-MF.ipynb and
example/model-parallel/matrix_factorization/ — user/item embeddings,
dot-product score, trained on rating triples).

TPU-native notes: the reference's model-parallel variant splits the
embedding tables across GPUs by hand (`group2ctx`); here large tables
shard over the mesh via ShardedTrainer param_rules (PartitionSpec on the
row axis) — see --sharded.

Usage: python matrix_factorization.py [--epochs 10] [--cpu] [--sharded]
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np


def make_ratings(rng, n_users, n_items, n_obs, rank=8):
    """Synthetic low-rank ratings with noise."""
    U = rng.randn(n_users, rank) * 0.7
    V = rng.randn(n_items, rank) * 0.7
    u = rng.randint(0, n_users, n_obs)
    i = rng.randint(0, n_items, n_obs)
    r = (U[u] * V[i]).sum(1) + rng.randn(n_obs) * 0.1
    return (u.astype("float32"), i.astype("float32"),
            r.astype("float32"))


def build_net(n_users, n_items, dim):
    import mxnet_tpu as mx
    from mxnet_tpu.gluon import nn, HybridBlock

    class MFBlock(HybridBlock):
        def __init__(self, **kw):
            super().__init__(**kw)
            with self.name_scope():
                self.user = nn.Embedding(n_users, dim)
                self.item = nn.Embedding(n_items, dim)

        def hybrid_forward(self, F, users, items):
            eu = self.user(users)
            ei = self.item(items)
            return F.sum(eu * ei, axis=-1)

    return MFBlock(prefix="mf_")


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=10)
    p.add_argument("--batch-size", type=int, default=256)
    p.add_argument("--users", type=int, default=512)
    p.add_argument("--items", type=int, default=256)
    p.add_argument("--obs", type=int, default=16384)
    p.add_argument("--dim", type=int, default=16)
    p.add_argument("--lr", type=float, default=0.02)
    p.add_argument("--cpu", action="store_true")
    p.add_argument("--sharded", action="store_true",
                   help="shard embedding tables over the device mesh "
                        "(the reference's model-parallel MF, TPU-style)")
    args = p.parse_args()
    if args.cpu:
        os.environ.setdefault(
            "XLA_FLAGS", "--xla_force_host_platform_device_count=8")
        import jax
        jax.config.update("jax_platforms", "cpu")
    import mxnet_tpu as mx
    from mxnet_tpu import gluon, autograd

    rng = np.random.RandomState(0)
    users, items, ratings = make_ratings(rng, args.users, args.items,
                                         args.obs)
    net = build_net(args.users, args.items, args.dim)
    net.initialize(mx.init.Normal(0.1))
    net(mx.nd.zeros((1,)), mx.nd.zeros((1,)))
    l2 = gluon.loss.L2Loss()

    if args.sharded:
        # model-parallel: table rows sharded over the mesh; XLA inserts
        # the gather collectives (vs the reference's group2ctx pinning)
        from mxnet_tpu.parallel import (make_mesh, ShardedTrainer,
                                        PartitionSpec)
        mesh = make_mesh()
        st = ShardedTrainer(
            net, lambda o, l: l2(o, l), "adam",
            {"learning_rate": args.lr}, mesh=mesh,
            param_rules=[(r"embedding\d*_weight$", PartitionSpec("dp"))],
            data_names=("data", "data1"), label_names=("label",))
        n_batches = len(ratings) // args.batch_size
        first = last = None
        for epoch in range(args.epochs):
            tot = 0.0
            for b in range(n_batches):
                s = slice(b * args.batch_size, (b + 1) * args.batch_size)
                tot += float(st.step(users[s], items[s],
                                     ratings[s]).asscalar())
            mse = tot / n_batches
            first, last = (mse if first is None else first), mse
            if epoch % 3 == 0 or epoch == args.epochs - 1:
                print("epoch %3d  mse %.4f" % (epoch, mse))
    else:
        net.hybridize()
        trainer = gluon.Trainer(net.collect_params(), "adam",
                                {"learning_rate": args.lr})
        ds = gluon.data.ArrayDataset(users, items, ratings)
        loader = gluon.data.DataLoader(ds, batch_size=args.batch_size,
                                       shuffle=True)
        first = last = None
        for epoch in range(args.epochs):
            tot, cnt = 0.0, 0
            for ub, ib, rb in loader:
                with autograd.record():
                    loss = l2(net(ub, ib), rb)
                loss.backward()
                trainer.step(ub.shape[0])
                tot += float(loss.mean().asscalar()) * ub.shape[0]
                cnt += ub.shape[0]
            mse = tot / cnt
            first, last = (mse if first is None else first), mse
            if epoch % 3 == 0 or epoch == args.epochs - 1:
                print("epoch %3d  mse %.4f" % (epoch, mse))

    print("final mse %.4f (from %.4f)" % (last, first))
    assert last < first, "MF did not learn"
    return last


if __name__ == "__main__":
    main()
