"""Minimal Faster-RCNN-style pipeline on synthetic scenes.

Reference: example/rcnn/ — the RPN (anchor cls + bbox deltas) ->
contrib.Proposal (decode + NMS) -> ROIPooling -> head classification
chain (SURVEY.md N5d detection ops).

Synthetic task: scenes contain one bright square; the RPN learns
objectness, Proposal produces candidate boxes, ROIPooling crops features
and a small head classifies each ROI as object/background. Demonstrates
the whole detection-op family end-to-end; training updates the RPN
objectness head (the reference's alternating scheme, stage 1).

Usage: python train_rcnn.py [--steps 40]
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np


def make_scene(rng, size=32):
    img = np.zeros((3, size, size), np.float32)
    w = rng.randint(10, 18)
    x0 = rng.randint(0, size - w)
    y0 = rng.randint(0, size - w)
    img[:, y0:y0 + w, x0:x0 + w] = 1.0
    return img, np.array([x0, y0, x0 + w - 1, y0 + w - 1], np.float32)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--steps", type=int, default=40)
    p.add_argument("--lr", type=float, default=0.1)
    p.add_argument("--cpu", action="store_true")
    args = p.parse_args()
    if args.cpu:
        os.environ.setdefault(
            "XLA_FLAGS", "--xla_force_host_platform_device_count=8")
        import jax
        jax.config.update("jax_platforms", "cpu")

    import mxnet_tpu as mx
    from mxnet_tpu import nd, autograd

    rng = np.random.RandomState(0)
    size, stride = 32, 8
    A = 1  # one anchor per cell (scale 1, ratio 1 at stride 8 ~ 8px box)
    fs = size // stride

    conv_w = nd.array(0.3 * rng.randn(8, 3, 3, 3).astype("f"))
    cls_w = nd.array(0.1 * rng.randn(2 * A, 8, 1, 1).astype("f"))
    for w in (conv_w, cls_w):
        w.attach_grad()

    def rpn(img_batch):
        feat = nd.Activation(nd.Convolution(
            img_batch, conv_w, kernel=(3, 3), num_filter=8,
            stride=(stride, stride), pad=(1, 1), no_bias=True),
            act_type="relu")
        logits = nd.Convolution(feat, cls_w, kernel=(1, 1),
                                num_filter=2 * A, no_bias=True)
        return feat, logits

    # --- stage 1: train RPN objectness on anchor/gt IoU labels --------
    for step in range(args.steps):
        imgs, boxes = zip(*[make_scene(rng, size) for _ in range(8)])
        x = nd.array(np.stack(imgs))
        # objectness label per cell: does the anchor center fall in gt?
        labels = np.zeros((8, fs * fs), np.float32)
        for b, gt in enumerate(boxes):
            for i in range(fs):
                for j in range(fs):
                    cy, cx = i * stride + stride / 2, j * stride + stride / 2
                    if gt[0] <= cx <= gt[2] and gt[1] <= cy <= gt[3]:
                        labels[b, i * fs + j] = 1.0
        with autograd.record():
            _, logits = rpn(x)
            flat = logits.reshape((8, 2, -1)).transpose(
                (0, 2, 1)).reshape((-1, 2))
            out = nd.SoftmaxOutput(flat, nd.array(labels.reshape(-1)))
        out.backward()
        for w in (conv_w, cls_w):
            w -= args.lr * w.grad
            w.grad[:] = 0
        if (step + 1) % 20 == 0:
            pred = out.asnumpy().argmax(1)
            acc = (pred == labels.reshape(-1)).mean()
            print("rpn step %d: objectness acc %.3f" % (step + 1, acc))

    # --- stage 2: proposals + ROI pooling + per-ROI scoring -----------
    imgs, boxes = zip(*[make_scene(rng, size) for _ in range(2)])
    x = nd.array(np.stack(imgs))
    feat, logits = rpn(x)
    cls_prob = nd.softmax(
        logits.reshape((2, 2, -1)).transpose((0, 2, 1)))
    cls_prob = cls_prob.transpose((0, 2, 1)).reshape((2, 2 * A, fs, fs))
    bbox_pred = nd.zeros((2, 4 * A, fs, fs))
    im_info = nd.array(np.array([[size, size, 1.0]] * 2, "f"))
    rois = nd.contrib.Proposal(cls_prob, bbox_pred, im_info,
                               scales=(1.5,), ratios=(1.0,),
                               feature_stride=stride,
                               rpn_pre_nms_top_n=16, rpn_post_nms_top_n=4,
                               threshold=0.5, rpn_min_size=4)
    pooled = nd.ROIPooling(feat, rois, pooled_size=(3, 3),
                           spatial_scale=1.0 / stride)
    print("proposals:", rois.shape, "-> roi features:", pooled.shape)
    r = rois.asnumpy()
    hits = 0
    for row in r:
        b = int(row[0])
        gt = boxes[b]
        ix1, iy1 = max(row[1], gt[0]), max(row[2], gt[1])
        ix2, iy2 = min(row[3], gt[2]), min(row[4], gt[3])
        inter = max(ix2 - ix1, 0) * max(iy2 - iy1, 0)
        area = (row[3] - row[1]) * (row[4] - row[2]) + 1e-9
        if inter / area > 0.3:
            hits += 1
    print("proposals overlapping gt: %d/%d" % (hits, len(r)))


if __name__ == "__main__":
    main()
