"""WGAN-GP style gradient penalty with gluon (higher-order autograd).

No reference analog (the 2018 reference's autograd.grad exposes
create_graph=True but no example uses it); this is the canonical use:
the critic's loss includes a penalty on the norm of its INPUT
gradient, so training needs d/dw of a function of d/dx — grad-of-grad
through the same gluon block.

Usage: python wgan_gp.py [--steps 150] [--cpu]
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=150)
    ap.add_argument("--batch", type=int, default=64)
    ap.add_argument("--lambda-gp", type=float, default=25.0)
    ap.add_argument("--cpu", action="store_true")
    args = ap.parse_args()
    if args.cpu:
        import jax
        jax.config.update("jax_platforms", "cpu")

    import mxnet_tpu as mx
    from mxnet_tpu import nd, autograd, gluon
    from mxnet_tpu.gluon import nn

    rng = np.random.RandomState(0)
    D = 16

    def real_batch(n):            # data lives on a shifted shell
        x = rng.randn(n, D).astype("float32")
        return 2.0 * x / np.linalg.norm(x, axis=1, keepdims=True) + 1.0

    def fake_batch(n):            # generator stand-in: unit gaussian
        return rng.randn(n, D).astype("float32")

    critic = nn.Sequential()
    with critic.name_scope():
        critic.add(nn.Dense(64, activation="tanh"),
                   nn.Dense(64, activation="tanh"), nn.Dense(1))
    critic.initialize(mx.init.Xavier())
    trainer = gluon.Trainer(critic.collect_params(), "adam",
                            {"learning_rate": 1e-3, "beta1": 0.5})

    B = args.batch
    w_dist, gp_vals = [], []
    for step in range(args.steps):
        xr = nd.array(real_batch(B))
        xf = nd.array(fake_batch(B))
        eps = nd.array(rng.rand(B, 1).astype("float32"))
        xi = eps * xr + (1 - eps) * xf       # interpolates
        xi.attach_grad()
        with autograd.record():
            wd = nd.mean(critic(xf)) - nd.mean(critic(xr))
            # gradient penalty: (||d critic/d xi||_2 - 1)^2, trained
            # THROUGH the gradient (create_graph=True)
            (gx,) = autograd.grad(nd.sum(critic(xi)), [xi],
                                  create_graph=True)
            gnorm = nd.sqrt(nd.sum(gx * gx, axis=1) + 1e-12)
            gp = nd.mean((gnorm - 1.0) ** 2)
            loss = wd + args.lambda_gp * gp
            loss.backward()
        trainer.step(B)
        w_dist.append(float(wd.asnumpy()))
        gp_vals.append(float(gp.asnumpy()))
        if step % 30 == 0:
            print("step %3d  critic gap %.4f  penalty %.4f"
                  % (step, -w_dist[-1], gp_vals[-1]))

    early_gap = -np.mean(w_dist[:20])
    late_gap = -np.mean(w_dist[-20:])
    late_gp = np.mean(gp_vals[-20:])
    print("critic gap %.4f -> %.4f ; penalty settles at %.4f"
          % (early_gap, late_gap, late_gp))
    # the critic separates real from fake while the penalty keeps its
    # gradient pinned near unit norm — both need 2nd-order to be right
    assert late_gap > max(0.3, early_gap + 0.1), "critic did not learn"
    assert late_gp < 0.12, "gradient norm not pinned near 1"
    print("WGAN_GP_OK")


if __name__ == "__main__":
    main()
