"""Adversarial examples via FGSM (reference: example/adversary —
fast gradient sign attack on MNIST).

Proves input-gradient access through the eager autograd tape: train a
classifier, mark the INPUT as a variable, take d(loss)/d(input), and
perturb by epsilon*sign(grad). Success = clean accuracy high, adversarial
accuracy collapses, and (bonus) adversarial retraining recovers most
of it.

Usage: python fgsm.py [--epochs 8] [--cpu]
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np


def make_digits(rng, protos, n, noise=0.3):
    y = rng.randint(0, 10, n)
    X = protos[y] + rng.randn(n, protos.shape[1]).astype("float32") * noise
    return X.astype("float32"), y.astype("float32")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=8)
    ap.add_argument("--batch", type=int, default=128)
    ap.add_argument("--eps", type=float, default=1.0)
    ap.add_argument("--train-size", type=int, default=4096)
    ap.add_argument("--cpu", action="store_true")
    args = ap.parse_args()
    if args.cpu:
        import jax
        jax.config.update("jax_platforms", "cpu")

    import mxnet_tpu as mx
    from mxnet_tpu import nd, autograd, gluon
    from mxnet_tpu.gluon import nn

    rng = np.random.RandomState(0)
    protos = rng.randn(10, 64).astype("float32")
    Xtr, ytr = make_digits(rng, protos, args.train_size)
    Xte, yte = make_digits(rng, protos, 1024)

    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(128, activation="relu"), nn.Dense(10))
    net.initialize(mx.init.Xavier())
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 2e-3})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

    def fit(X, y, epochs):
        B = args.batch
        for _ in range(epochs):
            perm = rng.permutation(len(X))
            for b in range(len(X) // B):
                idx = perm[b * B:(b + 1) * B]
                xb, yb = nd.array(X[idx]), nd.array(y[idx])
                with autograd.record():
                    loss = loss_fn(net(xb), yb)
                loss.backward()
                trainer.step(B)

    def accuracy(X, y):
        return float((net(nd.array(X)).asnumpy().argmax(1) == y).mean())

    def fgsm(X, y):
        x = nd.array(X)
        x.attach_grad()
        with autograd.record():
            loss = loss_fn(net(x), nd.array(y))
        loss.backward()
        return (X + args.eps *
                np.sign(x.grad.asnumpy())).astype("float32")

    fit(Xtr, ytr, args.epochs)
    clean = accuracy(Xte, yte)
    adv = accuracy(fgsm(Xte, yte), yte)
    print("clean acc %.3f  adversarial acc %.3f (eps=%.2f)"
          % (clean, adv, args.eps))
    assert clean > 0.9 and adv < clean - 0.3, \
        "attack did not degrade the model"

    # ONLINE adversarial training: every batch is re-attacked against
    # the current weights (static adversarial sets do not survive a
    # white-box re-attack)
    B = args.batch
    for _ in range(args.epochs):
        perm = rng.permutation(len(Xtr))
        for b in range(len(Xtr) // B):
            idx = perm[b * B:(b + 1) * B]
            xb = np.concatenate([Xtr[idx], fgsm(Xtr[idx], ytr[idx])])
            yb = np.concatenate([ytr[idx], ytr[idx]])
            x_, y_ = nd.array(xb), nd.array(yb)
            with autograd.record():
                loss = loss_fn(net(x_), y_)
            loss.backward()
            trainer.step(len(xb))
    hardened = accuracy(fgsm(Xte, yte), yte)
    print("after online adversarial training: adversarial acc %.3f"
          % hardened)
    assert hardened > adv + 0.2, "adversarial training did not help"
    print("FGSM_OK")


if __name__ == "__main__":
    main()
