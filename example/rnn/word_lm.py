"""Word-level language model with truncated BPTT
(reference: example/rnn/word_lm/train.py — stateful LSTM carrying hidden
state across batches and detaching, SURVEY.md §5.7).

Uses a synthetic integer corpus with learnable structure (next token =
f(current)) unless --text points at a tokenizable file.

Usage: python word_lm.py [--epochs 3] [--cpu]
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))  # run from a source checkout

import numpy as np


def batchify(tokens, batch_size):
    n = len(tokens) // batch_size
    return np.asarray(tokens[:n * batch_size]).reshape(
        batch_size, n).T  # (T, N)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=3)
    p.add_argument("--batch-size", type=int, default=16)
    p.add_argument("--bptt", type=int, default=20)
    p.add_argument("--hidden", type=int, default=100)
    p.add_argument("--embed", type=int, default=64)
    p.add_argument("--vocab", type=int, default=50)
    p.add_argument("--lr", type=float, default=0.01)
    p.add_argument("--optimizer", default="adam")
    p.add_argument("--text", default=None)
    p.add_argument("--cpu", action="store_true")
    args = p.parse_args()
    if args.cpu:
        os.environ.setdefault(
            "XLA_FLAGS", "--xla_force_host_platform_device_count=8")
        import jax
        jax.config.update("jax_platforms", "cpu")
    import mxnet_tpu as mx
    from mxnet_tpu import gluon, autograd
    from mxnet_tpu.gluon import nn, rnn

    if args.text and os.path.exists(args.text):
        with open(args.text) as f:
            words = f.read().split()
        vocab = {w: i for i, w in enumerate(sorted(set(words)))}
        tokens = [vocab[w] for w in words]
        args.vocab = len(vocab)
    else:
        rng = np.random.RandomState(0)
        # markov-ish synthetic corpus: next = (cur * 7 + noise) % vocab
        tokens = [0]
        for _ in range(20000):
            nxt = (tokens[-1] * 7 + rng.randint(0, 3)) % args.vocab
            tokens.append(nxt)

    data = batchify(tokens, args.batch_size)  # (T, N)

    class RNNModel(gluon.Block):
        def __init__(self, **kw):
            super().__init__(**kw)
            with self.name_scope():
                self.embed = nn.Embedding(args.vocab, args.embed)
                self.lstm = rnn.LSTM(args.hidden, num_layers=2,
                                     input_size=args.embed)
                self.decoder = nn.Dense(args.vocab,
                                        in_units=args.hidden)

        def forward(self, x, state):
            emb = self.embed(x)              # (T, N, E)
            out, state = self.lstm(emb, state)
            dec = self.decoder(out.reshape((-1, args.hidden)))
            return dec, state

    model = RNNModel()
    model.initialize(mx.initializer.Xavier())
    trainer = gluon.Trainer(model.collect_params(), args.optimizer,
                            {"learning_rate": args.lr})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

    T = data.shape[0]
    for epoch in range(args.epochs):
        state = model.lstm.begin_state(batch_size=args.batch_size)
        total, n = 0.0, 0
        for i in range(0, T - args.bptt - 1, args.bptt):
            x = mx.nd.array(data[i:i + args.bptt])
            y = mx.nd.array(
                data[i + 1:i + args.bptt + 1].reshape(-1))
            # truncated BPTT: carry state, cut the graph
            state = [s.detach() for s in state]
            with autograd.record():
                out, state = model(x, state)
                loss = loss_fn(out, y)
            loss.backward()
            grads = [p.grad() for p in
                     model.collect_params().values()
                     if p.grad_req != "null"]
            gluon.utils.clip_global_norm(
                grads, 0.25 * args.bptt * args.batch_size)
            trainer.step(args.bptt * args.batch_size)
            total += float(loss.mean().asscalar())
            n += 1
        ppl = float(np.exp(total / n))
        print("epoch %d loss %.3f ppl %.2f" % (epoch, total / n, ppl))
        if epoch == 0:
            first_ppl = ppl
    assert ppl < first_ppl, \
        "perplexity did not improve: %.2f -> %.2f" % (first_ppl, ppl)
    print("WORD_LM_OK")


if __name__ == "__main__":
    main()
