"""Bayes by Backprop (reference: example/bayesian-methods/bdk.ipynb /
bayes-by-backprop — weight-uncertainty networks, Blundell et al.).

A variational posterior N(mu, sigma^2) over every weight: each forward
draws w = mu + sigma*eps inside autograd.record(), and the loss is the
ELBO (data NLL + KL(q||prior) with an analytic gaussian KL). Proves
per-weight reparameterized sampling and uncertainty calibration: the
posterior std must shrink on informative weights while predictions on
out-of-distribution inputs stay uncertain.

Usage: python bayes_by_backprop.py [--epochs 20] [--cpu]
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=25)
    ap.add_argument("--batch", type=int, default=128)
    ap.add_argument("--hidden", type=int, default=32)
    ap.add_argument("--train-size", type=int, default=2048)
    ap.add_argument("--kl-weight", type=float, default=1e-3)
    ap.add_argument("--cpu", action="store_true")
    args = ap.parse_args()
    if args.cpu:
        import jax
        jax.config.update("jax_platforms", "cpu")

    import mxnet_tpu as mx
    from mxnet_tpu import nd, autograd, gluon

    rng = np.random.RandomState(0)
    D = 16
    protos = rng.randn(4, D).astype("float32")

    def batch(n):
        y = rng.randint(0, 4, n)
        return (protos[y] + rng.randn(n, D).astype("float32") * 0.4,
                y.astype("float32"))

    Xtr, ytr = batch(args.train_size)
    Xte, yte = batch(512)

    H, C = args.hidden, 4
    shapes = {"w1": (D, H), "b1": (H,), "w2": (H, C), "b2": (C,)}
    mus, rhos = {}, {}
    for k, shp in shapes.items():
        mus[k] = nd.array(rng.randn(*shp).astype("float32") * 0.1)
        # sigma = softplus(rho); rho=-3 -> sigma ~ 0.049
        rhos[k] = nd.array(np.full(shp, -3.0, "float32"))
        mus[k].attach_grad()
        rhos[k].attach_grad()

    def sample_weights():
        ws, kl = {}, 0.0
        for k in shapes:
            sigma = nd.log(1 + nd.exp(rhos[k]))
            eps = nd.random.normal(shape=shapes[k])
            ws[k] = mus[k] + sigma * eps
            # analytic KL(N(mu, sigma) || N(0, 1)) summed over weights
            kl = kl + nd.sum(0.5 * (sigma ** 2 + mus[k] ** 2)
                             - nd.log(sigma) - 0.5)
        return ws, kl

    def forward(ws, x):
        h = nd.relu(nd.dot(x, ws["w1"]) + ws["b1"])
        return nd.dot(h, ws["w2"]) + ws["b2"]

    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    lr = 5e-2
    B = args.batch
    for epoch in range(args.epochs):
        perm = rng.permutation(len(Xtr))
        tot = 0.0
        for b in range(len(Xtr) // B):
            idx = perm[b * B:(b + 1) * B]
            x, y = nd.array(Xtr[idx]), nd.array(ytr[idx])
            with autograd.record():
                ws, kl = sample_weights()
                nll = nd.mean(loss_fn(forward(ws, x), y))
                loss = nll + args.kl_weight * kl
            loss.backward()
            for k in shapes:
                mus[k] -= lr * mus[k].grad
                rhos[k] -= lr * rhos[k].grad
                mus[k].grad[:] = 0
                rhos[k].grad[:] = 0
            tot += float(loss.asnumpy())
        if epoch % 5 == 0:
            print("epoch %2d elbo-loss %.4f" % (epoch, tot / (len(Xtr) // B)))

    # predictive accuracy: average over posterior samples
    votes = np.zeros((len(Xte), C))
    for _ in range(8):
        ws, _ = sample_weights()
        votes += forward(ws, nd.array(Xte)).asnumpy()
    acc = (votes.argmax(1) == yte).mean()

    # epistemic uncertainty: posterior-predictive entropy on OOD inputs
    # (random directions far from every prototype) must exceed in-dist
    def pred_entropy(X):
        ps = []
        for _ in range(8):
            ws, _ = sample_weights()
            logits = forward(ws, nd.array(X)).asnumpy()
            e = np.exp(logits - logits.max(1, keepdims=True))
            ps.append(e / e.sum(1, keepdims=True))
        p = np.mean(ps, axis=0)
        return float(-(p * np.log(p + 1e-9)).sum(1).mean())

    ood = rng.randn(256, D).astype("float32") * 4.0
    h_in, h_ood = pred_entropy(Xte), pred_entropy(ood)
    print("accuracy %.3f  entropy in-dist %.3f  OOD %.3f"
          % (acc, h_in, h_ood))
    assert acc > 0.9, "posterior mean failed to classify"
    assert h_ood > h_in + 0.1, "no epistemic uncertainty on OOD inputs"
    print("BAYES_OK")


if __name__ == "__main__":
    main()
