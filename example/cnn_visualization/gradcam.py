"""Grad-CAM saliency (reference: example/cnn_visualization — gradcam.py
class-activation maps from conv-feature gradients).

Proves feature-map gradient access: a conv net is trained on images
whose class evidence lives in a KNOWN quadrant; Grad-CAM weights the
last conv features by the class-score gradient (channel-wise GAP of
d score / d features) and the resulting localization map must
concentrate on the evidence quadrant.

Usage: python gradcam.py [--epochs 6] [--cpu]
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np

SIZE = 16


def _patches():
    h = SIZE // 2
    xs = np.arange(h)
    checker = ((xs[None, :] // 2 + xs[:, None] // 2) % 2).astype("f4")
    hbars = (np.sin(2 * np.pi * xs / 4)[:, None] > 0) * np.ones((h, h))
    vbars = hbars.T
    diag = (np.sin(2 * np.pi * (xs[None, :] + xs[:, None]) / 4) > 0
            ).astype("f4")
    return [checker, hbars.astype("f4"), vbars.astype("f4"), diag]


def make_images(rng, n):
    """Class = the PATTERN of a patch placed in a random quadrant (GAP
    heads are translation-invariant, so identity is learnable while the
    location — which Grad-CAM must recover — varies per sample)."""
    X = rng.randn(n, 1, SIZE, SIZE).astype("float32") * 0.1
    y = rng.randint(0, 4, n)
    quad = rng.randint(0, 4, n)
    pats = _patches()
    h = SIZE // 2
    for i in range(n):
        r, c = divmod(int(quad[i]), 2)
        X[i, 0, r * h:(r + 1) * h, c * h:(c + 1) * h] += 2.0 * pats[int(y[i])]
    return X, y.astype("float32"), quad


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=6)
    ap.add_argument("--batch", type=int, default=64)
    ap.add_argument("--train-size", type=int, default=2048)
    ap.add_argument("--cpu", action="store_true")
    args = ap.parse_args()
    if args.cpu:
        import jax
        jax.config.update("jax_platforms", "cpu")

    import mxnet_tpu as mx
    from mxnet_tpu import nd, autograd, gluon
    from mxnet_tpu.gluon import nn

    rng = np.random.RandomState(0)
    Xtr, ytr, _ = make_images(rng, args.train_size)
    Xte, yte, qte = make_images(rng, 256)

    # split trunk/head so the conv feature map is reachable
    trunk = nn.Sequential()
    with trunk.name_scope():
        trunk.add(nn.Conv2D(8, 3, padding=1, activation="relu"),
                  nn.Conv2D(16, 3, padding=1, activation="relu"))
    head = nn.Sequential()
    with head.name_scope():
        head.add(nn.GlobalAvgPool2D(), nn.Dense(4))
    trunk.initialize(mx.init.Xavier())
    head.initialize(mx.init.Xavier())
    trainer = gluon.Trainer(trunk.collect_params(), "adam",
                            {"learning_rate": 2e-3})
    trainer_head = gluon.Trainer(head.collect_params(), "adam",
                                 {"learning_rate": 2e-3})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

    B = args.batch
    for epoch in range(args.epochs):
        perm = rng.permutation(len(Xtr))
        tot = 0.0
        for b in range(len(Xtr) // B):
            idx = perm[b * B:(b + 1) * B]
            x, y = nd.array(Xtr[idx]), nd.array(ytr[idx])
            with autograd.record():
                loss = loss_fn(head(trunk(x)), y)
            loss.backward()
            trainer.step(B)
            trainer_head.step(B)
            tot += float(nd.mean(loss).asnumpy())
        print("epoch %d loss %.4f" % (epoch, tot / (len(Xtr) // B)))

    acc = (head(trunk(nd.array(Xte))).asnumpy().argmax(1) == yte).mean()
    print("accuracy %.3f" % acc)
    assert acc > 0.95, "classifier failed"

    # Grad-CAM: weights = GAP of d(score_c)/d(features); map = relu(w.F)
    def gradcam(x, cls):
        feats = trunk(nd.array(x))
        feats.attach_grad()
        with autograd.record():
            score = nd.pick(head(feats), nd.array(cls), axis=1)
            total = nd.sum(score)
        total.backward()
        g = feats.grad.asnumpy()              # (N, C, H, W)
        f = feats.asnumpy()
        w = g.mean(axis=(2, 3), keepdims=True)
        cam = np.maximum((w * f).sum(axis=1), 0)   # (N, H, W)
        return cam

    cam = gradcam(Xte[:64], yte[:64])
    h = SIZE // 2
    hits = 0
    for i in range(64):
        m = cam[i]
        masses = [m[r * h:(r + 1) * h, c * h:(c + 1) * h].sum()
                  for r in (0, 1) for c in (0, 1)]
        hits += int(np.argmax(masses)) == int(qte[i])
    frac = hits / 64
    print("Grad-CAM picks the evidence quadrant for %.0f%% of samples "
          "(chance 25%%)" % (100 * frac))
    assert frac > 0.5, "Grad-CAM localization should beat 2x chance"

    # occlusion sensitivity (the reference's second visualization): mask
    # each quadrant; the largest class-score drop marks the evidence
    def occlusion_quadrant(X, cls):
        base = head(trunk(nd.array(X))).asnumpy()
        base = base[np.arange(len(X)), cls.astype(int)]
        drops = []
        for r in (0, 1):
            for c in (0, 1):
                Xm = X.copy()
                Xm[:, :, r * h:(r + 1) * h, c * h:(c + 1) * h] = 0
                sc = head(trunk(nd.array(Xm))).asnumpy()
                drops.append(base - sc[np.arange(len(X)),
                                       cls.astype(int)])
        return np.argmax(np.stack(drops, 1), axis=1)

    occ = occlusion_quadrant(Xte[:64], yte[:64])
    occ_frac = float((occ == qte[:64]).mean())
    print("occlusion sensitivity picks the evidence quadrant for "
          "%.0f%% of samples" % (100 * occ_frac))
    assert occ_frac > 0.9, "occlusion did not localize the evidence"
    print("GRADCAM_OK")


if __name__ == "__main__":
    main()
