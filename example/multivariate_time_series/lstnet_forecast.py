"""Multivariate time-series forecasting (reference:
example/multivariate_time_series — LSTNet on the electricity dataset).

Proves multivariate sequence regression: a conv feature extractor over
a sliding window + LSTM + dense head forecasts the next step of a
coupled 8-channel oscillator system, beating the persistence baseline
(predict last value) by a wide margin.

Usage: python lstnet_forecast.py [--epochs 10] [--cpu]
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np

C = 8           # channels
W = 24          # window


def make_series(rng, n):
    """Coupled noisy oscillators: each channel is a phase-shifted
    mixture of two shared latent sine processes."""
    t = np.arange(n + W + 1)
    lat1 = np.sin(2 * np.pi * t / 17.0)
    lat2 = np.sin(2 * np.pi * t / 5.0)
    mix = rng.randn(2, C) * 0.8
    series = (lat1[:, None] * mix[0] + lat2[:, None] * mix[1]
              + rng.randn(len(t), C) * 0.05).astype("float32")
    X = np.stack([series[i:i + W] for i in range(n)])          # (n,W,C)
    Y = series[W:W + n]                                        # (n,C)
    return X, Y


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=10)
    ap.add_argument("--batch", type=int, default=64)
    ap.add_argument("--train-size", type=int, default=4096)
    ap.add_argument("--cpu", action="store_true")
    args = ap.parse_args()
    if args.cpu:
        import jax
        jax.config.update("jax_platforms", "cpu")

    import mxnet_tpu as mx
    from mxnet_tpu import nd, autograd, gluon
    from mxnet_tpu.gluon import nn

    rng = np.random.RandomState(0)
    X, Y = make_series(rng, args.train_size + 512)
    Xtr, Ytr = X[:args.train_size], Y[:args.train_size]
    Xte, Yte = X[args.train_size:], Y[args.train_size:]

    class LSTNetLite(gluon.HybridBlock):
        def __init__(self, **kw):
            super().__init__(**kw)
            with self.name_scope():
                self.conv = nn.Conv1D(16, kernel_size=3, padding=1,
                                      activation="relu")
                self.lstm = gluon.rnn.LSTM(32, layout="NTC")
                self.head = nn.Dense(C)

        def hybrid_forward(self, F, x):
            # (N, W, C) -> conv over time needs NCW
            h = self.conv(F.transpose(x, axes=(0, 2, 1)))
            h = self.lstm(F.transpose(h, axes=(0, 2, 1)))
            return self.head(F.slice_axis(h, axis=1, begin=-1, end=None)
                             .reshape((0, -1)))

    net = LSTNetLite()
    net.initialize(mx.init.Xavier())
    net(nd.array(Xtr[:2]))
    net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 2e-3})
    loss_fn = gluon.loss.L2Loss()

    B = args.batch
    for epoch in range(args.epochs):
        perm = rng.permutation(len(Xtr))
        tot = 0.0
        for b in range(len(Xtr) // B):
            idx = perm[b * B:(b + 1) * B]
            x, y = nd.array(Xtr[idx]), nd.array(Ytr[idx])
            with autograd.record():
                loss = loss_fn(net(x), y)
            loss.backward()
            trainer.step(B)
            tot += float(nd.mean(loss).asnumpy())
        print("epoch %2d loss %.5f" % (epoch, tot / (len(Xtr) // B)))

    pred = net(nd.array(Xte)).asnumpy()
    mse = float(np.mean((pred - Yte) ** 2))
    persistence = float(np.mean((Xte[:, -1] - Yte) ** 2))
    print("forecast mse %.5f vs persistence %.5f" % (mse, persistence))
    assert mse < 0.3 * persistence, "forecaster no better than persistence"
    print("FORECAST_OK")


if __name__ == "__main__":
    main()
