"""SVM output layer (reference: example/svm_mnist — softmax replaced by
an SVMOutput hinge-loss head on MNIST).

Proves the SVMOutput head end-to-end on the Module API: an MLP trunk
with a margin-based (L1/L2 hinge) objective instead of cross-entropy,
on synthetic prototype digits.

Usage: python svm_classifier.py [--epochs 10] [--l2] [--cpu]
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np


def make_digits(rng, protos, n, noise=0.4):
    y = rng.randint(0, 10, n)
    X = protos[y] + rng.randn(n, protos.shape[1]).astype("float32") * noise
    return X, y.astype("float32")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=10)
    ap.add_argument("--batch", type=int, default=64)
    ap.add_argument("--train-size", type=int, default=4096)
    ap.add_argument("--l2", action="store_true",
                    help="squared hinge (default: linear hinge)")
    ap.add_argument("--cpu", action="store_true")
    args = ap.parse_args()
    if args.cpu:
        import jax
        jax.config.update("jax_platforms", "cpu")

    import mxnet_tpu as mx

    rng = np.random.RandomState(1)
    protos = rng.randn(10, 64).astype("float32")
    X, y = make_digits(rng, protos, args.train_size)
    Xt, yt = make_digits(rng, protos, 1024)

    data = mx.sym.Variable("data")
    h = mx.sym.Activation(mx.sym.FullyConnected(data, num_hidden=128),
                          act_type="relu")
    scores = mx.sym.FullyConnected(h, num_hidden=10)
    out = mx.sym.SVMOutput(scores, mx.sym.Variable("svm_label"),
                           use_linear=not args.l2, name="svm")

    mod = mx.mod.Module(out, data_names=("data",),
                        label_names=("svm_label",), context=mx.cpu())
    it = mx.io.NDArrayIter({"data": X}, {"svm_label": y},
                           batch_size=args.batch, shuffle=True)
    mod.fit(it, num_epoch=args.epochs, optimizer="sgd",
            optimizer_params=(("learning_rate", 0.05),
                              ("momentum", 0.9)))

    mod.forward(mx.io.DataBatch(data=[mx.nd.array(Xt)]), is_train=False)
    pred = mod.get_outputs()[0].asnumpy().argmax(1)
    acc = (pred == yt).mean()
    print("hinge-%s accuracy: %.3f" % ("L2" if args.l2 else "L1", acc))
    assert acc > 0.9, "SVM head failed to learn"
    print("SVM_OK")


if __name__ == "__main__":
    main()
