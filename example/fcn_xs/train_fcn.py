"""FCN-style semantic segmentation on synthetic shape scenes
(reference: example/fcn-xs/ — fully-convolutional nets with a
Deconvolution upsampling head and per-pixel softmax, fcn_xs.py +
symbol_fcnxs.py).

Scenes contain a bright square (class 1) and a dim disk (class 2) on a
dark background (class 0); the net is a small conv encoder, a stride-2
downsample, and a Conv2DTranspose (Deconvolution) decoder with an
encoder skip — the fcn-8s pattern at toy scale. Trains with per-pixel
SoftmaxCrossEntropy; asserts pixel accuracy.

Usage: python train_fcn.py [--steps 80] [--cpu]
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))  # run from a source checkout

import numpy as np


def make_scene(rng, size=32):
    img = np.zeros((size, size, 1), np.float32)
    seg = np.zeros((size, size), np.int32)
    # square -> class 1
    w = rng.randint(6, 12)
    x0, y0 = rng.randint(0, size - w, size=2)
    img[y0:y0 + w, x0:x0 + w, 0] = 1.0
    seg[y0:y0 + w, x0:x0 + w] = 1
    # disk -> class 2
    r = rng.randint(4, 7)
    cx, cy = rng.randint(r, size - r, size=2)
    yy, xx = np.mgrid[0:size, 0:size]
    disk = (yy - cy) ** 2 + (xx - cx) ** 2 <= r * r
    img[disk, 0] = 0.5
    seg[disk] = 2
    return img.transpose(2, 0, 1), seg


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--steps", type=int, default=80)
    p.add_argument("--batch-size", type=int, default=8)
    p.add_argument("--cpu", action="store_true")
    args = p.parse_args()
    if args.cpu:
        os.environ.setdefault(
            "XLA_FLAGS", "--xla_force_host_platform_device_count=8")
        import jax
        jax.config.update("jax_platforms", "cpu")
    import mxnet_tpu as mx
    from mxnet_tpu import gluon, autograd
    from mxnet_tpu.gluon import nn

    n_class = 3

    class TinyFCN(gluon.Block):
        def __init__(self):
            super().__init__()
            with self.name_scope():
                self.enc1 = nn.Conv2D(16, 3, padding=1,
                                      activation="relu")
                self.down = nn.Conv2D(32, 3, strides=2, padding=1,
                                      activation="relu")
                self.mid = nn.Conv2D(32, 3, padding=1,
                                     activation="relu")
                # stride-2 transposed conv back to full resolution
                self.up = nn.Conv2DTranspose(16, 4, strides=2,
                                             padding=1)
                self.head = nn.Conv2D(n_class, 1)

        def forward(self, x):
            skip = self.enc1(x)
            h = self.mid(self.down(skip))
            h = mx.nd.relu(self.up(h) + skip)  # fcn-xs skip fusion
            return self.head(h)  # (B, n_class, H, W)

    net = TinyFCN()
    net.initialize(mx.initializer.Xavier())
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 3e-3})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss(axis=1)

    rng = np.random.RandomState(0)

    def batch():
        imgs, segs = zip(*[make_scene(rng)
                           for _ in range(args.batch_size)])
        return (mx.nd.array(np.stack(imgs)),
                mx.nd.array(np.stack(segs).astype(np.float32)))

    def pixel_acc():
        x, seg = batch()
        pred = net(x).asnumpy().argmax(axis=1)
        return float((pred == seg.asnumpy()).mean())

    acc0 = pixel_acc()
    for step in range(args.steps):
        x, seg = batch()
        with autograd.record():
            out = net(x)
            l = loss_fn(out, seg)
        l.backward()
        trainer.step(args.batch_size)
        if step % 20 == 0:
            print("step %d loss %.4f" % (step,
                                         float(l.mean().asscalar())))
    acc1 = pixel_acc()
    print("pixel-acc %.3f -> %.3f" % (acc0, acc1))
    assert acc1 > 0.9 and acc1 > acc0, "segmentation did not learn"
    print("final pixel-acc %.3f" % acc1)


if __name__ == "__main__":
    main()
