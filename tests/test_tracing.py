"""Distributed tracing + live introspection plane (ISSUE 13,
docs/observability.md "Distributed tracing").

Covers: W3C traceparent parse/format/echo; span parentage across
thread-pool hops (the PR-2 orphaned-span fix); deterministic per-step
trace ids across ranks + StepTimer integration; the gateway E2E chain
(gateway.request → gateway.admission → serving.batch →
engine.dispatch with the same trace id echoed in the response);
rank-shard merging + critical path via tools/trace_report.py; metric
label-cardinality bounding; histogram trace-id exemplars surfacing in
telemetry_report and a forced perf_gate p99 breach; Prometheus
exposition correctness (escaping, HELP/TYPE once per family,
round-trip through a strict parser); docs_drift as a fast gate; and
~zero-cost disablement via MXTPU_TRACE=0.
"""
import importlib.util
import json
import os
import re
import sys
import threading
import urllib.request

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import observability as obs
from mxnet_tpu.observability import httpz, registry as obs_registry
from mxnet_tpu.observability import trace
from mxnet_tpu.observability.span import capture_context, restored
from mxnet_tpu.observability.telemetry import StepTimer

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name + "_t", os.path.join(ROOT, "tools", name + ".py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(autouse=True)
def _clean_trace(monkeypatch):
    monkeypatch.delenv("MXTPU_TRACE", raising=False)
    monkeypatch.delenv("MXTPU_TRACE_DIR", raising=False)
    monkeypatch.delenv("MXTPU_TRACE_SAMPLE", raising=False)
    trace.reset_ring()
    trace.close_shard()
    yield
    trace.reset_ring()
    trace.close_shard()


# -- TraceContext / traceparent ------------------------------------------
def test_traceparent_roundtrip():
    ctx = trace.TraceContext("ab" * 16, "cd" * 8, True)
    parsed = trace.TraceContext.from_traceparent(ctx.to_traceparent())
    assert parsed.trace_id == "ab" * 16
    assert parsed.span_id == "cd" * 8
    assert parsed.sampled


def test_traceparent_rejects_malformed():
    bad = [None, "", "garbage", "00-short-cdcd-01",
           "00-" + "0" * 32 + "-" + "cd" * 8 + "-01",   # zero trace id
           "00-" + "ab" * 16 + "-" + "0" * 16 + "-01",  # zero span id
           "ff-" + "ab" * 16 + "-" + "cd" * 8 + "-01",  # version ff
           "00-" + "zz" * 16 + "-" + "cd" * 8 + "-01"]  # non-hex
    for header in bad:
        assert trace.TraceContext.from_traceparent(header) is None, header


def test_unsampled_flag_parses_and_reemits():
    ctx = trace.TraceContext.from_traceparent(
        "00-" + "ab" * 16 + "-" + "cd" * 8 + "-00")
    assert not ctx.sampled
    assert ctx.to_traceparent().endswith("-00")


def test_span_parentage_and_nesting():
    with trace.trace_span("root", ctx=trace.TraceContext.new()) as r:
        with trace.trace_span("child") as c:
            with trace.trace_span("grandchild"):
                pass
    by_name = {s["name"]: s for s in trace.ring_spans()}
    assert by_name["root"]["parent_id"] is None
    assert by_name["child"]["parent_id"] == r.span_id
    assert by_name["grandchild"]["parent_id"] == c.span_id
    assert len({s["trace_id"] for s in by_name.values()}) == 1


def test_capture_restore_across_thread_pool():
    """The satellite fix: a span opened on a worker thread parents to
    the submitting request, not to a fresh orphan root."""
    cap = {}
    with trace.trace_span("submit", ctx=trace.TraceContext.new()) as s:
        cap["ctx"] = capture_context()

    def worker():
        with restored(cap["ctx"]):
            with trace.trace_span("exec"):
                pass

    t = threading.Thread(target=worker)
    t.start()
    t.join()
    execd = [x for x in trace.ring_spans() if x["name"] == "exec"]
    assert execd and execd[0]["parent_id"] == s.span_id
    assert execd[0]["trace_id"] == s.ctx.trace_id


def test_legacy_span_stack_restored_too(tmp_path):
    """capture_context() also carries the PR-2 span() name stack: the
    profiler event for a worker-side span names the submitting span as
    its parent instead of None."""
    from mxnet_tpu import profiler
    profiler.set_config(filename=str(tmp_path / "prof"),
                        aggregate_stats=True)
    profiler.start()
    try:
        with obs.span("submitter"):
            cap = capture_context()

            def worker():
                with restored(cap):
                    with obs.span("worker-side"):
                        pass

            t = threading.Thread(target=worker)
            t.start()
            t.join()
    finally:
        path = profiler.dump()
    events = json.load(open(path))["traceEvents"]
    ws = [e for e in events if e.get("name") == "worker-side"]
    assert ws and ws[0]["args"]["parent"] == "submitter"


def test_trace_disabled_is_noop(monkeypatch):
    monkeypatch.setenv("MXTPU_TRACE", "0")
    assert not trace.enabled()
    with trace.trace_span("root", ctx=trace.TraceContext("a" * 32)):
        with trace.trace_span("child"):
            pass
    assert trace.ring_spans() == []
    assert trace.step_trace_context("t", 0) is None


def test_unsampled_records_nothing_but_keeps_identity():
    ctx = trace.TraceContext("a" * 32, None, sampled=False)
    with trace.trace_span("root", ctx=ctx):
        # identity visible to children (echoed trace ids), no records
        assert trace.current() is ctx
    assert trace.ring_spans() == []


def test_step_trace_context_deterministic_across_ranks(monkeypatch):
    monkeypatch.setenv("MXTPU_GANG_DIR", "/tmp/gang-x")
    a = trace.step_trace_context("gluon.trainer", 7)
    monkeypatch.setenv("JAX_PROCESS_ID", "1")   # another "rank"
    b = trace.step_trace_context("gluon.trainer", 7)
    c = trace.step_trace_context("gluon.trainer", 8)
    assert a.trace_id == b.trace_id
    assert a.trace_id != c.trace_id


def test_steptimer_step_trace_and_exemplar(tmp_path, monkeypatch):
    monkeypatch.setenv("MXTPU_TRACE_DIR", str(tmp_path))
    timer = StepTimer("trace.test")
    recs = []
    for _ in range(3):
        timer.begin_step()
        with timer.phase("allreduce"):
            pass
        recs.append(timer.end_step(batch_size=4))
    trace.close_shard()
    assert all("trace_id" in r for r in recs)
    shard = tmp_path / ("trace_rank_%d.jsonl" % trace.current_rank())
    spans = [json.loads(l) for l in open(shard)
             if json.loads(l).get("event") == "span"]
    steps = [s for s in spans if s["name"] == "step"]
    phases = [s for s in spans if s["name"] == "allreduce"]
    assert len(steps) == 3 and len(phases) == 3
    roots = {s["trace_id"]: s["span_id"] for s in steps}
    for p in phases:
        assert p["parent_id"] == roots[p["trace_id"]]
    # the step-time histogram kept the worst steps' trace ids
    hist = obs.REGISTRY.get("train.step.seconds")
    ex = hist.exemplars(source="trace.test")
    assert ex and all(tid in roots for _, tid in ex)


# -- registry: cardinality + exemplars + exposition ----------------------
def test_label_cardinality_collapses_to_overflow(monkeypatch):
    monkeypatch.setenv("MXTPU_METRIC_MAX_LABELS", "3")
    c = obs_registry.Counter("t.cardinality")
    for i in range(10):
        c.inc(model="m%d" % i)
    keys = c.labelsets()
    assert len(keys) == 4                     # 3 real + overflow
    assert obs_registry.OVERFLOW_KEY in keys
    assert c.get(overflow="true") == 7
    # established labelsets keep counting exactly
    c.inc(model="m0")
    assert c.get(model="m0") == 2
    dropped = obs.REGISTRY.get("observability.labels.dropped")
    assert dropped.get(metric="t.cardinality") >= 7


def test_cardinality_bound_applies_to_gauge_and_histogram(monkeypatch):
    monkeypatch.setenv("MXTPU_METRIC_MAX_LABELS", "2")
    g = obs_registry.Gauge("t.gauge.cardinality")
    h = obs_registry.Histogram("t.hist.cardinality")
    for i in range(5):
        g.set(i, trace="t%d" % i)
        h.observe(0.1, trace="t%d" % i)
    assert len(g.labelsets()) == 3
    assert len(h.labelsets()) == 3
    assert h.count(overflow="true") == 3


def test_histogram_exemplars_keep_worst_k(monkeypatch):
    monkeypatch.setenv("MXTPU_TRACE_EXEMPLARS", "2")
    h = obs.REGISTRY.histogram("t.exemplars")
    h.observe(0.1, exemplar="fast")
    h.observe(0.9, exemplar="slowest")
    h.observe(0.5, exemplar="slow")
    h.observe(0.2)                 # untagged observations still count
    assert h.exemplars() == [(0.9, "slowest"), (0.5, "slow")]
    assert h.count() == 4
    # snapshot/export carries them
    rows = {name: val for name, kind, labels, val
            in obs.REGISTRY.snapshot() if name == "t.exemplars"}
    assert rows and rows["t.exemplars"]["exemplars"][0][1] == "slowest"


_PROM_LINE = re.compile(
    r'^([a-zA-Z_:][a-zA-Z0-9_:]*)(?:\{(.*)\})?\s+(-?[0-9.e+-]+|NaN)$')
_PROM_LABEL = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def _parse_prometheus(text):
    """Strict exposition-format parser: every non-comment line must be
    `name{labels} value`; label values unescape per the format. Returns
    ({(name, frozen labels): value}, {name: [help/type lines]})."""
    samples, meta = {}, {}
    for lineno, line in enumerate(text.splitlines(), 1):
        if not line:
            continue
        if line.startswith("# HELP ") or line.startswith("# TYPE "):
            parts = line.split(None, 3)
            meta.setdefault(parts[2], []).append(parts[1])
            continue
        assert not line.startswith("#"), "stray comment %r" % line
        m = _PROM_LINE.match(line)
        assert m, "line %d unparseable: %r" % (lineno, line)
        name, labelstr, value = m.groups()
        labels = {}
        if labelstr:
            consumed = 0
            for lm in _PROM_LABEL.finditer(labelstr):
                raw = lm.group(2)
                labels[lm.group(1)] = (
                    raw.replace("\\n", "\n").replace('\\"', '"')
                    .replace("\\\\", "\\"))
                consumed = lm.end()
            rest = labelstr[consumed:].strip(", ")
            assert not rest, "unparsed label text %r" % rest
        samples[(name, tuple(sorted(labels.items())))] = float(value)
    return samples, meta


def test_prometheus_escaping_roundtrips():
    c = obs_registry.Counter("t.escaping")
    nasty = 'quo"te\\back\nslash'
    c.inc(3, op=nasty)
    reg = obs_registry.MetricsRegistry()
    reg._metrics["t.escaping"] = c      # isolated registry
    samples, _ = _parse_prometheus(reg.to_prometheus())
    key = ("mxtpu_t_escaping_total", (("op", nasty),))
    assert samples.get(key) == 3.0, sorted(samples)


def test_prometheus_help_type_once_per_family_and_roundtrip():
    reg = obs_registry.MetricsRegistry()
    c = reg.counter("t.family", help="a help line")
    c.inc(1, shard="a")
    c.inc(2, shard="b")
    h = reg.histogram("t.latency", help="hist help",
                      buckets=(0.1, 1.0))
    h.observe(0.05, route="x")
    h.observe(5.0, route="x")
    text = reg.to_prometheus()
    assert text.count("# TYPE mxtpu_t_family_total counter") == 1
    assert text.count("# HELP mxtpu_t_family_total a help line") == 1
    assert text.count("# TYPE mxtpu_t_latency histogram") == 1
    samples, meta = _parse_prometheus(text)
    assert samples[("mxtpu_t_family_total", (("shard", "a"),))] == 1.0
    assert samples[("mxtpu_t_family_total", (("shard", "b"),))] == 2.0
    # histogram cumulative buckets + sum/count round-trip
    assert samples[("mxtpu_t_latency_bucket",
                    (("le", "0.1"), ("route", "x")))] == 1.0
    assert samples[("mxtpu_t_latency_bucket",
                    (("le", "+Inf"), ("route", "x")))] == 2.0
    assert samples[("mxtpu_t_latency_count", (("route", "x"),))] == 2.0
    assert meta["mxtpu_t_family_total"] == ["HELP", "TYPE"]


def test_full_registry_exposition_parses():
    """The real process registry (every metric the suite touched so
    far) round-trips through the strict parser — /metricsz is always
    scrapeable."""
    _parse_prometheus(obs.REGISTRY.to_prometheus())


# -- live plane -----------------------------------------------------------
def test_observability_server_routes():
    srv = httpz.ObservabilityServer(port=0).start()
    try:
        text = urllib.request.urlopen(
            srv.url + "/metricsz", timeout=10).read().decode()
        _parse_prometheus(text)
        dbg = json.loads(urllib.request.urlopen(
            srv.url + "/debugz", timeout=10).read().decode())
        assert "threads" in dbg and "trace" in dbg and "lease" in dbg
        assert "compile" in dbg
        ok = json.loads(urllib.request.urlopen(
            srv.url + "/healthz", timeout=10).read().decode())
        assert ok["ok"]
        assert urllib.request.urlopen(
            srv.url + "/metricsz?x=1", timeout=10).status == 200
    finally:
        srv.close()


# -- gateway E2E ----------------------------------------------------------
FEATURES, CLASSES = 8, 4


def _mlp_engine(seed, name):
    from mxnet_tpu.serving import InferenceEngine
    rng = np.random.RandomState(seed)
    h = mx.sym.FullyConnected(data=mx.sym.var("data"),
                              num_hidden=CLASSES, name="fc1")
    sym = mx.sym.SoftmaxOutput(data=h, name="softmax")
    args = {"fc1_weight": mx.nd.array(
                (rng.randn(CLASSES, FEATURES) * 0.5).astype(np.float32)),
            "fc1_bias": mx.nd.array(
                rng.randn(CLASSES).astype(np.float32))}
    return InferenceEngine.from_symbol(
        sym, args, {}, {"data": (FEATURES,)}, 2, name=name)


def test_gateway_traceparent_e2e(tmp_path, monkeypatch):
    """ISSUE acceptance: a request with a traceparent header yields the
    same trace id echoed in the response AND a merged trace with
    gateway → admission → batch → dispatch spans correctly parented
    across >= 2 thread hops (handler thread -> dispatcher -> worker)."""
    from mxnet_tpu.serving import Gateway, ModelRegistry
    monkeypatch.setenv("MXTPU_TRACE_DIR", str(tmp_path))
    monkeypatch.setenv("MXTPU_TELEMETRY", str(tmp_path / "t.jsonl"))
    reg = ModelRegistry()
    reg.register("m0", lambda: _mlp_engine(0, "m0"), eager=True)
    gw = Gateway(reg).start()
    try:
        tp_in = "00-" + "ab" * 16 + "-" + "cd" * 8 + "-01"
        req = urllib.request.Request(
            gw.url + "/v1/models/m0:predict",
            data=json.dumps({"inputs": [[0.1] * FEATURES]}).encode(),
            headers={"Content-Type": "application/json",
                     "traceparent": tp_in})
        resp = urllib.request.urlopen(req, timeout=60)
        body = json.loads(resp.read().decode())
        tp_out = resp.headers.get("traceparent")
        assert tp_out and tp_out.split("-")[1] == "ab" * 16
        assert body["trace_id"] == "ab" * 16
        # a second request WITHOUT a header mints a fresh root
        req2 = urllib.request.Request(
            gw.url + "/v1/models/m0:predict",
            data=json.dumps({"inputs": [[0.2] * FEATURES]}).encode(),
            headers={"Content-Type": "application/json"})
        resp2 = urllib.request.urlopen(req2, timeout=60)
        tid2 = json.loads(resp2.read().decode())["trace_id"]
        assert tid2 != "ab" * 16
        # gateway introspection routes
        _parse_prometheus(urllib.request.urlopen(
            gw.url + "/metricsz", timeout=10).read().decode())
        dbg = json.loads(urllib.request.urlopen(
            gw.url + "/debugz", timeout=10).read().decode())
        assert dbg["gateway"]["queues"].keys() >= {"interactive"}
        assert "m0" in dbg["registry"]["resident"]
        assert "servers" in dbg and "threads" in dbg
    finally:
        gw.close()
        from mxnet_tpu.observability import telemetry
        telemetry.close_stream()
    trace.close_shard()
    shard = tmp_path / ("trace_rank_%d.jsonl" % trace.current_rank())
    spans = [json.loads(l) for l in open(shard)]
    mine = {s["name"]: s for s in spans
            if s.get("trace_id") == "ab" * 16}
    assert {"gateway.request", "gateway.admission", "serving.queue",
            "serving.batch", "engine.dispatch"} <= set(mine)
    root = mine["gateway.request"]
    assert root["parent_id"] == "cd" * 8          # the client's span
    assert mine["gateway.admission"]["parent_id"] == root["span_id"]
    assert mine["serving.queue"]["parent_id"] == root["span_id"]
    assert mine["serving.batch"]["parent_id"] == root["span_id"]
    assert mine["engine.dispatch"]["parent_id"] == \
        mine["serving.batch"]["span_id"]
    # >= 2 thread hops: handler thread vs worker thread
    assert mine["serving.batch"]["tid"] != root["tid"]
    # trace_report merges the shard and reconstructs the chain
    tr = _load_tool("trace_report")
    entries = tr.summarize(tr.load_spans([str(shard)]))
    e = {x["trace_id"]: x for x in entries}["ab" * 16]
    assert e["name"] == "gateway.request"
    names = [c["name"] for c in e["critical"]]
    assert names[0] == "gateway.request"
    # the gateway telemetry record carries the trace id for exemplars
    recs = [json.loads(l) for l in open(tmp_path / "t.jsonl")]
    served = [r for r in recs if r.get("source") == "gateway"
              and r.get("event") == "request"]
    assert any(r.get("trace_id") == "ab" * 16 for r in served)


def test_gateway_trace_off_no_header(monkeypatch):
    from mxnet_tpu.serving import Gateway, ModelRegistry
    monkeypatch.setenv("MXTPU_TRACE", "0")
    reg = ModelRegistry()
    reg.register("m0", lambda: _mlp_engine(0, "m0"), eager=True)
    gw = Gateway(reg).start()
    try:
        req = urllib.request.Request(
            gw.url + "/v1/models/m0:predict",
            data=json.dumps({"inputs": [[0.1] * FEATURES]}).encode(),
            headers={"Content-Type": "application/json"})
        resp = urllib.request.urlopen(req, timeout=60)
        assert resp.headers.get("traceparent") is None
        assert "trace_id" not in json.loads(resp.read().decode())
    finally:
        gw.close()
    assert trace.ring_spans() == []


# -- trace_report ---------------------------------------------------------
def _write_shard(path, rank, spans, clock_wall=1000.0):
    with open(path, "w") as f:
        f.write(json.dumps({"source": "trace", "event": "clock",
                            "step_time": 0.0, "ts": clock_wall,
                            "perf": 0.0, "rank": rank,
                            "pid": 1}) + "\n")
        for s in spans:
            rec = {"source": "trace", "event": "span", "rank": rank,
                   "pid": 1, "tid": 1, "step_time": s.pop("dur"), **s}
            f.write(json.dumps(rec) + "\n")


def test_trace_report_merges_ranks_and_aligns(tmp_path):
    tid = "f" * 32
    _write_shard(tmp_path / "trace_rank_0.jsonl", 0, [
        {"name": "step", "trace_id": tid, "span_id": "r0",
         "parent_id": None, "ts": 100.0, "dur": 1.0, "step": 4,
         "source": "gluon.trainer"},
        {"name": "allreduce", "trace_id": tid, "span_id": "a0",
         "parent_id": "r0", "ts": 100.1, "dur": 0.8},
        {"name": "exchange/bucket", "trace_id": tid, "span_id": "x0",
         "parent_id": "a0", "ts": 100.15, "dur": 0.7},
    ])
    _write_shard(tmp_path / "trace_rank_1.jsonl", 1, [
        {"name": "step", "trace_id": tid, "span_id": "r1",
         "parent_id": None, "ts": 100.0, "dur": 1.2, "step": 4,
         "source": "gluon.trainer"},
        {"name": "exchange/bucket", "trace_id": tid, "span_id": "x1",
         "parent_id": "r1", "ts": 100.2, "dur": 1.0},
    ])
    tr = _load_tool("trace_report")
    spans = tr.load_spans(tr._shard_files([str(tmp_path)]))
    assert len(spans) == 5
    entries = tr.summarize(spans)
    assert len(entries) == 1
    e = entries[0]
    # ONE merged per-step trace carrying BOTH ranks' exchange spans
    assert e["ranks"] == [0, 1] and e["step"] == 4
    assert e["roots"] == 2
    # critical path follows the slowest root (rank 1)
    assert e["dur_s"] == pytest.approx(1.2)
    assert [c["name"] for c in e["critical"]] == ["step",
                                                  "exchange/bucket"]
    assert e["critical"][1]["rank"] == 1
    # chrome trace: one process lane per rank
    chrome = tr.to_chrome_trace(spans)
    pids = {ev["pid"] for ev in chrome["traceEvents"]
            if ev.get("ph") == "X"}
    assert pids == {0, 1}
    report = tr.format_report(entries)
    assert "step 4" in report and "rank(s) 0,1" in report


def test_trace_report_clock_offset_from_heartbeats(tmp_path):
    tid = "e" * 32
    _write_shard(tmp_path / "trace_rank_0.jsonl", 0, [
        {"name": "step", "trace_id": tid, "span_id": "r0",
         "parent_id": None, "ts": 100.0, "dur": 1.0}])
    # rank 0's clock runs 5s behind the shared FS: heartbeat stamp
    # 100, file mtime now — offset shifts its spans forward
    hb = tmp_path / "rank_0.hb"
    hb.write_text(json.dumps({"rank": 0, "heartbeat": 100.0}))
    tr = _load_tool("trace_report")
    offsets = tr.rank_offsets([str(tmp_path)])
    assert 0 in offsets and offsets[0] > 0
    spans = tr.load_spans([str(tmp_path / "trace_rank_0.jsonl")],
                          offsets)
    assert spans[0]["ts"] == pytest.approx(100.0 + offsets[0])


def test_trace_report_strict_on_garbage(tmp_path):
    tr = _load_tool("trace_report")
    with pytest.raises(tr.TraceReportError):
        tr._shard_files([str(tmp_path)])          # no shards
    bad = tmp_path / "trace_rank_0.jsonl"
    bad.write_text("not json\n{}\n")
    with pytest.raises(tr.TraceReportError):
        tr.load_spans([str(bad)])
    # a torn LAST line (writer died mid-span) is tolerated
    tid = "d" * 32
    torn = tmp_path / "trace_rank_1.jsonl"
    _write_shard(torn, 1, [
        {"name": "s", "trace_id": tid, "span_id": "a",
         "parent_id": None, "ts": 1.0, "dur": 0.1}])
    with open(torn, "a") as f:
        f.write('{"source": "trace", "event": "span", "trunc')
    assert len(tr.load_spans([str(torn)])) == 1


# -- exemplars through report + gate --------------------------------------
def test_report_excludes_trace_source_and_surfaces_exemplars(tmp_path):
    stream = tmp_path / "t.jsonl"
    recs = [
        {"source": "train", "step": 0, "step_time": 0.01,
         "trace_id": "t-fast"},
        {"source": "train", "step": 1, "step_time": 5.0,
         "trace_id": "t-slow"},
        # trace spans must be excluded from the headline exactly once
        {"source": "trace", "event": "span", "step_time": 99.0,
         "trace_id": "t-slow", "name": "step", "span_id": "x",
         "ts": 0.0},
        {"source": "gateway", "event": "request", "step_time": 0.002,
         "class": "interactive", "model": "m", "status": 200,
         "trace_id": "g-fast"},
        {"source": "gateway", "event": "request", "step_time": 0.9,
         "class": "interactive", "model": "m", "status": 200,
         "trace_id": "g-slow"},
    ]
    with open(stream, "w") as f:
        for r in recs:
            f.write(json.dumps(r) + "\n")
    rep = _load_tool("telemetry_report")
    assert "trace" in rep.EXCLUDED_HEADLINE_SOURCES
    s = rep.summarize(rep.load_records(str(stream)))
    assert s["steps"] == 2                     # trace span NOT blended
    assert s["step_time_p99_s"] == pytest.approx(5.0)
    assert s["trace_spans"] == 1
    assert s["step_time_exemplars"][0] == "t-slow"
    assert s["gateway_interactive_exemplars"][0] == "g-slow"
    out = rep.format_summary(s)
    assert "t-slow" in out

    # a forced p99 breach prints >= 1 exemplar trace id (acceptance)
    gate = _load_tool("perf_gate")
    import io
    from contextlib import redirect_stderr, redirect_stdout
    err, out_buf = io.StringIO(), io.StringIO()
    with redirect_stdout(out_buf), redirect_stderr(err):
        rc = gate.main([str(stream),
                        "--max-p99-ms-class", "interactive=1",
                        "--max-step-p95-s", "0.1"])
    assert rc == 1
    stderr = err.getvalue()
    assert "BREACH gateway_interactive_p99_ms" in stderr
    assert "g-slow" in stderr and "t-slow" in stderr
    verdict = json.loads(out_buf.getvalue().splitlines()[0])
    assert verdict["exemplars"]["gateway_interactive_p99_ms"][0] == \
        "g-slow"


# -- docs drift -----------------------------------------------------------
def test_docs_drift_gate_passes():
    drift = _load_tool("docs_drift")
    assert drift.main([]) == 0


def test_docs_drift_detects_both_directions(tmp_path, monkeypatch):
    drift = _load_tool("docs_drift")
    code = drift.code_metrics()
    docs = drift.doc_metrics()
    assert code == docs
    # the expansion shorthand: `a.b.c` / `.d` and `.d.e`
    doc = tmp_path / "obs.md"
    doc.write_text("| `a.b.c` / `.d` / `.d.e` | counter | x |\n")
    assert drift.doc_metrics(str(doc)) == {"a.b.c", "a.b.d", "a.d.e"}
    src = tmp_path / "src"
    src.mkdir()
    (src / "m.py").write_text(
        'from x import counter\n'
        'C = counter("emitted.not.documented")\n'
        'import time\n'
        't = time.perf_counter()\n')
    assert drift.code_metrics(str(src)) == {"emitted.not.documented"}


@pytest.mark.slow
def test_two_rank_step_traces_merge_for_real(tmp_path):
    """The real path, not synthetic shards: two processes tagged as
    ranks 0/1 of one gang train a few steps through the actual
    Trainer/StepTimer pipeline; their shards merge into one per-step
    trace carrying both ranks (the deterministic step-id contract)."""
    import subprocess
    code = (
        "import numpy as np\n"
        "import mxnet_tpu as mx\n"
        "from mxnet_tpu import gluon, autograd\n"
        "net = gluon.nn.Dense(4)\n"
        "net.initialize(mx.init.Xavier())\n"
        "tr = gluon.Trainer(net.collect_params(), 'sgd',\n"
        "                   {'learning_rate': 0.1})\n"
        "x = mx.nd.array(np.ones((4, 8), np.float32))\n"
        "y = mx.nd.array(np.ones((4, 4), np.float32))\n"
        "lf = gluon.loss.L2Loss()\n"
        "for _ in range(2):\n"
        "    with autograd.record():\n"
        "        loss = lf(net(x), y)\n"
        "    loss.backward()\n"
        "    tr.step(4)\n")
    for rank in ("0", "1"):
        env = dict(os.environ, MXTPU_GANG_DIR=str(tmp_path),
                   JAX_PROCESS_ID=rank, JAX_PLATFORMS="cpu")
        r = subprocess.run([sys.executable, "-c", code], env=env,
                           capture_output=True, text=True, timeout=300)
        assert r.returncode == 0, r.stderr[-2000:]
    tr_tool = _load_tool("trace_report")
    spans = tr_tool.load_spans(tr_tool._shard_files([str(tmp_path)]))
    entries = tr_tool.summarize(spans)
    steps = [e for e in entries if e["name"] == "step"]
    assert steps and all(e["ranks"] == [0, 1] for e in steps), entries
    # each merged step trace has one root per rank, phases under each
    assert all(e["roots"] == 2 for e in steps)


def test_metrics_port_singleton(monkeypatch):
    httpz.stop_singleton()
    monkeypatch.delenv("MXTPU_METRICS_PORT", raising=False)
    assert httpz.maybe_start() is None
    monkeypatch.setenv("MXTPU_METRICS_PORT", "0")   # 0 = disabled
    assert httpz.maybe_start() is None
