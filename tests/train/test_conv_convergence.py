"""Convergence gate for conv nets (reference: tests/python/train/
test_conv.py — MNIST LeNet must reach 0.93 test accuracy).

Real CIFAR-10 binaries are not present in this zero-egress environment
(SCOPE.md §10): when `~/.mxnet/datasets/cifar10` holds the binary
batches this gate trains ResNet on them (the chip path, results logged
to PERF.md); otherwise it trains on a procedural 10-class image set
whose classes are spatial patterns (oriented bars / checker scales /
center blobs) — learnable only by actual convolutional feature
learning, not color histograms.
"""
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, autograd, gluon
from mxnet_tpu.gluon import nn


def _cifar_available():
    root = os.path.expanduser("~/.mxnet/datasets/cifar10")
    return any(os.path.exists(os.path.join(root, f))
               for f in ("data_batch_1.bin", "cifar-10-binary.tar.gz"))


def synth_images(rng, n, size=28):
    """10 classes of rendered spatial patterns + noise."""
    X = np.zeros((n, 1, size, size), "float32")
    y = rng.randint(0, 10, n)
    xs = np.arange(size)
    for i in range(n):
        c = y[i]
        img = np.zeros((size, size), "float32")
        if c < 4:                      # oriented bars, 4 angles
            period = 6
            ang = c * np.pi / 4
            gx = np.cos(ang) * xs[None, :] + np.sin(ang) * xs[:, None]
            img = (np.sin(2 * np.pi * gx / period) > 0).astype("float32")
        elif c < 7:                    # checkerboard at 3 scales
            k = [2, 4, 7][c - 4]
            img = ((xs[None, :] // k + xs[:, None] // k) % 2
                   ).astype("float32")
        else:                          # blobs at 3 radii
            r = [4, 8, 12][c - 7]
            cx = rng.randint(size // 3, 2 * size // 3)
            cy = rng.randint(size // 3, 2 * size // 3)
            d2 = (xs[None, :] - cx) ** 2 + (xs[:, None] - cy) ** 2
            img = (d2 < r * r).astype("float32")
        shift = rng.randint(-3, 4, 2)
        img = np.roll(np.roll(img, shift[0], 0), shift[1], 1)
        X[i, 0] = img + rng.randn(size, size) * 0.3
    return X, y.astype("float32")


def small_cnn():
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Conv2D(16, 3, padding=1), nn.BatchNorm(),
                nn.Activation("relu"), nn.MaxPool2D(2),
                nn.Conv2D(32, 3, padding=1), nn.BatchNorm(),
                nn.Activation("relu"), nn.MaxPool2D(2),
                nn.GlobalAvgPool2D(), nn.Dense(10))
    return net


@pytest.mark.skipif(_cifar_available(), reason="real CIFAR present — "
                    "run tools/train_gates.py for the full gate")
def test_conv_net_converges_synthetic():
    rng = np.random.RandomState(0)
    Xtr, ytr = synth_images(rng, 3000)
    Xte, yte = synth_images(rng, 600)
    net = small_cnn()
    net.initialize(mx.init.Xavier())
    net(nd.array(Xtr[:2]))
    net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 2e-3})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    B = 100
    for epoch in range(6):
        perm = rng.permutation(len(Xtr))
        for b in range(len(Xtr) // B):
            idx = perm[b * B:(b + 1) * B]
            x, y = nd.array(Xtr[idx]), nd.array(ytr[idx])
            with autograd.record():
                loss = loss_fn(net(x), y)
            loss.backward()
            trainer.step(B)
    preds = []
    for b in range(len(Xte) // B):
        preds.append(net(nd.array(Xte[b * B:(b + 1) * B])
                         ).asnumpy().argmax(1))
    acc = (np.concatenate(preds) == yte[:len(preds) * B]).mean()
    assert acc >= 0.90, "conv net failed the 0.90 gate: %.3f" % acc
