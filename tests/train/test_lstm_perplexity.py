"""Language-model convergence gate (reference: tests/python/train/
test_bucketing.py — the PTB LSTM must reach a perplexity bound).

A synthetic order-2 Markov character corpus stands in for PTB (zero
egress, SCOPE.md §10); its entropy floor is known by construction, so
the assertions are meaningful: perplexity must (a) drop monotonically
across epoch pairs and (b) close most of the gap from the unigram
baseline to the process floor.
"""
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import nd, autograd, gluon
from mxnet_tpu.gluon import nn


V = 20


def markov_corpus(rng, n):
    """Order-2 chain: next char depends on the previous two; each
    context has 3 plausible continuations (floor = log 3 when uniform)."""
    trans = rng.randint(0, V, size=(V, V, 3))
    toks = [0, 1]
    for _ in range(n):
        a, b = toks[-2], toks[-1]
        toks.append(int(trans[a, b, rng.randint(0, 3)]))
    return np.asarray(toks, "int32")


class CharLSTM(gluon.HybridBlock):
    def __init__(self, hidden=96, **kw):
        super().__init__(**kw)
        with self.name_scope():
            self.embed = nn.Embedding(V, 32)
            self.lstm = gluon.rnn.LSTM(hidden, layout="NTC")
            self.head = nn.Dense(V, flatten=False)

    def hybrid_forward(self, F, x):
        return self.head(self.lstm(self.embed(x)))


def _perplexity(net, toks, T, B):
    n = (len(toks) - 1) // T // B * B
    x = toks[:n * T].reshape(n, T)
    t = toks[1:n * T + 1].reshape(n, T)
    nll = []
    for b in range(n // B):
        logits = net(nd.array(x[b * B:(b + 1) * B].astype("float32"))
                     ).asnumpy()
        lp = logits - logits.max(-1, keepdims=True)
        lp = lp - np.log(np.exp(lp).sum(-1, keepdims=True))
        tgt = t[b * B:(b + 1) * B]
        nll.append(-np.take_along_axis(
            lp, tgt[..., None], axis=-1).mean())
    return float(np.exp(np.mean(nll)))


def test_lstm_perplexity_decreases_to_near_floor():
    rng = np.random.RandomState(3)
    corpus = markov_corpus(rng, 60000)
    # validation must come from the SAME transition table: hold out tail
    val = corpus[-8000:]
    train = corpus[:-8000]
    T, B = 16, 64

    net = CharLSTM()
    net.initialize(mx.init.Xavier())
    net(nd.array(np.zeros((2, T), "float32")))
    net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 3e-3})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

    n = (len(train) - 1) // T
    x = train[:n * T].reshape(n, T).astype("float32")
    t = train[1:n * T + 1].reshape(n, T).astype("float32")

    ppl = [_perplexity(net, val, T, B)]
    for epoch in range(4):
        perm = rng.permutation(n)
        for b in range(n // B):
            idx = perm[b * B:(b + 1) * B]
            xb, tb = nd.array(x[idx]), nd.array(t[idx])
            with autograd.record():
                loss = loss_fn(net(xb), tb)
            loss.backward()
            trainer.step(B)
        ppl.append(_perplexity(net, val, T, B))

    assert all(b < a * 1.02 for a, b in zip(ppl, ppl[1:])), \
        "perplexity not decreasing: %s" % ppl
    # unigram baseline ~V (uniformish); process floor ~3 given 2 context
    # chars (model sees 16, so it can reach near-floor)
    assert ppl[-1] < 0.45 * ppl[0], \
        "perplexity %.1f closed too little of the %.1f->3 gap" \
        % (ppl[-1], ppl[0])
