import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))

# CPU by default (the chip path is opt-in via MXTPU_TRAIN_ON_CHIP=1,
# run from a fresh process with the tunnel up)
if not os.environ.get("MXTPU_TRAIN_ON_CHIP"):
    import jax
    jax.config.update("jax_platforms", "cpu")
