"""Serving front door & model multiplexing (ISSUE 12, docs/serving.md).

Covers the acceptance surface end to end: three models multiplexed in
one process under a budget that only fits two — LRU eviction and
transparent single-flight reload observed over REAL HTTP, responses
byte-identical to direct `ModelServer.infer`/`generate`; in-flight
requests on an evicted model finish token-identically; priority-class
admission grants interactive before batch before best_effort and sheds
expired-in-queue requests before compute; `/readyz` flips only after
every eager engine's warmup; `ServerClosed` names the draining server;
the `gateway.admit` chaos site fails one request, not the server; and
`tools/kill_stale.py` recognizes a gateway-role lease holder.
"""
import importlib.util
import json
import os
import re
import socket
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.resilience import Deadline, DeadlineExceeded, chaos
from mxnet_tpu.resilience.lease import _proc_starttime
from mxnet_tpu.serving import (DecodeEngine, Gateway, InferenceEngine,
                               ModelRegistry, ModelServer,
                               RequestRejected, ServerClosed)
from mxnet_tpu.serving.gateway.frontdoor import _Admission

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

FEATURES, CLASSES = 8, 4


@pytest.fixture(autouse=True)
def _clean_chaos():
    chaos.configure("")
    yield
    chaos.reset()


def _mlp_engine(seed, name=None, max_batch=2):
    """Tiny frozen MLP; every seed shares one program set (same
    shapes), different weights — a response routed to the wrong model
    cannot pass the byte-identity checks."""
    rng = np.random.RandomState(seed)
    h = mx.sym.FullyConnected(data=mx.sym.var("data"),
                              num_hidden=CLASSES, name="fc1")
    sym = mx.sym.SoftmaxOutput(data=h, name="softmax")
    args = {"fc1_weight": mx.nd.array(
                (rng.randn(CLASSES, FEATURES) * 0.5).astype(np.float32)),
            "fc1_bias": mx.nd.array(
                rng.randn(CLASSES).astype(np.float32))}
    return InferenceEngine.from_symbol(
        sym, args, {}, {"data": (FEATURES,)}, max_batch,
        name=name or ("m%d" % seed))


def _mlp_builder(seed, name=None):
    return lambda: _mlp_engine(seed, name=name)


def _gpt_block(seed=3, vocab=32, max_seq_len=16):
    from mxnet_tpu.gluon.model_zoo.gpt import GPTDecoder
    np.random.seed(seed)
    blk = GPTDecoder(vocab, max_seq_len=max_seq_len, num_layers=1,
                     num_heads=2, embed_dim=16)
    blk.initialize(mx.init.Xavier(magnitude=2.5))
    return blk


def _post(url, payload, timeout=60):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode("utf-8"),
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read().decode("utf-8"))
    except urllib.error.HTTPError as err:
        return err.code, json.loads(err.read().decode("utf-8"))


def _get(url, timeout=30):
    try:
        with urllib.request.urlopen(url, timeout=timeout) as r:
            return r.status, json.loads(r.read().decode("utf-8"))
    except urllib.error.HTTPError as err:
        return err.code, json.loads(err.read().decode("utf-8"))


# -- ServerClosed attribution (the PR-12 bugfix) --------------------------

def test_server_closed_names_the_draining_server():
    server = ModelServer(_mlp_engine(0, name="attrib"), num_workers=1,
                         max_wait_ms=1.0).start()
    assert server.drain(timeout=30)
    with pytest.raises(ServerClosed) as err:
        server.submit(np.zeros((1, FEATURES), np.float32))
    assert err.value.server == "attrib"
    assert "attrib" in str(err.value)


def test_batcher_and_scheduler_closed_errors_carry_the_name():
    from mxnet_tpu.serving import (ContinuousBatchScheduler,
                                   DynamicBatcher)
    b = DynamicBatcher(["data"], name="named_batcher")
    b.close()
    with pytest.raises(ServerClosed) as err:
        b.submit(np.zeros((1, FEATURES), np.float32))
    assert err.value.server == "named_batcher"
    sched = ContinuousBatchScheduler(
        DecodeEngine(_gpt_block(), max_slots=1, name="named_decode"),
        name="named_decode")
    sched.close()
    with pytest.raises(ServerClosed) as err:
        sched.submit([1, 2])
    assert err.value.server == "named_decode"


# -- accounting -----------------------------------------------------------

def test_device_bytes_measures_params_and_kv_cache():
    eng = _mlp_engine(1)
    expect = (CLASSES * FEATURES + CLASSES) * 4
    assert eng.device_bytes() == expect
    dec = DecodeEngine(_gpt_block(), max_slots=2)
    n = dec.device_bytes()
    assert n > int(dec._cache_k.nbytes) + int(dec._cache_v.nbytes) > 0
    server = ModelServer(eng, num_workers=1)
    assert server.device_bytes() == expect


# -- ModelRegistry --------------------------------------------------------

def test_registry_lru_eviction_and_transparent_reload():
    reg = ModelRegistry()
    for i in range(3):
        reg.register("m%d" % i, _mlp_builder(i), num_workers=1,
                     max_wait_ms=1.0)
    x = np.ones((1, FEATURES), np.float32)
    out0 = np.asarray(reg.get("m0").infer(x, timeout=30)[0])
    reg.get("m1").infer(x, timeout=30)
    per = reg.stats()["models"]["m0"]["bytes"]
    assert per > 0
    # budget fits two models; m0 is the coldest after touching m1
    reg.set_budget(budget_bytes=int(2.5 * per))
    assert reg.resident() == ["m0", "m1"]
    reg.get("m2").infer(x, timeout=30)
    assert reg.resident() == ["m1", "m2"]
    # transparent reload of the evicted model, counted, same answer
    assert reg.stats()["reloads"] == 0
    out0b = np.asarray(reg.get("m0").infer(x, timeout=30)[0])
    assert np.array_equal(out0, out0b)
    assert reg.stats()["reloads"] == 1
    assert reg.resident() == ["m0", "m2"]    # m1 was coldest
    assert reg.drain_all(timeout=30)


def test_registry_max_models_budget_and_unknown_name():
    reg = ModelRegistry(max_models=1)
    reg.register("a", _mlp_builder(0), num_workers=1)
    reg.register("b", _mlp_builder(1), num_workers=1)
    reg.get("a")
    reg.get("b")
    assert reg.resident() == ["b"]
    with pytest.raises(mx.base.MXNetError, match="unknown model"):
        reg.get("nope")
    with pytest.raises(mx.base.MXNetError, match="already registered"):
        reg.register("a", _mlp_builder(0))
    with pytest.raises(mx.base.MXNetError, match="name"):
        reg.register("bad:name", _mlp_builder(0))
    assert reg.drain_all(timeout=30)


def test_registry_single_flight_reload():
    """Concurrent requests for the same cold model trigger exactly ONE
    build; the rest wait on it and share the server."""
    calls = []

    def slow_builder():
        calls.append(1)
        time.sleep(0.2)
        return _mlp_engine(5, name="single")

    reg = ModelRegistry()
    reg.register("s", slow_builder, num_workers=1, max_wait_ms=1.0)
    reg.get("s")
    assert reg.evict("s", timeout=30)
    got, errs = [], []

    def hit():
        try:
            got.append(reg.get("s"))
        except Exception as err:  # noqa: BLE001 — recorded
            errs.append(err)

    threads = [threading.Thread(target=hit) for _ in range(6)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errs
    assert len(calls) == 2               # initial load + ONE reload
    assert all(s is got[0] for s in got)
    assert reg.stats()["reloads"] == 1
    assert reg.drain_all(timeout=30)


def test_eviction_under_load_finishes_inflight_token_identically():
    """The drain contract through the registry: a generation in flight
    on the evicted model completes with exactly the tokens the
    full-reforward oracle predicts; post-eviction submits get the
    model-named ServerClosed; the next registry.get serves again."""
    block = _gpt_block(seed=7)
    reg = ModelRegistry()
    reg.register("gpt",
                 lambda: DecodeEngine(block, max_slots=2, name="gpt"),
                 num_workers=1)
    server = reg.get("gpt")
    prompt = np.asarray([1, 4, 7], np.int32)
    handle = server.submit(prompt, max_new_tokens=8)
    evicted = threading.Thread(target=lambda: reg.evict("gpt",
                                                        timeout=60))
    evicted.start()
    toks = handle.result(timeout=60)
    evicted.join(timeout=60)
    expect = block.generate_reference(prompt, max_new_tokens=8)
    assert list(map(int, toks)) == list(map(int, expect))
    with pytest.raises(ServerClosed) as err:
        server.submit(prompt, max_new_tokens=2)
    assert err.value.server is not None
    # transparent reload serves the same tokens again
    toks2 = reg.get("gpt").generate(prompt, max_new_tokens=8,
                                    timeout=60)
    assert list(map(int, toks2)) == list(map(int, expect))
    assert reg.drain_all(timeout=30)


def test_registry_closed_after_drain_all_and_gateway_restart():
    """drain_all is terminal: a handler thread racing shutdown cannot
    resurrect a drained model (the rebuilt engine would outlive the
    released device lease) — it gets the model-named ServerClosed.
    A restarted Gateway reopens the registry and serves again."""
    reg = ModelRegistry()
    reg.register("c", _mlp_builder(6, name="c"), eager=True,
                 num_workers=1, max_wait_ms=1.0)
    gw = Gateway(reg, port=0).start()
    x = np.zeros((1, FEATURES), np.float32)
    st, _ = _post(gw.url + "/v1/models/c:predict",
                  {"inputs": x.tolist()})
    assert st == 200
    assert gw.close(timeout=30)
    with pytest.raises(ServerClosed) as err:
        reg.get("c")
    assert err.value.server == "c"
    # second life: start() reopens the registry, the eager model
    # reloads (readyz gates on it), requests serve again
    gw2 = Gateway(reg, port=0).start()
    try:
        assert gw2.ready()
        st, _ = _post(gw2.url + "/v1/models/c:predict",
                      {"inputs": x.tolist()})
        assert st == 200
    finally:
        gw2.close(timeout=30)


def test_registry_builder_failure_returns_to_cold():
    state = {"fail": True}

    def builder():
        if state["fail"]:
            raise RuntimeError("flaky load")
        return _mlp_engine(2, name="flaky")

    reg = ModelRegistry()
    reg.register("f", builder, num_workers=1)
    with pytest.raises(RuntimeError):
        reg.get("f")
    state["fail"] = False
    assert reg.get("f") is reg.get("f")     # retried, now resident
    assert reg.drain_all(timeout=30)


# -- priority-class admission --------------------------------------------

def test_admission_grants_strict_priority_order():
    adm = _Admission(concurrency=1, queue_depth=8)
    adm.enter("best_effort")                 # slot taken
    order = []
    done = threading.Event()

    def waiter(cls):
        adm.enter(cls)
        order.append(cls)
        adm.leave()
        if len(order) == 3:
            done.set()

    # enqueue in REVERSE priority; grants must come back in priority
    threads = []
    for cls in ("best_effort", "batch", "interactive"):
        t = threading.Thread(target=waiter, args=(cls,))
        t.start()
        threads.append(t)
        time.sleep(0.1)       # deterministic queue arrival order
    adm.leave()               # free the slot -> drain by priority
    assert done.wait(10)
    for t in threads:
        t.join(10)
    assert order == ["interactive", "batch", "best_effort"]


def test_admission_sheds_queue_full_and_expired_deadline():
    adm = _Admission(concurrency=1, queue_depth=1)
    adm.enter("interactive")
    blocker = threading.Thread(
        target=lambda: (adm.enter("best_effort"), adm.leave()))
    blocker.start()
    time.sleep(0.1)           # the queue slot is now occupied
    with pytest.raises(RequestRejected, match="queue full"):
        adm.enter("best_effort")
    assert adm.shed["best_effort"] == 1
    # an expired deadline sheds BEFORE any compute slot is granted
    with pytest.raises(DeadlineExceeded, match="shed before compute"):
        adm.enter("interactive", Deadline(0.0, what="t"))
    adm.leave()
    blocker.join(10)
    with pytest.raises(mx.base.MXNetError, match="priority"):
        adm.enter("vip")


# -- the HTTP front door --------------------------------------------------

@pytest.fixture(scope="module")
def gateway():
    reg = ModelRegistry()
    for i in range(3):
        reg.register("m%d" % i, _mlp_builder(i), eager=(i < 2),
                     num_workers=1, max_wait_ms=1.0)
    reg.register("gpt",
                 lambda: DecodeEngine(_gpt_block(seed=9), max_slots=2,
                                      name="gpt"),
                 num_workers=1)
    gw = Gateway(reg, port=0, concurrency=2, queue_depth=4).start()
    yield gw
    gw.close(timeout=60)


def test_http_predict_byte_identical_to_direct_infer(gateway):
    x = np.linspace(-1, 1, FEATURES, dtype=np.float32)[None]
    for name in ("m0", "m1"):
        direct = np.asarray(gateway.registry.get(name).infer(
            x, timeout=30)[0])
        st, body = _post(gateway.url + "/v1/models/%s:predict" % name,
                         {"inputs": x.tolist()})
        assert st == 200, body
        got = np.asarray(body["outputs"][0], np.float32)
        assert np.array_equal(direct, got)    # byte-identical round trip
    # distinct weights produced distinct answers (no routing mixup)
    _, b0 = _post(gateway.url + "/v1/models/m0:predict",
                  {"inputs": x.tolist()})
    _, b1 = _post(gateway.url + "/v1/models/m1:predict",
                  {"inputs": x.tolist()})
    assert b0["outputs"] != b1["outputs"]


def test_http_eviction_and_transparent_reload_under_budget(gateway):
    """The E2E acceptance: 3 models under a budget that fits 2 — LRU
    eviction + transparent reload over real HTTP, correct answers
    throughout."""
    reg = gateway.registry
    x = np.ones((1, FEATURES), np.float32)
    expected = {}
    for name in ("m0", "m1", "m2"):
        expected[name] = np.asarray(reg.get(name).infer(
            x, timeout=30)[0])
    per = max(s["bytes"] for s in reg.stats()["models"].values()
              if s["bytes"])
    reloads0 = reg.stats()["reloads"]
    try:
        reg.set_budget(budget_bytes=int(2.5 * per))
        assert len(reg.resident()) == 2
        for _ in range(2):
            for name in ("m0", "m1", "m2"):
                st, body = _post(
                    gateway.url + "/v1/models/%s:predict" % name,
                    {"inputs": x.tolist()})
                assert st == 200, body
                assert np.array_equal(
                    expected[name],
                    np.asarray(body["outputs"][0], np.float32))
        assert reg.stats()["reloads"] > reloads0   # misses observed
        st, body = _get(gateway.url + "/v1/models")
        assert st == 200
        assert len(body["models"]["resident"]) <= 2
    finally:
        reg.set_budget(budget_bytes=0)   # unbounded again


def test_http_generate_stream_and_nonstream_token_identical(gateway):
    prompt = [2, 5, 8]
    direct = gateway.registry.get("gpt").generate(
        np.asarray(prompt, np.int32), max_new_tokens=6, timeout=60)
    st, body = _post(gateway.url + "/v1/models/gpt:generate",
                     {"tokens": prompt, "max_new_tokens": 6})
    assert st == 200, body
    assert body["tokens"] == list(map(int, direct))
    req = urllib.request.Request(
        gateway.url + "/v1/models/gpt:generate",
        data=json.dumps({"tokens": prompt, "max_new_tokens": 6,
                         "stream": True}).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=60) as r:
        assert r.status == 200
        lines = [json.loads(l) for l in r.read().decode().splitlines()]
    assert [l["token"] for l in lines if "token" in l] \
        == list(map(int, direct))
    tail = dict(lines[-1])
    # the tail line also carries the request's trace id (ISSUE 13:
    # proxies drop unknown headers, so streaming callers join their
    # logs to the merged trace from the payload)
    trace_id = tail.pop("trace_id", None)
    assert trace_id is None or re.fullmatch("[0-9a-f]{32}", trace_id)
    assert tail == {"done": True, "tokens": 6}


def test_http_shed_and_error_paths(gateway):
    x = np.zeros((1, FEATURES), np.float32)
    # expired deadline: shed in the admission queue, 504, never computed
    served0 = gateway.registry.stats()["models"]["m0"]["requests"]
    st, body = _post(gateway.url + "/v1/models/m0:predict",
                     {"inputs": x.tolist(), "deadline_ms": 0})
    assert st == 504 and "shed before compute" in body["error"]
    assert gateway.registry.stats()["models"]["m0"]["requests"] \
        == served0
    st, body = _post(gateway.url + "/v1/models/nope:predict",
                     {"inputs": x.tolist()})
    assert st == 404 and "unknown model" in body["error"]
    st, body = _post(gateway.url + "/v1/models/m0:predict",
                     {"inputs": x.tolist(), "priority": "vip"})
    assert st == 400
    st, body = _post(gateway.url + "/v1/models/m0:generate",
                     {"tokens": [1, 2]})
    assert st in (400, 500)     # a forward model cannot generate
    st, body = _get(gateway.url + "/no/such/route")
    assert st == 404


def test_http_keep_alive_survives_errors(gateway):
    """One HTTP/1.1 keep-alive connection through every error shape:
    the body is always drained (a 404-with-body must not poison the
    next pipelined request), malformed payloads answer 400 instead of
    killing the connection, and the connection keeps serving."""
    import http.client
    conn = http.client.HTTPConnection("127.0.0.1", gateway.port,
                                      timeout=30)
    try:
        def post(path, payload):
            conn.request("POST", path, body=json.dumps(payload))
            r = conn.getresponse()
            return r.status, json.loads(r.read())

        x = [[0.0] * FEATURES]
        st, _ = post("/nope", {"inputs": x})          # 404 with a body
        assert st == 404
        st, _ = post("/v1/models/m0:predict", {"inputs": x})
        assert st == 200          # the connection was NOT poisoned
        st, body = post("/v1/models/m0:predict",
                        {"inputs": [[1, 2], [3]]})    # ragged
        assert st == 400 and "ValueError" in body["error"]
        st, _ = post("/v1/models/gpt:generate",
                     {"tokens": [1], "max_new_tokens": "lots"})
        assert st == 400
        st, body = post("/v1/models/m0:generate",
                        {"tokens": [1], "stream": True})
        assert st == 400          # forward model has no token stream
        assert "decode" in body["error"]
        st, _ = post("/v1/models/m0:predict", {"inputs": x})
        assert st == 200          # still serving after every error
    finally:
        conn.close()


def test_http_healthz_and_chaos_admit(gateway):
    st, body = _get(gateway.url + "/healthz")
    assert st == 200 and body["ok"] is True
    x = np.zeros((1, FEATURES), np.float32)
    chaos.configure("gateway.admit:kind=fatal,n=1")
    st, body = _post(gateway.url + "/v1/models/m0:predict",
                     {"inputs": x.tolist()})
    assert st == 500 and "chaos" in body["error"]
    # one injected fault is one failed request, not a dead gateway
    st, body = _post(gateway.url + "/v1/models/m0:predict",
                     {"inputs": x.tolist()})
    assert st == 200, body
    chaos.configure("")


def test_readyz_flips_only_after_every_eager_warmup():
    """Boot readiness: the socket answers during the eager load, but
    /readyz reads 503 until EVERY eager model finished loading (each
    load runs the server warmup before the registry marks it
    resident)."""
    gate = threading.Event()

    def slow_builder(seed):
        def build():
            gate.wait(30)
            return _mlp_engine(seed, name="slow%d" % seed)
        return build

    reg = ModelRegistry()
    reg.register("a", slow_builder(0), eager=True, num_workers=1)
    reg.register("b", slow_builder(1), eager=True, num_workers=1)
    gw = Gateway(reg, port=0, concurrency=2)
    boot = threading.Thread(target=gw.start, daemon=True)
    boot.start()
    try:
        for _ in range(100):
            if gw._started:
                break
            time.sleep(0.05)
        st, body = _get(gw.url + "/readyz")
        assert st == 503 and body["ready"] is False
        st, _ = _get(gw.url + "/healthz")
        assert st == 200                      # alive, just not ready
        gate.set()
        boot.join(timeout=60)
        assert not boot.is_alive()
        st, body = _get(gw.url + "/readyz")
        assert st == 200 and body["ready"] is True
        assert sorted(body["resident"]) == ["a", "b"]
    finally:
        gate.set()
        gw.close(timeout=30)


def test_gateway_telemetry_report_and_perf_gate(tmp_path, monkeypatch):
    """source="gateway" records feed the report's gateway section and
    perf_gate's --max-p99-ms-class budget (exit 0 within, 1 breached)."""
    stream = str(tmp_path / "t.jsonl")
    monkeypatch.setenv("MXTPU_TELEMETRY", stream)
    reg = ModelRegistry()
    reg.register("tm", _mlp_builder(4, name="tm"), eager=True,
                 num_workers=1, max_wait_ms=1.0)
    gw = Gateway(reg, port=0, concurrency=1, queue_depth=2).start()
    x = np.zeros((1, FEATURES), np.float32)
    try:
        for cls in ("interactive", "batch", "best_effort"):
            st, _ = _post(gw.url + "/v1/models/tm:predict",
                          {"inputs": x.tolist(), "priority": cls})
            assert st == 200
        st, _ = _post(gw.url + "/v1/models/tm:predict",
                      {"inputs": x.tolist(), "deadline_ms": 0})
        assert st == 504
        # an eviction + reload lands reload records on the stream too
        reg.evict("tm", timeout=30)
        st, _ = _post(gw.url + "/v1/models/tm:predict",
                      {"inputs": x.tolist()})
        assert st == 200
    finally:
        gw.close(timeout=30)
        from mxnet_tpu.observability import telemetry
        telemetry.close_stream()
    monkeypatch.delenv("MXTPU_TELEMETRY")
    spec = importlib.util.spec_from_file_location(
        "telemetry_report_gw", os.path.join(ROOT, "tools",
                                            "telemetry_report.py"))
    rep = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(rep)
    s = rep.summarize(rep.load_records(stream))
    assert s["gateway_requests"] == 4
    assert s["gateway_sheds"] == 1
    assert s["gateway_reloads"] == 1
    assert s["gateway_interactive_p99_ms"] > 0
    assert s["gateway_shed_by_class"] == {"interactive": 1}
    assert "gateway" in rep.format_summary(s)
    gate = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "perf_gate.py"),
         stream, "--max-p99-ms-class", "interactive=60000"],
        capture_output=True, text=True, timeout=120)
    assert gate.returncode == 0, gate.stdout + gate.stderr
    gate = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "perf_gate.py"),
         stream, "--max-p99-ms-class", "interactive=0.000001",
         "--max-p99-ms-class", "batch=60000"],
        capture_output=True, text=True, timeout=120)
    assert gate.returncode == 1
    assert "gateway_interactive_p99_ms" in gate.stderr


# -- tools/kill_stale.py gateway role ------------------------------------

def _gateway_lease_record(pid, heartbeat_age=0.0, takeover_s=2.0):
    return {"pid": pid, "host": socket.gethostname(),
            "boot_id": open("/proc/sys/kernel/random/boot_id")
            .read().strip(),
            "starttime": _proc_starttime(pid), "what": "gateway",
            "created": time.time() - heartbeat_age - 1.0,
            "heartbeat": time.time() - heartbeat_age,
            "heartbeat_s": 0.5, "takeover_s": takeover_s}


def _kill_stale(*args):
    return subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "kill_stale.py")]
        + list(args), capture_output=True, text=True, timeout=120)


def test_kill_stale_recognizes_and_refuses_fresh_gateway(tmp_path):
    lease_path = str(tmp_path / "dev.lease")
    holder = subprocess.Popen([sys.executable, "-S", "-c",
                               "import time; time.sleep(600)"])
    try:
        time.sleep(0.2)
        rec = _gateway_lease_record(holder.pid, takeover_s=600.0)
        with open(lease_path, "w") as f:
            f.write(json.dumps(rec))
        r = _kill_stale("--kill", "--lease-path", lease_path)
        assert r.returncode == 2, r.stdout + r.stderr
        assert "role 'gateway'" in r.stdout
        assert "GATEWAY" in r.stdout
        assert holder.poll() is None
    finally:
        holder.kill()
        holder.wait()


def test_kill_stale_reaps_expired_gateway(tmp_path):
    lease_path = str(tmp_path / "dev.lease")
    holder = subprocess.Popen([sys.executable, "-S", "-c",
                               "import time; time.sleep(600)"])
    try:
        time.sleep(0.2)
        rec = _gateway_lease_record(holder.pid, heartbeat_age=100.0)
        with open(lease_path, "w") as f:
            f.write(json.dumps(rec))
        r = _kill_stale("--kill", "--lease-path", lease_path)
        holder.wait(timeout=10)
        assert r.returncode == 0, r.stdout + r.stderr
        assert "GATEWAY-EXPIRED" in r.stdout
        assert "-> killed" in r.stdout
        assert not os.path.exists(lease_path)
    finally:
        if holder.poll() is None:
            holder.kill()
            holder.wait()
