"""Multi-process DistKVStore test: N real processes over jax.distributed
on the CPU backend (reference: tests/nightly/dist_sync_kvstore.py run
via `tools/launch.py -n 4` — here the launcher is subprocess + a local
coordinator)."""
import os
import socket
import subprocess
import sys

import pytest

NPROC = 4


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_dist_sync_kvstore_four_processes():
    coordinator = "127.0.0.1:%d" % _free_port()
    worker = os.path.join(os.path.dirname(__file__),
                          "dist_kvstore_worker.py")
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)  # workers use their own 1-device CPU
    env["JAX_PLATFORMS"] = "cpu"
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
    procs = [subprocess.Popen(
        [sys.executable, worker, coordinator, str(NPROC), str(r)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, env=env)
        for r in range(NPROC)]
    outs = []
    try:
        for r, p in enumerate(procs):
            out, _ = p.communicate(timeout=240)
            outs.append((r, p.returncode, out.decode(errors="replace")))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for r, rc, out in outs:
        assert rc == 0, "worker %d failed (rc=%d):\n%s" % (r, rc, out[-3000:])
        assert ("WORKER_%d_OK" % r) in out
        # bucketed exchange bit-identical to per-key, compression on/off
        # (asserted inside the worker; the markers prove it ran)
        assert ("BUCKET_PARITY_OK_%d" % r) in out
        assert ("COMPRESSED_BUCKET_PARITY_OK_%d" % r) in out
        # fused one-program step: ZeRO-1-sharded == replicated ==
        # staged, one dispatch per step, state all-gather bit-exact
        # (asserted inside the worker; the marker proves it ran)
        assert ("ZERO1_PARITY_OK_%d" % r) in out
        assert ("ZERO1_TOGGLE_OK_%d" % r) in out
