"""Vision/detection operator tests: spatial transformer family, ROI
pooling family, deformable conv, proposals, SVMOutput.

Reference behaviors pinned against independent numpy oracles and
numeric-gradient checks (the reference's test_operator.py strategy for
these ops: check_numeric_gradient + hand oracles).
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, autograd
from mxnet_tpu.test_utils import check_numeric_gradient


class TestGridBilinear:
    def test_identity_affine_reproduces_input(self):
        rng = np.random.RandomState(0)
        x = rng.randn(2, 3, 5, 7).astype("f")
        theta = np.tile(np.array([1, 0, 0, 0, 1, 0], "f"), (2, 1))
        g = nd.GridGenerator(nd.array(theta), transform_type="affine",
                             target_shape=(5, 7))
        y = nd.BilinearSampler(nd.array(x), g)
        np.testing.assert_allclose(y.asnumpy(), x, rtol=1e-4, atol=1e-4)

    def test_translation_shifts(self):
        x = np.zeros((1, 1, 5, 5), "f")
        x[0, 0, 2, 2] = 1.0
        # shift sampling grid one pixel right: x_src = x_dst + 2/(W-1)
        theta = np.array([[1, 0, 0.5, 0, 1, 0]], "f")
        y = nd.SpatialTransformer(nd.array(x), nd.array(theta),
                                  target_shape=(5, 5))
        got = y.asnumpy()[0, 0]
        assert got[2, 1] == pytest.approx(1.0, abs=1e-4), got

    def test_warp_grid(self):
        flow = np.zeros((1, 2, 4, 4), "f")
        g = nd.GridGenerator(nd.array(flow), transform_type="warp")
        # zero flow = identity grid in [-1, 1]
        gx = g.asnumpy()[0, 0]
        np.testing.assert_allclose(gx[0], np.linspace(-1, 1, 4),
                                   atol=1e-6)

    def test_bilinear_sampler_gradients(self):
        rng = np.random.RandomState(1)
        data = mx.sym.var("data")
        grid = mx.sym.var("grid")
        out = mx.sym.BilinearSampler(data, grid)
        loc = {"data": rng.randn(1, 2, 5, 5).astype("f"),
               "grid": (rng.rand(1, 2, 3, 3).astype("f") - 0.5)}
        check_numeric_gradient(out, loc, numeric_eps=1e-3, rtol=6e-2,
                               atol=6e-2)

    def test_spatial_transformer_gradient_wrt_loc(self):
        rng = np.random.RandomState(2)
        data = mx.sym.var("data")
        loc = mx.sym.var("loc")
        out = mx.sym.SpatialTransformer(data, loc, target_shape=(4, 4))
        location = {"data": rng.randn(1, 2, 6, 6).astype("f"),
                    "loc": np.array([[1, 0.1, 0, -0.1, 1, 0]], "f")}
        check_numeric_gradient(out, location, numeric_eps=1e-3,
                               rtol=6e-2, atol=6e-2)


class TestROIFamily:
    def test_roi_pooling_oracle(self):
        rng = np.random.RandomState(3)
        x = rng.randn(1, 2, 8, 8).astype("f")
        rois = np.array([[0, 0, 0, 3, 3]], "f")   # 4x4 region
        y = nd.ROIPooling(nd.array(x), nd.array(rois),
                          pooled_size=(2, 2), spatial_scale=1.0)
        got = y.asnumpy()[0]
        for c in range(2):
            region = x[0, c, :4, :4]
            expect = np.array(
                [[region[:2, :2].max(), region[:2, 2:4].max()],
                 [region[2:4, :2].max(), region[2:4, 2:4].max()]])
            np.testing.assert_allclose(got[c], expect, rtol=1e-5)

    def test_roi_pooling_batch_index(self):
        rng = np.random.RandomState(4)
        x = rng.randn(2, 1, 4, 4).astype("f")
        rois = np.array([[1, 0, 0, 3, 3]], "f")
        y = nd.ROIPooling(nd.array(x), nd.array(rois),
                          pooled_size=(1, 1), spatial_scale=1.0)
        assert y.asnumpy()[0, 0, 0, 0] == pytest.approx(x[1, 0].max(),
                                                        rel=1e-5)

    def test_psroi_pooling_channel_map(self):
        # C = output_dim * g * g; each bin must read its own channel
        p = 2
        out_dim = 1
        C = out_dim * p * p
        x = np.zeros((1, C, 4, 4), "f")
        for c in range(C):
            x[0, c] = c + 1
        rois = np.array([[0, 0, 0, 3, 3]], "f")
        y = nd.contrib.PSROIPooling(nd.array(x), nd.array(rois),
                                    spatial_scale=1.0, output_dim=out_dim,
                                    pooled_size=p, group_size=p)
        got = y.asnumpy()[0, 0]
        np.testing.assert_allclose(got, [[1, 2], [3, 4]], rtol=1e-5)

    def test_deformable_conv_zero_offset_matches_conv(self):
        rng = np.random.RandomState(5)
        x = rng.randn(2, 3, 7, 7).astype("f")
        w = rng.randn(4, 3, 3, 3).astype("f")
        off = np.zeros((2, 18, 5, 5), "f")
        y1 = nd.contrib.DeformableConvolution(
            nd.array(x), nd.array(off), nd.array(w), kernel=(3, 3),
            num_filter=4)
        y2 = nd.Convolution(nd.array(x), nd.array(w), kernel=(3, 3),
                            num_filter=4, no_bias=True)
        np.testing.assert_allclose(y1.asnumpy(), y2.asnumpy(),
                                   rtol=1e-3, atol=1e-3)

    def test_deformable_conv_gradient(self):
        rng = np.random.RandomState(6)
        d = mx.sym.var("data")
        o = mx.sym.var("offset")
        w = mx.sym.var("weight")
        out = mx.sym.contrib.DeformableConvolution(
            d, o, w, kernel=(3, 3), num_filter=2)
        loc = {"data": rng.randn(1, 2, 5, 5).astype("f"),
               "offset": 0.1 * rng.randn(1, 18, 3, 3).astype("f"),
               "weight": rng.randn(2, 2, 3, 3).astype("f")}
        check_numeric_gradient(out, loc, numeric_eps=1e-3, rtol=7e-2,
                               atol=7e-2)


class TestProposal:
    def test_proposal_shapes_and_validity(self):
        rng = np.random.RandomState(7)
        A = 9  # 3 scales x 3 ratios
        H = W = 6
        cls = rng.rand(1, 2 * A, H, W).astype("f")
        bbox = 0.1 * rng.randn(1, 4 * A, H, W).astype("f")
        im_info = np.array([[96, 96, 1.0]], "f")
        rois = nd.contrib.Proposal(
            nd.array(cls), nd.array(bbox), nd.array(im_info),
            scales=(2, 4, 8), ratios=(0.5, 1, 2), feature_stride=16,
            rpn_pre_nms_top_n=50, rpn_post_nms_top_n=10,
            threshold=0.7, rpn_min_size=4)
        r = rois.asnumpy()
        assert r.shape == (10, 5)
        assert (r[:, 0] == 0).all()
        # boxes clipped to the image
        assert (r[:, 1] >= 0).all() and (r[:, 3] <= 95).all()
        assert (r[:, 2] >= 0).all() and (r[:, 4] <= 95).all()
        # ordered, valid boxes
        assert (r[:, 3] >= r[:, 1]).all() and (r[:, 4] >= r[:, 2]).all()

    def test_multi_proposal_batches(self):
        rng = np.random.RandomState(8)
        A = 3
        cls = rng.rand(2, 2 * A, 4, 4).astype("f")
        bbox = 0.05 * rng.randn(2, 4 * A, 4, 4).astype("f")
        im_info = np.array([[64, 64, 1.0], [64, 64, 1.0]], "f")
        rois = nd.contrib.MultiProposal(
            nd.array(cls), nd.array(bbox), nd.array(im_info),
            scales=(4,), ratios=(0.5, 1, 2), feature_stride=16,
            rpn_pre_nms_top_n=20, rpn_post_nms_top_n=5,
            threshold=0.7, rpn_min_size=2)
        r = rois.asnumpy()
        assert r.shape == (10, 5)
        assert set(np.unique(r[:, 0])) <= {0.0, 1.0}


class TestSVMOutput:
    def test_forward_identity_and_hinge_grad(self):
        scores = np.array([[2.0, 1.0, -1.0], [0.0, 0.5, 0.2]], "f")
        label = np.array([0, 2], "f")
        s = nd.array(scores)
        s.attach_grad()
        with autograd.record():
            out = nd.SVMOutput(s, nd.array(label), margin=1.0,
                               regularization_coefficient=1.0,
                               use_linear=True)
        np.testing.assert_allclose(out.asnumpy(), scores)
        out.backward()
        g = s.grad.asnumpy()
        # sample 0: true class 0 (score 2); violations: class 1
        # (1 - (2-1) = 0, not > 0), class 2 (1 - (2-(-1)) < 0) -> no grad
        np.testing.assert_allclose(g[0], [0, 0, 0], atol=1e-6)
        # sample 1: true 2 (score .2); class 0: 1-(0.2-0)= .8>0;
        # class 1: 1-(0.2-0.5)=1.3>0 -> both violate
        np.testing.assert_allclose(g[1], [1, 1, -2], atol=1e-6)


class TestSyncBN:
    def test_matches_batchnorm(self):
        rng = np.random.RandomState(9)
        x = rng.randn(4, 3, 5, 5).astype("f")
        args = [nd.array(x), nd.ones((3,)), nd.zeros((3,)),
                nd.zeros((3,)), nd.ones((3,))]
        with autograd.train_mode():
            y1 = nd.BatchNorm(*args, fix_gamma=False)
            y2 = nd.contrib.SyncBatchNorm(*args, fix_gamma=False,
                                          ndev=8, key="k")
        np.testing.assert_allclose(y1.asnumpy(), y2.asnumpy(), atol=1e-5)


class TestCrop:
    def test_crop_offset_and_like(self):
        x = np.arange(2 * 1 * 6 * 6, dtype="f").reshape(2, 1, 6, 6)
        y = nd.Crop(nd.array(x), offset=(1, 2), h_w=(3, 3))
        np.testing.assert_allclose(y.asnumpy(), x[:, :, 1:4, 2:5])
        like = nd.zeros((2, 1, 4, 4))
        y2 = nd.Crop(nd.array(x), like, offset=(0, 0), num_args=2)
        assert y2.shape == (2, 1, 4, 4)


class TestDeformablePSROI:
    def test_no_trans_averages_bins(self):
        # constant-per-channel input: every bin's average = channel value
        p, out_dim = 2, 1
        C = out_dim * p * p
        x = np.zeros((1, C, 8, 8), "f")
        for c in range(C):
            x[0, c] = c + 1
        rois = np.array([[0, 0, 0, 7, 7]], "f")
        y = nd.contrib.DeformablePSROIPooling(
            nd.array(x), nd.array(rois), spatial_scale=1.0,
            output_dim=out_dim, group_size=p, pooled_size=p,
            sample_per_part=2, no_trans=True)
        got = y.asnumpy()[0, 0]
        np.testing.assert_allclose(got, [[1, 2], [3, 4]], atol=0.2)

    def test_zero_trans_matches_no_trans(self):
        rng = np.random.RandomState(0)
        p, out_dim = 2, 2
        C = out_dim * p * p
        x = rng.randn(1, C, 8, 8).astype("f")
        rois = np.array([[0, 1, 1, 6, 6]], "f")
        trans = np.zeros((1, 2, p, p), "f")
        y1 = nd.contrib.DeformablePSROIPooling(
            nd.array(x), nd.array(rois), spatial_scale=1.0,
            output_dim=out_dim, group_size=p, pooled_size=p,
            sample_per_part=2, no_trans=True)
        y2 = nd.contrib.DeformablePSROIPooling(
            nd.array(x), nd.array(rois), nd.array(trans),
            spatial_scale=1.0, output_dim=out_dim, group_size=p,
            pooled_size=p, sample_per_part=2, trans_std=0.1,
            no_trans=False)
        np.testing.assert_allclose(y1.asnumpy(), y2.asnumpy(),
                                   rtol=1e-5, atol=1e-5)


class TestSamplerPadding:
    def test_bilinear_sampler_zero_outside(self):
        x = np.ones((1, 1, 4, 4), "f")
        # grid entirely outside [-1,1] -> zeros
        grid = np.full((1, 2, 2, 2), 3.0, "f")
        y = nd.BilinearSampler(nd.array(x), nd.array(grid))
        np.testing.assert_allclose(y.asnumpy(), 0.0)

    def test_bilinear_sampler_edge_blend(self):
        x = np.ones((1, 1, 2, 2), "f")
        # exactly on the boundary samples full value
        grid = np.zeros((1, 2, 1, 1), "f")
        grid[0, 0] = 1.0   # x = right edge
        grid[0, 1] = -1.0  # y = top edge
        y = nd.BilinearSampler(nd.array(x), nd.array(grid))
        assert y.asnumpy()[0, 0, 0, 0] == pytest.approx(1.0, abs=1e-6)
