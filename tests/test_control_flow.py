"""Control-flow op tests (reference: tests covering
src/operator/control_flow.cc semantics via python contrib API)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd


def test_foreach_eager_forward():
    step = lambda data, states: (data + states[0], [states[0] * 2])
    data = mx.nd.array(np.arange(20).reshape(2, 10).astype("f"))
    states = [mx.nd.ones((10,))]
    outs, st = mx.nd.contrib.foreach(step, data, states)
    assert np.allclose(outs.asnumpy()[0], np.arange(10) + 1)
    assert np.allclose(outs.asnumpy()[1], np.arange(10, 20) + 2)
    assert np.allclose(st[0].asnumpy(), 4.0)


def test_foreach_eager_single_state_and_list_data():
    # data as list; out as list
    body = lambda d, states: ([d[0] + d[1], d[0] * 2], [states[0] + 1])
    d0 = mx.nd.array(np.ones((3, 2), "f"))
    d1 = mx.nd.array(np.full((3, 2), 2.0, "f"))
    outs, st = mx.nd.contrib.foreach(body, [d0, d1], [mx.nd.zeros((1,))])
    assert np.allclose(outs[0].asnumpy(), 3.0)
    assert np.allclose(outs[1].asnumpy(), 2.0)
    assert np.allclose(st[0].asnumpy(), 3.0)


def test_foreach_eager_grad_numeric():
    rng = np.random.RandomState(0)
    xs = rng.randn(3, 4).astype("f")
    x = mx.nd.array(xs)
    s0 = mx.nd.zeros((4,))
    x.attach_grad()
    s0.attach_grad()
    with autograd.record():
        outs, st = mx.nd.contrib.foreach(
            lambda d, states: (d * d + states[0], [states[0] + d]), x, [s0])
        loss = outs.sum() + st[0].sum()
    loss.backward()

    def f(xv, sv):
        s = sv.copy()
        total = 0.0
        for t in range(3):
            total += (xv[t] ** 2 + s).sum()
            s = s + xv[t]
        return total + s.sum()

    eps = 1e-3
    g_num = np.zeros_like(xs)
    for i in range(3):
        for j in range(4):
            xp, xm = xs.copy(), xs.copy()
            xp[i, j] += eps
            xm[i, j] -= eps
            g_num[i, j] = (f(xp, s0.asnumpy()) - f(xm, s0.asnumpy())) / (2 * eps)
    assert np.allclose(x.grad.asnumpy(), g_num, atol=1e-2)
    assert np.allclose(s0.grad.asnumpy(), 4.0)  # s0 reaches every term


def test_while_loop_eager():
    cond = lambda i, s: i <= 5
    func = lambda i, s: ([i + s], [i + 1, s + i])
    lv = (mx.nd.array([0.0]), mx.nd.array([1.0]))
    outs, states = mx.nd.contrib.while_loop(cond, func, lv,
                                            max_iterations=10)
    assert np.allclose(outs[0].asnumpy().ravel(),
                       [1, 2, 4, 7, 11, 16, 0, 0, 0, 0])
    assert np.allclose(states[0].asnumpy(), 6)
    assert np.allclose(states[1].asnumpy(), 16)


def test_while_loop_eager_never_true():
    outs, states = mx.nd.contrib.while_loop(
        lambda i: i < 0, lambda i: ([i], [i + 1]),
        [mx.nd.array([3.0])], max_iterations=4)
    assert outs == []
    assert np.allclose(states[0].asnumpy(), 3.0)


def test_while_loop_requires_max_iterations():
    with pytest.raises(Exception):
        mx.nd.contrib.while_loop(lambda i: i < 5, lambda i: ([i], [i + 1]),
                                 [mx.nd.array([0.0])])


def test_cond_eager():
    a, b = mx.nd.array([1.0]), mx.nd.array([2.0])
    pred = a * b < 5
    out = mx.nd.contrib.cond(pred, lambda: (a + 5) * (b + 5),
                             lambda: (a - 5) * (b - 5))
    assert out.asnumpy()[0] == 42.0
    pred2 = a * b > 5
    out2 = mx.nd.contrib.cond(pred2, lambda: (a + 5) * (b + 5),
                              lambda: (a - 5) * (b - 5))
    assert out2.asnumpy()[0] == 12.0


def test_foreach_symbol_forward_and_grad():
    data = mx.sym.var("data")
    s0 = mx.sym.var("s0")
    w = mx.sym.var("w")
    outs, states = mx.sym.contrib.foreach(
        lambda d, st: (d * w + st[0], [st[0] + d]), data, [s0])
    g = mx.sym.Group([outs, states[0]])

    xs = np.arange(6).reshape(3, 2).astype("f")
    wv = np.array([2.0, 3.0], "f")
    ex = g.bind(mx.cpu(), {"data": mx.nd.array(xs),
                           "s0": mx.nd.zeros((2,)),
                           "w": mx.nd.array(wv)},
                args_grad={"w": mx.nd.zeros((2,))})
    o = ex.forward(is_train=True)
    s = np.zeros(2)
    refs = []
    for t in range(3):
        refs.append(xs[t] * wv + s)
        s = s + xs[t]
    assert np.allclose(o[0].asnumpy(), np.stack(refs))
    assert np.allclose(o[1].asnumpy(), s)

    ex.backward([mx.nd.ones((3, 2)), mx.nd.zeros((2,))])
    assert np.allclose(ex.grad_dict["w"].asnumpy(), xs.sum(0))


def test_while_loop_symbol():
    i = mx.sym.var("i")
    s = mx.sym.var("s")
    outs, states = mx.sym.contrib.while_loop(
        lambda i, s: i < 4, lambda i, s: ([s + i], [i + 1, s + i]),
        [i, s], max_iterations=8)
    g = mx.sym.Group(outs + states)
    ex = g.bind(mx.cpu(), {"i": mx.nd.zeros((1,)), "s": mx.nd.ones((1,))})
    o = ex.forward()
    assert np.allclose(o[0].asnumpy().ravel(), [1, 2, 4, 7, 0, 0, 0, 0])
    assert np.allclose(o[1].asnumpy(), 4)
    assert np.allclose(o[2].asnumpy(), 7)


def test_cond_symbol_both_branches():
    p = mx.sym.var("p")
    a = mx.sym.var("a")
    out = mx.sym.contrib.cond(p > 0, lambda: a * 2, lambda: a - 1)
    ex = out.bind(mx.cpu(), {"p": mx.nd.array([1.0]),
                             "a": mx.nd.array([5.0])})
    assert ex.forward()[0].asnumpy()[0] == 10.0
    ex2 = out.bind(mx.cpu(), {"p": mx.nd.array([-1.0]),
                              "a": mx.nd.array([5.0])})
    assert ex2.forward()[0].asnumpy()[0] == 4.0


def test_rnn_via_foreach_matches_fused_rnn():
    """VERDICT-mandated equivalence: a vanilla RNN stepped with foreach
    must match the fused RNN op (reference: rnn-inl.h semantics)."""
    from mxnet_tpu.ops.nn import rnn_param_size
    T, B, I, H = 5, 3, 4, 6
    rng = np.random.RandomState(42)
    x = rng.randn(T, B, I).astype("f") * 0.5
    h0 = rng.randn(1, B, H).astype("f") * 0.5
    wi = rng.randn(H, I).astype("f") * 0.3
    wh = rng.randn(H, H).astype("f") * 0.3
    bi = rng.randn(H).astype("f") * 0.1
    bh = rng.randn(H).astype("f") * 0.1
    packed = np.concatenate([wi.ravel(), wh.ravel(), bi, bh])
    assert packed.size == rnn_param_size(1, I, H, False, "rnn_tanh")

    fused = mx.nd.RNN(mx.nd.array(x), mx.nd.array(packed),
                      mx.nd.array(h0), state_size=H, num_layers=1,
                      mode="rnn_tanh", state_outputs=True)
    fused_out, fused_hT = fused[0], fused[1]

    wi_nd, wh_nd = mx.nd.array(wi), mx.nd.array(wh)
    bi_nd, bh_nd = mx.nd.array(bi), mx.nd.array(bh)

    def body(xt, states):
        h = states[0]
        pre = (mx.nd.dot(xt, wi_nd, transpose_b=True) + bi_nd
               + mx.nd.dot(h, wh_nd, transpose_b=True) + bh_nd)
        h_new = mx.nd.tanh(pre)
        return h_new, [h_new]

    outs, st = mx.nd.contrib.foreach(body, mx.nd.array(x),
                                     [mx.nd.array(h0[0])])
    assert np.allclose(outs.asnumpy(), fused_out.asnumpy(), atol=1e-5)
    assert np.allclose(st[0].asnumpy(), fused_hT.asnumpy()[0], atol=1e-5)


def test_foreach_in_hybridized_block():
    """foreach inside a HybridBlock survives hybridize (CachedOp trace)."""
    from mxnet_tpu import gluon

    class Cum(gluon.HybridBlock):
        def hybrid_forward(self, F, x):
            outs, st = F.contrib.foreach(
                lambda d, states: (d + states[0], [states[0] + d]),
                x, [mx.nd.zeros((2,)) if F is mx.nd
                    else mx.sym.zeros((2,))])
            return outs

    net = Cum()
    net.initialize()
    x = mx.nd.array(np.arange(8).reshape(4, 2).astype("f"))
    ref = net(x).asnumpy()
    net.hybridize()
    hyb = net(x).asnumpy()
    assert np.allclose(ref, hyb)
