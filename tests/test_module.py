"""Module API + IO tests, ending in the LeNet end-to-end gate.

Mirrors the reference's tests/python/unittest/test_module.py, test_io.py
and tests/python/train/test_mlp.py / test_conv.py.
"""
import numpy as np
import pytest

import mxnet_tpu as mx


def make_blobs(n=400, nf=8, seed=7):
    """Linearly separable 2-class blobs."""
    rng = np.random.RandomState(seed)
    w = rng.randn(nf)
    x = rng.randn(n, nf).astype(np.float32)
    y = (x @ w > 0).astype(np.float32)
    return x, y


def mlp_symbol(nclass=2):
    data = mx.sym.var("data")
    h = mx.sym.FullyConnected(data=data, num_hidden=32, name="fc1")
    h = mx.sym.Activation(data=h, act_type="relu")
    h = mx.sym.FullyConnected(data=h, num_hidden=nclass, name="fc2")
    return mx.sym.SoftmaxOutput(data=h, name="softmax")


def test_ndarray_iter():
    x = np.arange(40).reshape(10, 4).astype(np.float32)
    y = np.arange(10).astype(np.float32)
    it = mx.io.NDArrayIter(x, y, batch_size=4, last_batch_handle="pad")
    batches = list(it)
    assert len(batches) == 3
    assert batches[0].data[0].shape == (4, 4)
    assert batches[-1].pad == 2
    it.reset()
    assert len(list(it)) == 3
    # discard mode drops the ragged tail
    it2 = mx.io.NDArrayIter(x, y, batch_size=4, last_batch_handle="discard")
    assert len(list(it2)) == 2


def test_ndarray_iter_dict_and_shuffle():
    x = {"a": np.zeros((10, 2), np.float32),
         "b": np.ones((10, 3), np.float32)}
    it = mx.io.NDArrayIter(x, None, batch_size=5, shuffle=True)
    names = [d.name for d in it.provide_data]
    assert sorted(names) == ["a", "b"]
    b = next(it)
    assert b.data[0].shape == (5, 2)
    assert b.data[1].shape == (5, 3)


def test_resize_iter():
    x = np.zeros((10, 2), np.float32)
    it = mx.io.ResizeIter(mx.io.NDArrayIter(x, batch_size=5), size=7)
    assert len(list(it)) == 7


def test_prefetching_iter():
    x = np.arange(20).reshape(10, 2).astype(np.float32)
    base = mx.io.NDArrayIter(x, batch_size=5)
    it = mx.io.PrefetchingIter(base)
    batches = list(it)
    assert len(batches) == 2
    it.reset()
    assert len(list(it)) == 2


def test_module_bind_init_forward():
    sym = mlp_symbol()
    mod = mx.Module(sym, context=mx.cpu())
    mod.bind(data_shapes=[("data", (8, 8))],
             label_shapes=[("softmax_label", (8,))])
    mod.init_params(initializer=mx.initializer.Xavier())
    batch = mx.io.DataBatch(data=[mx.nd.ones((8, 8))],
                            label=[mx.nd.zeros((8,))])
    mod.forward(batch, is_train=False)
    out = mod.get_outputs()[0]
    assert out.shape == (8, 2)
    np.testing.assert_allclose(out.asnumpy().sum(axis=1), np.ones(8),
                               rtol=1e-5)


def test_module_get_set_params_roundtrip():
    sym = mlp_symbol()
    mod = mx.Module(sym, context=mx.cpu())
    mod.bind(data_shapes=[("data", (4, 8))],
             label_shapes=[("softmax_label", (4,))])
    mod.init_params(initializer=mx.initializer.Xavier())
    args, auxs = mod.get_params()
    assert set(args) == {"fc1_weight", "fc1_bias", "fc2_weight", "fc2_bias"}
    mod2 = mx.Module(sym, context=mx.cpu())
    mod2.bind(data_shapes=[("data", (4, 8))],
              label_shapes=[("softmax_label", (4,))])
    mod2.init_params(arg_params=args, aux_params=auxs)
    a2, _ = mod2.get_params()
    np.testing.assert_allclose(a2["fc1_weight"].asnumpy(),
                               args["fc1_weight"].asnumpy())


def test_module_fit_mlp_converges():
    """The reference's test_mlp.py gate: accuracy threshold after a few
    epochs."""
    x, y = make_blobs()
    train = mx.io.NDArrayIter(x, y, batch_size=40, shuffle=True)
    mod = mx.Module(mlp_symbol(), context=mx.cpu())
    mod.fit(train, num_epoch=12, optimizer="sgd",
            optimizer_params={"learning_rate": 0.5},
            initializer=mx.initializer.Xavier(),
            eval_metric="acc")
    score = mod.score(mx.io.NDArrayIter(x, y, batch_size=40), "acc")
    assert score[0][1] > 0.95, score


def test_module_fit_with_adam_and_validation():
    x, y = make_blobs(seed=3)
    train = mx.io.NDArrayIter(x[:300], y[:300], batch_size=30, shuffle=True)
    val = mx.io.NDArrayIter(x[300:], y[300:], batch_size=30)
    mod = mx.Module(mlp_symbol(), context=mx.cpu())
    mod.fit(train, eval_data=val, num_epoch=8, optimizer="adam",
            optimizer_params={"learning_rate": 0.02},
            initializer=mx.initializer.Xavier())
    score = mod.score(val, "acc")
    assert score[0][1] > 0.9, score


def test_module_predict():
    x, y = make_blobs()
    mod = mx.Module(mlp_symbol(), context=mx.cpu())
    mod.bind(data_shapes=[("data", (40, 8))],
             label_shapes=[("softmax_label", (40,))])
    mod.init_params(initializer=mx.initializer.Xavier())
    out = mod.predict(mx.io.NDArrayIter(x, y, batch_size=40))
    assert out.shape == (400, 2)


def test_module_checkpoint_roundtrip(tmp_path):
    x, y = make_blobs()
    prefix = str(tmp_path / "mlp")
    mod = mx.Module(mlp_symbol(), context=mx.cpu())
    mod.bind(data_shapes=[("data", (40, 8))],
             label_shapes=[("softmax_label", (40,))])
    mod.init_params(initializer=mx.initializer.Xavier())
    mod.save_checkpoint(prefix, 3)
    mod2 = mx.Module.load(prefix, 3)
    mod2.bind(data_shapes=[("data", (40, 8))],
              label_shapes=[("softmax_label", (40,))])
    mod2.init_params()
    a1, _ = mod.get_params()
    a2, _ = mod2.get_params()
    for k in a1:
        np.testing.assert_allclose(a1[k].asnumpy(), a2[k].asnumpy(),
                                   rtol=1e-6)


def test_lenet_conv_module():
    """LeNet on synthetic image classes — the reference's test_conv.py gate
    scaled down (BASELINE config 1: LeNet via Module API)."""
    rng = np.random.RandomState(0)
    n = 160
    y = rng.randint(0, 2, n).astype(np.float32)
    # class-dependent mean images make the task easy
    x = rng.randn(n, 1, 16, 16).astype(np.float32) * 0.3 + \
        y[:, None, None, None] * 1.0
    data = mx.sym.var("data")
    c1 = mx.sym.Convolution(data=data, kernel=(3, 3), num_filter=8,
                            name="conv1")
    a1 = mx.sym.Activation(data=c1, act_type="tanh")
    p1 = mx.sym.Pooling(data=a1, pool_type="max", kernel=(2, 2),
                        stride=(2, 2))
    f = mx.sym.Flatten(data=p1)
    fc1 = mx.sym.FullyConnected(data=f, num_hidden=32, name="fc1")
    a2 = mx.sym.Activation(data=fc1, act_type="tanh")
    fc2 = mx.sym.FullyConnected(data=a2, num_hidden=2, name="fc2")
    lenet = mx.sym.SoftmaxOutput(data=fc2, name="softmax")

    train = mx.io.NDArrayIter(x, y, batch_size=32, shuffle=True)
    mod = mx.Module(lenet, context=mx.cpu())
    mod.fit(train, num_epoch=6, optimizer="sgd",
            optimizer_params={"learning_rate": 0.1, "momentum": 0.9},
            initializer=mx.initializer.Xavier())
    score = mod.score(mx.io.NDArrayIter(x, y, batch_size=32), "acc")
    assert score[0][1] > 0.9, score


def test_module_multi_device_data_parallel():
    """DP over multiple contexts = one SPMD executor over a device mesh
    (the reference's executor_group slices the batch per GPU)."""
    import jax
    n_dev = min(4, len(jax.devices()))
    if n_dev < 2:
        pytest.skip("needs multiple devices")
    ctxs = [mx.cpu(i) for i in range(n_dev)]
    x, y = make_blobs()
    train = mx.io.NDArrayIter(x, y, batch_size=40, shuffle=True)
    mod = mx.Module(mlp_symbol(), context=ctxs)
    mod.fit(train, num_epoch=8, optimizer="sgd",
            optimizer_params={"learning_rate": 0.5},
            initializer=mx.initializer.Xavier(), kvstore="device")
    score = mod.score(mx.io.NDArrayIter(x, y, batch_size=40), "acc")
    assert score[0][1] > 0.95, score


def test_bucketing_module():
    """BucketingModule over two sequence lengths sharing params."""
    def sym_gen(seq_len):
        data = mx.sym.var("data")
        fc = mx.sym.FullyConnected(data=data, num_hidden=2, name="fc",
                                   flatten=True)
        sm = mx.sym.SoftmaxOutput(data=fc, name="softmax")
        return sm, ("data",), ("softmax_label",)

    # note: same param shapes across buckets requires flatten dims to agree;
    # use a shared fc over padded features
    x8, y8 = make_blobs(nf=8, seed=1)
    mod = mx.module.BucketingModule(sym_gen, default_bucket_key=8,
                                    context=mx.cpu())
    mod.bind(data_shapes=[("data", (20, 8))],
             label_shapes=[("softmax_label", (20,))])
    mod.init_params(initializer=mx.initializer.Xavier())
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.5})
    batch = mx.io.DataBatch(data=[mx.nd.array(x8[:20])],
                            label=[mx.nd.array(y8[:20])],
                            bucket_key=8,
                            provide_data=[("data", (20, 8))],
                            provide_label=[("softmax_label", (20,))])
    mod.forward(batch, is_train=True)
    mod.backward()
    mod.update()
    out = mod.get_outputs()[0]
    assert out.shape == (20, 2)
