"""ONNX converter tests: codec round-trip, zoo-family export→import
forward parity, and import of an externally-shaped graph.

Reference: the reference's onnx tests
(tests/python-pytest/onnx/export/mxnet_export_test.py) assert forward
parity after export→reimport over model-zoo networks; this file does
the same through the self-contained codec
(mxnet_tpu/contrib/onnx/_proto.py — no `onnx` package in this
environment, see that module's docstring).
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.contrib.onnx import _proto as P
from mxnet_tpu.contrib.onnx.mx2onnx import export_model, HANDLERS
from mxnet_tpu.contrib.onnx.onnx2mx import import_model, IMPORTERS

RNG = np.random.RandomState(11)


def _forward_sym(sym, params, data, aux=None, data_name="data"):
    args = dict(params)
    args[data_name] = nd.array(data)
    ex = sym.bind(mx.cpu(), args, aux_states=dict(aux or {}))
    return ex.forward(is_train=False)[0].asnumpy()


def _gluon_params_to_flat(net):
    """Collect gluon params under their symbol-visible names."""
    out = {}
    for name, p in net.collect_params().items():
        out[name] = p.data()
    return out


def _roundtrip_net(net, shape, tmp_path, atol):
    x = RNG.rand(*shape).astype("float32")
    net.initialize()
    ref = net(nd.array(x)).asnumpy()

    sym = net(mx.sym.var("data"))
    params = _gluon_params_to_flat(net)
    path = str(tmp_path / "model.onnx")
    export_model(sym, params, [shape], onnx_file_path=path)

    sym2, arg2, aux2 = import_model(path)
    out = _forward_sym(sym2, {k: v for k, v in arg2.items()},
                       x, aux2)
    assert out.shape == ref.shape
    assert np.allclose(out, ref, atol=atol, rtol=1e-3), (
        np.abs(out - ref).max())


def test_handler_breadth():
    """Round 3 shipped ~20 handlers; the zoo needs ~60 both ways."""
    assert len(HANDLERS) >= 60, len(HANDLERS)
    assert len(IMPORTERS) >= 55, len(IMPORTERS)


def test_roundtrip_mlp(tmp_path):
    data = mx.sym.var("data")
    h = mx.sym.FullyConnected(data, num_hidden=16, name="fc1")
    h = mx.sym.Activation(h, act_type="relu")
    h = mx.sym.FullyConnected(h, num_hidden=4, name="fc2")
    out = mx.sym.softmax(h)
    params = {
        "fc1_weight": nd.array(RNG.rand(16, 8) - 0.5),
        "fc1_bias": nd.zeros((16,)),
        "fc2_weight": nd.array(RNG.rand(4, 16) - 0.5),
        "fc2_bias": nd.zeros((4,)),
    }
    x = RNG.rand(2, 8).astype("float32")
    ref = _forward_sym(out, params, x)
    path = str(tmp_path / "mlp.onnx")
    export_model(out, params, [(2, 8)], onnx_file_path=path)
    sym2, arg2, aux2 = import_model(path)
    got = _forward_sym(sym2, arg2, x, aux2)
    assert np.allclose(got, ref, atol=1e-5)


@pytest.mark.parametrize("family,ctor,shape,atol", [
    ("resnet18_v1", "resnet18_v1", (1, 3, 64, 64), 1e-3),
    ("mobilenet", "mobilenet0_25", (1, 3, 64, 64), 1e-3),
    ("squeezenet", "squeezenet1_0", (1, 3, 64, 64), 1e-3),
    ("alexnet", "alexnet", (1, 3, 224, 224), 1e-3),
])
def test_roundtrip_zoo(family, ctor, shape, tmp_path, atol):
    from mxnet_tpu.gluon.model_zoo import vision
    net = getattr(vision, ctor)()
    _roundtrip_net(net, shape, tmp_path, atol)


def test_export_covers_extended_ops(tmp_path):
    """Ops beyond the zoo: pad/clip/slice/lrn/upsampling/deconv/
    concat/split/reduce/transpose round-trip with parity."""
    data = mx.sym.var("data")
    h = mx.sym.Pad(data, mode="constant",
                   pad_width=(0, 0, 0, 0, 1, 1, 1, 1), constant_value=0.5)
    h = mx.sym.Convolution(h, kernel=(3, 3), num_filter=4, name="c1")
    h = mx.sym.LRN(h, nsize=3)
    h = mx.sym.LeakyReLU(h, act_type="leaky", slope=0.1)
    h = mx.sym.UpSampling(h, scale=2, sample_type="nearest")
    h = mx.sym.Pooling(h, kernel=(2, 2), stride=(2, 2), pool_type="avg")
    a, b = mx.sym.SliceChannel(h, num_outputs=2, axis=1)
    h = mx.sym.Concat(a, b, dim=1)
    h = mx.sym.clip(h, a_min=-1.0, a_max=1.0)
    h = mx.sym.slice_axis(h, axis=1, begin=0, end=3)
    h = mx.sym.transpose(h, axes=(0, 2, 3, 1))
    h = mx.sym.mean(h, axis=3, keepdims=False)
    out = mx.sym.sum(h, axis=(1, 2), keepdims=False) * 0.5 + 1.0
    params = {"c1_weight": nd.array(RNG.rand(4, 3, 3, 3) - 0.5),
              "c1_bias": nd.zeros((4,))}
    x = RNG.rand(2, 3, 8, 8).astype("float32")
    ref = _forward_sym(out, params, x)
    path = str(tmp_path / "ext.onnx")
    export_model(out, params, [(2, 3, 8, 8)], onnx_file_path=path)
    sym2, arg2, aux2 = import_model(path)
    got = _forward_sym(sym2, arg2, x, aux2)
    assert np.allclose(got, ref, atol=1e-4), np.abs(got - ref).max()


def test_import_external_graph(tmp_path):
    """A graph our exporter would never produce (foreign producer
    conventions): Gemm with alpha/beta/transB=0, attribute-form Slice,
    Clip attrs, Constant node — importer must still translate it."""
    g = P.Graph("ext")
    w = RNG.rand(8, 4).astype("float32")  # (in, out): transB=0
    b = RNG.rand(4).astype("float32")
    g.initializers.append(P.Tensor("W", w))
    g.initializers.append(P.Tensor("B", b))
    g.inputs.append(P.ValueInfo("x", P.FLOAT, [2, 8]))
    g.nodes.append(P.Node("Gemm", ["x", "W", "B"], ["g1"], "gemm",
                          {"alpha": 0.5, "beta": 2.0, "transB": 0}))
    g.nodes.append(P.Node("Clip", ["g1"], ["c1"], "clip",
                          {"min": -1.0, "max": 1.0}))
    g.nodes.append(P.Node("Slice", ["c1"], ["s1"], "sl",
                          {"starts": [0], "ends": [3], "axes": [1]}))
    g.nodes.append(P.Node("Relu", ["s1"], ["y"], "act"))
    g.outputs.append(P.ValueInfo("y", P.FLOAT, None))
    path = str(tmp_path / "external.onnx")
    P.save(P.Model(g, opset=9, producer="someone-else"), path)

    sym, args, aux = import_model(path)
    x = RNG.rand(2, 8).astype("float32")
    got = _forward_sym(sym, args, x, aux, data_name="x")
    ref = np.clip(0.5 * (x @ w) + 2.0 * b, -1.0, 1.0)[:, :3]
    ref = np.maximum(ref, 0)
    assert np.allclose(got, ref, atol=1e-5), np.abs(got - ref).max()


def test_export_error_is_actionable(tmp_path):
    out = mx.sym.BilinearSampler(mx.sym.var("data"), mx.sym.var("grid"))
    with pytest.raises(mx.MXNetError, match="unsupported op"):
        export_model(out, {}, [(1, 1, 4, 4), (1, 2, 4, 4)],
                     onnx_file_path=str(tmp_path / "x.onnx"))


def test_proto_foreign_producer_quirks():
    """Wire-format corners foreign producers emit: proto3 zero-default
    scalar attrs omitted from the wire, fp16 initializers carried as
    int32_data bit patterns, unpacked repeated ints."""
    from mxnet_tpu.contrib.onnx._proto import (
        Attr, Tensor, f_bytes, f_varint, _field, _varint, FLOAT16)

    # attribute with only name+type on the wire (value 0 omitted)
    buf = f_bytes(1, "axis") + f_varint(20, 2)  # type=INT, no i field
    a = Attr.parse(bytes(buf))
    assert a.name == "axis" and a.value == 0

    buf = f_bytes(1, "mode") + f_varint(20, 3)  # type=STRING, no s
    assert Attr.parse(bytes(buf)).value == ""

    # fp16 tensor in int32_data: 15360 is the bit pattern of 1.0
    t = (f_varint(1, 2) + f_varint(2, FLOAT16)
         + f_bytes(8, "w")
         + _field(5, 0, _varint(15360)) + _field(5, 0, _varint(0)))
    arr = Tensor.parse(bytes(t)).array
    assert arr.dtype == np.float16 and arr.tolist() == [1.0, 0.0]

    # unpacked repeated int64 (one tag per element)
    n = (f_bytes(1, "x") + f_bytes(2, "y") + f_bytes(4, "Foo")
         + f_bytes(5, f_bytes(1, "ints")
                   + _field(8, 0, _varint(3)) + _field(8, 0, _varint(5))
                   + f_varint(20, 7)))
    from mxnet_tpu.contrib.onnx._proto import Node
    node = Node.parse(bytes(n))
    assert node.attrs["ints"] == [3, 5]


@pytest.mark.parametrize("mode,bidir", [
    ("lstm", False), ("lstm", True), ("gru", False),
    ("rnn_tanh", False), ("rnn_relu", True),
])
def test_roundtrip_rnn(mode, bidir, tmp_path):
    """Fused RNN export->import forward parity: gates reordered to the
    ONNX iofc/zrh conventions and back; Y layout round-trips through
    the (T,D,B,H) ONNX form."""
    from mxnet_tpu.ops.nn import rnn_param_size
    T, B, I, H = 5, 3, 4, 6
    D = 2 if bidir else 1
    n = rnn_param_size(1, I, H, bidir, mode)
    params = {"rnn_w": nd.array((RNG.rand(n) - 0.5) * 0.4)}
    data = mx.sym.var("data")
    h0 = mx.sym.var("h0")
    args = [data, mx.sym.var("rnn_w"), h0]
    shapes = [(T, B, I), (D, B, H)]
    names = ["data", "h0"]
    if mode == "lstm":
        args.append(mx.sym.var("c0"))
        shapes.append((D, B, H))
        names.append("c0")
    out = mx.sym.RNN(*args, state_size=H, num_layers=1, mode=mode,
                     bidirectional=bidir)

    feed = {nm: RNG.rand(*s).astype("float32")
            for nm, s in zip(names, shapes)}
    ex = out.bind(mx.cpu(), {**{k: nd.array(v) for k, v in feed.items()},
                             "rnn_w": params["rnn_w"]})
    ref = ex.forward(is_train=False)[0].asnumpy()
    assert ref.shape == (T, B, D * H)

    path = str(tmp_path / ("rnn_%s_%d.onnx" % (mode, D)))
    export_model(out, params, shapes, onnx_file_path=path)
    sym2, args2, aux2 = import_model(path)
    ex2 = sym2.bind(mx.cpu(), {**{k: nd.array(v)
                                  for k, v in feed.items()}, **args2},
                    aux_states=aux2)
    got = ex2.forward(is_train=False)[0].asnumpy()
    assert got.shape == ref.shape
    assert np.allclose(got, ref, atol=1e-4), np.abs(got - ref).max()


def test_import_lstm_omitted_middle_output(tmp_path):
    """Foreign LSTM declaring outputs ['Y', '', 'Y_c'] (Y_h omitted):
    Y_c must bind to the CELL state, not slide into Y_h's slot."""
    from mxnet_tpu.ops.nn import rnn_param_size
    T, B, I, H = 4, 2, 3, 5
    n = rnn_param_size(1, I, H, False, "lstm")
    flat = (RNG.rand(n).astype("float32") - 0.5) * 0.4
    # repack mx [i,f,g,o] -> onnx iofc W/R/B for the hand-built graph
    gH = 4 * H
    wi = flat[:gH * I].reshape(gH, I)
    wh = flat[gH * I:gH * I + gH * H].reshape(gH, H)
    bi = flat[gH * I + gH * H:gH * I + gH * H + gH]
    bh = flat[gH * I + gH * H + gH:]
    perm = (0, 3, 1, 2)

    def po(mat):
        blocks = [mat[g * H:(g + 1) * H] for g in range(4)]
        return np.concatenate([blocks[g] for g in perm], axis=0)

    g = P.Graph("lstm_ext")
    g.initializers.append(P.Tensor("W", po(wi)[None]))
    g.initializers.append(P.Tensor("R", po(wh)[None]))
    g.initializers.append(P.Tensor(
        "B", np.concatenate([po(bi[:, None]).ravel(),
                             po(bh[:, None]).ravel()])[None]))
    g.inputs.append(P.ValueInfo("x", P.FLOAT, [T, B, I]))
    g.inputs.append(P.ValueInfo("h0", P.FLOAT, [1, B, H]))
    g.inputs.append(P.ValueInfo("c0", P.FLOAT, [1, B, H]))
    g.nodes.append(P.Node("LSTM", ["x", "W", "R", "B", "", "h0", "c0"],
                          ["Y", "", "Yc"], "l1", {"hidden_size": H}))
    g.outputs.append(P.ValueInfo("Yc", P.FLOAT, None))
    path = str(tmp_path / "lstm_ext.onnx")
    P.save(P.Model(g), path)

    sym2, args2, aux2 = import_model(path)
    feed = {"x": RNG.rand(T, B, I).astype("float32"),
            "h0": np.zeros((1, B, H), "float32"),
            "c0": np.zeros((1, B, H), "float32")}
    ex = sym2.bind(mx.cpu(), {**{k: nd.array(v) for k, v in feed.items()},
                              **args2}, aux_states=aux2)
    got_c = ex.forward(is_train=False)[0].asnumpy()

    # oracle: run the fused RNN directly and take the cell state
    outs = mx.nd.RNN(nd.array(feed["x"]), nd.array(flat),
                     nd.array(feed["h0"]), nd.array(feed["c0"]),
                     state_size=H, num_layers=1, mode="lstm",
                     state_outputs=True)
    ref_c = outs[2].asnumpy()
    assert got_c.shape == ref_c.shape
    assert np.allclose(got_c, ref_c, atol=1e-5), np.abs(got_c - ref_c).max()


def test_softmax_activation_export_modes(tmp_path):
    """SoftmaxActivation has no axis param: channel mode -> Softmax(axis=1);
    instance mode -> Flatten+Softmax+Reshape (reference:
    nn/softmax_activation-inl.h)."""
    import mxnet_tpu as mx
    data = mx.sym.Variable("data")
    out = mx.sym.SoftmaxActivation(data, mode="channel")
    path = str(tmp_path / "sm_chan.onnx")
    export_model(out, {}, [(2, 3, 4, 4)], onnx_file_path=path)
    from mxnet_tpu.contrib.onnx import _proto as P
    m = P.load(path)
    nodes = [(n.op_type, dict(n.attrs)) for n in m.graph.nodes]
    assert nodes[-1][0] == "Softmax" and nodes[-1][1].get("axis") == 1

    out = mx.sym.SoftmaxActivation(data)  # instance mode
    path = str(tmp_path / "sm_inst.onnx")
    export_model(out, {}, [(2, 3, 4, 4)], onnx_file_path=path)
    m = P.load(path)
    types = [n.op_type for n in m.graph.nodes]
    assert types == ["Flatten", "Softmax", "Shape", "Reshape"]


def test_import_weight_from_node_output_is_actionable(tmp_path):
    """A Conv weight produced by another node must raise MXNetError, not
    KeyError (valid ONNX, unsupported here)."""
    from mxnet_tpu.contrib.onnx import _proto as P
    import mxnet_tpu as mx
    g = P.Graph("g")
    g.initializers.append(P.Tensor("w_raw", np.ones((4, 3, 3, 3), np.float32)))
    g.inputs.append(P.ValueInfo("data", P.FLOAT, [1, 3, 8, 8]))
    g.nodes.append(P.Node("Identity", ["w_raw"], ["w"], "id0"))
    g.nodes.append(P.Node("Conv", ["data", "w"], ["y"], "conv0",
                          {"kernel_shape": [3, 3]}))
    g.outputs.append(P.ValueInfo("y", P.FLOAT, None))
    path = str(tmp_path / "nodew.onnx")
    P.save(P.Model(g, opset=13), path)
    with pytest.raises(mx.MXNetError, match="initializer"):
        import_model(path)
