"""Host→device double buffering (reference: src/io/iter_prefetcher.h
PrefetcherIter semantics: a background thread keeps batches staged
ahead of the consumer; exceptions surface at the consumer)."""
import time
import threading

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.io import DataBatch, NDArrayIter
from mxnet_tpu.parallel import (DevicePrefetcher, ShardedTrainer,
                                make_mesh, stage_databatch)
from mxnet_tpu import gluon


class SlowSource:
    """Iterator that takes `delay` seconds per batch and records when
    each pull happened."""

    def __init__(self, n, delay, shape=(4, 8)):
        self.n = n
        self.delay = delay
        self.shape = shape
        self.pulled = []
        self._i = 0

    def __iter__(self):
        return self

    def __next__(self):
        if self._i >= self.n:
            raise StopIteration
        time.sleep(self.delay)
        self.pulled.append((self._i, time.monotonic()))
        self._i += 1
        x = np.full(self.shape, self._i, np.float32)
        return (x, np.zeros((self.shape[0],), np.float32))


def test_prefetcher_orders_and_completes():
    src = SlowSource(6, 0.0)
    out = list(DevicePrefetcher(src, depth=2))
    assert len(out) == 6
    assert [int(x[0][0, 0]) for x in out] == [1, 2, 3, 4, 5, 6]


def test_prefetcher_runs_ahead_of_consumer():
    """While the consumer works on batch k, the worker must already
    have pulled batch k+1 (double buffering — the whole point)."""
    src = SlowSource(8, 0.01)
    pf = DevicePrefetcher(src, depth=2)
    seen = 0
    for k, item in enumerate(pf):
        time.sleep(0.03)  # consumer 3x slower than producer
        if k < 5:
            # by now the producer filled the buffer past k+1
            assert len(src.pulled) >= min(8, k + 2), (k, len(src.pulled))
        seen += 1
    assert seen == 8


def test_prefetcher_hides_slow_iterator_wall_clock():
    """Step cadence is set by max(producer, consumer), not their sum,
    up to the buffer depth."""
    n, delay = 8, 0.03

    def consume(pf_or_src, step_time):
        t0 = time.monotonic()
        for _ in pf_or_src:
            time.sleep(step_time)
        return time.monotonic() - t0

    serial = consume(SlowSource(n, delay), delay)          # no overlap
    overlapped = consume(DevicePrefetcher(SlowSource(n, delay),
                                          depth=2), delay)
    # serial ≈ n*2*delay, overlapped ≈ n*delay (+ 1 warmup); demand a
    # conservative 25% saving so 1-core CI noise can't flake this
    assert overlapped < serial * 0.75, (overlapped, serial)


def test_prefetcher_propagates_exceptions():
    def bad():
        yield (np.zeros((2, 2), np.float32),)
        raise RuntimeError("decode exploded")

    pf = DevicePrefetcher(bad(), depth=2)
    next(pf)
    with pytest.raises(RuntimeError, match="decode exploded"):
        next(pf)


def test_prefetcher_close_stops_worker():
    src = SlowSource(1000, 0.001)
    pf = DevicePrefetcher(src, depth=2)
    next(pf)
    pf.close()
    n_at_close = len(src.pulled)
    time.sleep(0.05)
    assert len(src.pulled) <= n_at_close + 3  # worker stopped promptly
    with pytest.raises(StopIteration):
        next(pf)


def test_sharded_trainer_fit_prefetched():
    """ShardedTrainer.fit consumes a DataIter through the double
    buffer and still converges (staged inputs carry the trainer's
    input shardings)."""
    import jax
    mesh = make_mesh({"dp": len(jax.devices())})
    net = gluon.nn.Dense(1)
    net.initialize()
    net(nd.zeros((1, 4)))  # materialize deferred shapes
    rng = np.random.RandomState(0)
    X = rng.rand(64, 4).astype("float32")
    w = np.array([[1.0], [-2.0], [0.5], [3.0]], np.float32)
    y = (X @ w).ravel()
    it = NDArrayIter(X, y, batch_size=8, shuffle=False)
    st = ShardedTrainer(net, lambda o, l: gluon.loss.L2Loss()(o, l),
                        "sgd", {"learning_rate": 0.5}, mesh=mesh)
    first = None
    for epoch in range(30):
        loss = st.fit(it, num_epochs=1, prefetch_depth=2)
        if first is None:
            first = float(loss.asnumpy())
    assert float(loss.asnumpy()) < first * 0.05


def test_stage_databatch_puts_on_device():
    orig_data = nd.array(np.ones((2, 3)))
    b = DataBatch(data=[orig_data],
                  label=[np.zeros((2,), np.float32)], pad=0)
    out = stage_databatch(b)
    # a NEW batch: recycled source batches must not be mutated while
    # the consumer still trains on the previous one
    assert out is not b and b.data[0] is orig_data
    assert isinstance(out.data[0], nd.NDArray)
    assert isinstance(out.label[0], nd.NDArray)
    assert out.data[0].shape == (2, 3) and out.pad == 0


def test_module_fit_through_prefetcher():
    """Module.fit's epoch loop rides the DevicePrefetcher (staged
    DataBatches) and still trains."""
    rng = np.random.RandomState(0)
    X = rng.rand(64, 5).astype("float32")
    y = (X.sum(axis=1) > 2.5).astype("float32")
    it = NDArrayIter(X, y, batch_size=8, shuffle=False,
                     label_name="softmax_label")
    data = mx.sym.var("data")
    out = mx.sym.SoftmaxOutput(
        mx.sym.FullyConnected(data, num_hidden=2), name="softmax")
    mod = mx.mod.Module(out, context=mx.cpu())
    mod.fit(it, num_epoch=8,
            optimizer_params={"learning_rate": 0.5})
    it.reset()
    score = mod.score(it, "acc")
    assert dict(score)["accuracy"] > 0.8


def test_close_joins_worker_before_return():
    """close() must not return while the worker can still pull from the
    shared source: a lingering worker races the next epoch's reset()."""
    import threading
    from mxnet_tpu.parallel.prefetch import DevicePrefetcher

    pulled = []
    release = threading.Event()

    def slow_source():
        for i in range(100):
            pulled.append(i)
            yield i
            release.wait(0.05)

    pf = DevicePrefetcher(slow_source(), depth=1)
    assert next(pf) == 0
    pf.close()
    assert not pf._thread.is_alive()
    n = len(pulled)
    release.set()
    import time
    time.sleep(0.2)
    assert len(pulled) == n  # no pulls after close() returned
