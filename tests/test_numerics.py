"""Training numerics guard (resilience/numerics.py, ISSUE 10).

1. in-graph detection + skip: a NaN in one fused group's packed
   gradients leaves that group's weights AND optimizer state
   bit-identical to pre-step while other lanes update (per-lane
   isolation); MXTPU_NUMERICS=0 restores the poison-through behavior.
2. dynamic loss scaling: GradScaler halve-on-overflow /
   grow-after-window schedule, armed only by scale_loss.
3. divergence watchdog + rollback: spike detection vs the rolling
   median, last-trusted-step arithmetic, committed-checkpoint rollback
   + typed TrainingDiverged (exit 77).
4. SDC replay classification: a bit-identical replay is
   data/optimization, a bit-differing one is suspected hardware SDC.
5. satellites: fused clip_global_norm bit parity, chaos nan/bitflip
   corruption kinds, telemetry/perf-gate skipped-step budgets,
   chaos_run --nan-at-step, and the slow bitflip -> skip/spike ->
   rollback -> resume bit-identical oracle.
"""
import json
import os
import signal
import subprocess
import sys

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import optimizer as opt
from mxnet_tpu.resilience import chaos
from mxnet_tpu.resilience import numerics as num

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_guard_state(monkeypatch):
    num.reset_flags()
    chaos.reset()
    yield
    num.reset_flags()
    chaos.reset()


def _two_lane_updater():
    """Two params in two distinct fused lanes (lr_mult split), one
    group each."""
    o = opt.create("sgd", learning_rate=0.1, momentum=0.9)
    o.lr_mult = {0: 1.0, 1: 0.5}
    return opt.get_updater(o)


def _params(seed=0, n=2, dim=8):
    rng = np.random.RandomState(seed)
    return [mx.nd.array(rng.randn(dim).astype("float32"))
            for _ in range(n)]


# -- in-graph skip -------------------------------------------------------
def test_nan_group_skipped_bit_identical_other_lane_updates():
    ws, gs = _params(0), _params(1)
    upd = _two_lane_updater()
    upd.update_all([0, 1], gs, ws)
    num.reset_flags()
    w_before = [w.asnumpy().copy() for w in ws]
    s_before = {i: np.asarray(upd.states[i]._data).copy() for i in (0, 1)}
    gs[0]._data = gs[0]._data.at[3].set(float("nan"))
    upd.update_all([0, 1], gs, ws)
    r = num.drain_flags()
    assert r["bad"] == 1 and r["total"] == 2
    assert r["skipped_steps"] == 1 and r["bad_keys"] == [0]
    assert not r["full_skip"]
    # poisoned lane: weights AND momentum bit-identical to pre-step
    assert np.array_equal(ws[0].asnumpy(), w_before[0])
    assert np.array_equal(np.asarray(upd.states[0]._data), s_before[0])
    # clean lane still updated
    assert not np.array_equal(ws[1].asnumpy(), w_before[1])
    assert not np.array_equal(np.asarray(upd.states[1]._data),
                              s_before[1])


def test_guard_off_restores_poison_through(monkeypatch):
    monkeypatch.setenv("MXTPU_NUMERICS", "0")
    ws, gs = _params(0), _params(1)
    upd = _two_lane_updater()
    gs[0]._data = gs[0]._data.at[0].set(float("nan"))
    upd.update_all([0, 1], gs, ws)
    assert num.pending_flags() == 0     # no flags recorded when off
    assert np.isnan(ws[0].asnumpy()).any()   # today's behavior


def test_clean_path_bit_parity_with_guard_off(monkeypatch):
    """where(True, new, old) is a bitwise identity: guarded and
    unguarded updates agree bit-for-bit on finite gradients."""
    results = {}
    for flag in ("1", "0"):
        monkeypatch.setenv("MXTPU_NUMERICS", flag)
        ws, gs = _params(3), _params(4)
        upd = _two_lane_updater()
        for _ in range(3):
            upd.update_all([0, 1], gs, ws)
        results[flag] = [w.asnumpy().copy() for w in ws]
    for a, b in zip(results["1"], results["0"]):
        assert np.array_equal(a, b)
    num.reset_flags()


def test_sharded_trainer_in_graph_skip():
    import jax
    from mxnet_tpu import gluon
    from mxnet_tpu.parallel import make_mesh, ShardedTrainer
    net = gluon.nn.Dense(4)
    net.initialize()
    rng = np.random.RandomState(0)
    x = rng.randn(8, 6).astype("float32")
    net(mx.nd.array(x))
    loss = gluon.loss.L2Loss()
    st = ShardedTrainer(net, lambda o_, l: loss(o_, l), "sgd",
                        {"learning_rate": 0.05},
                        mesh=make_mesh({"dp": 1}))
    y = np.zeros((8, 4), "float32")
    st.step(mx.nd.array(x), mx.nd.array(y))
    num.reset_flags()
    before = {k: np.asarray(v) for k, v in st.params.items()}
    xb = x.copy()
    xb[0, 0] = np.nan
    st.step(mx.nd.array(xb), mx.nd.array(y))
    r = num.drain_flags()
    assert r["bad"] == 1 and r["full_skip"]
    for k, v in st.params.items():
        assert np.array_equal(np.asarray(v), before[k]), k
    # clean step afterwards updates again
    st.step(mx.nd.array(x), mx.nd.array(y))
    assert num.drain_flags()["bad"] == 0
    changed = any(not np.array_equal(np.asarray(v), before[k])
                  for k, v in st.params.items())
    assert changed


def test_gluon_trainer_step_skips_and_counts():
    from mxnet_tpu import autograd, gluon
    net = gluon.nn.Dense(3)
    net.initialize()
    x = mx.nd.array(np.random.RandomState(0).randn(4, 5)
                    .astype("float32"))
    net(x)
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.1})
    loss_fn = gluon.loss.L2Loss()
    y = mx.nd.zeros((4, 3))
    with autograd.record():
        loss = loss_fn(net(x), y)
    loss.backward()
    tr.step(4)
    before = {p.name: p.data().asnumpy().copy()
              for p in net.collect_params().values()}
    skipped0 = num.SKIPPED.total()
    for p in net.collect_params().values():
        g = p.grad()
        g._data = (g._data.at[0].set(float("nan")) if g._data.ndim == 1
                   else g._data.at[0, 0].set(float("nan")))
    tr.step(4)
    assert num.SKIPPED.total() == skipped0 + 1
    for p in net.collect_params().values():
        assert np.array_equal(p.data().asnumpy(), before[p.name])


# -- loss scaling --------------------------------------------------------
def test_grad_scaler_schedule():
    s = num.GradScaler(init_scale=1024.0, growth_interval=3,
                       min_scale=1.0, max_scale=4096.0)
    # disarmed: identity
    assert s.scale == 1.0
    assert s.update(True) == 1.0 and s.scale == 1.0
    # armed by scale_loss
    assert s.scale_loss(2.0) == 2048.0
    assert s.scale == 1024.0
    s.update(True)
    assert s.scale == 512.0           # halve on overflow
    s.update(False)
    s.update(False)
    assert s.scale == 512.0           # window not reached
    s.update(False)
    assert s.scale == 1024.0          # grew after 3 clean steps
    s.update(True)
    assert s.good_steps == 0          # overflow resets the window
    for _ in range(40):
        s.update(True)
    assert s.scale == 1.0             # clamped at min
    for _ in range(100):
        s.update(False)
    assert s.scale <= 4096.0          # clamped at max


def test_trainer_scale_loss_folds_unscale_into_rescale_grad():
    from mxnet_tpu import gluon
    net = gluon.nn.Dense(2)
    net.initialize()
    net(mx.nd.zeros((2, 3)))
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.1})
    assert tr.loss_scale == 1.0
    assert tr._rescale(2) == 0.5      # unarmed: plain 1/batch
    scaled = tr.scale_loss(mx.nd.ones((2,)))
    assert float(scaled.asnumpy()[0]) == tr.loss_scale
    assert tr.loss_scale > 1.0
    assert tr._rescale(2) == pytest.approx(0.5 / tr.loss_scale)


# -- divergence watchdog + rollback -------------------------------------
def test_watchdog_spike_and_last_good():
    wd = num.DivergenceWatchdog(patience=3, factor=10.0, window=16,
                                min_history=3)
    for step in range(8):
        assert not wd.observe(step, 1.0 + 0.01 * step)
    assert not wd.observe(8, 5.0)     # 5x: under the factor
    assert wd.bad_streak == 0
    assert not wd.observe(9, 1e5)
    assert wd.bad_streak == 1 and wd.first_bad_step == 9
    assert not wd.observe(10, float("nan"))
    assert wd.observe(11, 1e5)        # patience 3 reached
    assert wd.last_good_step() == 7   # first_bad - 2
    # a good value resets the streak
    wd2 = num.DivergenceWatchdog(patience=2, factor=10.0, window=8,
                                 min_history=2)
    wd2.observe(0, 1.0)
    wd2.observe(1, 1.0)
    wd2.observe(2, 1e5)
    assert not wd2.observe(3, 1.0)
    assert wd2.bad_streak == 0 and wd2.first_bad_step is None


def _ckpt_state(dim=4):
    class _State:
        def __init__(self):
            self._params = {"w": np.zeros(dim, "float32")}
            self._aux = {}
            self._opt_state = {}
            self._step_count = 0
    return _State()


def test_rollback_drops_suspect_steps_and_restores(tmp_path):
    from mxnet_tpu.parallel.checkpoint import TrainerCheckpoint
    st = _ckpt_state()
    ck = TrainerCheckpoint(str(tmp_path / "ck"))
    for step in range(1, 7):
        st._params["w"] = st._params["w"] + np.float32(1.0)
        st._step_count = step
        ck.save(step, st, wait=True)
    rollbacks0 = num.ROLLBACKS.total()
    guard = num.NumericsGuard(
        source="t",
        watchdog=num.DivergenceWatchdog(patience=2, min_history=2))
    guard.attach_rollback(ck, st)
    for step in range(5):
        guard.step_boundary(step=step, loss=1.0)
    guard.step_boundary(step=5, loss=1e9)
    with pytest.raises(num.TrainingDiverged) as ei:
        guard.step_boundary(step=6, loss=1e9)
    err = ei.value
    assert err.exit_code == 77 and num.EXIT_DIVERGED == 77
    assert err.first_bad_step == 5
    # first bad observation at 5 indicts checkpoint 4: trusted == 3
    assert err.restored_step == 3
    assert st._step_count == 3
    assert float(st._params["w"][0]) == 3.0
    assert sorted(ck.all_steps()) == [1, 2, 3]
    assert num.ROLLBACKS.total() == rollbacks0 + 1


def test_drop_steps_after(tmp_path):
    from mxnet_tpu.parallel.checkpoint import TrainerCheckpoint
    st = _ckpt_state()
    ck = TrainerCheckpoint(str(tmp_path / "ck"))
    for step in (1, 2, 3, 4):
        st._step_count = step
        ck.save(step, st, wait=True)
    assert ck.drop_steps_after(2) == [3, 4]
    assert sorted(ck.all_steps()) == [1, 2]
    assert ck.drop_steps_after(10) == []


# -- SDC replay classification ------------------------------------------
def _bad_flag():
    import jax.numpy as jnp
    return jnp.array(False)


def test_sdc_replay_bit_identical_is_deterministic():
    """Persistent anomalies (chaos kind=nan shape: the data itself is
    bad) replay bit-identically -> data/optimization verdict."""
    grads = [mx.nd.array(np.ones(4, "float32"))]
    guard = num.NumericsGuard(source="t")
    guard.attach_replay(lambda: grads)
    num.record_flag(_bad_flag(), keys=[0], where="update")
    rep = guard.step_boundary(step=0, grads=grads)
    assert rep["sdc"] == "deterministic"
    assert num.ANOMALIES.get(kind="deterministic") >= 1


def test_sdc_replay_bit_differing_is_suspected_sdc():
    sdc0 = num.SDC_SUSPECTED.total()
    grads = [mx.nd.array(np.ones(4, "float32"))]
    replayed = [mx.nd.array(np.ones(4, "float32") * 2)]
    guard = num.NumericsGuard(source="t")
    guard.attach_replay(lambda: replayed)
    num.record_flag(_bad_flag(), keys=[0], where="update")
    rep = guard.step_boundary(step=0, grads=grads)
    assert rep["sdc"] == "sdc"
    assert num.SDC_SUSPECTED.total() == sdc0 + 1
    # only the FIRST anomaly replays
    num.record_flag(_bad_flag(), keys=[0], where="update")
    rep2 = guard.step_boundary(step=1, grads=grads)
    assert rep2["sdc"] is None


def test_exchange_only_bad_is_anomaly_not_skip():
    """With the per-key fallback the exchange probe is the ONLY
    signal: it must count as an anomaly but never claim the step was
    skipped (the unguarded apply DID poison the weights)."""
    num.record_flag(_bad_flag(), keys=[3], where="exchange")
    r = num.drain_flags()
    assert r["anomalies"] == 1 and r["exchange_bad"] == 1
    assert r["skipped_steps"] == 0 and not r["full_skip"]


def test_exchange_plus_update_bad_is_one_anomaly():
    """Fused-on dist config: the exchange verdict is a second
    observation of the SAME NaNs, not a second anomaly."""
    num.record_flag(_bad_flag(), keys=[0], where="exchange")
    num.record_flag(_bad_flag(), keys=[0], where="update")
    r = num.drain_flags()
    assert r["bad"] == 2 and r["anomalies"] == 1
    assert r["skipped_steps"] == 1 and r["full_skip"]


def test_window_bad_is_detection_only():
    """step_many's window verdict: anomaly yes, skipped/replayable
    no — the scanned body is unguarded and the weights were
    poisoned."""
    num.record_flag(_bad_flag(), where="window")
    r = num.drain_flags()
    assert r["anomalies"] == 1
    assert r["skipped_steps"] == 0 and not r["full_skip"]


def test_unguarded_leftovers_veto_full_skip():
    num.record_flag(_bad_flag(), keys=[0], where="update")
    num.note_unguarded(1)
    r = num.drain_flags()
    assert r["skipped_steps"] == 1
    assert not r["full_skip"] and r["unguarded"] == 1


def test_diverged_without_rollback_target_is_plain_crash():
    """exit 77 is the supervisor's 'already rolled back' contract; a
    guard with no checkpoint attached must surface divergence as an
    ordinary crash (exit 1), not claim a rollback that never ran."""
    guard = num.NumericsGuard(
        source="t",
        watchdog=num.DivergenceWatchdog(patience=1, min_history=1))
    with pytest.raises(num.TrainingDiverged) as ei:
        guard.step_boundary(step=0, loss=float("nan"))
    assert ei.value.exit_code == 1
    assert ei.value.restored_step is None


def test_armed_scaler_overflow_is_calibration_not_divergence():
    """Loss-scale warm-up (an armed scaler backing off) must not feed
    the divergence watchdog — only a FLOORED scale makes skips count."""
    scaler = num.GradScaler(init_scale=8.0, min_scale=1.0,
                            growth_interval=1000)
    scaler.scale_loss(1.0)   # arm
    guard = num.NumericsGuard(
        source="t", scaler=scaler,
        watchdog=num.DivergenceWatchdog(patience=2, min_history=99))
    # three overflow steps: scale 8 -> 4 -> 2 -> 1, never diverges
    for step in range(3):
        num.record_flag(_bad_flag(), keys=[0], where="update")
        guard.step_boundary(step=step)
    assert scaler.scale == 1.0
    assert guard.watchdog.bad_streak == 0
    # floored scale: skips are real anomalies again
    num.record_flag(_bad_flag(), keys=[0], where="update")
    guard.step_boundary(step=3)
    num.record_flag(_bad_flag(), keys=[0], where="update")
    with pytest.raises(num.TrainingDiverged):
        guard.step_boundary(step=4)


def test_sdc_replay_none_return_abstains():
    grads = [mx.nd.array(np.ones(4, "float32"))]
    guard = num.NumericsGuard(source="t")
    guard.attach_replay(lambda: None)
    num.record_flag(_bad_flag(), keys=[0], where="update")
    rep = guard.step_boundary(step=0, grads=grads)
    assert rep["sdc"] is None


def test_sdc_replay_requires_full_skip():
    """A partially-applied step (one clean lane updated) makes replay
    unsound — the guard must not classify."""
    grads = [mx.nd.array(np.ones(4, "float32"))]
    guard = num.NumericsGuard(source="t")
    guard.attach_replay(lambda: grads)
    import jax.numpy as jnp
    num.record_flag(jnp.array(False), keys=[0], where="update")
    num.record_flag(jnp.array(True), keys=[1], where="update")
    rep = guard.step_boundary(step=0, grads=grads)
    assert rep["sdc"] is None


def test_chaos_nan_at_fused_update_is_skipped_and_counted():
    chaos.configure("grad.post:kind=nan,n=1", seed=5)
    ws, gs = _params(0), _params(1)
    o = opt.create("sgd", learning_rate=0.1)
    upd = opt.get_updater(o)
    w_before = [w.asnumpy().copy() for w in ws]
    upd.update_all([0, 1], gs, ws)     # one lane -> ONE group
    r = num.drain_flags()
    assert chaos.trip_count("grad.post") == 1
    assert r["bad"] == 1 and r["full_skip"]
    for w, b in zip(ws, w_before):
        assert np.array_equal(w.asnumpy(), b)
    # n=1: next step clean, updates proceed
    upd.update_all([0, 1], gs, ws)
    assert num.drain_flags()["bad"] == 0
    assert not np.array_equal(ws[0].asnumpy(), w_before[0])


# -- chaos corruption kinds ---------------------------------------------
def test_parse_spec_accepts_corrupt_kinds():
    spec = chaos.parse_spec("grad.post:kind=nan,after=3;"
                            "weight.post:kind=bitflip,n=1")
    assert spec["grad.post"]["kind"] == "nan"
    assert spec["weight.post"]["kind"] == "bitflip"
    with pytest.raises(Exception):
        chaos.parse_spec("grad.post:kind=frobnicate")


def test_corrupt_point_deterministic_and_chaos_point_free():
    import jax.numpy as jnp
    a = jnp.ones(16, "float32")
    chaos.configure("grad.post:kind=bitflip,n=1", seed=3)
    # chaos_point on a corrupt-kind site must not burn the draw
    chaos.chaos_point("grad.post")
    c1 = np.asarray(chaos.corrupt_point("grad.post", a))
    chaos.configure("grad.post:kind=bitflip,n=1", seed=3)
    c2 = np.asarray(chaos.corrupt_point("grad.post", a))
    assert np.array_equal(c1, c2)
    assert (c1 != np.asarray(a)).sum() == 1      # exactly one element
    # n=1 exhausted: identity afterwards
    chaos.configure("grad.post:kind=nan,n=1", seed=3)
    c3 = np.asarray(chaos.corrupt_point("grad.post", a))
    assert np.isnan(c3).sum() == 1
    c4 = chaos.corrupt_point("grad.post", a)
    assert np.array_equal(np.asarray(c4), np.asarray(a))
    # unarmed site: identity, no copy semantics surprises
    chaos.reset()
    assert chaos.corrupt_point("grad.post", a) is a


# -- clip_global_norm satellite -----------------------------------------
def _legacy_clip(arrays, max_norm):
    total = 0.0
    for arr in arrays:
        total = total + (arr.astype("float32") ** 2).sum()
    total = float(np.sqrt(float(total)))
    scale = max_norm / (total + 1e-8)
    out = arrays
    if scale < 1.0:
        out = [np.asarray(a * scale) for a in arrays]
    return total, out


def test_clip_global_norm_bit_parity():
    from mxnet_tpu.gluon.utils import clip_global_norm
    rng = np.random.RandomState(7)
    raw = [rng.randn(5, 7).astype("float32"),
           rng.randn(11).astype("float32"),
           rng.randn(2, 3, 4).astype("float32")]
    import jax.numpy as jnp
    expect_norm, expect = _legacy_clip([jnp.asarray(a) for a in raw],
                                       1.0)
    arrs = [mx.nd.array(a) for a in raw]
    got = clip_global_norm(arrs, 1.0)
    assert got == expect_norm
    for a, e in zip(arrs, expect):
        assert np.array_equal(a.asnumpy(), np.asarray(e))
    # no-clip case leaves arrays untouched
    arrs2 = [mx.nd.array(a) for a in raw]
    clip_global_norm(arrs2, 1e9)
    for a, r in zip(arrs2, raw):
        assert np.array_equal(a.asnumpy(), r)


def test_clip_global_norm_finite_flag_warns():
    from mxnet_tpu.gluon.utils import clip_global_norm
    arrs = [mx.nd.array(np.array([1.0, np.nan], "float32"))]
    with pytest.warns(UserWarning):
        clip_global_norm(arrs, 1.0)
    # check_isfinite=False stays silent
    import warnings
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        clip_global_norm([mx.nd.array(np.array([np.inf], "float32"))],
                         1.0, check_isfinite=False)


# -- telemetry / perf gate ----------------------------------------------
def test_step_records_carry_skip_fields(tmp_path, monkeypatch):
    from mxnet_tpu import autograd, gluon
    tel = str(tmp_path / "t.jsonl")
    monkeypatch.setenv("MXTPU_TELEMETRY", tel)
    net = gluon.nn.Dense(2)
    net.initialize()
    x = mx.nd.array(np.random.RandomState(0).randn(4, 3)
                    .astype("float32"))
    net(x)
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.1})
    loss_fn = gluon.loss.L2Loss()
    for poison in (False, False, True):
        with autograd.record():
            loss = loss_fn(net(x), mx.nd.zeros((4, 2)))
        loss.backward()
        if poison:
            for p in net.collect_params().values():
                g = p.grad()
                g._data = g._data * float("nan")
        tr.step(4)
    monkeypatch.delenv("MXTPU_TELEMETRY")
    from mxnet_tpu.observability.telemetry import close_stream
    close_stream()
    recs = [json.loads(line) for line in open(tel)]
    train = [r for r in recs if r.get("source") == "gluon.trainer"]
    assert sum(r.get("skipped_steps", 0) for r in train) == 1
    assert any(r.get("event") == "numerics_skip" for r in recs)
    # report + gate over the same stream
    sys.path.insert(0, os.path.join(ROOT, "tools"))
    try:
        from telemetry_report import load_records, summarize
        import perf_gate
        s = summarize(load_records(tel))
        assert s["skipped_steps"] == 1
        assert s["anomalies"] >= 1
        assert perf_gate.main([tel, "--max-skipped-steps", "0"]) == 1
        assert perf_gate.main([tel, "--max-skipped-steps", "1"]) == 0
    finally:
        sys.path.remove(os.path.join(ROOT, "tools"))


def test_perf_gate_clean_stream_reads_zero_skips(tmp_path):
    path = str(tmp_path / "clean.jsonl")
    with open(path, "w") as f:
        for i in range(3):
            f.write(json.dumps({"ts": i, "source": "train", "step": i,
                                "step_time": 0.01}) + "\n")
    sys.path.insert(0, os.path.join(ROOT, "tools"))
    try:
        import perf_gate
        assert perf_gate.main([path, "--max-skipped-steps", "0"]) == 0
    finally:
        sys.path.remove(os.path.join(ROOT, "tools"))


# -- chaos_run --nan-at-step --------------------------------------------
_NAN_CHILD = """
import numpy as np
import mxnet_tpu as mx
from mxnet_tpu import optimizer as opt
ws = [mx.nd.array(np.ones(8, "float32")),
      mx.nd.array(np.ones(8, "float32"))]
gs = [mx.nd.array(np.ones(8, "float32")),
      mx.nd.array(np.ones(8, "float32"))]
upd = opt.get_updater(opt.create("sgd", learning_rate=0.1))
from mxnet_tpu.resilience import numerics
guard = numerics.NumericsGuard(source="t")
for step in range(4):
    upd.update_all([0, 1], gs, ws)
    guard.step_boundary(step=step)
print("CHILD_DONE")
"""


def _run_chaos_run(extra_args, extra_env=None, script=_NAN_CHILD):
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=ROOT + os.pathsep
               + os.environ.get("PYTHONPATH", ""))
    env.update(extra_env or {})
    return subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "chaos_run.py")]
        + extra_args + ["--timeout", "240", "--",
                        sys.executable, "-c", script],
        capture_output=True, text=True, env=env, timeout=300)


def test_chaos_run_nan_at_step_detects_injection():
    r = _run_chaos_run(["--nan-at-step", "1", "--expect", "complete"])
    assert r.returncode == 0, r.stdout[-3000:] + r.stderr[-2000:]
    verdict = json.loads(r.stdout.strip().splitlines()[-1])
    assert verdict["ok"] and verdict["numerics_markers"] >= 1
    assert "grad.post" in verdict["chaos_sites"]


def test_chaos_run_nan_at_step_fails_without_detection():
    """The no-injection-detected guard: guard disabled -> no marker ->
    the run must NOT pass, whatever --expect says."""
    r = _run_chaos_run(["--nan-at-step", "1", "--expect", "either"],
                       extra_env={"MXTPU_NUMERICS": "0"})
    assert r.returncode == 2, r.stdout[-3000:] + r.stderr[-2000:]
    verdict = json.loads(r.stdout.strip().splitlines()[-1])
    assert not verdict["ok"]
    assert "unproven" in verdict.get("note", "")


# -- the slow end-to-end oracle -----------------------------------------
STEPS = 10
KILL_STEP = 7        # chaos draw 7 corrupts step 7's packed grads


def _worker_cmd(ckpt_dir, out):
    return [sys.executable,
            os.path.join(ROOT, "tests", "numerics_worker.py"),
            "--steps", str(STEPS), "--ckpt-dir", str(ckpt_dir),
            "--out", str(out)]


def _env(extra=None):
    env = dict(os.environ)
    env.pop("MXTPU_CHAOS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = ROOT + os.pathsep + env.get("PYTHONPATH", "")
    # one bad step is enough evidence in this deterministic worker
    env["MXTPU_DIVERGE_PATIENCE"] = "1"
    env.update(extra or {})
    return env


def _read_events(out):
    with open("%s.r0.jsonl" % out) as f:
        return [json.loads(line) for line in f if line.strip()]


@pytest.mark.slow
def test_bitflip_triggers_rollback_resume_bit_identical(tmp_path):
    """The ISSUE-10 acceptance oracle: a chaos bitflip in step 7's
    packed gradients (seed 22 flips a top exponent bit -> the
    corrupted update explodes the float32 loss to inf) must drive
    divergence rollback (committed steps 6/7 dropped, step 5
    restored), a supervisor restart labeled as rolled-back (exit 77),
    and a resumed run whose FINAL PARAMS ARE BIT-IDENTICAL to an
    uninterrupted run's."""
    # --- uninterrupted reference ------------------------------------
    ref = subprocess.run(
        _worker_cmd(tmp_path / "ck_ref", tmp_path / "ref"),
        env=_env(), capture_output=True, text=True, timeout=240)
    assert ref.returncode == 0, ref.stdout[-3000:] + ref.stderr[-2000:]
    ref_done = [e for e in _read_events(tmp_path / "ref")
                if e["event"] == "done"]
    assert len(ref_done) == 1 and ref_done[0]["step"] == STEPS

    # --- chaos run under the supervisor ------------------------------
    cmd = [sys.executable, os.path.join(ROOT, "tools", "launch.py"),
           "-n", "1", "--supervise",
           "--gang-dir", str(tmp_path / "gang"),
           "--max-restarts", "2", "--restart-backoff", "0.2",
           ] + _worker_cmd(tmp_path / "ck", tmp_path / "out")
    chaos_env = _env({
        # seed 22: the flip hits bit 30 (top exponent bit) of one
        # packed-gradient element — deterministically catastrophic
        "MXTPU_CHAOS_SEED": "22",
        "MXTPU_CHAOS_RANK_0":
            "grad.post:kind=bitflip,after=%d,n=1" % (KILL_STEP - 1),
    })
    run = subprocess.run(cmd, env=chaos_env, capture_output=True,
                         text=True, timeout=240)
    assert run.returncode == 0, run.stdout[-4000:] + run.stderr[-2000:]
    assert "MXTPU_NUMERICS rollback" in run.stdout, run.stdout[-4000:]

    report = json.loads(open(
        os.path.join(str(tmp_path / "gang"), "report.json")).read())
    assert report["restarts"] == 1, report
    inc = report["incidents"][0]
    assert inc["diverged"] is True
    assert inc["exit_code"] == 77
    assert inc["action"] == "restart (rolled back)"

    events = _read_events(tmp_path / "out")
    starts = [e for e in events if e["event"] == "start"]
    assert [e["generation"] for e in starts] == [0, 1]
    assert starts[0]["restored_step"] is None
    # first bad observation at step 7 indicts checkpoint 6: trusted 5
    assert starts[1]["restored_step"] == KILL_STEP - 2
    done = [e for e in events if e["event"] == "done"]
    assert len(done) == 1 and done[0]["step"] == STEPS
    # the acceptance oracle: bit-identical to the uninterrupted run
    assert done[0]["params_hex"] == ref_done[0]["params_hex"]
    # the suspect committed steps are gone; the resumed run re-saved
    # them from clean state
    ck_steps = sorted(int(d) for d in os.listdir(str(tmp_path / "ck"))
                      if d.isdigit())
    assert STEPS in ck_steps


@pytest.mark.slow
def test_numerics_guard_overhead_within_budget():
    """ISSUE-10 acceptance: happy-path guard overhead <= 2% step time
    on the CPU bench probe (min-of-3, dispatch-bound worst case). The
    budget gets slack for 1-core CI noise; the recorded BENCH number
    is the authoritative one."""
    sys.path.insert(0, ROOT)
    try:
        import bench
        pct = bench._numerics_overhead_pct(steps=120, warmup=30)
    finally:
        sys.path.remove(ROOT)
    assert pct <= 10.0, "numerics guard overhead %.2f%%" % pct
