"""ImageRecordIter: threaded record-file image pipeline tests.

Reference behaviors pinned: iter_image_recordio_2.cc batch semantics
(label from IRHeader, round_batch padding, reset->new epoch), NCHW/NHWC
emission, mean/std normalization, multi-threaded decode correctness
(every record decoded exactly once per epoch).
"""
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import recordio as rio


N, H, W = 23, 12, 10


@pytest.fixture(scope="module")
def rec_path(tmp_path_factory):
    """A .rec of N synthetic images whose (R,G) pixels encode their id."""
    d = tmp_path_factory.mktemp("rec")
    path = str(d / "data.rec")
    w = rio.MXRecordIO(path, "w")
    for i in range(N):
        img = np.zeros((H, W, 3), np.uint8)
        img[:, :, 0] = i * 10          # id channel
        img[:, :, 1] = 255 - i * 10
        header = rio.IRHeader(0, float(i), i, 0)
        w.write(rio.pack_img(header, img, quality=100, img_fmt=".png"))
    w.close()
    return path


def test_basic_epoch(rec_path):
    it = mx.io.ImageRecordIter(path_imgrec=rec_path, data_shape=(3, H, W),
                               batch_size=4, preprocess_threads=3,
                               round_batch=False)
    seen = []
    for batch in it:
        x = batch.data[0].asnumpy()
        y = batch.label[0].asnumpy()
        assert x.shape == (4, 3, H, W)
        for b in range(x.shape[0]):
            i = int(round(y[b]))
            # R channel encodes 10*i
            assert abs(x[b, 0].mean() - i * 10) < 1.5, (i, x[b, 0].mean())
            seen.append(i)
    # round_batch=False drops the trailing partial batch (23 -> 20)
    assert len(seen) == 20
    assert len(set(seen)) == 20  # each decoded once, no duplicates
    it.close()


def test_round_batch_pads(rec_path):
    it = mx.io.ImageRecordIter(path_imgrec=rec_path, data_shape=(3, H, W),
                               batch_size=4, preprocess_threads=2)
    batches = list(it)
    assert len(batches) == 6  # ceil(23/4)
    assert batches[-1].pad == 1
    it.close()


def test_nhwc_layout_and_normalize(rec_path):
    it = mx.io.ImageRecordIter(path_imgrec=rec_path, data_shape=(3, H, W),
                               batch_size=4, layout="NHWC",
                               mean_r=10.0, mean_g=20.0, mean_b=0.0,
                               round_batch=False, preprocess_threads=2)
    batch = next(iter(it))
    x = batch.data[0].asnumpy()
    y = batch.label[0].asnumpy()
    assert x.shape == (4, H, W, 3)
    i = int(round(y[0]))
    assert abs(x[0, :, :, 0].mean() - (i * 10 - 10.0)) < 1.5
    it.close()


def test_reset_gives_new_epoch(rec_path):
    it = mx.io.ImageRecordIter(path_imgrec=rec_path, data_shape=(3, H, W),
                               batch_size=4, round_batch=False,
                               preprocess_threads=2)
    first = [int(v) for b in it for v in b.label[0].asnumpy()]
    it.reset()
    second = [int(v) for b in it for v in b.label[0].asnumpy()]
    assert first == second and len(first) == 20
    it.close()


def test_shuffle_changes_order(rec_path):
    it = mx.io.ImageRecordIter(path_imgrec=rec_path, data_shape=(3, H, W),
                               batch_size=16, shuffle=True, seed=3,
                               round_batch=False, preprocess_threads=2)
    order = [int(v) for b in it for v in b.label[0].asnumpy()]
    assert sorted(order) != order  # shuffled within the chunk
    it.close()


def test_gluon_dataloader_over_record_dataset(rec_path):
    """Gluon route: ImageRecordDataset + DataLoader (reference:
    gluon/data/vision/datasets.py ImageRecordDataset)."""
    # needs the .idx for random access
    idx_path = os.path.splitext(rec_path)[0] + ".idx"
    if not os.path.exists(idx_path):
        reader = rio.MXRecordIO(rec_path, "r")
        with open(idx_path, "w") as f:
            i = 0
            while True:
                pos = reader.tell()
                if reader.read() is None:
                    break
                f.write("%d\t%d\n" % (i, pos))
                i += 1
        reader.close()
    from mxnet_tpu.gluon.data import DataLoader
    from mxnet_tpu.gluon.data.vision import ImageRecordDataset
    ds = ImageRecordDataset(rec_path)
    loader = DataLoader(ds, batch_size=4, last_batch="discard")
    n = 0
    for x, y in loader:
        assert x.shape == (4, H, W, 3)
        n += x.shape[0]
    assert n == 20


def test_record_iter_feeds_sharded_trainer(rec_path):
    """End-to-end: record file -> threaded iterator (NHWC) -> fused
    ShardedTrainer step on the 8-device mesh (the train_imagenet.py
    composition, minimized)."""
    from mxnet_tpu import gluon
    from mxnet_tpu.gluon import nn as gnn
    from mxnet_tpu.parallel import make_mesh, ShardedTrainer

    net = gnn.HybridSequential()
    net.add(gnn.Conv2D(8, 3, padding=1, layout="NHWC"),
            gnn.BatchNorm(axis=3), gnn.Activation("relu"),
            gnn.GlobalAvgPool2D(layout="NHWC"), gnn.Dense(23))
    net.initialize()
    net(mx.nd.zeros((1, H, W, 3)))
    loss = gluon.loss.SoftmaxCrossEntropyLoss()
    st = ShardedTrainer(net, lambda o, l: loss(o, l), "sgd",
                        {"learning_rate": 0.01},
                        mesh=make_mesh({"dp": 8}),
                        compute_dtype="bfloat16")
    it = mx.io.ImageRecordIter(path_imgrec=rec_path, data_shape=(3, H, W),
                               batch_size=8, layout="NHWC",
                               round_batch=False, preprocess_threads=2)
    n = 0
    for batch in it:
        l = st.step(batch.data[0], batch.label[0])
        n += 1
    assert n == 2  # 23 records -> 2 full batches of 8
    assert np.isfinite(float(l.asnumpy()))
    it.close()


def test_exhausted_iter_raises_stopiteration_repeatedly(rec_path):
    it = mx.io.ImageRecordIter(path_imgrec=rec_path, data_shape=(3, H, W),
                               batch_size=8, round_batch=False,
                               preprocess_threads=2)
    list(it)
    for _ in range(3):  # must not deadlock
        with pytest.raises(StopIteration):
            it.next()
    it.close()


def test_imread_copymakeborder(tmp_path):
    # reference: mx.image.imread (_cvimread) and _cvcopyMakeBorder
    # (src/io/image_io.cc)
    import mxnet_tpu as mx
    from PIL import Image
    f = str(tmp_path / "im.jpg")
    Image.fromarray(np.full((8, 10, 3), 128, np.uint8)).save(f)
    r = mx.image.imread(f)
    assert r.shape == (8, 10, 3) and r.dtype == np.uint8
    p = mx.img.copyMakeBorder(np.zeros((4, 6, 3), np.uint8),
                              1, 2, 3, 4, fill_value=7)
    assert p.shape == (7, 13, 3)
    pn = p.asnumpy()
    assert (pn[0] == 7).all() and (pn[-1] == 7).all()
    assert (pn[1:-2, 3:-4] == 0).all()


def test_shuffle_mixes_across_batches(rec_path):
    # shuffle must permute MEMBERSHIP over a multi-batch buffer, not
    # just order within one batch-size chunk (reference:
    # iter_image_recordio_2 shuffle_chunk_size)
    import mxnet_tpu as mx
    it = mx.io.ImageRecordIter(path_imgrec=rec_path, batch_size=4,
                               data_shape=(3, 8, 8), shuffle=True,
                               seed=3, preprocess_threads=2)
    first = next(iter(it))
    labels = sorted(first.label[0].asnumpy().ravel().tolist())
    # file order would give exactly labels [0,1,2,3] in the first batch
    assert labels != [0.0, 1.0, 2.0, 3.0], \
        "first batch membership identical to file order"


def test_part_index_sharding(tmp_path):
    """part_index/num_parts split the record stream disjointly and
    exhaustively across workers (reference: iter_image_recordio_2.cc
    partition knobs; ImageIter's list sharding)."""
    import numpy as np
    from mxnet_tpu import recordio
    from mxnet_tpu.io_record import ImageRecordIter

    path = str(tmp_path / "shard")
    rec = recordio.MXIndexedRecordIO(path + ".idx", path + ".rec", "w")
    rng = np.random.RandomState(0)
    n = 24
    for i in range(n):
        img = rng.randint(0, 255, (8, 8, 3), np.uint8)
        rec.write_idx(i, recordio.pack_img(
            recordio.IRHeader(0, float(i), i, 0), img, quality=95))
    rec.close()

    def labels_of(part, parts):
        it = ImageRecordIter(path + ".rec", data_shape=(3, 8, 8),
                             batch_size=4, preprocess_threads=1,
                             part_index=part, num_parts=parts,
                             round_batch=False)
        out = []
        for b in it:
            out.extend(b.label[0].asnumpy().astype(int).tolist())
        it.close()
        return out

    a = labels_of(0, 2)
    b = labels_of(1, 2)
    assert sorted(a + b) == list(range(n))   # disjoint + exhaustive
    assert set(a) & set(b) == set()
    assert all(x % 2 == 0 for x in a) and all(x % 2 == 1 for x in b)

    # the image-list iterator shards its sequence the same way
    import mxnet_tpu as mx
    it0 = mx.image.ImageIter(batch_size=2, data_shape=(3, 8, 8),
                             path_imgrec=path + ".rec",
                             part_index=0, num_parts=3)
    assert len(it0.seq) == 8
