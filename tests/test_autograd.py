"""Autograd tests (reference model: tests/python/unittest/test_autograd.py)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, autograd


def test_basic_backward():
    x = nd.array([1.0, 2.0, 3.0])
    x.attach_grad()
    with autograd.record():
        y = (x * x).sum()
    y.backward()
    assert np.allclose(x.grad.asnumpy(), 2 * x.asnumpy())


def test_chain():
    x = nd.array([2.0])
    x.attach_grad()
    with autograd.record():
        y = x * x
        z = y * x  # x^3
    z.backward()
    assert np.allclose(x.grad.asnumpy(), [12.0])  # 3x^2


def test_multi_input():
    a = nd.array([1.0, 2.0])
    b = nd.array([3.0, 4.0])
    a.attach_grad()
    b.attach_grad()
    with autograd.record():
        c = (a * b).sum()
    c.backward()
    assert np.allclose(a.grad.asnumpy(), b.asnumpy())
    assert np.allclose(b.grad.asnumpy(), a.asnumpy())


def test_head_grads():
    x = nd.array([1.0, 2.0])
    x.attach_grad()
    with autograd.record():
        y = x * 2
    y.backward(nd.array([10.0, 100.0]))
    assert np.allclose(x.grad.asnumpy(), [20.0, 200.0])


def test_detach_blocks_grad():
    x = nd.array([3.0])
    x.attach_grad()
    with autograd.record():
        y = x * x
        z = y.detach() * x
    z.backward()
    assert np.allclose(x.grad.asnumpy(), [9.0])  # only the direct factor


def test_blockgrad_op():
    x = nd.array([3.0])
    x.attach_grad()
    with autograd.record():
        y = nd.BlockGrad(x * x) * x
    y.backward()
    assert np.allclose(x.grad.asnumpy(), [9.0])


def test_grad_fn():
    x = nd.array([2.0])
    with autograd.record():
        y = x * x * x
    (g,) = autograd.grad(y, [x])
    assert np.allclose(g.asnumpy(), [12.0])


def test_mark_variables():
    x = nd.array([1.0, 1.0])
    g = nd.zeros((2,))
    autograd.mark_variables([x], [g])
    with autograd.record():
        y = nd.sum(x * 3)
    y.backward()
    assert np.allclose(x.grad.asnumpy(), [3.0, 3.0])


def test_recording_state():
    assert not autograd.is_recording()
    with autograd.record():
        assert autograd.is_recording()
        assert autograd.is_training()
        with autograd.pause():
            assert not autograd.is_recording()
        with autograd.predict_mode():
            assert not autograd.is_training()
    assert not autograd.is_recording()


def test_training_mode_affects_dropout():
    x = nd.ones((100, 100))
    eval_out = nd.Dropout(x, p=0.5)
    assert np.allclose(eval_out.asnumpy(), 1.0)
    with autograd.record():
        train_out = nd.Dropout(x, p=0.5)
    vals = np.unique(train_out.asnumpy())
    assert set(np.round(vals, 3)).issubset({0.0, 2.0})


def test_grad_req_add():
    x = nd.array([1.0])
    x.attach_grad(grad_req="add")
    for _ in range(3):
        with autograd.record():
            y = x * 2
        y.backward()
    assert np.allclose(x.grad.asnumpy(), [6.0])


def test_grad_req_null():
    x = nd.array([1.0])
    x.attach_grad(grad_req="null")
    with autograd.record():
        y = x * 2
    y.backward()
    assert np.allclose(x.grad.asnumpy(), [0.0])


def test_retain_graph():
    x = nd.array([2.0])
    x.attach_grad()
    with autograd.record():
        y = x * x
    y.backward(retain_graph=True)
    g1 = x.grad.asnumpy().copy()
    y.backward()
    assert np.allclose(g1, [4.0])
    with pytest.raises(mx.MXNetError):
        y.backward()  # graph freed now


def test_multi_output_partial_use():
    x = nd.array([[5.0, 1.0, 3.0]])
    x.attach_grad()
    with autograd.record():
        vals, idxs = nd.topk(x, k=2, ret_typ="both")
        loss = vals.sum()
    loss.backward()
    # gradient flows only to the top-2 entries
    assert np.allclose(x.grad.asnumpy(), [[1.0, 0.0, 1.0]])


def test_custom_function():
    class Double(autograd.Function):
        def forward(self, x):
            return x * 2

        def backward(self, dy):
            return dy * 2

    x = nd.array([3.0])
    x.attach_grad()
    f = Double()
    with autograd.record():
        y = f(x)
    y.backward()
    assert np.allclose(y.asnumpy(), [6.0])
    assert np.allclose(x.grad.asnumpy(), [2.0])


def test_numeric_vs_autograd():
    """Finite-difference check (reference: check_numeric_gradient)."""
    rng = np.random.RandomState(0)
    xv = rng.rand(4, 5).astype(np.float32)

    def f_np(x):
        return np.tanh(x @ x.T).sum()

    x = nd.array(xv)
    x.attach_grad()
    with autograd.record():
        y = nd.sum(nd.tanh(nd.dot(x, x.T)))
    y.backward()
    eps = 1e-3
    num = np.zeros_like(xv)
    for i in range(xv.shape[0]):
        for j in range(xv.shape[1]):
            xp = xv.copy(); xp[i, j] += eps
            xm = xv.copy(); xm[i, j] -= eps
            num[i, j] = (f_np(xp) - f_np(xm)) / (2 * eps)
    assert np.allclose(x.grad.asnumpy(), num, atol=1e-2, rtol=1e-2)
