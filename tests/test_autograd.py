"""Autograd tests (reference model: tests/python/unittest/test_autograd.py)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, autograd


def test_basic_backward():
    x = nd.array([1.0, 2.0, 3.0])
    x.attach_grad()
    with autograd.record():
        y = (x * x).sum()
    y.backward()
    assert np.allclose(x.grad.asnumpy(), 2 * x.asnumpy())


def test_chain():
    x = nd.array([2.0])
    x.attach_grad()
    with autograd.record():
        y = x * x
        z = y * x  # x^3
    z.backward()
    assert np.allclose(x.grad.asnumpy(), [12.0])  # 3x^2


def test_multi_input():
    a = nd.array([1.0, 2.0])
    b = nd.array([3.0, 4.0])
    a.attach_grad()
    b.attach_grad()
    with autograd.record():
        c = (a * b).sum()
    c.backward()
    assert np.allclose(a.grad.asnumpy(), b.asnumpy())
    assert np.allclose(b.grad.asnumpy(), a.asnumpy())


def test_head_grads():
    x = nd.array([1.0, 2.0])
    x.attach_grad()
    with autograd.record():
        y = x * 2
    y.backward(nd.array([10.0, 100.0]))
    assert np.allclose(x.grad.asnumpy(), [20.0, 200.0])


def test_detach_blocks_grad():
    x = nd.array([3.0])
    x.attach_grad()
    with autograd.record():
        y = x * x
        z = y.detach() * x
    z.backward()
    assert np.allclose(x.grad.asnumpy(), [9.0])  # only the direct factor


def test_blockgrad_op():
    x = nd.array([3.0])
    x.attach_grad()
    with autograd.record():
        y = nd.BlockGrad(x * x) * x
    y.backward()
    assert np.allclose(x.grad.asnumpy(), [9.0])


def test_grad_fn():
    x = nd.array([2.0])
    with autograd.record():
        y = x * x * x
    (g,) = autograd.grad(y, [x])
    assert np.allclose(g.asnumpy(), [12.0])


def test_mark_variables():
    x = nd.array([1.0, 1.0])
    g = nd.zeros((2,))
    autograd.mark_variables([x], [g])
    with autograd.record():
        y = nd.sum(x * 3)
    y.backward()
    assert np.allclose(x.grad.asnumpy(), [3.0, 3.0])


def test_recording_state():
    assert not autograd.is_recording()
    with autograd.record():
        assert autograd.is_recording()
        assert autograd.is_training()
        with autograd.pause():
            assert not autograd.is_recording()
        with autograd.predict_mode():
            assert not autograd.is_training()
    assert not autograd.is_recording()


def test_training_mode_affects_dropout():
    x = nd.ones((100, 100))
    eval_out = nd.Dropout(x, p=0.5)
    assert np.allclose(eval_out.asnumpy(), 1.0)
    with autograd.record():
        train_out = nd.Dropout(x, p=0.5)
    vals = np.unique(train_out.asnumpy())
    assert set(np.round(vals, 3)).issubset({0.0, 2.0})


def test_grad_req_add():
    x = nd.array([1.0])
    x.attach_grad(grad_req="add")
    for _ in range(3):
        with autograd.record():
            y = x * 2
        y.backward()
    assert np.allclose(x.grad.asnumpy(), [6.0])


def test_grad_req_null():
    x = nd.array([1.0])
    x.attach_grad(grad_req="null")
    with autograd.record():
        y = x * 2
    y.backward()
    assert np.allclose(x.grad.asnumpy(), [0.0])


def test_retain_graph():
    x = nd.array([2.0])
    x.attach_grad()
    with autograd.record():
        y = x * x
    y.backward(retain_graph=True)
    g1 = x.grad.asnumpy().copy()
    y.backward()
    assert np.allclose(g1, [4.0])
    with pytest.raises(mx.MXNetError):
        y.backward()  # graph freed now


def test_multi_output_partial_use():
    x = nd.array([[5.0, 1.0, 3.0]])
    x.attach_grad()
    with autograd.record():
        vals, idxs = nd.topk(x, k=2, ret_typ="both")
        loss = vals.sum()
    loss.backward()
    # gradient flows only to the top-2 entries
    assert np.allclose(x.grad.asnumpy(), [[1.0, 0.0, 1.0]])


def test_custom_function():
    class Double(autograd.Function):
        def forward(self, x):
            return x * 2

        def backward(self, dy):
            return dy * 2

    x = nd.array([3.0])
    x.attach_grad()
    f = Double()
    with autograd.record():
        y = f(x)
    y.backward()
    assert np.allclose(y.asnumpy(), [6.0])
    assert np.allclose(x.grad.asnumpy(), [2.0])


def test_numeric_vs_autograd():
    """Finite-difference check (reference: check_numeric_gradient)."""
    rng = np.random.RandomState(0)
    xv = rng.rand(4, 5).astype(np.float32)

    def f_np(x):
        return np.tanh(x @ x.T).sum()

    x = nd.array(xv)
    x.attach_grad()
    with autograd.record():
        y = nd.sum(nd.tanh(nd.dot(x, x.T)))
    y.backward()
    eps = 1e-3
    num = np.zeros_like(xv)
    for i in range(xv.shape[0]):
        for j in range(xv.shape[1]):
            xp = xv.copy(); xp[i, j] += eps
            xm = xv.copy(); xm[i, j] -= eps
            num[i, j] = (f_np(xp) - f_np(xm)) / (2 * eps)
    assert np.allclose(x.grad.asnumpy(), num, atol=1e-2, rtol=1e-2)


# ---------------------------------------------------------------------------
# higher-order autograd (reference: python/mxnet/autograd.py:270 grad() with
# create_graph=True; tests/python/unittest/test_autograd.py grad_and_loss)
# ---------------------------------------------------------------------------


def test_second_order_polynomial():
    # y = x^3  =>  dy/dx = 3x^2,  d2y/dx2 = 6x
    x = nd.array([1.0, 2.0, -3.0])
    x.attach_grad()
    with autograd.record():
        y = x * x * x
        (dx,) = autograd.grad(y, [x], create_graph=True)
        assert np.allclose(dx.asnumpy(), 3 * x.asnumpy() ** 2)
        (d2x,) = autograd.grad(dx, [x])
    assert np.allclose(d2x.asnumpy(), 6 * x.asnumpy())


def test_second_order_sin():
    xv = np.linspace(-2, 2, 9).astype(np.float32)
    x = nd.array(xv)
    x.attach_grad()
    with autograd.record():
        y = nd.sin(x)
        (dx,) = autograd.grad(y, [x], create_graph=True)
        (d2x,) = autograd.grad(dx, [x])
    assert np.allclose(dx.asnumpy(), np.cos(xv), atol=1e-5)
    assert np.allclose(d2x.asnumpy(), -np.sin(xv), atol=1e-5)


def test_third_order():
    # y = x^4 => y''' = 24x
    x = nd.array([0.5, 1.5])
    x.attach_grad()
    with autograd.record():
        y = x ** 4
        (g1,) = autograd.grad(y, [x], create_graph=True)
        (g2,) = autograd.grad(g1, [x], create_graph=True)
        (g3,) = autograd.grad(g2, [x])
    assert np.allclose(g1.asnumpy(), 4 * x.asnumpy() ** 3, atol=1e-4)
    assert np.allclose(g2.asnumpy(), 12 * x.asnumpy() ** 2, atol=1e-4)
    assert np.allclose(g3.asnumpy(), 24 * x.asnumpy(), atol=1e-4)


def test_second_order_backward_into_grad_buffers():
    # grad-of-grad via .backward() on the first-order grads
    x = nd.array([2.0])
    x.attach_grad()
    with autograd.record():
        y = nd.exp(x) * x
        (dx,) = autograd.grad(y, [x], create_graph=True)
        z = dx * dx        # z = ((x+1)e^x)^2 ; dz/dx = 2(x+1)e^x (x+2)e^x
        z.backward()
    e = np.exp(2.0)
    expect = 2 * (3 * e) * (4 * e)
    assert np.allclose(x.grad.asnumpy(), [expect], rtol=1e-5)


def test_second_order_fc_chain():
    # Hessian-vector-style check on a small dense network via finite diff
    rng = np.random.RandomState(3)
    wv = rng.randn(4, 4).astype(np.float32) * 0.3
    xv = rng.randn(2, 4).astype(np.float32)

    def loss_grad_np(w):
        # f = sum(tanh(x @ w)^2); df/dw via numeric diff of f
        eps = 1e-3
        g = np.zeros_like(w)
        def f(w):
            return float((np.tanh(xv @ w) ** 2).sum())
        for i in range(w.shape[0]):
            for j in range(w.shape[1]):
                wp = w.copy(); wp[i, j] += eps
                wm = w.copy(); wm[i, j] -= eps
                g[i, j] = (f(wp) - f(wm)) / (2 * eps)
        return g

    w = nd.array(wv)
    w.attach_grad()
    x = nd.array(xv)
    with autograd.record():
        h = nd.tanh(nd.dot(x, w))
        loss = nd.sum(h * h)
        (dw,) = autograd.grad(loss, [w], create_graph=True)
        # second-order: d(sum(dw^2))/dw, checked against finite diff of dw
        s = nd.sum(dw * dw)
        (d2,) = autograd.grad(s, [w])
    assert np.allclose(dw.asnumpy(), loss_grad_np(wv), atol=5e-2, rtol=5e-2)
    eps = 1e-2
    num = np.zeros_like(wv)
    def s_np(w):
        return float((loss_grad_np(w) ** 2).sum())
    for i in range(2):           # spot-check a few entries (numeric 2nd order)
        for j in range(2):
            wp = wv.copy(); wp[i, j] += eps
            wm = wv.copy(); wm[i, j] -= eps
            num[i, j] = (s_np(wp) - s_np(wm)) / (2 * eps)
    assert np.allclose(d2.asnumpy()[:2, :2], num[:2, :2], atol=0.1, rtol=0.1)


def test_create_graph_rejects_custom_function():
    class Double(autograd.Function):
        def forward(self, x):
            return x * 2

        def backward(self, dy):
            return dy * 2

    x = nd.array([3.0])
    x.attach_grad()
    f = Double()
    with autograd.record():
        y = f(x)
        try:
            autograd.grad(y, [x], create_graph=True)
            assert False, "expected MXNetError"
        except Exception as e:
            assert "replay" in str(e)


def test_second_order_conv():
    rng = np.random.RandomState(7)
    xv = rng.randn(1, 4, 4, 2).astype(np.float32)  # NHWC
    wv = rng.randn(2, 3, 3, 2).astype(np.float32) * 0.2  # (O, kH, kW, I)
    x = nd.array(xv)
    w = nd.array(wv)
    x.attach_grad()
    with autograd.record():
        y = nd.Convolution(x, w, nd.zeros((2,)), kernel=(3, 3), num_filter=2,
                           layout="NHWC")
        loss = nd.sum(y * y)
        (dx,) = autograd.grad(loss, [x], create_graph=True)
        s = nd.sum(dx * dx)
        (d2,) = autograd.grad(s, [x])
    # loss is quadratic in x so s = sum(dx^2) is quartic; check d2 = ds/dx
    # against central differences of s computed purely numerically
    eps = 1e-2
    def s_np(xin):
        def loss_of(xa):
            yv = nd.Convolution(nd.array(xa), w, nd.zeros((2,)), kernel=(3, 3),
                                num_filter=2, layout="NHWC")
            return float((yv.asnumpy() ** 2).sum())
        g = np.zeros_like(xin)
        it = np.nditer(xin, flags=["multi_index"])
        for _ in it:
            idx = it.multi_index
            xp = xin.copy(); xp[idx] += eps
            xm = xin.copy(); xm[idx] -= eps
            g[idx] = (loss_of(xp) - loss_of(xm)) / (2 * eps)
        return float((g ** 2).sum())
    d2n = d2.asnumpy()
    for idx in [(0, 0, 0, 0), (0, 1, 2, 1), (0, 3, 3, 0)]:
        xp = xv.copy(); xp[idx] += eps
        xm = xv.copy(); xm[idx] -= eps
        numv = (s_np(xp) - s_np(xm)) / (2 * eps)
        assert np.allclose(d2n[idx], numv, rtol=0.15, atol=0.5), (idx, d2n[idx], numv)


def test_create_graph_uses_record_time_values():
    # in-place mutation between record and grad() must not change the answer
    x = nd.array([2.0])
    x.attach_grad()
    with autograd.record():
        y = x * x
    x += 1.0  # rebinds x._data; the tape saw 2.0
    (g,) = autograd.grad(y, [x], create_graph=True)
    assert np.allclose(g.asnumpy(), [4.0])


def test_create_graph_unreachable_raises():
    x = nd.array([1.0])
    w = nd.array([1.0])
    x.attach_grad(); w.attach_grad()
    with autograd.record():
        y = x * 2
        with pytest.raises(mx.MXNetError):
            autograd.grad(y, [w], create_graph=True)


def test_create_graph_duplicate_variable():
    x = nd.array([3.0])
    x.attach_grad()
    with autograd.record():
        y = x * x
        g1, g2 = autograd.grad(y, [x, x], create_graph=True)
    assert np.allclose(g1.asnumpy(), [6.0])
    assert np.allclose(g2.asnumpy(), [6.0])


def test_create_graph_constant_function_branch_folds():
    # a custom Function on a branch constant w.r.t. the variable is folded to
    # its recorded value rather than rejected
    class Double(autograd.Function):
        def forward(self, x):
            return x * 2

        def backward(self, dy):
            return dy * 2

    k = nd.array([5.0])
    x = nd.array([2.0])
    x.attach_grad()
    with autograd.record():
        c = Double()(k)          # constant branch
        y = x * x + c
        (dx,) = autograd.grad(y, [x], create_graph=True)
        (d2x,) = autograd.grad(dx, [x])
    assert np.allclose(dx.asnumpy(), [4.0])
    assert np.allclose(d2x.asnumpy(), [2.0])


def test_create_graph_rejects_mutated_between_uses():
    x = nd.array([2.0])
    x.attach_grad()
    with autograd.record():
        a = x * x
        with autograd.pause():
            x += 1.0
        b = x * x
        y = a + b
        with pytest.raises(mx.MXNetError):
            autograd.grad(y, [x], create_graph=True)


def test_create_graph_penalty_reaches_other_leaves():
    """WGAN-GP pattern: grad w.r.t. x, penalty backprops into w too."""
    x = nd.array([2.0]); w = nd.array([3.0])
    x.attach_grad(); w.attach_grad()
    with autograd.record():
        y = x * x * w           # dy/dx = 2xw
        (dx,) = autograd.grad(y, [x], create_graph=True)
        penalty = dx * dx       # (2xw)^2 ; d/dw = 8x^2 w ; d/dx = 8xw^2
        penalty.backward()
    assert np.allclose(w.grad.asnumpy(), [8 * 4 * 3.0])
    assert np.allclose(x.grad.asnumpy(), [8 * 2 * 9.0])


def test_deep_chain_no_recursion_error():
    import sys
    x = nd.array([1.0])
    x.attach_grad()
    n = sys.getrecursionlimit() + 200
    with autograd.record():
        y = x
        for _ in range(n):
            y = y + 0.001
        y.backward()
    assert np.allclose(x.grad.asnumpy(), [1.0])
