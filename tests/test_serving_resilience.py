"""Serving resilience plane tests (ISSUE-14, docs/fault_tolerance.md
"Serving resilience").

Covers: watchdog-bounded dispatch (typed `DeviceUnreachable` trips,
bit-identical off-path), the replica health state machine (wedge →
quarantine → canary re-admission; worker death → reroute; typed
failure only when NO replica survives), the scheduler loop-crash fix
(every stranded request resolves, `drain()` returns — previously those
handles hung forever), the per-model gateway circuit breaker,
Retry-After backpressure, hedged requests, client-disconnect slot
reclamation, and the CI surface (`perf_gate --min-success-rate`,
`telemetry_report` resilience section, `chaos_run --wedge-replica`).
"""
import json
import os
import socket
import subprocess
import sys
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.observability import registry as obs
from mxnet_tpu.resilience import Deadline, chaos
from mxnet_tpu.resilience.watchdog import HealthWatchdog
from mxnet_tpu.serving import (BreakerOpen, ContinuousBatchScheduler,
                               DecodeEngine, DeviceUnreachable, Gateway,
                               InferenceEngine, ModelRegistry,
                               ModelServer, NoHealthyReplica,
                               SchedulerCrashed, ServerClosed)
from mxnet_tpu.serving import health
from mxnet_tpu.serving.batcher import InferenceRequest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FEATURES, CLASSES = 6, 3


@pytest.fixture(autouse=True)
def _clean(monkeypatch):
    chaos.configure("")
    monkeypatch.delenv("MXTPU_SERVE_DISPATCH_TIMEOUT_S", raising=False)
    monkeypatch.delenv("MXTPU_GATEWAY_HEDGE_MS", raising=False)
    yield
    chaos.reset()


def _arm(monkeypatch, timeout="0.2", trips="2", canary="0.05"):
    monkeypatch.setenv("MXTPU_SERVE_DISPATCH_TIMEOUT_S", timeout)
    monkeypatch.setenv("MXTPU_SERVE_TRIP_LIMIT", trips)
    monkeypatch.setenv("MXTPU_SERVE_CANARY_S", canary)


def _mlp_engine(seed=0, name=None, max_batch=4):
    rng = np.random.RandomState(seed)
    h = mx.sym.FullyConnected(data=mx.sym.var("data"),
                              num_hidden=CLASSES, name="fc1")
    sym = mx.sym.SoftmaxOutput(data=h, name="softmax")
    args = {"fc1_weight": mx.nd.array(
                (rng.randn(CLASSES, FEATURES) * 0.5).astype(np.float32)),
            "fc1_bias": mx.nd.array(
                rng.randn(CLASSES).astype(np.float32))}
    return InferenceEngine.from_symbol(
        sym, args, {}, {"data": (FEATURES,)}, max_batch,
        name=name or ("res%d" % seed))


def _gpt_block(seed=3, vocab=32, max_seq_len=32):
    from mxnet_tpu.gluon.model_zoo.gpt import GPTDecoder
    np.random.seed(seed)
    blk = GPTDecoder(vocab, max_seq_len=max_seq_len, num_layers=1,
                     num_heads=2, embed_dim=16)
    blk.initialize(mx.init.Xavier(magnitude=2.5))
    return blk


def _x(n=1, seed=7):
    return np.random.RandomState(seed).randn(
        n, FEATURES).astype(np.float32)


def _counter_total(name):
    m = obs.REGISTRY.get(name)
    return 0.0 if m is None else float(m.total())


def _teardown(server, timeout=30):
    """drain + wait out the canary thread: a lingering canary probe
    from THIS test could steal seeded chaos draws from the shared
    `serving.replica0.dispatch` site armed by the NEXT test."""
    chaos.reset()
    server.drain(timeout=timeout)
    th = getattr(server, "_canary_thread", None)
    if th is not None:
        th.join(timeout=15)


# -- watchdog-bounded dispatch -------------------------------------------

def test_guard_off_is_direct_call():
    # default (no env): no watchdog thread, plain call
    assert health.dispatch_timeout() == 0.0
    wd = HealthWatchdog()
    assert health.guard(wd, lambda: 41, "x") == 41


def test_guard_trip_is_typed_device_unreachable(monkeypatch):
    monkeypatch.setenv("MXTPU_SERVE_DISPATCH_TIMEOUT_S", "0.1")
    wd = HealthWatchdog()
    before = _counter_total("resilience.watchdog.trips")
    with pytest.raises(DeviceUnreachable) as err:
        health.guard(wd, lambda: time.sleep(5), "wedged thing")
    assert "wedged thing" in str(err.value)
    assert _counter_total("resilience.watchdog.trips") > before


def test_guard_errors_propagate(monkeypatch):
    monkeypatch.setenv("MXTPU_SERVE_DISPATCH_TIMEOUT_S", "5")
    wd = HealthWatchdog()
    with pytest.raises(ValueError):
        health.guard(wd, lambda: (_ for _ in ()).throw(ValueError("e")),
                     "x")


def test_chaos_hang_kind():
    spec = chaos.parse_spec("engine.dispatch:kind=hang,n=1")
    assert spec["engine.dispatch"]["kind"] == "hang"
    # a hang without secs defaults far past any deadline in the system
    chaos.configure("s.x:kind=hang,n=1")
    site = chaos._lookup("s.x")
    assert site.secs == 3600.0


def test_watchdog_off_and_armed_are_bit_identical(monkeypatch):
    server = ModelServer(_mlp_engine(1, name="parity"), num_workers=1,
                         max_wait_ms=1.0, warmup=True).start()
    try:
        x = _x()
        off = np.asarray(server.infer(x, timeout=30)[0])
        monkeypatch.setenv("MXTPU_SERVE_DISPATCH_TIMEOUT_S", "5")
        armed = np.asarray(server.infer(x, timeout=30)[0])
        assert np.array_equal(off, armed)
    finally:
        monkeypatch.delenv("MXTPU_SERVE_DISPATCH_TIMEOUT_S")
        assert server.drain(timeout=30)


# -- replica health state machine ----------------------------------------

def test_wedged_replica_quarantined_then_canary_readmitted(monkeypatch):
    """The tentpole sequence: replica 0 wedges (injected hangs), its
    batches re-dispatch to replica 1 (every request still succeeds),
    the replica quarantines at the trip limit, and once the fault
    clears the canary probe re-admits it."""
    _arm(monkeypatch)
    server = ModelServer(_mlp_engine(2, name="wedge"), num_workers=2,
                         max_wait_ms=1.0, warmup=True).start()
    try:
        # 2 trips to quarantine + 1 canary trip, then the fault clears
        chaos.configure(
            "serving.replica0.dispatch:kind=hang,secs=2,n=3")
        deadline_ok = []
        t_start = time.perf_counter()
        for i in range(6):
            t0 = time.perf_counter()
            out = server.infer(_x(seed=i), timeout=30)
            deadline_ok.append(time.perf_counter() - t0 <= 0.2 + 1.0)
            assert out[0].shape == (1, CLASSES)
        assert all(deadline_ok), "a request outlived budget + grace"
        # quarantined at the trip limit...
        t_stop = time.monotonic() + 30
        quarantined = False
        while time.monotonic() < t_stop and not quarantined:
            st = {w["index"]: w for w in server.stats()["workers"]}
            quarantined = st[0]["state"] == "quarantined"
            if not quarantined:
                server.infer(_x(), timeout=30)   # keep pressure on
        assert quarantined
        # ...then canary-re-admitted once the injected hangs exhaust
        readmitted = False
        t_stop = time.monotonic() + 30
        while time.monotonic() < t_stop and not readmitted:
            st = {w["index"]: w for w in server.stats()["workers"]}
            readmitted = st[0]["state"] == "healthy"
            time.sleep(0.02)
        assert readmitted
        assert _counter_total("serving.replica.quarantines") >= 1
        assert _counter_total("serving.replica.readmits") >= 1
        assert _counter_total("serving.replica.trips") >= 2
        assert obs.REGISTRY.get("serving.replica.state") is not None
    finally:
        _teardown(server)


def test_single_replica_wedge_fails_typed_not_hanging(monkeypatch):
    """With NO surviving replica the request fails typed
    (`NoHealthyReplica`) in bounded time — never a hang."""
    _arm(monkeypatch, timeout="0.15")
    server = ModelServer(_mlp_engine(3, name="solo"), num_workers=1,
                         max_wait_ms=1.0, warmup=True).start()
    try:
        chaos.configure(
            "serving.replica0.dispatch:kind=hang,secs=2,n=50")
        t0 = time.perf_counter()
        with pytest.raises(NoHealthyReplica) as err:
            server.infer(_x(), timeout=10)
        assert time.perf_counter() - t0 < 5.0
        assert err.value.server == "solo"
    finally:
        _teardown(server)


def test_worker_death_detected_and_rerouted():
    """ISSUE-14 satellite: a dead worker thread must stop receiving
    traffic; its in-hand batch re-dispatches and every request still
    resolves. Previously the dispatcher kept feeding the corpse and
    the queue stranded silently."""
    server = ModelServer(_mlp_engine(4, name="death"), num_workers=2,
                         max_wait_ms=1.0, warmup=True).start()
    orig = server._run_batch

    def boom(worker, batch):
        if worker.index == 0:
            raise RuntimeError("synthetic worker crash")
        return orig(worker, batch)

    server._run_batch = boom
    try:
        before = _counter_total("serving.worker.deaths")
        outs = [server.infer(_x(seed=i), timeout=30) for i in range(4)]
        assert all(o[0].shape == (1, CLASSES) for o in outs)
        st = {w["index"]: w for w in server.stats()["workers"]}
        assert st[0]["state"] == "dead" and st[0]["alive"] is False
        assert st[1]["state"] == "healthy" and st[1]["alive"] is True
        assert server.stats()["healthy_workers"] == 1
        assert _counter_total("serving.worker.deaths") == before + 1
    finally:
        assert server.drain(timeout=30)


def test_all_workers_dead_fails_typed_and_drain_returns():
    server = ModelServer(_mlp_engine(5, name="grave"), num_workers=1,
                         max_wait_ms=1.0, warmup=True).start()
    server._run_batch = lambda worker, batch: (_ for _ in ()).throw(
        RuntimeError("synthetic crash"))
    try:
        with pytest.raises(NoHealthyReplica):
            server.infer(_x(), timeout=10)
        # later requests are refused typed at dispatch, not stranded
        with pytest.raises(NoHealthyReplica):
            server.infer(_x(), timeout=10)
    finally:
        assert server.drain(timeout=10)


# -- scheduler loop crash (the drain()-hangs fix) ------------------------

def test_scheduler_crash_rejects_all_and_drain_returns():
    """The satellite bug: a crashed `_loop` left `_closed` False —
    later submits queued into a dead loop and their `result()` hung
    forever. Now: every stranded request resolves with a typed
    `SchedulerCrashed` naming the scheduler, `drain(timeout)` returns,
    and new submits are refused typed."""
    engine = DecodeEngine(_gpt_block(), max_slots=2, name="crashd")
    sched = ContinuousBatchScheduler(engine, max_new_tokens=4,
                                     name="crashd/0")
    before = _counter_total("serving.decode.loop_crash")

    def boom():
        raise RuntimeError("synthetic scheduler crash")

    sched._admit = boom
    sched.start()
    h = sched.submit([1, 2, 3])
    with pytest.raises(SchedulerCrashed) as err:
        h.result(timeout=10)
    assert "crashd/0" in str(err.value)
    assert err.value.server == "crashd/0"
    assert sched.drain(timeout=10)          # returns — used to hang
    assert not sched.alive()
    assert sched.state == "dead"
    with pytest.raises(SchedulerCrashed):
        sched.submit([1, 2, 3])
    assert _counter_total("serving.decode.loop_crash") == before + 1
    st = sched.stats()
    assert st["alive"] is False and st["crashed"] is not None


def test_decode_server_routes_around_crashed_scheduler():
    engine = DecodeEngine(_gpt_block(), max_slots=2, name="route")
    server = ModelServer(engine, num_workers=2, max_new_tokens=4)
    server.start()
    try:
        s0 = server._schedulers[0]
        s0._admit = lambda: (_ for _ in ()).throw(
            RuntimeError("synthetic"))
        # first submit lands on s0 (tie-break) and is rejected typed
        with pytest.raises(SchedulerCrashed):
            server.generate([1, 2, 3], timeout=10)
        # the dead replica stops receiving traffic; s1 serves
        toks = server.generate([1, 2, 3], timeout=30)
        assert len(toks) >= 1
        assert server.stats()["healthy_workers"] == 1
    finally:
        server.drain(timeout=30)


def test_wedged_prefill_requeues_prompt_until_recovery(monkeypatch):
    """A tripped decode PREFILL must not fail the (uncomputed) prompt:
    it requeues at the head and rides the replica once the canary
    re-admits it — only mid-decode sequences fail typed."""
    _arm(monkeypatch, timeout="0.2", trips="2", canary="0.05")
    engine = DecodeEngine(_gpt_block(), max_slots=2, name="requeue")
    sched = ContinuousBatchScheduler(engine, max_new_tokens=3,
                                     name="requeue/0").start()
    try:
        chaos.configure(
            "serving.replica0.dispatch:kind=hang,secs=2,n=3")
        h = sched.submit([1, 2, 3])
        toks = h.result(timeout=60)      # survives the whole wedge
        assert len(toks) >= 1
        assert sched.trips >= 2
        assert sched.state == "healthy"  # canary re-admitted it
    finally:
        chaos.reset()
        sched.drain(timeout=30)


def test_no_live_decode_replica_is_typed():
    engine = DecodeEngine(_gpt_block(), max_slots=2, name="alldead")
    server = ModelServer(engine, num_workers=1, max_new_tokens=4)
    server.start()
    try:
        s0 = server._schedulers[0]
        s0._admit = lambda: (_ for _ in ()).throw(
            RuntimeError("synthetic"))
        with pytest.raises(SchedulerCrashed):
            server.generate([1, 2], timeout=10)
        t_stop = time.monotonic() + 10
        while time.monotonic() < t_stop and s0.alive():
            time.sleep(0.01)        # let the crashed loop finish dying
        with pytest.raises(NoHealthyReplica):
            server.generate([1, 2], timeout=10)
    finally:
        server.drain(timeout=10)


# -- client cancel / disconnect ------------------------------------------

def test_cancel_evicts_sequence_and_frees_slot():
    engine = DecodeEngine(_gpt_block(max_seq_len=128), max_slots=2,
                          name="cancel")
    sched = ContinuousBatchScheduler(engine, max_new_tokens=100).start()
    try:
        h = sched.submit([1, 2, 3])
        while not h.generated and not h.done():
            time.sleep(0.005)
        h.cancel()
        t0 = time.monotonic()
        with pytest.raises(Exception):
            h.result(timeout=10)
        assert time.monotonic() - t0 < 5.0
        # the KV slot is freed at the step boundary, not leaked until
        # max_new_tokens
        assert len(h.generated) < 100
        t_stop = time.monotonic() + 5
        while time.monotonic() < t_stop and \
                sched.stats()["active_slots"]:
            time.sleep(0.01)
        assert sched.stats()["active_slots"] == 0
        assert sched.evicted >= 1
        # the scheduler still serves
        toks = sched.generate([4, 5], max_new_tokens=3, timeout=30)
        assert len(toks) >= 1
    finally:
        sched.drain(timeout=30)


def test_stream_disconnect_frees_slot_and_keeps_serving():
    """ISSUE-14 satellite: a broken pipe mid-:generate-stream must
    retire the sequence (KV slot freed long before max_new_tokens)
    and must not kill the handler thread."""
    reg = ModelRegistry()
    reg.register("gen", lambda: ModelServer(
        DecodeEngine(_gpt_block(max_seq_len=256), max_slots=2,
                     name="genstream"),
        num_workers=1, max_new_tokens=200), warmup=False)
    gw = Gateway(reg, port=0, concurrency=2).start()
    try:
        server = reg.get("gen")
        # throttle decode steps so the disconnect lands MID-generation
        # (the tiny model would otherwise finish all 200 tokens before
        # the broken pipe is detectable)
        chaos.configure("serving.decode:kind=sleep,secs=0.05")
        body = json.dumps({"tokens": [1, 2, 3], "stream": True,
                           "max_new_tokens": 200}).encode()
        s = socket.create_connection(("127.0.0.1", gw.port), timeout=10)
        s.sendall(b"POST /v1/models/gen:generate HTTP/1.1\r\n"
                  b"Host: x\r\nContent-Type: application/json\r\n" +
                  ("Content-Length: %d\r\n\r\n" % len(body)).encode() +
                  body)
        # read a little of the stream, wait until the sequence is
        # actually decoding, then vanish mid-generation
        s.recv(512)
        sched = server._schedulers[0]
        t_stop = time.monotonic() + 20
        while time.monotonic() < t_stop and \
                not sched.stats()["active_slots"]:
            time.sleep(0.01)
        assert sched.stats()["active_slots"] == 1
        s.close()
        t_stop = time.monotonic() + 20
        while time.monotonic() < t_stop and \
                sched.stats()["active_slots"]:
            time.sleep(0.02)
        st = sched.stats()
        assert st["active_slots"] == 0, \
            "disconnected stream leaked its KV slot"
        assert st["evicted"] >= 1, \
            "sequence ran to completion instead of being cancelled"
        chaos.reset()
        # the handler thread survived: a fresh request still serves
        import urllib.request
        req = urllib.request.Request(
            gw.url + "/v1/models/gen:generate",
            data=json.dumps({"tokens": [1, 2],
                             "max_new_tokens": 2}).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=30) as r:
            assert r.status == 200
    finally:
        gw.close(timeout=30)


# -- circuit breaker ------------------------------------------------------

def test_breaker_opens_half_opens_and_recovers(monkeypatch):
    monkeypatch.setenv("MXTPU_BREAKER_FAILS", "2")
    monkeypatch.setenv("MXTPU_BREAKER_COOLDOWN_S", "0.2")
    calls = [0]
    healthy = [False]

    def builder():
        calls[0] += 1
        if not healthy[0]:
            raise RuntimeError("builder down")
        return ModelServer(_mlp_engine(6, name="brk"), num_workers=1,
                           max_wait_ms=1.0)

    reg = ModelRegistry()
    reg.register("brk", builder, warmup=False)
    for _ in range(2):
        with pytest.raises(RuntimeError):
            reg.get("brk")
    assert calls[0] == 2
    assert reg.breaker_state("brk") == "open"
    # open: instant typed refusal, the builder is NOT hammered
    with pytest.raises(BreakerOpen) as err:
        reg.get("brk")
    assert calls[0] == 2
    assert err.value.retry_after_s is not None
    assert err.value.model == "brk"
    # half-open after the cooldown: ONE canary; its success closes
    healthy[0] = True
    time.sleep(0.25)
    server = reg.get("brk")
    assert server is not None and calls[0] == 3
    assert reg.breaker_state("brk") == "closed"
    st = reg.stats()["models"]["brk"]
    assert st["breaker"] == "closed" and st["breaker_opens"] == 1
    reg.drain_all(timeout=30)


def test_breaker_half_open_failure_reopens(monkeypatch):
    monkeypatch.setenv("MXTPU_BREAKER_FAILS", "1")
    monkeypatch.setenv("MXTPU_BREAKER_COOLDOWN_S", "0.15")
    reg = ModelRegistry()
    reg.register("flaky", lambda: (_ for _ in ()).throw(
        RuntimeError("still down")), warmup=False)
    with pytest.raises(RuntimeError):
        reg.get("flaky")
    assert reg.breaker_state("flaky") == "open"
    time.sleep(0.2)
    with pytest.raises(RuntimeError):    # the half-open canary fails
        reg.get("flaky")
    assert reg.breaker_state("flaky") == "open"
    assert _counter_total("serving.breaker.opens") >= 2


def test_breaker_open_ignores_straggler_success(monkeypatch):
    """A success landing mid-cooldown (admitted before the failures)
    must NOT close an OPEN breaker — recovery goes through the
    half-open canary, never around it."""
    monkeypatch.setenv("MXTPU_BREAKER_FAILS", "1")
    monkeypatch.setenv("MXTPU_BREAKER_COOLDOWN_S", "30")
    reg = ModelRegistry()
    reg.register("strag", lambda: (_ for _ in ()).throw(
        RuntimeError("down")), warmup=False)
    with pytest.raises(RuntimeError):
        reg.get("strag")
    assert reg.breaker_state("strag") == "open"
    reg.record_success("strag")
    assert reg.breaker_state("strag") == "open"


def test_breaker_over_http_503_with_retry_after(monkeypatch):
    import urllib.error
    import urllib.request
    monkeypatch.setenv("MXTPU_BREAKER_FAILS", "1")
    monkeypatch.setenv("MXTPU_BREAKER_COOLDOWN_S", "30")
    reg = ModelRegistry()
    reg.register("down", lambda: (_ for _ in ()).throw(
        RuntimeError("dead builder")), warmup=False)
    gw = Gateway(reg, port=0).start()
    try:
        def post():
            req = urllib.request.Request(
                gw.url + "/v1/models/down:predict",
                data=json.dumps(
                    {"inputs": [[0.0] * FEATURES]}).encode(),
                headers={"Content-Type": "application/json"})
            try:
                with urllib.request.urlopen(req, timeout=30) as r:
                    return r.status, dict(r.headers), json.loads(
                        r.read())
            except urllib.error.HTTPError as err:
                return err.code, dict(err.headers), json.loads(
                    err.read())

        status, _, _ = post()
        assert status == 500          # the builder failure itself
        status, headers, body = post()
        assert status == 503
        assert "down" in body["error"] and "breaker" in body["error"] \
            or "circuit" in body["error"]
        assert int(headers.get("Retry-After")) >= 1
    finally:
        gw.close(timeout=30)


# -- Retry-After backpressure --------------------------------------------

def test_retry_after_derivation():
    reg = ModelRegistry()
    gw = Gateway(reg, port=0, concurrency=2)
    assert gw._retry_after("interactive") == 1      # no data yet
    gw._svc_ewma["interactive"] = 0.5
    ra = gw._retry_after("interactive")
    assert 1 <= ra <= 30
    gw._svc_ewma["interactive"] = 1e9               # absurd backlog
    assert gw._retry_after("interactive") == 30     # clamped


def test_shed_response_carries_retry_after(monkeypatch):
    import urllib.error
    import urllib.request
    reg = ModelRegistry()
    reg.register("m", lambda: ModelServer(
        _mlp_engine(7, name="shedder"), num_workers=1,
        max_wait_ms=1.0), warmup=True)
    gw = Gateway(reg, port=0, concurrency=1, queue_depth=1).start()
    try:
        # deadline 0 → shed before compute with the backpressure hint
        req = urllib.request.Request(
            gw.url + "/v1/models/m:predict",
            data=json.dumps({"inputs": [[0.0] * FEATURES],
                             "deadline_ms": 0.001}).encode(),
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req, timeout=30) as r:
                status, headers = r.status, dict(r.headers)
        except urllib.error.HTTPError as err:
            status, headers = err.code, dict(err.headers)
        assert status == 504
        assert int(headers.get("Retry-After")) >= 1
    finally:
        gw.close(timeout=30)


# -- hedged requests ------------------------------------------------------

def _handle(resolve_after=None, value=None):
    req = InferenceRequest({"data": np.zeros((1, FEATURES),
                                             np.float32)}, 1)
    if resolve_after is not None:
        import threading

        def later():
            time.sleep(resolve_after)
            req.resolve(value)
        threading.Thread(target=later, daemon=True).start()
    return req


def test_hedge_fires_and_duplicate_wins(monkeypatch):
    monkeypatch.setenv("MXTPU_GATEWAY_HEDGE_MS", "30")
    gw = Gateway(ModelRegistry(), port=0)
    h1 = _handle()                                  # never resolves
    h2 = _handle(resolve_after=0.05, value=["dup"])
    monkeypatch.setattr(gw, "_submit_with_retry",
                        lambda model, submit, count=True: h2)
    before_f = _counter_total("serving.hedge.fired")
    before_w = _counter_total("serving.hedge.won")
    out = gw._hedged_result("m", None, h1, 0.03, 10.0)
    assert out == ["dup"]
    assert gw.hedges == {"fired": 1, "won": 1}
    assert _counter_total("serving.hedge.fired") == before_f + 1
    assert _counter_total("serving.hedge.won") == before_w + 1


def test_hedge_primary_wins_no_fire(monkeypatch):
    monkeypatch.setenv("MXTPU_GATEWAY_HEDGE_MS", "200")
    gw = Gateway(ModelRegistry(), port=0)
    h1 = _handle(resolve_after=0.01, value=["fast"])
    out = gw._hedged_result("m", None, h1, 0.2, 10.0)
    assert out == ["fast"]
    assert gw.hedges == {"fired": 0, "won": 0}


def test_hedge_cancels_losing_decode_handle(monkeypatch):
    """The hedge loser is discarded, not abandoned: a cancellable
    (decode) handle is cancelled so its KV slot frees at the next
    step boundary instead of generating to max_new_tokens."""
    import threading
    monkeypatch.setenv("MXTPU_GATEWAY_HEDGE_MS", "10")
    gw = Gateway(ModelRegistry(), port=0)

    class H:
        def __init__(self):
            self._event = threading.Event()
            self.was_cancelled = False

        def done(self):
            return self._event.is_set()

        def result(self, timeout=None):
            return ["winner"]

        def cancel(self):
            self.was_cancelled = True

    h1, h2 = H(), H()
    h2._event.set()                          # the duplicate wins
    monkeypatch.setattr(gw, "_submit_with_retry",
                        lambda model, submit, count=True: h2)
    out = gw._hedged_result("m", None, h1, 0.01, 5.0)
    assert out == ["winner"]
    assert h1.was_cancelled


def test_hedge_not_fired_when_budget_gone(monkeypatch):
    """A request whose deadline lands exactly at the hedge delay must
    not burn a duplicate it could never use."""
    monkeypatch.setenv("MXTPU_GATEWAY_HEDGE_MS", "50")
    gw = Gateway(ModelRegistry(), port=0)
    h1 = _handle()                                  # never resolves
    with pytest.raises(Exception):
        gw._hedged_result("m", None, h1, 0.05, 0.05)
    assert gw.hedges["fired"] == 0


def test_hedge_off_by_default():
    gw = Gateway(ModelRegistry(), port=0)
    assert gw._hedge_delay_s("interactive") is None
    assert gw._hedge_delay_s("batch") is None


def test_hedge_only_interactive(monkeypatch):
    monkeypatch.setenv("MXTPU_GATEWAY_HEDGE_MS", "10")
    gw = Gateway(ModelRegistry(), port=0)
    assert gw._hedge_delay_s("interactive") == pytest.approx(0.010)
    assert gw._hedge_delay_s("batch") is None
    assert gw._hedge_delay_s("best_effort") is None


# -- CI surface -----------------------------------------------------------

def _write_stream(tmp_path, records):
    p = tmp_path / "t.jsonl"
    p.write_text("\n".join(json.dumps(r) for r in records) + "\n")
    return str(p)


def test_telemetry_report_resilience_section(tmp_path):
    sys.path.insert(0, os.path.join(ROOT, "tools"))
    from telemetry_report import load_records, summarize
    path = _write_stream(tmp_path, [
        {"ts": 1, "source": "serving", "event": "replica_state",
         "step_time": 0.0, "server": "e", "replica": 0,
         "state": "quarantined", "reason": "watchdog"},
        {"ts": 1, "source": "serving", "event": "replica_state",
         "step_time": 0.0, "server": "e", "replica": 0,
         "state": "healthy", "reason": "canary"},
        {"ts": 1, "source": "serving", "event": "loop_crash",
         "step_time": 0.0, "scheduler": "d/0"},
        {"ts": 1, "source": "serving", "event": "worker_death",
         "step_time": 0.0, "server": "e", "replica": 1},
        {"ts": 1, "source": "serving", "event": "breaker",
         "step_time": 0.0, "model": "m", "state": "open"},
        {"ts": 1, "source": "serving", "event": "hedge",
         "step_time": 0.0, "model": "m", "won": True},
        {"ts": 1, "source": "serving", "step_time": 0.004, "step": 0,
         "batch_size": 2, "requests": 2, "fill_ratio": 0.5,
         "queue_depth": 0, "shed_total": 0, "worker": 0},
        {"ts": 1, "source": "gateway", "event": "request",
         "step_time": 0.01, "model": "m", "class": "interactive",
         "status": 200},
        {"ts": 1, "source": "gateway", "event": "error",
         "step_time": 0.01, "model": "m", "class": "interactive",
         "status": 500},
    ])
    s = summarize(load_records(path))
    assert s["serving_quarantines"] == 1
    assert s["serving_readmits"] == 1
    assert s["serving_loop_crashes"] == 1
    assert s["serving_worker_deaths"] == 1
    assert s["breaker_opens"] == 1 and s["breaker_models"] == ["m"]
    assert s["hedges_fired"] == 1 and s["hedges_won"] == 1
    assert s["gateway_success_rate"] == pytest.approx(0.5)
    # the zero-step_time events must not dilute the batch percentiles
    assert s["serving_batches"] == 1
    assert s["serving_batch_p50_s"] == pytest.approx(0.004)


def test_perf_gate_min_success_rate(tmp_path):
    path = _write_stream(tmp_path, [
        {"ts": 1, "source": "gateway", "event": "request",
         "step_time": 0.01, "model": "m", "class": "interactive",
         "status": 200},
        {"ts": 1, "source": "gateway", "event": "error",
         "step_time": 0.01, "model": "m", "class": "interactive",
         "status": 500},
        {"ts": 1, "source": "gateway", "event": "shed",
         "step_time": 0.0, "model": "m", "class": "best_effort",
         "reason": "queue_full"},
    ])
    gate = os.path.join(ROOT, "tools", "perf_gate.py")
    r = subprocess.run([sys.executable, gate, path,
                        "--min-success-rate", "0.4"],
                       capture_output=True, text=True, timeout=60)
    assert r.returncode == 0, r.stdout + r.stderr
    r = subprocess.run([sys.executable, gate, path,
                        "--min-success-rate", "0.9"],
                       capture_output=True, text=True, timeout=60)
    assert r.returncode == 1
    assert "gateway_success_rate" in r.stderr
    # absent metric = breach, same contract as every other budget
    path2 = _write_stream(tmp_path / "..", [
        {"ts": 1, "source": "train", "step_time": 0.01}])
    r = subprocess.run([sys.executable, gate, path2,
                        "--min-success-rate", "0.5"],
                       capture_output=True, text=True, timeout=60)
    assert r.returncode == 1


def test_chaos_run_wedge_replica_unproven_guard():
    """A run that never touches serving must FAIL the --wedge-replica
    drill (no MXTPU_SERVE marker = no proof the injection fired)."""
    r = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "chaos_run.py"),
         "--wedge-replica", "0", "--timeout", "60", "--expect",
         "complete", "--", sys.executable, "-c", "print('idle')"],
        capture_output=True, text=True, timeout=120)
    assert r.returncode == 2, r.stdout + r.stderr
    summary = json.loads(r.stdout.splitlines()[-1])
    assert summary["ok"] is False
    assert "unproven" in summary["note"]
    assert summary["serve_markers"] == 0


@pytest.mark.slow
def test_chaos_run_wedge_replica_end_to_end():
    """The drill against a real serving process: chaos_run arms the
    replica-0 hang via env, the child serves through it (watchdog
    armed), and the MXTPU_SERVE markers prove trips were observed."""
    child = (
        "import numpy as np, os\n"
        "import mxnet_tpu as mx\n"
        "from mxnet_tpu.serving import InferenceEngine, ModelServer\n"
        "h = mx.sym.FullyConnected(data=mx.sym.var('data'),"
        " num_hidden=3, name='fc1')\n"
        "sym = mx.sym.SoftmaxOutput(data=h, name='softmax')\n"
        "rng = np.random.RandomState(0)\n"
        "args = {'fc1_weight': mx.nd.array(rng.randn(3, 6)"
        ".astype(np.float32)), 'fc1_bias':"
        " mx.nd.array(rng.randn(3).astype(np.float32))}\n"
        "eng = InferenceEngine.from_symbol(sym, args, {},"
        " {'data': (6,)}, 4, name='drill')\n"
        "srv = ModelServer(eng, num_workers=2, max_wait_ms=1.0,"
        " warmup=True).start()\n"
        "for i in range(6):\n"
        "    srv.infer(np.zeros((1, 6), np.float32), timeout=30)\n"
        "srv.drain(timeout=30)\n"
        "print('served')\n")
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               MXTPU_SERVE_DISPATCH_TIMEOUT_S="0.3",
               MXTPU_SERVE_TRIP_LIMIT="2", MXTPU_SERVE_CANARY_S="0.1")
    r = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "chaos_run.py"),
         "--wedge-replica", "0", "--wedge-trips", "2", "--timeout",
         "300", "--expect", "complete", "--", sys.executable, "-c",
         child],
        capture_output=True, text=True, timeout=400, env=env)
    assert r.returncode == 0, r.stdout[-3000:] + r.stderr[-2000:]
    summary = json.loads(r.stdout.splitlines()[-1])
    assert summary["ok"] is True
    assert summary["serve_markers"] >= 1


@pytest.mark.slow
def test_gateway_wedge_acceptance_over_http(monkeypatch):
    """ISSUE-14 acceptance (real HTTP): one of two replicas wedged —
    every interactive request still answers within deadline + grace,
    the replica quarantines then canary-re-admits, and the sequence is
    visible in /debugz replica health."""
    import urllib.request
    _arm(monkeypatch, timeout="0.3", trips="2", canary="0.1")
    reg = ModelRegistry()
    reg.register("acc", lambda: ModelServer(
        _mlp_engine(9, name="acc"), num_workers=2, max_wait_ms=1.0),
        eager=True, warmup=True)
    gw = Gateway(reg, port=0, concurrency=4).start()
    try:
        chaos.configure(
            "serving.replica0.dispatch:kind=hang,secs=3,n=3")
        server = reg.get("acc")
        ok = 0
        for i in range(10):
            req = urllib.request.Request(
                gw.url + "/v1/models/acc:predict",
                data=json.dumps({"inputs": [[0.1] * FEATURES],
                                 "deadline_ms": 5000}).encode(),
                headers={"Content-Type": "application/json"})
            t0 = time.perf_counter()
            with urllib.request.urlopen(req, timeout=30) as r:
                assert r.status == 200
                ok += 1
            assert time.perf_counter() - t0 <= 5.0 + 0.3 + 1.0
        assert ok == 10            # >= (N-1)/N floor, trivially
        t_stop = time.monotonic() + 30
        seen_quarantine = readmitted = False
        while time.monotonic() < t_stop and not readmitted:
            st = {w["index"]: w["state"]
                  for w in server.stats()["workers"]}
            seen_quarantine = seen_quarantine or \
                st[0] == "quarantined"
            readmitted = seen_quarantine and st[0] == "healthy"
            time.sleep(0.05)
        assert seen_quarantine and readmitted
        # visible in /debugz replica health
        with urllib.request.urlopen(gw.url + "/debugz",
                                    timeout=30) as r:
            debug = json.loads(r.read())
        workers = debug["servers"]["acc"]["workers"]
        assert all("state" in w and "alive" in w for w in workers)
    finally:
        chaos.reset()
        gw.close(timeout=30)
