"""Gluon frontend tests.

Mirrors the reference's tests/python/unittest/test_gluon.py: parameter
management, block composition, hybridize consistency, layer shapes,
save/load round-trips, trainer convergence.
"""
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon, autograd
from mxnet_tpu.gluon import nn
from mxnet_tpu.gluon.parameter import Parameter, ParameterDict, \
    DeferredInitializationError


def test_parameter():
    p = gluon.Parameter("weight", shape=(10, 10))
    p.initialize(init="xavier")
    assert len(p.list_data()) == 1
    assert len(p.list_grad()) == 1
    assert p.data().shape == (10, 10)
    assert p.grad().shape == (10, 10)


def test_parameter_invalid_access():
    p = gluon.Parameter("weight", shape=(10, 10))
    with pytest.raises(RuntimeError):
        p.data()
    with pytest.raises(RuntimeError):
        p.grad()


def test_parameter_dict():
    params = gluon.ParameterDict("net_")
    params.get("weight", shape=(10, 10))
    assert list(params.keys()) == ["net_weight"]
    params.initialize(ctx=mx.cpu())
    params.save("/tmp/test_pd.params")
    params.load("/tmp/test_pd.params", mx.cpu())
    # shared dict finds the same parameter
    shared = gluon.ParameterDict("net_", shared=params)
    w2 = shared.get("weight")
    assert w2 is params["net_weight"]


def test_constant():
    class Test(gluon.HybridBlock):
        def __init__(self, **kwargs):
            super().__init__(**kwargs)
            self.value = np.asarray([[1, 2], [3, 4]], dtype="float32")
            self.const = self.params.get_constant("const", self.value)

        def hybrid_forward(self, F, x, const):
            return x + const

    test = Test()
    test.initialize()
    trainer = gluon.Trainer(test.collect_params(), "sgd",
                            {"learning_rate": 1.0, "momentum": 0.5})
    with autograd.record():
        x = mx.nd.ones((2, 2))
        x.attach_grad()
        y = test(x)
        y.backward()
    trainer.step(1)
    assert (test.const.data().asnumpy() == test.value).all()


def test_basic_blocks():
    model = nn.Sequential()
    model.add(nn.Dense(128, activation="tanh", in_units=10, flatten=False))
    model.add(nn.Dropout(0.5))
    model.add(nn.Dense(64, activation="tanh", in_units=256))
    model.add(nn.Dense(32, in_units=64))
    model.add(nn.Activation("relu"))
    model.initialize()
    x = mx.nd.zeros((32, 2, 10))
    out = model(x)
    assert out.shape == (32, 32)
    params = model.collect_params()
    assert len(params) == 6


def test_dense():
    model = nn.Dense(128, activation="tanh", in_units=10, flatten=False,
                     prefix="test_")
    inputs = mx.sym.Variable("data")
    outputs = model(inputs)
    assert set(model.collect_params().keys()) == \
        {"test_weight", "test_bias"}
    model.initialize()
    x = mx.nd.random.uniform(shape=(2, 3, 10))
    assert model(x).shape == (2, 3, 128)

    model = nn.Dense(128, activation="relu", in_units=30, flatten=True,
                     prefix="test2_")
    model.initialize()
    x = mx.nd.random.uniform(shape=(17, 2, 5, 3))
    assert model(x).shape == (17, 128)


def _check_hybrid_consistency(net, x, atol=1e-5):
    net.initialize()
    eager = net(x).asnumpy()
    net.hybridize()
    jitted = net(x).asnumpy()
    np.testing.assert_allclose(eager, jitted, rtol=1e-5, atol=atol)


def test_hybrid_consistency_mlp():
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(32, activation="relu"))
        net.add(nn.BatchNorm())
        net.add(nn.Dense(10))
    _check_hybrid_consistency(net, mx.nd.random.uniform(shape=(4, 16)))

def test_hybrid_consistency_conv():
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Conv2D(8, 3, padding=1, activation="relu"))
        net.add(nn.MaxPool2D(2))
        net.add(nn.Flatten())
        net.add(nn.Dense(10))
    _check_hybrid_consistency(
        net, mx.nd.random.uniform(shape=(2, 3, 8, 8)))


def test_conv_layers():
    for layer, shape, out_shape in [
            (nn.Conv1D(16, 3, in_channels=4), (2, 4, 10), (2, 16, 8)),
            (nn.Conv2D(16, 3, strides=2, in_channels=4), (2, 4, 10, 10),
             (2, 16, 4, 4)),
            (nn.Conv3D(16, 3, in_channels=4), (2, 4, 8, 8, 8),
             (2, 16, 6, 6, 6)),
            (nn.Conv2DTranspose(16, 3, in_channels=4), (2, 4, 5, 5),
             (2, 16, 7, 7)),
            (nn.MaxPool2D(2), (2, 4, 8, 8), (2, 4, 4, 4)),
            (nn.AvgPool2D(2), (2, 4, 8, 8), (2, 4, 4, 4)),
            (nn.GlobalAvgPool2D(), (2, 4, 8, 8), (2, 4, 1, 1)),
            (nn.GlobalMaxPool2D(), (2, 4, 8, 8), (2, 4, 1, 1))]:
        layer.initialize()
        out = layer(mx.nd.random.uniform(shape=shape))
        assert out.shape == out_shape, (layer, out.shape)


def test_batchnorm_running_stats():
    bn = nn.BatchNorm(in_channels=4)
    bn.initialize()
    x = mx.nd.random.uniform(shape=(8, 4, 3, 3))
    with autograd.record():
        bn(x)
    rm = bn.running_mean.data().asnumpy()
    assert not np.allclose(rm, np.zeros(4)), "moving mean not updated"


def test_deferred_init():
    net = nn.Dense(10)
    net.initialize()
    # shape unknown until first forward
    with pytest.raises(DeferredInitializationError):
        net.weight.data()
    net(mx.nd.ones((2, 7)))
    assert net.weight.shape == (10, 7)


def test_save_load_parameters(tmp_path):
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(16, in_units=8))
        net.add(nn.Dense(4, in_units=16))
    net.initialize()
    x = mx.nd.ones((2, 8))
    y = net(x).asnumpy()
    f = str(tmp_path / "net.params")
    net.save_parameters(f)

    net2 = nn.HybridSequential()
    with net2.name_scope():
        net2.add(nn.Dense(16, in_units=8))
        net2.add(nn.Dense(4, in_units=16))
    net2.load_parameters(f)
    np.testing.assert_allclose(net2(x).asnumpy(), y, rtol=1e-6)


def test_export_import(tmp_path):
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(16, in_units=8, activation="relu"))
        net.add(nn.Dense(4, in_units=16))
    net.initialize()
    net.hybridize()
    x = mx.nd.ones((2, 8))
    y = net(x).asnumpy()
    path = str(tmp_path / "model")
    net.export(path)
    net2 = gluon.SymbolBlock.imports(path + "-symbol.json", ["data"],
                                     path + "-0000.params")
    np.testing.assert_allclose(net2(x).asnumpy(), y, rtol=1e-5)


def test_trainer_convergence():
    np.random.seed(0)
    X = np.random.randn(64, 10).astype("float32")
    w = np.random.randn(10, 1).astype("float32")
    Y = X @ w
    net = nn.Dense(1)
    net.initialize()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 0.1})
    L = gluon.loss.L2Loss()
    xb, yb = mx.nd.array(X), mx.nd.array(Y)
    for _ in range(100):
        with autograd.record():
            l = L(net(xb), yb)
        l.backward()
        trainer.step(64)
    assert float(l.mean().asscalar()) < 1e-2


def test_trainer_lr():
    net = nn.Dense(1, in_units=2)
    net.initialize()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.5})
    assert trainer.learning_rate == 0.5
    trainer.set_learning_rate(0.1)
    assert trainer.learning_rate == 0.1


def test_block_apply_and_cast():
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(4, in_units=8))
    net.initialize()
    seen = []
    net.apply(lambda b: seen.append(b.name))
    assert len(seen) >= 2
    net.cast("float16")
    assert net[0].weight.data().dtype == np.float16


def test_embedding():
    layer = nn.Embedding(10, 5)
    layer.initialize()
    x = mx.nd.array([0, 2, 5])
    out = layer(x)
    assert out.shape == (3, 5)
    with autograd.record():
        y = layer(x).sum()
    y.backward()
    g = layer.weight.grad().asnumpy()
    assert g[0].sum() != 0 and g[1].sum() == 0


def test_lambda_blocks():
    net = nn.Sequential()
    net.add(nn.Lambda("tanh"))
    net.add(nn.HybridLambda(lambda F, x: F.relu(x)))
    x = mx.nd.array([[-1.0, 2.0]])
    out = net(x)
    np.testing.assert_allclose(out.asnumpy(),
                               np.maximum(np.tanh([[-1.0, 2.0]]), 0),
                               rtol=1e-5)


def test_zero_grad():
    net = nn.Dense(4, in_units=4)
    net.initialize()
    with autograd.record():
        net(mx.nd.ones((2, 4))).backward()
    assert net.weight.grad().asnumpy().sum() != 0
    net.collect_params().zero_grad()
    assert net.weight.grad().asnumpy().sum() == 0
