"""Sharded/async checkpoint for ShardedTrainer (parallel/checkpoint.py
— the TPU-native upgrade over the reference's single-blob
save_checkpoint, SURVEY.md §5.4)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon
from mxnet_tpu.gluon import nn
from mxnet_tpu.parallel import (make_mesh, ShardedTrainer,
                                PartitionSpec)
from mxnet_tpu.parallel.checkpoint import TrainerCheckpoint


def _net():
    m = nn.HybridSequential()
    m.add(nn.Dense(16, activation="relu"), nn.Dense(10))
    m.initialize()
    m(mx.nd.zeros((1, 8)))
    return m


def _trainer(net, mesh, rules=None):
    loss = gluon.loss.SoftmaxCrossEntropyLoss()
    return ShardedTrainer(net, lambda o, l: loss(o, l), "adam",
                          {"learning_rate": 0.01}, mesh=mesh,
                          param_rules=rules)


def _batch(rng):
    return (rng.randn(16, 8).astype("float32"),
            (np.arange(16) % 10).astype("float32"))


def test_save_restore_resumes_identically(tmp_path):
    rng = np.random.RandomState(0)
    net = _net()
    mesh = make_mesh({"dp": 8})
    x, y = _batch(rng)

    a = _trainer(net, mesh)
    for _ in range(3):
        a.step(x, y)
    with TrainerCheckpoint(tmp_path / "ck") as ck:
        ck.save(a._step_count, a, wait=True)
        after = [float(a.step(x, y).asscalar()) for _ in range(3)]

        b = _trainer(net, mesh)
        assert ck.restore_latest(b) == 3
        resumed = [float(b.step(x, y).asscalar()) for _ in range(3)]
    np.testing.assert_allclose(after, resumed, rtol=1e-5, atol=1e-6)


def test_restore_onto_different_sharding(tmp_path):
    # save from a replicated dp trainer, restore into a dp x tp trainer
    # whose dense weights shard over 'tp' — restore must re-shard
    rng = np.random.RandomState(1)
    net = _net()
    x, y = _batch(rng)
    a = _trainer(net, make_mesh({"dp": 8}))
    a.step(x, y)
    with TrainerCheckpoint(tmp_path / "ck2") as ck:
        ck.save(1, a, wait=True)
        b = _trainer(net, make_mesh({"dp": 4, "tp": 2}),
                     rules=[(r"dense1_weight$", PartitionSpec("tp"))])
        ck.restore_latest(b)
    for k in a._params:
        np.testing.assert_allclose(np.asarray(a._params[k]),
                                   np.asarray(b._params[k]),
                                   rtol=1e-6, atol=1e-7)
    la = float(a.step(x, y).asscalar())
    lb = float(b.step(x, y).asscalar())
    assert abs(la - lb) < 1e-4


def test_async_save_and_max_to_keep(tmp_path):
    rng = np.random.RandomState(2)
    net = _net()
    x, y = _batch(rng)
    a = _trainer(net, make_mesh({"dp": 8}))
    with TrainerCheckpoint(tmp_path / "ck3", max_to_keep=2,
                           async_save=True) as ck:
        for s in range(1, 5):
            a.step(x, y)
            ck.save(s, a)          # overlaps next steps
        ck.wait_until_finished()
        assert ck.latest_step() == 4
        assert ck.all_steps() == [3, 4]  # pruned to max_to_keep
        b = _trainer(net, make_mesh({"dp": 8}))
        assert ck.restore_latest(b) == a._step_count
