"""Sharded/async checkpoint for ShardedTrainer (parallel/checkpoint.py
— the TPU-native upgrade over the reference's single-blob
save_checkpoint, SURVEY.md §5.4)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon
from mxnet_tpu.gluon import nn
from mxnet_tpu.parallel import (make_mesh, ShardedTrainer,
                                PartitionSpec)
from mxnet_tpu.parallel.checkpoint import TrainerCheckpoint


def _net():
    m = nn.HybridSequential()
    m.add(nn.Dense(16, activation="relu"), nn.Dense(10))
    m.initialize()
    m(mx.nd.zeros((1, 8)))
    return m


def _trainer(net, mesh, rules=None):
    loss = gluon.loss.SoftmaxCrossEntropyLoss()
    return ShardedTrainer(net, lambda o, l: loss(o, l), "adam",
                          {"learning_rate": 0.01}, mesh=mesh,
                          param_rules=rules)


def _batch(rng):
    return (rng.randn(16, 8).astype("float32"),
            (np.arange(16) % 10).astype("float32"))


def test_save_restore_resumes_identically(tmp_path):
    rng = np.random.RandomState(0)
    net = _net()
    mesh = make_mesh({"dp": 8})
    x, y = _batch(rng)

    a = _trainer(net, mesh)
    for _ in range(3):
        a.step(x, y)
    with TrainerCheckpoint(tmp_path / "ck") as ck:
        ck.save(a._step_count, a, wait=True)
        after = [float(a.step(x, y).asscalar()) for _ in range(3)]

        b = _trainer(net, mesh)
        assert ck.restore_latest(b) == 3
        resumed = [float(b.step(x, y).asscalar()) for _ in range(3)]
    np.testing.assert_allclose(after, resumed, rtol=1e-5, atol=1e-6)


def test_restore_onto_different_sharding(tmp_path):
    # save from a replicated dp trainer, restore into a dp x tp trainer
    # whose dense weights shard over 'tp' — restore must re-shard
    rng = np.random.RandomState(1)
    net = _net()
    x, y = _batch(rng)
    a = _trainer(net, make_mesh({"dp": 8}))
    a.step(x, y)
    with TrainerCheckpoint(tmp_path / "ck2") as ck:
        ck.save(1, a, wait=True)
        b = _trainer(net, make_mesh({"dp": 4, "tp": 2}),
                     rules=[(r"dense1_weight$", PartitionSpec("tp"))])
        ck.restore_latest(b)
    for k in a._params:
        np.testing.assert_allclose(np.asarray(a._params[k]),
                                   np.asarray(b._params[k]),
                                   rtol=1e-6, atol=1e-7)
    la = float(a.step(x, y).asscalar())
    lb = float(b.step(x, y).asscalar())
    assert abs(la - lb) < 1e-4


def test_async_save_and_max_to_keep(tmp_path):
    rng = np.random.RandomState(2)
    net = _net()
    x, y = _batch(rng)
    a = _trainer(net, make_mesh({"dp": 8}))
    with TrainerCheckpoint(tmp_path / "ck3", max_to_keep=2,
                           async_save=True) as ck:
        for s in range(1, 5):
            a.step(x, y)
            ck.save(s, a)          # overlaps next steps
        ck.wait_until_finished()
        assert ck.latest_step() == 4
        assert ck.all_steps() == [3, 4]  # pruned to max_to_keep
        b = _trainer(net, make_mesh({"dp": 8}))
        assert ck.restore_latest(b) == a._step_count


def test_compressed_trainer_checkpoints_residuals(tmp_path):
    # error-feedback residuals are training state: resume must carry
    # them, or the compressed exchange diverges from an uninterrupted run
    rng = np.random.RandomState(3)
    net = _net()
    x, y = _batch(rng)
    gc = {"type": "2bit", "threshold": 0.05}
    loss = gluon.loss.SoftmaxCrossEntropyLoss()
    mk = lambda: ShardedTrainer(net, lambda o, l: loss(o, l), "sgd",
                                {"learning_rate": 0.05},
                                mesh=make_mesh({"dp": 8}),
                                gradient_compression=gc)
    a = mk()
    for _ in range(3):
        a.step(x, y)
    with TrainerCheckpoint(tmp_path / "ckgc") as ck:
        ck.save(3, a, wait=True)
        after = [float(a.step(x, y).asscalar()) for _ in range(2)]
        b = mk()
        assert ck.restore_latest(b) == 3
        resumed = [float(b.step(x, y).asscalar()) for _ in range(2)]
    np.testing.assert_allclose(after, resumed, rtol=1e-5, atol=1e-6)


def test_shard_opt_state_rejected_with_compression():
    import pytest
    net = _net()
    loss = gluon.loss.SoftmaxCrossEntropyLoss()
    with pytest.raises(mx.MXNetError):
        ShardedTrainer(net, lambda o, l: loss(o, l), "sgd", {},
                       mesh=make_mesh({"dp": 8}),
                       gradient_compression={"type": "2bit"},
                       shard_optimizer_state=True)


def test_restore_across_compression_config_changes(tmp_path):
    # checkpoints from a plain trainer restore into a compressed one
    # (residuals keep their fresh zeros) and vice versa (extra key on
    # disk ignored) — structure drift must not break resume
    rng = np.random.RandomState(4)
    net = _net()
    x, y = _batch(rng)
    loss = gluon.loss.SoftmaxCrossEntropyLoss()
    mk = lambda **kw: ShardedTrainer(net, lambda o, l: loss(o, l),
                                     "sgd", {"learning_rate": 0.05},
                                     mesh=make_mesh({"dp": 8}), **kw)
    gc = {"gradient_compression": {"type": "2bit", "threshold": 0.05}}
    plain = mk()
    plain.step(x, y)
    with TrainerCheckpoint(tmp_path / "p2c") as ck:
        ck.save(1, plain, wait=True)
        comp = mk(**gc)
        assert ck.restore_latest(comp) == 1
        assert float(comp.step(x, y).asscalar()) > 0
    comp2 = mk(**gc)
    for _ in range(2):
        comp2.step(x, y)
    with TrainerCheckpoint(tmp_path / "c2p") as ck:
        ck.save(2, comp2, wait=True)
        plain2 = mk()
        assert ck.restore_latest(plain2) == 2
        for k in comp2._params:
            np.testing.assert_allclose(np.asarray(plain2._params[k]),
                                       np.asarray(comp2._params[k]),
                                       rtol=1e-6, atol=1e-7)


def test_old_plain_sgd_checkpoint_restores_into_stateless_trainer(
        tmp_path):
    # pre-0.3 checkpoints stored a zero-momentum dict for plain SGD;
    # restore must migrate (drop it), not crash
    import jax.numpy as jnp
    rng = np.random.RandomState(5)
    net = _net()
    x, y = _batch(rng)
    loss = gluon.loss.SoftmaxCrossEntropyLoss()
    a = ShardedTrainer(net, lambda o, l: loss(o, l), "sgd",
                       {"learning_rate": 0.05},
                       mesh=make_mesh({"dp": 8}))
    a.step(x, y)
    assert a._opt_state == {}
    # simulate the legacy on-disk structure
    a._opt_state = {k: jnp.zeros_like(v) for k, v in a._params.items()}
    with TrainerCheckpoint(tmp_path / "old") as ck:
        ck.save(1, a, wait=True)
        a._opt_state = {}
        b = ShardedTrainer(net, lambda o, l: loss(o, l), "sgd",
                           {"learning_rate": 0.05},
                           mesh=make_mesh({"dp": 8}))
        assert ck.restore_latest(b) == 1
        assert b._opt_state == {}
        for k in a._params:
            np.testing.assert_allclose(np.asarray(b._params[k]),
                                       np.asarray(a._params[k]),
                                       rtol=1e-6, atol=1e-7)


def test_restore_latest_falls_back_past_corrupt_step(tmp_path):
    """A preempted save / disk corruption can leave the newest step
    unreadable; restore_latest must warn, fall back to the newest
    READABLE step, and report that step's number — not die on the
    corpse (resilience layer, docs/fault_tolerance.md)."""
    import os
    rng = np.random.RandomState(7)
    net = _net()
    mesh = make_mesh({"dp": 8})
    x, y = _batch(rng)
    a = _trainer(net, mesh)
    with TrainerCheckpoint(tmp_path / "ck", max_to_keep=3) as ck:
        for s in (1, 2):
            a.step(x, y)
            ck.save(s, a, wait=True)
        good = {k: np.asarray(v).copy() for k, v in a._params.items()}
        a.step(x, y)
        ck.save(3, a, wait=True)

        # corrupt every data file of the newest step (keep the
        # step-level metadata so orbax still lists the step)
        step_dir = str(tmp_path / "ck" / "3")
        assert os.path.isdir(step_dir)
        clobbered = 0
        for root, _dirs, files in os.walk(step_dir):
            for fn in files:
                if fn == "_CHECKPOINT_METADATA":
                    continue
                with open(os.path.join(root, fn), "wb") as f:
                    f.write(b"\x00garbage\x00" * 16)
                clobbered += 1
        assert clobbered > 0
        assert ck.latest_step() == 3  # still listed — that's the trap

        b = _trainer(net, mesh)
        with pytest.warns(RuntimeWarning, match="step 3 .* unreadable"):
            restored = ck.restore_latest(b)
        assert restored == 2
        assert b._step_count == 2
        for k in good:
            np.testing.assert_allclose(np.asarray(b._params[k]),
                                       good[k], rtol=1e-6, atol=1e-7)


def test_elastic_restore_onto_smaller_world(tmp_path):
    """Elasticity beyond the reference: save from a dp=8 mesh, resume on
    a dp=4 mesh (half the devices). The training math is world-size
    independent (mean over the global batch), so the resumed run must
    continue bit-compatibly with an uninterrupted same-size run."""
    import jax
    devs = jax.devices()[:8]
    rng = np.random.RandomState(0)
    x, y = _batch(rng)

    net = _net()
    big = _trainer(net, make_mesh({"dp": 8}, devs))
    for _ in range(3):
        big.step(x, y)
    with TrainerCheckpoint(str(tmp_path / "ck")) as ck:
        ck.save(3, big, wait=True)

        # resume on HALF the world
        small = _trainer(net, make_mesh({"dp": 4}, devs[:4]))
        assert ck.restore_latest(small) == 3
        resumed = [float(small.step(x, y).asscalar()) for _ in range(2)]

        # oracle: an uninterrupted dp=4 run restored from the same
        # checkpoint-3 state
        oracle = _trainer(net, make_mesh({"dp": 4}, devs[4:]))
        ck.restore_latest(oracle)
        expect = [float(oracle.step(x, y).asscalar()) for _ in range(2)]
    for a, b in zip(resumed, expect):
        assert abs(a - b) < 1e-5, (resumed, expect)
    # and the loss is actually improving across the world change
    assert resumed[-1] < resumed[0] * 1.05


def test_elastic_restore_reshards_compression_residuals(tmp_path):
    """Elastic resume for a COMPRESSED trainer: residual banks carry a
    per-stream leading axis of size n_dp, so a world-size change must
    reshard them. Error feedback is correct as long as the global
    untransmitted error (sum over streams) is preserved — the restore
    spreads each param's total evenly over the new streams."""
    import jax
    devs = jax.devices()[:8]
    rng = np.random.RandomState(3)
    x, y = _batch(rng)
    gc = {"type": "2bit", "threshold": 0.05}

    def compressed(mesh):
        loss = gluon.loss.SoftmaxCrossEntropyLoss()
        return ShardedTrainer(net, lambda o, l: loss(o, l), "sgd",
                              {"learning_rate": 0.05}, mesh=mesh,
                              gradient_compression=gc)

    net = _net()
    big = compressed(make_mesh({"dp": 8}, devs))
    for _ in range(4):
        big.step(x, y)
    saved_total = {k: np.asarray(v).sum(axis=0)
                   for k, v in big._gc_residuals.items()}
    assert any(np.abs(v).max() > 0 for v in saved_total.values()), \
        "test needs nonzero residuals to be meaningful"
    with TrainerCheckpoint(str(tmp_path / "ck")) as ck:
        ck.save(4, big, wait=True)
        small = compressed(make_mesh({"dp": 4}, devs[:4]))
        assert ck.restore_latest(small) == 4
    for k, tot in saved_total.items():
        bank = np.asarray(small._gc_residuals[k])
        assert bank.shape[0] == 4
        np.testing.assert_allclose(bank.sum(axis=0), tot,
                                   rtol=1e-5, atol=1e-7)
    # the resumed compressed run keeps training sanely
    ls = [float(small.step(x, y).asscalar()) for _ in range(3)]
    assert all(np.isfinite(ls)) and ls[-1] < ls[0] * 1.25
