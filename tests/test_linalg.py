"""Oracle tests for the linalg_* operator family vs numpy.linalg.

Reference: src/operator/tensor/la_op.cc (linalg_gemm/gemm2/potrf/potri/
trmm/trsm/syrk/sumlogdiag/syevd/gelqf); test breadth model:
tests/python/unittest/test_operator.py (the reference exercises every
registered op at least once — this file closes the linalg gap found in
round 3's audit).

Conventions under test (mxnet semantics):
  potrf(A)    = lower Cholesky factor of SPD A
  potri(L)    = A^-1 given L = potrf(A)
  gemm        = alpha*op(A)op(B) + beta*C
  gemm2       = alpha*op(A)op(B)
  syrk        = alpha*A·Aᵀ (transpose=False) / alpha*Aᵀ·A
  trmm        = alpha*tri(A)·B (rightside/transpose variants)
  trsm        solves tri(A)·X = alpha*B (and variants)
  sumlogdiag  = sum(log(diag(A)))
  syevd       = (U, w) with A = Uᵀ·diag(w)·U, rows of U eigenvectors
  gelqf       = (L, Q) with A = L·Q, Q orthonormal rows
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.test_utils import check_numeric_gradient

RNG = np.random.RandomState(42)


def spd(n, batch=()):
    b = RNG.randn(*batch, n, n).astype("float64")
    a = np.matmul(b, np.swapaxes(b, -1, -2)) + n * np.eye(n)
    return a.astype("float32")


def test_potrf_vs_numpy():
    for batch in [(), (3,)]:
        a = spd(5, batch)
        l = nd.linalg_potrf(nd.array(a)).asnumpy()
        ref = np.linalg.cholesky(a.astype("float64"))
        assert np.allclose(l, ref, atol=1e-3)
        # lower-triangular by construction
        assert np.allclose(l, np.tril(l), atol=1e-6)


def test_potri_is_inverse():
    a = spd(4)
    l = nd.linalg_potrf(nd.array(a))
    inv = nd.linalg_potri(l).asnumpy()
    assert np.allclose(inv, np.linalg.inv(a.astype("float64")), atol=1e-3)


def test_gemm_family():
    a = RNG.randn(3, 4).astype("float32")
    b = RNG.randn(4, 5).astype("float32")
    c = RNG.randn(3, 5).astype("float32")
    out = nd.linalg_gemm(nd.array(a), nd.array(b), nd.array(c),
                         alpha=2.0, beta=-1.0).asnumpy()
    assert np.allclose(out, 2.0 * a @ b - c, atol=1e-5)
    # transposed operands
    out = nd.linalg_gemm(nd.array(a.T), nd.array(b.T), nd.array(c),
                         transpose_a=True, transpose_b=True).asnumpy()
    assert np.allclose(out, a @ b + c, atol=1e-5)
    out2 = nd.linalg_gemm2(nd.array(a), nd.array(b), alpha=0.5).asnumpy()
    assert np.allclose(out2, 0.5 * a @ b, atol=1e-5)


def test_syrk():
    a = RNG.randn(3, 5).astype("float32")
    assert np.allclose(nd.linalg_syrk(nd.array(a), alpha=1.5).asnumpy(),
                       1.5 * a @ a.T, atol=1e-5)
    assert np.allclose(
        nd.linalg_syrk(nd.array(a), transpose=True).asnumpy(),
        a.T @ a, atol=1e-5)


def test_trmm_trsm_roundtrip():
    n = 4
    a = np.tril(RNG.randn(n, n)).astype("float32") + 3 * np.eye(
        n, dtype="float32")
    b = RNG.randn(n, 3).astype("float32")
    # trmm computes tri(A)@B; trsm must undo it
    prod = nd.linalg_trmm(nd.array(a), nd.array(b), alpha=2.0)
    assert np.allclose(prod.asnumpy(), 2.0 * np.tril(a) @ b, atol=1e-5)
    back = nd.linalg_trsm(nd.array(a), prod, alpha=0.5).asnumpy()
    assert np.allclose(back, b, atol=1e-4)
    # rightside: B@tri(A); and its solve
    br = RNG.randn(3, n).astype("float32")
    pr = nd.linalg_trmm(nd.array(a), nd.array(br), rightside=True)
    assert np.allclose(pr.asnumpy(), br @ np.tril(a), atol=1e-5)
    xr = nd.linalg_trsm(nd.array(a), pr, rightside=True).asnumpy()
    assert np.allclose(xr, br, atol=1e-4)
    # transpose: tri(A)ᵀ X = B  <=>  X = tri(A)^-ᵀ B
    xt = nd.linalg_trsm(nd.array(a), nd.array(b), transpose=True).asnumpy()
    assert np.allclose(np.tril(a).T @ xt, b, atol=1e-4)


def test_sumlogdiag():
    a = spd(4)
    out = nd.linalg_sumlogdiag(nd.array(a)).asnumpy()
    assert np.allclose(out, np.log(np.diag(a)).sum(), atol=1e-5)


def test_syevd_vs_numpy():
    a = spd(5)
    u, w = nd.linalg_syevd(nd.array(a))
    u, w = u.asnumpy(), w.asnumpy()
    w_ref = np.linalg.eigvalsh(a.astype("float64"))
    assert np.allclose(np.sort(w), np.sort(w_ref), atol=1e-3)
    # rows of U are eigenvectors: A = Uᵀ diag(w) U
    rec = u.T @ np.diag(w) @ u
    assert np.allclose(rec, a, atol=1e-3)
    # orthonormality
    assert np.allclose(u @ u.T, np.eye(5), atol=1e-4)


def test_gelqf():
    a = RNG.randn(3, 6).astype("float32")  # m <= n
    q, l = nd.linalg_gelqf(nd.array(a))  # mxnet order: (Q, L), A = L·Q
    l, q = l.asnumpy(), q.asnumpy()
    assert np.allclose(l @ q, a, atol=1e-4)       # A = L·Q
    assert np.allclose(l, np.tril(l), atol=1e-5)  # L lower-triangular
    assert np.allclose(q @ q.T, np.eye(3), atol=1e-4)  # orthonormal rows


def test_linalg_symbol_and_grad():
    """linalg ops compose in graphs and differentiate correctly
    (numeric-grad check, mxnet_tpu/test_utils.py:170 checker pattern)."""
    a = mx.sym.var("a")
    b = mx.sym.var("b")
    out = mx.sym.sum(mx.sym.linalg_gemm2(a, b, alpha=1.5))
    check_numeric_gradient(
        out, {"a": RNG.randn(3, 4).astype("float32"),
              "b": RNG.randn(4, 2).astype("float32")})

    # potrf grad on an SPD input
    av = spd(3)
    out = mx.sym.sum(mx.sym.linalg_sumlogdiag(mx.sym.linalg_potrf(a)))
    check_numeric_gradient(out, {"a": av}, rtol=2e-2, atol=1e-2)


def test_linalg_batched():
    """Batch dims broadcast through the whole family (XLA batches the
    underlying lax ops; the reference loops per-matrix in la_op.cc)."""
    a = spd(4, (2, 3))
    l = nd.linalg_potrf(nd.array(a)).asnumpy()
    assert l.shape == (2, 3, 4, 4)
    ref = np.linalg.cholesky(a.astype("float64"))
    assert np.allclose(l, ref, atol=1e-3)
    s = nd.linalg_sumlogdiag(nd.array(a)).asnumpy()
    assert s.shape == (2, 3)
