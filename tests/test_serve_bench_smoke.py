"""tools/serve_bench.py must stay runnable: the driver checks its
closed-loop record (>= 3x serial at output parity) on real hardware, so
a tiny-shape CPU smoke run gates bitrot — same contract as
tests/test_bench_smoke.py for bench.py."""
import json
import os
import subprocess
import sys

import pytest


def _run(extra_env=None, args=()):
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env.update(JAX_PLATFORMS="cpu",
               MXTPU_SERVE_BENCH_CLIENTS="8",
               MXTPU_SERVE_BENCH_REQUESTS="96",
               MXTPU_SERVE_BENCH_SERIAL="48",
               MXTPU_SERVE_BENCH_FEATURES="64",
               MXTPU_SERVE_BENCH_HIDDEN="64")
    env.update(extra_env or {})
    r = subprocess.run(
        [sys.executable, os.path.join(root, "tools", "serve_bench.py"),
         *args],
        capture_output=True, text=True, timeout=600, env=env)
    assert r.returncode == 0, r.stdout[-2000:] + r.stderr[-2000:]
    line = [l for l in r.stdout.splitlines() if l.startswith("{")][-1]
    return json.loads(line)


def test_serve_bench_smoke_closed_loop():
    out = _run()
    assert out["metric"] == "serving_closed_loop_throughput"
    assert out["unit"] == "req/s" and out["value"] > 0
    assert out["platform"] == "cpu"
    extra = out["extra"]
    # equal output parity between the serial Predictor and the batched
    # server is a hard requirement, whatever the speedup
    assert extra["parity"] is True
    assert extra["serial_rps"] > 0
    assert extra["errors"] == 0
    assert "speedup_vs_serial" in extra
    for key in ("latency_p50_ms", "latency_p95_ms", "latency_p99_ms",
                "shed_rate", "batches"):
        assert key in extra, extra


def test_serve_bench_smoke_open_loop():
    out = _run(args=("--mode", "open", "--rate", "500"))
    assert out["metric"] == "serving_open_loop_throughput"
    ol = out["extra"]["open_loop"]
    assert ol["completed"] + ol["shed"] + ol["failed"] == ol["requests"]
    assert out["extra"]["parity"] is True


@pytest.mark.slow
def test_serve_bench_meets_3x_acceptance():
    """ISSUE-5 acceptance: closed-loop batched throughput >= 3x the
    serial per-request Predictor loop on CPU (full-size run; excluded
    from tier-1 where CI load makes throughput ratios flaky)."""
    out = _run(extra_env={"MXTPU_SERVE_BENCH_CLIENTS": "16",
                          "MXTPU_SERVE_BENCH_REQUESTS": "640",
                          "MXTPU_SERVE_BENCH_SERIAL": "200",
                          "MXTPU_SERVE_BENCH_FEATURES": "256",
                          "MXTPU_SERVE_BENCH_HIDDEN": "256"})
    assert out["extra"]["parity"] is True
    assert out["extra"]["speedup_vs_serial"] >= 3.0, out["extra"]
