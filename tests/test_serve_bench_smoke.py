"""tools/serve_bench.py must stay runnable: the driver checks its
closed-loop record (>= 3x serial at output parity) on real hardware, so
a tiny-shape CPU smoke run gates bitrot — same contract as
tests/test_bench_smoke.py for bench.py."""
import json
import os
import subprocess
import sys

import pytest


def _run(extra_env=None, args=()):
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env.update(JAX_PLATFORMS="cpu",
               MXTPU_SERVE_BENCH_CLIENTS="8",
               MXTPU_SERVE_BENCH_REQUESTS="96",
               MXTPU_SERVE_BENCH_SERIAL="48",
               MXTPU_SERVE_BENCH_FEATURES="64",
               MXTPU_SERVE_BENCH_HIDDEN="64")
    env.update(extra_env or {})
    r = subprocess.run(
        [sys.executable, os.path.join(root, "tools", "serve_bench.py"),
         *args],
        capture_output=True, text=True, timeout=600, env=env)
    assert r.returncode == 0, r.stdout[-2000:] + r.stderr[-2000:]
    line = [l for l in r.stdout.splitlines() if l.startswith("{")][-1]
    return json.loads(line)


def test_serve_bench_smoke_closed_loop():
    out = _run()
    assert out["metric"] == "serving_closed_loop_throughput"
    assert out["unit"] == "req/s" and out["value"] > 0
    assert out["platform"] == "cpu"
    extra = out["extra"]
    # equal output parity between the serial Predictor and the batched
    # server is a hard requirement, whatever the speedup
    assert extra["parity"] is True
    assert extra["serial_rps"] > 0
    assert extra["errors"] == 0
    assert "speedup_vs_serial" in extra
    for key in ("latency_p50_ms", "latency_p95_ms", "latency_p99_ms",
                "shed_rate", "batches"):
        assert key in extra, extra


def test_serve_bench_smoke_open_loop():
    out = _run(args=("--mode", "open", "--rate", "500"))
    assert out["metric"] == "serving_open_loop_throughput"
    ol = out["extra"]["open_loop"]
    assert ol["completed"] + ol["shed"] + ol["failed"] == ol["requests"]
    assert out["extra"]["parity"] is True


def test_serve_bench_smoke_decode():
    """--mode decode must stay runnable and token-parity-true: small
    shapes, but the full continuous-batching pipeline (bucketed
    prefill, admit, donated step, scheduler) and the sequential
    baseline both execute."""
    out = _run(extra_env={"MXTPU_SERVE_BENCH_DECODE_SEQS": "8",
                          "MXTPU_SERVE_BENCH_DECODE_SLOTS": "4",
                          "MXTPU_SERVE_BENCH_DECODE_NEW": "6",
                          "MXTPU_SERVE_BENCH_DECODE_PROMPT": "8",
                          "MXTPU_SERVE_BENCH_DECODE_EMBED": "16"},
               args=("--mode", "decode"))
    assert out["metric"] == "serving_decode_throughput"
    assert out["unit"] == "tok/s" and out["value"] > 0
    assert out["platform"] == "cpu"
    extra = out["extra"]
    # continuous batching and the sequential baseline must emit the
    # same greedy tokens — the decode analogue of the parity contract
    assert extra["parity"] is True
    assert extra["sequential_tok_s"] > 0
    assert extra["tokens"] == 8 * 6
    # the exactly-two-programs invariant holds under bench load too
    assert {k: v for k, v in extra["compiled_programs"].items()
            if k != "prefill"} == {"admit": 1, "step": 1}
    for key in ("ttft_p50_ms", "ttft_p95_ms", "ttft_p99_ms",
                "intertoken_p50_ms", "intertoken_p95_ms",
                "intertoken_p99_ms", "eviction_rate",
                "speedup_vs_sequential"):
        assert key in extra, extra


def test_serve_bench_smoke_coldstart():
    """--mode coldstart must stay runnable: tiny shapes, but the full
    pipeline executes — cold child populates cache + AOT store, warm
    child loads executables, record carries the before/after."""
    out = _run(args=("--mode", "coldstart", "--depth", "4",
                     "--cold-hidden", "32", "--max-batch", "4"))
    assert out["metric"] == "serving_cold_start_speedup"
    assert out["unit"] == "x" and out["value"] > 0
    assert out["platform"] == "cpu"
    extra = out["extra"]
    assert extra["cold_start_s"] > 0 and extra["warm_start_s"] > 0
    # the cold child compiled (misses), the warm child did not
    assert extra["cold"]["cache_misses"] > 0
    assert extra["warm"]["cache_hits"] > 0 or \
        extra["warm"]["aot_loads"] > 0
    # the warm child loaded the cold child's exported executables
    assert extra["warm"]["aot_buckets"] == [1, 2, 4]
    for key in ("speedup", "cold_start_s", "warm_start_s"):
        assert key in extra, extra


def test_serve_bench_smoke_gateway():
    """--mode gateway must stay runnable over real HTTP, and the
    ISSUE-12 acceptance rides this record: under mixed-class overload
    the interactive p99 stays within budget while best_effort absorbs
    ALL sheds, responses stay parity-true, and the reload storm under
    a fits-all-but-one budget observes LRU eviction + transparent
    reload."""
    out = _run(extra_env={"MXTPU_SERVE_BENCH_GATEWAY_MODELS": "3",
                          "MXTPU_SERVE_BENCH_GATEWAY_REQUESTS": "8",
                          "MXTPU_SERVE_BENCH_GATEWAY_ROUNDS": "3"},
               args=("--mode", "gateway"))
    assert out["metric"] == "serving_gateway_interactive_p99"
    assert out["unit"] == "ms" and out["value"] > 0
    assert out["platform"] == "cpu"
    extra = out["extra"]
    # the same request through HTTP and the direct in-process server
    # must produce identical bytes, whatever the load
    assert extra["parity"] is True
    assert extra["errors"] == 0
    # shed fairness: best_effort absorbs EVERY shed; interactive and
    # batch traffic is never shed behind it
    assert extra["fairness"] is True, extra
    assert extra["shed_by_class"]["interactive"] == 0
    assert extra["shed_by_class"]["batch"] == 0
    assert extra["shed_by_class"]["best_effort"] > 0
    # the interactive tail holds its budget under the overload
    assert extra["interactive_p99_within_budget"] is True, extra
    for cls in ("interactive", "batch", "best_effort"):
        for key in ("p50_ms", "p95_ms", "p99_ms", "requests"):
            assert key in extra[cls], extra
    # reload storm: a budget that fits all but one model produced real
    # evictions + transparent reloads, and a reload costs more than a
    # resident hit (it rebuilds the engine, even cache-warm)
    rl = extra["reload"]
    assert rl["reloads"] > 0, rl
    assert rl["reload_p50_ms"] > rl["hit_p50_ms"] > 0, rl


def test_serve_bench_smoke_chaos():
    """--mode chaos must stay runnable AND its invariants must hold
    (ISSUE-14 acceptance): with replica 0 of 2 wedged via the
    injected dispatch hang, no request resolves later than
    deadline + watchdog grace, >= 1/2 of the offered load succeeds
    (in practice all of it — tripped batches re-dispatch), the
    replica is quarantined then canary-re-admitted after the fault
    clears, the sequence is visible in metrics, and the watchdog-off
    path stays output-identical."""
    out = _run(extra_env={"MXTPU_SERVE_BENCH_CHAOS_CLIENTS": "4",
                          "MXTPU_SERVE_BENCH_CHAOS_REQUESTS": "6",
                          "MXTPU_SERVE_BENCH_CHAOS_TIMEOUT_S": "0.3",
                          "MXTPU_SERVE_BENCH_CHAOS_DEADLINE_S": "2.0",
                          # generous scheduling slack: this box is a
                          # single loaded core; a real hang would
                          # overshoot any slack by the hang duration
                          "MXTPU_SERVE_BENCH_CHAOS_GRACE_S": "8.0"},
               args=("--mode", "chaos"))
    assert out["metric"] == "serving_chaos_soak"
    assert out["platform"] == "cpu"
    extra = out["extra"]
    assert extra["invariants_ok"] is True, extra
    assert extra["no_late_resolution"] is True
    assert extra["availability_ok"] is True
    assert out["value"] >= extra["availability_floor"]
    assert extra["quarantined"] is True
    assert extra["readmitted"] is True
    assert extra["parity_watchdog_off"] is True
    assert extra["watchdog_trips"] >= extra["trip_limit"]
    assert extra["quarantines"] >= 1 and extra["readmits"] >= 1
    for key in ("watchdog_overhead_p50_pct", "p50_off_ms",
                "p50_armed_ms", "max_resolution_s", "worker_states"):
        assert key in extra, extra


@pytest.mark.slow
def test_serve_bench_chaos_overhead_within_budget():
    """ISSUE-14 acceptance: armed-watchdog dispatch overhead <= 2%
    p50 on the closed-loop baseline shapes (excluded from tier-1
    where CI load makes wall-clock ratios flaky; min-of-3 p50s on an
    idle box)."""
    out = _run(extra_env={"MXTPU_SERVE_BENCH_FEATURES": "256",
                          "MXTPU_SERVE_BENCH_HIDDEN": "256"},
               args=("--mode", "chaos"))
    extra = out["extra"]
    assert extra["invariants_ok"] is True, extra
    assert extra["watchdog_overhead_p50_pct"] <= 2.0, extra


@pytest.mark.slow
def test_serve_bench_coldstart_meets_2x_acceptance():
    """ISSUE-11 acceptance: fresh-process warm start >= 2x faster than
    cold start on CPU at the full coldstart shapes (excluded from
    tier-1 where CI load makes wall-clock ratios flaky)."""
    out = _run(args=("--mode", "coldstart"))
    extra = out["extra"]
    assert extra["warm"]["aot_loads"] > 0, extra
    assert extra["speedup"] >= 2.0, extra


@pytest.mark.slow
def test_serve_bench_decode_meets_2x_acceptance():
    """ISSUE-6 acceptance: continuous-batching decode >= 2x the
    sequential per-request-decode baseline in tokens/s on CPU, at
    token parity (full-size run; excluded from tier-1 where CI load
    makes throughput ratios flaky)."""
    out = _run(args=("--mode", "decode"))
    extra = out["extra"]
    assert extra["parity"] is True
    assert extra["speedup_vs_sequential"] >= 2.0, extra


@pytest.mark.slow
def test_serve_bench_meets_3x_acceptance():
    """ISSUE-5 acceptance: closed-loop batched throughput >= 3x the
    serial per-request Predictor loop on CPU (full-size run; excluded
    from tier-1 where CI load makes throughput ratios flaky)."""
    out = _run(extra_env={"MXTPU_SERVE_BENCH_CLIENTS": "16",
                          "MXTPU_SERVE_BENCH_REQUESTS": "640",
                          "MXTPU_SERVE_BENCH_SERIAL": "200",
                          "MXTPU_SERVE_BENCH_FEATURES": "256",
                          "MXTPU_SERVE_BENCH_HIDDEN": "256"})
    assert out["extra"]["parity"] is True
    assert out["extra"]["speedup_vs_serial"] >= 3.0, out["extra"]
