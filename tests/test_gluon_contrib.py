"""gluon.contrib tests: Concurrent/Identity/SparseEmbedding, contrib RNN
cells, IntervalSampler.

Reference: python/mxnet/gluon/contrib/{nn/basic_layers.py,
rnn/rnn_cell.py, data/sampler.py}.
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon, autograd
from mxnet_tpu.gluon import nn
from mxnet_tpu.gluon.contrib import nn as cnn
from mxnet_tpu.gluon.contrib import rnn as crnn
from mxnet_tpu.gluon.contrib.data import IntervalSampler


def test_hybrid_concurrent_and_identity():
    net = cnn.HybridConcurrent(axis=1)
    net.add(nn.Dense(3), cnn.Identity(), nn.Dense(2))
    net.initialize()
    x = mx.nd.array(np.random.RandomState(0).randn(4, 5).astype("f"))
    y = net(x)
    assert y.shape == (4, 3 + 5 + 2)
    # identity branch passes x through unchanged
    np.testing.assert_allclose(y.asnumpy()[:, 3:8], x.asnumpy(),
                               rtol=1e-6)


def test_sparse_embedding_lookup_and_grad():
    emb = cnn.SparseEmbedding(10, 4)
    emb.initialize()
    x = mx.nd.array(np.array([1, 3, 1], "f"))
    with autograd.record():
        out = emb(x)
        loss = out.sum()
    loss.backward()
    assert out.shape == (3, 4)
    g = emb.weight.grad().asnumpy()
    # rows 1 (twice) and 3 touched; others zero
    assert np.allclose(g[1], 2.0) and np.allclose(g[3], 1.0)
    assert np.allclose(g[[0, 2, 4, 5, 6, 7, 8, 9]], 0.0)


def test_variational_dropout_constant_mask():
    base = gluon.rnn.LSTMCell(8)
    cell = crnn.VariationalDropoutCell(base, drop_outputs=0.5)
    cell.initialize()
    x = mx.nd.ones((2, 4))
    states = cell.begin_state(batch_size=2)
    with autograd.train_mode():
        o1, states = cell(x, states)
        o2, states = cell(x, states)
    # the SAME output mask must apply at both steps: zeros co-located
    z1 = o1.asnumpy() == 0
    z2 = o2.asnumpy() == 0
    assert (z1 == z2).all()
    cell.reset()
    assert cell._masks == {}


def test_lstmp_cell_projects():
    cell = crnn.LSTMPCell(hidden_size=8, projection_size=3)
    cell.initialize()
    x = mx.nd.ones((2, 5))
    states = cell.begin_state(batch_size=2)
    out, new_states = cell(x, states)
    assert out.shape == (2, 3)              # projected
    assert new_states[0].shape == (2, 3)    # r state
    assert new_states[1].shape == (2, 8)    # c state

    # unrolls like any recurrent cell
    seq = mx.nd.ones((2, 4, 5))
    outputs, _ = cell.unroll(4, seq, merge_outputs=True)
    assert outputs.shape == (2, 4, 3)


def test_interval_sampler():
    assert list(IntervalSampler(6, 2)) == [0, 2, 4, 1, 3, 5]
    assert list(IntervalSampler(6, 2, rollover=False)) == [0, 2, 4]
    assert len(IntervalSampler(6, 2)) == 6


# ---------------------------------------------------------------------------
# advanced-parallelism blocks (VERDICT r4 #8): RingAttention / MoEFFN usable
# from HybridBlock + ShardedTrainer without raw jax
# ---------------------------------------------------------------------------


def test_moe_ffn_block_eager_hybrid_parity():
    from mxnet_tpu.gluon.contrib.nn import MoEFFN
    np.random.seed(1)
    moe = MoEFFN(embed_dim=8, hidden_size=16, num_experts=4)
    moe.initialize()
    x = mx.nd.array(np.random.randn(6, 8).astype("float32"))
    out, aux = moe(x)
    moe.hybridize()
    out2, aux2 = moe(x)
    assert np.allclose(out.asnumpy(), out2.asnumpy(), atol=1e-5)
    assert out.shape == (6, 8) and aux.shape == ()


def test_ring_attention_block_matches_softmax_attention():
    from mxnet_tpu.gluon.contrib.nn import RingAttention
    np.random.seed(2)
    q = np.random.randn(2, 2, 8, 4).astype("float32")
    att = RingAttention(causal=False)
    out = att(mx.nd.array(q), mx.nd.array(q), mx.nd.array(q)).asnumpy()
    # oracle
    s = np.einsum("bhqd,bhkd->bhqk", q, q) / np.sqrt(4)
    p = np.exp(s - s.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    exp = np.einsum("bhqk,bhkd->bhqd", p, q)
    assert np.allclose(out, exp, atol=1e-4)


def test_moe_block_trains_under_sharded_trainer_ep_mesh():
    from mxnet_tpu.gluon.contrib.nn import MoEFFN
    from mxnet_tpu.parallel import make_mesh, ShardedTrainer

    class MoENet(gluon.HybridBlock):
        def __init__(self, **kw):
            super().__init__(**kw)
            with self.name_scope():
                self.moe = MoEFFN(embed_dim=8, hidden_size=16,
                                  num_experts=4)
                self.head = nn.Dense(1)

        def hybrid_forward(self, F, x):
            h, aux = self.moe(x)
            return self.head(h), aux

    np.random.seed(0)
    X = np.random.randn(64, 8).astype("float32")
    Y = (X[:, :1] * 2 + X[:, 1:2]).astype("float32")
    net = MoENet()
    net.initialize()
    net(mx.nd.array(X[:4]))
    mesh = make_mesh({"dp": 2, "ep": 4})

    def loss_fn(out, label):
        pred, aux = out
        return gluon.loss.L2Loss()(pred, label) + 0.01 * aux

    st = ShardedTrainer(net, loss_fn, "adam", {"learning_rate": 0.02},
                        mesh=mesh)
    first = float(st.step(X, Y).asscalar())
    for _ in range(80):
        loss = st.step(X, Y)
    assert float(loss.asscalar()) < first * 0.3


def test_ring_attention_block_trains_under_sharded_trainer_sp_mesh():
    from mxnet_tpu.gluon.contrib.nn import RingAttention
    from mxnet_tpu.parallel import make_mesh, ShardedTrainer

    class AttNet(gluon.HybridBlock):
        def __init__(self, **kw):
            super().__init__(**kw)
            with self.name_scope():
                self.proj = nn.Dense(16, flatten=False)  # -> q|k|v  
                self.att = RingAttention(causal=True)
                self.head = nn.Dense(1, flatten=False)

        def hybrid_forward(self, F, x):
            qkv = self.proj(x)                       # (B, S, 16)

            def heads(lo, hi):
                h = F.slice_axis(qkv, axis=-1, begin=lo, end=hi)
                h = F.reshape(h, shape=(0, 0, 1, 4))  # (B, S, 1, 4)
                return F.transpose(h, axes=(0, 2, 1, 3))

            o = self.att(heads(0, 4), heads(4, 8), heads(8, 12))
            o = F.reshape(F.transpose(o, axes=(0, 2, 1, 3)),
                          shape=(0, 0, -1))
            return self.head(o)

    np.random.seed(3)
    B, S = 4, 16
    X = np.random.randn(B, S, 8).astype("float32")
    Y = np.cumsum(X[:, :, :1], axis=1).astype("float32")  # causal target
    net = AttNet()
    net.initialize()
    net(mx.nd.array(X[:2]))
    mesh = make_mesh({"dp": 2, "sp": 4})
    st = ShardedTrainer(net, lambda o, l: gluon.loss.L2Loss()(o, l),
                        "adam", {"learning_rate": 0.02}, mesh=mesh)
    first = float(st.step(X, Y).asscalar())
    for _ in range(60):
        loss = st.step(X, Y)
    assert float(loss.asscalar()) < first * 0.5


def test_moe_block_checkpoints_across_mesh_layouts(tmp_path):
    """An expert-parallel trainer's state must checkpoint and restore
    onto a DIFFERENT dp x ep factorization (orbax reshards leaves onto
    the new mesh), and keep training — scaling experts up or down is
    the ep analog of elastic dp resume."""
    from mxnet_tpu.gluon.contrib.nn import MoEFFN
    from mxnet_tpu.parallel import make_mesh, ShardedTrainer
    from mxnet_tpu.parallel.checkpoint import TrainerCheckpoint

    class MoENet(gluon.HybridBlock):
        def __init__(self, **kw):
            super().__init__(**kw)
            with self.name_scope():
                self.moe = MoEFFN(embed_dim=8, hidden_size=16,
                                  num_experts=4)
                self.head = nn.Dense(1)

        def hybrid_forward(self, F, x):
            h, aux = self.moe(x)
            return self.head(h), aux

    np.random.seed(1)
    X = np.random.randn(32, 8).astype("float32")
    Y = (X[:, :1] * 2).astype("float32")
    net = MoENet()
    net.initialize()
    net(mx.nd.array(X[:4]))

    def loss_fn(out, label):
        pred, aux = out
        return gluon.loss.L2Loss()(pred, label) + 0.01 * aux

    def trainer(mesh):
        return ShardedTrainer(net, loss_fn, "adam",
                              {"learning_rate": 0.02}, mesh=mesh)

    a = trainer(make_mesh({"dp": 2, "ep": 4}))
    for _ in range(3):
        a.step(X, Y)
    with TrainerCheckpoint(str(tmp_path / "ck")) as ck:
        ck.save(3, a, wait=True)
        b = trainer(make_mesh({"dp": 4, "ep": 2}))
        assert ck.restore_latest(b) == 3
        # restored params are bit-identical to the saved ones
        for k, v in a._params.items():
            np.testing.assert_array_equal(np.asarray(v),
                                          np.asarray(b._params[k]))
        ls = [float(b.step(X, Y).asscalar()) for _ in range(2)]
    assert all(np.isfinite(ls)), ls
