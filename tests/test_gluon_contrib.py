"""gluon.contrib tests: Concurrent/Identity/SparseEmbedding, contrib RNN
cells, IntervalSampler.

Reference: python/mxnet/gluon/contrib/{nn/basic_layers.py,
rnn/rnn_cell.py, data/sampler.py}.
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon, autograd
from mxnet_tpu.gluon import nn
from mxnet_tpu.gluon.contrib import nn as cnn
from mxnet_tpu.gluon.contrib import rnn as crnn
from mxnet_tpu.gluon.contrib.data import IntervalSampler


def test_hybrid_concurrent_and_identity():
    net = cnn.HybridConcurrent(axis=1)
    net.add(nn.Dense(3), cnn.Identity(), nn.Dense(2))
    net.initialize()
    x = mx.nd.array(np.random.RandomState(0).randn(4, 5).astype("f"))
    y = net(x)
    assert y.shape == (4, 3 + 5 + 2)
    # identity branch passes x through unchanged
    np.testing.assert_allclose(y.asnumpy()[:, 3:8], x.asnumpy(),
                               rtol=1e-6)


def test_sparse_embedding_lookup_and_grad():
    emb = cnn.SparseEmbedding(10, 4)
    emb.initialize()
    x = mx.nd.array(np.array([1, 3, 1], "f"))
    with autograd.record():
        out = emb(x)
        loss = out.sum()
    loss.backward()
    assert out.shape == (3, 4)
    g = emb.weight.grad().asnumpy()
    # rows 1 (twice) and 3 touched; others zero
    assert np.allclose(g[1], 2.0) and np.allclose(g[3], 1.0)
    assert np.allclose(g[[0, 2, 4, 5, 6, 7, 8, 9]], 0.0)


def test_variational_dropout_constant_mask():
    base = gluon.rnn.LSTMCell(8)
    cell = crnn.VariationalDropoutCell(base, drop_outputs=0.5)
    cell.initialize()
    x = mx.nd.ones((2, 4))
    states = cell.begin_state(batch_size=2)
    with autograd.train_mode():
        o1, states = cell(x, states)
        o2, states = cell(x, states)
    # the SAME output mask must apply at both steps: zeros co-located
    z1 = o1.asnumpy() == 0
    z2 = o2.asnumpy() == 0
    assert (z1 == z2).all()
    cell.reset()
    assert cell._masks == {}


def test_lstmp_cell_projects():
    cell = crnn.LSTMPCell(hidden_size=8, projection_size=3)
    cell.initialize()
    x = mx.nd.ones((2, 5))
    states = cell.begin_state(batch_size=2)
    out, new_states = cell(x, states)
    assert out.shape == (2, 3)              # projected
    assert new_states[0].shape == (2, 3)    # r state
    assert new_states[1].shape == (2, 8)    # c state

    # unrolls like any recurrent cell
    seq = mx.nd.ones((2, 4, 5))
    outputs, _ = cell.unroll(4, seq, merge_outputs=True)
    assert outputs.shape == (2, 4, 3)


def test_interval_sampler():
    assert list(IntervalSampler(6, 2)) == [0, 2, 4, 1, 3, 5]
    assert list(IntervalSampler(6, 2, rollover=False)) == [0, 2, 4]
    assert len(IntervalSampler(6, 2)) == 6
