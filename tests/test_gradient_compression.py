"""2-bit gradient compression tests.

Reference semantics: src/kvstore/gradient_compression.h:38-52 — values
quantized to {-threshold, 0, +threshold} with an error-feedback residual,
16 two-bit codes per 32-bit word on the wire. The numpy oracle below
implements those rules independently of the jax implementation.
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.gradient_compression import (GradientCompression,
                                            quantize_2bit, dequantize_2bit,
                                            packed_size)


def oracle_quantize(grad, residual, thr):
    """Reference-rule quantizer: returns (decoded, new_residual)."""
    acc = residual + grad
    decoded = np.where(acc > thr, thr, np.where(acc < -thr, -thr, 0.0))
    return decoded.astype(grad.dtype), (acc - decoded).astype(grad.dtype)


class TestQuantizer:
    def test_roundtrip_matches_oracle(self):
        rng = np.random.RandomState(0)
        g = (rng.randn(37, 13) * 0.8).astype("float32")
        res = np.zeros_like(g)
        dec_ref, res_ref = oracle_quantize(g, res, 0.5)
        packed, new_res = quantize_2bit(g, res, 0.5)
        dec = dequantize_2bit(packed, g.shape, 0.5)
        np.testing.assert_allclose(np.asarray(dec), dec_ref)
        np.testing.assert_allclose(np.asarray(new_res), res_ref, atol=1e-6)

    def test_error_feedback_accumulates(self):
        # a constant small gradient must eventually fire through the
        # residual: sum of decoded over steps tracks sum of grads
        thr = 0.5
        g = np.full((16,), 0.2, "float32")
        res = np.zeros_like(g)
        total = np.zeros_like(g)
        for _ in range(10):
            packed, res = quantize_2bit(g, res, thr)
            total = total + np.asarray(dequantize_2bit(packed, g.shape, thr))
        # 10 steps x 0.2 = 2.0 true mass; decoded fires 0.5 every ~2.5
        # steps -> expect 3-4 firings each worth 0.5
        assert np.all(np.abs(total - 2.0) <= thr + 1e-6), total[:4]

    def test_wire_size_is_16x_smaller(self):
        n = 10_000
        g = np.ones((n,), "float32")
        packed, _ = quantize_2bit(g, np.zeros_like(g), 0.5)
        assert packed.dtype == np.uint32
        assert packed.size == packed_size(n) == 625
        assert packed.size * 4 * 16 >= n * 4  # 16x fewer bytes than fp32

    def test_odd_sizes_pad(self):
        for n in (1, 15, 16, 17, 33):
            g = np.linspace(-1, 1, n).astype("float32")
            packed, _ = quantize_2bit(g, np.zeros_like(g), 0.3)
            dec = np.asarray(dequantize_2bit(packed, (n,), 0.3))
            ref, _ = oracle_quantize(g, np.zeros_like(g), 0.3)
            np.testing.assert_allclose(dec, ref)


class TestKVStoreCompression:
    def test_local_kvstore_rejects(self):
        kv = mx.kv.create("local")
        with pytest.raises(Exception):
            kv.set_gradient_compression({"type": "2bit", "threshold": 0.5})

    def test_device_kvstore_compresses_push(self):
        kv = mx.kv.create("device")
        kv.set_gradient_compression({"type": "2bit", "threshold": 0.5})
        v = nd.zeros((64,))
        kv.init("w", v)
        g = nd.array(np.full((64,), 0.7, "float32"))
        kv.push("w", [g, g])  # two "device" addends
        out = nd.zeros((64,))
        kv.pull("w", out=out)
        # each addend quantizes 0.7 -> 0.5; store (no updater) keeps sum
        np.testing.assert_allclose(out.asnumpy(), np.full((64,), 1.0),
                                   atol=1e-6)
        # residual carries 0.2 per addend; next push of 0.7 fires 0.5 again
        # and residuals reach 0.4; third push (0.7+0.4=1.1) still fires 0.5
        kv.push("w", [g, g])
        kv.pull("w", out=out)
        np.testing.assert_allclose(out.asnumpy(), np.full((64,), 1.0),
                                   atol=1e-6)

    def test_unsupported_type_raises(self):
        kv = mx.kv.create("device")
        with pytest.raises(Exception):
            kv.set_gradient_compression({"type": "1bit"})


class TestShardedTrainerCompression:
    def test_compressed_dp_converges(self):
        from mxnet_tpu import gluon
        from mxnet_tpu.gluon import nn as gnn
        from mxnet_tpu.parallel import make_mesh, ShardedTrainer

        rng = np.random.RandomState(1)
        # separable 2-class problem, MNIST-ish dimensionality
        X = rng.randn(64, 64).astype("float32")
        Y = (X[:, :32].sum(1) > X[:, 32:].sum(1)).astype("float32")

        net = gnn.HybridSequential()
        net.add(gnn.Dense(32, activation="relu"), gnn.Dense(2))
        net.initialize()
        net(mx.nd.zeros((1, 64)))
        loss = gluon.loss.SoftmaxCrossEntropyLoss()
        st = ShardedTrainer(net, lambda o, l: loss(o, l), "sgd",
                            {"learning_rate": 0.1, "momentum": 0.9},
                            mesh=make_mesh({"dp": 8}),
                            gradient_compression={"type": "2bit",
                                                  "threshold": 0.05})
        losses = [float(st.step(X, Y).asnumpy()) for _ in range(40)]
        assert losses[-1] < 0.5 * losses[0], (losses[0], losses[-1])

    def test_compressed_matches_uncompressed_direction(self):
        # with a huge threshold nothing fires and params must not move;
        # sanity-pins that the collective really gates on the quantizer
        from mxnet_tpu import gluon
        from mxnet_tpu.gluon import nn as gnn
        from mxnet_tpu.parallel import make_mesh, ShardedTrainer

        rng = np.random.RandomState(2)
        X = rng.randn(16, 8).astype("float32")
        Y = (np.arange(16) % 2).astype("float32")
        net = gnn.HybridSequential()
        net.add(gnn.Dense(2))
        net.initialize()
        net(mx.nd.zeros((1, 8)))
        loss = gluon.loss.SoftmaxCrossEntropyLoss()
        st = ShardedTrainer(net, lambda o, l: loss(o, l), "sgd",
                            {"learning_rate": 0.5},
                            mesh=make_mesh({"dp": 8}),
                            gradient_compression={"type": "2bit",
                                                  "threshold": 1e9})
        p0 = {k: np.asarray(v) for k, v in st.params.items()}
        st.step(X, Y)
        for k, v in st.params.items():
            np.testing.assert_allclose(np.asarray(v), p0[k])

    def test_rejects_with_param_rules(self):
        from mxnet_tpu import gluon
        from mxnet_tpu.gluon import nn as gnn
        from mxnet_tpu.parallel import ShardedTrainer
        from jax.sharding import PartitionSpec

        net = gnn.HybridSequential()
        net.add(gnn.Dense(2))
        net.initialize()
        net(mx.nd.zeros((1, 4)))
        with pytest.raises(Exception):
            ShardedTrainer(net, None, "sgd", {},
                           param_rules=[(".*", PartitionSpec("tp"))],
                           gradient_compression={"type": "2bit"})
