"""Registry-wide operator coverage: the audit gate + the smoke/oracle
cases that close it.

Round-3 finding: 374 registered ops but no proof each is exercised.
The reference keeps breadth honest with one gigantic test file
(tests/python/unittest/test_operator.py, 6,785 LoC); the TPU-native
equivalent is this gate:

  test_registry_audit — every op in registry.list_ops() must be
  (a) named somewhere in the test corpus (word match over tests/*.py),
  (b) share its fn with a named op (alias closure),
  (c) have a CASES entry here (executed by test_case below), or
  (d) appear in CREDIT (covered by a named test under a frontend
      spelling) or EXEMPT (justified, kept tiny).

CASES are not mere smokes where an independent numpy oracle is cheap:
elementwise/scalar/broadcast families all assert exact values; LRN /
UpSampling / Correlation / count_sketch / Deconvolution get dedicated
oracle tests below (reference: src/operator/correlation.cc, lrn.cc,
nn/upsampling.cc, contrib/count_sketch.cc, nn/deconvolution.cc).
"""
import glob
import math
import os
import re

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.ndarray.ndarray import invoke
from mxnet_tpu.ops import registry as R

RNG = np.random.RandomState(3)


def run(name, arrays, params=None):
    outs = invoke(R.get(name), [nd.array(a) for a in arrays],
                  dict(params or {}))
    return [o.asnumpy() for o in outs]


def ocheck(out, exp, atol=1e-4):
    out = np.asarray(out, dtype="float64")
    exp = np.asarray(exp, dtype="float64")
    assert out.shape == exp.shape, (out.shape, exp.shape)
    assert np.allclose(out, exp, atol=atol, rtol=1e-4)


CASES = {}


def case(name):
    def deco(fn):
        assert name not in CASES, name
        CASES[name] = fn
        return fn
    return deco


def table_case(name, fn):
    assert name not in CASES, name
    CASES[name] = fn


# ---------------------------------------------------------------------------
# scalar elementwise (reference: elemwise_binary_scalar_op_basic.cc)
# ---------------------------------------------------------------------------
_X = RNG.rand(3, 4).astype("float32") + 0.5  # positive: safe for mod/pow
_S = 2.5

_SCALAR = {
    "_plus_scalar": lambda x, s: x + s,
    "_PlusScalar": lambda x, s: x + s,
    "_minus_scalar": lambda x, s: x - s,
    "_MinusScalar": lambda x, s: x - s,
    "_rminus_scalar": lambda x, s: s - x,
    "_mul_scalar": lambda x, s: x * s,
    "_MulScalar": lambda x, s: x * s,
    "_div_scalar": lambda x, s: x / s,
    "_DivScalar": lambda x, s: x / s,
    "_rdiv_scalar": lambda x, s: s / x,
    "_mod_scalar": lambda x, s: np.mod(x, s),
    "_rmod_scalar": lambda x, s: np.mod(s, x),
    "_power_scalar": lambda x, s: np.power(x, s),
    "_rpower_scalar": lambda x, s: np.power(s, x),
    "_maximum_scalar": lambda x, s: np.maximum(x, s),
    "_minimum_scalar": lambda x, s: np.minimum(x, s),
    "_hypot_scalar": lambda x, s: np.hypot(x, s),
    "_equal_scalar": lambda x, s: (x == s),
    "_not_equal_scalar": lambda x, s: (x != s),
    "_greater_scalar": lambda x, s: (x > s),
    "_greater_equal_scalar": lambda x, s: (x >= s),
    "_lesser_scalar": lambda x, s: (x < s),
    "_lesser_equal_scalar": lambda x, s: (x <= s),
    "_logical_and_scalar": lambda x, s: np.logical_and(x, s),
    "_logical_or_scalar": lambda x, s: np.logical_or(x, s),
    "_logical_xor_scalar": lambda x, s: np.logical_xor(x != 0, s != 0),
    "_scatter_plus_scalar": lambda x, s: x + s,
    "_scatter_minus_scalar": lambda x, s: x - s,
}
for _n, _f in _SCALAR.items():
    if _n in R.list_ops():
        table_case(_n, lambda n=_n, f=_f: ocheck(
            run(n, [_X], {"scalar": _S})[0], f(_X, _S)))

# ---------------------------------------------------------------------------
# binary (broadcast) elementwise
# ---------------------------------------------------------------------------
_A = RNG.rand(3, 4).astype("float32") + 0.5
_B = RNG.rand(3, 4).astype("float32") + 0.5
_B1 = RNG.rand(1, 4).astype("float32") + 0.5  # broadcasting rhs

_BINARY = {
    "_mod": (lambda a, b: np.mod(a, b), _B),
    "_grad_add": (lambda a, b: a + b, _B),
    "_equal": (lambda a, b: a == b, _A),       # equal on same array: 1s
    "_not_equal": (lambda a, b: a != b, _B),
    "_greater": (lambda a, b: a > b, _B),
    "_greater_equal": (lambda a, b: a >= b, _B),
    "_lesser": (lambda a, b: a < b, _B),
    "_lesser_equal": (lambda a, b: a <= b, _B),
    "broadcast_mod": (lambda a, b: np.mod(a, b), _B1),
    "broadcast_equal": (lambda a, b: a == b, _B1),
    "broadcast_not_equal": (lambda a, b: a != b, _B1),
    "broadcast_greater": (lambda a, b: a > b, _B1),
    "broadcast_greater_equal": (lambda a, b: a >= b, _B1),
    "broadcast_lesser": (lambda a, b: a < b, _B1),
    "broadcast_lesser_equal": (lambda a, b: a <= b, _B1),
    "broadcast_logical_and": (lambda a, b: np.logical_and(a, b), _B1),
    "broadcast_logical_or": (lambda a, b: np.logical_or(a, b), _B1),
    "broadcast_logical_xor": (
        lambda a, b: np.logical_xor(a != 0, b != 0), _B1),
    "_scatter_elemwise_div": (lambda a, b: a / b, _B),
}
for _n, (_f, _rhs) in _BINARY.items():
    table_case(_n, lambda n=_n, f=_f, rhs=_rhs: ocheck(
        run(n, [_A, rhs])[0], f(_A, rhs)))

# ---------------------------------------------------------------------------
# unary elementwise / reductions
# ---------------------------------------------------------------------------
_U = (RNG.rand(3, 4).astype("float32") - 0.5) * 1.6  # in (-0.8, 0.8)


def _softmin(x):
    e = np.exp(-x - (-x).max(-1, keepdims=True))
    return e / e.sum(-1, keepdims=True)


_UNARY = {
    "fix": np.fix,
    "floor": np.floor,
    "rint": np.rint,
    "trunc": np.trunc,
    "degrees": np.degrees,
    "radians": np.radians,
    "logical_not": np.logical_not,
    "ones_like": np.ones_like,
    "softmin": _softmin,
    "cumsum": lambda x: np.cumsum(x, axis=None).astype("float32"),
    "logsumexp": lambda x: np.log(np.exp(x).sum()),
    "nanprod": lambda x: np.nanprod(x),
    "shape_array": lambda x: np.array(x.shape, dtype="int64"),
    "size_array": lambda x: np.array([x.size], dtype="int64"),
    "smooth_l1": lambda x: np.where(np.abs(x) < 1, 0.5 * x * x,
                                    np.abs(x) - 0.5),
    "_contrib_div_sqrt_dim": lambda x: x / np.sqrt(x.shape[-1]),
}
for _n, _f in _UNARY.items():
    table_case(_n, lambda n=_n, f=_f: ocheck(run(n, [_U])[0], f(_U)))


@case("erfinv")
def _case_erfinv():
    out = run("erfinv", [_U])[0]
    back = np.vectorize(math.erf)(out.astype("float64"))
    ocheck(back, _U, atol=1e-3)


@case("diag")
def _case_diag():
    m = RNG.rand(4, 4).astype("float32")
    ocheck(run("diag", [m])[0], np.diag(m))
    ocheck(run("diag", [m], {"k": 1})[0], np.diag(m, k=1))


@case("argmax_channel")
def _case_argmax_channel():
    m = RNG.rand(5, 7).astype("float32")
    ocheck(run("argmax_channel", [m])[0], m.argmax(axis=1))


# ---------------------------------------------------------------------------
# creation ops
# ---------------------------------------------------------------------------
@case("_ones")
def _case_ones():
    ocheck(run("_ones", [], {"shape": (2, 3)})[0], np.ones((2, 3)))


@case("_zeros")
def _case_zeros():
    ocheck(run("_zeros", [], {"shape": (2, 3)})[0], np.zeros((2, 3)))


@case("ones_op")
def _case_ones_op():
    ocheck(run("ones_op", [], {"shape": (4,)})[0], np.ones((4,)))


@case("zeros_op")
def _case_zeros_op():
    ocheck(run("zeros_op", [], {"shape": (4,)})[0], np.zeros((4,)))


@case("_full")
def _case_full():
    ocheck(run("_full", [], {"shape": (2, 2), "value": 7.0})[0],
           np.full((2, 2), 7.0))


@case("_arange")
def _case_arange():
    ocheck(run("_arange", [], {"start": 2.0, "stop": 8.0, "step": 1.5})[0],
           np.arange(2.0, 8.0, 1.5, dtype="float32"))


@case("khatri_rao")
def _case_khatri_rao():
    a = RNG.rand(2, 3).astype("float32")
    b = RNG.rand(4, 3).astype("float32")
    exp = np.stack([np.kron(a[:, i], b[:, i]) for i in range(3)], axis=1)
    ocheck(run("khatri_rao", [a, b])[0], exp)


# ---------------------------------------------------------------------------
# shape / indexing ops
# ---------------------------------------------------------------------------
@case("broadcast_axis")
def _case_broadcast_axis():
    x = RNG.rand(1, 4).astype("float32")
    ocheck(run("broadcast_axis", [x], {"axis": 0, "size": 3})[0],
           np.broadcast_to(x, (3, 4)))


@case("broadcast_like")
def _case_broadcast_like():
    x = RNG.rand(1, 4).astype("float32")
    y = np.zeros((5, 4), "float32")
    ocheck(run("broadcast_like", [x, y])[0], np.broadcast_to(x, (5, 4)))


@case("reshape_like")
def _case_reshape_like():
    x = RNG.rand(2, 6).astype("float32")
    ocheck(run("reshape_like", [x, np.zeros((3, 4), "float32")])[0],
           x.reshape(3, 4))


@case("slice_like")
def _case_slice_like():
    x = RNG.rand(4, 6).astype("float32")
    ocheck(run("slice_like", [x, np.zeros((2, 3), "float32")])[0],
           x[:2, :3])


@case("pick")
def _case_pick():
    x = RNG.rand(4, 5).astype("float32")
    idx = np.array([0, 2, 4, 1], "float32")
    ocheck(run("pick", [x, idx])[0],
           x[np.arange(4), idx.astype(int)])


@case("batch_take")
def _case_batch_take():
    x = RNG.rand(4, 5).astype("float32")
    idx = np.array([1, 0, 3, 2], "float32")
    ocheck(run("batch_take", [x, idx])[0],
           x[np.arange(4), idx.astype(int)])


@case("cast_storage")
def _case_cast_storage():
    x = RNG.rand(3, 3).astype("float32")
    ocheck(run("cast_storage", [x], {"stype": "default"})[0], x)


@case("depth_to_space")
def _case_depth_space():
    x = RNG.rand(2, 8, 3, 3).astype("float32")
    d2s = run("depth_to_space", [x], {"block_size": 2})[0]
    assert d2s.shape == (2, 2, 6, 6)
    back = run("space_to_depth", [d2s], {"block_size": 2})[0]
    ocheck(back, x)  # exact roundtrip pins both layouts


CASES["space_to_depth"] = _case_depth_space


@case("ravel_multi_index")
def _case_ravel():
    idx = np.array([[1, 2, 0], [3, 1, 4]], "float32")  # (ndim=2, n)
    out = run("ravel_multi_index", [idx], {"shape": (4, 5)})[0]
    ocheck(out, np.ravel_multi_index(idx.astype(int), (4, 5)))
    back = run("unravel_index", [out], {"shape": (4, 5)})[0]
    ocheck(back, idx)


CASES["unravel_index"] = _case_ravel


@case("_slice_assign_scalar")
def _case_slice_assign_scalar():
    x = np.zeros((4, 4), "float32")
    out = run("_slice_assign_scalar", [x],
              {"scalar": 5.0, "begin": (1, 1), "end": (3, 3)})[0]
    exp = x.copy()
    exp[1:3, 1:3] = 5.0
    ocheck(out, exp)


@case("_crop_assign_scalar")
def _case_crop_assign_scalar():
    x = np.ones((3, 3), "float32")
    out = run("_crop_assign_scalar", [x],
              {"scalar": -1.0, "begin": (0, 0), "end": (2, 2)})[0]
    exp = x.copy()
    exp[:2, :2] = -1.0
    ocheck(out, exp)


@case("_scatter_set_nd")
def _case_scatter_set_nd():
    lhs = np.zeros((3, 3), "float32")
    indices = np.array([[0, 2], [1, 0]], "float32")  # (ndim, n)
    rhs = np.array([9.0, 8.0], "float32")
    out = run("_scatter_set_nd", [lhs, indices, rhs],
              {"shape": (3, 3)})[0]
    exp = lhs.copy()
    exp[0, 1] = 9.0
    exp[2, 0] = 8.0
    ocheck(out, exp)


@case("_identity_with_attr_like_rhs")
def _case_identity_like_rhs():
    a = RNG.rand(3,).astype("float32")
    ocheck(run("_identity_with_attr_like_rhs",
               [a, np.zeros((3,), "float32")])[0], a)


@case("_CrossDeviceCopy")
def _case_cross_device_copy():
    a = RNG.rand(2, 2).astype("float32")
    ocheck(run("_CrossDeviceCopy", [a])[0], a)


@case("add_n")
def _case_add_n():
    xs = [RNG.rand(2, 3).astype("float32") for _ in range(3)]
    ocheck(run("add_n", xs, {"num_args": 3})[0], sum(xs))
    ocheck(run("ElementWiseSum", xs, {"num_args": 3})[0], sum(xs))


CASES["ElementWiseSum"] = _case_add_n


@case("_sparse_retain")
def _case_sparse_retain():
    x = RNG.rand(5, 3).astype("float32")
    out = run("_sparse_retain", [x, np.array([0, 3], "float32")])[0]
    exp = np.zeros_like(x)
    exp[[0, 3]] = x[[0, 3]]
    ocheck(out, exp)


# ---------------------------------------------------------------------------
# legacy nn heads / normalizers (reference: src/operator/*-inl.h)
# ---------------------------------------------------------------------------
@case("LinearRegressionOutput")
def _case_linreg():
    d = RNG.rand(4, 3).astype("float32")
    lbl = RNG.rand(4, 3).astype("float32")
    ocheck(run("LinearRegressionOutput", [d, lbl])[0], d)  # fwd=identity


@case("MAERegressionOutput")
def _case_mae():
    d = RNG.rand(4, 3).astype("float32")
    ocheck(run("MAERegressionOutput", [d, np.zeros_like(d)])[0], d)


@case("LogisticRegressionOutput")
def _case_logistic():
    d = _U
    ocheck(run("LogisticRegressionOutput", [d, np.zeros_like(d)])[0],
           1.0 / (1.0 + np.exp(-d)))


@case("MakeLoss")
def _case_makeloss():
    ocheck(run("MakeLoss", [_X])[0], _X)
    ocheck(run("make_loss", [_X])[0], _X)


CASES["make_loss"] = _case_makeloss


@case("IdentityAttachKLSparseReg")
def _case_kl_reg():
    ocheck(run("IdentityAttachKLSparseReg", [_X])[0], _X)


@case("SoftmaxActivation")
def _case_softmax_act():
    d = RNG.rand(4, 5).astype("float32")
    e = np.exp(d - d.max(-1, keepdims=True))
    ocheck(run("SoftmaxActivation", [d])[0], e / e.sum(-1, keepdims=True))


@case("LeakyReLU")
def _case_leaky():
    d = _U
    ocheck(run("LeakyReLU", [d], {"act_type": "leaky", "slope": 0.1})[0],
           np.where(d > 0, d, 0.1 * d))


@case("InstanceNorm")
def _case_instancenorm():
    d = RNG.rand(2, 3, 4, 4).astype("float32")
    gamma = np.ones((3,), "float32")
    beta = np.zeros((3,), "float32")
    out = run("InstanceNorm", [d, gamma, beta], {"eps": 1e-5})[0]
    mean = d.mean(axis=(2, 3), keepdims=True)
    var = d.var(axis=(2, 3), keepdims=True)
    ocheck(out, (d - mean) / np.sqrt(var + 1e-5), atol=1e-3)


@case("L2Normalization")
def _case_l2norm():
    d = RNG.rand(3, 8).astype("float32")
    norm = np.sqrt((d * d).sum(axis=1, keepdims=True) + 1e-10)
    ocheck(run("L2Normalization", [d])[0], d / norm)


# ---------------------------------------------------------------------------
# random samplers: domain/shape checks (values are PRNG-dependent)
# ---------------------------------------------------------------------------
def _sampler_case(name, params, check):
    def _run():
        out = run(name, [], dict(params, shape=(200,)))[0]
        assert out.shape == (200,)
        assert np.isfinite(out.astype("float64")).all()
        assert check(out), name
    return _run


for _n, _p, _c in [
    ("_random_exponential", {"lam": 2.0}, lambda o: (o >= 0).all()),
    ("_random_gamma", {"alpha": 3.0, "beta": 1.0}, lambda o: (o > 0).all()),
    ("_random_poisson", {"lam": 4.0},
     lambda o: (o >= 0).all() and np.allclose(o, np.round(o))),
    ("_random_negative_binomial", {"k": 3, "p": 0.5},
     lambda o: (o >= 0).all() and np.allclose(o, np.round(o))),
    ("_random_generalized_negative_binomial",
     {"mu": 2.0, "alpha": 0.5}, lambda o: (o >= 0).all()),
    ("bernoulli", {"prob": 0.3},
     lambda o: set(np.unique(o)) <= {0.0, 1.0}),
]:
    table_case(_n, _sampler_case(_n, _p, _c))
    plain = _n.lstrip("_")
    if plain != _n and plain in R.list_ops() and plain not in CASES:
        table_case(plain, _sampler_case(plain, _p, _c))


@case("_sample_normal")
def _case_sample_normal():
    mu = np.array([0.0, 100.0], "float32")
    sigma = np.array([1.0, 1.0], "float32")
    out = run("_sample_normal", [mu, sigma], {"shape": (500,)})[0]
    assert out.shape == (2, 500)
    assert abs(out[0].mean()) < 1.0 and abs(out[1].mean() - 100.0) < 1.0


CASES["sample_normal"] = _case_sample_normal


@case("_sample_uniform")
def _case_sample_uniform():
    low = np.array([0.0, 10.0], "float32")
    high = np.array([1.0, 20.0], "float32")
    out = run("_sample_uniform", [low, high], {"shape": (300,)})[0]
    assert out.shape == (2, 300)
    assert (out[0] >= 0).all() and (out[0] <= 1).all()
    assert (out[1] >= 10).all() and (out[1] <= 20).all()


CASES["sample_uniform"] = _case_sample_uniform


@case("_sample_generalized_negative_binomial")
def _case_sample_gnb():
    mu = np.array([2.0], "float32")
    alpha = np.array([0.5], "float32")
    out = run("_sample_generalized_negative_binomial", [mu, alpha],
              {"shape": (100,)})[0]
    assert out.shape == (1, 100) and (out >= 0).all()


@case("_sample_multinomial")
def _case_sample_multinomial():
    probs = np.array([[0.1, 0.0, 0.9], [0.5, 0.5, 0.0]], "float32")
    out = run("_sample_multinomial", [probs], {"shape": (50,)})[0]
    assert out.shape == (2, 50)
    assert (out[0] != 1).all() and (out[1] != 2).all()  # zero-prob bins
    assert ((out >= 0) & (out <= 2)).all()


CASES["sample_multinomial"] = _case_sample_multinomial


# ---------------------------------------------------------------------------
# fused optimizer update ops
# ---------------------------------------------------------------------------
@case("ftml_update")
def _case_ftml():
    w = RNG.rand(4,).astype("float32")
    g = RNG.rand(4,).astype("float32")
    z = np.zeros((4,), "float32")
    outs = run("ftml_update", [w, g, z.copy(), z.copy(), z.copy()],
               {"lr": 0.1, "t": 1})
    assert len(outs) >= 1 and outs[0].shape == w.shape
    assert np.isfinite(outs[0]).all() and not np.allclose(outs[0], w)


@case("mp_sgd_mom_update")
def _case_mp_sgd():
    w32 = RNG.rand(4,).astype("float32")
    w16 = w32.astype("float16")
    g = np.ones((4,), "float16")
    mom = np.zeros((4,), "float32")
    outs = run("mp_sgd_mom_update", [w16, g, mom, w32],
               {"lr": 0.1, "momentum": 0.9})
    # plain SGD step 1: w - lr*g (momentum buffer starts at 0)
    ocheck(outs[0].astype("float32"), (w32 - 0.1).astype("float16"),
           atol=1e-2)


@case("rmspropalex_update")
def _case_rmspropalex():
    w = RNG.rand(4,).astype("float32")
    g = RNG.rand(4,).astype("float32")
    z = np.zeros((4,), "float32")
    outs = run("rmspropalex_update", [w, g, z.copy(), z.copy(), z.copy()],
               {"lr": 0.05})
    assert np.isfinite(outs[0]).all() and not np.allclose(outs[0], w)


@case("_sparse_adagrad_update")
def _case_sparse_adagrad():
    w = RNG.rand(4, 2).astype("float32")
    g = RNG.rand(4, 2).astype("float32")
    h = np.zeros((4, 2), "float32")
    outs = run("_sparse_adagrad_update", [w, g, h], {"lr": 0.1})
    assert np.isfinite(outs[0]).all() and not np.allclose(outs[0], w)


# ---------------------------------------------------------------------------
# int8 tail (quantize/dequantize/requantize cores are in test_int8.py)
# ---------------------------------------------------------------------------
@case("_contrib_quantized_act")
def _case_quantized_act():
    d = ((RNG.rand(2, 4) - 0.5) * 254).astype("int8").astype("float32")
    mn, mx_ = np.array([-1.0], "float32"), np.array([1.0], "float32")
    out, omin, omax = run("_contrib_quantized_act", [d, mn, mx_])
    ocheck(out, np.maximum(d, 0))
    assert float(omin[0]) == 0.0 and float(omax[0]) == 1.0


@case("_contrib_quantized_flatten")
def _case_quantized_flatten():
    d = RNG.rand(2, 3, 4).astype("float32")
    mn, mx_ = np.array([-1.0], "float32"), np.array([1.0], "float32")
    out, omin, omax = run("_contrib_quantized_flatten", [d, mn, mx_])
    ocheck(out, d.reshape(2, 12))
    assert float(omin[0]) == -1.0 and float(omax[0]) == 1.0


@case("_contrib_quantized_fully_connected")
def _case_quantized_fc():
    d = ((RNG.rand(2, 3) - 0.5) * 100).astype("int8")
    w = ((RNG.rand(4, 3) - 0.5) * 100).astype("int8")
    b = np.zeros((4,), "int8")
    rng_ = np.array([-1.0], "float32"), np.array([1.0], "float32")
    outs = run("_contrib_quantized_fully_connected",
               [d, w, b, rng_[0], rng_[1], rng_[0], rng_[1],
                rng_[0], rng_[1]], {"num_hidden": 4})
    # int8×int8 accumulates exactly in int32
    ocheck(outs[0].astype("float64"),
           d.astype("int32") @ w.astype("int32").T)


@case("_contrib_requantize")
def _case_requantize():
    d = np.array([[1000, -2000, 30000]], "float32")  # int32 domain
    mn = np.array([-3.0], "float32")
    mx_ = np.array([3.0], "float32")
    out, omin, omax = run("_contrib_requantize", [d, mn, mx_],
                          {"min_calib_range": -1.0,
                           "max_calib_range": 1.0})
    assert out.dtype == np.int8 or np.abs(out).max() <= 127


@case("_contrib_int8_fc")
def _case_int8_fc():
    d = RNG.rand(2, 3).astype("float32")
    w = RNG.rand(4, 3).astype("float32")
    out = run("_contrib_int8_fc", [d, w],
              {"amax_data": 1.0, "num_hidden": 4})[0]
    # int8-simulated fc ≈ fp32 fc within quantization error
    ocheck(out, d @ w.T, atol=0.15)


# ---------------------------------------------------------------------------
# control flow op nodes: exercised through the SYMBOL frontends (the
# registered _foreach/_while_loop/_cond graphs are what sym.contrib
# builds — see also test_control_flow.py's eager+symbol suites)
# ---------------------------------------------------------------------------
@case("_foreach")
def _case_foreach_sym():
    d = mx.sym.var("d")
    s = mx.sym.var("s")
    outs, states = mx.sym.contrib.foreach(
        lambda x, st: (x + st[0], [st[0] + 1]), d, [s])
    ex = outs.simple_bind(mx.cpu(), d=(3, 2), s=(2,))
    dv = RNG.rand(3, 2).astype("float32")
    out = ex.forward(d=nd.array(dv), s=nd.zeros((2,)))[0].asnumpy()
    ocheck(out, dv + np.arange(3)[:, None])


@case("_while_loop")
def _case_while_sym():
    s = mx.sym.var("s")
    outs, states = mx.sym.contrib.while_loop(
        lambda st: mx.sym.sum(st[0]) < 10,
        lambda st: ([st[0]], [st[0] + 1]),
        [s], max_iterations=20)
    ex = states[0].simple_bind(mx.cpu(), s=(1,))
    out = ex.forward(s=nd.zeros((1,)))[0].asnumpy()
    assert float(out[0]) == 10.0


@case("_cond")
def _case_cond_sym():
    p = mx.sym.var("p")
    x = mx.sym.var("x")
    out = mx.sym.contrib.cond(p > 0, lambda: x * 2, lambda: x - 1)
    ex = out.simple_bind(mx.cpu(), p=(1,), x=(3,))
    xv = RNG.rand(3).astype("float32")
    o1 = ex.forward(p=nd.ones((1,)), x=nd.array(xv))[0].asnumpy()
    ocheck(o1, xv * 2)
    o2 = ex.forward(p=nd.zeros((1,)) - 1, x=nd.array(xv))[0].asnumpy()
    ocheck(o2, xv - 1)


# ---------------------------------------------------------------------------
# dedicated oracle tests (round-3 audit's named gaps)
# ---------------------------------------------------------------------------
def test_lrn_oracle():
    """LRN vs a direct numpy implementation of the reference formula
    (src/operator/lrn.cc): out = x / (knorm + alpha/n * sum_win x²)^beta."""
    x = RNG.rand(2, 7, 3, 3).astype("float32")
    nsize, alpha, beta, knorm = 5, 1e-2, 0.75, 2.0
    out = run("LRN", [x], {"nsize": nsize, "alpha": alpha, "beta": beta,
                           "knorm": knorm})[0]
    exp = np.empty_like(x)
    half = nsize // 2
    for c in range(7):
        lo, hi = max(0, c - half), min(7, c + half + 1)
        acc = (x[:, lo:hi] ** 2).sum(axis=1)
        exp[:, c] = x[:, c] / (knorm + alpha / nsize * acc) ** beta
    ocheck(out, exp, atol=1e-4)


def test_upsampling_oracle():
    """UpSampling nearest vs np.repeat (reference nn/upsampling.cc)."""
    x = RNG.rand(2, 3, 4, 4).astype("float32")
    out = run("UpSampling", [x], {"scale": 2})[0]
    ocheck(out, x.repeat(2, axis=2).repeat(2, axis=3))


def test_correlation_oracle():
    """Correlation vs a naive displacement/patch loop (reference
    src/operator/correlation.cc semantics)."""
    n, c, h, w = 1, 2, 8, 8
    a = RNG.rand(n, c, h, w).astype("float32")
    b = RNG.rand(n, c, h, w).astype("float32")
    k, d, s1, s2, pad = 3, 2, 1, 1, 2
    out = run("Correlation", [a, b],
              {"kernel_size": k, "max_displacement": d, "stride1": s1,
               "stride2": s2, "pad_size": pad})[0]
    rad = (k - 1) // 2
    border = d + rad
    hp, wp = h + 2 * pad, w + 2 * pad
    out_h = -(-(hp - 2 * border) // s1)
    out_w = -(-(wp - 2 * border) // s1)
    pa = np.pad(a, ((0, 0), (0, 0), (pad, pad), (pad, pad)))
    pb = np.pad(b, ((0, 0), (0, 0), (pad, pad), (pad, pad)))
    reach = (d // s2) * s2  # reference grid-radius convention
    disp = [(dy, dx) for dy in range(-reach, reach + 1, s2)
            for dx in range(-reach, reach + 1, s2)]
    exp = np.zeros((n, len(disp), out_h, out_w), "float32")
    for q, (dy, dx) in enumerate(disp):
        for u in range(out_h):
            for v in range(out_w):
                i0, j0 = border + u * s1, border + v * s1
                pa_patch = pa[:, :, i0 - rad:i0 + rad + 1,
                              j0 - rad:j0 + rad + 1]
                pb_patch = pb[:, :, i0 + dy - rad:i0 + dy + rad + 1,
                              j0 + dx - rad:j0 + dx + rad + 1]
                exp[:, q, u, v] = (pa_patch * pb_patch).sum(
                    axis=(1, 2, 3)) / (k * k * c)
    assert out.shape == exp.shape
    ocheck(out, exp, atol=1e-4)
    # abs-difference mode
    out2 = run("Correlation", [a, b],
               {"kernel_size": 1, "max_displacement": 1, "pad_size": 1,
                "is_multiply": False})[0]
    assert out2.shape[1] == 9 and (out2 >= 0).all()
    # indivisible max_displacement rounds the grid DOWN (reference:
    # neighborhood_grid_radius = max_displacement // stride2) while the
    # output geometry keeps the full displacement border
    out3 = run("Correlation", [a, b],
               {"kernel_size": 1, "max_displacement": 3, "stride2": 2,
                "pad_size": 3})[0]
    assert out3.shape[1] == 9  # grid {-2,0,2}² not {-3,-1,1,3}²


def test_count_sketch_oracle():
    """count_sketch vs a scatter-add loop (contrib/count_sketch.cc)."""
    bsz, in_dim, out_dim = 3, 10, 6
    data = RNG.rand(bsz, in_dim).astype("float32")
    h = RNG.randint(0, out_dim, size=(in_dim,)).astype("float32")
    s = (RNG.randint(0, 2, size=(in_dim,)) * 2 - 1).astype("float32")
    out = run("_contrib_count_sketch", [data, h, s],
              {"out_dim": out_dim})[0]
    exp = np.zeros((bsz, out_dim), "float32")
    for j in range(in_dim):
        exp[:, int(h[j])] += s[j] * data[:, j]
    ocheck(out, exp)


def test_deconvolution_oracle():
    """Deconvolution vs a naive transposed-conv loop (weight layout
    (in_channels, num_filter, kH, kW) — nn/deconvolution.cc)."""
    n, cin, cout, h, w, k = 1, 2, 3, 4, 4, 3
    x = RNG.rand(n, cin, h, w).astype("float32")
    wt = RNG.rand(cin, cout, k, k).astype("float32")
    out = run("Deconvolution", [x, wt],
              {"kernel": (k, k), "num_filter": cout})[0]
    exp = np.zeros((n, cout, h + k - 1, w + k - 1), "float32")
    for c in range(cin):
        for f in range(cout):
            for y in range(h):
                for xx in range(w):
                    exp[:, f, y:y + k, xx:xx + k] += (
                        x[:, c, y, xx, None, None] * wt[c, f])
    assert out.shape == exp.shape
    ocheck(out, exp, atol=1e-3)
    # stride-2 output size follows the reference formula
    out2 = run("Deconvolution", [x, wt],
               {"kernel": (k, k), "num_filter": cout, "stride": (2, 2)})[0]
    assert out2.shape == (n, cout, 2 * (h - 1) + k, 2 * (w - 1) + k)


CASES["Correlation"] = test_correlation_oracle
CASES["_contrib_count_sketch"] = test_count_sketch_oracle
CASES["LRN"] = test_lrn_oracle
CASES["UpSampling"] = test_upsampling_oracle
CASES["Deconvolution"] = test_deconvolution_oracle


# ---------------------------------------------------------------------------
# the gate
# ---------------------------------------------------------------------------
# covered by a named test under a frontend spelling (each entry names
# the proof so the claim is checkable)
CREDIT = {}

# justified exemptions — keep under 10 (round-3 audit target)
EXEMPT = {
    # none currently: every registered op is exercised somewhere.
}


@pytest.mark.parametrize("name", sorted(CASES))
def test_case(name):
    CASES[name]()


def test_registry_audit():
    """Every registered op is exercised by at least one test: named in
    the corpus, alias of a named op, CASES here, or CREDIT/EXEMPT."""
    corpus = ""
    here = os.path.dirname(os.path.abspath(__file__))
    for f in glob.glob(os.path.join(here, "*.py")):
        if os.path.basename(f) == "test_op_coverage.py":
            continue
        with open(f) as fh:
            corpus += fh.read()
    words = set(re.findall(r"[A-Za-z_][A-Za-z0-9_]*", corpus))

    def name_covered(n):
        if n in words or n in CASES or n in CREDIT or n in EXEMPT:
            return True
        return (n.startswith("_contrib_")
                and n[len("_contrib_"):] in words)

    ops = sorted(R.list_ops())
    fams = {}
    for n in ops:
        fams.setdefault(id(R.get(n).fn), []).append(n)
    missing = []
    for names in fams.values():
        if not any(name_covered(n) for n in names):
            missing.extend(names)
    assert not missing, (
        "untested ops (add a CASES entry in test_op_coverage.py): %s"
        % sorted(missing))
    assert len(EXEMPT) < 10
