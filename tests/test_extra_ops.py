"""Breadth-op tests: fused optimizer updates vs numpy oracles,
distribution samplers, misc tensor ops, LibSVMIter.

Reference: optimizer_op.cc update formulas, sample_op.cc,
tensor extras, src/io/iter_libsvm.cc.
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd


class TestOptimizerUpdateOps:
    def test_sgd_update(self):
        w = np.array([1.0, -2.0], "f")
        g = np.array([0.5, 0.5], "f")
        out = nd.sgd_update(nd.array(w), nd.array(g), lr=0.1, wd=0.01)
        ref = w - 0.1 * (g + 0.01 * w)
        np.testing.assert_allclose(out.asnumpy(), ref, rtol=1e-6)

    def test_sgd_mom_update_mutates_mom(self):
        w = nd.array(np.ones(3, "f"))
        g = nd.array(np.full(3, 0.5, "f"))
        mom = nd.zeros((3,))
        out = nd.sgd_mom_update(w, g, mom, lr=0.1, momentum=0.9)
        np.testing.assert_allclose(out.asnumpy(), 1 - 0.05, rtol=1e-6)
        np.testing.assert_allclose(mom.asnumpy(), -0.05, rtol=1e-6)
        out2 = nd.sgd_mom_update(out, g, mom, lr=0.1, momentum=0.9)
        # mom' = 0.9*(-0.05) - 0.05 = -0.095
        np.testing.assert_allclose(mom.asnumpy(), -0.095, rtol=1e-5)

    def test_adam_update_oracle(self):
        rng = np.random.RandomState(0)
        w = rng.randn(4).astype("f")
        g = rng.randn(4).astype("f")
        m = np.zeros(4, "f")
        v = np.zeros(4, "f")
        mn, vn = nd.array(m), nd.array(v)
        out = nd.adam_update(nd.array(w), nd.array(g), mn, vn, lr=0.01)
        m_ref = 0.1 * g
        v_ref = 0.001 * g * g
        ref = w - 0.01 * m_ref / (np.sqrt(v_ref) + 1e-8)
        np.testing.assert_allclose(out.asnumpy(), ref, rtol=1e-5)
        np.testing.assert_allclose(mn.asnumpy(), m_ref, rtol=1e-5)

    def test_mp_sgd_keeps_fp32_master(self):
        w16 = nd.array(np.ones(3, "f")).astype("float16")
        w32 = nd.array(np.ones(3, "f"))
        g = nd.array(np.full(3, 1e-4, "f")).astype("float16")
        out = nd.mp_sgd_update(w16, g, w32, lr=1.0)
        # fp32 master moved by 1e-4 even though fp16 cannot hold 1-1e-4
        np.testing.assert_allclose(w32.asnumpy(), 1 - 1e-4, rtol=1e-6)
        assert out.dtype == np.float16

    def test_signsgd_and_signum(self):
        w = nd.array(np.zeros(2, "f"))
        g = nd.array(np.array([0.3, -0.7], "f"))
        out = nd.signsgd_update(w, g, lr=0.1)
        np.testing.assert_allclose(out.asnumpy(), [-0.1, 0.1], atol=1e-7)
        mom = nd.zeros((2,))
        out2 = nd.signum_update(w, g, mom, lr=0.1, momentum=0.9)
        np.testing.assert_allclose(out2.asnumpy(), [-0.1, 0.1],
                                   atol=1e-7)

    def test_ftrl_sparsifies(self):
        w = nd.array(np.full(2, 0.5, "f"))
        g = nd.array(np.array([1e-4, 5.0], "f"))
        z = nd.zeros((2,))
        n = nd.zeros((2,))
        out = nd.ftrl_update(w, g, z, n, lr=0.1, lamda1=0.01)
        got = out.asnumpy()
        assert got[0] == 0.0          # |z| <= lambda1 -> exactly zero
        assert got[1] != 0.0

    def test_rmsprop(self):
        w = nd.array(np.ones(2, "f"))
        g = nd.array(np.full(2, 2.0, "f"))
        n = nd.zeros((2,))
        out = nd.rmsprop_update(w, g, n, lr=0.1, gamma1=0.9)
        n_ref = 0.1 * 4.0
        ref = 1 - 0.1 * 2.0 / np.sqrt(n_ref + 1e-8)
        np.testing.assert_allclose(out.asnumpy(), ref, rtol=1e-5)


class TestSamplers:
    def test_sample_exponential_mean(self):
        lam = nd.array(np.array([2.0, 0.5], "f"))
        s = nd.sample_exponential(lam, shape=(4000,)) \
            if hasattr(nd, "sample_exponential") else \
            nd._sample_exponential(lam, shape=(4000,))
        a = s.asnumpy()
        assert a.shape == (2, 4000)
        assert abs(a[0].mean() - 0.5) < 0.08
        assert abs(a[1].mean() - 2.0) < 0.25

    def test_sample_gamma_mean(self):
        alpha = nd.array(np.array([3.0], "f"))
        beta = nd.array(np.array([2.0], "f"))
        s = nd._sample_gamma(alpha, beta, shape=(4000,))
        assert abs(s.asnumpy().mean() - 6.0) < 0.5

    def test_sample_poisson(self):
        lam = nd.array(np.array([4.0], "f"))
        s = nd._sample_poisson(lam, shape=(4000,))
        assert abs(s.asnumpy().mean() - 4.0) < 0.3

    def test_sample_negative_binomial(self):
        k = nd.array(np.array([5.0], "f"))
        p = nd.array(np.array([0.5], "f"))
        s = nd._sample_negative_binomial(k, p, shape=(4000,))
        # mean = k(1-p)/p = 5
        assert abs(s.asnumpy().mean() - 5.0) < 0.6


class TestMiscOps:
    def test_histogram(self):
        x = nd.array(np.array([0.1, 0.4, 0.6, 0.9, 0.9], "f"))
        cnt, edges = nd._histogram(x, bin_cnt=2, range=(0.0, 1.0))
        np.testing.assert_array_equal(cnt.asnumpy(), [2, 3])

    def test_ravel_unravel_roundtrip(self):
        idx = nd.array(np.array([[1, 2], [3, 0]], "f"))  # (ndim=2, N=2)
        flat = nd._ravel_multi_index(idx, shape=(4, 5))
        np.testing.assert_array_equal(flat.asnumpy(), [8, 10])
        back = nd._unravel_index(flat, shape=(4, 5))
        np.testing.assert_array_equal(back.asnumpy(), idx.asnumpy())

    def test_logical_ops(self):
        a = nd.array(np.array([0, 1, 2], "f"))
        b = nd.array(np.array([1, 0, 3], "f"))
        np.testing.assert_array_equal(
            nd._logical_and(a, b).asnumpy(), [0, 0, 1])
        np.testing.assert_array_equal(
            nd._logical_or(a, b).asnumpy(), [1, 1, 1])
        np.testing.assert_array_equal(
            nd._logical_xor(a, b).asnumpy(), [1, 1, 0])

    def test_slice_assign(self):
        x = nd.zeros((4, 4))
        y = nd._slice_assign(x, nd.ones((2, 2)), begin=(1, 1),
                             end=(3, 3))
        got = y.asnumpy()
        assert got[1:3, 1:3].sum() == 4 and got.sum() == 4

    def test_square_sum_and_hard_sigmoid(self):
        x = nd.array(np.array([[1.0, 2.0], [3.0, 4.0]], "f"))
        np.testing.assert_allclose(
            nd._square_sum(x, axis=1).asnumpy(), [5, 25])
        h = nd.hard_sigmoid(nd.array(np.array([-10, 0, 10], "f")))
        np.testing.assert_allclose(h.asnumpy(), [0, 0.5, 1])

    def test_softmax_cross_entropy(self):
        logits = np.array([[10.0, 0.0], [0.0, 10.0]], "f")
        lbl = np.array([0, 1], "f")
        out = nd.softmax_cross_entropy(nd.array(logits), nd.array(lbl))
        assert out.shape == (1,)
        assert float(out.asnumpy()[0]) < 0.01

    def test_bipartite_matching(self):
        score = nd.array(np.array([[0.9, 0.1], [0.2, 0.8]], "f"))
        r, c = nd.contrib.bipartite_matching(score, threshold=0.05)
        np.testing.assert_array_equal(r.asnumpy(), [0, 1])
        np.testing.assert_array_equal(c.asnumpy(), [0, 1])

    def test_image_to_tensor_and_normalize(self):
        img = nd.array((np.ones((4, 5, 3)) * 255).astype("f"))
        t = nd._image_to_tensor(img)
        assert t.shape == (3, 4, 5)
        np.testing.assert_allclose(t.asnumpy(), 1.0)
        nrm = nd._image_normalize(t, mean=(1, 1, 1), std=(0.5, 0.5, 0.5))
        np.testing.assert_allclose(nrm.asnumpy(), 0.0)


class TestLibSVMIter:
    def test_reads_csr_batches(self, tmp_path):
        path = tmp_path / "data.libsvm"
        path.write_text(
            "1 0:1.5 3:2.0\n"
            "0 1:1.0\n"
            "1 2:3.0 4:4.0\n")
        it = mx.io.LibSVMIter(data_libsvm=str(path), data_shape=(5,),
                              batch_size=2, round_batch=True)
        b1 = it.next()
        assert b1.data[0].stype == "csr"
        dense = b1.data[0].asnumpy()
        np.testing.assert_allclose(dense[0], [1.5, 0, 0, 2.0, 0])
        np.testing.assert_allclose(dense[1], [0, 1.0, 0, 0, 0])
        np.testing.assert_array_equal(b1.label[0].asnumpy(), [1, 0])
        b2 = it.next()
        assert b2.pad == 1
        with pytest.raises(StopIteration):
            it.next()
        it.reset()
        assert it.next().label[0].asnumpy()[0] == 1


def test_libsvm_label_file(tmp_path):
    """Separate label_libsvm file -> dense multi-label vectors
    (reference: iter_libsvm.cc label path)."""
    d = tmp_path / "d.libsvm"
    d.write_text("0 0:1.0\n0 1:2.0\n")
    l = tmp_path / "l.libsvm"
    l.write_text("0 0:1.0 2:0.5\n0 1:1.0\n")
    it = mx.io.LibSVMIter(data_libsvm=str(d), data_shape=(3,),
                          label_libsvm=str(l), label_shape=(3,),
                          batch_size=2)
    b = it.next()
    lbl = b.label[0].asnumpy()
    assert lbl.shape == (2, 3)
    np.testing.assert_allclose(lbl[0], [1.0, 0, 0.5])
    np.testing.assert_allclose(lbl[1], [0, 1.0, 0])
    assert it.provide_label[0].shape == (2, 3)
