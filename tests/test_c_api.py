"""C ABI (src/c_api.cc): NDArray handles + MXImperativeInvoke + the
predict API, exercised through ctypes (in-process interpreter) and a
real compiled C host (embedded interpreter).

Reference: include/mxnet/c_api.h (MXNDArray*/MXImperativeInvoke),
amalgamation/c_predict_api.h (MXPred*). SCOPE.md §2 scopes non-Python
frontends out; this is the attach surface a frontend WOULD use, kept
to the generic core the reference's 189 functions decompose into.
"""
import ctypes
import os
import subprocess
import sys

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SO = os.path.join(REPO, "src", "libmxtpu_capi.so")


def _build():
    if not os.path.exists(SO):
        subprocess.run(["make", "-C", os.path.join(REPO, "src"),
                        "capi"], check=False,
                       capture_output=True)
    return os.path.exists(SO)


pytestmark = pytest.mark.skipif(not _build(),
                                reason="capi lib not buildable")


def _lib():
    lib = ctypes.CDLL(SO)
    lib.MXGetLastError.restype = ctypes.c_char_p
    # full argtypes: a bare int (e.g. outs[0]) would otherwise be
    # passed as a truncated 32-bit c_int where a pointer is expected
    lib.MXNDArrayCreate.argtypes = [
        ctypes.POINTER(ctypes.c_int64), ctypes.c_int, ctypes.c_int,
        ctypes.POINTER(ctypes.c_void_p)]
    lib.MXNDArrayFree.argtypes = [ctypes.c_void_p]
    lib.MXNDArrayGetShape.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_int),
        ctypes.POINTER(ctypes.POINTER(ctypes.c_int64))]
    lib.MXNDArraySyncCopyFromCPU.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_size_t]
    lib.MXNDArraySyncCopyToCPU.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_size_t]
    lib.MXImperativeInvoke.argtypes = [
        ctypes.c_char_p, ctypes.c_int,
        ctypes.POINTER(ctypes.c_void_p), ctypes.POINTER(ctypes.c_int),
        ctypes.POINTER(ctypes.POINTER(ctypes.c_void_p)), ctypes.c_int,
        ctypes.POINTER(ctypes.c_char_p),
        ctypes.POINTER(ctypes.c_char_p)]
    lib.MXPredCreate.argtypes = [
        ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int,
        ctypes.POINTER(ctypes.c_char_p),
        ctypes.POINTER(ctypes.POINTER(ctypes.c_int64)),
        ctypes.POINTER(ctypes.c_int), ctypes.POINTER(ctypes.c_void_p)]
    lib.MXPredSetInput.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_void_p,
        ctypes.c_size_t]
    lib.MXPredForward.argtypes = [ctypes.c_void_p]
    lib.MXPredGetOutputShape.argtypes = [
        ctypes.c_void_p, ctypes.c_int,
        ctypes.POINTER(ctypes.POINTER(ctypes.c_int64)),
        ctypes.POINTER(ctypes.c_int)]
    lib.MXPredGetOutput.argtypes = [
        ctypes.c_void_p, ctypes.c_int, ctypes.c_void_p,
        ctypes.c_size_t]
    lib.MXPredFree.argtypes = [ctypes.c_void_p]
    return lib


def test_ndarray_roundtrip_and_invoke():
    lib = _lib()
    ver = ctypes.c_int()
    assert lib.MXGetVersion(ctypes.byref(ver)) == 0 and ver.value > 0

    shape = (ctypes.c_int64 * 2)(2, 3)
    h = ctypes.c_void_p()
    assert lib.MXNDArrayCreate(shape, 2, 0, ctypes.byref(h)) == 0, \
        lib.MXGetLastError()

    data = np.arange(6, dtype=np.float32)
    assert lib.MXNDArraySyncCopyFromCPU(
        h, data.ctypes.data_as(ctypes.c_void_p), data.nbytes) == 0, \
        lib.MXGetLastError()

    ndim = ctypes.c_int()
    pdata = ctypes.POINTER(ctypes.c_int64)()
    assert lib.MXNDArrayGetShape(h, ctypes.byref(ndim),
                                 ctypes.byref(pdata)) == 0
    assert [pdata[i] for i in range(ndim.value)] == [2, 3]

    # invoke a registered op through the generic C entry point
    n_out = ctypes.c_int()
    outs = ctypes.POINTER(ctypes.c_void_p)()
    ins = (ctypes.c_void_p * 1)(h)
    keys = (ctypes.c_char_p * 1)(b"scalar")
    vals = (ctypes.c_char_p * 1)(b"2.5")
    assert lib.MXImperativeInvoke(
        b"_mul_scalar", 1, ins, ctypes.byref(n_out),
        ctypes.byref(outs), 1, keys, vals) == 0, lib.MXGetLastError()
    assert n_out.value == 1

    out_buf = np.empty(6, np.float32)
    assert lib.MXNDArraySyncCopyToCPU(
        outs[0], out_buf.ctypes.data_as(ctypes.c_void_p),
        out_buf.nbytes) == 0
    assert np.allclose(out_buf, data * 2.5)
    lib.MXNDArrayFree(outs[0])
    lib.MXNDArrayFree(h)

    # errors surface with a message, not a crash
    bad = ctypes.c_void_p()
    assert lib.MXNDArrayCreate(shape, 2, 99, ctypes.byref(bad)) == -1
    assert b"dtype" in lib.MXGetLastError()


def test_predict_api(tmp_path):
    # checkpoint a small net the reference way
    data = mx.sym.var("data")
    out = mx.sym.softmax(
        mx.sym.FullyConnected(data, num_hidden=3, name="fc"))
    rng = np.random.RandomState(0)
    params = {"arg:fc_weight": nd.array(rng.rand(3, 4) - 0.5),
              "arg:fc_bias": nd.zeros((3,))}
    sym_path = str(tmp_path / "m-symbol.json")
    par_path = str(tmp_path / "m-0000.params")
    out.save(sym_path)
    nd.save(par_path, params)

    x = rng.rand(2, 4).astype("float32")
    ref = None  # computed below via python for comparison
    ex = out.bind(mx.cpu(), {"data": nd.array(x),
                             "fc_weight": params["arg:fc_weight"],
                             "fc_bias": params["arg:fc_bias"]})
    ref = ex.forward(is_train=False)[0].asnumpy()

    lib = _lib()
    keys = (ctypes.c_char_p * 1)(b"data")
    shp = (ctypes.c_int64 * 2)(2, 4)
    shapes = (ctypes.POINTER(ctypes.c_int64) * 1)(shp)
    ndims = (ctypes.c_int * 1)(2)
    pred = ctypes.c_void_p()
    assert lib.MXPredCreate(sym_path.encode(), par_path.encode(), 1,
                            keys, shapes, ndims,
                            ctypes.byref(pred)) == 0, \
        lib.MXGetLastError()
    assert lib.MXPredSetInput(
        pred, b"data", x.ctypes.data_as(ctypes.c_void_p), x.size) == 0, \
        lib.MXGetLastError()
    assert lib.MXPredForward(pred) == 0, lib.MXGetLastError()

    oshape = ctypes.POINTER(ctypes.c_int64)()
    odim = ctypes.c_int()
    assert lib.MXPredGetOutputShape(pred, 0, ctypes.byref(oshape),
                                    ctypes.byref(odim)) == 0
    shape = tuple(oshape[i] for i in range(odim.value))
    assert shape == (2, 3)
    got = np.empty(shape, np.float32)
    assert lib.MXPredGetOutput(
        pred, 0, got.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        got.size) == 0
    assert np.allclose(got, ref, atol=1e-5)
    lib.MXPredFree(pred)


C_HOST = r"""
#include <stdio.h>
#include <stdint.h>
extern int MXGetVersion(int *);
extern const char *MXGetLastError(void);
extern int MXNDArrayCreate(const int64_t *, int, int, void **);
extern int MXNDArraySyncCopyFromCPU(void *, const void *, size_t);
extern int MXNDArraySyncCopyToCPU(void *, void *, size_t);
extern int MXImperativeInvoke(const char *, int, void **, int *,
                              void ***, int, const char **,
                              const char **);
int main(void) {
  int64_t shape[1] = {4};
  void *h;
  if (MXNDArrayCreate(shape, 1, 0, &h)) {
    fprintf(stderr, "create: %s\n", MXGetLastError());
    return 1;
  }
  float xs[4] = {1, 2, 3, 4};
  if (MXNDArraySyncCopyFromCPU(h, xs, sizeof xs)) return 2;
  void **outs; int n_out;
  const char *k[1] = {"scalar"}; const char *v[1] = {"10"};
  if (MXImperativeInvoke("_plus_scalar", 1, &h, &n_out, &outs,
                         1, k, v)) {
    fprintf(stderr, "invoke: %s\n", MXGetLastError());
    return 3;
  }
  float out[4];
  if (MXNDArraySyncCopyToCPU(outs[0], out, sizeof out)) return 4;
  if (out[0] != 11 || out[3] != 14) return 5;
  printf("C_HOST_OK %g %g\n", out[0], out[3]);
  return 0;
}
"""


def test_embedded_c_host(tmp_path):
    """A real C program links the ABI, embeds the interpreter, and runs
    an op — the path a C++ frontend would take."""
    src = tmp_path / "host.c"
    src.write_text(C_HOST)
    exe = str(tmp_path / "host")
    r = subprocess.run(
        ["gcc", str(src), "-o", exe, "-L" + os.path.join(REPO, "src"),
         "-lmxtpu_capi", "-Wl,-rpath," + os.path.join(REPO, "src")],
        capture_output=True, text=True)
    assert r.returncode == 0, r.stderr
    env = dict(os.environ)
    env["MXTPU_HOME"] = REPO
    env["MXTPU_CAPI_PLATFORM"] = "cpu"
    r = subprocess.run([exe], capture_output=True, text=True,
                       timeout=300, env=env)
    assert r.returncode == 0, (r.stdout, r.stderr)
    assert "C_HOST_OK 11 14" in r.stdout


C_TRAIN_HOST = r"""
#include <stdio.h>
#include <stdlib.h>
#include <stdint.h>

extern int MXSymbolCreateFromFile(const char *path, void **out);
extern int MXSymbolListArguments(void *sym, int *n, const char ***names);
extern int MXTrainerCreate(void *sym, int num_inputs,
                           const char **keys, const int64_t **shapes,
                           const int *ndims, const char *label_name,
                           const char *optimizer, int num_opt,
                           const char **opt_keys, const char **opt_vals,
                           void **out);
extern int MXTrainerStep(void *tr, const float *data, size_t nd,
                         const float *label, size_t nl, float *loss);
extern int MXTrainerSaveParams(void *tr, const char *path);
extern int MXTrainerFree(void *tr);
extern int MXSymbolFree(void *sym);
extern const char *MXGetLastError();

/* deterministic 2-class problem: class = sign of mean(x) */
static void make_batch(unsigned *seed, float *x, float *y, int n, int d) {
  for (int i = 0; i < n; ++i) {
    int cls = (*seed = *seed * 1103515245u + 12345u) >> 30 & 1;
    float base = cls ? 0.5f : -0.5f;
    for (int j = 0; j < d; ++j) {
      *seed = *seed * 1103515245u + 12345u;
      x[i * d + j] = base + ((*seed >> 16 & 0xffff) / 65536.0f - 0.5f);
    }
    y[i] = (float)cls;
  }
}

int main(int argc, char **argv) {
  if (argc < 3) return 10;
  void *sym = NULL, *tr = NULL;
  if (MXSymbolCreateFromFile(argv[1], &sym)) {
    fprintf(stderr, "symbol: %s\n", MXGetLastError());
    return 1;
  }
  int n_args; const char **names;
  if (MXSymbolListArguments(sym, &n_args, &names)) return 2;
  printf("symbol has %d arguments\n", n_args);

  const int N = 32, D = 16;
  const char *keys[2] = {"data", "softmax_label"};
  int64_t dshape[2] = {N, D}, lshape[1] = {N};
  const int64_t *shapes[2] = {dshape, lshape};
  int ndims[2] = {2, 1};
  const char *ok[1] = {"learning_rate"};
  const char *ov[1] = {"0.5"};
  if (MXTrainerCreate(sym, 2, keys, shapes, ndims, "softmax_label",
                      "sgd", 1, ok, ov, &tr)) {
    fprintf(stderr, "trainer: %s\n", MXGetLastError());
    return 3;
  }
  float *x = malloc(N * D * sizeof(float));
  float *y = malloc(N * sizeof(float));
  unsigned seed = 7;
  float first = 0, loss = 0;
  for (int step = 0; step < 30; ++step) {
    make_batch(&seed, x, y, N, D);
    if (MXTrainerStep(tr, x, N * D, y, N, &loss)) {
      fprintf(stderr, "step: %s\n", MXGetLastError());
      return 4;
    }
    if (step == 0) first = loss;
  }
  printf("loss %g -> %g\n", first, loss);
  if (!(loss < 0.5f * first)) return 5;
  if (MXTrainerSaveParams(tr, argv[2])) return 6;
  MXTrainerFree(tr);
  MXSymbolFree(sym);
  printf("C_TRAIN_OK\n");
  free(x); free(y);
  return 0;
}
"""


def test_embedded_c_host_training(tmp_path):
    """A compiled C host builds a symbol from JSON, creates a trainer,
    fits it on synthetic data (loss must halve), and saves params —
    the c_api_symbolic/executor training path (VERDICT r4 #7)."""
    # the network the C host trains: an MLP classifier
    data = mx.sym.Variable("data")
    h = mx.sym.Activation(mx.sym.FullyConnected(data, num_hidden=32),
                          act_type="relu")
    out = mx.sym.SoftmaxOutput(mx.sym.FullyConnected(h, num_hidden=2),
                               mx.sym.Variable("softmax_label"))
    sym_path = tmp_path / "mlp-symbol.json"
    out.save(str(sym_path))

    src = tmp_path / "train_host.c"
    src.write_text(C_TRAIN_HOST)
    exe = str(tmp_path / "train_host")
    r = subprocess.run(
        ["gcc", str(src), "-o", exe, "-L" + os.path.join(REPO, "src"),
         "-lmxtpu_capi", "-Wl,-rpath," + os.path.join(REPO, "src")],
        capture_output=True, text=True)
    assert r.returncode == 0, r.stderr
    env = dict(os.environ)
    env["MXTPU_HOME"] = REPO
    env["MXTPU_CAPI_PLATFORM"] = "cpu"
    params_path = str(tmp_path / "trained.params")
    r = subprocess.run([exe, str(sym_path), params_path],
                       capture_output=True, text=True, timeout=300,
                       env=env)
    assert r.returncode == 0, (r.stdout, r.stderr)
    assert "C_TRAIN_OK" in r.stdout
    # the checkpoint the C host saved loads back in python
    loaded = mx.nd.load(params_path)
    assert any(k.startswith("arg:") for k in loaded)


def test_cached_op_c_abi():
    """Symbol-from-JSON + CachedOp create/invoke through ctypes."""
    lib = _lib()
    lib.MXSymbolCreateFromJSON.argtypes = [
        ctypes.c_char_p, ctypes.POINTER(ctypes.c_void_p)]
    lib.MXCreateCachedOp.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_void_p)]
    lib.MXInvokeCachedOp.argtypes = [
        ctypes.c_void_p, ctypes.c_int, ctypes.POINTER(ctypes.c_void_p),
        ctypes.POINTER(ctypes.c_int),
        ctypes.POINTER(ctypes.POINTER(ctypes.c_void_p))]
    lib.MXFreeCachedOp.argtypes = [ctypes.c_void_p]
    lib.MXSymbolFree.argtypes = [ctypes.c_void_p]

    x = mx.sym.Variable("x")
    sym = 2 * x + 1
    h_sym = ctypes.c_void_p()
    rc = lib.MXSymbolCreateFromJSON(sym.tojson().encode(),
                                    ctypes.byref(h_sym))
    assert rc == 0, lib.MXGetLastError()
    h_op = ctypes.c_void_p()
    assert lib.MXCreateCachedOp(h_sym, ctypes.byref(h_op)) == 0, \
        lib.MXGetLastError()

    shape = (ctypes.c_int64 * 2)(2, 3)
    h_in = ctypes.c_void_p()
    assert lib.MXNDArrayCreate(shape, 2, 0, ctypes.byref(h_in)) == 0
    vals = np.arange(6, dtype=np.float32).reshape(2, 3)
    assert lib.MXNDArraySyncCopyFromCPU(
        h_in, vals.ctypes.data_as(ctypes.c_void_p), vals.nbytes) == 0

    n_out = ctypes.c_int()
    outs = ctypes.POINTER(ctypes.c_void_p)()
    ins = (ctypes.c_void_p * 1)(h_in)
    assert lib.MXInvokeCachedOp(h_op, 1, ins, ctypes.byref(n_out),
                                ctypes.byref(outs)) == 0, \
        lib.MXGetLastError()
    assert n_out.value == 1
    got = np.zeros((2, 3), np.float32)
    assert lib.MXNDArraySyncCopyToCPU(
        outs[0], got.ctypes.data_as(ctypes.c_void_p), got.nbytes) == 0
    assert np.allclose(got, 2 * vals + 1)
    lib.MXFreeCachedOp(h_op)
    lib.MXSymbolFree(h_sym)


def test_kvstore_c_abi():
    """MXKVStore create/init/push/pull through ctypes — the parameter
    exchange a C host drives (reference: c_api.h KVStore surface)."""
    lib = _lib()
    lib.MXKVStoreCreate.argtypes = [
        ctypes.c_char_p, ctypes.POINTER(ctypes.c_void_p)]
    lib.MXKVStoreInit.argtypes = [
        ctypes.c_void_p, ctypes.c_int, ctypes.c_void_p]
    lib.MXKVStorePush.argtypes = [
        ctypes.c_void_p, ctypes.c_int, ctypes.c_void_p]
    lib.MXKVStorePull.argtypes = [
        ctypes.c_void_p, ctypes.c_int, ctypes.c_void_p]
    lib.MXKVStoreFree.argtypes = [ctypes.c_void_p]

    kv = ctypes.c_void_p()
    assert lib.MXKVStoreCreate(b"local", ctypes.byref(kv)) == 0, \
        lib.MXGetLastError()

    def mk(vals):
        shape = (ctypes.c_int64 * 1)(len(vals))
        h = ctypes.c_void_p()
        assert lib.MXNDArrayCreate(shape, 1, 0, ctypes.byref(h)) == 0
        a = np.asarray(vals, np.float32)
        assert lib.MXNDArraySyncCopyFromCPU(
            h, a.ctypes.data_as(ctypes.c_void_p), a.nbytes) == 0
        return h

    assert lib.MXKVStoreInit(kv, 3, mk([0.0, 0.0, 0.0])) == 0, \
        lib.MXGetLastError()
    # push stores the merged value (reference kvstore_local PushImpl);
    # a second push overwrites
    assert lib.MXKVStorePush(kv, 3, mk([1.0, 2.0, 3.0])) == 0, \
        lib.MXGetLastError()
    assert lib.MXKVStorePush(kv, 3, mk([10.0, 20.0, 30.0])) == 0

    out = mk([0.0, 0.0, 0.0])
    assert lib.MXKVStorePull(kv, 3, out) == 0, lib.MXGetLastError()
    got = np.zeros(3, np.float32)
    assert lib.MXNDArraySyncCopyToCPU(
        out, got.ctypes.data_as(ctypes.c_void_p), got.nbytes) == 0
    assert np.allclose(got, [10.0, 20.0, 30.0]), got
    lib.MXKVStoreFree(kv)
