"""Resilience layer: fault injection, retry/deadline policies, and
preemption-safe training (mxnet_tpu/resilience/, docs/fault_tolerance.md).

Tier-1-safe: everything runs on the virtual CPU mesh, chaos is armed
programmatically (seeded — every run replays identically), and the
SIGTERM path delivers the signal in-process via os.kill.
"""
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
from mxnet_tpu.gluon import nn
from mxnet_tpu import recordio as rio
from mxnet_tpu import resilience
from mxnet_tpu.resilience import (chaos, metrics, atomic_write,
                                  Deadline, DeadlineExceeded,
                                  InjectedFault, InjectedFailure,
                                  PreemptionGuard, RetryPolicy,
                                  TrainingPreempted, TransientError,
                                  retry, retry_call, run_with_deadline)


@pytest.fixture(autouse=True)
def _clean_chaos():
    chaos.configure("")          # disarm, whatever the ambient env says
    metrics.reset_counters()
    yield
    chaos.reset()


# -- chaos spec / injector ------------------------------------------------

def test_parse_spec():
    spec = chaos.parse_spec(
        "kvstore.push:p=0.1,kind=raise;io.read:p=0.05;"
        "dist.init:kind=sleep,secs=0.5,n=3,after=2")
    assert spec["kvstore.push"] == {"p": 0.1, "kind": "raise"}
    assert spec["io.read"] == {"p": 0.05}
    assert spec["dist.init"] == {"kind": "sleep", "secs": 0.5,
                                 "n": 3, "after": 2}
    assert chaos.parse_spec("") == {}
    with pytest.raises(mx.MXNetError):
        chaos.parse_spec("site:bogus=1")
    with pytest.raises(mx.MXNetError):
        chaos.parse_spec("site:kind=explode")


def test_seeded_draws_replay_identically():
    def pattern(seed):
        chaos.configure("s:p=0.5", seed=seed)
        out = []
        for _ in range(64):
            try:
                chaos.chaos_point("s")
                out.append(0)
            except InjectedFault:
                out.append(1)
        return out

    a, b = pattern(7), pattern(7)
    assert a == b and sum(a) > 0
    assert pattern(8) != a


def test_wildcard_site_and_trip_budget():
    chaos.configure("kvstore.*:p=1,n=2")
    with pytest.raises(InjectedFault):
        chaos.chaos_point("kvstore.push")
    with pytest.raises(InjectedFault):
        chaos.chaos_point("kvstore.pull")
    chaos.chaos_point("kvstore.push")  # budget n=2 spent: no more trips
    assert chaos.trip_count("kvstore.push") == 2
    chaos.chaos_point("io.read")       # unarmed site: never trips


def test_env_driven_configuration(monkeypatch):
    monkeypatch.setenv("MXTPU_CHAOS", "x:p=1,n=1")
    monkeypatch.setenv("MXTPU_CHAOS_SEED", "3")
    chaos.reset()                      # next point re-reads the env
    with pytest.raises(InjectedFault):
        chaos.chaos_point("x")
    chaos.chaos_point("x")
    assert chaos.trip_count("x") == 1


def test_sleep_kind_exercises_deadlines():
    chaos.configure("slow:kind=sleep,secs=0.05")
    t0 = time.monotonic()
    chaos.chaos_point("slow")          # does not raise, just stalls
    assert time.monotonic() - t0 >= 0.04


# -- retry / deadline toolkit ---------------------------------------------

def test_retry_call_absorbs_transients_then_succeeds():
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise TransientError("transient %d" % calls["n"])
        return "ok"

    assert retry_call(flaky, policy=RetryPolicy(
        max_attempts=5, base_delay=0.001)) == "ok"
    assert calls["n"] == 3


def test_retry_exhaustion_reraises_last_error():
    calls = {"n": 0}

    def always_fails():
        calls["n"] += 1
        raise TransientError("still down")

    with pytest.raises(TransientError, match="still down"):
        retry_call(always_fails, policy=RetryPolicy(
            max_attempts=3, base_delay=0.001))
    assert calls["n"] == 3


def test_retry_decorator_and_give_up_on():
    class Fatal(Exception):
        pass

    calls = {"n": 0}

    @retry(RetryPolicy(max_attempts=5, base_delay=0.001,
                       retry_on=(Exception,), give_up_on=(Fatal,)))
    def fails_fatally():
        calls["n"] += 1
        raise Fatal("do not retry me")

    with pytest.raises(Fatal):
        fails_fatally()
    assert calls["n"] == 1


def test_deadline_expiry():
    dl = Deadline(0.02, what="unit test op")
    dl.check()                         # fresh: fine
    time.sleep(0.03)
    assert dl.expired()
    with pytest.raises(DeadlineExceeded, match="unit test op"):
        dl.check()


def test_retry_respects_deadline():
    calls = {"n": 0}

    def always_fails():
        calls["n"] += 1
        raise TransientError("down")

    # generous attempts but a deadline too short for the backoff: the
    # loop must stop early rather than sleep past the budget
    with pytest.raises((TransientError, DeadlineExceeded)):
        retry_call(always_fails, policy=RetryPolicy(
            max_attempts=50, base_delay=0.05,
            deadline=Deadline(0.05, what="bounded retries")))
    assert calls["n"] < 50


def test_run_with_deadline():
    assert run_with_deadline(lambda: 42, 5.0, what="quick") == 42
    with pytest.raises(ValueError):
        run_with_deadline(lambda: (_ for _ in ()).throw(ValueError("x")),
                          5.0, what="raising")
    with pytest.raises(DeadlineExceeded, match="wedged barrier"):
        run_with_deadline(lambda: time.sleep(10), 0.05,
                          what="wedged barrier")


# -- kvstore.push site ----------------------------------------------------

def test_kvstore_push_injection_is_absorbed_by_retry():
    chaos.configure("kvstore.push:p=1,n=2")
    kv = mx.kv.create("device")
    kv.init(0, mx.nd.ones((4,)))
    kv.push(0, mx.nd.full((4,), 3.0))
    out = mx.nd.zeros((4,))
    kv.pull(0, out)
    np.testing.assert_allclose(out.asnumpy(), 3.0)
    assert chaos.trip_count("kvstore.push") == 2
    assert metrics.get("chaos.injected.kvstore.push") == 2


def test_kvstore_push_retry_exhaustion(monkeypatch):
    monkeypatch.setenv("MXTPU_KV_PUSH_RETRIES", "3")
    monkeypatch.setenv("MXTPU_RETRY_BASE_DELAY_S", "0.001")
    chaos.configure("kvstore.push:p=1")
    kv = mx.kv.create("device")
    kv.init(0, mx.nd.ones((4,)))
    with pytest.raises(InjectedFault):
        kv.push(0, mx.nd.ones((4,)))
    assert chaos.trip_count("kvstore.push") == 3


def test_kvstore_push_fatal_injection_not_retried():
    chaos.configure("kvstore.push:p=1,kind=fatal")
    kv = mx.kv.create("device")
    kv.init(0, mx.nd.ones((4,)))
    with pytest.raises(InjectedFailure):
        kv.push(0, mx.nd.ones((4,)))
    assert chaos.trip_count("kvstore.push") == 1


# -- dist.init site -------------------------------------------------------

def test_dist_init_retry_exhaustion(monkeypatch):
    from mxnet_tpu.parallel import kvstore_dist
    monkeypatch.setenv("MXTPU_DIST_INIT_RETRIES", "3")
    monkeypatch.setenv("MXTPU_DIST_INIT_BACKOFF_S", "0.001")
    chaos.configure("dist.init:p=1")
    # every attempt trips before jax.distributed.initialize runs, so
    # the bogus coordinator is never actually contacted
    with pytest.raises(InjectedFault):
        kvstore_dist.init_distributed(
            coordinator_address="127.0.0.1:1",
            num_processes=2, process_id=0)
    assert chaos.trip_count("dist.init") == 3
    assert not kvstore_dist._dist_initialized


# -- io.read site ---------------------------------------------------------

def test_io_read_chaos_preserves_the_batch_stream():
    X = np.arange(48, dtype="float32").reshape(12, 4)
    Y = (np.arange(12) % 3).astype("float32")

    def epoch():
        it = mx.io.NDArrayIter(X, Y, batch_size=4)
        return [(b.data[0].asnumpy().copy(), b.label[0].asnumpy().copy())
                for b in it]

    clean = epoch()
    chaos.configure("io.read:p=0.5", seed=11)
    chaotic = epoch()
    assert chaos.trip_count("io.read") > 0
    assert len(clean) == len(chaotic)
    for (xa, ya), (xb, yb) in zip(clean, chaotic):
        np.testing.assert_array_equal(xa, xb)
        np.testing.assert_array_equal(ya, yb)


# -- corrupt-record budget ------------------------------------------------

def _write_plain_rec(path, payloads, monkeypatch):
    """Write records via the pure-python framing (native lib bypassed)
    and return each record's byte offset."""
    monkeypatch.setattr(rio, "_native_lib", lambda: None)
    w = rio.MXRecordIO(path, "w")
    offsets = [w.write(p) for p in payloads]
    w.close()
    return offsets


def test_recordio_bad_magic_resync_within_budget(tmp_path, monkeypatch):
    path = str(tmp_path / "x.rec")
    payloads = [b"rec-%d-" % i + bytes(range(8)) for i in range(5)]
    offsets = _write_plain_rec(path, payloads, monkeypatch)
    with open(path, "r+b") as f:      # corrupt record 3's magic word
        f.seek(offsets[3])
        f.write(b"\xde\xad\xbe\xef")

    r = rio.MXRecordIO(path, "r", bad_record_budget=2)
    got = []
    while True:
        rec = r.read()
        if rec is None:
            break
        got.append(rec)
    r.close()
    assert got == [payloads[0], payloads[1], payloads[2], payloads[4]]
    assert r.bad_records == 1
    assert metrics.get("io.bad_records") == 1

    strict = rio.MXRecordIO(path, "r")  # default budget 0: reference
    assert strict.read() == payloads[0]
    assert strict.read() == payloads[1]
    assert strict.read() == payloads[2]
    with pytest.raises(IOError, match="Invalid RecordIO magic"):
        strict.read()
    strict.close()


def test_recordio_truncated_tail_is_warned_eof_even_at_budget_zero(
        tmp_path, monkeypatch):
    # a torn TRAILING record (crashed/concurrent writer) must read as
    # EOF whatever the budget — the pre-budget reader ended there too;
    # the counter just makes the damage visible
    path = str(tmp_path / "t.rec")
    payloads = [b"a" * 40, b"b" * 40]
    _write_plain_rec(path, payloads, monkeypatch)
    size = os.path.getsize(path)
    with open(path, "r+b") as f:      # tear the last record's payload
        f.truncate(size - 20)
    r = rio.MXRecordIO(path, "r")     # default budget 0
    assert r.read() == payloads[0]
    assert r.read() is None           # torn record reads as EOF
    assert r.bad_records == 1
    r.close()


def test_io_read_exhaustion_surfaces_instead_of_truncating(monkeypatch):
    # only the injection gate is retried: when retries exhaust, the
    # fault must surface from __next__ — NOT consume iterator state or
    # decay into a silent early StopIteration
    monkeypatch.setenv("MXTPU_IO_RETRIES", "3")
    monkeypatch.setenv("MXTPU_RETRY_BASE_DELAY_S", "0.001")
    chaos.configure("io.read:p=1")
    it = mx.io.NDArrayIter(np.zeros((8, 2), "float32"),
                           np.zeros(8, "float32"), batch_size=4)
    with pytest.raises(InjectedFault):
        next(it)
    chaos.configure("")               # iterator state untouched: the
    batches = list(it)                # full epoch is still there
    assert len(batches) == 2


def test_image_record_iter_skips_bad_records_within_budget(tmp_path):
    path = str(tmp_path / "img.rec")
    w = rio.MXRecordIO(path, "w")
    n_good = 8
    for i in range(n_good):
        img = np.full((6, 5, 3), i * 9, np.uint8)
        w.write(rio.pack_img(rio.IRHeader(0, float(i), i, 0), img))
        if i == 3:                    # a record whose decode must fail
            w.write(rio.pack(rio.IRHeader(0, 99.0, 99, 0),
                             b"NOT-AN-IMAGE"))
    w.close()

    it = mx.io.ImageRecordIter(path_imgrec=path, data_shape=(3, 6, 5),
                               batch_size=4, preprocess_threads=2,
                               bad_record_budget=2)
    labels = []
    for batch in it:
        labels.extend(batch.label[0].asnumpy()[:4 - batch.pad].tolist())
    it.close()
    assert sorted(labels) == sorted(float(i) for i in range(n_good))
    assert it.bad_record_count == 1

    strict = mx.io.ImageRecordIter(path_imgrec=path, data_shape=(3, 6, 5),
                                   batch_size=4, preprocess_threads=2)
    with pytest.raises(mx.MXNetError, match="bad-record budget"):
        for _ in strict:
            pass
    strict.close()


# -- crash-consistent writes ----------------------------------------------

def test_atomic_write_failure_leaves_target_untouched(tmp_path):
    target = tmp_path / "state.params"
    with atomic_write(str(target)) as f:
        f.write(b"generation-1")
    with pytest.raises(RuntimeError, match="mid-write crash"):
        with atomic_write(str(target)) as f:
            f.write(b"gener")        # partial second generation...
            raise RuntimeError("mid-write crash")
    assert target.read_bytes() == b"generation-1"
    assert os.listdir(str(tmp_path)) == ["state.params"]  # no tmp litter


def test_nd_save_is_crash_consistent(tmp_path):
    fname = str(tmp_path / "w.params")
    mx.nd.save(fname, {"w": mx.nd.ones((3, 3))})
    loaded = mx.nd.load(fname)
    np.testing.assert_allclose(loaded["w"].asnumpy(), 1.0)
    assert os.listdir(str(tmp_path)) == ["w.params"]


# -- checkpoint.save site + preemption ------------------------------------

def _sharded(net):
    from mxnet_tpu.parallel import make_mesh, ShardedTrainer
    loss = gluon.loss.SoftmaxCrossEntropyLoss()
    return ShardedTrainer(net, lambda o, l: loss(o, l), "sgd",
                          {"learning_rate": 0.05},
                          mesh=make_mesh({"dp": 8}))


def _small_net():
    net = nn.HybridSequential()
    net.add(nn.Dense(16, activation="relu"), nn.Dense(10))
    net.initialize()
    net(mx.nd.zeros((1, 8)))
    return net


def _batch(rng):
    return (rng.randn(16, 8).astype("float32"),
            (np.arange(16) % 10).astype("float32"))


def test_checkpoint_save_injection_retried(tmp_path):
    from mxnet_tpu.parallel.checkpoint import TrainerCheckpoint
    rng = np.random.RandomState(0)
    net = _small_net()
    x, y = _batch(rng)
    tr = _sharded(net)
    tr.step(x, y)
    chaos.configure("checkpoint.save:p=1,n=2")
    with TrainerCheckpoint(str(tmp_path / "ck")) as ck:
        ck.save(1, tr, wait=True)    # two injected faults absorbed
        assert chaos.trip_count("checkpoint.save") == 2
        fresh = _sharded(net)
        assert ck.restore_latest(fresh) == 1


def test_sigterm_checkpoints_at_next_step_boundary(tmp_path):
    from mxnet_tpu.parallel.checkpoint import TrainerCheckpoint
    rng = np.random.RandomState(1)
    net = _small_net()
    x, y = _batch(rng)
    tr = _sharded(net)
    old = signal.getsignal(signal.SIGTERM)
    with TrainerCheckpoint(str(tmp_path / "ck")) as ck:
        with pytest.raises(TrainingPreempted) as ei:
            with PreemptionGuard.for_trainer(ck, tr) as guard:
                for i in range(100):
                    tr.step(x, y)
                    if i == 2:       # preemption arrives mid-run...
                        os.kill(os.getpid(), signal.SIGTERM)
        # ...and fires at the NEXT step boundary: 3 completed steps
        assert ei.value.step == 3
        assert guard.preempted and guard.saved_step == 3
        assert signal.getsignal(signal.SIGTERM) is old  # restored
        resumed = _sharded(net)
        assert ck.restore_latest(resumed) == 3
        assert resumed._step_count == 3
        # the resumed run continues training from exactly there
        assert float(resumed.step(x, y).asscalar()) > 0
        assert resumed._step_count == 4


def test_second_signal_escalates_to_keyboard_interrupt():
    # a wedged loop never reaches a boundary; the second signal must
    # escape with the clean unwind the reaping ladders rely on
    with PreemptionGuard(reraise=False):
        os.kill(os.getpid(), signal.SIGTERM)
        with pytest.raises(KeyboardInterrupt):
            os.kill(os.getpid(), signal.SIGTERM)
            time.sleep(0.1)  # let the pending signal be delivered


def test_preemption_guard_cooperative_mode():
    with PreemptionGuard(reraise=False) as guard:
        os.kill(os.getpid(), signal.SIGTERM)
        resilience.at_step_boundary()
        assert guard.preempted
    assert not resilience.preemption_requested()


# -- engine.host_push site ------------------------------------------------

def test_host_push_site():
    from mxnet_tpu import engine
    if engine.host_engine() is None:
        assert engine.host_push(lambda: 5) == 5  # inline fallback path
    chaos.configure("engine.host_push:p=1,kind=fatal")
    with pytest.raises(InjectedFailure):
        engine.host_push(lambda: 5)


# -- acceptance: chaos training run ---------------------------------------

def _train_losses(net, init_params, n_epochs=3):
    params = net.collect_params()
    for k, v in init_params.items():
        params[k].set_data(mx.nd.array(v))
    trainer = gluon.Trainer(params, "sgd", {"learning_rate": 0.1},
                            kvstore="device")
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    rng = np.random.RandomState(0)
    X = rng.randn(24, 8).astype("float32")
    Y = (np.arange(24) % 10).astype("float32")
    losses = []
    for _ in range(n_epochs):
        it = mx.io.NDArrayIter(X, Y, batch_size=8)
        for batch in it:
            with autograd.record():
                l = loss_fn(net(batch.data[0]), batch.label[0])
            l.backward()
            trainer.step(8)
            losses.append(float(l.mean().asscalar()))
    return losses


def test_training_identical_loss_under_chaos(monkeypatch):
    """Acceptance: 10% transient injection at kvstore.push and io.read
    is fully absorbed — the loss trajectory is identical to the
    fault-free run (every site precedes mutation, so retries replay
    bit-identically)."""
    monkeypatch.setenv("MXTPU_RETRY_BASE_DELAY_S", "0.001")
    net = _small_net()
    init = {k: p.data().asnumpy().copy()
            for k, p in net.collect_params().items()}
    clean = _train_losses(net, init)
    chaos.configure("kvstore.push:p=0.1;io.read:p=0.1", seed=5)
    chaotic = _train_losses(net, init)
    trips = (chaos.trip_count("kvstore.push") +
             chaos.trip_count("io.read"))
    assert trips > 0, "chaos must actually have fired for this to mean anything"
    np.testing.assert_array_equal(np.asarray(clean), np.asarray(chaotic))
    assert clean[-1] < clean[0]       # and training actually trains


# -- chaos_run harness -----------------------------------------------------

TOOLS = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tools")


def _chaos_run(*args, timeout=120):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    return subprocess.run(
        [sys.executable, os.path.join(TOOLS, "chaos_run.py")] + list(args),
        capture_output=True, text=True, timeout=timeout, env=env)


def test_chaos_run_completion_and_clean_error():
    r = _chaos_run("--chaos", "io.read:p=0", "--timeout", "90",
                   "--expect", "complete", "--",
                   sys.executable, "-c", "print('done')")
    assert r.returncode == 0, r.stdout + r.stderr
    assert '"outcome": "COMPLETED"' in r.stdout

    r = _chaos_run("--chaos", "io.read:p=0", "--timeout", "90",
                   "--expect", "error", "--",
                   sys.executable, "-c",
                   "import sys; sys.exit('diagnosable boom')")
    assert r.returncode == 0, r.stdout + r.stderr
    assert '"outcome": "CLEAN_ERROR"' in r.stdout


def test_chaos_run_flags_hangs():
    r = _chaos_run("--chaos", "io.read:p=0", "--timeout", "1",
                   "--grace", "2", "--",
                   sys.executable, "-c", "import time; time.sleep(120)")
    assert r.returncode == 3, r.stdout + r.stderr
    assert '"outcome": "HANG"' in r.stdout
