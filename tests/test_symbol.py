"""Symbol graph construction, inference, serialization, executor tests.

Mirrors the reference's tests/python/unittest/test_symbol.py and
test_executor.py.
"""
import numpy as np
import pytest

import mxnet_tpu as mx


def test_variable_and_compose():
    x = mx.sym.var("x")
    y = mx.sym.var("y")
    z = x + y
    assert set(z.list_arguments()) == {"x", "y"}
    assert z.num_outputs == 1


def test_auto_variable_creation():
    data = mx.sym.var("data")
    fc = mx.sym.FullyConnected(data=data, num_hidden=10, name="fc1")
    args = fc.list_arguments()
    assert args == ["data", "fc1_weight", "fc1_bias"]
    fc_nb = mx.sym.FullyConnected(data=data, num_hidden=10, no_bias=True,
                                  name="fc2")
    assert fc_nb.list_arguments() == ["data", "fc2_weight"]


def test_auto_naming():
    with mx.name.NameManager():
        data = mx.sym.var("data")
        c1 = mx.sym.Convolution(data=data, kernel=(3, 3), num_filter=8)
        c2 = mx.sym.Convolution(data=c1, kernel=(3, 3), num_filter=8)
        assert c1.name == "convolution0"
        assert c2.name == "convolution1"
        assert "convolution0_weight" in c2.list_arguments()


def test_batchnorm_aux_states():
    data = mx.sym.var("data")
    bn = mx.sym.BatchNorm(data=data, name="bn")
    assert bn.list_arguments() == ["data", "bn_gamma", "bn_beta"]
    assert bn.list_auxiliary_states() == ["bn_moving_mean", "bn_moving_var"]


def test_infer_shape_mlp():
    data = mx.sym.var("data")
    h = mx.sym.FullyConnected(data=data, num_hidden=128, name="fc1")
    h = mx.sym.Activation(data=h, act_type="relu")
    out = mx.sym.FullyConnected(data=h, num_hidden=10, name="fc2")
    arg_shapes, out_shapes, aux_shapes = out.infer_shape(data=(32, 784))
    args = out.list_arguments()
    d = dict(zip(args, arg_shapes))
    assert d["fc1_weight"] == (128, 784)
    assert d["fc1_bias"] == (128,)
    assert d["fc2_weight"] == (10, 128)
    assert out_shapes == [(32, 10)]


def test_infer_shape_conv():
    data = mx.sym.var("data")
    c = mx.sym.Convolution(data=data, kernel=(3, 3), num_filter=16,
                           pad=(1, 1), name="c1")
    p = mx.sym.Pooling(data=c, kernel=(2, 2), stride=(2, 2), pool_type="max")
    arg_shapes, out_shapes, _ = p.infer_shape(data=(4, 3, 32, 32))
    d = dict(zip(p.list_arguments(), arg_shapes))
    assert d["c1_weight"] == (16, 3, 3, 3)
    assert out_shapes == [(4, 16, 16, 16)]


def test_group_and_internals():
    x = mx.sym.var("x")
    a = x * 2
    b = x + 1
    g = mx.sym.Group([a, b])
    assert g.num_outputs == 2
    outs = g.list_outputs()
    assert len(outs) == 2
    internals = (a + b).get_internals()
    assert internals.num_outputs >= 3


def test_json_roundtrip():
    data = mx.sym.var("data")
    net = mx.sym.FullyConnected(data=data, num_hidden=4, name="fc")
    net = mx.sym.SoftmaxOutput(data=net, name="sm")
    js = net.tojson()
    net2 = mx.sym.load_json(js)
    assert net2.list_arguments() == net.list_arguments()
    a1, o1, _ = net.infer_shape(data=(2, 8))
    a2, o2, _ = net2.infer_shape(data=(2, 8))
    assert o1 == o2 and a1 == a2


def test_simple_bind_forward_backward():
    data = mx.sym.var("data")
    fc = mx.sym.FullyConnected(data=data, num_hidden=3, name="fc")
    out = mx.sym.sum(fc)
    ex = out.simple_bind(mx.cpu(), data=(2, 5))
    ex.arg_dict["data"][:] = 1.0
    ex.arg_dict["fc_weight"][:] = 0.5
    ex.arg_dict["fc_bias"][:] = 0.0
    outs = ex.forward(is_train=True)
    np.testing.assert_allclose(outs[0].asnumpy(), 2 * 3 * 5 * 0.5, rtol=1e-5)
    ex.backward()
    # d out / d bias = 2 (batch size)
    np.testing.assert_allclose(ex.grad_dict["fc_bias"].asnumpy(),
                               np.full(3, 2.0), rtol=1e-5)
    np.testing.assert_allclose(ex.grad_dict["fc_weight"].asnumpy(),
                               np.full((3, 5), 2.0), rtol=1e-5)


def test_executor_softmax_output_grad():
    """SoftmaxOutput is a loss head: backward seeds (p - onehot)/..."""
    data = mx.sym.var("data")
    label = mx.sym.var("label")
    out = mx.sym.SoftmaxOutput(data=data, label=label, name="sm")
    ex = out.simple_bind(mx.cpu(), data=(2, 4), label=(2,),
                         grad_req={"data": "write"})
    x = np.random.randn(2, 4).astype(np.float32)
    ex.arg_dict["data"][:] = x
    ex.arg_dict["label"][:] = np.array([1, 3], dtype=np.float32)
    outs = ex.forward(is_train=True)
    p = outs[0].asnumpy()
    np.testing.assert_allclose(p.sum(axis=1), np.ones(2), rtol=1e-5)
    ex.backward()
    onehot = np.zeros((2, 4), np.float32)
    onehot[0, 1] = 1
    onehot[1, 3] = 1
    np.testing.assert_allclose(ex.grad_dict["data"].asnumpy(), p - onehot,
                               rtol=1e-4, atol=1e-6)


def test_executor_batchnorm_aux_update():
    data = mx.sym.var("data")
    bn = mx.sym.BatchNorm(data=data, name="bn", momentum=0.5)
    loss = mx.sym.sum(bn)
    ex = loss.simple_bind(mx.cpu(), data=(8, 3))
    ex.aux_dict["bn_moving_var"][:] = 1.0
    x = np.random.randn(8, 3).astype(np.float32) * 3 + 1
    ex.arg_dict["data"][:] = x
    mm_before = ex.aux_dict["bn_moving_mean"].asnumpy().copy()
    ex.forward(is_train=True)
    mm_after = ex.aux_dict["bn_moving_mean"].asnumpy()
    expected = 0.5 * mm_before + 0.5 * x.mean(axis=0)
    np.testing.assert_allclose(mm_after, expected, rtol=1e-4)
    # predict mode must NOT touch the stats
    ex.forward(is_train=False)
    np.testing.assert_allclose(ex.aux_dict["bn_moving_mean"].asnumpy(),
                               mm_after, rtol=1e-6)


def test_bind_with_arrays():
    x = mx.sym.var("x")
    y = x * 2 + 1
    xv = mx.nd.array(np.arange(6).reshape(2, 3))
    ex = y.bind(mx.cpu(), {"x": xv})
    out = ex.forward()[0].asnumpy()
    np.testing.assert_allclose(out, np.arange(6).reshape(2, 3) * 2 + 1)


def test_grad_req_add_and_null():
    x = mx.sym.var("x")
    y = mx.sym.sum(x * 3)
    ex = y.simple_bind(mx.cpu(), x=(4,), grad_req="add")
    ex.arg_dict["x"][:] = 1.0
    ex.forward(is_train=True)
    ex.backward()
    ex.forward(is_train=True)
    ex.backward()
    np.testing.assert_allclose(ex.grad_dict["x"].asnumpy(), np.full(4, 6.0))
    ex2 = y.simple_bind(mx.cpu(), x=(4,), grad_req="null")
    ex2.arg_dict["x"][:] = 1.0
    ex2.forward(is_train=True)
    ex2.backward()   # no-op
    assert ex2.grad_dict == {}


def test_slice_channel_multi_output():
    x = mx.sym.var("x")
    parts = mx.sym.SliceChannel(x, num_outputs=3, axis=1, name="split")
    assert parts.num_outputs == 3
    s = parts[0] + parts[1] + parts[2]
    ex = s.simple_bind(mx.cpu(), x=(2, 6))
    ex.arg_dict["x"][:] = 1.0
    out = ex.forward()[0]
    assert out.shape == (2, 2)
    np.testing.assert_allclose(out.asnumpy(), np.full((2, 2), 3.0))


def test_rnn_symbol_infer():
    data = mx.sym.var("data")
    rnn = mx.sym.RNN(data=data, state_size=16, num_layers=2, mode="lstm",
                     name="lstm", state_outputs=True)
    arg_shapes, out_shapes, _ = rnn.infer_shape(data=(10, 4, 8))
    d = dict(zip(rnn.list_arguments(), arg_shapes))
    assert out_shapes[0] == (10, 4, 16)
    assert d["lstm_state"] == (2, 4, 16)
    assert rnn.num_outputs == 3  # out, h, c


def test_cached_op_forward():
    data = mx.sym.var("data")
    net = mx.sym.FullyConnected(data=data, num_hidden=3, name="fc")
    op = mx.CachedOp(net)
    assert op.input_names == ["data", "fc_weight", "fc_bias"]
    d = mx.nd.ones((2, 5))
    w = mx.nd.full((3, 5), 0.5)
    b = mx.nd.zeros((3,))
    (out,) = op(d, w, b)
    np.testing.assert_allclose(out.asnumpy(), np.full((2, 3), 2.5), rtol=1e-5)


def test_cached_op_backward_through_tape():
    data = mx.sym.var("data")
    net = mx.sym.FullyConnected(data=data, num_hidden=3, name="fc")
    op = mx.CachedOp(net)
    d = mx.nd.ones((2, 5))
    w = mx.nd.full((3, 5), 0.5)
    b = mx.nd.zeros((3,))
    w.attach_grad()
    b.attach_grad()
    with mx.autograd.record():
        (out,) = op(d, w, b)
        loss = out.sum()
    loss.backward()
    np.testing.assert_allclose(b.grad.asnumpy(), np.full(3, 2.0), rtol=1e-5)
    np.testing.assert_allclose(w.grad.asnumpy(), np.full((3, 5), 2.0),
                               rtol=1e-5)


def test_eval():
    x = mx.sym.var("x")
    y = x * 2
    out = y.eval(x=mx.nd.ones((2, 2)))
    np.testing.assert_allclose(out[0].asnumpy(), np.full((2, 2), 2.0))


def test_dropout_rng_in_graph():
    x = mx.sym.var("x")
    y = mx.sym.Dropout(x, p=0.5)
    ex = y.simple_bind(mx.cpu(), x=(100,))
    ex.arg_dict["x"][:] = 1.0
    out_train = ex.forward(is_train=True)[0].asnumpy()
    assert (out_train == 0).any()
    out_pred = ex.forward(is_train=False)[0].asnumpy()
    np.testing.assert_allclose(out_pred, np.ones(100))


def test_backward_out_grads_same_dropout_mask():
    """backward(out_grads) must replay the SAME dropout mask as forward
    (regression: fresh PRNG key made grads disagree with outputs)."""
    x = mx.sym.var("x")
    y = mx.sym.Dropout(x, p=0.5)
    ex = y.simple_bind(mx.cpu(), x=(200,))
    ex.arg_dict["x"][:] = 1.0
    out = ex.forward(is_train=True)[0].asnumpy()
    ex.backward(out_grads=mx.nd.ones((200,)))
    g = ex.grad_dict["x"].asnumpy()
    kept = out != 0
    np.testing.assert_allclose(g[kept], np.full(kept.sum(), 2.0))
    np.testing.assert_allclose(g[~kept], 0.0)


def test_shared_var_not_reclassified_as_aux():
    """A var used as a BatchNorm moving stat in one graph stays a plain
    argument in an unrelated graph (regression: global is_aux mutation)."""
    mm = mx.sym.var("mm")
    other = mm * 2
    assert other.list_arguments() == ["mm"]
    d = mx.sym.var("d")
    bn = mx.sym.BatchNorm(data=d, moving_mean=mm, name="bn")
    assert "mm" in bn.list_auxiliary_states()
    assert other.list_arguments() == ["mm"]
    assert other.list_auxiliary_states() == []


def test_extra_positional_inputs_raise():
    x = mx.sym.var("x")
    w = mx.sym.var("w")
    b = mx.sym.var("b")
    with pytest.raises(mx.MXNetError):
        mx.sym.FullyConnected(x, w, b, num_hidden=3, no_bias=True)
