"""Elastic gang supervision (resilience/supervisor.py, ISSUE 8): rank
heartbeats, fast dead-peer detection (`PeerLost`), the GangSupervisor
restart state machine, the exit-code contract, the `worker.kill` chaos
site, two-phase checkpoint commit, and the kill_stale SUPERVISED tag.

The slow 4-process end-to-end proof lives in test_gang_restart.py;
these tests are the fast single-host slice of the same machinery."""
import json
import os
import signal
import socket
import subprocess
import sys
import time

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

from mxnet_tpu.resilience import (EXIT_PEER_LOST, EXIT_PREEMPTED,
                                  GangSupervisor, PeerLost,
                                  RankHeartbeat, TrainingPreempted)
from mxnet_tpu.resilience.supervisor import (dead_peers, exit_status,
                                             peer_checker, peer_status,
                                             read_heartbeat)
from mxnet_tpu.resilience.watchdog import HealthWatchdog
from mxnet_tpu.resilience.retry import DeadlineExceeded


# -- exit-code contract ---------------------------------------------------

def test_exit_code_contract():
    """Preempted vs peer-lost vs crash are distinct exit codes, so the
    supervisor decides restart-vs-stop without parsing stderr."""
    assert TrainingPreempted.exit_code == EXIT_PREEMPTED == 75
    assert PeerLost.exit_code == EXIT_PEER_LOST == 76
    assert EXIT_PREEMPTED != EXIT_PEER_LOST
    err = PeerLost("rank down", rank=3)
    assert err.rank == 3
    assert exit_status(err) == EXIT_PEER_LOST
    assert exit_status(TrainingPreempted("bye", step=7)) == EXIT_PREEMPTED
    assert exit_status(RuntimeError("boom")) == 1


# -- rank heartbeats ------------------------------------------------------

def test_heartbeat_roundtrip_and_peer_status(tmp_path):
    d = str(tmp_path)
    hb = RankHeartbeat(0, d, interval_s=0.05)
    hb.beat(step=4)
    rec = read_heartbeat(os.path.join(d, "rank_0.hb"))
    assert rec["rank"] == 0 and rec["pid"] == os.getpid()
    assert rec["step"] == 4
    assert isinstance(rec["starttime"], int)
    # peer view: we are alive, and exclude_rank hides ourselves
    st = peer_status(d)
    assert [s["rank"] for s in st] == [0]
    assert st[0]["alive"] and st[0]["age_s"] < 5.0
    assert peer_status(d, exclude_rank=0) == []
    assert dead_peers(d) == []
    hb.stop(unlink=True)
    assert not os.path.exists(os.path.join(d, "rank_0.hb"))


def _spawn_rank_beacon(d, rank):
    """A real peer process that writes its heartbeat then sleeps."""
    code = ("import sys; sys.path.insert(0, %r);"
            "from mxnet_tpu.resilience.supervisor import RankHeartbeat;"
            "RankHeartbeat(%d, %r).beat();"
            "print('BEATING', flush=True);"
            "import time; time.sleep(600)" % (ROOT, rank, d))
    p = subprocess.Popen([sys.executable, "-c", code],
                         stdout=subprocess.PIPE)
    assert b"BEATING" in p.stdout.readline()
    return p


def test_sigkilled_peer_is_provably_dead_immediately(tmp_path):
    """A SIGKILLed peer's heartbeat file proves it dead via the pid/
    starttime identity record — no timeout has to elapse."""
    d = str(tmp_path)
    p = _spawn_rank_beacon(d, 1)
    try:
        assert dead_peers(d, exclude_rank=0) == []
        p.send_signal(signal.SIGKILL)
        p.wait()
        t0 = time.monotonic()
        dead = dead_peers(d, exclude_rank=0, timeout_s=1e9)
        assert time.monotonic() - t0 < 2.0
        assert [r for r, _ in dead] == [1]
        assert "gone" in dead[0][1]
    finally:
        if p.poll() is None:
            p.kill()
            p.wait()


def test_wedged_peer_detected_by_heartbeat_timeout(tmp_path):
    """A live-pid peer whose heartbeat went silent past the timeout is
    wedged-dead (the watchdog cannot tell it from a hang)."""
    d = str(tmp_path)
    hb = RankHeartbeat(2, d)
    hb.beat()
    rec = read_heartbeat(hb.path)
    rec["heartbeat"] = time.time() - 100.0
    with open(hb.path, "w") as f:
        f.write(json.dumps(rec))
    assert dead_peers(d, exclude_rank=0, timeout_s=5.0) == [
        (2, dead_peers(d, exclude_rank=0, timeout_s=5.0)[0][1])]
    assert "silent" in dead_peers(d, exclude_rank=0, timeout_s=5.0)[0][1]
    # fresh heartbeat: not dead
    rec["heartbeat"] = time.time()
    with open(hb.path, "w") as f:
        f.write(json.dumps(rec))
    assert dead_peers(d, exclude_rank=0, timeout_s=5.0) == []


# -- PeerLost via the collective watchdog ---------------------------------

def test_guard_collective_raises_peer_lost_before_watchdog_budget(
        tmp_path, monkeypatch):
    """The ISSUE-8 detection acceptance: a SIGKILLed peer is reported
    while the collective watchdog budget (30s here) has barely
    started — typed PeerLost naming the dead rank, not a generic
    DeadlineExceeded after the full wait."""
    monkeypatch.setenv("MXTPU_GANG_PEER_POLL_S", "0.1")
    d = str(tmp_path)
    p = _spawn_rank_beacon(d, 1)
    p.send_signal(signal.SIGKILL)
    p.wait()
    wd = HealthWatchdog()
    check = peer_checker(exclude_rank=0, directory=d)
    t0 = time.monotonic()
    with pytest.raises(PeerLost) as ei:
        wd.guard_collective(lambda: time.sleep(60),
                            what="stand-in collective",
                            timeout_s=30.0, peer_check=check)
    elapsed = time.monotonic() - t0
    assert elapsed < 5.0, elapsed          # seconds, not the 30s budget
    assert ei.value.rank == 1
    assert "rank 1" in str(ei.value)


def test_guard_collective_peer_check_without_deadline(tmp_path,
                                                      monkeypatch):
    """With no collective deadline configured (the default), a
    supervised gang still never blocks forever: the peer poll alone
    bounds the wait."""
    monkeypatch.setenv("MXTPU_GANG_PEER_POLL_S", "0.1")
    d = str(tmp_path)
    p = _spawn_rank_beacon(d, 3)
    p.send_signal(signal.SIGKILL)
    p.wait()
    wd = HealthWatchdog(collective_timeout_s=0.0)
    with pytest.raises(PeerLost) as ei:
        wd.guard_collective(lambda: time.sleep(60), timeout_s=0.0,
                            peer_check=peer_checker(exclude_rank=0,
                                                    directory=d))
    assert ei.value.rank == 3


def test_guard_collective_converts_collective_error_to_peer_lost(
        tmp_path):
    """When the collective itself errors (gloo connection reset) while
    a peer is dead, the dead peer is the diagnosis — PeerLost, with
    the transport error chained underneath."""
    d = str(tmp_path)
    p = _spawn_rank_beacon(d, 1)
    p.send_signal(signal.SIGKILL)
    p.wait()

    def exploding_collective():
        raise RuntimeError("connection reset by peer")

    wd = HealthWatchdog()
    with pytest.raises(PeerLost) as ei:
        wd.guard_collective(exploding_collective, timeout_s=30.0,
                            peer_check=peer_checker(exclude_rank=0,
                                                    directory=d))
    assert ei.value.rank == 1
    assert isinstance(ei.value.__cause__, RuntimeError)


def test_guard_collective_deadline_with_live_peers(tmp_path,
                                                   monkeypatch):
    """All peers heartbeating but the collective still stuck: the
    deadline trips as before (DeadlineExceeded, kind=collective) —
    PeerLost is only for provably-lost peers."""
    monkeypatch.setenv("MXTPU_GANG_PEER_POLL_S", "0.05")
    d = str(tmp_path)
    hb = RankHeartbeat(1, d, interval_s=0.05)
    hb.start()
    try:
        wd = HealthWatchdog()
        with pytest.raises(DeadlineExceeded):
            wd.guard_collective(lambda: time.sleep(60),
                                timeout_s=0.5,
                                peer_check=peer_checker(
                                    exclude_rank=0, directory=d))
    finally:
        hb.stop(unlink=True)


# -- worker.kill chaos site ----------------------------------------------

def _run_child(code, env=None):
    full_env = dict(os.environ)
    full_env.pop("MXTPU_CHAOS", None)
    full_env.update(env or {})
    return subprocess.run([sys.executable, "-c", code], env=full_env,
                          capture_output=True, timeout=60)


_KILL_CHILD = (
    "import sys; sys.path.insert(0, %r);"
    "from mxnet_tpu.resilience.preempt import at_step_boundary;"
    "[at_step_boundary() for _ in range(6)];"
    "print('SURVIVED', flush=True)" % ROOT)


def test_chaos_kill_kind_sigkills_the_rank():
    r = _run_child(_KILL_CHILD,
                   env={"MXTPU_CHAOS": "worker.kill:kind=kill,after=2"})
    assert r.returncode == -signal.SIGKILL
    assert b"SURVIVED" not in r.stdout


def test_chaos_rank_spec_arms_only_the_named_rank():
    """MXTPU_CHAOS_RANK_<r> (the chaos_run --kill-rank plumbing) arms
    only the rank whose rendezvous env matches."""
    spec = {"MXTPU_CHAOS_RANK_2": "worker.kill:kind=kill"}
    hit = _run_child(_KILL_CHILD,
                     env=dict(spec, JAX_PROCESS_ID="2"))
    assert hit.returncode == -signal.SIGKILL
    miss = _run_child(_KILL_CHILD,
                      env=dict(spec, JAX_PROCESS_ID="0"))
    assert miss.returncode == 0, miss.stdout + miss.stderr
    assert b"SURVIVED" in miss.stdout


def test_chaos_rank_spec_merges_with_global_spec():
    """A global MXTPU_CHAOS must not mask the per-rank spec (the
    chaos_run --chaos + --kill-rank combination): the targeted rank
    arms BOTH."""
    env = {"MXTPU_CHAOS": "io.read:p=0",
           "MXTPU_CHAOS_RANK_2": "worker.kill:kind=kill",
           "JAX_PROCESS_ID": "2"}
    hit = _run_child(_KILL_CHILD, env=env)
    assert hit.returncode == -signal.SIGKILL, hit.stdout + hit.stderr


# -- GangSupervisor state machine ----------------------------------------

def _gen_rank_cmd(body):
    """A tiny gang member: `g` and `r` are bound from the rendezvous
    env the supervisor injects."""
    return [sys.executable, "-c",
            "import os, sys, time;"
            "g=int(os.environ['MXTPU_GANG_GENERATION']);"
            "r=int(os.environ['JAX_PROCESS_ID']);" + body]


def test_supervisor_restarts_crashed_gang_once(tmp_path):
    cmd = _gen_rank_cmd("sys.exit(3 if (g==0 and r==1) else 0)")
    sup = GangSupervisor(cmd, 3, gang_dir=str(tmp_path),
                         max_restarts=2, backoff_s=0.05)
    rc = sup.run()
    assert rc == 0
    rep = sup.report()
    assert sup.restarts == 1 and rep["restarts"] == 1
    assert len(rep["incidents"]) == 1
    inc = rep["incidents"][0]
    assert inc["rank"] == 1 and inc["exit_code"] == 3
    assert inc["action"] == "restart"
    assert inc["downtime_s"] >= 0.05       # includes the backoff
    # the report also lands on disk for harnesses
    on_disk = json.loads(
        open(os.path.join(str(tmp_path), "report.json")).read())
    assert on_disk["restarts"] == 1


def test_supervisor_stops_on_preemption_without_restart(tmp_path):
    cmd = _gen_rank_cmd("sys.exit(%d if r==0 else 0)" % EXIT_PREEMPTED)
    sup = GangSupervisor(cmd, 2, gang_dir=str(tmp_path),
                         max_restarts=3, backoff_s=0.05)
    assert sup.run() == EXIT_PREEMPTED
    assert sup.restarts == 0
    assert sup.report()["incidents"][0]["action"] == "stop (preempted)"


def test_supervisor_restarts_when_crash_precedes_preempted_collateral(
        tmp_path):
    """The flagship OOM/SIGKILL scenario with PreemptionGuard-equipped
    stragglers: the crash is the root cause; survivors exiting 75 in
    response to OUR teardown SIGTERM are collateral and must not
    re-label the incident as a preemption (which would stop instead of
    restart)."""
    cmd = _gen_rank_cmd(
        "import signal as sg;"
        "sg.signal(sg.SIGTERM, lambda *a: sys.exit(%d));"
        "sys.exit(0) if g else ("
        "sys.exit(9) if r==0 else time.sleep(600))" % EXIT_PREEMPTED)
    sup = GangSupervisor(cmd, 2, gang_dir=str(tmp_path),
                         max_restarts=2, backoff_s=0.05,
                         kill_grace_s=2.0)
    assert sup.run() == 0
    assert sup.restarts == 1
    inc = sup.report()["incidents"][0]
    assert inc["action"] == "restart"
    assert inc["rank"] == 0 and inc["exit_code"] == 9
    # the straggler really did exit with the preemption code
    assert inc["rank_exit_codes"][1] == EXIT_PREEMPTED


def test_supervisor_attributes_wedged_peer_not_first_reporter(tmp_path):
    """When every observed exit is a survivor's EXIT_PEER_LOST (the
    wedged-but-alive peer never exits on its own), the incident must
    name the wedged rank from the heartbeats — not the first reporter,
    and not another 76-exited survivor whose lingering heartbeat file
    also reads as dead (collateral is never the root cause)."""
    # rank 1 "wedges": writes a heartbeat far in the past, then
    # sleeps; ranks 0 and 2 play survivors — each leaves a heartbeat
    # with its own pid (dead once exited) and exits 76 at staggered
    # times, so the reattribution must skip a dead 76-survivor and
    # land on the wedged rank whatever the observation order
    cmd = _gen_rank_cmd(
        "import json;"
        "sys.exit(0) if g else None;"
        "d=os.environ['MXTPU_GANG_DIR'];"
        "json.dump({'rank':r,'pid':os.getpid(),"
        "'heartbeat':1.0 if r==1 else 1e12},"
        "open(os.path.join(d,'rank_%%d.hb'%%r),'w'));"
        "time.sleep(600) if r==1 else "
        "(time.sleep(0.5 if r==2 else 2.0), sys.exit(%d))"
        % EXIT_PEER_LOST)
    sup = GangSupervisor(cmd, 3, gang_dir=str(tmp_path),
                         max_restarts=1, backoff_s=0.05,
                         kill_grace_s=1.0)
    assert sup.run() == 0
    inc = sup.report()["incidents"][0]
    assert inc["rank"] == 1, inc          # the wedged one
    assert inc["wedged"] is True
    assert inc["exit_code"] < 0           # reaped by our teardown


def test_supervisor_gives_up_after_restart_budget(tmp_path):
    cmd = _gen_rank_cmd("sys.exit(9)")
    sup = GangSupervisor(cmd, 2, gang_dir=str(tmp_path),
                         max_restarts=1, backoff_s=0.05)
    assert sup.run() == 9
    assert sup.restarts == 1
    actions = [i["action"] for i in sup.report()["incidents"]]
    assert actions[0] == "restart" and "give up" in actions[-1]


def test_supervisor_tears_down_stragglers(tmp_path):
    """Rank 1 dies; rank 0 would sleep 600s (the survivor hanging on
    its next collective) — the supervisor must reap it promptly."""
    cmd = _gen_rank_cmd(
        "sys.exit(5) if r==1 else time.sleep(600)")
    sup = GangSupervisor(cmd, 2, gang_dir=str(tmp_path),
                         max_restarts=0, backoff_s=0.05,
                         kill_grace_s=1.0)
    t0 = time.monotonic()
    rc = sup.run()
    assert time.monotonic() - t0 < 30.0
    assert rc == 5
    codes = sup.report()["incidents"][0]["rank_exit_codes"]
    assert codes[1] == 5
    assert codes[0] < 0          # straggler signalled, not left behind


def test_supervisor_strips_rank_chaos_env_on_relaunch(tmp_path):
    """An injected incident happens ONCE: MXTPU_CHAOS_RANK_* reaches
    generation 0 only, so the recovered gang cannot re-kill itself
    forever."""
    cmd = _gen_rank_cmd(
        "sys.exit(4 if os.environ.get('MXTPU_CHAOS_RANK_0') else 0)")
    env = dict(os.environ, MXTPU_CHAOS_RANK_0="worker.kill:kind=kill")
    sup = GangSupervisor(cmd, 2, gang_dir=str(tmp_path), base_env=env,
                         max_restarts=2, backoff_s=0.05)
    assert sup.run() == 0
    assert sup.restarts == 1     # gen 0 died via the env, gen 1 clean


def test_supervisor_clears_stale_heartbeats_between_generations(
        tmp_path):
    """A dead previous generation's heartbeat files must not poison
    the relaunched gang with instant PeerLost."""
    stale = os.path.join(str(tmp_path), "rank_7.hb")
    with open(stale, "w") as f:
        f.write(json.dumps({"rank": 7, "pid": 2 ** 22 + 1,
                            "heartbeat": time.time() - 1e6}))
    cmd = _gen_rank_cmd("sys.exit(0)")
    sup = GangSupervisor(cmd, 2, gang_dir=str(tmp_path),
                         max_restarts=0, backoff_s=0.05)
    assert sup.run() == 0
    assert not os.path.exists(stale)


def test_supervisor_adopts_externally_spawned_gang(tmp_path):
    """`adopt()` attaches supervision to ranks the caller already
    launched: liveness watching, teardown, and restart (spawned by the
    supervisor from then on) all apply."""
    cmd = _gen_rank_cmd("sys.exit(0 if g else 2)")
    sup = GangSupervisor(cmd, 2, gang_dir=str(tmp_path),
                         max_restarts=1, backoff_s=0.05)
    # external launcher: generation "0" spawned by the caller (crash)
    external = [subprocess.Popen(
        [sys.executable, "-c", "import sys; sys.exit(2)"])
        for _ in range(2)]
    rc = sup.run(procs=external)
    assert rc == 0                 # relaunched generation exits clean
    assert sup.restarts == 1


def test_supervisor_record_written_for_kill_stale(tmp_path):
    cmd = _gen_rank_cmd("sys.exit(0)")
    sup = GangSupervisor(cmd, 2, gang_dir=str(tmp_path),
                         max_restarts=0, backoff_s=0.05)
    sup.run()
    rec = json.loads(open(
        os.path.join(str(tmp_path), "supervisor.json")).read())
    assert rec["what"] == "gang-supervisor"
    assert rec["pid"] == os.getpid()
    assert rec["nranks"] == 2
    assert isinstance(rec["starttime"], int)


# -- two-phase checkpoint commit -----------------------------------------

def _mini_rig():
    """(make_trainer, x, y): trainers share ONE net so checkpoints
    restore across instances (param names are per-net)."""
    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu import gluon
    from mxnet_tpu.gluon import nn
    from mxnet_tpu.parallel import make_mesh, ShardedTrainer
    m = nn.HybridSequential()
    m.add(nn.Dense(8, activation="relu"), nn.Dense(4))
    m.initialize()
    m(mx.nd.zeros((1, 6)))
    loss = gluon.loss.SoftmaxCrossEntropyLoss()

    def make_trainer():
        return ShardedTrainer(m, lambda o, l: loss(o, l), "sgd",
                              {"learning_rate": 0.05},
                              mesh=make_mesh({"dp": 8}))
    rng = np.random.RandomState(0)
    x = rng.randn(8, 6).astype("float32")
    y = (np.arange(8) % 4).astype("float32")
    return make_trainer, x, y


def test_commit_manifest_written_and_step_committed(tmp_path):
    from mxnet_tpu.parallel.checkpoint import (TrainerCheckpoint,
                                               COMMIT_BASENAME)
    mk, x, y = _mini_rig()
    tr = mk()
    with TrainerCheckpoint(tmp_path / "ck") as ck:
        tr.step(x, y)
        ck.save(1, tr, wait=True)
        marker = os.path.join(str(tmp_path / "ck"), "1",
                              COMMIT_BASENAME)
        assert os.path.exists(marker)
        manifest = json.loads(open(marker).read())
        assert manifest["step"] == 1
        assert manifest["files"]           # per-file sha256/size map
        for ent in manifest["files"].values():
            assert len(ent["sha256"]) == 64 and ent["bytes"] >= 0
        assert ck.committed_steps() == [1]


def test_restore_latest_refuses_uncommitted_step(tmp_path):
    """A gang killed mid-save leaves the newest step without its
    commit marker: restore_latest must fall back to the previous
    committed step, never resume from the torn one."""
    from mxnet_tpu.observability import registry as obs
    from mxnet_tpu.parallel.checkpoint import TrainerCheckpoint
    mk, x, y = _mini_rig()
    tr = mk()
    rejected = obs.REGISTRY.get("checkpoint.rejected")
    with TrainerCheckpoint(tmp_path / "ck") as ck:
        for s in (1, 2):
            tr.step(x, y)
            ck.save(s, tr, wait=True)
        os.unlink(ck._commit_path(2))       # the torn-save signature
        before = rejected.total()
        tr2 = mk()
        with pytest.warns(RuntimeWarning, match="step 2 .* unreadable"):
            assert ck.restore_latest(tr2) == 1
        assert rejected.total() > before


def test_restore_latest_refuses_checksum_mismatch(tmp_path):
    """A step whose data was silently truncated/corrupted AFTER commit
    fails manifest verification and is rejected the same way."""
    from mxnet_tpu.parallel.checkpoint import (TrainerCheckpoint,
                                               COMMIT_BASENAME)
    mk, x, y = _mini_rig()
    tr = mk()
    with TrainerCheckpoint(tmp_path / "ck") as ck:
        for s in (1, 2):
            tr.step(x, y)
            ck.save(s, tr, wait=True)
        step_dir = os.path.join(str(tmp_path / "ck"), "2")
        clobbered = 0
        for root, _dirs, files in os.walk(step_dir):
            for fn in files:
                if fn in (COMMIT_BASENAME, "_CHECKPOINT_METADATA"):
                    continue
                with open(os.path.join(root, fn), "wb") as f:
                    f.write(b"\x00torn\x00")
                clobbered += 1
        assert clobbered
        tr2 = mk()
        with pytest.warns(RuntimeWarning, match="checksum"):
            assert ck.restore_latest(tr2) == 1


def test_rejected_step_is_dropped_so_resume_can_resave_it(tmp_path):
    """The recovery loop re-trains and RE-SAVES the very step whose
    torn save was rejected; the corpse must be gone or orbax raises
    StepAlreadyExistsError and recovery becomes a crash loop."""
    from mxnet_tpu.parallel.checkpoint import TrainerCheckpoint
    mk, x, y = _mini_rig()
    tr = mk()
    with TrainerCheckpoint(tmp_path / "ck") as ck:
        for s in (1, 2):
            tr.step(x, y)
            ck.save(s, tr, wait=True)
        os.unlink(ck._commit_path(2))       # torn save of step 2
    # a fresh manager (the relaunched gang) restores, then re-saves 2
    with TrainerCheckpoint(tmp_path / "ck") as ck2:
        tr2 = mk()
        with pytest.warns(RuntimeWarning, match="step 2"):
            assert ck2.restore_latest(tr2) == 1
        assert not os.path.isdir(
            os.path.join(str(tmp_path / "ck"), "2"))
        tr2.step(x, y)
        ck2.save(2, tr2, wait=True)         # must not raise
        assert ck2.committed_steps() == [1, 2]


def test_mixed_history_keeps_legacy_steps_restorable(tmp_path):
    """An upgraded run has pre-commit-era steps (no manifest) below a
    committed one: when the newest committed step is rejected
    (corrupted), the fallback must reach the older legacy step — only
    steps NEWER than the newest committed one count as torn."""
    from mxnet_tpu.parallel.checkpoint import TrainerCheckpoint
    mk, x, y = _mini_rig()
    tr = mk()
    with TrainerCheckpoint(tmp_path / "ck") as ck:
        for s in (1, 2):
            tr.step(x, y)
            ck.save(s, tr, wait=True)
        os.unlink(ck._commit_path(1))     # step 1: legacy (pre-upgrade)
        # corrupt the committed newest step so it fails its checksums
        step_dir = os.path.join(str(tmp_path / "ck"), "2")
        for root, _dirs, files in os.walk(step_dir):
            for fn in files:
                if fn not in ("mxtpu_commit.json",
                              "_CHECKPOINT_METADATA"):
                    with open(os.path.join(root, fn), "wb") as f:
                        f.write(b"torn")
        tr2 = mk()
        with pytest.warns(RuntimeWarning, match="step 2"):
            assert ck.restore_latest(tr2) == 1   # legacy step survives


def test_legacy_directory_without_markers_still_restores(tmp_path):
    """Checkpoints written before two-phase commit have no manifests
    anywhere — they must keep restoring (enforcement arms only once a
    committed step exists)."""
    from mxnet_tpu.parallel.checkpoint import TrainerCheckpoint
    mk, x, y = _mini_rig()
    tr = mk()
    with TrainerCheckpoint(tmp_path / "ck") as ck:
        for s in (1, 2):
            tr.step(x, y)
            ck.save(s, tr, wait=True)
        for s in (1, 2):
            os.unlink(ck._commit_path(s))   # simulate the legacy layout
        tr2 = mk()
        assert ck.restore_latest(tr2) == 2


def test_async_saves_commit_at_the_next_boundary(tmp_path):
    from mxnet_tpu.parallel.checkpoint import TrainerCheckpoint
    mk, x, y = _mini_rig()
    tr = mk()
    with TrainerCheckpoint(tmp_path / "ck", async_save=True) as ck:
        for s in (1, 2, 3):
            tr.step(x, y)
            ck.save(s, tr)                  # async, no wait
        ck.wait_until_finished()
        assert ck.committed_steps() == [1, 2, 3]
        tr2 = mk()
        assert ck.restore_latest(tr2) == 3


def test_commit_barrier_fences_the_marker(tmp_path):
    """The commit barrier runs before the marker write — the two-phase
    ordering every rank relies on."""
    from mxnet_tpu.parallel.checkpoint import TrainerCheckpoint
    mk, x, y = _mini_rig()
    tr = mk()
    order = []

    def barrier():
        # at barrier time the marker must not exist yet
        order.append(os.path.exists(ck._commit_path(1)))

    with TrainerCheckpoint(tmp_path / "ck",
                           commit_barrier=barrier) as ck:
        tr.step(x, y)
        ck.save(1, tr, wait=True)
        assert order == [False]
        assert os.path.exists(ck._commit_path(1))


# -- kill_stale SUPERVISED tag -------------------------------------------

def _kill_stale(*args):
    return subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "kill_stale.py")]
        + list(args), capture_output=True, text=True, timeout=120)


def _supervised_sleeper(gang_dir):
    """A candidate process (cmdline mentions mxnet_tpu) tagged as a
    supervised gang worker via MXTPU_GANG_DIR in its environment."""
    env = dict(os.environ, MXTPU_GANG_DIR=gang_dir)
    return subprocess.Popen(
        [sys.executable, "-S", "-c", "import time; time.sleep(600)",
         "mxnet_tpu-gang-worker"], env=env)


def _write_supervisor_record(gang_dir, pid, heartbeat_age=0.0):
    from mxnet_tpu.resilience.lease import _boot_id, _proc_starttime
    rec = {"what": "gang-supervisor", "pid": pid,
           "host": socket.gethostname(), "boot_id": _boot_id(),
           "starttime": _proc_starttime(pid) if pid else 1,
           "nranks": 2, "created": time.time() - heartbeat_age - 1,
           "heartbeat": time.time() - heartbeat_age}
    with open(os.path.join(gang_dir, "supervisor.json"), "w") as f:
        f.write(json.dumps(rec))


def test_kill_stale_refuses_supervised_worker(tmp_path):
    """A gang whose supervisor is alive is never reaped: killing a
    worker only triggers a supervisor restart. Exit 2 tells callers
    recovery is blocked (the lease-holder contract)."""
    d = str(tmp_path)
    lease = os.path.join(d, "none.lease")   # isolate from any real lease
    _write_supervisor_record(d, os.getpid())  # us: alive, fresh
    w = _supervised_sleeper(d)
    try:
        time.sleep(0.3)
        r = _kill_stale("--kill", "--lease-path", lease)
        assert "SUPERVISED" in r.stdout
        assert "refused (supervised worker" in r.stdout
        assert r.returncode == 2, r.stdout + r.stderr
        assert w.poll() is None             # still alive
    finally:
        w.kill()
        w.wait()


def test_kill_stale_dead_supervisor_removes_protection(tmp_path):
    """Supervisor gone (dead pid + stale heartbeat): the worker is an
    ordinary candidate again, not SUPERVISED."""
    d = str(tmp_path)
    lease = os.path.join(d, "none.lease")
    _write_supervisor_record(d, 2 ** 22 + 1, heartbeat_age=1000.0)
    w = _supervised_sleeper(d)
    try:
        time.sleep(0.3)
        r = _kill_stale("--lease-path", lease)   # list mode
        lines = [ln for ln in r.stdout.splitlines()
                 if "pid %d " % w.pid in ln]
        assert lines and "SUPERVISED" not in lines[0], r.stdout
    finally:
        w.kill()
        w.wait()


# -- supervision telemetry in the report ---------------------------------

def test_telemetry_report_supervision_section(tmp_path):
    sys.path.insert(0, os.path.join(ROOT, "tools"))
    import telemetry_report
    recs = [
        {"step_time": 0.1, "batch_size": 4},
        {"source": "resilience", "event": "rank_lost", "rank": 2,
         "step_time": 0.0},
        {"source": "resilience", "event": "gang_restart", "rank": 2,
         "step_time": 1.5, "restarts": 1},
        {"source": "resilience", "event": "ckpt_commit", "step": 3,
         "step_time": 0.02},
    ]
    path = tmp_path / "t.jsonl"
    with open(path, "w") as f:
        for r in recs:
            f.write(json.dumps(r) + "\n")
    s = telemetry_report.summarize(telemetry_report.load_records(
        str(path)))
    assert s["steps"] == 1                 # headline excludes resilience
    assert s["ranks_lost"] == 1 and s["ranks_lost_set"] == [2]
    assert s["gang_restarts"] == 1
    assert abs(s["gang_downtime_s"] - 1.5) < 1e-9
    assert s["ckpt_commits"] == 1
    text = telemetry_report.format_summary(s)
    assert "supervision" in text and "ckpt commit" in text
