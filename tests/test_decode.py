"""Continuous-batching decode tests (ISSUE-6, docs/serving.md).

The acceptance surface: greedy decode through the KV-cached
continuous-batching path is TOKEN-IDENTICAL to a full-context
re-forward reference at every step — including for sequences that
joined mid-batch — and each DecodeEngine compiles exactly two
decode-path programs (prefill buckets aside). Plus the scheduler edge
cases: join into a freed slot, deadline eviction at a step boundary,
drain with sequences in flight, cache-slot exhaustion reaching the
shed policy, and the bf16 serving dtype.
"""
import json
import os
import threading
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.gluon.model_zoo.gpt import GPTDecoder
from mxnet_tpu.observability import registry as obs
from mxnet_tpu.resilience import (Deadline, DeadlineExceeded,
                                  InjectedFault, chaos)
from mxnet_tpu.serving import (ContinuousBatchScheduler, DecodeEngine,
                               InferenceEngine, ModelServer,
                               RequestRejected, ServerClosed)

VOCAB, MAXLEN = 96, 32


@pytest.fixture(autouse=True)
def _clean_chaos():
    chaos.configure("")
    yield
    chaos.reset()


def make_block(seed=7, max_seq_len=MAXLEN, eos_token=None, layers=2):
    np.random.seed(seed)
    blk = GPTDecoder(VOCAB, max_seq_len=max_seq_len, num_layers=layers,
                     num_heads=2, embed_dim=16, eos_token=eos_token)
    blk.initialize(mx.init.Xavier(magnitude=2.5))
    return blk


def prompts_for(n, seed=11, lo=2, hi=10):
    rng = np.random.RandomState(seed)
    return [rng.randint(1, VOCAB, size=rng.randint(lo, hi + 1))
            for _ in range(n)]


# ---------------------------------------------------------------------------
# the model: hybridizable full forward + single-token step path
# ---------------------------------------------------------------------------

def test_gpt_hybridize_matches_eager():
    blk = make_block()
    toks = mx.nd.array(np.random.RandomState(0).randint(
        0, VOCAB, size=(2, 9)).astype(np.int32))
    eager = blk(toks).asnumpy()
    assert eager.shape == (2, 9, VOCAB)
    blk.hybridize()
    hybrid = blk(toks).asnumpy()
    assert np.array_equal(eager, hybrid)


def test_gpt_jax_forward_matches_block():
    blk = make_block()
    toks = np.random.RandomState(1).randint(0, VOCAB, size=(2, 7))
    want = blk(mx.nd.array(toks.astype(np.int32))).asnumpy()
    got = np.asarray(blk.forward_fn()(
        blk.decode_params(), toks.astype(np.int32)))
    assert np.allclose(want, got, atol=1e-5)


def test_gpt_eager_step_api():
    """step(token, kv_cache, position): the single-token path is usable
    without any engine, and matches the reference from a prompt of 1."""
    blk = make_block()
    kv = blk.init_cache(2)
    pos = np.zeros(2, np.int32)
    tok = np.array([5, 9], np.int32)
    out = []
    for _ in range(4):
        nxt, kv, pos = blk.step(tok, kv, pos)
        tok = nxt.asnumpy()
        out.append(tok.copy())
    seq = np.stack(out)     # (steps, 2)
    ref0 = blk.generate_reference([5], 4)
    ref1 = blk.generate_reference([9], 4)
    assert np.array_equal(seq[:, 0], ref0)
    assert np.array_equal(seq[:, 1], ref1)


# ---------------------------------------------------------------------------
# DecodeEngine: token identity + the exactly-two-programs invariant
# ---------------------------------------------------------------------------

def test_engine_prefill_step_token_identity():
    blk = make_block()
    eng = DecodeEngine(blk, max_slots=1, name="ti")
    for prompt in prompts_for(4, seed=3):
        out = [eng.prefill(prompt, 0)]
        for _ in range(7):
            out.append(int(eng.step()[0]))
        eng.retire(0)
        ref = blk.generate_reference(prompt, 8)
        assert np.array_equal(np.asarray(out), ref), prompt


def test_exactly_two_decode_programs():
    """Prefill buckets aside, a DecodeEngine compiles exactly TWO
    decode-path programs (admit + step) — however many prompts, slots,
    lengths, or join/leave cycles it serves. Checked against both the
    engine's own counter and jax's jit cache sizes (the latter catches
    silent retraces the logical counter can't)."""
    blk = make_block()
    eng = DecodeEngine(blk, max_slots=3, name="two")
    sched = ContinuousBatchScheduler(eng, max_new_tokens=5).start()
    handles = [sched.submit(p) for p in prompts_for(9, seed=5, hi=17)]
    for h in handles:
        h.result(timeout=60)
    assert sched.drain(timeout=30)
    progs = eng.compiled_programs
    non_prefill = {k: v for k, v in progs.items() if k != "prefill"}
    assert non_prefill == {"admit": 1, "step": 1}, progs
    assert 1 <= progs["prefill"] <= 6     # <= log2(max_seq_len)+1
    sizes = eng.xla_cache_sizes()
    if sizes:                              # newer jax exposes the cache
        assert sizes["admit"] + sizes["step"] == 2, sizes
        assert sizes["prefill"] == progs["prefill"], sizes
    # the compile counter metric agrees
    counter = obs.REGISTRY.get("serving.decode.compiles")
    assert counter.get(engine="two", kind="admit") == 1
    assert counter.get(engine="two", kind="step") == 1
    assert counter.get(engine="two", kind="prefill") == progs["prefill"]


def test_continuous_batching_token_identity_with_joins():
    """More sequences than slots, random lengths: late sequences join
    mid-batch into freed slots, and every one of them still decodes
    token-identically to the full re-forward reference."""
    blk = make_block(seed=19)
    eng = DecodeEngine(blk, max_slots=3, name="joins")
    sched = ContinuousBatchScheduler(eng, max_new_tokens=9).start()
    prompts = prompts_for(10, seed=23, hi=12)
    handles = [sched.submit(p) for p in prompts]
    outs = [h.result(timeout=60) for h in handles]
    stats = sched.stats()
    assert stats["served"] == len(prompts)
    for prompt, out in zip(prompts, outs):
        ref = blk.generate_reference(prompt, 9)
        assert np.array_equal(out, ref), (prompt, out, ref)
    assert sched.drain(timeout=30)


def test_staggered_joins_stay_token_identical():
    """Sequences submitted while others are mid-decode (true mid-flight
    joins, not a starting burst) produce identical tokens."""
    blk = make_block(seed=29)
    eng = DecodeEngine(blk, max_slots=2, name="stagger")
    sched = ContinuousBatchScheduler(eng, max_new_tokens=12).start()
    prompts = prompts_for(6, seed=31)
    handles = []
    for i, p in enumerate(prompts):
        handles.append(sched.submit(p))
        time.sleep(0.004)      # land between decode steps
    for prompt, h in zip(prompts, handles):
        assert np.array_equal(h.result(timeout=60),
                              blk.generate_reference(prompt, 12))
    sched.drain(timeout=30)


def test_eos_token_stops_generation():
    blk = make_block(seed=37)
    prompt = prompts_for(1, seed=41)[0]
    ref = blk.generate_reference(prompt, 8)
    eos = int(ref[3])
    eng = DecodeEngine(blk, max_slots=1, name="eos")
    sched = ContinuousBatchScheduler(eng, max_new_tokens=8).start()
    out = sched.generate(prompt, eos_token=eos, timeout=60)
    stop = int(np.argmax(ref == eos)) + 1
    assert np.array_equal(out, ref[:stop])
    sched.drain(timeout=30)


def test_cache_full_retires_sequence():
    """A sequence that fills its cache slot resolves with what it has
    instead of stepping past max_seq_len."""
    blk = make_block(max_seq_len=8)
    eng = DecodeEngine(blk, max_slots=1, name="full")
    sched = ContinuousBatchScheduler(eng, max_new_tokens=50).start()
    out = sched.generate(np.arange(1, 5), timeout=60)   # 4 prompt toks
    # prefill leaves position 4; steps write at 4..7 -> 1 prefill token
    # + tokens until the slot is full
    assert 1 <= len(out) <= 5
    assert np.array_equal(out, blk.generate_reference(np.arange(1, 5),
                                                      len(out)))
    sched.drain(timeout=30)


def test_prompt_validation():
    blk = make_block()
    eng = DecodeEngine(blk, max_slots=1)
    sched = ContinuousBatchScheduler(eng)
    with pytest.raises(mx.MXNetError):
        sched.submit([])
    with pytest.raises(mx.MXNetError):
        sched.submit(np.arange(MAXLEN + 1))
    with pytest.raises(mx.MXNetError):
        sched.submit([1, 2], max_new_tokens=0)


# ---------------------------------------------------------------------------
# scheduler edge cases
# ---------------------------------------------------------------------------

def test_join_into_freed_slot_single_slot():
    """slots=1 serializes sequences through one cache slot: every later
    request joins only when the slot frees, and all still finish."""
    blk = make_block()
    eng = DecodeEngine(blk, max_slots=1, name="one")
    sched = ContinuousBatchScheduler(eng, max_new_tokens=4).start()
    prompts = prompts_for(4, seed=43)
    outs = [sched.submit(p) for p in prompts]
    for prompt, h in zip(prompts, outs):
        assert np.array_equal(h.result(timeout=60),
                              blk.generate_reference(prompt, 4))
    assert sched.stats()["served"] == 4
    sched.drain(timeout=30)


def test_deadline_eviction_at_step_boundary():
    """An in-flight sequence whose Deadline runs out is EVICTED between
    steps: rejected with DeadlineExceeded, slot freed, eviction
    counted — and a co-resident sequence without a deadline finishes
    normally."""
    blk = make_block(max_seq_len=128)
    eng = DecodeEngine(blk, max_slots=2, name="evict")
    sched = ContinuousBatchScheduler(eng, max_new_tokens=120).start()
    # chaos stretches each decode step so the 60ms budget dies mid-
    # generation, deterministically
    chaos.configure("serving.decode:kind=sleep,secs=0.01")
    doomed = sched.submit(np.arange(1, 4), deadline=Deadline(0.06))
    safe = sched.submit(np.arange(4, 9), max_new_tokens=3)
    assert np.array_equal(safe.result(timeout=60),
                          blk.generate_reference(np.arange(4, 9), 3))
    with pytest.raises(DeadlineExceeded):
        doomed.result(timeout=60)
    assert doomed.generated, "evicted mid-flight, not at admission"
    stats = sched.stats()
    assert stats["evicted"] == 1
    # the freed slot is reusable: a follow-up request still decodes
    chaos.configure("")
    again = sched.generate(np.arange(1, 4), max_new_tokens=2,
                           timeout=60)
    assert np.array_equal(again,
                          blk.generate_reference(np.arange(1, 4), 2))
    sched.drain(timeout=30)


def test_deadline_rejected_at_admission():
    """A request already expired when its turn comes is rejected
    without ever being prefilled."""
    blk = make_block()
    eng = DecodeEngine(blk, max_slots=1, name="adm")
    sched = ContinuousBatchScheduler(eng, max_new_tokens=4)
    h = sched.submit([1, 2, 3], deadline=Deadline(0.0))
    steps_before = eng.steps
    sched.start()
    with pytest.raises(DeadlineExceeded):
        h.result(timeout=30)
    assert not h.generated              # never produced a token
    assert eng.steps == steps_before    # never computed
    sched.drain(timeout=30)


def test_drain_finishes_sequences_in_flight():
    blk = make_block()
    eng = DecodeEngine(blk, max_slots=2, name="drain")
    sched = ContinuousBatchScheduler(eng, max_new_tokens=6).start()
    prompts = prompts_for(5, seed=47)
    handles = [sched.submit(p) for p in prompts]
    assert sched.drain(timeout=60)
    # every admitted AND queued sequence finished with full output
    for prompt, h in zip(prompts, handles):
        assert np.array_equal(h.result(timeout=0.1),
                              blk.generate_reference(prompt, 6))
    with pytest.raises(ServerClosed):
        sched.submit([1, 2])


def test_slot_exhaustion_reaches_shed_policy():
    """With every slot busy the queue backs up; past queue_depth the
    shed policy applies — reject refuses the newcomer, drop_oldest
    evicts the stalest queued request in its favor."""
    chaos.configure("serving.decode:kind=sleep,secs=0.02")
    blk = make_block()
    eng = DecodeEngine(blk, max_slots=1, name="shed")
    sched = ContinuousBatchScheduler(eng, max_new_tokens=20,
                                     queue_depth=2).start()
    running = sched.submit([1, 2, 3])       # occupies the slot
    time.sleep(0.03)                        # let it admit
    q1, q2 = sched.submit([4, 5]), sched.submit([5, 6])
    with pytest.raises(RequestRejected):
        sched.submit([6, 7])                # queue full -> shed
    assert sched.stats()["shed"] == 1
    chaos.configure("")
    for h in (running, q1, q2):
        h.result(timeout=60)
    sched.drain(timeout=30)

    # drop_oldest: the newcomer displaces the stalest queued request
    chaos.configure("serving.decode:kind=sleep,secs=0.02")
    eng2 = DecodeEngine(blk, max_slots=1, name="shed2")
    sched2 = ContinuousBatchScheduler(eng2, max_new_tokens=20,
                                      queue_depth=1,
                                      shed_policy="drop_oldest").start()
    sched2.submit([1, 2, 3])
    time.sleep(0.03)
    victim = sched2.submit([4, 5])
    newcomer = sched2.submit([5, 6])        # evicts `victim`
    with pytest.raises(RequestRejected):
        victim.result(timeout=30)
    chaos.configure("")
    newcomer.result(timeout=60)
    sched2.drain(timeout=30)


def test_chaos_step_fault_fails_inflight_and_recovers():
    """An injected fault at the serving.decode site is delivered to
    every in-flight sequence; the scheduler clears the slots and keeps
    serving later traffic."""
    blk = make_block()
    eng = DecodeEngine(blk, max_slots=2, name="chaos")
    sched = ContinuousBatchScheduler(eng, max_new_tokens=4).start()
    chaos.configure("serving.decode:kind=raise,n=1")
    h = sched.submit([1, 2, 3, 4])
    with pytest.raises(InjectedFault):
        h.result(timeout=30)
    # next request decodes normally (n=1: the fault tripped once)
    out = sched.generate([1, 2, 3, 4], timeout=60)
    assert np.array_equal(out, blk.generate_reference([1, 2, 3, 4], 4))
    sched.drain(timeout=30)


# ---------------------------------------------------------------------------
# bf16 serving dtype (MXTPU_SERVE_DTYPE)
# ---------------------------------------------------------------------------

def _mlp(nf=16, nh=24, nc=6, seed=5):
    data = mx.sym.var("data")
    h = mx.sym.FullyConnected(data=data, num_hidden=nh, name="fc1")
    h = mx.sym.Activation(data=h, act_type="relu")
    out = mx.sym.SoftmaxOutput(
        data=mx.sym.FullyConnected(data=h, num_hidden=nc, name="fc2"),
        name="softmax")
    rng = np.random.RandomState(seed)
    args = {
        "fc1_weight": mx.nd.array(rng.randn(nh, nf).astype("f") * 0.2),
        "fc1_bias": mx.nd.array(rng.randn(nh).astype("f") * 0.1),
        "fc2_weight": mx.nd.array(rng.randn(nc, nh).astype("f") * 0.2),
        "fc2_bias": mx.nd.array(rng.randn(nc).astype("f") * 0.1)}
    return out, args, nf


def test_bf16_inference_engine_parity_within_tolerance():
    sym, args, nf = _mlp()
    e32 = InferenceEngine.from_symbol(sym, args, {}, {"data": (nf,)}, 8)
    e16 = InferenceEngine.from_symbol(sym, args, {}, {"data": (nf,)}, 8,
                                      dtype="bf16")
    assert e32.dtype == "fp32" and e16.dtype == "bf16"
    x = np.random.RandomState(9).randn(5, nf).astype(np.float32)
    o32 = e32.infer(x)[0].asnumpy()
    o16 = e16.infer(x)[0].asnumpy()
    # responses stay fp32 regardless of the compute dtype
    assert o16.dtype == np.float32
    assert not np.array_equal(o32, o16)      # genuinely bf16 inside
    assert np.allclose(o32, o16, rtol=0.05, atol=0.02)
    # same compile-cache bound as fp32
    assert e16.buckets == e32.buckets


def test_bf16_env_var_selects_dtype():
    sym, args, nf = _mlp()
    os.environ["MXTPU_SERVE_DTYPE"] = "bf16"
    try:
        eng = InferenceEngine.from_symbol(sym, args, {},
                                          {"data": (nf,)}, 4)
        assert eng.dtype == "bf16"
    finally:
        del os.environ["MXTPU_SERVE_DTYPE"]
    with pytest.raises(mx.MXNetError):
        InferenceEngine.from_symbol(sym, args, {}, {"data": (nf,)}, 4,
                                    dtype="int7")


def test_bf16_decode_engine_generates():
    """bf16 decode: params and KV cache in bfloat16, greedy tokens out;
    still exactly two decode-path programs, and the tokens track the
    fp32 reference for a short horizon (argmax over well-separated
    logits survives the precision drop)."""
    blk = make_block(seed=53)
    eng = DecodeEngine(blk, max_slots=2, dtype="bf16", name="bf16")
    assert eng._cache_k.dtype == np.dtype("bfloat16")
    sched = ContinuousBatchScheduler(eng, max_new_tokens=3).start()
    prompt = prompts_for(1, seed=59)[0]
    out = sched.generate(prompt, timeout=60)
    assert out.dtype == np.int32 and len(out) == 3
    assert np.array_equal(out, blk.generate_reference(prompt, 3))
    non_prefill = {k: v for k, v in eng.compiled_programs.items()
                   if k != "prefill"}
    assert non_prefill == {"admit": 1, "step": 1}
    sched.drain(timeout=30)


# ---------------------------------------------------------------------------
# ModelServer: the second engine kind
# ---------------------------------------------------------------------------

def test_model_server_decode_kind():
    blk = make_block(seed=61)
    eng = DecodeEngine(blk, max_slots=2, name="srv")
    prompts = prompts_for(5, seed=67)
    with ModelServer(eng, num_workers=1, max_new_tokens=5,
                     warmup=True) as server:
        assert server.kind == "decode"
        handles = [server.submit(p) for p in prompts]
        for prompt, h in zip(prompts, handles):
            assert np.array_equal(h.result(timeout=60),
                                  blk.generate_reference(prompt, 5))
        out = server.generate(prompts[0], max_new_tokens=2, timeout=60)
        assert np.array_equal(out, blk.generate_reference(prompts[0], 2))
        stats = server.stats()
        assert stats["kind"] == "decode"
        assert stats["served"] == len(prompts) + 1
        assert stats["max_slots"] == 2
    # context exit drained: new submits refused
    with pytest.raises(ServerClosed):
        server.submit(prompts[0])


def test_model_server_decode_drain_finishes_inflight():
    blk = make_block(seed=71)
    eng = DecodeEngine(blk, max_slots=1, name="srvdrain")
    server = ModelServer(eng, num_workers=1, max_new_tokens=6).start()
    handles = [server.submit(p) for p in prompts_for(3, seed=73)]
    assert server.drain(timeout=60)
    for h in handles:
        assert len(h.result(timeout=0.1)) == 6


def test_model_server_decode_sigterm_drains():
    """SIGTERM under handle_signals() must actually drain the decode
    schedulers (the handler only sets a flag; the watcher thread does
    the close), finishing in-flight sequences and refusing new ones."""
    import signal as _signal
    blk = make_block(seed=97)
    eng = DecodeEngine(blk, max_slots=1, name="sig")
    server = ModelServer(eng, num_workers=1, max_new_tokens=6).start()
    with server.handle_signals():
        handles = [server.submit(p) for p in prompts_for(3, seed=101)]
        _signal.raise_signal(_signal.SIGTERM)
        deadline = time.perf_counter() + 10
        while not all(s.closed for s in server._schedulers):
            assert time.perf_counter() < deadline, "watcher never closed"
            time.sleep(0.01)
        with pytest.raises(ServerClosed):
            server.submit([1, 2])
        for h in handles:               # accepted work still finishes
            assert len(h.result(timeout=60)) == 6
    assert server.drain(timeout=30)


def test_decode_server_rejects_forward_kwargs():
    blk = make_block(seed=103)
    eng = DecodeEngine(blk, max_slots=1)
    with pytest.raises(mx.MXNetError):
        ModelServer(eng, max_batch_size=8)
    with pytest.raises(mx.MXNetError):
        ModelServer(eng, max_wait_ms=5.0)


def test_bf16_engine_set_params_keeps_dtype():
    """Swapping fp32 weights into a bf16 engine must stage them in
    bf16 (no silent fp32 retrace of the warm buckets)."""
    sym, args, nf = _mlp()
    eng = InferenceEngine.from_symbol(sym, args, {}, {"data": (nf,)}, 4,
                                      dtype="bf16")
    eng.warmup()
    compiled = eng.compiled_buckets
    eng.set_params({"fc1_weight":
                    mx.nd.array(np.ones((24, nf), np.float32))})
    assert all(v.dtype == np.dtype("bfloat16")
               for v in eng._params.values())
    eng.infer(np.zeros((3, nf), np.float32))
    assert eng.compiled_buckets == compiled


def test_forward_server_rejects_decode_kwargs():
    sym, args, nf = _mlp()
    eng = InferenceEngine.from_symbol(sym, args, {}, {"data": (nf,)}, 4)
    with ModelServer(eng) as server:
        with pytest.raises(mx.MXNetError):
            server.submit(np.zeros((1, nf), np.float32),
                          max_new_tokens=4)


# ---------------------------------------------------------------------------
# observability wiring
# ---------------------------------------------------------------------------

def test_decode_telemetry_records(tmp_path, monkeypatch):
    path = tmp_path / "decode.jsonl"
    monkeypatch.setenv("MXTPU_TELEMETRY", str(path))
    blk = make_block(seed=79)
    eng = DecodeEngine(blk, max_slots=2, name="tel")
    sched = ContinuousBatchScheduler(eng, max_new_tokens=4).start()
    for h in [sched.submit(p) for p in prompts_for(3, seed=83)]:
        h.result(timeout=60)
    sched.drain(timeout=30)
    records = [json.loads(l) for l in
               path.read_text().splitlines() if l.strip()]
    steps = [r for r in records if r["source"] == "decode"
             and r.get("event") != "request"]
    reqs = [r for r in records if r.get("event") == "request"]
    assert steps and len(reqs) == 3
    for r in steps:
        assert {"step_time", "tokens", "fill_ratio",
                "queue_depth"} <= set(r)
    for r in reqs:
        assert r["tokens"] == 4
        assert r["ttft_s"] > 0
        assert "intertoken_s" in r

    # the report renders a decode section and stays strict
    import subprocess
    import sys
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    res = subprocess.run(
        [sys.executable, os.path.join(root, "tools",
                                      "telemetry_report.py"),
         "--json", str(path)],
        capture_output=True, text=True, timeout=120)
    assert res.returncode == 0, res.stderr
    summary = json.loads(res.stdout)
    assert summary["decode_requests"] == 3
    assert summary["decode_tokens"] >= 9   # step tokens (3 via prefill)
    assert "decode_ttft_p95_s" in summary
    assert "decode_intertoken_p50_s" in summary


def test_decode_metrics_registered():
    blk = make_block(seed=89)
    eng = DecodeEngine(blk, max_slots=1, name="met")
    sched = ContinuousBatchScheduler(eng, max_new_tokens=3).start()
    sched.generate([2, 3, 4], timeout=60)
    sched.drain(timeout=30)
    ttft = obs.REGISTRY.get("serving.decode.ttft")
    assert ttft.percentile(0.5, engine="met") > 0
    tokens = obs.REGISTRY.get("serving.decode.tokens")
    assert tokens.get(engine="met") == 3
    fill = obs.REGISTRY.get("serving.decode.slot.fill_ratio")
    # one slot, always full — p50 lands in the top histogram bucket
    assert fill.percentile(0.5, engine="met") >= 0.9
