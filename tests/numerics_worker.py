"""Supervised training worker for the numerics-guard restart tests.

A single-rank deterministic training loop through the REAL fused-update
path (`optimizer.get_updater` -> `parallel.FusedUpdater`, so the
`grad.post` chaos corruption site and the in-graph isfinite skip both
apply), checkpointing every step through `TrainerCheckpoint`'s
committed manifests, with a `NumericsGuard` wired for divergence
rollback. Under `tools/launch.py --supervise -n 1` with a
`MXTPU_CHAOS_RANK_0="grad.post:kind=bitflip,..."` injection the chain
to prove is (ISSUE 10 acceptance):

    bitflip at step K -> in-graph skip (non-finite grads preserved
    pre-step weights bit-identically) and/or loss spike -> divergence
    watchdog -> rollback (suspect committed steps dropped, last trusted
    restored) -> TrainingDiverged exit 77 -> supervisor relaunch (chaos
    stripped from generation 1) -> resume -> final params BIT-IDENTICAL
    to an uninterrupted run.

Gradients are a pure function of (step, params): grad_i = 0.1*w_i +
0.01*noise(step), so replaying rolled-back steps from a bit-identical
restored state reproduces the reference trajectory bit-for-bit — the
`tests/test_gang_restart.py` oracle applied to numerics.

Events land in `<out>.r0.jsonl`:
  {"event": "start", "restored_step": ..., "generation": ...}
  {"event": "done", "step": ..., "params_hex": <float32 bytes>}
"""
import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--dim", type=int, default=8)
    ap.add_argument("--ckpt-dir", required=True)
    ap.add_argument("--out", required=True)
    args = ap.parse_args()

    import jax
    jax.config.update("jax_platforms", "cpu")

    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu import optimizer as opt
    from mxnet_tpu.parallel.checkpoint import TrainerCheckpoint
    from mxnet_tpu.resilience import (at_step_boundary, numerics,
                                      run_supervised)

    out_path = "%s.r0.jsonl" % args.out

    def emit(rec):
        with open(out_path, "a") as f:
            f.write(json.dumps(rec, sort_keys=True) + "\n")
            f.flush()

    class _State:
        """TrainerCheckpoint state contract with host (numpy) truth —
        replicated-and-serializable, exactly the gang_worker shape."""

        def __init__(self):
            self._params = {
                "w0": np.full((args.dim,), 1.5, "float32"),
                "w1": np.full((args.dim,), -0.8, "float32")}
            self._aux = {}
            self._opt_state = {}
            self._step_count = 0

    st = _State()
    ck = TrainerCheckpoint(args.ckpt_dir, max_to_keep=None)
    restored = ck.restore_latest(st)
    emit({"event": "start", "restored_step": restored,
          "generation": int(os.environ.get("MXTPU_GANG_GENERATION",
                                           -1))})

    guard = numerics.NumericsGuard(source="numerics_worker")
    guard.attach_rollback(ck, st)
    # momentum-less SGD: no optimizer state to round-trip, and the two
    # same-lane params still fuse into ONE group -> one grad.post draw
    # per step, which makes the chaos spec's `after=K` count steps
    updater = opt.get_updater(opt.create("sgd", learning_rate=0.05))

    def body():
        for step in range(st._step_count + 1, args.steps + 1):
            at_step_boundary()
            rng = np.random.RandomState(9991 * step)
            ws = [mx.nd.array(st._params["w0"]),
                  mx.nd.array(st._params["w1"])]
            gs = []
            noise = rng.randn(2, args.dim).astype("float32")
            for i, k in enumerate(("w0", "w1")):
                gs.append(mx.nd.array(
                    (np.float32(0.1) * st._params[k]
                     + np.float32(0.01) * noise[i]).astype("float32")))
            updater.update_all([0, 1], gs, ws)
            st._params = {"w0": np.asarray(ws[0]._data),
                          "w1": np.asarray(ws[1]._data)}
            st._step_count = step
            # float32 loss on purpose: corrupted (huge) weights must
            # overflow to inf so the watchdog sees a non-finite value
            loss = float(np.sum(np.square(st._params["w0"]),
                                dtype=np.float32)
                         + np.sum(np.square(st._params["w1"]),
                                  dtype=np.float32))
            ck.save(step, st, wait=True)
            # boundary AFTER the save: a diverged verdict must be able
            # to drop the step just saved (it captured suspect weights)
            guard.step_boundary(step=step, loss=loss)
        emit({"event": "done", "step": st._step_count,
              "params_hex": (np.asarray(st._params["w0"], "float32")
                             .tobytes()
                             + np.asarray(st._params["w1"], "float32")
                             .tobytes()).hex()})
        print("NUMERICS_WORKER_DONE", flush=True)

    run_supervised(body)


if __name__ == "__main__":
    main()
