"""Contrib tests: quantization, contrib ops (NMS/multibox/CTC), text."""
import collections

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.contrib import quantize_model
from mxnet_tpu.contrib.text import Vocabulary
from mxnet_tpu.contrib.text.embedding import CustomEmbedding
from mxnet_tpu.contrib.text.utils import count_tokens_from_str


def test_quantize_model_close_to_fp32():
    np.random.seed(0)
    X = np.random.randn(64, 8).astype("float32")
    net = mx.sym.SoftmaxOutput(mx.sym.FullyConnected(
        mx.sym.Activation(mx.sym.FullyConnected(
            mx.sym.var("data"), num_hidden=16, name="fc1"),
            act_type="relu"),
        num_hidden=4, name="fc2"), name="softmax")
    args = {"fc1_weight": mx.nd.random.normal(shape=(16, 8)),
            "fc1_bias": mx.nd.zeros((16,)),
            "fc2_weight": mx.nd.random.normal(shape=(4, 16)),
            "fc2_bias": mx.nd.zeros((4,))}
    it = mx.io.NDArrayIter(X, np.zeros(64, "float32"), batch_size=16)
    qsym, qargs, _ = quantize_model(net, args, {}, calib_data=it,
                                    num_calib_examples=32,
                                    quantize_mode="qdq")
    common = {"data": mx.nd.array(X[:16]),
              "softmax_label": mx.nd.zeros((16,))}
    out_fp = net.bind(mx.cpu(), args={**args, **common},
                      grad_req="null").forward()[0].asnumpy()
    out_q = qsym.bind(mx.cpu(), args={**qargs, **common},
                      grad_req="null").forward()[0].asnumpy()
    # int8 QDQ on data, weights, biases AND activations: ~1% of range
    assert np.abs(out_fp - out_q).max() < 0.1
    # and the rewrite really must quantize internal activations
    from mxnet_tpu.graph import topo_order
    qdq = [n.name for n in topo_order(qsym._entries)
           if not n.is_variable and n.op.name == "_contrib_qdq"]
    assert any("relu" in n or "activation" in n for n in qdq) or \
        len(qdq) >= 6


def test_quantize_dequantize_roundtrip():
    x = mx.nd.array(np.linspace(-3, 3, 32, dtype="float32"))
    q, mn, mx_ = mx.nd.contrib.quantize(x, mx.nd.array([-3.0]),
                                        mx.nd.array([3.0]))
    assert q.dtype == np.int8
    back = mx.nd.contrib.dequantize(q, mn, mx_)
    assert np.abs(back.asnumpy() - x.asnumpy()).max() < 3.0 / 127 + 1e-6


def test_box_nms():
    # three boxes: two overlapping (keep higher score), one separate
    boxes = mx.nd.array([[[0, 0.9, 0.0, 0.0, 1.0, 1.0],
                          [0, 0.8, 0.05, 0.05, 1.0, 1.0],
                          [1, 0.7, 2.0, 2.0, 3.0, 3.0]]])
    out = mx.nd.contrib.box_nms(boxes, overlap_thresh=0.5,
                                id_index=0).asnumpy()[0]
    kept = out[out[:, 1] > 0]
    assert len(kept) == 2
    assert np.isclose(kept[0, 1], 0.9)
    assert np.isclose(kept[1, 1], 0.7)


def test_box_iou():
    a = mx.nd.array([[0.0, 0.0, 1.0, 1.0]])
    b = mx.nd.array([[0.5, 0.5, 1.5, 1.5], [2.0, 2.0, 3.0, 3.0]])
    iou = mx.nd.contrib.box_iou(a, b).asnumpy()
    assert np.isclose(iou[0, 0], 0.25 / 1.75, atol=1e-5)
    assert iou[0, 1] == 0


def test_multibox_prior_shapes():
    x = mx.nd.zeros((1, 8, 4, 4))
    anchors = mx.nd.contrib.MultiBoxPrior(
        x, sizes=(0.5, 0.25), ratios=(1, 2)).asnumpy()
    assert anchors.shape == (1, 4 * 4 * 3, 4)
    # centers in (0,1)
    cx = (anchors[0, :, 0] + anchors[0, :, 2]) / 2
    assert (cx > 0).all() and (cx < 1).all()


def test_multibox_target_detection_roundtrip():
    anchors = mx.nd.contrib.MultiBoxPrior(mx.nd.zeros((1, 4, 2, 2)),
                                          sizes=(0.5,), ratios=(1,))
    # one GT box near the first anchor
    label = mx.nd.array([[[0, 0.0, 0.0, 0.55, 0.55]]])
    cls_pred = mx.nd.zeros((1, 2, anchors.shape[1]))
    bt, bm, ct = mx.nd.contrib.MultiBoxTarget(anchors, label, cls_pred)
    ct = ct.asnumpy()
    assert (ct == 1).sum() >= 1  # at least the forced match
    # decode a perfect prediction back to the GT box
    loc_pred = bt  # predicting exactly the target must recover the box
    probs = mx.nd.array(np.stack(
        [np.where(ct == 1, 0.1, 0.9), np.where(ct == 1, 0.9, 0.1)],
        axis=1))
    det = mx.nd.contrib.MultiBoxDetection(probs, loc_pred, anchors,
                                          nms_threshold=0.5).asnumpy()
    best = det[0][det[0, :, 1].argmax()]
    assert best[0] == 0  # class id
    np.testing.assert_allclose(best[2:6], [0.0, 0.0, 0.55, 0.55],
                               atol=0.05)


def test_ctc_loss_matches_bruteforce():
    """2-frame, 3-class brute force check."""
    T, N, C = 2, 1, 3
    logits = np.log(np.array(
        [[[0.6, 0.3, 0.1]], [[0.2, 0.5, 0.3]]], dtype="float32"))
    label = np.array([[1]], dtype="float32")  # single symbol '1'
    loss = mx.nd.contrib.CTCLoss(mx.nd.array(logits),
                                 mx.nd.array(label)).asnumpy()[0]
    # paths for label [1] with blank=0 over 2 frames:
    # (1,1), (0,1), (1,0)
    p = 0.3 * 0.5 + 0.6 * 0.5 + 0.3 * 0.2
    assert np.isclose(loss, -np.log(p), atol=1e-4)


def test_vocabulary_and_embedding(tmp_path):
    counter = count_tokens_from_str("a b b c c c")
    v = Vocabulary(counter, min_freq=2)
    assert v.to_indices("c") == 1  # most frequent first
    assert v.to_indices("a") == 0  # below min_freq -> unknown
    p = tmp_path / "emb.txt"
    p.write_text("b 1.0 0.0\nc 0.0 1.0\n")
    emb = CustomEmbedding(str(p))
    np.testing.assert_allclose(
        emb.get_vecs_by_tokens(["b", "c", "zzz"]).asnumpy(),
        [[1, 0], [0, 1], [0, 0]])


def test_roi_align_and_resize():
    x = mx.nd.array(np.arange(16, dtype="float32").reshape(1, 1, 4, 4))
    rois = mx.nd.array([[0, 0, 0, 3, 3]])
    out = mx.nd.contrib.ROIAlign(x, rois, pooled_size=(2, 2),
                                 spatial_scale=1.0)
    assert out.shape == (1, 1, 2, 2)
    r = mx.nd.contrib.BilinearResize2D(x, height=8, width=8)
    assert r.shape == (1, 1, 8, 8)
    a = mx.nd.contrib.AdaptiveAvgPooling2D(x, output_size=(2, 2))
    np.testing.assert_allclose(
        a.asnumpy()[0, 0], [[2.5, 4.5], [10.5, 12.5]])


def test_fft_roundtrip():
    x = mx.nd.random.uniform(shape=(2, 8))
    f = mx.nd.contrib.fft(x)
    assert f.shape == (2, 16)
    back = mx.nd.contrib.ifft(f)
    np.testing.assert_allclose(back.asnumpy(), x.asnumpy(), atol=1e-5)


def test_quadratic():
    x = mx.nd.array([1.0, 2.0])
    out = mx.nd.contrib.quadratic(x, a=1.0, b=2.0, c=3.0)
    np.testing.assert_allclose(out.asnumpy(), [6.0, 11.0])
