"""Small frontend modules: AttrScope, registry, libinfo, log, torch
interop (reference: python/mxnet/{attribute,registry,libinfo,log,
torch}.py)."""
import logging

import numpy as np
import pytest

import mxnet_tpu as mx


class TestAttrScope:
    def test_scope_attaches_to_vars_and_ops(self):
        with mx.AttrScope(ctx_group="stage1", lr_mult="0.1"):
            w = mx.sym.var("asw")
            z = mx.sym.var("asx") + w
        assert w.attr("ctx_group") == "stage1"
        assert w.attr("lr_mult") == "0.1"
        assert z.attr("ctx_group") == "stage1"
        assert mx.sym.var("asy").attr("ctx_group") is None

    def test_nested_inner_wins_and_explicit_beats_scope(self):
        with mx.AttrScope(a="1", b="1"):
            with mx.AttrScope(a="2"):
                s = mx.sym.var("asn", attr={"b": "9"})
        assert s.attr("a") == "2" and s.attr("b") == "9"

    def test_non_string_value_rejected(self):
        with pytest.raises(ValueError):
            mx.AttrScope(x=3)


def test_registry_register_alias_create():
    from mxnet_tpu.registry import (get_register_func, get_alias_func,
                                    get_create_func)

    class Thing:
        def __init__(self, a=1):
            self.a = a

    reg = get_register_func(Thing, "thing")
    create = get_create_func(Thing, "thing")

    class Foo(Thing):
        pass

    reg(Foo)
    get_alias_func(Thing, "thing")("other")(Foo)
    assert isinstance(create("foo"), Foo)
    assert create("other", a=2).a == 2
    assert create('["foo", {"a": 5}]').a == 5
    inst = Foo()
    assert create(inst) is inst
    with pytest.raises(mx.MXNetError):
        create("nope")
    with pytest.raises(mx.MXNetError):
        reg(int)


def test_libinfo_finds_native_lib():
    paths = mx.libinfo.find_lib_path()
    assert paths and paths[0].endswith("libmxtpu.so")
    assert mx.libinfo.__version__


def test_log_get_logger(tmp_path):
    f = str(tmp_path / "x.log")
    lg = mx.log.get_logger("mxtpu_test", filename=f, level=mx.log.INFO)
    lg.info("hello-%d", 7)
    for h in lg.handlers:
        h.flush()
    assert "hello-7" in open(f).read()
    # idempotent: second call reuses handlers
    assert mx.log.get_logger("mxtpu_test") is lg
    assert len(lg.handlers) == 1


def test_torch_roundtrip():
    torch = pytest.importorskip("torch")
    x = mx.nd.arange(12).reshape((3, 4))
    t = mx.torch.to_torch(x)
    assert isinstance(t, torch.Tensor)
    np.testing.assert_allclose(t.numpy(), x.asnumpy())
    back = mx.torch.from_torch(t * 2 + 1)
    np.testing.assert_allclose(back.asnumpy(), x.asnumpy() * 2 + 1)
    with pytest.raises(mx.MXNetError):
        mx.torch.from_torch(np.zeros(3))


def test_attrscope_get_unentered_returns_own_attrs():
    # reference API: AttrScope(x='y').get() == {'x': 'y'} without
    # entering the scope; explicit attr arg wins
    s = mx.AttrScope(x="y", z="1")
    assert s.get() == {"x": "y", "z": "1"}
    assert s.get({"z": "9"}) == {"x": "y", "z": "9"}


def test_callbacks_behavior(caplog, capsys):
    """Speedometer/log_train_metric/ProgressBar/do_checkpoint behavior
    (reference: python/mxnet/callback.py semantics)."""
    import logging
    from collections import namedtuple
    import mxnet_tpu as mx

    Param = namedtuple("Param", ["epoch", "nbatch", "eval_metric"])

    class FakeMetric:
        def __init__(self):
            self.resets = 0
        def get_name_value(self):
            return [("acc", 0.5)]
        def reset(self):
            self.resets += 1

    m = FakeMetric()
    sp = mx.callback.Speedometer(batch_size=4, frequent=2, auto_reset=True)
    with caplog.at_level(logging.INFO):
        for nb in (1, 2, 3, 4):
            sp(Param(0, nb, m))
    msgs = [r.message for r in caplog.records]
    # boundaries at nbatch 2 and 4 -> two reports, metric reset twice
    assert len(msgs) == 2 and all("samples/sec" in s and "acc" in s
                                  for s in msgs)
    assert m.resets == 2
    caplog.clear()

    # epoch rollover re-arms without logging
    with caplog.at_level(logging.INFO):
        sp(Param(1, 1, m))
        sp(Param(1, 2, m))
    assert len(caplog.records) == 1  # only the new boundary at nbatch 2

    with caplog.at_level(logging.INFO):
        caplog.clear()
        cb = mx.callback.log_train_metric(period=2)
        cb(Param(0, 2, m))
        cb(Param(0, 3, m))
    assert len(caplog.records) == 1 and "Train-acc" in caplog.records[0].message

    bar = mx.callback.ProgressBar(total=4, length=8)
    bar(Param(0, 2, None))
    outp = capsys.readouterr().out
    assert "[====----] 50%" in outp

    saved = []
    class FakeMod:
        def save_checkpoint(self, prefix, epoch, sos=False):
            saved.append(epoch)
    cb = mx.callback.module_checkpoint(FakeMod(), "p", period=2)
    for e in range(4):
        cb(e)
    assert saved == [2, 4]


def test_filesystem_uri_layer(tmp_path):
    """dmlc-filesystem role (SURVEY N17): URI dispatch for data paths."""
    from mxnet_tpu import filesystem as fs
    import mxnet_tpu as mx
    import pytest

    p = tmp_path / "x.bin"
    with fs.open_uri(str(p), "wb") as f:
        f.write(b"abc")
    assert fs.exists("file://" + str(p))
    with fs.open_uri("file://" + str(p), "rb") as f:
        assert f.read() == b"abc"
    with pytest.raises(mx.MXNetError, match="boto3"):
        fs.open_uri("s3://bucket/key")
    with pytest.raises(mx.MXNetError, match="hdfs"):
        fs.open_uri("hdfs://nn/path")
    with pytest.raises(mx.MXNetError, match="scheme"):
        fs.open_uri("gopher://x/y")

    # recordio round-trips through a file:// uri (python fallback path)
    from mxnet_tpu import recordio
    rec = tmp_path / "data.rec"
    w = recordio.MXRecordIO("file://" + str(rec), "w")
    w.write(b"hello")
    w.write(b"world")
    w.close()
    r = recordio.MXRecordIO("file://" + str(rec), "r")
    assert r.read() == b"hello" and r.read() == b"world"
    r.close()


def test_model_store_pinning(tmp_path, monkeypatch):
    """model_store: sha1-pinned cache hit, corrupt-file rejection, and
    an actionable egress error (reference: model_store.py:71)."""
    import hashlib
    import pytest
    from mxnet_tpu.gluon.model_zoo import model_store as ms

    # a fake pinned checkpoint whose hash we control
    payload = b"weights-bytes"
    sha = hashlib.sha1(payload).hexdigest()
    monkeypatch.setitem(ms._MODEL_SHA1, "fakenet", sha)
    f = tmp_path / ("fakenet-%s.params" % sha[:8])
    f.write_bytes(payload)
    assert ms.get_model_file("fakenet", root=str(tmp_path)) == str(f)

    # corrupting the cache forces a re-fetch, which fails with the
    # egress guidance in this environment
    f.write_bytes(b"tampered")
    with pytest.raises(RuntimeError, match="egress|download"):
        ms.get_model_file("fakenet", root=str(tmp_path))
    assert not f.exists()          # the corrupt file was evicted

    # unpinned names never hit the network
    with pytest.raises(RuntimeError, match="none is published"):
        ms.get_model_file("nosuchnet", root=str(tmp_path))

    # user-placed unpinned files still resolve
    loose = tmp_path / "resnet50_v1.params"
    loose.write_bytes(b"local")
    assert ms.get_model_file("resnet50_v1",
                             root=str(tmp_path)) == str(loose)
