"""Registry-wide numeric-gradient gate.

Reference discipline: `check_numeric_gradient` (python/mxnet/test_utils.py:792)
applied across tests/python/unittest/test_operator.py (6,785 LoC). The
TPU-native equivalent is generated rather than hand-written: every op in
`registry.list_ops()` must either

  (a) have a GRAD_CASES entry here — executed as jax.grad vs central
      finite differences on a small input drawn from a smooth domain, or
  (b) appear in exactly one EXEMPT_* list with a standing justification
      (non-float outputs, a.e.-zero derivatives, stochastic samplers,
      optimizer update rules, host-callback bridges, ...).

Aliases share the underlying fn, so covering one name covers them all.
`test_gate_registry_fully_cataloged` fails the moment a new op lands
without a grad case or exemption — that is the gate.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import mxnet_tpu as mx
from mxnet_tpu.ops import registry as R

RNG = np.random.RandomState(11)


def U(shape, lo=-2.0, hi=2.0):
    """float32 uniform in a smooth domain"""
    return RNG.uniform(lo, hi, shape).astype("float32")


def P(shape, lo=0.5, hi=2.0):
    """strictly positive"""
    return U(shape, lo, hi)


def spd(n):
    """symmetric positive definite (for linalg)"""
    a = RNG.randn(n, n).astype("float32")
    return a @ a.T + n * np.eye(n, dtype="float32")


# ---------------------------------------------------------------------------
# case table: name -> dict(arrays=[np arrays], params={}, wrt=[arg indices])
# wrt defaults to [0]; params default {}
# ---------------------------------------------------------------------------

GRAD_CASES = {}


def case(name, arrays, params=None, wrt=(0,), atol=1e-2, rtol=5e-2,
         eps=1e-2):
    assert name not in GRAD_CASES, name
    GRAD_CASES[name] = dict(arrays=arrays, params=params or {},
                            wrt=tuple(wrt), atol=atol, rtol=rtol, eps=eps)


# --- elementwise unary (smooth domains chosen per-op) ----------------------
for name, dom in [
    ("abs", (0.5, 2)), ("negative", (-2, 2)), ("exp", (-1, 1)),
    ("expm1", (-1, 1)), ("log", (0.5, 3)), ("log2", (0.5, 3)),
    ("log10", (0.5, 3)), ("log1p", (-0.4, 2)), ("sqrt", (0.5, 3)),
    ("rsqrt", (0.5, 3)), ("cbrt", (0.5, 3)), ("rcbrt", (0.5, 3)),
    ("square", (-2, 2)), ("reciprocal", (0.5, 3)), ("sin", (-2, 2)),
    ("cos", (-2, 2)), ("tan", (-0.5, 0.5)), ("arcsin", (-0.8, 0.8)),
    ("arccos", (-0.8, 0.8)), ("arctan", (-2, 2)), ("sinh", (-1.5, 1.5)),
    ("cosh", (-1.5, 1.5)), ("arcsinh", (-2, 2)), ("arccosh", (1.5, 3)),
    ("arctanh", (-0.7, 0.7)), ("erf", (-1.5, 1.5)), ("erfinv", (-0.7, 0.7)),
    ("gamma", (1.5, 3)), ("gammaln", (1.5, 3)), ("sigmoid", (-2, 2)),
    ("tanh", (-2, 2)), ("relu", (0.25, 2)), ("softsign", (-2, 2)),
    ("hard_sigmoid", (-0.4, 0.4)), ("degrees", (-2, 2)),
    ("radians", (-2, 2)), ("smooth_l1", (0.2, 0.8)),
    ("_copy", (-2, 2)),
]:
    case(name, [U((3, 4), *dom)])

# --- elementwise binary ----------------------------------------------------
for name, (la, lb) in [
    ("_add", ((-2, 2), (-2, 2))), ("_sub", ((-2, 2), (-2, 2))),
    ("_mul", ((-2, 2), (-2, 2))), ("_div", ((-2, 2), (0.5, 2))),
    ("_grad_add", ((-2, 2), (-2, 2))),
    ("_Power", ((0.5, 2), (0.5, 2))), ("_hypot", ((0.5, 2), (0.5, 2))),
    ("_Maximum", ((0.3, 0.9), (1.1, 2))), ("_Minimum", ((0.3, 0.9), (1.1, 2))),
    ("_mod", ((2.2, 2.8), (1.0, 1.0))),
]:
    case(name, [U((3, 4), *la), U((3, 4), *lb)], wrt=(0, 1))

# --- scalar variants -------------------------------------------------------
for name, dom, pr in [
    ("_PlusScalar", (-2, 2), {"scalar": 1.5}),
    ("_MinusScalar", (-2, 2), {"scalar": 1.5}),
    ("_rminus_scalar", (-2, 2), {"scalar": 1.5}),
    ("_MulScalar", (-2, 2), {"scalar": 1.5}),
    ("_DivScalar", (-2, 2), {"scalar": 1.5}),
    ("_rdiv_scalar", (0.5, 2), {"scalar": 1.5}),
    ("_power_scalar", (0.5, 2), {"scalar": 1.5}),
    ("_rpower_scalar", (-1, 1), {"scalar": 1.5}),
    ("_maximum_scalar", (1.2, 2), {"scalar": 1.0}),
    ("_minimum_scalar", (0.2, 0.8), {"scalar": 1.0}),
    ("_mod_scalar", (2.2, 2.8), {"scalar": 1.0}),
    ("_rmod_scalar", (1.0, 1.0), {"scalar": 2.5}),
    ("_hypot_scalar", (0.5, 2), {"scalar": 1.5}),
]:
    case(name, [U((3, 4), *dom)], params=pr)

# --- reductions / cumulative ----------------------------------------------
case("sum", [U((3, 4))], params={"axis": 1})
case("mean", [U((3, 4))], params={"axis": 1})
case("prod", [P((3, 4))], params={"axis": 1})
case("nansum", [U((3, 4))], params={"axis": 1})
case("nanprod", [P((3, 4))], params={"axis": 1})
case("max", [U((3, 4))])   # unique max a.e.: differentiable at sample
case("min", [U((3, 4))])
case("norm", [P((3, 4))])
case("logsumexp", [U((3, 4))], params={"axis": 1})
case("cumsum", [U((3, 4))], params={"axis": 1})
case("_square_sum", [U((3, 4))], params={"axis": 1})

# --- broadcast binary family (only fns not already covered via the
# elemwise names that share the implementation) -----------------------------
_BCAST = [
    ("broadcast_add", (-2, 2)), ("broadcast_sub", (-2, 2)),
    ("broadcast_mul", (-2, 2)), ("broadcast_div", (0.5, 2)),
    ("broadcast_power", (0.5, 2)), ("broadcast_hypot", (0.5, 2)),
    ("broadcast_maximum", (0.2, 0.9)), ("broadcast_minimum", (0.2, 0.9)),
    ("broadcast_mod", (2.2, 2.8)),
]
for _name, _dom in _BCAST:
    try:
        _op = R.get(_name)
    except Exception:
        continue
    if any(R.get(n).fn is _op.fn for n in GRAD_CASES):
        continue
    case(_name, [U((3, 4), *_dom), U((1, 4), max(_dom[0], 1.0),
                                     max(_dom[1], 1.5))], wrt=(0, 1))

# --- shape/structural (differentiable pass-throughs) -----------------------
case("Reshape", [U((3, 4))], params={"shape": (4, 3)})
case("Flatten", [U((2, 3, 4))])
case("transpose", [U((3, 4))], params={"axes": (1, 0)})
case("expand_dims", [U((3, 4))], params={"axis": 1})
case("squeeze", [U((3, 1, 4))], params={"axis": 1})
case("Concat", [U((2, 3)), U((2, 3))], params={"num_args": 2, "dim": 1},
     wrt=(0, 1))
case("stack", [U((2, 3)), U((2, 3))], params={"num_args": 2, "axis": 1},
     wrt=(0, 1))
case("split", [U((2, 4))], params={"num_outputs": 2, "axis": 1})
case("slice_axis", [U((3, 4))], params={"axis": 1, "begin": 1, "end": 3})
case("crop", [U((3, 4))], params={"begin": (0, 1), "end": (2, 3)})
case("slice_like", [U((3, 4)), U((2, 3))], params={},
     wrt=(0,))
case("tile", [U((2, 3))], params={"reps": (2, 2)})
case("repeat", [U((2, 3))], params={"repeats": 2, "axis": 1})
case("flip", [U((2, 3))], params={"axis": 1})
case("SwapAxis", [U((2, 3, 4))], params={"dim1": 0, "dim2": 2})
case("diag", [U((4, 4))])
case("Pad", [U((1, 2, 3, 4))],
     params={"mode": "constant", "pad_width": (0, 0, 0, 0, 1, 1, 1, 1)})
case("broadcast_to", [U((1, 3))], params={"shape": (4, 3)})
case("broadcast_axes", [U((1, 3))], params={"axis": 0, "size": 4})
case("broadcast_like", [U((1, 3)), U((4, 3))], wrt=(0,))
case("reshape_like", [U((3, 4)), U((4, 3))], wrt=(0,))
case("depth_to_space", [U((1, 4, 2, 2))], params={"block_size": 2})
case("space_to_depth", [U((1, 1, 4, 4))], params={"block_size": 2})
case("where", [np.array([[1.0, 0.0], [0.0, 1.0]], "float32"),
               U((2, 2)), U((2, 2))], wrt=(1, 2))
case("clip", [U((3, 4), -0.8, 0.8)], params={"a_min": -1.0, "a_max": 1.0})
case("Crop", [U((1, 2, 5, 5)), U((1, 2, 3, 3))],
     params={"num_args": 2, "offset": (1, 1)}, wrt=(0,))

# --- indexing (differentiable w.r.t. data) ---------------------------------
case("take", [U((5, 3)), np.array([1, 3], "int32")], wrt=(0,))
case("Embedding", [np.array([1, 2], "int32"), U((5, 3))],
     params={"input_dim": 5, "output_dim": 3}, wrt=(1,))
case("pick", [U((3, 4)), np.array([0, 2, 1], "int32")],
     params={"axis": 1}, wrt=(0,))
case("gather_nd", [U((4, 3)), np.array([[0, 2], [1, 0]], "int32")],
     wrt=(0,))
case("scatter_nd", [U((2,)), np.array([[0, 2]], "int32")],
     params={"shape": (4,)}, wrt=(0,))
case("one_hot", [np.array([0, 2], "int32")], params={"depth": 4}, wrt=())
case("SequenceLast", [U((3, 2, 4)), np.array([2, 3], "float32")],
     params={"use_sequence_length": True}, wrt=(0,))
case("SequenceMask", [U((3, 2, 4)), np.array([2, 3], "float32")],
     params={"use_sequence_length": True}, wrt=(0,))
case("SequenceReverse", [U((3, 2, 4))], wrt=(0,))
case("_sparse_retain", [U((4, 3)), np.array([0, 2], "int64")], wrt=(0,))

# --- matmul / linalg -------------------------------------------------------
case("dot", [U((3, 4)), U((4, 2))], wrt=(0, 1))
case("batch_dot", [U((2, 3, 4)), U((2, 4, 2))], wrt=(0, 1))
case("khatri_rao", [U((2, 3)), U((4, 3))], params={"num_args": 2},
     wrt=(0, 1))
case("linalg_gemm", [U((3, 4)), U((4, 2)), U((3, 2))], wrt=(0, 1, 2))
case("linalg_gemm2", [U((3, 4)), U((4, 2))], wrt=(0, 1))
case("linalg_potrf", [spd(3)], atol=5e-2)
case("linalg_potri", [spd(3)], atol=8e-2, rtol=0.1)
case("linalg_sumlogdiag", [spd(3)])
case("linalg_syrk", [U((3, 4))])
case("linalg_trmm", [np.tril(P((3, 3))).astype("float32"), U((3, 4))],
     wrt=(0, 1))
case("linalg_trsm", [(np.tril(U((3, 3), 0.8, 1.5)) +
                      2 * np.eye(3, dtype="float32")).astype("float32"),
                     U((3, 4))], wrt=(0, 1), atol=5e-2)
case("linalg_gelqf", [U((2, 4))], atol=8e-2, rtol=0.1)
case("linalg_syevd", [spd(3)], atol=8e-2, rtol=0.1)

# --- nn core ---------------------------------------------------------------
case("FullyConnected", [U((2, 5)), U((3, 5)), U((3,))],
     params={"num_hidden": 3}, wrt=(0, 1, 2))
case("Convolution", [U((1, 4, 4, 2)), U((2, 3, 3, 2)), U((2,))],
     params={"kernel": (3, 3), "num_filter": 2, "layout": "NHWC"},
     wrt=(0, 1, 2))
case("Deconvolution", [U((1, 3, 3, 3)), U((3, 2, 2, 2)), U((2,))],
     params={"kernel": (2, 2), "num_filter": 2, "no_bias": False},
     wrt=(0, 1, 2), atol=5e-2)
case("Pooling", [U((1, 4, 4, 2))],
     params={"kernel": (2, 2), "pool_type": "avg", "stride": (2, 2),
             "layout": "NHWC"})
case("Activation", [U((3, 4), 0.25, 2)], params={"act_type": "relu"})
case("LeakyReLU", [U((3, 4), 0.25, 2)], params={"act_type": "leaky"})
case("softmax", [U((3, 4))], params={"axis": -1})
case("softmin", [U((3, 4))], params={"axis": -1})
case("log_softmax", [U((3, 4))], params={"axis": -1})
case("SoftmaxActivation", [U((3, 4))])
case("LayerNorm", [U((3, 4)), P((4,)), U((4,))], wrt=(0, 1, 2))
case("InstanceNorm", [U((2, 3, 4)), P((3,)), U((3,))], wrt=(0, 1, 2))
case("L2Normalization", [P((3, 4))])
case("LRN", [P((1, 4, 3, 3))], params={"nsize": 3}, atol=5e-2)
case("BatchNorm",
     [U((2, 3, 4, 2)), P((2,)), U((2,)), np.zeros(2, "float32"),
      np.ones(2, "float32")],
     params={"axis": 3}, wrt=(0, 1, 2), atol=5e-2)
case("Dropout", [U((3, 4))], params={"p": 0.0})  # deterministic at p=0
case("Cast", [U((3, 4))], params={"dtype": "float32"})
case("UpSampling", [U((1, 2, 3, 3))],
     params={"scale": 2, "sample_type": "nearest", "num_args": 1})
case("BilinearSampler", [U((1, 2, 4, 4)),
                         np.clip(U((1, 2, 3, 3)), -0.9, 0.9)],
     wrt=(0,), atol=5e-2)
case("GridGenerator", [U((1, 6), -0.5, 0.5)],
     params={"transform_type": "affine", "target_shape": (4, 4)})
case("SpatialTransformer",
     [U((1, 2, 4, 4)), np.array([[1, 0, 0, 0, 1, 0]], "float32")],
     params={"transform_type": "affine", "sampler_type": "bilinear",
             "target_shape": (4, 4)}, wrt=(0,), atol=5e-2)
case("ROIPooling", [P((1, 2, 6, 6)), np.array([[0, 0, 0, 3, 3]], "float32")],
     params={"pooled_size": (2, 2), "spatial_scale": 1.0}, wrt=(0,))
case("Correlation", [P((1, 2, 4, 4)), P((1, 2, 4, 4))],
     params={"kernel_size": 1, "max_displacement": 1, "stride1": 1,
             "stride2": 1, "pad_size": 1}, wrt=(0, 1), atol=5e-2)
case("RNN", [U((3, 2, 4), -0.5, 0.5),
             U((sum([4 * 3 + 3 * 3 + 3 + 3]),), -0.3, 0.3),
             np.zeros((1, 2, 3), "float32")],
     params={"state_size": 3, "num_layers": 1, "mode": "rnn_tanh"},
     wrt=(0,), atol=5e-2)

# --- losses / outputs ------------------------------------------------------
# (loss HEADS — SoftmaxOutput, SVMOutput, *RegressionOutput — have custom
# vjps that return the loss gradient, not d(forward); they are checked
# against independent analytic formulas in ANALYTIC_GRAD_CASES below)
case("MakeLoss", [P((3, 4))])
case("softmax_cross_entropy", [U((3, 4)), np.array([0, 2, 1], "float32")],
     wrt=(0,))
case("IdentityAttachKLSparseReg", [P((3, 4), 0.05, 0.9)])
case("_contrib_CTCLoss",
     [U((4, 2, 5), -1, 1), np.array([[1, 2], [2, 1]], "float32")],
     wrt=(0,), atol=5e-2)

# --- contrib (differentiable) ----------------------------------------------
case("_contrib_quadratic", [U((3, 4))],
     params={"a": 1.0, "b": 2.0, "c": 3.0})
case("_contrib_div_sqrt_dim", [U((3, 4))])
case("_contrib_AdaptiveAvgPooling2D", [U((1, 2, 4, 4))],
     params={"output_size": 2})
case("_contrib_BilinearResize2D", [U((1, 2, 3, 3))],
     params={"height": 5, "width": 5}, atol=5e-2)
case("_contrib_ROIAlign",
     [P((1, 2, 6, 6)), np.array([[0, 0.5, 0.5, 3.5, 3.5]], "float32")],
     params={"pooled_size": (2, 2), "spatial_scale": 1.0}, wrt=(0,),
     atol=5e-2)
case("_contrib_PSROIPooling",
     [U((1, 8, 6, 6)), np.array([[0, 0, 0, 3, 3], [0, 1, 1, 4, 4]],
                                "float32")],
     params={"spatial_scale": 1.0, "output_dim": 2, "pooled_size": 2,
             "group_size": 2}, wrt=(0,), atol=5e-2)
# trans values are kept small (|dx| <= 0.1 px) so no bilinear sample
# crosses an integer grid line within the finite-difference eps
case("_contrib_DeformablePSROIPooling",
     [U((1, 8, 8, 8)), np.array([[0, 1, 1, 5, 5]], "float32"),
      U((1, 2, 2, 2), -0.2, 0.2)],
     params={"spatial_scale": 1.0, "output_dim": 2, "pooled_size": 2,
             "group_size": 2, "trans_std": 0.1, "no_trans": False},
     wrt=(0, 2), atol=5e-2)
case("_contrib_count_sketch", [U((2, 8)), np.array([0, 1, 0, 1, 1, 0, 1, 0],
                                                   "float32"),
                               np.array([1, 3, 0, 2, 4, 1, 0, 3], "float32")],
     params={"out_dim": 5}, wrt=(0,))
case("_contrib_fft", [U((2, 4))], params={}, atol=5e-2)
case("_contrib_ifft", [U((2, 8))], params={}, atol=5e-2)
case("_contrib_SparseEmbedding", [np.array([1, 2], "int32"), U((5, 3))],
     params={"input_dim": 5, "output_dim": 3}, wrt=(1,))
case("_image_normalize", [P((2, 3, 3))],
     params={"mean": (0.5,), "std": (0.3,)})
case("_npi_to_tensor", [U((4, 4, 3), 0, 255)])
case("_contrib_flash_attention",
     [U((1, 2, 4, 8), -0.5, 0.5), U((1, 2, 4, 8), -0.5, 0.5),
      U((1, 2, 4, 8), -0.5, 0.5)], wrt=(0, 1, 2), atol=5e-2)
case("_contrib_RingAttention",
     [U((1, 2, 4, 8), -0.5, 0.5), U((1, 2, 4, 8), -0.5, 0.5),
      U((1, 2, 4, 8), -0.5, 0.5)], wrt=(0, 1, 2), atol=5e-2)
case("_contrib_MoEFFN",
     [U((6, 8), -0.5, 0.5), U((8, 4), -0.3, 0.3),
      U((4, 8, 16), -0.3, 0.3), np.zeros((4, 16), "float32"),
      U((4, 16, 8), -0.3, 0.3), np.zeros((4, 8), "float32")],
     params={"capacity_factor": 4.0},  # nothing dropped: smooth at sample
     wrt=(0, 2, 4), atol=5e-2)
case("_contrib_SyncBatchNorm",
     [U((2, 3, 4, 2)), P((2,)), U((2,)), np.zeros(2, "float32"),
      np.ones(2, "float32")],
     params={"axis": 3}, wrt=(0, 1, 2), atol=5e-2)
case("_contrib_DeformableConvolution",
     [U((1, 2, 4, 4)), np.zeros((1, 18, 2, 2), "float32") + 0.01,
      U((2, 2, 3, 3))],
     params={"kernel": (3, 3), "num_filter": 2},
     wrt=(0, 2), atol=5e-2)

# arithmetic/assign-style ops
case("_scatter_elemwise_div", [U((3, 4)), P((3, 4))], wrt=(0, 1))
case("_scatter_plus_scalar", [U((3, 4))], params={"scalar": 1.5})
case("_scatter_minus_scalar", [U((3, 4))], params={"scalar": 1.5})
case("_crop_assign", [U((3, 4)), U((2, 2))],
     params={"begin": (0, 1), "end": (2, 3)}, wrt=(0, 1))
case("_crop_assign_scalar", [U((3, 4))],
     params={"scalar": 1.0, "begin": (0, 1), "end": (2, 3)})
case("_identity_with_attr_like_rhs", [U((3, 4)), U((3, 4))], wrt=(0,))
case("add_n", [U((3, 4)), U((3, 4))], params={"num_args": 2}, wrt=(0, 1))
case("BlockGrad", [U((3, 4))], wrt=())       # zero-grad by contract
case("_CrossDeviceCopy", [U((3, 4))])

# ---------------------------------------------------------------------------
# exemptions, each list = one standing justification
# ---------------------------------------------------------------------------

# outputs are indices / ints / bools / shapes: no gradient exists
EXEMPT_NONFLOAT_OUTPUT = {
    "argmax", "argmin", "argsort", "topk", "sort",  # sort: permutation —
    # value-grads exist but are just scatter of ones; covered via topk in
    # test_autograd.test_multi_output_partial_use
    "shape_array", "size_array", "_histogram", "histogram",
    "_ravel_multi_index", "ravel_multi_index", "_unravel_index",
    "unravel_index", "_contrib_bipartite_matching",
    "_equal", "_not_equal", "_greater", "_greater_equal", "_lesser",
    "_lesser_equal", "_equal_scalar", "_not_equal_scalar",
    "_greater_scalar", "_greater_equal_scalar", "_lesser_scalar",
    "_lesser_equal_scalar", "_logical_and", "_logical_or", "_logical_xor",
    "_logical_and_scalar", "_logical_or_scalar", "_logical_xor_scalar",
    "logical_not", "broadcast_logical_and", "broadcast_logical_or",
    "broadcast_logical_xor", "argmax_channel",
}

# derivative is zero almost everywhere: finite differences are vacuous
EXEMPT_PIECEWISE_CONSTANT = {
    "round", "rint", "fix", "floor", "ceil", "trunc", "sign",
}

# stochastic output: no meaningful numeric gradient (reparameterized
# sampling is not part of the reference API either)
EXEMPT_RANDOM = {
    "uniform", "normal", "randint", "bernoulli", "random_exponential",
    "random_gamma", "random_negative_binomial", "random_poisson",
    "random_generalized_negative_binomial", "sample_uniform",
    "sample_normal", "sample_multinomial", "_sample_exponential",
    "_sample_gamma", "_sample_negative_binomial", "_sample_poisson",
    "_sample_generalized_negative_binomial", "shuffle",
}

# optimizer update rules: applied under stop-gradient by contract
# (reference registers them without FGradient)
EXEMPT_OPTIMIZER_UPDATE = {
    "sgd_update", "sgd_mom_update", "mp_sgd_update", "mp_sgd_mom_update",
    "adam_update", "ftml_update", "ftrl_update", "rmsprop_update",
    "rmspropalex_update", "signsgd_update", "signum_update",
    "_sparse_adagrad_update", "_scatter_set_nd",
}

# constant constructors: no float inputs to differentiate
EXEMPT_CONSTANT = {
    "_zeros", "_ones", "_arange", "_full", "zeros_like", "ones_like",
    "eye", "_eye",
}

# int8/quantized kernels: integer tensors end-to-end
EXEMPT_QUANTIZED = {
    "_contrib_quantize", "_contrib_dequantize", "_contrib_requantize",
    "_contrib_qdq", "_contrib_int8_conv", "_contrib_int8_fc",
    "_contrib_quantized_act", "_contrib_quantized_conv",
    "_contrib_quantized_flatten", "_contrib_quantized_fully_connected",
    "_contrib_quantized_pooling", "cast_storage",
}

# host-callback / subgraph bridges: gradient correctness is covered by
# dedicated suites (test_custom_op.py, test_control_flow.py) because the
# op takes closures, not arrays
EXEMPT_BRIDGE = {
    "Custom", "_foreach", "_while_loop", "_cond",
}

# detection/proposal heads: outputs are box coordinates + scores whose
# reference implementations are likewise non-differentiable C++ kernels
# (no FGradient registered: multibox_*.cc, proposal.cc, bounding_box.cc).
# PSROIPooling / DeformablePSROIPooling do NOT belong here — the
# reference trains through both (psroi_pooling.cc PSROIPoolBackwardAcc,
# deformable_psroi_pooling.cc) — so they carry GRAD_CASES above.
EXEMPT_DETECTION = {
    "_contrib_MultiBoxPrior", "_contrib_MultiBoxTarget",
    "_contrib_MultiBoxDetection", "_contrib_box_nms", "_contrib_box_iou",
    "_contrib_Proposal", "_contrib_MultiProposal",
}

EXEMPT = (EXEMPT_NONFLOAT_OUTPUT | EXEMPT_PIECEWISE_CONSTANT
          | EXEMPT_RANDOM | EXEMPT_OPTIMIZER_UPDATE | EXEMPT_CONSTANT
          | EXEMPT_QUANTIZED | EXEMPT_BRIDGE | EXEMPT_DETECTION)


# ---------------------------------------------------------------------------
# loss heads: backward returns the LOSS gradient by contract (the incoming
# cotangent is ignored — reference regression_output-inl.h:206,
# softmax_output-inl.h, svm_output.cc), so finite differences of the
# forward are invalid by design. Each gets an independent numpy formula
# the custom vjp must reproduce.
# ---------------------------------------------------------------------------


def _np_softmax(x):
    e = np.exp(x - x.max(-1, keepdims=True))
    return e / e.sum(-1, keepdims=True)


def _np_onehot(lbl, n):
    return np.eye(n, dtype="float32")[lbl.astype("int64")]


def _exp_linear_regression(data, label):
    return (data - label) / data.shape[1]       # /num_output, ref :200-206


def _exp_mae_regression(data, label):
    return np.sign(data - label) / data.shape[1]


def _exp_logistic_regression(data, label):
    return (1 / (1 + np.exp(-data)) - label) / data.shape[1]


def _exp_softmax_output(data, label):
    return _np_softmax(data) - _np_onehot(label, data.shape[-1])


def _exp_svm_output(data, label):
    # L1-SVM (use_linear=True): g_j = coef·1{margin > s_t − s_j}, j ≠ t;
    # g_t = −Σ g_j  (reference svm_output.cc forward-identity hinge head)
    n = data.shape[-1]
    oh = _np_onehot(label, n)
    s_true = (data * oh).sum(-1, keepdims=True)
    viol = (1.0 - (s_true - data)) > 0
    g = np.where(oh > 0, 0.0, viol.astype("float32"))
    g_t = -g.sum(-1, keepdims=True)
    return g + oh * g_t


ANALYTIC_GRAD_CASES = {
    "LinearRegressionOutput": ([U((3, 4)), U((3, 4))], {},
                               _exp_linear_regression),
    "MAERegressionOutput": ([U((3, 4), 0.5, 2), U((3, 4), -0.4, 0.4)], {},
                            _exp_mae_regression),
    "LogisticRegressionOutput": ([U((3, 4)), P((3, 4), 0.1, 0.9)], {},
                                 _exp_logistic_regression),
    "SoftmaxOutput": ([U((3, 4)), np.array([0, 2, 1], "float32")], {},
                      _exp_softmax_output),
    "SVMOutput": ([U((3, 4)), np.array([0, 2, 1], "float32")],
                  {"use_linear": True}, _exp_svm_output),
}


@pytest.mark.parametrize("name", sorted(ANALYTIC_GRAD_CASES),
                         ids=sorted(ANALYTIC_GRAD_CASES))
def test_loss_head_analytic_vjp(name):
    arrays, params, expect = ANALYTIC_GRAD_CASES[name]
    op = R.get(name)
    full = R.apply_defaults(op, dict(params))

    def f(x):
        return jnp.sum(op.fn(x, jnp.asarray(arrays[1]), **full))

    g = np.asarray(jax.grad(f)(jnp.asarray(arrays[0])))
    exp = expect(np.asarray(arrays[0], "float64"),
                 np.asarray(arrays[1], "float64"))
    assert np.allclose(g, exp, atol=1e-4, rtol=1e-4), name


# ---------------------------------------------------------------------------
# the gate
# ---------------------------------------------------------------------------


def _covered_fns():
    ids = set()
    for name in GRAD_CASES:
        ids.add(id(R.get(name).fn))
    for name in ANALYTIC_GRAD_CASES:
        ids.add(id(R.get(name).fn))
    for name in EXEMPT:
        try:
            ids.add(id(R.get(name).fn))
        except Exception:
            pass
    return ids


def test_gate_registry_fully_cataloged():
    covered = _covered_fns()
    missing = sorted(
        n for n in R.list_ops()
        if id(R.get(n).fn) not in covered)
    assert not missing, (
        "ops with neither a numeric-gradient case nor a justified "
        "exemption in test_operator_grad_gate.py: %s" % missing)


def test_gate_exemptions_exist():
    """Exempt names must stay real registry entries (catch typos/renames)."""
    all_ops = set(R.list_ops())
    stale = sorted(n for n in EXEMPT if n not in all_ops)
    assert not stale, "stale exemptions: %s" % stale


def test_gate_no_double_booking():
    both = sorted(set(GRAD_CASES) & EXEMPT)
    assert not both, "ops both cased and exempted: %s" % both


# ---------------------------------------------------------------------------
# the generated check
# ---------------------------------------------------------------------------


def _run_case(name, spec):
    op = R.get(name)
    arrays = [jnp.asarray(a) for a in spec["arrays"]]
    # mimic the frontend: drop codegen-only params the fn doesn't take,
    # then validate + fill defaults exactly as invoke() does
    params = {k: v for k, v in spec["params"].items()
              if k in op.params or op.allow_extra_params}
    params = R.apply_defaults(op, params)
    if op.takes_mode:
        params["_mode"] = "predict"
    wrt = spec["wrt"]
    # rng ops: fix the key — deterministic given the key, so autodiff and
    # finite differences see the same function (Dropout is cased at p=0,
    # LeakyReLU at act_type=leaky, RNN in predict mode: all key-invariant)
    key = jax.random.PRNGKey(0) if op.needs_rng else None

    vis = op.visible_outputs
    n_vis = vis(params) if callable(vis) else (vis or None)

    def f(*diffs):
        ins = list(arrays)
        for k, j in enumerate(wrt):
            ins[j] = diffs[k]
        if key is not None:
            ins = [key] + ins
        out = op.fn(*ins, **params)
        outs = out if isinstance(out, tuple) else (out,)
        if n_vis is not None:
            outs = outs[:n_vis]
        tot = 0.0
        for o in outs:
            if jnp.issubdtype(o.dtype, jnp.floating):
                tot = tot + jnp.sum(o.astype(jnp.float32))
        return tot

    if not wrt:
        f()          # smoke only: no differentiable inputs by contract
        return

    diffs = [arrays[j] for j in wrt]
    grads = jax.grad(f, argnums=tuple(range(len(wrt))))(*diffs)
    eps = spec["eps"]
    for k, j in enumerate(wrt):
        base = np.asarray(arrays[j], "float64")
        g = np.asarray(grads[k], "float64")
        flat = base.reshape(-1)
        # sample a handful of coordinates — enough to catch a wrong vjp,
        # cheap enough to run registry-wide
        import zlib
        rng = np.random.RandomState(
            (zlib.crc32(name.encode()) ^ (j << 16)) & 0x7fffffff)
        idxs = rng.choice(flat.size, size=min(4, flat.size), replace=False)
        for idx in idxs:
            fp = flat.copy(); fp[idx] += eps
            fm = flat.copy(); fm[idx] -= eps
            vp = float(f(*[jnp.asarray(fp.reshape(base.shape), "float32")
                           if kk == k else diffs[kk]
                           for kk in range(len(wrt))]))
            vm = float(f(*[jnp.asarray(fm.reshape(base.shape), "float32")
                           if kk == k else diffs[kk]
                           for kk in range(len(wrt))]))
            num = (vp - vm) / (2 * eps)
            got = g.reshape(-1)[idx]
            assert np.isclose(got, num, rtol=spec["rtol"],
                              atol=spec["atol"]), (
                "%s: d/d(input %d)[%d]: autodiff %g vs numeric %g"
                % (name, j, idx, got, num))


@pytest.mark.parametrize("name", sorted(GRAD_CASES), ids=sorted(GRAD_CASES))
def test_numeric_gradient(name, ):
    _run_case(name, GRAD_CASES[name])


# ---------------------------------------------------------------------------
# second-order spot checks: jax.grad(jax.grad(...)) vs central differences
# of the analytic first derivative, on representative smooth ops (the
# breadth backing autograd.grad(create_graph=True) beyond the tape tests)
# ---------------------------------------------------------------------------

SECOND_ORDER_CASES = {
    "tanh": ([U((3, 4), -1.5, 1.5)], {}),
    "sigmoid": ([U((3, 4), -2, 2)], {}),
    "exp": ([U((3, 4), -1, 1)], {}),
    "log": ([P((3, 4), 0.5, 3)], {}),
    "square": ([U((3, 4))], {}),
    "softmax": ([U((3, 4))], {"axis": -1}),
    "FullyConnected": ([U((2, 5)), U((3, 5)), U((3,))],
                       {"num_hidden": 3}),
    "Convolution": ([U((1, 4, 4, 2)), U((2, 3, 3, 2)), U((2,))],
                    {"kernel": (3, 3), "num_filter": 2,
                     "layout": "NHWC"}),
    "LayerNorm": ([U((3, 4)), P((4,)), U((4,))], {}),
}


@pytest.mark.parametrize("name", sorted(SECOND_ORDER_CASES),
                         ids=sorted(SECOND_ORDER_CASES))
def test_second_order_gradient(name):
    arrays, params = SECOND_ORDER_CASES[name]
    op = R.get(name)
    full = R.apply_defaults(op, dict(params))
    if op.takes_mode:
        full["_mode"] = "predict"
    xs = [jnp.asarray(a) for a in arrays]

    def f(x0):
        out = op.fn(x0, *xs[1:], **full)
        out = out[0] if isinstance(out, tuple) else out
        # nonlinear functional so the 2nd derivative is nontrivial
        # even for linear ops (FC/conv)
        return jnp.sum(jnp.tanh(out.astype(jnp.float32)))

    g = jax.grad(f)
    gg = np.asarray(jax.grad(lambda x: jnp.sum(g(x)))(xs[0]), "float64")
    base = np.asarray(arrays[0], "float64")
    eps = 1e-3
    import zlib
    rng = np.random.RandomState(zlib.crc32(name.encode()) & 0x7fffffff)
    flat = base.reshape(-1)
    for idx in rng.choice(flat.size, size=min(3, flat.size),
                          replace=False):
        xp = flat.copy(); xp[idx] += eps
        xm = flat.copy(); xm[idx] -= eps
        gp = float(np.sum(np.asarray(
            g(jnp.asarray(xp.reshape(base.shape), "float32")))))
        gm = float(np.sum(np.asarray(
            g(jnp.asarray(xm.reshape(base.shape), "float32")))))
        num = (gp - gm) / (2 * eps)
        got = gg.reshape(-1)[idx]
        assert np.isclose(got, num, rtol=0.05, atol=5e-2), (
            "%s: d2[%d] %g vs numeric %g" % (name, idx, got, num))
