"""Compilation artifact subsystem (ISSUE 11, docs/compilation.md):
persistent-cache wiring, AOT executable store + fingerprint fallback,
cold-start telemetry, gang downtime split, GC/holder refusal."""
import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

import mxnet_tpu as mx  # noqa: F401 — framework wiring under test
from mxnet_tpu.compile import aot as aot_mod
from mxnet_tpu.compile import cache as cache_mod
from mxnet_tpu.compile import coldstart as coldstart_mod
from mxnet_tpu.compile import (ArtifactStore, StoreHeld, fingerprint,
                               gc_cache_dir)
from mxnet_tpu.observability import registry as obs
from mxnet_tpu.resilience import chaos

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _total(name):
    m = obs.REGISTRY.get(name)
    return m.total() if m is not None else 0


def _build_engine(name="m", dtype=None, hidden=16):
    sys.path.insert(0, os.path.join(ROOT, "tools"))
    from serve_bench import _build_model
    from mxnet_tpu.serving import InferenceEngine
    sym, params = _build_model(8, hidden)
    return InferenceEngine.from_symbol(
        sym, params, {}, {"data": (8,)}, 4, name=name, dtype=dtype)


# ---------------------------------------------------------------------------
# cache dir resolution + raw-dir GC
# ---------------------------------------------------------------------------
class TestCacheDir:
    def test_explicit_path_wins(self):
        env = {"MXTPU_COMPILE_CACHE": "/x/y"}
        assert cache_mod.resolve_cache_dir(env) == "/x/y"

    def test_zero_disables(self):
        assert cache_mod.resolve_cache_dir(
            {"MXTPU_COMPILE_CACHE": "0"}) is None

    def test_bench_legacy_spelling(self):
        # bench.py's MXTPU_XLA_CACHE is honored when the canonical
        # knob is absent, and loses to it when both are set
        assert cache_mod.resolve_cache_dir(
            {"MXTPU_XLA_CACHE": "/legacy"}) == "/legacy"
        assert cache_mod.resolve_cache_dir(
            {"MXTPU_XLA_CACHE": "/legacy",
             "MXTPU_COMPILE_CACHE": "/canon"}) == "/canon"

    def test_jax_env_respected(self):
        assert cache_mod.resolve_cache_dir(
            {"JAX_COMPILATION_CACHE_DIR": "/operator",
             "MXTPU_COMPILE_CACHE": "0"}) == "/operator"

    def test_default_is_uid_scoped(self):
        d = cache_mod.resolve_cache_dir({})
        if d is not None:        # None only if the default dir refused
            assert str(os.getuid()) in d

    def test_gc_scrubs_empty_and_evicts_lru(self, tmp_path):
        old = tmp_path / "old.bin"
        new = tmp_path / "new.bin"
        husk = tmp_path / "husk.bin"
        old.write_bytes(b"x" * 100)
        new.write_bytes(b"y" * 100)
        husk.write_bytes(b"")
        past = time.time() - 3600
        os.utime(old, (past, past))
        report = gc_cache_dir(str(tmp_path), max_bytes=150)
        assert report["scrubbed"] == 1
        assert not husk.exists()
        # LRU: the old entry goes, the fresh one stays
        assert not old.exists() and new.exists()
        assert report["bytes_after"] <= 150

    def test_multidevice_read_guard_installed(self):
        """enable_cache must wrap jax's cache read so multi-device CPU
        entries never deserialize (jaxlib segfault — the
        test_trainer_checkpoint reproducer); single-device reads pass
        through."""
        cache_mod.enable_cache()
        if not cache_mod.cache_enabled():
            pytest.skip("cache disabled in this session")
        from jax._src import compiler as jc
        assert jc._cache_read.__name__ == "guarded_read"

        class EBO:
            def __init__(self, n):
                self.num_replicas = n
                self.num_partitions = 1

        class Opts:
            def __init__(self, n):
                self.executable_build_options = EBO(n)

        class Backend:
            platform = "cpu"

        # spanning: forced miss, underlying cache never touched
        assert jc._cache_read("m", "key-that-does-not-exist",
                              Opts(8), Backend()) == (None, None)

    def test_gc_dry_run_touches_nothing(self, tmp_path):
        f = tmp_path / "a.bin"
        f.write_bytes(b"z" * 100)
        report = gc_cache_dir(str(tmp_path), max_bytes=1, dry_run=True)
        assert report["evicted"] == 1 and f.exists()


# ---------------------------------------------------------------------------
# fingerprints
# ---------------------------------------------------------------------------
class TestFingerprint:
    def test_deterministic(self):
        assert fingerprint({"a": 1}) == fingerprint({"a": 1})

    def test_sensitive_to_extra(self):
        assert fingerprint({"a": 1}) != fingerprint({"a": 2})

    def test_sensitive_to_keyed_env_flag(self, monkeypatch):
        base = fingerprint({})
        monkeypatch.setenv("MXTPU_SERVE_DTYPE", "bf16")
        assert fingerprint({}) != base

    def test_aval_signature_orders_shapes_and_dtypes(self):
        import jax
        sig = aot_mod.aval_signature(
            {"w": jax.ShapeDtypeStruct((2, 3), np.float32), "k": None})
        assert sig == {"k": None, "w": [[2, 3], "float32"]}


# ---------------------------------------------------------------------------
# ArtifactStore
# ---------------------------------------------------------------------------
class TestArtifactStore:
    def _compiled(self):
        # compile_fresh, not a bare lower().compile(): an executable
        # that came out of the persistent cache serializes into a blob
        # a loader cannot resolve ("Symbols not found") — the exact
        # invariant export_jit enforces (see test below)
        import jax
        import jax.numpy as jnp
        jitted = jax.jit(lambda a: jnp.tanh(a) * 2.0)
        aval = (jax.ShapeDtypeStruct((4,), np.float32),)
        return aot_mod.compile_fresh(jitted, aval)

    def test_verify_and_prune_drops_unloadable_blob(self, tmp_path):
        """Regression guard for the export-verification invariant: a
        blob a fresh interpreter cannot load (here: torn payload) is
        pruned from the manifest and counted; a good blob survives."""
        store = ArtifactStore(tmp_path, create=True)
        good_fp = fingerprint({"k": "good"})
        store.put("good", good_fp, self._compiled())
        bad_fp = fingerprint({"k": "bad"})
        store.put("bad", bad_fp, self._compiled())
        blob = tmp_path / store.entries()["bad"]["file"]
        blob.write_bytes(b"\x80\x04not an executable")
        before = _total("compile.aot.fallbacks")
        result = store.verify_and_prune()
        assert result == {"good": True, "bad": False}
        assert set(store.entries()) == {"good"}
        assert not blob.exists()
        assert _total("compile.aot.fallbacks") == before + 1

    def test_export_after_warm_cache_hit_is_caught(self, tmp_path):
        """The flaky-export mode end to end: warm the persistent cache
        for a program in a subprocess, hit it in THIS process via
        lower().compile(), serialize that executable. Whether the blob
        comes out poisoned (symbol-referencing) depends on the
        process's accumulated dedup state — the invariant under test
        is HONESTY: after verify_and_prune, the surviving entries are
        exactly the ones a fresh interpreter proved loadable."""
        import jax
        import jax.numpy as jnp
        cache_dir = str(tmp_path / "cache")
        # the warming program must match the in-process one exactly —
        # the cache key covers the HLO module name, so `f` by `def`
        prog = ("import jax, jax.numpy as jnp, numpy as np\n"
                "jax.config.update('jax_platforms', 'cpu')\n"
                "jax.config.update('jax_compilation_cache_dir', %r)\n"
                "jax.config.update("
                "'jax_persistent_cache_min_compile_time_secs', 0.0)\n"
                "jax.config.update("
                "'jax_persistent_cache_min_entry_size_bytes', -1)\n"
                "def f(a):\n"
                "    return jnp.sinh(a) * 5.0\n"
                "jax.jit(f).lower(\n"
                "    jax.ShapeDtypeStruct((4,), jnp.float32)"
                ").compile()\n" % cache_dir)
        r = subprocess.run([sys.executable, "-c", prog],
                           capture_output=True, text=True, timeout=300,
                           env=dict(os.environ, JAX_PLATFORMS="cpu"))
        assert r.returncode == 0, r.stdout + r.stderr

        def f(a):
            return jnp.sinh(a) * 5.0

        aval = (jax.ShapeDtypeStruct((4,), np.float32),)
        prev = jax.config.jax_compilation_cache_dir
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        try:
            via_cache = jax.jit(f).lower(*aval).compile()
            fresh = aot_mod.compile_fresh(jax.jit(f), aval)
        finally:
            jax.config.update("jax_compilation_cache_dir", prev)
        store = ArtifactStore(tmp_path / "store", create=True)
        store.put("via_cache", fingerprint({"k": 1}), via_cache)
        store.put("fresh", fingerprint({"k": 2}), fresh)
        result = store.verify_and_prune()
        assert set(result) == {"via_cache", "fresh"}
        # survivors are exactly the provably-loadable blobs, and a
        # pruned blob is gone from disk as well as the manifest
        assert set(store.entries()) == {n for n, ok in result.items()
                                        if ok}
        for name, ok in result.items():
            if not ok:
                blobs = [f for f in os.listdir(tmp_path / "store")
                         if f.endswith(".aot")]
                assert len(blobs) == sum(result.values())

    def test_put_get_roundtrip(self, tmp_path):
        store = ArtifactStore(tmp_path, create=True)
        fp = fingerprint({"k": "v"})
        nbytes = store.put("p", fp, self._compiled())
        assert nbytes > 0
        assert store.entries()["p"]["fingerprint"] == fp
        fn = store.get("p", fp)
        assert fn is not None
        out = np.asarray(fn(np.ones(4, np.float32))[0]
                         if isinstance(fn(np.ones(4, np.float32)),
                                       tuple)
                         else fn(np.ones(4, np.float32)))
        assert np.allclose(out, np.tanh(1.0) * 2.0)

    def test_fingerprint_mismatch_falls_back(self, tmp_path):
        store = ArtifactStore(tmp_path, create=True)
        store.put("p", fingerprint({"k": 1}), self._compiled())
        before = _total("compile.aot.fallbacks")
        assert store.get("p", fingerprint({"k": 2})) is None
        assert _total("compile.aot.fallbacks") == before + 1

    def test_missing_and_corrupt_fall_back(self, tmp_path):
        store = ArtifactStore(tmp_path, create=True)
        assert store.get("absent", fingerprint({})) is None
        fp = fingerprint({"k": 3})
        store.put("p", fp, self._compiled())
        blob = tmp_path / store.entries()["p"]["file"]
        blob.write_bytes(b"not a pickle")
        assert store.get("p", fp) is None

    def test_torn_manifest_degrades_to_empty(self, tmp_path):
        store = ArtifactStore(tmp_path, create=True)
        (tmp_path / "manifest.json").write_text("{torn")
        assert store.entries() == {}
        assert store.get("p", fingerprint({})) is None

    def test_chaos_compile_load_falls_back_clean(self, tmp_path):
        store = ArtifactStore(tmp_path, create=True)
        fp = fingerprint({"k": 4})
        store.put("p", fp, self._compiled())
        chaos.configure("compile.load:kind=fatal")
        try:
            before = _total("compile.aot.fallbacks")
            assert store.get("p", fp) is None    # fault, not a raise
            assert _total("compile.aot.fallbacks") == before + 1
        finally:
            chaos.reset()
        assert store.get("p", fp) is not None    # disarmed: loads again

    # -- holders + gc --------------------------------------------------
    def test_gc_refuses_live_holder_then_runs_after_release(
            self, tmp_path):
        store = ArtifactStore(tmp_path, create=True)
        store.put("p", fingerprint({"k": 5}), self._compiled())
        store.hold(what="test")
        assert len(store.live_holders()) == 1
        with pytest.raises(StoreHeld):
            store.gc(max_bytes=0)
        store.release()
        report = store.gc(max_bytes=0)
        assert report["evicted"] == 1
        assert store.entries() == {}

    def test_dead_holder_cleared_in_passing(self, tmp_path):
        store = ArtifactStore(tmp_path, create=True)
        hd = tmp_path / "holders"
        hd.mkdir()
        (hd / "999999.json").write_text(json.dumps(
            {"pid": 999999, "host": "", "boot_id": "x",
             "starttime": 1, "heartbeat": 0}))
        assert store.live_holders() == []
        assert not (hd / "999999.json").exists()

    def test_gc_evicts_version_mismatch(self, tmp_path):
        store = ArtifactStore(tmp_path, create=True)
        fp = fingerprint({"k": 6})
        store.put("stale", fp, self._compiled())
        manifest = store.manifest()
        manifest["entries"]["stale"]["jax"] = "0.0.1"
        store._write_manifest(manifest)
        report = store.gc()
        assert report["evicted"] == 1
        assert "stale" not in store.entries()

    def test_gc_lru_respects_budget(self, tmp_path):
        store = ArtifactStore(tmp_path, create=True)
        store.put("a", fingerprint({"k": "a"}), self._compiled())
        store.put("b", fingerprint({"k": "b"}), self._compiled())
        blob_a = tmp_path / store.entries()["a"]["file"]
        past = time.time() - 3600
        os.utime(blob_a, (past, past))
        budget = int(store.entries()["b"]["bytes"]) + 10
        report = store.gc(max_bytes=budget)
        assert report["evicted"] == 1
        assert set(store.entries()) == {"b"}


# ---------------------------------------------------------------------------
# InferenceEngine AOT path
# ---------------------------------------------------------------------------
class TestEngineAOT:
    def test_export_load_bit_identical_no_compile(self, tmp_path):
        e1 = _build_engine("aot_m")
        # export BEFORE any dispatch: a warm-persistent-cache infer
        # first would dedupe the export's object code in-process (the
        # verification invariant; see TestArtifactStore)
        store = ArtifactStore(tmp_path, create=True)
        exported = e1.aot_export(store)
        assert [b for b, _ in exported] == [1, 2, 4]
        x = np.random.RandomState(0).randn(3, 8).astype(np.float32)
        ref = np.asarray(e1.infer(x)[0])

        e2 = _build_engine("aot_m")
        assert e2.aot_load(store) == [1, 2, 4]
        compiles_before = _total("serving.engine.compiles")
        out = np.asarray(e2.infer(x)[0])
        assert np.array_equal(ref, out)
        # the AOT dispatch marked the bucket warm without compiling
        assert _total("serving.engine.compiles") == compiles_before
        assert 4 in e2.compiled_buckets
        assert e2.aot_buckets == [1, 2, 4]

    def test_dtype_flip_refuses_load(self, tmp_path):
        e1 = _build_engine("aot_d")
        store = ArtifactStore(tmp_path, create=True)
        e1.aot_export(store)
        e2 = _build_engine("aot_d", dtype="bf16")
        before = _total("compile.aot.fallbacks")
        assert e2.aot_load(store) == []
        assert _total("compile.aot.fallbacks") > before
        # and the JIT path still serves
        out = e2.infer(np.zeros((2, 8), np.float32))[0]
        assert np.asarray(out).shape == (2, 16)

    def test_server_loads_artifacts_before_first_dispatch(
            self, tmp_path):
        from mxnet_tpu.serving import ModelServer
        e1 = _build_engine("aot_srv")
        store = ArtifactStore(tmp_path, create=True)
        e1.aot_export(store)
        e2 = _build_engine("aot_srv")
        with ModelServer(e2, num_workers=1, warmup=True,
                         artifacts=store) as server:
            stats = server.stats()
            assert stats["aot_buckets"] == [1, 2, 4]
            out = server.infer(np.zeros((1, 8), np.float32),
                               timeout=30)
            assert np.asarray(out[0]).shape == (1, 16)

    def test_decode_engine_aot_token_identical(self, tmp_path):
        from mxnet_tpu.gluon.model_zoo.gpt import GPTDecoder
        from mxnet_tpu.serving import DecodeEngine
        np.random.seed(3)
        block = GPTDecoder(32, max_seq_len=8, num_layers=1,
                           num_heads=2, embed_dim=8)
        block.initialize(mx.init.Xavier(magnitude=2.5))
        prompts = [np.array([3, 1, 4]), np.array([1, 5])]

        def run(engine):
            outs = []
            for p in prompts:
                slot = engine.free_slots[0]
                toks = [engine.prefill(p, slot)]
                while len(toks) < 3 and not engine.slot_full(slot):
                    toks.append(int(engine.step()[slot]))
                engine.retire(slot)
                outs.append(toks)
            return outs

        e1 = DecodeEngine(block, max_slots=2, name="aot_gpt")
        store = ArtifactStore(tmp_path, create=True)
        exported = e1.aot_export(store)      # before any dispatch
        assert len(exported) == 6            # admit+step+4 buckets
        ref = run(e1)
        e2 = DecodeEngine(block, max_slots=2, name="aot_gpt")
        loaded = e2.aot_load(store)
        assert "admit" in loaded and "step" in loaded
        assert run(e2) == ref
        # the whole run rode AOT executables — the program census
        # still holds its exactly-two invariant, while the compile
        # metric counted nothing (nothing compiled)
        census = e2.compiled_programs
        assert census["admit"] == 1 and census["step"] == 1

    def test_fresh_process_load_bit_identical(self, tmp_path):
        """ISSUE 11 acceptance: an AOT-serialized executable loaded in
        a FRESH process produces outputs bit-identical to the JIT
        path."""
        script = r"""
import json, os, sys
import numpy as np
sys.path.insert(0, os.path.join(%(root)r, "tools"))
sys.path.insert(0, %(root)r)
from serve_bench import _build_model
from mxnet_tpu.serving import InferenceEngine
from mxnet_tpu.compile import ArtifactStore
sym, params = _build_model(8, 16)
engine = InferenceEngine.from_symbol(
    sym, params, {}, {"data": (8,)}, 4, name="xproc")
x = np.random.RandomState(7).randn(3, 8).astype(np.float32)
mode = sys.argv[1]
store = ArtifactStore(%(store)r, create=True)
if mode == "export":
    exported = engine.aot_export(store)         # before any dispatch
    assert [b for b, _ in exported] == [1, 2, 4], exported
    out = engine.infer(x)[0].asnumpy()          # JIT path
    np.save(os.path.join(%(store)r, "ref.npy"), out)
else:
    loaded = engine.aot_load(store)
    assert loaded == [1, 2, 4], loaded
    out = engine.infer(x)[0].asnumpy()          # AOT path
    ref = np.load(os.path.join(%(store)r, "ref.npy"))
    print(json.dumps({"identical": bool(np.array_equal(out, ref))}))
""" % {"root": ROOT, "store": str(tmp_path)}

        def run(mode):
            return subprocess.run(
                [sys.executable, "-c", script, mode],
                capture_output=True, text=True, timeout=300,
                env=dict(os.environ, JAX_PLATFORMS="cpu"))

        r = run("export")
        assert r.returncode == 0, r.stdout + r.stderr
        r = run("load")
        assert r.returncode == 0, r.stdout + r.stderr
        verdict = json.loads(r.stdout.strip().splitlines()[-1])
        assert verdict["identical"] is True


# ---------------------------------------------------------------------------
# fused-update AOT capture/replay
# ---------------------------------------------------------------------------
class TestFusedUpdateAOT:
    def _train(self, seed=0, steps=3):
        from mxnet_tpu import autograd, gluon
        from mxnet_tpu.gluon import nn
        np.random.seed(seed)
        mx.random.seed(seed)    # identical init across runs
        net = nn.Dense(4, in_units=8)
        net.initialize(mx.init.Xavier())
        tr = gluon.Trainer(net.collect_params(), "adam",
                           {"learning_rate": 0.01})
        loss_fn = gluon.loss.L2Loss()
        rng = np.random.RandomState(9)
        X = rng.rand(steps * 8, 8).astype(np.float32)
        Y = rng.rand(steps * 8, 4).astype(np.float32)
        for i in range(steps):
            x = mx.nd.array(X[i * 8:(i + 1) * 8])
            y = mx.nd.array(Y[i * 8:(i + 1) * 8])
            with autograd.record():
                loss = loss_fn(net(x), y)
            loss.backward()
            tr.step(8)
        return {k: p.data().asnumpy()
                for k, p in net.collect_params().items()}

    def test_capture_then_replay_bit_identical(self, tmp_path,
                                               monkeypatch):
        from mxnet_tpu.parallel import fused_update
        ref = self._train()                       # plain JIT
        monkeypatch.setenv("MXTPU_AOT_STORE", str(tmp_path))
        monkeypatch.setenv("MXTPU_AOT_EXPORT", "1")
        fused_update._AOT.clear()
        try:
            # fused-step era (ISSUE 15): the Trainer loop dispatches
            # ONE exchange+update program per step, so the capture
            # harvests a fused_step/ executable; the staged kernels
            # are captured under the MXTPU_FUSED_STEP=0 escape hatch
            captured = self._train()              # capture pass
            store = ArtifactStore(tmp_path)
            assert any(n.startswith("fused_step/")
                       for n in store.entries())
            monkeypatch.setenv("MXTPU_FUSED_STEP", "0")
            staged = self._train()                # staged capture pass
            assert any(n.startswith("fused/adam/")
                       for n in store.entries())
            monkeypatch.delenv("MXTPU_FUSED_STEP")
            fused_update._AOT.clear()             # force a re-load
            monkeypatch.setenv("MXTPU_AOT_EXPORT", "0")
            loads_before = _total("compile.aot.loads")
            replayed = self._train()              # AOT replay pass
            assert _total("compile.aot.loads") > loads_before
        finally:
            fused_update._AOT.clear()

        # gluon name manager gives each run a fresh dense<N> prefix:
        # compare by (sorted) suffix — weight/bias
        def by_suffix(d):
            return {k.rsplit("_", 1)[1]: v for k, v in d.items()}

        ref, captured, staged, replayed = (
            by_suffix(ref), by_suffix(captured), by_suffix(staged),
            by_suffix(replayed))
        for k in ref:
            assert np.array_equal(ref[k], captured[k]), k
            assert np.array_equal(ref[k], staged[k]), k
            assert np.array_equal(ref[k], replayed[k]), k


# ---------------------------------------------------------------------------
# cold-start telemetry
# ---------------------------------------------------------------------------
class TestColdStart:
    def test_process_start_predates_now(self):
        t = coldstart_mod.process_start_time()
        assert 0 < t <= time.time()

    def test_mark_ready_once_and_record_fields(self, tmp_path,
                                               monkeypatch):
        from mxnet_tpu.observability.telemetry import close_stream
        stream = tmp_path / "t.jsonl"
        monkeypatch.setenv("MXTPU_TELEMETRY", str(stream))
        coldstart_mod._reset_for_tests()
        rec = coldstart_mod.mark_ready("serving", engine="e")
        assert rec is not None and rec["what"] == "serving"
        assert rec["step_time"] > 0
        for field in ("compile_seconds", "cache_hits", "cache_misses",
                      "aot_loads", "aot_fallbacks"):
            assert field in rec, field
        # once per process: the second marker is refused
        assert coldstart_mod.mark_ready("train") is None
        assert coldstart_mod.cold_record()["what"] == "serving"
        close_stream()
        lines = [json.loads(l)
                 for l in stream.read_text().splitlines()]
        assert len(lines) == 1
        assert lines[0]["source"] == "compile"
        assert lines[0]["event"] == "cold_start"

    def test_gang_record_appended_with_generation(self, tmp_path,
                                                  monkeypatch):
        monkeypatch.setenv("MXTPU_GANG_DIR", str(tmp_path))
        monkeypatch.setenv("MXTPU_GANG_GENERATION", "2")
        monkeypatch.setenv("JAX_PROCESS_ID", "1")
        coldstart_mod._reset_for_tests()
        coldstart_mod.mark_ready("train")
        lines = (tmp_path / "coldstart.jsonl").read_text().splitlines()
        rec = json.loads(lines[-1])
        assert rec["generation"] == 2 and rec["rank"] == 1
        coldstart_mod._reset_for_tests()


# ---------------------------------------------------------------------------
# supervisor downtime split
# ---------------------------------------------------------------------------
class TestGangReportSplit:
    def test_restart_incident_gains_downtime_split(self, tmp_path):
        from mxnet_tpu.resilience.supervisor import GangSupervisor
        sup = GangSupervisor(["true"], nranks=2,
                             gang_dir=str(tmp_path))
        os.makedirs(str(tmp_path), exist_ok=True)
        sup.incidents = [
            {"generation": 0, "rank": 1, "exit_code": -9,
             "action": "restart", "downtime_s": 0.4},
            {"generation": 1, "rank": 0, "exit_code": 75,
             "action": "stop (preempted)", "downtime_s": 0.0},
        ]
        with open(os.path.join(str(tmp_path), "coldstart.jsonl"),
                  "w") as f:
            for rank, gen, cold, comp in ((0, 0, 4.0, 3.0),
                                          (1, 0, 4.5, 3.2),
                                          (0, 1, 1.2, 0.1),
                                          (1, 1, 1.4, 0.2)):
                f.write(json.dumps({
                    "rank": rank, "generation": gen,
                    "step_time": cold, "compile_seconds": comp,
                    "cache_hits": 5, "cache_misses": 1,
                    "aot_loads": 0, "aot_fallbacks": 0,
                    "compile_count": 3}) + "\n")
            f.write("torn {\n")          # tolerated, skipped
        report = sup.report()
        restart = report["incidents"][0]
        assert restart["downtime_split"] == {
            "relaunch_s": 0.4, "recompile_s": 0.2,
            "rank_ready_max_s": 1.4}
        # the preempt-stop incident has no relaunched generation
        assert "downtime_split" not in report["incidents"][1]
        assert report["cold_starts"]["0"]["ranks"] == 2
        assert report["cold_starts"]["1"]["compile_s_max"] == 0.2

    def test_generation_zero_spawn_clears_stale_records(
            self, tmp_path):
        from mxnet_tpu.resilience.supervisor import GangSupervisor
        stale = tmp_path / "coldstart.jsonl"
        stale.write_text('{"generation": 0, "step_time": 9}\n')
        sup = GangSupervisor([sys.executable, "-c", "pass"], nranks=1,
                             gang_dir=str(tmp_path))
        procs = sup.spawn()
        for p in procs:
            p.wait()
        assert not stale.exists()


# ---------------------------------------------------------------------------
# telemetry_report + perf_gate integration
# ---------------------------------------------------------------------------
def _write_stream(path, cold_start_s=1.5):
    records = [
        {"ts": 1.0, "source": "train", "step": 0, "step_time": 0.1,
         "compile_cache_hits": 4, "compile_cache_misses": 2,
         "batch_size": 8},
        {"ts": 2.0, "source": "train", "step": 1, "step_time": 0.1,
         "batch_size": 8},
        {"ts": 3.0, "source": "compile", "event": "cold_start",
         "what": "serving", "step_time": cold_start_s,
         "compile_seconds": 1.0, "cache_hits": 1, "cache_misses": 9,
         "aot_loads": 3, "aot_fallbacks": 1, "rank": 0},
    ]
    with open(path, "w") as f:
        for r in records:
            f.write(json.dumps(r) + "\n")


class TestReporting:
    def test_compile_section_and_headline_exclusion(self, tmp_path):
        sys.path.insert(0, os.path.join(ROOT, "tools"))
        from telemetry_report import load_records, summarize
        p = str(tmp_path / "t.jsonl")
        _write_stream(p)
        s = summarize(load_records(p))
        assert s["steps"] == 2            # cold_start excluded
        assert s["cold_starts"] == 1
        assert s["cold_start_max_s"] == 1.5
        # step deltas win (the cold record's CUMULATIVE totals cover
        # the same warm-up hits — summing both would double-count)
        assert s["compile_cache_hits"] == 4
        assert s["compile_cache_misses"] == 2
        assert s["aot_loads"] == 3 and s["aot_fallbacks"] == 1
        # a serving-only stream has no step deltas: cold totals used
        with open(p, "w") as f:
            f.write(json.dumps({
                "ts": 3.0, "source": "compile", "event": "cold_start",
                "what": "serving", "step_time": 1.0,
                "cache_hits": 7, "cache_misses": 2}) + "\n")
        s2 = summarize(load_records(p))
        assert s2["compile_cache_hits"] == 7
        assert s2["compile_cache_misses"] == 2

    def test_perf_gate_cold_start_budget(self, tmp_path):
        p = str(tmp_path / "t.jsonl")
        _write_stream(p, cold_start_s=1.5)
        gate = os.path.join(ROOT, "tools", "perf_gate.py")

        def run(*args):
            return subprocess.run(
                [sys.executable, gate, p, *args],
                capture_output=True, text=True)

        ok = run("--max-cold-start-s", "2.0")
        assert ok.returncode == 0, ok.stdout + ok.stderr
        breach = run("--max-cold-start-s", "1.0")
        assert breach.returncode == 1
        assert "cold_start_s" in breach.stderr
        # a stream with no cold-start records can't satisfy the budget
        with open(p, "w") as f:
            f.write(json.dumps({"ts": 1, "source": "train",
                                "step_time": 0.1}) + "\n")
        absent = run("--max-cold-start-s", "2.0")
        assert absent.returncode == 1

    @pytest.mark.slow
    def test_chaos_run_compile_load_falls_back_to_jit(self, tmp_path):
        """The docs/fault_tolerance.md chaos-row proof, end to end via
        tools/chaos_run.py: with the compile.load site armed fatal, a
        serving process's artifact loads all fault — and it must still
        COMPLETE by serving through the JIT path."""
        script = r"""
import os, sys
import numpy as np
sys.path.insert(0, os.path.join(%(root)r, "tools"))
sys.path.insert(0, %(root)r)
from serve_bench import _build_model
from mxnet_tpu.serving import InferenceEngine, ModelServer
from mxnet_tpu.compile import ArtifactStore
from mxnet_tpu.observability import registry as obs
sym, params = _build_model(8, 16)
store = ArtifactStore(%(store)r)
engine = InferenceEngine.from_symbol(
    sym, params, {}, {"data": (8,)}, 4, name="chaosload")
with ModelServer(engine, num_workers=1, warmup=True,
                 artifacts=store) as server:
    assert server.stats()["aot_buckets"] == []   # every load faulted
    out = server.infer(np.zeros((1, 8), np.float32), timeout=60)
    assert np.asarray(out[0]).shape == (1, 16)
fb = obs.REGISTRY.get("compile.aot.fallbacks")
assert fb is not None and fb.total() >= 3, fb
print("served through JIT fallback")
""" % {"root": ROOT, "store": str(tmp_path)}
        # export the store from a clean process first
        exp = subprocess.run(
            [sys.executable, os.path.join(ROOT, "tools",
                                          "aot_build.py"),
             "--out", str(tmp_path), "--mlp", "--features", "8",
             "--hidden", "16", "--depth", "3", "--max-batch", "4"],
            capture_output=True, text=True, timeout=600,
            env=dict(os.environ, JAX_PLATFORMS="cpu"))
        assert exp.returncode == 0, exp.stdout + exp.stderr
        r = subprocess.run(
            [sys.executable,
             os.path.join(ROOT, "tools", "chaos_run.py"),
             "--chaos", "compile.load:kind=fatal",
             "--expect", "complete", "--timeout", "300",
             "--", sys.executable, "-c", script],
            capture_output=True, text=True, timeout=600,
            env=dict(os.environ, JAX_PLATFORMS="cpu"))
        assert r.returncode == 0, r.stdout + r.stderr
        verdict = json.loads(r.stdout.strip().splitlines()[-1])
        assert verdict["outcome"] == "COMPLETED"

    def test_aot_build_tool_roundtrip(self, tmp_path):
        build = os.path.join(ROOT, "tools", "aot_build.py")
        out = str(tmp_path / "store")
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        r = subprocess.run(
            [sys.executable, build, "--out", out, "--mlp",
             "--features", "8", "--hidden", "16", "--depth", "3",
             "--max-batch", "4"],
            capture_output=True, text=True, timeout=600, env=env)
        assert r.returncode == 0, r.stdout + r.stderr
        built = json.loads(r.stdout.strip().splitlines()[-1])
        assert built["entries"] == 3      # buckets 1, 2, 4
        listed = subprocess.run(
            [sys.executable, build, "--list", out],
            capture_output=True, text=True, timeout=300, env=env)
        assert listed.returncode == 0
        assert len(json.loads(
            listed.stdout.strip().splitlines()[-1])["entries"]) == 3
        # GC with a live holder refuses with exit 2
        store = ArtifactStore(out)
        store.hold(what="test")
        try:
            refused = subprocess.run(
                [sys.executable, build, "--gc", out,
                 "--max-bytes", "0"],
                capture_output=True, text=True, timeout=300, env=env)
            assert refused.returncode == 2
            assert json.loads(refused.stdout.strip().splitlines()[-1]
                              )["refused"] is True
        finally:
            store.release()
        done = subprocess.run(
            [sys.executable, build, "--gc", out, "--max-bytes", "0"],
            capture_output=True, text=True, timeout=300, env=env)
        assert done.returncode == 0
        assert ArtifactStore(out).entries() == {}
