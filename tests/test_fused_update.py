"""Fused, donated optimizer step (parallel/fused_update.py).

The contract under test (docs/performance.md "Fused weight update"):

1. bit-parity: the fused path produces byte-identical weights AND
   optimizer states vs the per-parameter path, for SGD/momentum, Adam,
   RMSProp (both modes), AdaGrad, across mixed dtypes, lr_mult/wd_mult
   per-param scaling, and multi-precision (fp32 master for fp16);
2. dispatch count: O(n_groups) fused update dispatches per step, not
   O(n_params) — asserted via the optimizer.update.dispatches counter;
3. donation: the fused jits alias inputs to outputs (no new
   weight/state buffers), asserted via compiled-HLO introspection and
   live-array accounting on CPU;
4. ignore_stale_grad, save/load_states round-trips through fused
   steps, and the kvstore updater path all stay exact.
"""
import json
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import optimizer as opt
from mxnet_tpu.observability import registry as obs
from mxnet_tpu.parallel import fused_update as fu


@pytest.fixture
def fused_env(monkeypatch, tmp_path):
    """MXTPU_FUSED_UPDATE toggle + a COLD per-test XLA compilation
    cache. The session conftest latches the shared
    ``$TMPDIR/mxtpu_xla_cache_<uid>`` dir for the whole process; a
    rerun against that warm cache serves executables from disk instead
    of compiling, so compile-count/donation/dispatch expectations that
    held on the first (cold) run could nondeterministically flip on
    the second. Pointing ``jax_compilation_cache_dir`` at a fresh
    tmp_path makes every parity test compile from scratch regardless
    of what earlier sessions left in the shared cache."""
    import jax
    prev = jax.config.jax_compilation_cache_dir
    jax.config.update("jax_compilation_cache_dir", str(tmp_path))

    def set_fused(on):
        monkeypatch.setenv("MXTPU_FUSED_UPDATE", "1" if on else "0")
    yield set_fused
    jax.config.update("jax_compilation_cache_dir", prev)


SHAPES = [(5, 3), (7,), (4, 4), (2, 2, 2), (11,)]


def _make_params(dtype="float32", seed=0):
    rng = np.random.RandomState(seed)
    return [mx.nd.array(rng.randn(*s).astype(dtype)) for s in SHAPES]


def _make_grads(step, dtype="float32"):
    rng = np.random.RandomState(100 + step)
    return [mx.nd.array((rng.randn(*s) * 0.1).astype(dtype))
            for s in SHAPES]


def _run(optname, optkw, fused, set_fused, steps=4, dtype="float32",
         mp=False, lr_mult=None, wd_mult=None):
    set_fused(fused)
    ws = _make_params(dtype)
    o = opt.create(optname, **optkw)
    if mp:
        o.multi_precision = True
    if lr_mult:
        o.lr_mult = dict(lr_mult)
    if wd_mult:
        o.wd_mult = dict(wd_mult)
    upd = opt.get_updater(o)
    for step in range(steps):
        gs = _make_grads(step, dtype)
        upd.update_all(list(range(len(ws))), gs, ws)
    return ws, upd


def _state_arrays(state):
    if state is None:
        return []
    if isinstance(state, mx.nd.NDArray):
        return [state.asnumpy()]
    out = []
    for s in state:
        out.extend(_state_arrays(s))
    return out


def _assert_bitwise(ws_a, upd_a, ws_b, upd_b):
    for a, b in zip(ws_a, ws_b):
        assert a.dtype == b.dtype
        np.testing.assert_array_equal(np.asarray(a.asnumpy(), np.float64),
                                      np.asarray(b.asnumpy(), np.float64))
    for i in upd_a.states:
        sa = _state_arrays(upd_a.states[i])
        sb = _state_arrays(upd_b.states[i])
        assert len(sa) == len(sb)
        for x, y in zip(sa, sb):
            np.testing.assert_array_equal(np.asarray(x, np.float64),
                                          np.asarray(y, np.float64))


@pytest.mark.parametrize("name,kw", [
    ("sgd", dict(learning_rate=0.1)),
    ("sgd", dict(learning_rate=0.1, momentum=0.9)),
    ("sgd", dict(learning_rate=0.1, momentum=0.9, wd=0.01,
                 clip_gradient=0.5)),
    ("adam", dict(learning_rate=0.01)),
    ("adam", dict(learning_rate=0.01, wd=0.001, clip_gradient=1.0)),
    ("rmsprop", dict(learning_rate=0.01)),
    ("rmsprop", dict(learning_rate=0.01, centered=True,
                     clip_weights=2.0)),
    ("adagrad", dict(learning_rate=0.1, wd=0.01)),
])
def test_fused_bit_parity(name, kw, fused_env):
    a_w, a_u = _run(name, kw, True, fused_env)
    b_w, b_u = _run(name, kw, False, fused_env)
    _assert_bitwise(a_w, a_u, b_w, b_u)


@pytest.mark.parametrize("name,kw", [
    ("sgd", dict(learning_rate=0.1, momentum=0.9)),
    ("adam", dict(learning_rate=0.01)),
])
def test_fused_bit_parity_float16(name, kw, fused_env):
    a_w, a_u = _run(name, kw, True, fused_env, dtype="float16")
    b_w, b_u = _run(name, kw, False, fused_env, dtype="float16")
    _assert_bitwise(a_w, a_u, b_w, b_u)


def test_fused_lr_wd_mult_lanes(fused_env):
    """Per-param lr_mult/wd_mult values split groups but stay exact."""
    mults = dict(lr_mult={1: 0.5, 3: 2.0}, wd_mult={2: 0.0})
    a_w, a_u = _run("sgd", dict(learning_rate=0.1, momentum=0.9, wd=0.01),
                    True, fused_env, **mults)
    b_w, b_u = _run("sgd", dict(learning_rate=0.1, momentum=0.9, wd=0.01),
                    False, fused_env, **mults)
    _assert_bitwise(a_w, a_u, b_w, b_u)


def test_fused_multi_precision_master_stays_fp32(fused_env):
    """fp16 params under multi_precision: the fused pack/unpack must
    keep the fp32 master weights and fp32 states (the regression the
    Updater.sync_state_context satellite guards)."""
    a_w, a_u = _run("sgd", dict(learning_rate=0.1, momentum=0.9), True,
                    fused_env, dtype="float16", mp=True)
    b_w, b_u = _run("sgd", dict(learning_rate=0.1, momentum=0.9), False,
                    fused_env, dtype="float16", mp=True)
    _assert_bitwise(a_w, a_u, b_w, b_u)
    for i, state in a_u.states.items():
        master, mom = state
        assert master._data.dtype == np.float32
        assert mom._data.dtype == np.float32
        assert a_w[i].dtype == np.float16


def test_mixed_dtypes_group_separately_and_match(fused_env):
    """One update_all over fp32 + fp16 params: two groups, exact."""
    def run(fused):
        fused_env(fused)
        rng = np.random.RandomState(3)
        ws = [mx.nd.array(rng.randn(4, 4).astype("float32")),
              mx.nd.array(rng.randn(6,).astype("float32")),
              mx.nd.array(rng.randn(3, 3).astype("float16")),
              mx.nd.array(rng.randn(5,).astype("float16"))]
        upd = opt.get_updater(opt.create("sgd", learning_rate=0.1,
                                         momentum=0.9))
        for step in range(3):
            g = np.random.RandomState(50 + step)
            gs = [mx.nd.array((g.randn(*w.shape) * 0.1).astype(
                str(w.dtype.name if hasattr(w.dtype, "name") else w.dtype)))
                for w in ws]
            upd.update_all(list(range(len(ws))), gs, ws)
        return ws, upd

    a_w, a_u = run(True)
    b_w, b_u = run(False)
    _assert_bitwise(a_w, a_u, b_w, b_u)


def test_dispatch_count_drops_to_group_count(fused_env):
    """The telemetry counter shows O(n_groups), not O(n_params)."""
    disp = obs.REGISTRY.get("optimizer.update.dispatches")
    groups = obs.REGISTRY.get("optimizer.fused.groups")

    fused_env(True)
    ws = _make_params()
    upd = opt.get_updater(opt.create("sgd", learning_rate=0.1,
                                     momentum=0.9))
    gs = _make_grads(0)
    d0, g0 = disp.total(), groups.total()
    upd.update_all(list(range(len(ws))), gs, ws)
    assert disp.total() - d0 == 1          # one group: one dispatch
    assert groups.total() - g0 == 1

    fused_env(False)
    d0 = disp.total()
    upd.update_all(list(range(len(ws))), _make_grads(1), ws)
    assert disp.total() - d0 == len(ws)    # per-key: one per param


def test_unsupported_optimizer_falls_back_per_key(fused_env):
    fused_env(True)
    disp = obs.REGISTRY.get("optimizer.update.dispatches")
    ws = _make_params()
    upd = opt.get_updater(opt.create("nag", learning_rate=0.05,
                                     momentum=0.9))
    d0 = disp.total()
    upd.update_all(list(range(len(ws))), _make_grads(0), ws)
    assert disp.total() - d0 == len(ws)


def test_fused_jit_donates_buffers(fused_env):
    """Compiled-HLO introspection: the fused update aliases its weight
    and state inputs to outputs — no new buffers per step."""
    import jax.numpy as jnp
    spec = fu._SUPPORTED[opt.SGD]
    o = opt.create("sgd", learning_rate=0.1, momentum=0.9)
    jfn = fu._jit_for(spec, donate=True)
    w = jnp.ones((32,)); g = jnp.ones((32,)); m = jnp.zeros((32,))
    lowered = jfn.lower(w, g, (m,), 0.1, 1, 0.0, spec.hyper(o))
    assert "input_output_alias" in lowered.compile().as_text()
    # and the undonated variant must NOT alias
    jfn0 = fu._jit_for(spec, donate=False)
    lowered0 = jfn0.lower(w, g, (m,), 0.1, 1, 0.0, spec.hyper(o))
    assert "input_output_alias" not in lowered0.compile().as_text()


def test_donation_consumes_packed_inputs(fused_env, monkeypatch):
    """Live-array accounting on CPU: after a fused step with donation
    on, a 1-D single-param group's original buffers (pack is a no-op
    reshape there) are deleted — the update ran in place."""
    import jax
    monkeypatch.setenv("MXTPU_DONATE_UPDATE", "1")
    fused_env(True)
    rng = np.random.RandomState(0)
    # two 1-D params in one group: pack concatenates, so originals
    # survive; run enough steps that steady state is reached, then
    # check live-array count stability (no per-step buffer growth)
    ws = [mx.nd.array(rng.randn(64).astype("float32")),
          mx.nd.array(rng.randn(32).astype("float32"))]
    upd = opt.get_updater(opt.create("sgd", learning_rate=0.1,
                                     momentum=0.9))
    gs = [mx.nd.array(rng.randn(64).astype("float32")),
          mx.nd.array(rng.randn(32).astype("float32"))]
    from mxnet_tpu.resilience import numerics
    upd.update_all([0, 1], gs, ws)
    numerics.drain_flags()   # resolve the guard's ok flag, as a real
    # training loop's step boundary does — otherwise the pending 0-d
    # verdicts count as live arrays here
    jax.block_until_ready([w._data for w in ws])
    n0 = len(jax.live_arrays())
    for _ in range(3):
        upd.update_all([0, 1], gs, ws)
        numerics.drain_flags()
        jax.block_until_ready([w._data for w in ws])
    assert len(jax.live_arrays()) <= n0 + 2  # no unbounded buffer growth


def _stale_test_params(seed=7):
    from mxnet_tpu.gluon import Parameter
    rng = np.random.RandomState(seed)
    params = []
    for i, s in enumerate([(4, 3), (5,)]):
        p = Parameter("p%d_weight" % i, shape=s)
        p.initialize(init="zeros")
        p.set_data(mx.nd.array(rng.randn(*s).astype("float32")))
        params.append(p)
    return params


def _backward_through(params):
    """A real backward over exactly these params (sets _fresh_grad)."""
    from mxnet_tpu import autograd
    with autograd.record():
        loss = sum((p.data() * p.data()).sum() for p in params)
    loss.backward()


def test_ignore_stale_grad_parity(fused_env):
    """Trainer.step(ignore_stale_grad=True) skips params whose grad was
    not refreshed by a backward since the last update — identically on
    the fused and per-key paths."""
    def run(fused):
        fused_env(fused)
        params = _stale_test_params()
        tr = mx.gluon.Trainer(params, "sgd",
                              {"learning_rate": 0.1, "momentum": 0.9})
        _backward_through(params)
        tr.step(1, ignore_stale_grad=True)
        snap1 = [p.data().asnumpy().copy() for p in params]
        # no new backward: a second stale step must be a no-op
        tr.step(1, ignore_stale_grad=True)
        snap2 = [p.data().asnumpy() for p in params]
        for a, b in zip(snap1, snap2):
            np.testing.assert_array_equal(a, b)
        # refresh ONE param's grad: only that one moves
        _backward_through(params[:1])
        tr.step(1, ignore_stale_grad=True)
        return [p.data().asnumpy() for p in params]

    a = run(True)
    b = run(False)
    for x, y in zip(a, b):
        np.testing.assert_array_equal(x, y)


def test_ignore_stale_grad_skips_never_backwarded(fused_env):
    """A param no backward ever touched must not move (wd/momentum on a
    zero grad would silently drift it), and zero_grad() must NOT count
    as a refresh — the reference's _fresh_grad contract."""
    fused_env(True)
    params = _stale_test_params()
    tr = mx.gluon.Trainer(params, "sgd",
                          {"learning_rate": 0.1, "momentum": 0.9,
                           "wd": 0.1})
    before = [p.data().asnumpy().copy() for p in params]
    tr.step(1, ignore_stale_grad=True)   # no backward at all: no-op
    for p, b in zip(params, before):
        np.testing.assert_array_equal(p.data().asnumpy(), b)
    _backward_through(params[:1])        # p0 fresh, p1 still never
    tr.step(1, ignore_stale_grad=True)
    assert not np.array_equal(params[0].data().asnumpy(), before[0])
    np.testing.assert_array_equal(params[1].data().asnumpy(), before[1])
    moved = params[0].data().asnumpy().copy()
    params[0].zero_grad()                # zeroing is not a refresh
    tr.step(1, ignore_stale_grad=True)
    np.testing.assert_array_equal(params[0].data().asnumpy(), moved)


def test_multi_precision_flag_on_fp32_weights_consistent(fused_env):
    """multi_precision=True on fp32 weights (no master pair exists):
    BOTH paths must take the plain update branch and agree bitwise —
    the per-key path used to misread Adam's (mean, var) as
    (master, base) and crash."""
    results = []
    for fused in (True, False):
        fused_env(fused)
        ws = _make_params()
        o = opt.create("adam", learning_rate=0.01)
        o.multi_precision = True
        upd = opt.get_updater(o)
        for step in range(3):
            upd.update_all(list(range(len(ws))), _make_grads(step), ws)
        results.append((ws, upd))
    _assert_bitwise(*results[0], *results[1])


def test_save_load_states_roundtrip_through_fused_step(fused_env):
    """get_states/set_states mid-run: the resumed updater continues
    bit-identically to the uninterrupted one."""
    fused_env(True)
    ws_a = _make_params()
    ws_b = _make_params()
    u_a = opt.get_updater(opt.create("adam", learning_rate=0.01))
    for step in range(2):
        u_a.update_all(list(range(len(ws_a))), _make_grads(step), ws_a)
    blob = u_a.get_states(dump_optimizer=True)

    u_b = opt.get_updater(opt.create("adam", learning_rate=0.01))
    for step in range(2):
        u_b.update_all(list(range(len(ws_b))), _make_grads(step), ws_b)
    u_b.set_states(blob)
    # weights continue from the same values (states came from u_a;
    # both weight sets saw identical updates so they are equal here)
    for step in range(2, 4):
        u_a.update_all(list(range(len(ws_a))), _make_grads(step), ws_a)
        u_b.update_all(list(range(len(ws_b))), _make_grads(step), ws_b)
    _assert_bitwise(ws_a, u_a, ws_b, u_b)


def test_kvstore_updater_path_fused_parity(fused_env):
    """update-on-kvstore: push_all lands the whole batch through ONE
    fused update, bit-identical to the per-key store."""
    disp = obs.REGISTRY.get("optimizer.update.dispatches")

    def run(fused):
        fused_env(fused)
        rng = np.random.RandomState(11)
        kv = mx.kv.create("device")
        kv.set_optimizer(opt.create("sgd", learning_rate=0.1,
                                    momentum=0.9))
        keys = list(range(len(SHAPES)))
        for k, s in zip(keys, SHAPES):
            kv.init(k, mx.nd.array(rng.randn(*s).astype("float32")))
        d0 = disp.total()
        for step in range(3):
            kv.push_all(keys, _make_grads(step),
                        priorities=[-k for k in keys])
        return [kv._data[k].asnumpy() for k in keys], disp.total() - d0

    a, da = run(True)
    b, db = run(False)
    for x, y in zip(a, b):
        np.testing.assert_array_equal(x, y)
    assert da == 3          # one fused group per push
    assert db == 3 * len(SHAPES)


def test_kvstore_push_duplicate_keys_updates_twice(fused_env):
    """Repeated keys in one push keep per-key semantics (two sequential
    optimizer steps) — the batched-update scope must not collapse them."""
    fused_env(True)
    kv = mx.kv.create("device")
    kv.set_optimizer(opt.create("sgd", learning_rate=0.1, momentum=0.9))
    kv.init("w", mx.nd.array(np.ones(4, np.float32)))
    g = mx.nd.array(np.full(4, 1.0, np.float32))
    kv.push(["w", "w"], [g, g])
    # two momentum steps: m=-0.1, w=0.9; m=0.9*-0.1-0.1=-0.19, w=0.71
    np.testing.assert_allclose(kv._data["w"].asnumpy(),
                               np.full(4, 0.71), rtol=1e-6)


def test_donate_toggle_works_after_import(monkeypatch):
    """MXTPU_DONATE_UPDATE is re-read per call by the per-op kernels
    too, so opting out after import really stops donation."""
    import jax.numpy as jnp
    monkeypatch.setenv("MXTPU_DONATE_UPDATE", "0")
    o = opt.create("sgd", learning_rate=0.1, momentum=0.9)
    w = mx.nd.array(np.ones(8, np.float32))
    s = o.create_state(0, w)
    keep = w._data
    o.update(0, w, mx.nd.array(np.ones(8, np.float32)), s)
    assert not keep.is_deleted()
    monkeypatch.setenv("MXTPU_DONATE_UPDATE", "1")
    keep = w._data
    o.update(0, w, mx.nd.array(np.ones(8, np.float32)), s)
    assert keep.is_deleted()


def test_scheduler_skewed_counts_parity(fused_env):
    """lr_scheduler + skewed update counts: two same-t params can
    resolve different lr mid-collection (the scheduler reads the global
    num_update a higher-count param just bumped); the fused cohorts
    must honor each resolved lr exactly like the per-key path."""
    def run(fused):
        fused_env(fused)
        ws = _make_params()
        o = opt.create("sgd", learning_rate=0.5, momentum=0.9,
                       lr_scheduler=mx.lr_scheduler.FactorScheduler(
                           step=2, factor=0.5, base_lr=0.5))
        upd = opt.get_updater(o)
        # skew: param 1 advances three steps alone (per-key: len<2)
        for step in range(3):
            upd.update_all([1], [_make_grads(step)[1]], [ws[1]])
        # now a full update_all: params 0 and 2 share t but straddle
        # param 1's num_update bump in caller order
        for step in range(3, 6):
            upd.update_all(list(range(len(ws))), _make_grads(step), ws)
        return ws, upd

    a_w, a_u = run(True)
    b_w, b_u = run(False)
    _assert_bitwise(a_w, a_u, b_w, b_u)


def test_steptimer_records_fused_fields(fused_env):
    from mxnet_tpu.observability.telemetry import StepTimer
    fused_env(True)
    timer = StepTimer("test.fused")
    timer.begin_step()
    ws = _make_params()
    upd = opt.get_updater(opt.create("sgd", learning_rate=0.1,
                                     momentum=0.9))
    upd.update_all(list(range(len(ws))), _make_grads(0), ws)
    rec = timer.end_step(batch_size=4)
    assert rec["update_dispatches"] == 1
    assert rec["fused_groups"] == 1
    assert rec.get("fused_pack_seconds", 0) > 0


def test_telemetry_report_optimizer_section(tmp_path):
    from tools import telemetry_report as tr
    records = [{"step_time": 0.1, "optimizer_time": 0.02,
                "update_dispatches": 2, "fused_groups": 2,
                "fused_pack_seconds": 0.001,
                "fused_update_seconds": 0.004, "batch_size": 8}
               for _ in range(4)]
    s = tr.summarize(records)
    assert s["update_dispatches"] == 8
    assert s["update_dispatches_per_step"] == 2.0
    assert s["fused_groups"] == 8
    assert s["optimizer_p50_s"] == pytest.approx(0.02)
    text = tr.format_summary(s)
    assert "optimizer" in text and "dispatches" in text
    # CI gate behavior unchanged: malformed input still exits non-zero
    bad = tmp_path / "bad.jsonl"
    bad.write_text('{"step_time": 0.1}\nnot json\n')
    assert tr.main([str(bad)]) == 1
    good = tmp_path / "good.jsonl"
    good.write_text("\n".join(json.dumps(r) for r in records) + "\n")
    assert tr.main(["--json", str(good)]) == 0


def test_update_cost_accounting():
    """MFU accounting helper: fused update FLOPs/bytes per optimizer."""
    from mxnet_tpu.parallel import update_cost
    n = 1000
    sgd = update_cost(opt.create("sgd", momentum=0.9), n, 4)
    plain = update_cost(opt.create("sgd"), n, 4)
    adam = update_cost(opt.create("adam"), n, 4)
    assert sgd["bytes"] == 5 * n * 4 and sgd["flops"] == 5 * n
    assert plain["bytes"] < sgd["bytes"] < adam["bytes"]
    assert adam["flops"] > sgd["flops"]
    assert update_cost(opt.create("nag"), n, 4) is None


def test_fused_layout_plans_are_reused(fused_env):
    """Steady-state steps reuse the memoized layout plan (the PR-3
    GradBucketer invariant carried over to the update path)."""
    fused_env(True)
    ws = _make_params()
    upd = opt.get_updater(opt.create("sgd", learning_rate=0.1,
                                     momentum=0.9))
    for step in range(3):
        upd.update_all(list(range(len(ws))), _make_grads(step), ws)
    assert len(upd._layout._plans) == 1
