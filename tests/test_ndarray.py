"""NDArray unit tests (reference model: tests/python/unittest/test_ndarray.py)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd


def test_creation():
    a = nd.zeros((2, 3))
    assert a.shape == (2, 3)
    assert a.dtype == np.float32
    assert a.asnumpy().sum() == 0

    b = nd.ones((4,), dtype="int32")
    assert b.dtype == np.int32
    assert b.asnumpy().tolist() == [1, 1, 1, 1]

    c = nd.full((2, 2), 7.0)
    assert (c.asnumpy() == 7).all()

    d = nd.array([[1, 2], [3, 4]])
    assert d.shape == (2, 2)
    assert d.dtype == np.float32

    e = nd.arange(0, 10, 2)
    assert e.asnumpy().tolist() == [0, 2, 4, 6, 8]


def test_elementwise_arithmetic():
    a = nd.array([1.0, 2.0, 3.0])
    b = nd.array([4.0, 5.0, 6.0])
    assert ((a + b).asnumpy() == [5, 7, 9]).all()
    assert ((b - a).asnumpy() == [3, 3, 3]).all()
    assert ((a * b).asnumpy() == [4, 10, 18]).all()
    assert np.allclose((b / a).asnumpy(), [4, 2.5, 2])
    assert ((a + 1).asnumpy() == [2, 3, 4]).all()
    assert ((1 + a).asnumpy() == [2, 3, 4]).all()
    assert ((2 - a).asnumpy() == [1, 0, -1]).all()
    assert np.allclose((2 / a).asnumpy(), [2, 1, 2.0 / 3])
    assert ((a ** 2).asnumpy() == [1, 4, 9]).all()
    assert ((-a).asnumpy() == [-1, -2, -3]).all()


def test_comparison_ops():
    a = nd.array([1.0, 2.0, 3.0])
    b = nd.array([2.0, 2.0, 2.0])
    assert ((a > b).asnumpy() == [0, 0, 1]).all()
    assert ((a >= b).asnumpy() == [0, 1, 1]).all()
    assert ((a == 2).asnumpy() == [0, 1, 0]).all()
    assert ((a != 2).asnumpy() == [1, 0, 1]).all()


def test_broadcast():
    a = nd.ones((2, 1, 3))
    b = nd.ones((1, 4, 3))
    assert (a + b).shape == (2, 4, 3)
    c = nd.broadcast_to(nd.ones((1, 3)), shape=(5, 3))
    assert c.shape == (5, 3)


def test_reduce():
    a = nd.array(np.arange(24).reshape(2, 3, 4))
    assert a.sum().asscalar() == np.arange(24).sum()
    assert a.sum(axis=1).shape == (2, 4)
    assert a.sum(axis=(0, 2)).shape == (3,)
    assert a.mean().asscalar() == pytest.approx(11.5)
    assert a.max().asscalar() == 23
    assert a.min().asscalar() == 0
    s = nd.sum(a, axis=1, keepdims=True)
    assert s.shape == (2, 1, 4)
    e = nd.sum(a, axis=1, exclude=True)
    assert e.shape == (3,)


def test_reshape_codes():
    a = nd.zeros((2, 3, 4))
    assert a.reshape((24,)).shape == (24,)
    assert a.reshape((-1, 4)).shape == (6, 4)
    assert a.reshape((0, -1)).shape == (2, 12)
    assert nd.Reshape(a, shape=(-3, 4)).shape == (6, 4)
    assert nd.Reshape(a, shape=(0, 0, -1)).shape == (2, 3, 4)
    assert nd.Reshape(a, shape=(-2,)).shape == (2, 3, 4)
    assert nd.Reshape(a, shape=(-4, 1, 2, 0, 0)).shape == (1, 2, 3, 4)


def test_transpose_and_shape_ops():
    a = nd.zeros((2, 3, 4))
    assert a.T.shape == (4, 3, 2)
    assert nd.transpose(a, axes=(1, 0, 2)).shape == (3, 2, 4)
    assert nd.expand_dims(a, axis=1).shape == (2, 1, 3, 4)
    assert a.flatten().shape == (2, 12)
    assert nd.squeeze(nd.zeros((1, 3, 1)), axis=0).shape == (3, 1)
    assert nd.swapaxes(a, dim1=0, dim2=2).shape == (4, 3, 2)
    assert nd.tile(nd.ones((2, 2)), reps=(2, 3)).shape == (4, 6)
    assert nd.repeat(nd.ones((2,)), repeats=3).shape == (6,)
    assert nd.flip(nd.array([1, 2, 3]), axis=0).asnumpy().tolist() == [3, 2, 1]


def test_concat_split_stack():
    a, b = nd.ones((2, 3)), nd.zeros((2, 3))
    c = nd.Concat(a, b, dim=0)
    assert c.shape == (4, 3)
    c2 = nd.concat(a, b, dim=1)
    assert c2.shape == (2, 6)
    s = nd.stack(a, b, axis=0)
    assert s.shape == (2, 2, 3)
    parts = nd.split(nd.ones((4, 6)), num_outputs=3, axis=1)
    assert len(parts) == 3 and parts[0].shape == (4, 2)
    parts = nd.split(nd.ones((4, 6)), num_outputs=2, axis=0, squeeze_axis=False)
    assert parts[0].shape == (2, 6)


def test_slicing_indexing():
    a = nd.array(np.arange(12).reshape(3, 4))
    assert a[1].asnumpy().tolist() == [4, 5, 6, 7]
    assert a[0:2].shape == (2, 4)
    assert a[1, 2].asscalar() == 6
    assert nd.slice(a, begin=(0, 1), end=(2, 3)).shape == (2, 2)
    assert nd.slice_axis(a, axis=1, begin=1, end=3).shape == (3, 2)
    a[0] = 9.0
    assert (a[0].asnumpy() == 9).all()
    a[1, 1] = -1.0
    assert a.asnumpy()[1, 1] == -1


def test_take_embedding_onehot():
    w = nd.array(np.arange(12).reshape(4, 3))
    idx = nd.array([0, 2])
    t = nd.take(w, idx)
    assert t.shape == (2, 3)
    emb = nd.Embedding(idx, w, input_dim=4, output_dim=3)
    assert (emb.asnumpy() == t.asnumpy()).all()
    oh = nd.one_hot(nd.array([0, 1, 2]), depth=4)
    assert oh.shape == (3, 4)
    assert oh.asnumpy().sum() == 3


def test_dot():
    a = nd.array(np.random.rand(3, 4))
    b = nd.array(np.random.rand(4, 5))
    c = nd.dot(a, b)
    assert np.allclose(c.asnumpy(), a.asnumpy() @ b.asnumpy(), atol=1e-5)
    ct = nd.dot(a, nd.array(b.asnumpy().T), transpose_b=True)
    assert np.allclose(ct.asnumpy(), c.asnumpy(), atol=1e-5)
    bd = nd.batch_dot(nd.ones((2, 3, 4)), nd.ones((2, 4, 5)))
    assert bd.shape == (2, 3, 5)


def test_ordering():
    a = nd.array([[3.0, 1.0, 2.0], [0.5, 2.5, 1.5]])
    top = nd.topk(a, k=2, ret_typ="value")
    assert top.asnumpy()[0].tolist() == [3, 2]
    s = nd.sort(a, axis=-1)
    assert s.asnumpy()[0].tolist() == [1, 2, 3]
    ags = nd.argsort(a, axis=-1)
    assert ags.asnumpy()[0].tolist() == [1, 2, 0]
    assert nd.argmax(a, axis=1).asnumpy().tolist() == [0, 1]
    assert nd.argmin(a, axis=1).asnumpy().tolist() == [1, 0]


def test_cast_astype():
    a = nd.array([1.5, 2.5])
    b = a.astype("int32")
    assert b.dtype == np.int32
    c = a.astype(np.float16)
    assert c.dtype == np.float16
    d = nd.Cast(a, dtype="bfloat16")
    assert d.asnumpy().astype(np.float32).tolist() == [1.5, 2.5]


def test_context_placement():
    a = nd.ones((2, 2), ctx=mx.cpu(0))
    assert a.context.device_type == "cpu"
    b = a.as_in_context(mx.cpu(0))
    assert b is a
    c = a.copyto(mx.cpu(1))
    assert c.context.device_id in (0, 1)  # single-device fallback allowed


def test_serialization(tmp_path):
    fname = str(tmp_path / "arrs.npz")
    data = {"w": nd.array(np.random.rand(3, 3)), "b": nd.ones((3,))}
    nd.save(fname, data)
    loaded = nd.load(fname)
    assert set(loaded) == {"w", "b"}
    assert np.allclose(loaded["w"].asnumpy(), data["w"].asnumpy())

    fname2 = str(tmp_path / "arrs_list.npz")
    nd.save(fname2, [nd.zeros((2,)), nd.ones((3,))])
    ll = nd.load(fname2)
    assert len(ll) == 2 and ll[1].shape == (3,)


def test_wait_and_async():
    a = nd.ones((100, 100))
    b = nd.dot(a, a)
    b.wait_to_read()
    nd.waitall()
    assert b.asnumpy()[0, 0] == 100


def test_inplace_ops():
    a = nd.ones((3,))
    aid = id(a)
    a += 1
    assert id(a) == aid and (a.asnumpy() == 2).all()
    a *= 3
    assert (a.asnumpy() == 6).all()
    a -= 1
    assert (a.asnumpy() == 5).all()
    a /= 5
    assert (a.asnumpy() == 1).all()


def test_unary_math():
    a = nd.array([1.0, 4.0, 9.0])
    assert np.allclose(nd.sqrt(a).asnumpy(), [1, 2, 3])
    assert np.allclose(nd.square(a).asnumpy(), [1, 16, 81])
    assert np.allclose(nd.exp(nd.zeros((2,))).asnumpy(), [1, 1])
    assert np.allclose(nd.log(a).asnumpy(), np.log([1, 4, 9]), atol=1e-6)
    assert np.allclose(nd.rsqrt(a).asnumpy(), 1 / np.sqrt([1, 4, 9]))
    assert np.allclose(nd.abs(nd.array([-1.0, 2.0])).asnumpy(), [1, 2])
    assert np.allclose(nd.sign(nd.array([-5.0, 0.0, 3.0])).asnumpy(), [-1, 0, 1])
    assert np.allclose(nd.clip(a, a_min=2, a_max=5).asnumpy(), [2, 4, 5])
    assert np.allclose(nd.relu(nd.array([-1.0, 1.0])).asnumpy(), [0, 1])
    assert np.allclose(nd.sigmoid(nd.zeros((1,))).asnumpy(), [0.5])


def test_where():
    cond = nd.array([1.0, 0.0, 1.0])
    x = nd.array([1.0, 2.0, 3.0])
    y = nd.array([10.0, 20.0, 30.0])
    assert nd.where(cond, x, y).asnumpy().tolist() == [1, 20, 3]


def test_sequence_ops():
    data = nd.array(np.arange(24).reshape(4, 2, 3))  # (T=4, B=2, 3)
    length = nd.array([2, 3])
    masked = nd.SequenceMask(data, length, use_sequence_length=True, value=-1)
    npd = masked.asnumpy()
    assert (npd[2, 0] == -1).all() and (npd[3, 1] == -1).all()
    assert (npd[1, 0] != -1).all()
    last = nd.SequenceLast(data, length, use_sequence_length=True)
    assert last.shape == (2, 3)
    assert np.allclose(last.asnumpy()[0], data.asnumpy()[1, 0])
    rev = nd.SequenceReverse(data, length, use_sequence_length=True)
    assert np.allclose(rev.asnumpy()[0, 0], data.asnumpy()[1, 0])


def test_gather_scatter():
    data = nd.array(np.arange(9).reshape(3, 3))
    idx = nd.array([[0, 2], [1, 0]])
    g = nd.gather_nd(data, idx)
    assert g.asnumpy().tolist() == [1, 6]
    s = nd.scatter_nd(nd.array([9.0, 8.0]), idx, shape=(3, 3))
    assert s.asnumpy()[0, 1] == 9 and s.asnumpy()[2, 0] == 8


def test_strict_fence(monkeypatch):
    """wait_to_read/wait_to_write/waitall share ONE fence (_fence), and
    strict mode device_gets a dependent slice — the only reliable fence
    on remote/tunneled backends where block_until_ready can return
    before remote execution completes (docs/faq/env_var.md,
    MXTPU_STRICT_FENCE; reference WaitToRead semantics,
    include/mxnet/ndarray.h:315)."""
    import jax
    from mxnet_tpu.ndarray import ndarray as nd_mod

    gets = []
    real_get = jax.device_get
    monkeypatch.setattr(jax, "device_get",
                        lambda x: (gets.append(1), real_get(x))[1])

    monkeypatch.setenv("MXTPU_STRICT_FENCE", "1")
    a = nd.ones((4, 4)) * 3
    a.wait_to_read()
    assert len(gets) == 1          # one tiny dependent-slice fetch
    assert a.asnumpy()[0, 0] == 3  # value untouched by the fence
    a.wait_to_write()
    assert len(gets) == 2

    n_before = len(gets)
    nd.waitall()
    assert len(gets) > n_before    # waitall fences strictly too

    # scalars and empty arrays fence without error
    nd.array(7.0).wait_to_read()
    nd.zeros((0, 3)).wait_to_read()

    # forced off: no device_get
    monkeypatch.setenv("MXTPU_STRICT_FENCE", "0")
    n = len(gets)
    a.wait_to_read()
    assert len(gets) == n

    # both user entry points route through the shared implementation
    # (_fence_many; waitall batches its strict leg into one device_get)
    fenced = []
    monkeypatch.setattr(nd_mod, "_fence_many",
                        lambda ds: fenced.extend(id(d) for d in ds))
    a.wait_to_read()
    assert fenced == [id(a._data)]
    nd.waitall()
    assert fenced.count(id(a._data)) >= 2
