"""Unit tests for the gradient fusion-bucket layer (ISSUE 3):
GradBucketer planning/packing, priority-ordered batched push/pull, the
fused multi-addend merge, the bucketed DistKVStore exchange (with a
stubbed collective — the 4-process bit-identity parity runs in
test_dist_kvstore.py), and the telemetry/report plumbing."""
import json
import os
import subprocess
import sys

import numpy as np
import pytest

import jax.numpy as jnp

import mxnet_tpu as mx
from mxnet_tpu.kvstore import _sum_arrays, _sum_jnp, _priority_order
from mxnet_tpu.observability import registry as obs
from mxnet_tpu.parallel.bucketing import GradBucketer

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# GradBucketer planning
# ---------------------------------------------------------------------------
def test_plan_fills_buckets_to_target():
    b = GradBucketer(target_bytes=1024)
    # 8 fp32 keys of 64 elems = 256 B each -> 4 keys per bucket
    items = [("k%d" % i, (64,), "float32", -i, False) for i in range(8)]
    plan = b.plan(items)
    assert len(plan) == 2
    assert plan[0].keys == ["k0", "k1", "k2", "k3"]
    assert plan[1].keys == ["k4", "k5", "k6", "k7"]
    assert plan[0].offsets == [0, 64, 128, 192]
    assert plan[0].total == 256 and plan[0].nbytes == 1024


def test_plan_separates_dtypes_and_lanes():
    b = GradBucketer(target_bytes=1 << 20)
    items = [("a", (8,), "float32", 0, False),
             ("b", (8,), "float16", 0, False),
             ("c", (8,), "float32", 0, False),
             ("d", (8,), "float32", 0, True)]  # different lane
    plan = b.plan(items)
    assert len(plan) == 3
    by_keys = {tuple(p.keys) for p in plan}
    assert ("a", "c") in by_keys
    assert ("b",) in by_keys
    assert ("d",) in by_keys


def test_plan_big_key_rides_alone():
    b = GradBucketer(target_bytes=1024)
    items = [("small1", (8,), "float32", 0, False),
             ("big", (1024,), "float32", -1, False),
             ("small2", (8,), "float32", -2, False)]
    plan = b.plan(items)
    assert len(plan) == 2
    solo = [p for p in plan if p.keys == ["big"]]
    assert solo and solo[0].total == 1024
    small = [p for p in plan if "small1" in p.keys][0]
    assert small.keys == ["small1", "small2"]


def test_plan_orders_buckets_by_priority():
    b = GradBucketer(target_bytes=32)  # each 32 B key rides alone
    items = [("low", (8,), "float32", -5, False),
             ("high", (8,), "float32", 0, False),
             ("mid", (8,), "float32", -2, False)]
    plan = b.plan(items)
    assert [p.keys[0] for p in plan] == ["high", "mid", "low"]


def test_plan_is_cached_per_signature():
    b = GradBucketer(target_bytes=1024)
    items = tuple(("k%d" % i, (4,), "float32", -i, False)
                  for i in range(4))
    assert b.plan(items) is b.plan(items)
    b.clear()
    assert b.plan(items) is b.plan(items)


def test_pack_unpack_roundtrip_bit_identical():
    b = GradBucketer(target_bytes=1 << 20)
    shapes = [(5,), (3, 4), (2, 2, 2)]
    items = [("k%d" % i, s, "float32", -i, False)
             for i, s in enumerate(shapes)]
    (bucket,) = b.plan(items)
    rng = np.random.RandomState(0)
    grads = [jnp.asarray(rng.randn(*s).astype(np.float32))
             for s in shapes]
    outs = bucket.unpack(bucket.pack(grads))
    for g, o in zip(grads, outs):
        assert o.shape == g.shape
        assert np.asarray(o).tobytes() == np.asarray(g).tobytes()


# ---------------------------------------------------------------------------
# fused multi-addend merge (satellite: no O(n) serial add chain)
# ---------------------------------------------------------------------------
def test_sum_jnp_same_shape_fast_path():
    arrs = [jnp.full((3, 2), float(i + 1)) for i in range(4)]
    out = _sum_jnp(arrs)
    assert np.array_equal(np.asarray(out), np.full((3, 2), 10.0))
    assert out.dtype == arrs[0].dtype


def test_sum_jnp_mismatched_shapes_fall_back_to_chain():
    out = _sum_jnp([jnp.ones((2, 2)), jnp.ones((2,))])
    assert np.array_equal(np.asarray(out), np.full((2, 2), 2.0))


def test_sum_arrays_matches_manual_sum():
    vals = [mx.nd.full((4,), float(i)) for i in range(3)]
    assert np.array_equal(np.asarray(_sum_arrays(vals)),
                          np.full((4,), 3.0))


# ---------------------------------------------------------------------------
# priority plumbing (satellite: push/pull no longer drop priority)
# ---------------------------------------------------------------------------
def test_priority_order_stable_descending():
    assert _priority_order(3, None) == [0, 1, 2]
    assert _priority_order(3, [0, 5, 1]) == [1, 2, 0]
    assert _priority_order(3, [0, 0, 0]) == [0, 1, 2]  # stable ties
    with pytest.raises(mx.MXNetError):
        _priority_order(3, [1, 2])


def test_push_all_issues_in_priority_order(monkeypatch):
    kv = mx.kv.create("local")
    for i in range(3):
        kv.init(i, mx.nd.zeros((2,)))
    seen = []
    orig = kv._push_one

    def spy(k, v):
        seen.append(k)
        return orig(k, v)

    monkeypatch.setattr(kv, "_push_one", spy)
    kv.push_all([0, 1, 2], [mx.nd.ones((2,))] * 3, priorities=[-0, -1, -2])
    assert seen == [0, 1, 2]
    seen.clear()
    kv.push_all([0, 1, 2], [mx.nd.ones((2,))] * 3, priorities=[-2, 0, -1])
    assert seen == [1, 2, 0]


def test_pull_all_priority_and_values():
    kv = mx.kv.create("local")
    for i in range(3):
        kv.init(i, mx.nd.full((2,), float(i)))
    outs = [mx.nd.zeros((2,)) for _ in range(3)]
    kv.pull_all([0, 1, 2], outs, priorities=[-0, -1, -2])
    for i, o in enumerate(outs):
        assert np.array_equal(o.asnumpy(), np.full((2,), float(i)))


def test_local_push_all_matches_sequential_push():
    kv_seq = mx.kv.create("local")
    kv_all = mx.kv.create("local")
    shapes = [(3,), (2, 4), (5,)]
    rng = np.random.RandomState(3)
    grads = [mx.nd.array(rng.randn(*s).astype(np.float32))
             for s in shapes]
    for i, s in enumerate(shapes):
        kv_seq.init(i, mx.nd.zeros(s))
        kv_all.init(i, mx.nd.zeros(s))
        kv_seq.push(i, grads[i], priority=-i)
    kv_all.push_all(list(range(3)), grads,
                    priorities=[-i for i in range(3)])
    for i, s in enumerate(shapes):
        a, b = mx.nd.zeros(s), mx.nd.zeros(s)
        kv_seq.pull(i, out=a)
        kv_all.pull(i, out=b)
        assert a.asnumpy().tobytes() == b.asnumpy().tobytes()


# ---------------------------------------------------------------------------
# bucketed DistKVStore exchange with a stubbed collective
# ---------------------------------------------------------------------------
def _fake_dist_store(monkeypatch, calls):
    """DistKVStore forced onto the bucketed path with the cross-process
    collective replaced by a recording doubler (nproc=2 stand-in)."""
    from mxnet_tpu.parallel.kvstore_dist import DistKVStore
    kv = DistKVStore("dist_sync")  # single process: init is a no-op
    kv._nproc = 2

    def fake_sum(x):
        calls.append(int(x.size))
        return x * 2

    monkeypatch.setattr(kv, "_cross_process_sum", fake_sum)
    return kv


def test_dist_push_all_one_collective_per_bucket(monkeypatch):
    calls = []
    kv = _fake_dist_store(monkeypatch, calls)
    shapes = [((8,), "float32"), ((16,), "float32"), ((4, 4), "float32"),
              ((6,), "float16")]
    keys = ["p%d" % i for i in range(len(shapes))]
    grads = []
    for k, (s, dt) in zip(keys, shapes):
        kv.init(k, mx.nd.zeros(s, dtype=dt))
        grads.append(mx.nd.full(s, 3.0, dtype=dt))
    b0 = obs.REGISTRY.get("kvstore.bucket.count").total()
    k0 = obs.REGISTRY.get("kvstore.bucket.keys").total()
    kv.push_all(keys, grads, priorities=[-i for i in range(len(keys))])
    # 3 fp32 keys fuse into one bucket, the fp16 key gets its own:
    # 2 collectives for 4 parameters
    assert calls == [8 + 16 + 16, 6]
    assert obs.REGISTRY.get("kvstore.bucket.count").total() - b0 == 2
    assert obs.REGISTRY.get("kvstore.bucket.keys").total() - k0 == 4
    for k, (s, dt) in zip(keys, shapes):
        out = mx.nd.zeros(s, dtype=dt)
        kv.pull(k, out=out)
        assert np.array_equal(out.asnumpy(),
                              np.full(s, 6.0, dtype=dt))  # doubled


def test_dist_push_all_bucket_size_zero_falls_back(monkeypatch):
    calls = []
    kv = _fake_dist_store(monkeypatch, calls)
    kv.set_bucket_size_mb(0)
    for i in range(3):
        kv.init("q%d" % i, mx.nd.zeros((4,)))
    kv.push_all(["q0", "q1", "q2"], [mx.nd.ones((4,))] * 3,
                priorities=[0, -1, -2])
    assert calls == [4, 4, 4]  # per-key path: one collective per key


def test_dist_push_all_uninitialized_key_raises(monkeypatch):
    kv = _fake_dist_store(monkeypatch, [])
    with pytest.raises(mx.MXNetError):
        kv.push_all(["nope"], [mx.nd.ones((2,))])


def test_trainer_step_uses_batched_exchange(monkeypatch):
    """gluon Trainer's STAGED reduce routes through push_all/pull_all
    (the fused one-program step subsumes the kvstore hop entirely —
    pinned off here; tests/test_fused_step.py covers that path)."""
    monkeypatch.setenv("MXTPU_FUSED_STEP", "0")
    from mxnet_tpu import gluon, autograd
    from mxnet_tpu.gluon import nn
    net = nn.Dense(3, in_units=4)
    net.initialize()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1}, kvstore="device")
    trainer._ensure_ready()
    pushed = {}
    orig_push_all = trainer._kvstore.push_all

    def spy(keys, values, priorities=None):
        pushed["keys"] = list(keys)
        pushed["priorities"] = list(priorities)
        return orig_push_all(keys, values, priorities=priorities)

    monkeypatch.setattr(trainer._kvstore, "push_all", spy)
    x = mx.nd.ones((2, 4))
    with autograd.record():
        y = net(x)
        loss = (y * y).sum()
    loss.backward()
    trainer.step(2)
    assert len(pushed["keys"]) == 2  # weight + bias in ONE batched push
    assert pushed["priorities"] == [-k for k in pushed["keys"]]


# ---------------------------------------------------------------------------
# telemetry record + report section
# ---------------------------------------------------------------------------
def test_steptimer_records_allreduce_and_bucket_deltas():
    from mxnet_tpu.observability.telemetry import StepTimer
    timer = StepTimer("unit.bucket")
    timer.begin_step()
    obs.counter("kvstore.allreduce.calls").inc(3)
    obs.counter("kvstore.allreduce.bytes").inc(4096)
    obs.REGISTRY.get("kvstore.allreduce.seconds").observe(0.25)
    obs.counter("kvstore.bucket.count").inc(2)
    obs.REGISTRY.get("kvstore.bucket.fill_ratio").observe(0.5)
    rec = timer.end_step()
    assert rec["allreduce_calls"] == 3
    assert rec["allreduce_bytes"] == 4096
    assert rec["allreduce_seconds"] == pytest.approx(0.25)
    assert rec["bucket_count"] == 2
    assert rec["bucket_fill_sum"] == pytest.approx(0.5)
    # a quiet step omits the section (single-process records stay small)
    timer.begin_step()
    rec2 = timer.end_step()
    assert "allreduce_calls" not in rec2


def _report(path, *flags):
    return subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "telemetry_report.py"),
         *flags, str(path)], capture_output=True, text=True)


def test_report_allreduce_section(tmp_path):
    recs = [{"step_time": 0.1, "allreduce_calls": 4,
             "allreduce_bytes": 1 << 20, "allreduce_seconds": 0.02,
             "bucket_count": 4, "bucket_fill_sum": 3.2,
             "bucket_pack_seconds": 0.001, "bucket_unpack_seconds": 0.002}
            for _ in range(3)]
    # quiet steps (no allreduce fields) must not dilute the p95 to zero
    recs += [{"step_time": 0.05} for _ in range(5)]
    path = tmp_path / "dist.jsonl"
    path.write_text("".join(json.dumps(r) + "\n" for r in recs))
    proc = _report(path)
    assert proc.returncode == 0, proc.stderr
    assert "allreduce" in proc.stdout and "buckets" in proc.stdout
    proc = _report(path, "--json")
    summary = json.loads(proc.stdout)
    assert summary["allreduce_calls"] == 12
    assert summary["bucket_count"] == 12
    assert summary["bucket_fill_mean"] == pytest.approx(0.8)
    assert summary["allreduce_p95_s"] == pytest.approx(0.02)


def test_report_without_allreduce_omits_section(tmp_path):
    path = tmp_path / "plain.jsonl"
    path.write_text('{"step_time": 0.1}\n')
    proc = _report(path)
    assert proc.returncode == 0
    assert "allreduce" not in proc.stdout


def test_report_still_rejects_malformed_with_bucket_fields(tmp_path):
    path = tmp_path / "torn.jsonl"
    path.write_text('{"step_time": 0.1, "allreduce_calls": 2}\n{"allre')
    proc = _report(path)
    assert proc.returncode != 0  # CI gate still bites


# ---------------------------------------------------------------------------
# bandwidth tool sweep plumbing
# ---------------------------------------------------------------------------
def test_bandwidth_synthetic_shapes_total():
    sys.path.insert(0, os.path.join(ROOT, "tools"))
    try:
        from bandwidth import _synthetic_shapes
    finally:
        sys.path.pop(0)
    shapes = _synthetic_shapes(16, 1.0)
    assert len(shapes) == 16
    total = sum(s[0] for s in shapes)
    target = 1.0 * (1 << 20) / 4
    assert 0.9 * target <= total <= 1.1 * target
    assert shapes[0][0] > shapes[-1][0]  # few big, many small


@pytest.mark.slow
def test_bandwidth_sweep_two_processes():
    proc = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "bandwidth.py"),
         "--cpu", "--nproc", "2", "--sweep-bucket-mb", "0,1",
         "--params", "8", "--total-mb", "0.5", "--iters", "2"],
        capture_output=True, text=True, timeout=300,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "per-key" in proc.stdout
    assert "effective" in proc.stdout
