"""Operator correctness via the generic checkers.

Mirrors the reference's test strategy (SURVEY.md §4.1): finite-difference
gradients vs autograd (check_numeric_gradient), forward/backward vs
closed-form (check_symbolic_*), and cross-dtype consistency
(check_consistency) — the backbone of
tests/python/unittest/test_operator.py.
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.test_utils import (check_numeric_gradient,
                                  check_symbolic_forward,
                                  check_symbolic_backward,
                                  check_consistency, assert_almost_equal)


def _rand(*shape):
    return np.random.RandomState(hash(shape) % 2**31).uniform(
        -1, 1, size=shape).astype("float32")


@pytest.mark.parametrize("op,np_fn,lo,hi", [
    ("tanh", np.tanh, -2, 2),
    ("sigmoid", lambda x: 1 / (1 + np.exp(-x)), -2, 2),
    ("exp", np.exp, -1, 1),
    ("log", np.log, 0.2, 3),
    ("sqrt", np.sqrt, 0.2, 3),
    ("square", np.square, -2, 2),
    ("abs", np.abs, 0.2, 2),
])
def test_unary_grad(op, np_fn, lo, hi):
    x = np.random.uniform(lo, hi, size=(3, 4)).astype("float32")
    s = mx.sym.var("x")
    out = getattr(mx.sym, op)(s)
    check_symbolic_forward(out, {"x": x}, [np_fn(x)], rtol=1e-4,
                           atol=1e-5)
    check_numeric_gradient(out, {"x": x}, numeric_eps=1e-3, rtol=5e-2,
                           atol=1e-2)


@pytest.mark.parametrize("op", ["broadcast_add", "broadcast_mul",
                                "broadcast_sub", "broadcast_div"])
def test_binary_broadcast_grad(op):
    a = _rand(3, 1, 4) + 1.5
    b = _rand(1, 2, 4) + 1.5
    sa, sb = mx.sym.var("a"), mx.sym.var("b")
    out = getattr(mx.sym, op)(sa, sb)
    check_numeric_gradient(out, {"a": a, "b": b}, numeric_eps=1e-3,
                           rtol=5e-2, atol=1e-2)


def test_fully_connected_grad():
    check_numeric_gradient(
        mx.sym.FullyConnected(mx.sym.var("data"), mx.sym.var("w"),
                              mx.sym.var("b"), num_hidden=3),
        {"data": _rand(2, 5), "w": _rand(3, 5), "b": _rand(3)},
        numeric_eps=1e-3, rtol=5e-2, atol=1e-2)


def test_convolution_grad():
    check_numeric_gradient(
        mx.sym.Convolution(mx.sym.var("data"), mx.sym.var("w"),
                           mx.sym.var("b"), kernel=(2, 2), num_filter=2),
        {"data": _rand(1, 2, 4, 4), "w": _rand(2, 2, 2, 2),
         "b": _rand(2)},
        numeric_eps=1e-3, rtol=5e-2, atol=2e-2)


def test_pooling_grad():
    for ptype in ("max", "avg"):
        check_numeric_gradient(
            mx.sym.Pooling(mx.sym.var("data"), kernel=(2, 2),
                           stride=(2, 2), pool_type=ptype),
            {"data": _rand(1, 2, 4, 4)},
            numeric_eps=1e-3, rtol=5e-2, atol=2e-2)


def test_softmax_grad():
    check_numeric_gradient(
        mx.sym.softmax(mx.sym.var("x")), {"x": _rand(3, 5)},
        numeric_eps=1e-3, rtol=5e-2, atol=1e-2)


def test_layernorm_grad():
    check_numeric_gradient(
        mx.sym.LayerNorm(mx.sym.var("x"), mx.sym.var("g"),
                         mx.sym.var("b")),
        {"x": _rand(3, 6), "g": _rand(6) + 1.5, "b": _rand(6)},
        numeric_eps=1e-3, rtol=5e-2, atol=2e-2)


def test_dot_backward():
    a = _rand(3, 4)
    b = _rand(4, 5)
    g = np.ones((3, 5), dtype="float32")
    check_symbolic_backward(
        mx.sym.dot(mx.sym.var("a"), mx.sym.var("b")),
        {"a": a, "b": b}, [g],
        {"a": g @ b.T, "b": a.T @ g}, rtol=1e-4, atol=1e-4)


def test_consistency_fp16_fp32():
    sym = mx.sym.Convolution(mx.sym.var("data"), mx.sym.var("w"),
                             no_bias=True, kernel=(3, 3), num_filter=4,
                             pad=(1, 1))
    check_consistency(sym, [
        {"ctx": mx.cpu(), "data": (2, 3, 8, 8), "w": (4, 3, 3, 3),
         "type_dict": {"data": np.float32}},
        {"ctx": mx.cpu(), "data": (2, 3, 8, 8), "w": (4, 3, 3, 3),
         "type_dict": {"data": np.float16}},
    ])


def test_embedding_take_grad():
    w = _rand(7, 4)
    idx = np.array([1, 3, 5], dtype="float32")
    out = mx.sym.Embedding(mx.sym.var("idx"), mx.sym.var("w"),
                           input_dim=7, output_dim=4)
    g = np.ones((3, 4), dtype="float32")
    expected_w = np.zeros_like(w)
    for i in idx.astype(int):
        expected_w[i] += 1
    check_symbolic_backward(out, {"idx": idx, "w": w}, [g],
                            {"w": expected_w}, rtol=1e-4, atol=1e-4,
                            grad_req={"idx": "null", "w": "write"})


def test_batchnorm_consistency_train_predict():
    x = _rand(4, 3, 5, 5) * 2
    gamma = np.ones(3, dtype="float32")
    beta = np.zeros(3, dtype="float32")
    mean = x.mean(axis=(0, 2, 3))
    var = x.var(axis=(0, 2, 3))
    out = mx.sym.BatchNorm(mx.sym.var("x"), mx.sym.var("g"),
                           mx.sym.var("b"), mx.sym.var("mm"),
                           mx.sym.var("mv"), fix_gamma=False, eps=1e-5)
    expected = (x - mean.reshape(1, 3, 1, 1)) / np.sqrt(
        var.reshape(1, 3, 1, 1) + 1e-5)
    ex = out.bind(mx.cpu(), args={"x": mx.nd.array(x),
                                  "g": mx.nd.array(gamma),
                                  "b": mx.nd.array(beta)},
                  aux_states={"mm": mx.nd.zeros(3),
                              "mv": mx.nd.ones(3)}, grad_req="null")
    y = ex.forward(is_train=True)[0]
    assert_almost_equal(y, expected, rtol=1e-3, atol=1e-3)
