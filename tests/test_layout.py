"""NHWC (channels-last) layout + mixed-precision tests.

The reference grew NHWC support for tensor cores
(src/operator/nn/convolution.cc layout param, docs/faq/perf.md fp16
guidance); on TPU channels-last is the MXU-native layout. These tests pin
the NCHW<->NHWC numerical equivalence for every layout-aware op and the
compute_dtype="bfloat16" mixed-precision path of ShardedTrainer.
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd


def _to_nhwc(x):
    return np.transpose(x, (0, 2, 3, 1)).copy()


class TestConvLayout:
    def test_conv_nhwc_matches_nchw(self):
        rng = np.random.RandomState(0)
        x = rng.randn(2, 8, 10, 10).astype("float32")
        w = rng.randn(16, 8, 3, 3).astype("float32")
        b = rng.randn(16).astype("float32")
        y1 = nd.Convolution(nd.array(x), nd.array(w), nd.array(b),
                            kernel=(3, 3), num_filter=16, pad=(1, 1))
        # NHWC weight is (O, kh, kw, I)
        y2 = nd.Convolution(nd.array(_to_nhwc(x)),
                            nd.array(np.transpose(w, (0, 2, 3, 1)).copy()),
                            nd.array(b), kernel=(3, 3), num_filter=16,
                            pad=(1, 1), layout="NHWC")
        np.testing.assert_allclose(_to_nhwc(y1.asnumpy()), y2.asnumpy(),
                                   rtol=1e-5, atol=1e-5)

    def test_conv_nhwc_strided_grouped(self):
        rng = np.random.RandomState(1)
        x = rng.randn(2, 8, 9, 9).astype("float32")
        w = rng.randn(8, 4, 3, 3).astype("float32")
        y1 = nd.Convolution(nd.array(x), nd.array(w), kernel=(3, 3),
                            num_filter=8, stride=(2, 2), num_group=2,
                            no_bias=True)
        y2 = nd.Convolution(nd.array(_to_nhwc(x)),
                            nd.array(np.transpose(w, (0, 2, 3, 1)).copy()),
                            kernel=(3, 3), num_filter=8, stride=(2, 2),
                            num_group=2, no_bias=True, layout="NHWC")
        np.testing.assert_allclose(_to_nhwc(y1.asnumpy()), y2.asnumpy(),
                                   rtol=1e-5, atol=1e-5)


class TestPoolingLayout:
    @pytest.mark.parametrize("pool_type", ["max", "avg"])
    def test_pool_nhwc(self, pool_type):
        rng = np.random.RandomState(2)
        x = rng.randn(2, 4, 8, 8).astype("float32")
        y1 = nd.Pooling(nd.array(x), kernel=(3, 3), stride=(2, 2),
                        pad=(1, 1), pool_type=pool_type)
        y2 = nd.Pooling(nd.array(_to_nhwc(x)), kernel=(3, 3), stride=(2, 2),
                        pad=(1, 1), pool_type=pool_type, layout="NHWC")
        np.testing.assert_allclose(_to_nhwc(y1.asnumpy()), y2.asnumpy(),
                                   rtol=1e-6, atol=1e-6)

    def test_pool_nhwc_ceil_mode(self):
        rng = np.random.RandomState(3)
        x = rng.randn(1, 3, 7, 7).astype("float32")
        y1 = nd.Pooling(nd.array(x), kernel=(3, 3), stride=(2, 2),
                        pooling_convention="full")
        y2 = nd.Pooling(nd.array(_to_nhwc(x)), kernel=(3, 3), stride=(2, 2),
                        pooling_convention="full", layout="NHWC")
        np.testing.assert_allclose(_to_nhwc(y1.asnumpy()), y2.asnumpy(),
                                   rtol=1e-6, atol=1e-6)

    def test_global_pool_nhwc(self):
        rng = np.random.RandomState(4)
        x = rng.randn(2, 5, 6, 6).astype("float32")
        y1 = nd.Pooling(nd.array(x), global_pool=True, pool_type="avg")
        y2 = nd.Pooling(nd.array(_to_nhwc(x)), global_pool=True,
                        pool_type="avg", layout="NHWC")
        np.testing.assert_allclose(_to_nhwc(y1.asnumpy()), y2.asnumpy(),
                                   rtol=1e-6, atol=1e-6)


class TestBatchNormAxis:
    def test_bn_axis_last_matches_axis1(self):
        rng = np.random.RandomState(5)
        x = rng.randn(4, 6, 5, 5).astype("float32")
        gamma = rng.rand(6).astype("float32") + 0.5
        beta = rng.randn(6).astype("float32")
        mm = np.zeros(6, "float32")
        mv = np.ones(6, "float32")
        with mx.autograd.train_mode():
            y1 = nd.BatchNorm(nd.array(x), nd.array(gamma), nd.array(beta),
                              nd.array(mm), nd.array(mv), fix_gamma=False)
            y2 = nd.BatchNorm(nd.array(_to_nhwc(x)), nd.array(gamma),
                              nd.array(beta), nd.array(mm), nd.array(mv),
                              fix_gamma=False, axis=3)
        np.testing.assert_allclose(_to_nhwc(y1.asnumpy()), y2.asnumpy(),
                                   rtol=1e-4, atol=1e-4)

    def test_bn_stats_fp32_under_bf16(self):
        # bf16 input: statistics must be computed in fp32 (single-pass
        # E[x^2]-E[x]^2), output dtype preserved
        rng = np.random.RandomState(6)
        x = (rng.randn(8, 4, 4, 16) * 3 + 5).astype("float32")
        import jax.numpy as jnp
        xb = nd.array(x).astype("bfloat16")
        gamma = nd.ones((16,))
        beta = nd.zeros((16,))
        with mx.autograd.train_mode():
            y = nd.BatchNorm(xb, gamma, beta, nd.zeros((16,)),
                             nd.ones((16,)), fix_gamma=False, axis=3)
        assert y.dtype == np.dtype("bfloat16") or str(y.dtype) == "bfloat16"
        ref = (x - x.mean((0, 1, 2))) / np.sqrt(x.var((0, 1, 2)) + 1e-3)
        np.testing.assert_allclose(y.asnumpy().astype("float32"), ref,
                                   atol=0.15)


class TestResNetNHWC:
    def test_resnet18_nhwc_forward_parity(self):
        from mxnet_tpu.gluon.model_zoo import vision
        rng = np.random.RandomState(7)
        x_nchw = rng.randn(2, 3, 32, 32).astype("float32")

        n1 = vision.resnet18_v1(classes=10)
        n1.initialize()
        y1 = n1(mx.nd.array(x_nchw))

        n2 = vision.resnet18_v1(classes=10, layout="NHWC")
        n2.initialize()

        def strip(n):
            return n.split("_", 1)[1]
        p1 = {strip(p.name): p for p in n1.collect_params().values()}
        p2 = {strip(p.name): p for p in n2.collect_params().values()}
        assert set(p1) == set(p2)
        for name, p in p2.items():
            v = p1[name].data().asnumpy()
            if v.ndim == 4:
                v = np.transpose(v, (0, 2, 3, 1)).copy()
            p.set_data(mx.nd.array(v))
        y2 = n2(mx.nd.array(_to_nhwc(x_nchw)))
        np.testing.assert_allclose(y1.asnumpy(), y2.asnumpy(),
                                   rtol=1e-4, atol=1e-4)


class TestComputeDtype:
    def test_sharded_trainer_bf16_converges(self):
        from mxnet_tpu import gluon
        from mxnet_tpu.gluon.model_zoo import vision
        from mxnet_tpu.parallel import ShardedTrainer
        import jax.numpy as jnp

        net = vision.resnet18_v1(classes=10, layout="NHWC")
        net.initialize()
        net(mx.nd.zeros((1, 32, 32, 3)))
        loss = gluon.loss.SoftmaxCrossEntropyLoss()
        st = ShardedTrainer(net, lambda o, l: loss(o, l), "sgd",
                            {"learning_rate": 0.1},
                            compute_dtype="bfloat16")
        rng = np.random.RandomState(8)
        x = rng.randn(8, 32, 32, 3).astype("float32")
        y = (np.arange(8) % 10).astype("float32")
        l0 = float(st.step(x, y).asnumpy())
        for _ in range(15):
            l = st.step(x, y)
        l1 = float(l.asnumpy())
        assert l1 < l0, (l0, l1)
        # master params stay fp32
        assert all(v.dtype == jnp.float32 for v in st.params.values())

    def test_bf16_matches_fp32_first_step_loss(self):
        # first-step loss of the bf16 path must track the fp32 path
        from mxnet_tpu import gluon
        from mxnet_tpu.parallel import ShardedTrainer
        from mxnet_tpu.gluon import nn as gnn

        def build():
            net = gnn.HybridSequential()
            net.add(gnn.Conv2D(8, 3, padding=1, layout="NHWC"),
                    gnn.BatchNorm(axis=3), gnn.Activation("relu"),
                    gnn.GlobalAvgPool2D(layout="NHWC"), gnn.Dense(5))
            return net

        rng = np.random.RandomState(9)
        x = rng.randn(8, 8, 8, 3).astype("float32")
        y = (np.arange(8) % 5).astype("float32")
        loss = gluon.loss.SoftmaxCrossEntropyLoss()
        losses = {}
        for cd in (None, "bfloat16"):
            np.random.seed(0)
            net = build()
            net.initialize()
            net(mx.nd.zeros((1, 8, 8, 3)))
            st = ShardedTrainer(net, lambda o, l: loss(o, l), "sgd",
                                {"learning_rate": 0.0}, compute_dtype=cd)
            losses[cd] = float(st.step(x, y).asnumpy())
        assert abs(losses[None] - losses["bfloat16"]) < 0.05, losses


class TestMobileNetNHWC:
    def test_mobilenet_v1_nhwc_parity(self):
        from mxnet_tpu.gluon.model_zoo import vision
        rng = np.random.RandomState(10)
        x = rng.randn(2, 3, 32, 32).astype("float32")
        n1 = vision.mobilenet0_25(classes=10)
        n1.initialize()
        y1 = n1(mx.nd.array(x))
        n2 = vision.mobilenet0_25(classes=10, layout="NHWC")
        n2.initialize()

        def strip(n):
            return n.split("_", 1)[1]
        p1 = {strip(p.name): p for p in n1.collect_params().values()}
        p2 = {strip(p.name): p for p in n2.collect_params().values()}
        assert set(p1) == set(p2)
        for name, p in p2.items():
            v = p1[name].data().asnumpy()
            if v.ndim == 4:
                v = np.transpose(v, (0, 2, 3, 1)).copy()
            p.set_data(mx.nd.array(v))
        y2 = n2(mx.nd.array(_to_nhwc(x)))
        np.testing.assert_allclose(y1.asnumpy(), y2.asnumpy(),
                                   rtol=2e-4, atol=2e-4)

    def test_mobilenet_v2_nhwc_runs(self):
        from mxnet_tpu.gluon.model_zoo import vision
        net = vision.mobilenet_v2_0_25(classes=10, layout="NHWC")
        net.initialize()
        y = net(mx.nd.zeros((2, 32, 32, 3)))
        assert y.shape == (2, 10)


class TestInceptionNHWC:
    def test_inception_nhwc_parity(self):
        from mxnet_tpu.gluon.model_zoo import vision
        rng = np.random.RandomState(11)
        x = rng.randn(1, 3, 299, 299).astype("float32")
        n1 = vision.inception_v3(classes=10)
        n1.initialize()
        y1 = n1(mx.nd.array(x))
        n2 = vision.inception_v3(classes=10, layout="NHWC")
        n2.initialize()
        n2(mx.nd.zeros((1, 299, 299, 3)))  # materialize deferred Dense

        def strip(n):
            return n.split("_", 1)[1]
        p1 = {strip(p.name): p for p in n1.collect_params().values()}
        p2 = {strip(p.name): p for p in n2.collect_params().values()}
        assert set(p1) == set(p2)
        for name, p in p2.items():
            v = p1[name].data().asnumpy()
            if v.ndim == 4:
                v = np.transpose(v, (0, 2, 3, 1)).copy()
            p.set_data(mx.nd.array(v))
        y2 = n2(mx.nd.array(_to_nhwc(x)))
        np.testing.assert_allclose(y1.asnumpy(), y2.asnumpy(),
                                   rtol=3e-4, atol=3e-4)


class TestOtherModelsNHWC:
    def test_densenet_nhwc_parity(self):
        from mxnet_tpu.gluon.model_zoo import vision
        rng = np.random.RandomState(12)
        x = rng.randn(1, 3, 224, 224).astype("float32")
        n1 = vision.densenet121(classes=10)
        n1.initialize()
        y1 = n1(mx.nd.array(x))
        n2 = vision.densenet121(classes=10, layout="NHWC")
        n2.initialize()
        n2(mx.nd.zeros((1, 224, 224, 3)))

        def strip(n):
            return n.split("_", 1)[1]
        p1 = {strip(p.name): p for p in n1.collect_params().values()}
        p2 = {strip(p.name): p for p in n2.collect_params().values()}
        assert set(p1) == set(p2)
        for name, p in p2.items():
            v = p1[name].data().asnumpy()
            if v.ndim == 4:
                v = np.transpose(v, (0, 2, 3, 1)).copy()
            p.set_data(mx.nd.array(v))
        y2 = n2(mx.nd.array(_to_nhwc(x)))
        np.testing.assert_allclose(y1.asnumpy(), y2.asnumpy(),
                                   rtol=3e-4, atol=3e-4)

    def test_squeezenet_vgg_alexnet_nhwc_run(self):
        from mxnet_tpu.gluon.model_zoo import vision
        for ctor, size in [(vision.squeezenet1_1, 64),
                           (vision.vgg11, 64),
                           (vision.alexnet, 224)]:
            net = ctor(classes=7, layout="NHWC")
            net.initialize()
            y = net(mx.nd.zeros((2, size, size, 3)))
            assert y.shape == (2, 7), ctor.__name__
