"""Unit tests for bench.py's ladder construction, compile-cache guard,
and child-reaping fence — the pure-Python pieces the CPU smoke
exercises only end-to-end. No jax import; the two fence tests spawn
short-lived -S subprocesses and sync on a readiness line, so the whole
file stays in low single-digit seconds."""
import importlib.util
import os
import signal
import subprocess
import sys

import pytest


@pytest.fixture()
def bench(monkeypatch, tmp_path):
    # a fresh module per test so env-derived module constants reset
    monkeypatch.setenv("MXTPU_XLA_CACHE", str(tmp_path / "cache"))
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "bench_under_test", os.path.join(root, "bench.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_default_ladder_order_and_shape(bench, monkeypatch):
    monkeypatch.delenv("MXTPU_BENCH_DEADLINES", raising=False)
    monkeypatch.delenv("MXTPU_BENCH_SCORE", raising=False)
    rungs = bench._rungs()
    assert [r[0] for r in rungs] == ["secure", "score", "mid", "full"]
    # secure and score measure the identical small train config; the
    # score rung exists only to isolate the inference compile
    assert rungs[0][1:3] == rungs[1][1:3]
    # escalation is monotone in work: steps then unroll
    assert rungs[2][1] >= rungs[0][1] and rungs[3][2] >= rungs[2][2]


def test_legacy_three_value_deadlines_keep_meaning(bench, monkeypatch):
    monkeypatch.setenv("MXTPU_BENCH_DEADLINES", "111,222,333")
    by_name = {r[0]: r[5] for r in bench._rungs()}
    # pre-round-5 spelling was (secure, mid, full): mid/full must NOT
    # silently inherit looser fences; score borrows secure's
    assert by_name == {"secure": 111.0, "score": 111.0,
                       "mid": 222.0, "full": 333.0}


def test_single_deadline_bounds_every_rung(bench, monkeypatch):
    monkeypatch.setenv("MXTPU_BENCH_DEADLINES", "77")
    assert [r[5] for r in bench._rungs()] == [77.0] * 4


def test_score_rung_dropped_when_scoring_masked(bench, monkeypatch):
    monkeypatch.setenv("MXTPU_BENCH_SCORE", "0")
    monkeypatch.setenv("MXTPU_BENCH_DEADLINES", "1,2,3,4")
    rungs = bench._rungs()
    assert [r[0] for r in rungs] == ["secure", "mid", "full"]
    # deadlines are zipped before the drop so the others keep slots
    assert [r[5] for r in rungs] == [1.0, 3.0, 4.0]


def _spawn_wedged(setup, payload):
    """Start a -S python child that runs `setup` (e.g. signal handler
    installs), prints `payload` to stdout, signals readiness on STDERR,
    then sleeps forever. Readiness rides stderr so the parent's
    buffered readline can't swallow the stdout payload fence_child's
    communicate must see; blocking on it replaces any fixed sleep."""
    emit = ("\nprint(%r, flush=True)\n"
            "print('ready', file=sys.stderr, flush=True)\n"
            "time.sleep(600)\n") % (payload,)
    code = "import sys, time\n" + setup + emit  # setup never %-parsed
    p = subprocess.Popen([sys.executable, "-S", "-c", code],
                         stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                         text=True)
    assert p.stderr.readline().strip() == "ready"
    return p


def test_fence_child_keeps_pre_wedge_stdout(bench):
    # child emits its result, then wedges ignoring SIGINT/SIGTERM —
    # the fence must escalate to SIGKILL AND return what was printed
    p = _spawn_wedged(
        "import signal\n"
        "signal.signal(signal.SIGINT, signal.SIG_IGN)\n"
        "signal.signal(signal.SIGTERM, signal.SIG_IGN)",
        '{"value": 42}')
    try:
        out, status = bench.fence_child(
            p, graces=((signal.SIGINT, 1), (signal.SIGTERM, 1),
                       (signal.SIGKILL, 5)))
        assert status == "SIGKILL"
        assert out is not None and '"value": 42' in out
    finally:
        p.kill()
        p.wait()


def test_fence_child_clean_sigint_unwind(bench):
    # a child that honors SIGINT exits within the first grace window
    p = _spawn_wedged("", "partial")
    try:
        out, status = bench.fence_child(
            p, graces=((signal.SIGINT, 10), (signal.SIGTERM, 5),
                       (signal.SIGKILL, 5)))
        assert status == "SIGINT"
        assert out is not None and "partial" in out
    finally:
        p.kill()
        p.wait()


def _guard_cache_env(monkeypatch):
    """_enable_compile_cache writes JAX_COMPILATION_CACHE_DIR straight
    into os.environ; register the var with monkeypatch first so the
    mutation is rolled back after the test instead of leaking into
    later jax-importing tests."""
    monkeypatch.setenv("JAX_COMPILATION_CACHE_DIR", "sentinel")
    monkeypatch.delenv("JAX_COMPILATION_CACHE_DIR")


def test_compile_cache_env_respects_explicit_dir(bench, monkeypatch,
                                                 tmp_path):
    target = tmp_path / "explicit"
    monkeypatch.setenv("MXTPU_XLA_CACHE", str(target))
    _guard_cache_env(monkeypatch)
    bench._enable_compile_cache()
    assert os.environ["JAX_COMPILATION_CACHE_DIR"] == str(target)


def test_compile_cache_disabled_by_zero(bench, monkeypatch):
    monkeypatch.setenv("MXTPU_XLA_CACHE", "0")
    _guard_cache_env(monkeypatch)
    bench._enable_compile_cache()
    assert "JAX_COMPILATION_CACHE_DIR" not in os.environ


def test_compile_cache_default_dir_created_private(bench, monkeypatch):
    # exercise the ownership guard on the real uid-derived default
    monkeypatch.delenv("MXTPU_XLA_CACHE", raising=False)
    _guard_cache_env(monkeypatch)
    bench._enable_compile_cache()
    d = os.environ.get("JAX_COMPILATION_CACHE_DIR")
    if d is not None:  # guard may refuse a pre-existing foreign dir
        assert not os.path.islink(d)
        st = os.lstat(d)
        assert st.st_uid == os.getuid()
        assert not (st.st_mode & 0o022)


def _guard_fallback_env(monkeypatch):
    """_fallback_to_cpu mutates os.environ directly; pre-register every
    var it touches so monkeypatch rolls the mutations back."""
    for var in ("JAX_PLATFORMS", "MXTPU_BENCH_PLATFORM",
                "MXTPU_BENCH_BATCH", "MXTPU_BENCH_IMG",
                "MXTPU_BENCH_STEPS", "MXTPU_BENCH_UNROLL",
                "MXTPU_BENCH_SCORE", "MXTPU_BENCH_EXTRAS"):
        monkeypatch.setenv(var, "sentinel")
        monkeypatch.delenv(var)


def test_cpu_fallback_pins_platform_and_shrinks(bench, monkeypatch):
    _guard_fallback_env(monkeypatch)
    monkeypatch.setenv("JAX_PLATFORMS", "tpu")  # the wedged pin
    monkeypatch.setattr(bench, "_apply_platform_override",
                        lambda: None)  # keep jax out of this test
    bench._fallback_to_cpu()
    assert os.environ["MXTPU_BENCH_PLATFORM"] == "cpu"
    assert os.environ["JAX_PLATFORMS"] == ""
    # workload shrank to the CI-smoke sizes (CPU-feasible, measured)
    assert (bench.BATCH, bench.IMG, bench.STEPS, bench.UNROLL) \
        == (8, 32, 2, 1)
    assert os.environ["MXTPU_BENCH_SCORE"] == "0"
    assert os.environ["MXTPU_BENCH_EXTRAS"] == "0"


def test_cpu_fallback_respects_explicit_sizes(bench, monkeypatch):
    _guard_fallback_env(monkeypatch)
    monkeypatch.setenv("MXTPU_BENCH_BATCH", "4")
    monkeypatch.setenv("MXTPU_BENCH_STEPS", "2")
    monkeypatch.setattr(bench, "_apply_platform_override",
                        lambda: None)
    bench._fallback_to_cpu()
    assert (bench.BATCH, bench.STEPS) == (4, 2)
