"""Pallas kernel tests (interpret mode on the CPU mesh; the same kernels
compile natively on TPU)."""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

from mxnet_tpu.ops.pallas_kernels import (flash_attention,
                                          pallas_layer_norm,
                                          _attn_reference)
import mxnet_tpu as mx


@pytest.mark.parametrize("causal", [False, True])
def test_flash_attention_matches_reference(causal):
    r = np.random.RandomState(0)
    B, H, T, D = 2, 2, 256, 64
    q, k, v = (jnp.asarray(r.randn(B, H, T, D), jnp.float32)
               for _ in range(3))
    out = flash_attention(q, k, v, causal)
    ref = _attn_reference(q, k, v, causal)
    assert float(jnp.abs(out - ref).max()) < 2e-4


def test_flash_attention_grad():
    r = np.random.RandomState(1)
    B, H, T, D = 1, 2, 128, 32
    q, k, v = (jnp.asarray(r.randn(B, H, T, D), jnp.float32)
               for _ in range(3))
    g1 = jax.grad(lambda a, b, c: flash_attention(a, b, c, True).sum(),
                  argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(lambda a, b, c: _attn_reference(a, b, c, True).sum(),
                  argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        assert float(jnp.abs(a - b).max()) < 2e-3


def test_pallas_layer_norm():
    r = np.random.RandomState(2)
    x = jnp.asarray(r.randn(37, 100), jnp.float32)
    g = jnp.asarray(r.randn(100), jnp.float32)
    b = jnp.asarray(r.randn(100), jnp.float32)
    out = pallas_layer_norm(x, g, b)
    mean = x.mean(-1, keepdims=True)
    var = x.var(-1, keepdims=True)
    ref = (x - mean) / jnp.sqrt(var + 1e-5) * g + b
    assert float(jnp.abs(out - ref).max()) < 1e-4


def test_flash_attention_nd_op():
    r = np.random.RandomState(3)
    q = mx.nd.array(r.randn(1, 2, 64, 16).astype("float32"))
    k = mx.nd.array(r.randn(1, 2, 64, 16).astype("float32"))
    v = mx.nd.array(r.randn(1, 2, 64, 16).astype("float32"))
    out = mx.nd.contrib.flash_attention(q, k, v, causal=True,
                                        block_q=64, block_k=64)
    ref = _attn_reference(q._data, k._data, v._data, True)
    assert float(jnp.abs(out._data - ref).max()) < 2e-4


def test_fused_sgd_momentum_matches_reference():
    """Pallas fused momentum-SGD vs the plain jnp update — both the
    lane-aligned zero-copy path and the padded general path."""
    import numpy as np
    import jax.numpy as jnp
    from mxnet_tpu.ops.pallas_kernels import fused_sgd_momentum

    rng = np.random.RandomState(0)
    for shape in [(512, 128), (3, 3, 7, 11), (1000,)]:
        w = rng.randn(*shape).astype("float32")
        g = rng.randn(*shape).astype("float32")
        m = rng.randn(*shape).astype("float32")
        lr, mom, wd, rs = 0.05, 0.9, 1e-4, 0.5
        ow, om = fused_sgd_momentum(jnp.asarray(w), jnp.asarray(g),
                                    jnp.asarray(m), lr, mom, wd, rs)
        m_ref = mom * m + rs * g + wd * w
        w_ref = w - lr * m_ref
        assert np.allclose(np.asarray(om), m_ref, atol=1e-5), shape
        assert np.allclose(np.asarray(ow), w_ref, atol=1e-5), shape


def test_fused_sgd_momentum_mixed_dtype():
    """bf16 weights + fp32 momentum (the mixed-precision pairing):
    accumulate in fp32, outputs keep their input dtypes."""
    import numpy as np
    import jax.numpy as jnp
    from mxnet_tpu.ops.pallas_kernels import fused_sgd_momentum

    rng = np.random.RandomState(1)
    w = rng.randn(64, 128).astype("float32")
    g = rng.randn(64, 128).astype("float32")
    m = rng.randn(64, 128).astype("float32")
    ow, om = fused_sgd_momentum(jnp.asarray(w, jnp.bfloat16),
                                jnp.asarray(g, jnp.bfloat16),
                                jnp.asarray(m), 0.1, 0.9)
    assert ow.dtype == jnp.bfloat16 and om.dtype == jnp.float32
    m_ref = 0.9 * m + np.asarray(jnp.asarray(g, jnp.bfloat16), "float32")
    assert np.allclose(np.asarray(om), m_ref, atol=2e-2)


def test_conv1x1_bn_stats_fusion():
    """Fused matmul+BN-stat epilogue matches the two-pass oracle,
    including the padded-rows path."""
    from mxnet_tpu.ops.pallas_kernels import conv1x1_bn_stats
    rng = np.random.RandomState(0)
    for M, Cin, Cout in [(512, 16, 32), (300, 8, 8)]:   # 300: pad path
        x = jnp.asarray(rng.randn(M, Cin), jnp.float32)
        w = jnp.asarray(rng.randn(Cin, Cout) * 0.2, jnp.float32)
        y, mean, var = conv1x1_bn_stats(x, w, block_rows=128)
        ref = np.asarray(x) @ np.asarray(w)
        assert np.allclose(np.asarray(y), ref, atol=1e-4)
        assert np.allclose(np.asarray(mean), ref.mean(0), atol=1e-4)
        assert np.allclose(np.asarray(var), ref.var(0), atol=1e-3)
