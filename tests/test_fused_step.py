"""One compiled program per training step + ZeRO-1
(parallel/fused_step.py; docs/performance.md "Fused train step &
ZeRO-1").

The contract under test:

1. bit parity: the fused one-program step behind gluon.Trainer /
   Module update produces byte-identical weights AND optimizer state
   vs the staged bucketed path (exchange then update) — SGD, momentum,
   Adam, fp16-under-fp32-master multi-precision — with the
   MXTPU_FUSED_STEP=0 and MXTPU_ZERO1=0 escape hatches exercised both
   ways;
2. dispatch count: the fused path issues exactly ONE device program
   per step (train.step.dispatches metric + program-cache census),
   the staged path O(buckets)+O(groups);
3. numerics-guard composition: chaos kind=nan at grad.post inside the
   fused step skips in-graph with weights/opt state preserved
   bit-identically, and the verdict reaches the watchdog/telemetry
   exactly once;
4. ZeRO-1 checkpoint round-trip: dp-sharded optimizer state saves
   through TrainerCheckpoint two-phase commit and restores
   bit-identically into sharded AND replicated topologies of a
   different replica count;
5. plan signatures: bucket-layout changes re-fingerprint AOT programs.

Multi-process (gloo, 4 ranks) ZeRO-1 == replicated == staged parity is
asserted in tests/dist_kvstore_worker.py (ZERO1_PARITY_OK markers).
"""
import json
import os
import pickle
import subprocess
import sys

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
from mxnet_tpu import optimizer as opt
from mxnet_tpu.observability import registry as obs
from mxnet_tpu.parallel import fused_step as fs
from mxnet_tpu.resilience import chaos

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def step_env(monkeypatch):
    def set_fused(on, zero1=False):
        monkeypatch.setenv("MXTPU_FUSED_STEP", "1" if on else "0")
        monkeypatch.setenv("MXTPU_ZERO1", "1" if zero1 else "0")
    yield set_fused


def _train_gluon(optname, optkw, steps=4, dtype="float32", seed=0):
    """A tiny gluon loop: returns (param arrays, pickled updater
    states) after `steps` autograd+Trainer.step iterations."""
    mx.random.seed(seed)
    net = gluon.nn.Sequential()
    net.add(gluon.nn.Dense(8, activation="relu"), gluon.nn.Dense(3))
    net.initialize()
    x0 = mx.nd.array(np.random.RandomState(1).randn(4, 5).astype("f"))
    net(x0)
    if dtype != "float32":
        net.cast(dtype)
        net(mx.nd.array(np.random.RandomState(1).randn(4, 5)
                        .astype(dtype)))
    tr = gluon.Trainer(net.collect_params(), optname, dict(optkw))
    loss_fn = gluon.loss.L2Loss()
    for s in range(steps):
        x = mx.nd.array(np.random.RandomState(10 + s).randn(4, 5)
                        .astype(dtype))
        y = mx.nd.array(np.random.RandomState(20 + s).randn(4, 3)
                        .astype(dtype))
        with autograd.record():
            loss = loss_fn(net(x), y)
        loss.backward()
        tr.step(4)
    params = [p.data().asnumpy() for p in net.collect_params().values()]
    states = pickle.loads(tr._updaters[0].get_states())
    return params, states, tr


def _state_bytes(states):
    out = []
    for k in sorted(states):
        st = states[k]
        stack = [st]
        while stack:
            s = stack.pop()
            if s is None:
                continue
            if isinstance(s, (list, tuple)):
                stack.extend(s)
            else:
                out.append(np.asarray(s.asnumpy()).tobytes())
    return out


@pytest.mark.parametrize("name,kw,dtype", [
    ("sgd", dict(learning_rate=0.1), "float32"),
    ("sgd", dict(learning_rate=0.1, momentum=0.9, wd=0.01), "float32"),
    ("adam", dict(learning_rate=0.01, wd=0.001), "float32"),
    ("sgd", dict(learning_rate=0.1, momentum=0.9,
                 multi_precision=True), "float16"),
    ("adam", dict(learning_rate=0.01,
                  multi_precision=True), "float16"),
])
def test_fused_step_bit_parity(name, kw, dtype, step_env):
    step_env(True)
    a_p, a_s, _ = _train_gluon(name, kw, dtype=dtype)
    step_env(False)
    b_p, b_s, _ = _train_gluon(name, kw, dtype=dtype)
    for a, b in zip(a_p, b_p):
        assert a.dtype == b.dtype
        assert a.tobytes() == b.tobytes()
    assert _state_bytes(a_s) == _state_bytes(b_s)


def test_fused_step_one_dispatch_per_step(step_env):
    disp = obs.REGISTRY.counter("train.step.dispatches")
    step_env(True)
    d0 = disp.total()
    _, _, tr = _train_gluon("sgd", dict(learning_rate=0.1,
                                        momentum=0.9), steps=5)
    assert disp.total() - d0 == 5          # exactly ONE program/step
    # jit-cache census: steady-state training holds exactly one
    # compiled step program (the PR-6 two-program-assert analog)
    owner = tr._updaters[0]._fused_step_owner
    assert owner is not None and owner.program_count() == 1
    # staged path: O(groups) per step (two lanes here: weight wd_mult
    # lane + bias lane collapse into one fp32 bucket per cohort)
    step_env(False)
    d0 = disp.total()
    _train_gluon("sgd", dict(learning_rate=0.1, momentum=0.9), steps=5)
    staged = disp.total() - d0
    assert staged >= 5                     # at least one per step


def test_fused_step_telemetry_record_and_phase(step_env, tmp_path,
                                               monkeypatch):
    tel = tmp_path / "t.jsonl"
    monkeypatch.setenv("MXTPU_TELEMETRY", str(tel))
    step_env(True)
    _train_gluon("sgd", dict(learning_rate=0.1), steps=3)
    from mxnet_tpu.observability.telemetry import close_stream
    close_stream()
    recs = [json.loads(line) for line in tel.read_text().splitlines()]
    steps = [r for r in recs if r.get("source") == "gluon.trainer"]
    assert steps
    # one "step" phase, no host allreduce/optimizer phases, and the
    # dispatch budget field reads 1 (acceptance: the host-side Python
    # between phases is gone from the trace)
    for r in steps[1:]:
        assert r.get("step_dispatches") == 1
        assert "step_time" in r
        assert "allreduce_time" not in r and "optimizer_time" not in r


def test_perf_gate_dispatch_budget(step_env, tmp_path, monkeypatch):
    tel = tmp_path / "t.jsonl"
    monkeypatch.setenv("MXTPU_TELEMETRY", str(tel))
    step_env(True)
    _train_gluon("adam", dict(learning_rate=0.01), steps=3)
    from mxnet_tpu.observability.telemetry import close_stream
    close_stream()
    gate = os.path.join(ROOT, "tools", "perf_gate.py")
    r = subprocess.run([sys.executable, gate, str(tel),
                        "--max-dispatches-per-step", "1"],
                       capture_output=True, text=True)
    assert r.returncode == 0, r.stdout + r.stderr
    # tighter than 1 program/step is unachievable: breach
    r = subprocess.run([sys.executable, gate, str(tel),
                        "--max-dispatches-per-step", "0.5"],
                       capture_output=True, text=True)
    assert r.returncode == 1
    assert "dispatches_per_step" in r.stdout
    # a stream without the metric must breach, not pass silently
    legacy = tmp_path / "legacy.jsonl"
    legacy.write_text(json.dumps(
        {"ts": 0, "source": "train", "step": 0, "step_time": 0.1}) +
        "\n")
    r = subprocess.run([sys.executable, gate, str(legacy),
                        "--max-dispatches-per-step", "1"],
                       capture_output=True, text=True)
    assert r.returncode == 1


def test_guard_composition_chaos_nan(step_env):
    """kind=nan at grad.post INSIDE the fused step: the lax.cond skip
    preserves weights + opt state bit-identically and the verdict
    reaches the watchdog/telemetry exactly once."""
    step_env(True)
    mx.random.seed(0)
    net = gluon.nn.Dense(3)
    net.initialize()
    x = mx.nd.array(np.random.RandomState(1).randn(4, 5).astype("f"))
    y = mx.nd.array(np.random.RandomState(2).randn(4, 3).astype("f"))
    net(x)
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.05, "momentum": 0.9})
    loss_fn = gluon.loss.L2Loss()

    def one_step():
        with autograd.record():
            loss = loss_fn(net(x), y)
        loss.backward()
        tr.step(4)

    one_step()                      # clean step: program + state exist
    pre_w = [p.data().asnumpy().copy()
             for p in net.collect_params().values()]
    pre_s = tr._updaters[0].get_states()
    anom0 = obs.REGISTRY.get("numerics.anomalies").total()
    skip0 = obs.REGISTRY.get("numerics.skipped_steps").total()
    bad0 = tr.numerics.watchdog.bad_streak
    chaos.configure("grad.post:kind=nan,n=1", seed=7)
    try:
        one_step()
    finally:
        chaos.reset()
    for a, b in zip(pre_w, [p.data().asnumpy()
                            for p in net.collect_params().values()]):
        assert a.tobytes() == b.tobytes()
    assert pre_s == tr._updaters[0].get_states()
    rep = tr.numerics.last_report
    assert rep["skipped_steps"] == 1 and rep["anomalies"] == 1
    # exactly once: metric deltas of 1, watchdog streak advanced by 1
    assert obs.REGISTRY.get("numerics.anomalies").total() - anom0 == 1
    assert (obs.REGISTRY.get("numerics.skipped_steps").total()
            - skip0 == 1)
    assert tr.numerics.watchdog.bad_streak == bad0 + 1
    one_step()                      # clean step: streak resets
    assert tr.numerics.last_report["anomalies"] == 0
    assert tr.numerics.watchdog.bad_streak == 0


def test_escape_hatch_mid_run(step_env):
    """Toggling MXTPU_FUSED_STEP mid-run keeps training exact: the
    fused and staged paths share updater state."""
    step_env(True)
    mx.random.seed(3)
    net = gluon.nn.Dense(4)
    net.initialize()
    x = mx.nd.array(np.random.RandomState(5).randn(4, 6).astype("f"))
    y = mx.nd.array(np.random.RandomState(6).randn(4, 4).astype("f"))
    net(x)
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.05, "momentum": 0.9})
    loss_fn = gluon.loss.L2Loss()

    def steps(n):
        for _ in range(n):
            with autograd.record():
                loss = loss_fn(net(x), y)
            loss.backward()
            tr.step(4)

    steps(2)
    step_env(False)
    steps(2)
    step_env(True)
    steps(2)
    mixed = [p.data().asnumpy() for p in net.collect_params().values()]
    step_env(False)
    b_p, _, _ = _train_gluon_fixed_dense(net_seed=3, steps=6)
    for a, b in zip(mixed, b_p):
        assert a.tobytes() == b.tobytes()


def _train_gluon_fixed_dense(net_seed, steps):
    mx.random.seed(net_seed)
    net = gluon.nn.Dense(4)
    net.initialize()
    x = mx.nd.array(np.random.RandomState(5).randn(4, 6).astype("f"))
    y = mx.nd.array(np.random.RandomState(6).randn(4, 4).astype("f"))
    net(x)
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.05, "momentum": 0.9})
    loss_fn = gluon.loss.L2Loss()
    for _ in range(steps):
        with autograd.record():
            loss = loss_fn(net(x), y)
        loss.backward()
        tr.step(4)
    return ([p.data().asnumpy() for p in net.collect_params().values()],
            None, tr)


def test_module_fit_fused_parity(step_env):
    def fit(fused):
        step_env(fused)
        mx.random.seed(0)
        np.random.seed(0)
        data = mx.sym.var("data")
        s = mx.sym.FullyConnected(data, num_hidden=8, name="fc1")
        s = mx.sym.Activation(s, act_type="relu")
        s = mx.sym.FullyConnected(s, num_hidden=4, name="fc2")
        s = mx.sym.SoftmaxOutput(s, name="softmax")
        X = np.random.RandomState(3).randn(16, 10).astype("f")
        Y = np.random.RandomState(4).randint(0, 4, (16,)).astype("f")
        it = mx.io.NDArrayIter(X, Y, batch_size=8,
                               label_name="softmax_label")
        mod = mx.mod.Module(s, data_names=("data",),
                            label_names=("softmax_label",))
        mod.fit(it, num_epoch=2, optimizer="sgd",
                optimizer_params={"learning_rate": 0.1,
                                  "momentum": 0.9})
        args, _ = mod.get_params()
        return {k: v.asnumpy() for k, v in args.items()}

    a = fit(True)
    b = fit(False)
    for k in sorted(a):
        assert a[k].tobytes() == b[k].tobytes(), k


def test_staged_oracle_unused_paths_intact(step_env):
    """allreduce_grads()/update() keep the staged halves regardless of
    the fused-step default (facade contract)."""
    step_env(True)
    mx.random.seed(1)
    net = gluon.nn.Dense(2)
    net.initialize()
    x = mx.nd.array(np.random.RandomState(0).randn(2, 3).astype("f"))
    net(x)
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.1})
    with autograd.record():
        loss = net(x).sum()
    loss.backward()
    tr.allreduce_grads()
    tr.update(2)
    # params moved; no fused program was built for these facades
    assert tr._updaters[0]._fused_step_owner is None


# -- ZeRO-1 ---------------------------------------------------------------

def test_zero1_env_defaults_sharded_trainer(step_env):
    from mxnet_tpu.parallel import make_mesh, ShardedTrainer
    step_env(True, zero1=True)
    mx.random.seed(0)
    net = gluon.nn.Dense(8)
    net.initialize()
    net(mx.nd.array(np.zeros((8, 4), "f")))
    st = ShardedTrainer(net, lambda o, l: gluon.loss.L2Loss()(o, l),
                        "sgd", {"learning_rate": 0.1, "momentum": 0.9},
                        mesh=make_mesh({"dp": 8}))
    assert st._shard_opt
    g = obs.REGISTRY.get("zero1.shard_params")
    assert g is not None
    step_env(True, zero1=False)
    st2 = ShardedTrainer(net, lambda o, l: gluon.loss.L2Loss()(o, l),
                         "sgd", {"learning_rate": 0.1,
                                 "momentum": 0.9},
                         mesh=make_mesh({"dp": 8}))
    assert not st2._shard_opt
    # explicit bool wins over env
    step_env(True, zero1=True)
    st3 = ShardedTrainer(net, lambda o, l: gluon.loss.L2Loss()(o, l),
                         "sgd", {"learning_rate": 0.1,
                                 "momentum": 0.9},
                         mesh=make_mesh({"dp": 8}),
                         shard_optimizer_state=False)
    assert not st3._shard_opt


def _make_sharded_trainer(n_dp, zero1, seed=0, prefix="z1ckpt_"):
    from mxnet_tpu.parallel import make_mesh, ShardedTrainer
    import jax
    mx.random.seed(seed)
    # fixed prefix: every instance names its params identically, so
    # checkpoints restore across instances and runs compare by key
    net = gluon.nn.Dense(8, prefix=prefix)   # (8, 8): shardable at 8 & 4
    net.initialize()
    net(mx.nd.array(np.zeros((8, 8), "f")))
    mesh = make_mesh({"dp": n_dp}, jax.devices()[:n_dp])
    st = ShardedTrainer(net, lambda o, l: gluon.loss.L2Loss()(o, l),
                        "sgd", {"learning_rate": 0.1, "momentum": 0.9},
                        mesh=mesh, shard_optimizer_state=zero1)
    return st


def test_zero1_checkpoint_roundtrip_elastic(tmp_path):
    """Sharded optimizer state saves through TrainerCheckpoint's
    two-phase commit and restores bit-identically into BOTH a sharded
    trainer of a different replica count (elastic 8 -> 4) and a
    replicated one."""
    from mxnet_tpu.parallel import checkpoint as ckpt
    import jax
    st = _make_sharded_trainer(8, zero1=True)
    x = mx.nd.array(np.random.RandomState(0).randn(8, 8).astype("f"))
    y = mx.nd.array(np.random.RandomState(1).randn(8, 8).astype("f"))
    for _ in range(3):
        st.step(x, y)
    # momentum state really is dp-sharded (the ZeRO-1 memory claim)
    from jax.sharding import PartitionSpec
    sharded = [v for v in st._opt_state.values()
               if v.sharding.spec == PartitionSpec("dp")]
    assert sharded, "no opt-state leaf was dp-sharded"
    want_state = {k: np.asarray(jax.device_get(v)).tobytes()
                  for k, v in st._opt_state.items()}
    want_params = {k: np.asarray(jax.device_get(v)).tobytes()
                   for k, v in st._params.items()}
    mngr = ckpt.TrainerCheckpoint(tmp_path, async_save=False)
    mngr.save(st._step_count, st, wait=True)
    # two-phase commit sealed the step
    assert mngr.commit_manifest(st._step_count) is not None

    for n_dp, zero1 in ((4, True), (8, False)):
        tgt = _make_sharded_trainer(n_dp, zero1=zero1)
        step = mngr.restore_latest(tgt)
        assert step == st._step_count
        got_state = {k: np.asarray(jax.device_get(v)).tobytes()
                     for k, v in tgt._opt_state.items()}
        got_params = {k: np.asarray(jax.device_get(v)).tobytes()
                      for k, v in tgt._params.items()}
        assert got_state == want_state, (n_dp, zero1)
        assert got_params == want_params, (n_dp, zero1)
    mngr.close()


def test_zero1_matches_replicated_sharded_trainer():
    """MXTPU_ZERO1 sharding changes memory layout, never numerics."""
    a = _make_sharded_trainer(8, zero1=True, seed=5)
    b = _make_sharded_trainer(8, zero1=False, seed=5)
    x = mx.nd.array(np.random.RandomState(2).randn(8, 8).astype("f"))
    y = mx.nd.array(np.random.RandomState(3).randn(8, 8).astype("f"))
    import jax
    for _ in range(3):
        a.step(x, y)
        b.step(x, y)
    for k in a._params:
        assert np.asarray(jax.device_get(a._params[k])).tobytes() == \
            np.asarray(jax.device_get(b._params[k])).tobytes(), k


# -- plan signatures ------------------------------------------------------

def test_plan_signature_stability_and_layout_sensitivity():
    from mxnet_tpu.parallel.bucketing import GradBucketer
    bk = GradBucketer(target_bytes=1 << 62)
    items = (("a", (4, 4), "float32", 0, None),
             ("b", (7,), "float32", -1, None))
    sig1 = bk.plan_signature(items)
    sig2 = bk.plan_signature(items)
    assert sig1 == sig2 and len(sig1) == 16
    # layout change (key order/priority) -> different signature
    flipped = (("a", (4, 4), "float32", -1, None),
               ("b", (7,), "float32", 0, None))
    assert bk.plan_signature(flipped) != sig1
    # an already-planned bucket list fingerprints identically
    assert bk.plan_signature(bk.plan(items)) == sig1


def test_fused_update_aot_sig_covers_layout():
    from mxnet_tpu.parallel import fused_update as fu
    import jax.numpy as jnp
    o = opt.create("sgd", learning_rate=0.1)
    spec = fu._SUPPORTED[type(o)]
    w = jnp.zeros((10,), jnp.float32)
    g = jnp.zeros((10,), jnp.float32)
    s1 = fu._aot_sig(spec, True, True, w, g, (), 0.0, (1, None, 0.0),
                     layout="aaaa")
    s2 = fu._aot_sig(spec, True, True, w, g, (), 0.0, (1, None, 0.0),
                     layout="bbbb")
    assert s1 != s2
