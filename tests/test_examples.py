"""Example smoke tests (reference: tests/python/train — small end-to-end
runs gating convergence). Each example asserts its own learning
criterion and exits nonzero on failure; tests run them as a user would.
"""
import os
import subprocess
import sys

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_example(rel, *argv, timeout=420):
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)  # examples set cpu themselves via --cpu
    r = subprocess.run(
        [sys.executable, os.path.join(ROOT, "example", rel), "--cpu",
         *argv],
        capture_output=True, text=True, timeout=timeout, env=env)
    assert r.returncode == 0, "example %s failed:\n%s\n%s" % (
        rel, r.stdout[-2000:], r.stderr[-2000:])
    return r.stdout


def test_dcgan():
    out = run_example("gan/dcgan.py", "--steps", "12",
                      "--batch-size", "8")
    assert "final loss_D" in out


def test_autoencoder():
    out = run_example("autoencoder/train_ae.py", "--epochs", "4",
                      "--n", "256")
    assert "final recon-mse" in out


def test_matrix_factorization():
    out = run_example("recommenders/matrix_factorization.py",
                      "--epochs", "3", "--obs", "4096")
    assert "final mse" in out


def test_matrix_factorization_sharded():
    out = run_example("recommenders/matrix_factorization.py",
                      "--epochs", "3", "--obs", "4096", "--sharded")
    assert "final mse" in out


@pytest.mark.parametrize("extra", [(), ("--no-moe",)],
                         ids=["moe", "dense"])
def test_transformer_ring_attention(extra):
    out = run_example("transformer/train_transformer.py",
                      "--steps", "25", *extra)
    assert "final nll" in out


def test_custom_softmax_numpy_op():
    out = run_example("numpy_ops/custom_softmax.py", "--epochs", "2")
    assert "final train accuracy" in out


def test_profiler_example(tmp_path):
    out = run_example("profiler_demo/profile_resnet.py", "--steps", "2",
                      "--output", str(tmp_path / "trace"))
    assert "trace written" in out


def test_quantization_example():
    out = run_example("quantization/quantize_resnet.py")
    assert "top-1 agreement" in out


def test_sharded_resnet_example():
    out = run_example("parallel/sharded_resnet.py", "--steps", "2")
    assert "params synced" in out


def test_gluon_cifar10_example():
    out = run_example("gluon/train_cifar10.py", "--epochs", "2")
    assert "epoch 0" in out


def test_fcn_segmentation():
    out = run_example("fcn_xs/train_fcn.py", "--steps", "60")
    assert "final pixel-acc" in out


def test_cnn_text_classification():
    out = run_example("cnn_text_classification/train_cnn_text.py",
                      "--epochs", "4", "--n", "1024")
    assert "final test-acc" in out


def test_neural_style():
    out = run_example("neural_style/neural_style.py", "--steps", "45")
    assert "final loss" in out


def test_transformer_pipeline_bucketed():
    out = run_example("transformer/train_pipeline_bucketed.py",
                      "--steps", "24")
    assert "PIPELINE_BUCKETED_OK" in out


def test_ctc_lstm_ocr():
    # loss-only: full decode convergence takes ~6 min on a 1-core VM
    # (the example's default config reaches 100% exact-sequence acc);
    # the smoke asserts the loss collapse phase
    out = run_example("ctc/lstm_ocr.py", "--epochs", "5",
                      "--train-size", "256", "--loss-only",
                      timeout=540)
    assert "CTC_OCR_OK" in out


def test_nce_toy():
    out = run_example("nce-loss/toy_nce.py", "--epochs", "8",
                      "--train-size", "4096")
    assert "NCE_OK" in out


def test_multi_task():
    out = run_example("multi-task/multi_task.py", "--epochs", "6")
    assert "MULTI_TASK_OK" in out


def test_bi_lstm_sort():
    out = run_example("bi-lstm-sort/sort_lstm.py", "--epochs", "8",
                      "--train-size", "2048", "--threshold", "0.75")
    assert "BI_LSTM_SORT_OK" in out


def test_vae():
    out = run_example("vae/vae_mnist.py", "--epochs", "8")
    assert "VAE_OK" in out


def test_reinforce_gridworld():
    out = run_example("reinforcement-learning/reinforce_gridworld.py",
                      "--episodes", "300")
    assert "REINFORCE_OK" in out


def test_svm_classifier():
    out = run_example("svm_mnist/svm_classifier.py", "--epochs", "8")
    assert "SVM_OK" in out


def test_multivariate_forecast():
    out = run_example("multivariate_time_series/lstnet_forecast.py",
                      "--epochs", "6", "--train-size", "2048")
    assert "FORECAST_OK" in out


def test_ner_tagger():
    out = run_example("named_entity_recognition/ner_tagger.py",
                      "--epochs", "8", "--train-size", "2048")
    assert "NER_OK" in out


def test_fgsm_adversary():
    out = run_example("adversary/fgsm.py", "--epochs", "5")
    assert "FGSM_OK" in out


def test_stochastic_depth():
    out = run_example("stochastic-depth/sd_resnet.py", "--epochs", "6",
                      "--train-size", "2000")
    assert "STOCHASTIC_DEPTH_OK" in out


def test_speech_recognition():
    out = run_example("speech_recognition/deepspeech_lite.py",
                      "--epochs", "5", "--train-size", "256",
                      "--loss-only", timeout=540)
    assert "SPEECH_OK" in out


def test_capsnet():
    out = run_example("capsnet/capsnet.py", "--epochs", "4",
                      "--train-size", "1500", timeout=540)
    assert "CAPSNET_OK" in out


def test_wgan_gradient_penalty():
    out = run_example("gradient_penalty/wgan_gp.py", "--steps", "120")
    assert "WGAN_GP_OK" in out


def test_word_lm():
    # 150-220 s/epoch on the 1-core CI box depending on load: the
    # default 420 s budget sits on the 2-epoch line and flakes when
    # anything else shares the core
    out = run_example("rnn/word_lm.py", "--epochs", "2", timeout=540)
    assert "WORD_LM_OK" in out


def test_mnist_module_fit():
    out = run_example("image_classification/train_mnist.py",
                      "--epochs", "8")
    assert "MNIST_EXAMPLE_OK" in out


def test_dsd_training():
    out = run_example("dsd/dsd_train.py", "--epochs-per-phase", "3")
    assert "DSD_OK" in out


def test_bayes_by_backprop():
    out = run_example("bayesian-methods/bayes_by_backprop.py",
                      "--epochs", "15")
    assert "BAYES_OK" in out


def test_gradcam_visualization():
    out = run_example("cnn_visualization/gradcam.py", "--epochs", "5")
    assert "GRADCAM_OK" in out


def test_memcost_remat():
    out = run_example("memcost/memory_cost.py")
    assert "MEMCOST_OK" in out


def test_deep_embedded_clustering():
    out = run_example("deep-embedded-clustering/dec.py")
    assert "DEC_OK" in out
