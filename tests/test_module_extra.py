"""SequentialModule and PythonModule tests.

Reference behaviors: sequential_module.py (chained bind/forward/backward
with take_labels meta) and python_module.py (PythonLossModule supplying
gradients from Python).
"""
import numpy as np
import pytest

import mxnet_tpu as mx


def _toy_data(n=64, d=8, classes=4, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, d).astype("float32")
    y = (np.abs(x.sum(1)).astype("int64") % classes).astype("float32")
    return mx.io.NDArrayIter(x, y, batch_size=16, shuffle=False,
                             label_name="softmax_label")


class TestSequentialModule:
    def _build(self):
        # net1: features; net2: classifier+loss (takes labels)
        d1 = mx.sym.var("data")
        f = mx.sym.FullyConnected(d1, num_hidden=16, name="fc1")
        f = mx.sym.Activation(f, act_type="relu", name="relu1")
        net1 = f
        d2 = mx.sym.var("fc1_relu")
        g = mx.sym.FullyConnected(d2, num_hidden=4, name="fc2")
        net2 = mx.sym.SoftmaxOutput(g, name="softmax")
        m1 = mx.mod.Module(net1, data_names=("data",), label_names=None)
        m2 = mx.mod.Module(net2, data_names=("fc1_relu",),
                           label_names=("softmax_label",))
        seq = mx.mod.SequentialModule()
        seq.add(m1).add(m2, take_labels=True, auto_wiring=True)
        return seq

    def test_fit_decreases_loss(self):
        seq = self._build()
        it = _toy_data()
        metric = mx.metric.Accuracy()
        seq.fit(it, num_epoch=3, eval_metric=metric,
                optimizer_params={"learning_rate": 0.1})
        assert seq.params_initialized and seq.binded

    def test_forward_shapes_and_predict(self):
        seq = self._build()
        it = _toy_data()
        seq.bind(data_shapes=it.provide_data,
                 label_shapes=it.provide_label)
        seq.init_params()
        batch = next(iter(it))
        seq.forward(batch, is_train=False)
        out = seq.get_outputs()[0]
        assert out.shape == (16, 4)
        assert seq.output_shapes[0][1] == (16, 4)

    def test_duplicate_param_names_rejected(self):
        d = mx.sym.var("data")
        net = mx.sym.FullyConnected(d, num_hidden=4, name="fc")
        m1 = mx.mod.Module(net, label_names=None)
        m2 = mx.mod.Module(mx.sym.FullyConnected(
            mx.sym.var("fc_output"), num_hidden=4, name="fc"),
            data_names=("fc_output",), label_names=None)
        seq = mx.mod.SequentialModule()
        seq.add(m1).add(m2, auto_wiring=True)
        seq.bind(data_shapes=[("data", (8, 8))])
        with pytest.raises(Exception):
            seq.init_params()


class TestPythonLossModule:
    def test_python_loss_head_trains(self):
        """Module (features) + PythonLossModule (softmax CE gradient in
        python) — the reference's python_module example composition."""
        d = mx.sym.var("data")
        net = mx.sym.FullyConnected(d, num_hidden=4, name="fc")
        feat = mx.mod.Module(net, label_names=None)

        def ce_grad(scores, labels):
            s = scores.asnumpy()
            s = np.exp(s - s.max(1, keepdims=True))
            s /= s.sum(1, keepdims=True)
            lbl = labels.asnumpy().astype(int)
            s[np.arange(len(lbl)), lbl] -= 1.0
            return mx.nd.array(s / len(lbl))

        loss = mx.mod.PythonLossModule(grad_func=ce_grad)
        seq = mx.mod.SequentialModule()
        seq.add(feat).add(loss, take_labels=True, auto_wiring=True)
        it = _toy_data()
        seq.bind(data_shapes=it.provide_data,
                 label_shapes=it.provide_label)
        seq.init_params()
        seq.init_optimizer(optimizer_params={"learning_rate": 0.5})

        def nll():
            it.reset()
            tot, n = 0.0, 0
            for b in it:
                seq.forward(b, is_train=False)
                s = seq.get_outputs()[0].asnumpy()
                p = np.exp(s - s.max(1, keepdims=True))
                p /= p.sum(1, keepdims=True)
                lbl = b.label[0].asnumpy().astype(int)
                tot += -np.log(p[np.arange(len(lbl)), lbl] + 1e-9).sum()
                n += len(lbl)
            return tot / n

        before = nll()
        for _ in range(5):
            it.reset()
            for b in it:
                seq.forward(b, is_train=True)
                seq.backward()
                seq.update()
        after = nll()
        assert after < before, (before, after)


def test_group2ctxs_raises_with_guidance():
    d = mx.sym.var("data")
    net = mx.sym.FullyConnected(d, num_hidden=2)
    with pytest.raises(Exception, match="ShardedTrainer"):
        mx.mod.Module(net, label_names=None,
                      group2ctxs={"dev1": [mx.cpu()]})


def test_bucketing_trains_into_fresh_bucket_after_init_optimizer():
    """A bucket first encountered AFTER init_optimizer must bind
    against the default bucket's executors (shared memory, no NDArray
    truthiness) and borrow its optimizer (reference: module.py:454) —
    the reference bucketing flow switches buckets lazily per batch."""
    from mxnet_tpu.rnn import BucketSentenceIter
    sentences = [[1, 2, 3], [4, 5], [7, 8, 9, 1],
                 [1, 2, 3, 4, 5, 6], [2, 4, 6, 8, 1], [9, 8, 7, 6, 5, 4, 3]]
    it = BucketSentenceIter(sentences, batch_size=2, buckets=[4, 8],
                            invalid_label=0)

    def sym_gen(seq_len):
        d = mx.sym.var("data")
        l = mx.sym.var("softmax_label")
        e = mx.sym.Embedding(d, input_dim=10, output_dim=4,
                             name="embed")
        r = mx.sym.Reshape(e, shape=(-1, 4))
        o = mx.sym.FullyConnected(r, num_hidden=10, name="pred")
        lf = mx.sym.Reshape(l, shape=(-1,))
        return (mx.sym.SoftmaxOutput(o, lf, name="softmax"),
                ("data",), ("softmax_label",))

    bm = mx.mod.BucketingModule(sym_gen, default_bucket_key=8)
    bm.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    bm.init_params()
    bm.init_optimizer(optimizer="sgd",
                      optimizer_params={"learning_rate": 0.1})
    keys_seen = set()
    for epoch in range(2):
        it.reset()
        for batch in it:
            bm.forward_backward(batch)
            bm.update()
            keys_seen.add(bm._curr_bucket_key)
    assert keys_seen == {4, 8}, keys_seen
    # the shared parameters actually moved
    args, _ = bm.get_params()
    assert float(np.abs(args["embed_weight"].asnumpy()).sum()) > 0
