"""Native runtime tests (src/libmxtpu.so): dependency engine semantics
and RecordIO round-trips.

Mirrors the reference's engine stress testing
(tests/cpp/engine/threaded_engine_test.cc pushes randomized dependency
patterns) and recordio tests (test_recordio.py), driven through ctypes.
"""
import os
import random
import threading
import time

import numpy as np
import pytest

from mxnet_tpu import _native
from mxnet_tpu import recordio as pyrec

LIB = _native.ensure_built()
pytestmark = pytest.mark.skipif(LIB is None,
                                reason="native lib not buildable")


def test_engine_write_serialization():
    """Writes to one var must serialize in push order."""
    eng = _native.NativeEngine(num_workers=4)
    v = eng.new_variable()
    order = []
    for i in range(50):
        eng.push(lambda i=i: order.append(i), mutable_vars=[v])
    eng.wait_for_all()
    assert order == list(range(50))
    eng.close()


def test_engine_parallel_reads():
    """Reads of one var run concurrently (no serialization)."""
    eng = _native.NativeEngine(num_workers=4)
    v = eng.new_variable()
    running = []
    peak = []
    lock = threading.Lock()

    def reader():
        with lock:
            running.append(1)
            peak.append(len(running))
        time.sleep(0.02)
        with lock:
            running.pop()

    for _ in range(8):
        eng.push(reader, const_vars=[v])
    eng.wait_for_all()
    assert max(peak) > 1, "reads never overlapped"
    eng.close()


def test_engine_read_write_ordering():
    """A write waits for prior reads; later reads wait for the write."""
    eng = _native.NativeEngine(num_workers=4)
    v = eng.new_variable()
    log = []
    eng.push(lambda: (time.sleep(0.02), log.append("r1")),
             const_vars=[v])
    eng.push(lambda: (time.sleep(0.02), log.append("r2")),
             const_vars=[v])
    eng.push(lambda: log.append("w"), mutable_vars=[v])
    eng.push(lambda: log.append("r3"), const_vars=[v])
    eng.wait_for_all()
    assert set(log[:2]) == {"r1", "r2"}
    assert log[2] == "w"
    assert log[3] == "r3"
    eng.close()


def test_engine_randomized_dependency_stress():
    """Randomized dependency pattern: per-var write counters must match
    push order (the threaded_engine_test.cc strategy)."""
    eng = _native.NativeEngine(num_workers=8)
    n_vars = 10
    vars_ = [eng.new_variable() for _ in range(n_vars)]
    counters = [[] for _ in range(n_vars)]
    rng = random.Random(0)
    expected = [[] for _ in range(n_vars)]
    for op in range(300):
        n_mut = rng.randint(1, 3)
        muts = rng.sample(range(n_vars), n_mut)
        reads = rng.sample(range(n_vars), rng.randint(0, 3))
        reads = [r for r in reads if r not in muts]

        def fn(op=op, muts=tuple(muts)):
            for m in muts:
                counters[m].append(op)
        for m in muts:
            expected[m].append(op)
        eng.push(fn, const_vars=[vars_[r] for r in reads],
                 mutable_vars=[vars_[m] for m in muts])
    eng.wait_for_all()
    for i in range(n_vars):
        assert counters[i] == expected[i], "var %d write order broken" % i
    eng.close()


def test_engine_wait_for_var():
    eng = _native.NativeEngine(num_workers=2)
    v = eng.new_variable()
    state = []
    eng.push(lambda: (time.sleep(0.05), state.append(1)),
             mutable_vars=[v])
    eng.wait_for_var(v)
    assert state == [1]
    eng.close()


def test_native_recordio_roundtrip(tmp_path):
    path = str(tmp_path / "test.rec")
    w = _native.RecordWriter(path)
    recs = [os.urandom(random.randint(1, 200)) for _ in range(20)]
    positions = [w.write(r) for r in recs]
    w.close()
    assert positions[0] == 0

    r = _native.RecordReader(path)
    got = []
    while True:
        rec = r.read()
        if rec is None:
            break
        got.append(rec)
    assert got == recs
    # seek back to record 5
    r.seek(positions[5])
    assert r.read() == recs[5]
    r.close()


def test_native_python_recordio_interop(tmp_path):
    """Files written by the python writer read back natively and vice
    versa (same dmlc format)."""
    path = str(tmp_path / "interop.rec")
    pw = pyrec.MXRecordIO(path, "w")
    recs = [bytes([i]) * (i + 1) for i in range(10)]
    for rec in recs:
        pw.write(rec)
    pw.close()
    nr = _native.RecordReader(path)
    got = [nr.read() for _ in range(10)]
    assert got == recs
    assert nr.read() is None
    nr.close()

    path2 = str(tmp_path / "interop2.rec")
    nw = _native.RecordWriter(path2)
    for rec in recs:
        nw.write(rec)
    nw.close()
    pr = pyrec.MXRecordIO(path2, "r")
    got2 = [pr.read() for _ in range(10)]
    assert got2 == recs


def test_prefetch_loader(tmp_path):
    path = str(tmp_path / "pf.rec")
    w = _native.RecordWriter(path)
    recs = [bytes([i % 256]) * 50 for i in range(100)]
    for rec in recs:
        w.write(rec)
    w.close()
    loader = _native.PrefetchLoader(path, batch_records=16, queue_cap=2)
    got = []
    for batch in loader:
        got.extend(batch)
    assert got == recs
    loader.close()


def test_engine_exception_surfaces_at_wait_for_all():
    """A throwing op must not kill the process: the exception is captured
    on the worker, attached to the op's vars, and rethrown at
    wait_for_all (reference: threaded_engine.h:179,256)."""
    from mxnet_tpu import _native
    eng = _native.NativeEngine(2)
    v = eng.new_variable()

    def boom():
        raise ValueError("deliberate-failure-42")

    eng.push(boom, mutable_vars=[v])
    with pytest.raises(_native.NativeError, match="deliberate-failure-42"):
        eng.wait_for_all()
    # engine stays usable after the rethrow
    hits = []
    eng.push(lambda: hits.append(1), mutable_vars=[v])
    eng.wait_for_all()
    assert hits == [1]
    eng.close()


def test_engine_exception_surfaces_at_wait_for_var_and_poisons():
    from mxnet_tpu import _native
    eng = _native.NativeEngine(2)
    v = eng.new_variable()
    ran = []

    def boom():
        raise RuntimeError("poisoned-var")

    eng.push(boom, mutable_vars=[v])
    # dependent op must NOT run; the poison propagates through v
    eng.push(lambda: ran.append(1), const_vars=[v])
    with pytest.raises(_native.NativeError, match="poisoned-var"):
        eng.wait_for_var(v)
    assert ran == []
    try:
        eng.wait_for_all()  # drain remaining global exception
    except _native.NativeError:
        pass
    eng.close()


def test_waitall_is_a_fence_and_raises_engine_errors():
    """nd.waitall() must drain the host engine and surface its captured
    exceptions (VERDICT r2 weak #5: waitall as a true fence)."""
    import mxnet_tpu as mx
    from mxnet_tpu import engine as eng_mod
    eng = eng_mod.host_engine()
    if eng is None:
        pytest.skip("native lib unavailable")
    done = []
    v = eng.new_variable()
    eng.push(lambda: (time.sleep(0.2), done.append(1)), mutable_vars=[v])
    mx.nd.waitall()
    assert done == [1]  # fence ordered after the host op

    def boom():
        raise RuntimeError("fence-sees-this")

    eng.push(boom, mutable_vars=[v])
    with pytest.raises(Exception, match="fence-sees-this"):
        mx.nd.waitall()


def test_naive_engine_env_selection():
    """MXNET_ENGINE_TYPE=NaiveEngine selects the serial oracle at import
    (reference: engine.cc CreateEngine env dispatch)."""
    import subprocess, sys, os
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["MXNET_ENGINE_TYPE"] = "NaiveEngine"
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    code = (
        "import jax; jax.config.update('jax_platforms','cpu')\n"
        "import mxnet_tpu as mx\n"
        "from mxnet_tpu import engine\n"
        "assert engine.engine_type() == 'NaiveEngine'\n"
        "a = mx.nd.ones((4,)) + 1\n"  # runs synchronously\n
        "print('NAIVE_OK')\n")
    r = subprocess.run([sys.executable, "-c", code], env=env,
                       capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, r.stderr[-2000:]
    assert "NAIVE_OK" in r.stdout


def test_native_jpeg_decode_matches_pil_and_scales():
    # src/image_decode.cc (reference: the OpenCV decode in image_io.cc)
    import io as pyio
    pytest.importorskip("PIL")
    from PIL import Image
    from mxnet_tpu._native import imdecode_jpeg, ensure_built
    if ensure_built() is None:
        pytest.skip("native lib unavailable")
    rng = np.random.RandomState(0)
    im = (rng.rand(96, 128, 3) * 255).astype(np.uint8)
    buf = pyio.BytesIO()
    Image.fromarray(im).save(buf, format="JPEG", quality=92)
    data = buf.getvalue()
    d = imdecode_jpeg(data)
    pil = np.asarray(Image.open(pyio.BytesIO(data)).convert("RGB"))
    assert d is not None and d.shape == pil.shape
    assert np.array_equal(d, pil)  # same libjpeg underneath
    ds = imdecode_jpeg(data, short_side=48)
    assert ds.shape == (48, 64, 3)
    assert imdecode_jpeg(b"\xff\xd8garbage") is None
    # grayscale jpegs come back as RGB
    buf2 = pyio.BytesIO()
    Image.fromarray(im[:, :, 0]).save(buf2, format="JPEG")
    assert imdecode_jpeg(buf2.getvalue()).shape == (96, 128, 3)


def test_unpack_img_grayscale_shape_independent_of_native_lib():
    # iscolor=-1 must keep a grayscale JPEG 2-D even when the native
    # RGB-only decoder is built (it is only used for iscolor=1)
    import io as pyio
    pytest.importorskip("PIL")
    from PIL import Image
    import mxnet_tpu.recordio as rio
    im = (np.random.RandomState(0).rand(16, 12) * 255).astype(np.uint8)
    buf = pyio.BytesIO()
    Image.fromarray(im).save(buf, format="JPEG")
    rec = rio.pack(rio.IRHeader(0, 1.0, 0, 0), buf.getvalue())
    _, img_as_stored = rio.unpack_img(rec, iscolor=-1)
    assert img_as_stored.ndim == 2
    _, img_color = rio.unpack_img(rec, iscolor=1)
    assert img_color.shape == (16, 12, 3)


def test_library_path_override_honored(tmp_path, monkeypatch):
    # MXTPU_LIBRARY_PATH must be what the loader actually dlopens
    from mxnet_tpu import _native
    real = tmp_path / "fake.so"
    real.write_bytes(b"")
    monkeypatch.setenv("MXTPU_LIBRARY_PATH", str(real))
    assert _native._lib_path() == str(real)
    # a stale override must not silently disable the in-tree lib
    monkeypatch.setenv("MXTPU_LIBRARY_PATH", str(tmp_path / "nope.so"))
    assert _native._lib_path() == _native._LIB_PATH
    monkeypatch.delenv("MXTPU_LIBRARY_PATH")
    monkeypatch.delenv("MXNET_LIBRARY_PATH", raising=False)
    assert _native._lib_path() == _native._LIB_PATH
