"""Checkpoint-format tests: binary .params container + graph JSON
round-trip across vintages (reference: src/ndarray/ndarray.cc:1537-1762,
src/nnvm/legacy_json_util.cc)."""
import json
import struct

import numpy as np

import mxnet_tpu as mx
import mxnet_tpu.ndarray.sparse as sp


def test_params_dict_roundtrip(tmp_path):
    f = str(tmp_path / "t.params")
    a = mx.nd.array(np.arange(6).reshape(2, 3).astype("f"))
    b = mx.nd.array(np.array([1, 2, 3], dtype="int32"))
    mx.nd.save(f, {"a": a, "b": b})
    d = mx.nd.load(f)
    assert np.allclose(d["a"].asnumpy(), a.asnumpy())
    assert d["b"].asnumpy().dtype == np.int32
    assert np.array_equal(d["b"].asnumpy(), [1, 2, 3])


def test_params_list_roundtrip(tmp_path):
    f = str(tmp_path / "t.params")
    arrs = [mx.nd.ones((2, 2)), mx.nd.zeros((3,))]
    mx.nd.save(f, arrs)
    l = mx.nd.load(f)
    assert isinstance(l, list) and len(l) == 2
    assert np.allclose(l[0].asnumpy(), 1.0)


def test_params_sparse_roundtrip(tmp_path):
    f = str(tmp_path / "t.params")
    rs = sp.RowSparseNDArray(np.eye(2, 3, dtype="f"),
                             np.array([0, 2], "i"), (4, 3))
    csr = sp.CSRNDArray(np.array([1.0, 2.0], "f"),
                        np.array([0, 2], "i"),
                        np.array([0, 1, 2], "i"), (2, 3))
    mx.nd.save(f, {"rs": rs, "csr": csr})
    d = mx.nd.load(f)
    assert isinstance(d["rs"], sp.RowSparseNDArray)
    assert isinstance(d["csr"], sp.CSRNDArray)
    dense = d["rs"].tostype("default").asnumpy()
    assert np.allclose(dense[0], [1, 0, 0])
    assert np.allclose(dense[2], [0, 1, 0])
    assert np.allclose(dense[1], 0)


def _golden_v2_dense():
    """Reference byte layout packed independently of the serializer."""
    out = [struct.pack("<QQ", 0x112, 0), struct.pack("<Q", 1),
           struct.pack("<I", 0xF993FAC9),            # V2 magic
           struct.pack("<i", 0),                     # dense stype
           struct.pack("<I", 2), struct.pack("<2q", 2, 2),  # shape
           struct.pack("<ii", 1, 0),                 # cpu ctx
           struct.pack("<i", 0),                     # float32
           np.arange(4, dtype="f").tobytes(),
           struct.pack("<Q", 1),
           struct.pack("<Q", 3), b"arr"]
    return b"".join(out)


def test_golden_reference_bytes():
    d = mx.nd.load_frombuffer(_golden_v2_dense())
    assert np.allclose(d["arr"].asnumpy(), [[0, 1], [2, 3]])


def test_golden_v1_and_v0_legacy_bytes():
    v1 = b"".join([struct.pack("<QQ", 0x112, 0), struct.pack("<Q", 1),
                   struct.pack("<I", 0xF993FAC8),
                   struct.pack("<I", 1), struct.pack("<q", 3),
                   struct.pack("<ii", 1, 0),
                   struct.pack("<i", 4),             # int32
                   np.array([7, 8, 9], "i").tobytes(),
                   struct.pack("<Q", 0)])
    g1 = mx.nd.load_frombuffer(v1)
    assert np.array_equal(g1[0].asnumpy(), [7, 8, 9])

    v0 = b"".join([struct.pack("<QQ", 0x112, 0), struct.pack("<Q", 1),
                   struct.pack("<I", 2),             # ndim-as-magic
                   struct.pack("<2I", 2, 2),
                   struct.pack("<ii", 1, 0),
                   struct.pack("<i", 0),
                   np.arange(4, dtype="f").tobytes(),
                   struct.pack("<Q", 0)])
    g0 = mx.nd.load_frombuffer(v0)
    assert np.allclose(g0[0].asnumpy(), [[0, 1], [2, 3]])


def test_npz_backcompat(tmp_path):
    """Round-1 .npz checkpoints still load."""
    f = str(tmp_path / "old.npz")
    np.savez(f, __format__="dict", w=np.ones((2, 2), "f"))
    d = mx.nd.load(f)
    assert np.allclose(d["w"].asnumpy(), 1.0)


def _legacy_vintage_json():
    """A 2015-style graph JSON: param/attr split, 2-element input
    entries, implicit BatchNorm aux states (shape mirrors the
    reference's tests/python/unittest/save_000800.json layout)."""
    nodes = [
        {"op": "null", "param": {}, "name": "data", "inputs": [],
         "backward_source_id": -1, "attr": {"ctx_group": "stage1"}},
        {"op": "null", "param": {}, "name": "fc1_weight", "inputs": [],
         "backward_source_id": -1},
        {"op": "null", "param": {}, "name": "fc1_bias", "inputs": [],
         "backward_source_id": -1},
        {"op": "FullyConnected",
         "param": {"no_bias": "False", "num_hidden": "8"},
         "name": "fc1", "inputs": [[0, 0], [1, 0], [2, 0]],
         "backward_source_id": -1, "attr": {"ctx_group": "stage1"}},
        {"op": "null", "param": {}, "name": "bn_gamma", "inputs": [],
         "backward_source_id": -1},
        {"op": "null", "param": {}, "name": "bn_beta", "inputs": [],
         "backward_source_id": -1},
        {"op": "BatchNorm",
         "param": {"eps": "0.001", "momentum": "0.9",
                   "fix_gamma": "True"},
         "name": "bn", "inputs": [[3, 0], [4, 0], [5, 0]],
         "backward_source_id": -1},
        {"op": "Activation", "param": {"act_type": "relu"},
         "name": "relu1", "inputs": [[6, 0]], "backward_source_id": -1},
        {"op": "null", "param": {}, "name": "softmax_label",
         "inputs": [], "backward_source_id": -1},
        {"op": "SoftmaxOutput",
         "param": {"grad_scale": "1", "multi_output": "False"},
         "name": "softmax", "inputs": [[7, 0], [8, 0]],
         "backward_source_id": -1},
    ]
    return json.dumps({"nodes": nodes,
                       "arg_nodes": [0, 1, 2, 4, 5, 8],
                       "heads": [[9, 0]]})


def test_legacy_json_import_and_roundtrip():
    sym = mx.sym.load_json(_legacy_vintage_json())
    assert "fc1_weight" in sym.list_arguments()
    # implicit BatchNorm aux states materialized like compose would
    assert sym.list_auxiliary_states() == ["bn_moving_mean",
                                           "bn_moving_var"]
    ex = sym.simple_bind(mx.cpu(), data=(2, 10), softmax_label=(2,))
    out = ex.forward(is_train=False)
    assert out[0].shape == (2, 8)

    # our export is string-attr JSON that reloads identically
    js = json.loads(sym.tojson())
    for node in js["nodes"]:
        for v in node.get("attrs", {}).values():
            assert isinstance(v, str)
    sym2 = mx.sym.load_json(sym.tojson())
    assert sym2.list_arguments() == sym.list_arguments()
    assert sym2.list_auxiliary_states() == sym.list_auxiliary_states()
    ex2 = sym2.simple_bind(mx.cpu(), data=(2, 10), softmax_label=(2,))
    assert ex2.forward(is_train=False)[0].shape == (2, 8)


def test_module_checkpoint_roundtrip(tmp_path):
    prefix = str(tmp_path / "model")
    rng = np.random.RandomState(0)
    X = rng.randn(32, 6).astype("f")
    Y = (X.sum(1) > 0).astype("f")
    data = mx.sym.var("data")
    net = mx.sym.FullyConnected(data, num_hidden=2, name="fc")
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    it = mx.io.NDArrayIter(X, Y, batch_size=16,
                           label_name="softmax_label")
    mod = mx.mod.Module(net, label_names=["softmax_label"])
    mod.fit(it, num_epoch=2, optimizer_params={"learning_rate": 0.1})
    mod.save_checkpoint(prefix, 2)

    sym2, args, auxs = mx.model.load_checkpoint(prefix, 2)
    mod2 = mx.mod.Module(sym2, label_names=["softmax_label"])
    mod2.bind(data_shapes=[("data", (16, 6))],
              label_shapes=[("softmax_label", (16,))])
    mod2.set_params(args, auxs)
    it.reset()
    p1 = mod.predict(it).asnumpy()
    it.reset()
    p2 = mod2.predict(it).asnumpy()
    assert np.allclose(p1, p2, atol=1e-6)
