"""Device lease & health subsystem (ISSUE 7, docs/fault_tolerance.md).

Covers the acceptance surface: contended acquire has exactly one
winner; a SIGKILLed holder is taken over within the hard timeout with
no orphan lease file; a wedged LIVE holder (stale heartbeat) is
recovered without --force; a fresh live holder is never killed (by the
lease, by kill_stale --force, or by bench's probe path); the health
watchdog trips typed errors with holder diagnostics; and
tools/perf_gate.py turns a telemetry stream into a CI exit code.

Everything runs on the CPU mesh. Subprocess workers import the real
package (the lease is cross-process by nature); the wedged-holder
stand-ins are plain sleepers whose lease records carry their /proc
starttime — the same identity DeviceLease verifies before signalling.
"""
import importlib.util
import json
import os
import signal
import socket
import subprocess
import sys
import time

import pytest

import mxnet_tpu as mx
from mxnet_tpu.observability import registry as obs
from mxnet_tpu.resilience import chaos
from mxnet_tpu.resilience.atomic import exclusive_create
from mxnet_tpu.resilience.lease import (DeviceLease, LeaseHeld,
                                        _proc_starttime, read_lease)
from mxnet_tpu.resilience.watchdog import (DeviceUnreachable,
                                           HealthWatchdog, diagnostics)
from mxnet_tpu.resilience.retry import DeadlineExceeded

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_chaos():
    chaos.configure("")
    yield
    chaos.reset()


@pytest.fixture()
def lease_path(tmp_path):
    return str(tmp_path / "dev.lease")


def _sleeper():
    """A wedged-holder stand-in: plain sleeper, no framework import."""
    return subprocess.Popen([sys.executable, "-S", "-c",
                             "import time; time.sleep(600)"])


def _lease_record(pid, heartbeat_age=0.0, takeover_s=2.0, starttime=...):
    if starttime is ...:
        starttime = _proc_starttime(pid)
    return {"pid": pid, "host": socket.gethostname(),
            "boot_id": open("/proc/sys/kernel/random/boot_id")
            .read().strip(),
            "starttime": starttime, "what": "wedged",
            "created": time.time() - heartbeat_age - 1.0,
            "heartbeat": time.time() - heartbeat_age,
            "heartbeat_s": 0.5, "takeover_s": takeover_s}


def _write_lease(path, rec):
    with open(path, "w") as f:
        f.write(json.dumps(rec))


# -- primitives -----------------------------------------------------------

def test_exclusive_create(tmp_path):
    p = str(tmp_path / "x")
    assert exclusive_create(p, "one")
    assert not exclusive_create(p, "two")
    assert open(p).read() == "one"


def test_acquire_release_roundtrip(lease_path):
    dl = DeviceLease(path=lease_path, takeover_s=5.0, what="test")
    with dl:
        rec = read_lease(lease_path)
        assert rec["pid"] == os.getpid()
        assert rec["what"] == "test"
        assert rec["starttime"] == _proc_starttime(os.getpid())
        hb0 = rec["heartbeat"]
        assert dl.refresh()
        assert read_lease(lease_path)["heartbeat"] >= hb0
    # no orphan file after release
    assert not os.path.exists(lease_path)
    assert not dl.held()


def test_reacquire_same_instance_is_idempotent(lease_path):
    dl = DeviceLease(path=lease_path, takeover_s=5.0)
    dl.acquire(timeout=5)
    assert dl.acquire(timeout=5) is dl       # held: no second create
    dl.release()


# -- staleness / takeover -------------------------------------------------

def test_fresh_live_holder_blocks_acquire(lease_path):
    holder = _sleeper()
    try:
        time.sleep(0.2)
        _write_lease(lease_path, _lease_record(holder.pid,
                                               takeover_s=60.0))
        with pytest.raises(LeaseHeld) as ei:
            DeviceLease(path=lease_path, takeover_s=60.0).acquire(
                timeout=0.8)
        assert ei.value.holder["pid"] == holder.pid
        # the holder was never signalled
        assert holder.poll() is None
        assert read_lease(lease_path)["pid"] == holder.pid
    finally:
        holder.kill()
        holder.wait()


def test_wedged_live_holder_taken_over_and_killed(lease_path):
    """The BENCH_r03–r05 mode: the holder is alive but stopped
    heartbeating past the hard timeout — SIGTERM→SIGKILL, then the
    lease changes hands. No --force anywhere."""
    holder = _sleeper()
    try:
        time.sleep(0.2)
        _write_lease(lease_path, _lease_record(holder.pid,
                                               heartbeat_age=100.0))
        dl = DeviceLease(path=lease_path, takeover_s=2.0,
                         kill_grace_s=1.0, what="taker")
        t0 = time.monotonic()
        dl.acquire(timeout=20)
        took = time.monotonic() - t0
        assert dl.takeovers == 1
        assert dl.taken_over_from["pid"] == holder.pid
        assert took < 10.0            # well within the hard timeout
        assert _proc_starttime(holder.pid) is None   # holder reaped
        assert read_lease(lease_path)["pid"] == os.getpid()
        dl.release()
        assert not os.path.exists(lease_path)
    finally:
        holder.kill()
        holder.wait()


def test_dead_holder_reclaimed_even_with_fresh_heartbeat(lease_path):
    """A dead pid holds nothing, whatever the timestamps say."""
    rec = _lease_record(os.getpid(), heartbeat_age=0.0)
    rec["pid"] = 2 ** 22 + 1              # vanishingly unlikely to exist
    rec["starttime"] = 12345
    _write_lease(lease_path, rec)
    dl = DeviceLease(path=lease_path, takeover_s=60.0)
    dl.acquire(timeout=10)
    assert dl.takeovers == 1
    dl.release()


def test_recycled_pid_never_blindly_killed(lease_path):
    """Stale lease whose pid now belongs to a DIFFERENT process
    (starttime mismatch): the lease is reclaimed but the innocent
    process is never signalled."""
    bystander = _sleeper()
    try:
        time.sleep(0.2)
        _write_lease(lease_path, _lease_record(
            bystander.pid, heartbeat_age=100.0, starttime=1))
        dl = DeviceLease(path=lease_path, takeover_s=2.0,
                         kill_grace_s=1.0)
        dl.acquire(timeout=10)
        assert dl.takeovers == 1
        assert bystander.poll() is None   # untouched
        dl.release()
    finally:
        bystander.kill()
        bystander.wait()


def test_refresh_detects_loss_and_stands_down(lease_path):
    """A holder that was (rightly) taken over after going silent must
    not stomp the new holder's lease on wakeup."""
    dl = DeviceLease(path=lease_path, takeover_s=5.0)
    dl.acquire(timeout=5)
    foreign = _lease_record(os.getpid())
    foreign["created"] = time.time() + 1   # a different lease identity
    _write_lease(lease_path, foreign)
    assert dl.refresh() is False
    assert dl.lost and not dl.held()
    dl.release()
    # the usurper's lease survives our release
    assert read_lease(lease_path)["created"] == foreign["created"]
    os.unlink(lease_path)


def test_chaos_lease_acquire_site(lease_path):
    chaos.configure("lease.acquire:kind=raise,n=1")
    from mxnet_tpu.resilience import InjectedFault
    with pytest.raises(InjectedFault):
        DeviceLease(path=lease_path).acquire(timeout=1)
    assert chaos.trip_count("lease.acquire") == 1
    assert not os.path.exists(lease_path)   # failed acquire left nothing
    chaos.configure("")
    dl = DeviceLease(path=lease_path)
    dl.acquire(timeout=5)
    dl.release()


# -- multi-process contention (the acceptance test) -----------------------

_WORKER = r'''
import os, sys, time
sys.path.insert(0, %r)
from mxnet_tpu.resilience.lease import DeviceLease, LeaseHeld
path, takeover, mode, timeout = (sys.argv[1], float(sys.argv[2]),
                                 sys.argv[3], float(sys.argv[4]))
dl = DeviceLease(path=path, takeover_s=takeover, kill_grace_s=1.0,
                 what=mode)
try:
    dl.acquire(timeout=timeout)
except LeaseHeld:
    print("LOST", flush=True)
    sys.exit(3)
print("WON %%d %%d" %% (os.getpid(), dl.takeovers), flush=True)
if mode == "hold":
    time.sleep(600)
else:
    dl.release()
    print("RELEASED", flush=True)
''' % ROOT


def _spawn_worker(path, takeover, mode, timeout):
    return subprocess.Popen(
        [sys.executable, "-c", _WORKER, path, str(takeover), mode,
         str(timeout)],
        cwd=ROOT, stdout=subprocess.PIPE, text=True, bufsize=1)


def _read_line(proc, deadline=60.0):
    end = time.monotonic() + deadline
    line = ""
    while time.monotonic() < end:
        line = proc.stdout.readline()
        if line:
            return line.strip()
    raise AssertionError("worker produced no output within %ss: %s"
                         % (deadline, line))


def test_multiprocess_contention_and_takeover(lease_path):
    """Two processes race: exactly one wins. SIGKILL the winner: the
    waiter takes over within the hard timeout, the lease file names
    the new holder, and release leaves no orphan file behind."""
    holder = _spawn_worker(lease_path, 2.0, "hold", 30)
    try:
        won = _read_line(holder)
        assert won.startswith("WON %d" % holder.pid)
        # contended acquire: the second process must LOSE, not co-hold
        loser = _spawn_worker(lease_path, 2.0, "take", 1.0)
        assert _read_line(loser) == "LOST"
        assert loser.wait(timeout=30) == 3
        assert read_lease(lease_path)["pid"] == holder.pid

        # now a patient waiter + a SIGKILLed holder
        waiter = _spawn_worker(lease_path, 2.0, "take", 30.0)
        time.sleep(0.5)                   # let it reach the wait loop
        t0 = time.monotonic()
        holder.kill()
        holder.wait()
        won = _read_line(waiter, deadline=30.0)
        took = time.monotonic() - t0
        assert won.startswith("WON %d" % waiter.pid), won
        assert took < 15.0                # hard timeout is 2s + margin
        assert _read_line(waiter) == "RELEASED"
        assert waiter.wait(timeout=30) == 0
        # no orphan/stale lease file left behind
        assert not os.path.exists(lease_path)
        assert not os.path.exists(lease_path + ".takeover")
    finally:
        for p in (holder,):
            if p.poll() is None:
                p.kill()
                p.wait()


# -- health watchdog ------------------------------------------------------

def _trips(kind):
    return obs.REGISTRY.get("resilience.watchdog.trips").get(kind=kind)


def test_watchdog_init_trip_fake_backend(lease_path):
    _write_lease(lease_path, _lease_record(os.getpid()))
    wd = HealthWatchdog(init_timeout_s=0.2, lease_path=lease_path)
    before = _trips("init")
    with pytest.raises(DeviceUnreachable) as ei:
        wd.init_devices(probe=lambda t: (None, "tunnel dead"))
    assert _trips("init") == before + 1
    # the trip names the probe error AND the lease holder
    assert "tunnel dead" in str(ei.value)
    assert str(os.getpid()) in str(ei.value)
    os.unlink(lease_path)


def test_watchdog_init_ok_real_backend():
    devs = HealthWatchdog(init_timeout_s=60).init_devices()
    assert devs and devs[0].platform == "cpu"


def test_watchdog_collective_trip():
    wd = HealthWatchdog(collective_timeout_s=0.2)
    before = _trips("collective")
    with pytest.raises(DeadlineExceeded):
        wd.guard_collective(lambda: time.sleep(5), what="fake barrier")
    assert _trips("collective") == before + 1
    # unguarded (0) runs inline
    assert wd.guard_collective(lambda: 7, timeout_s=0) == 7
    # within budget returns the value
    assert wd.guard_collective(lambda: 9, timeout_s=5.0) == 9


def test_device_init_chaos_site():
    chaos.configure("device.init:kind=fatal,n=1")
    from mxnet_tpu.resilience import InjectedFailure
    with pytest.raises(InjectedFailure):
        HealthWatchdog(init_timeout_s=1).init_devices(
            probe=lambda t: (["dev"], None))
    assert chaos.trip_count("device.init") == 1


def test_diagnostics_names_holder(lease_path):
    rec = _lease_record(os.getpid(), heartbeat_age=3.0)
    _write_lease(lease_path, rec)
    d = diagnostics(lease_path)
    assert str(os.getpid()) in d and "heartbeat" in d
    os.unlink(lease_path)
    assert "no holder" in diagnostics(lease_path)


def test_dist_lease_skipped_on_cpu():
    """Multi-process CPU runs (tests, gloo) share the backend: the
    training path must not serialize them on one lease."""
    from mxnet_tpu.parallel.kvstore_dist import _lease_wanted
    assert _lease_wanted() is False       # conftest pins jax to cpu


def test_lease_wanted_policy(monkeypatch):
    """Explicit MXTPU_LEASE wins; otherwise only a PRIMARY cpu platform
    skips — "axon,cpu" (accelerator with cpu fallback) must lease."""
    from mxnet_tpu.resilience.lease import lease_wanted
    monkeypatch.setenv("MXTPU_LEASE", "0")
    assert lease_wanted(_platforms="axon,cpu") is False
    monkeypatch.setenv("MXTPU_LEASE", "1")
    assert lease_wanted(_platforms="cpu") is True
    monkeypatch.delenv("MXTPU_LEASE")
    monkeypatch.delenv("MXNET_LEASE", raising=False)
    assert lease_wanted(_platforms="cpu") is False
    assert lease_wanted(_platforms="axon,cpu") is True
    assert lease_wanted(_platforms="") is True    # unknown: could be accel


def test_hold_refcount_survives_reacquire(lease_path, monkeypatch):
    """Re-acquiring the process-wide hold after the old lease was
    usurped must keep the outstanding refcount: the first rider's
    release_hold() must not drop the fresh lease out from under the
    later holders."""
    from mxnet_tpu.resilience import lease as L
    monkeypatch.setenv("MXTPU_LEASE_PATH", lease_path)
    try:
        L.hold(what="first", timeout=5)
        # usurp: a foreign record replaces ours; the holder notices on
        # its next heartbeat and stands down
        foreign = _lease_record(os.getpid())
        foreign["created"] = time.time() + 1
        _write_lease(lease_path, foreign)
        assert L._process["lease"].refresh() is False
        os.unlink(lease_path)
        L.hold(what="second", timeout=5)      # re-acquire: refs now 2
        L.release_hold()                      # first rider leaves
        assert L.held_state() is not None     # second STILL holds
        assert read_lease(lease_path)["what"] == "second"
        L.release_hold()
        assert L.held_state() is None
        assert not os.path.exists(lease_path)
    finally:
        while L.held_state() is not None:
            L.release_hold()


# -- telemetry / observability -------------------------------------------

def test_lease_events_feed_telemetry_report(lease_path, tmp_path,
                                            monkeypatch):
    stream = str(tmp_path / "tele.jsonl")
    monkeypatch.setenv("MXTPU_TELEMETRY", stream)
    holder = _sleeper()
    try:
        time.sleep(0.2)
        _write_lease(lease_path, _lease_record(holder.pid,
                                               heartbeat_age=100.0))
        dl = DeviceLease(path=lease_path, takeover_s=2.0,
                         kill_grace_s=1.0)
        dl.acquire(timeout=20)
        dl.release()
    finally:
        holder.kill()
        holder.wait()
    from mxnet_tpu.observability import telemetry
    telemetry.close_stream()
    monkeypatch.delenv("MXTPU_TELEMETRY")
    events = [json.loads(l) for l in open(stream)]
    kinds = {e["event"] for e in events}
    assert {"lease_acquire", "lease_takeover"} <= kinds
    # the report renders a lease section from the same stream
    spec = importlib.util.spec_from_file_location(
        "telemetry_report_t", os.path.join(ROOT, "tools",
                                           "telemetry_report.py"))
    rep = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(rep)
    s = rep.summarize(rep.load_records(stream))
    assert s["lease_acquires"] == 1 and s["lease_takeovers"] == 1
    assert s["lease_stale_heartbeat_max_s"] > 50.0
    assert "lease" in rep.format_summary(s)


def test_lease_metrics_registered():
    for name, kind in (("resilience.lease.acquire.seconds", "histogram"),
                       ("resilience.lease.takeovers", "counter"),
                       ("resilience.lease.heartbeat.age", "gauge"),
                       ("resilience.watchdog.trips", "counter")):
        m = obs.REGISTRY.get(name)
        assert m is not None and m.kind == kind, name


# -- tools/kill_stale.py --------------------------------------------------

def _kill_stale(*args):
    return subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "kill_stale.py")]
        + list(args), capture_output=True, text=True, timeout=120)


def test_kill_stale_refuses_fresh_holder_even_forced(lease_path):
    holder = _sleeper()
    try:
        time.sleep(0.2)
        _write_lease(lease_path, _lease_record(holder.pid,
                                               takeover_s=600.0))
        r = _kill_stale("--kill", "--force", "--lease-path", lease_path)
        assert r.returncode == 2, r.stdout + r.stderr
        assert "refused" in r.stdout
        assert holder.poll() is None          # still alive
        assert os.path.exists(lease_path)     # lease intact
        # the old dead-end wording is gone for good
        assert "holds the device lease?" not in r.stdout
    finally:
        holder.kill()
        holder.wait()


def test_kill_stale_reaps_expired_holder_and_clears_lease(lease_path):
    holder = _sleeper()
    try:
        time.sleep(0.2)
        _write_lease(lease_path, _lease_record(holder.pid,
                                               heartbeat_age=100.0))
        r = _kill_stale("--kill", "--lease-path", lease_path)
        holder.wait(timeout=10)
        assert r.returncode == 0, r.stdout + r.stderr
        assert "-> killed" in r.stdout
        assert not os.path.exists(lease_path), r.stdout
    finally:
        if holder.poll() is None:
            holder.kill()
            holder.wait()


def test_kill_stale_never_clears_foreign_host_lease(lease_path):
    """A holder on another host (shared-filesystem lease path) can't be
    inspected from here: a fresh one blocks recovery (exit 2), and the
    lease file is never cleared either way."""
    rec = _lease_record(2 ** 22 + 1, heartbeat_age=0.0, starttime=1)
    rec["host"] = "some-other-host"
    _write_lease(lease_path, rec)
    r = _kill_stale("--kill", "--lease-path", lease_path)
    assert r.returncode == 2, r.stdout + r.stderr
    assert os.path.exists(lease_path)
    assert "cannot recover" in r.stdout


def test_kill_stale_foreign_holder_pid_never_hits_local_process(
        lease_path):
    """A foreign-host holder's pid means nothing in OUR /proc: a local
    process that happens to share the number must not be tagged (or
    killed) as the expired holder."""
    bystander = _sleeper()
    try:
        time.sleep(0.2)
        rec = _lease_record(bystander.pid, heartbeat_age=100.0)
        rec["host"] = "some-other-host"
        _write_lease(lease_path, rec)
        r = _kill_stale("--kill", "--lease-path", lease_path)
        assert bystander.poll() is None       # untouched
        assert os.path.exists(lease_path)     # not ours to clear
        assert "-> killed" not in r.stdout
    finally:
        bystander.kill()
        bystander.wait()


def test_kill_stale_clears_orphan_lease(lease_path):
    rec = _lease_record(2 ** 22 + 1, heartbeat_age=100.0, starttime=1)
    _write_lease(lease_path, rec)
    r = _kill_stale("--kill", "--lease-path", lease_path)
    assert r.returncode == 0
    assert not os.path.exists(lease_path)
    assert "cleared" in r.stdout


# -- bench.py probe path --------------------------------------------------

@pytest.fixture()
def bench(monkeypatch, tmp_path, lease_path):
    monkeypatch.setenv("MXTPU_XLA_CACHE", str(tmp_path / "cache"))
    monkeypatch.setenv("MXTPU_LEASE_PATH", lease_path)
    monkeypatch.setenv("MXTPU_BENCH_PLATFORM", "cpu")
    spec = importlib.util.spec_from_file_location(
        "bench_lease_test", os.path.join(ROOT, "bench.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    yield mod
    if mod._LEASE is not None:
        mod._LEASE.release()


def test_bench_probe_runs_through_lease(bench, lease_path):
    plat = bench._probe_devices(timeout_s=120, parent_init=False,
                                retries=1)
    assert plat == "cpu"
    assert bench._PROBE_INFO["probes"] == 1
    assert bench._PROBE_INFO["takeovers"] == 0
    assert bench._PROBE_INFO["lease_holder"]["pid"] == os.getpid()
    assert read_lease(lease_path)["pid"] == os.getpid()


def test_bench_probe_recovers_wedged_holder_without_force(
        bench, lease_path, monkeypatch):
    """ISSUE 7 acceptance: a simulated wedged holder (live, silent
    heartbeat) is recovered by the probe path itself — no kill_stale
    --force, no skip-and-pray ladder."""
    monkeypatch.setenv("MXTPU_LEASE_TAKEOVER_S", "2")
    monkeypatch.setenv("MXTPU_LEASE_KILL_GRACE_S", "1")
    holder = _sleeper()
    try:
        time.sleep(0.2)
        _write_lease(lease_path, _lease_record(holder.pid,
                                               heartbeat_age=100.0))
        plat = bench._probe_devices(timeout_s=120, parent_init=False,
                                    retries=1)
        assert plat == "cpu"
        assert bench._PROBE_INFO["takeovers"] == 1
        assert bench._PROBE_INFO["lease_holder"]["pid"] == holder.pid
        assert _proc_starttime(holder.pid) is None   # wedge cleared
        assert read_lease(lease_path)["pid"] == os.getpid()
    finally:
        holder.kill()
        holder.wait()


def test_bench_probe_live_holder_is_clean_exit(bench, lease_path,
                                               monkeypatch):
    """A holder doing real work: bench exits with a diagnosable error
    naming it instead of a doomed multi-probe retry ladder."""
    monkeypatch.setenv("MXTPU_LEASE_ACQUIRE_S", "1")
    holder = _sleeper()
    try:
        time.sleep(0.2)
        _write_lease(lease_path, _lease_record(holder.pid,
                                               takeover_s=600.0))
        with pytest.raises(SystemExit) as ei:
            bench._probe_devices(timeout_s=30, parent_init=False,
                                 retries=1)
        assert "live holder" in str(ei.value)
        assert str(holder.pid) in str(ei.value)
        assert holder.poll() is None
    finally:
        holder.kill()
        holder.wait()


def test_bench_probe_lease_optout(bench, lease_path, monkeypatch):
    """MXTPU_LEASE=0 is the documented escape hatch: bench probes
    without touching the lease file."""
    monkeypatch.setenv("MXTPU_LEASE", "0")
    plat = bench._probe_devices(timeout_s=120, parent_init=False,
                                retries=1)
    assert plat == "cpu"
    assert not os.path.exists(lease_path)


# -- serving lease hold ---------------------------------------------------

def test_model_server_reports_lease(lease_path, monkeypatch):
    from mxnet_tpu.serving import InferenceEngine, ModelServer
    import numpy as np
    monkeypatch.setenv("MXTPU_LEASE", "1")       # CPU backend: opt in
    monkeypatch.setenv("MXTPU_LEASE_PATH", lease_path)
    rng = np.random.RandomState(0)
    data = mx.sym.var("data")
    sym = mx.sym.FullyConnected(data=data, num_hidden=3, name="fc")
    params = {"fc_weight": mx.nd.array(rng.randn(3, 4).astype("float32")),
              "fc_bias": mx.nd.zeros((3,))}
    engine = InferenceEngine.from_symbol(sym, params, {}, {"data": (4,)},
                                         max_batch_size=4)
    server = ModelServer(engine, num_workers=1)
    server.start()
    try:
        st = server.stats()
        assert st["lease"] is not None and st["lease"]["held"]
        assert read_lease(lease_path)["pid"] == os.getpid()
        assert read_lease(lease_path)["what"] == "serving"
    finally:
        assert server.drain(timeout=30)
    from mxnet_tpu.resilience.lease import held_state
    assert held_state() is None
    assert not os.path.exists(lease_path)
    assert server.stats()["lease"] is None


def test_model_server_releases_lease_on_start_failure(lease_path,
                                                      monkeypatch):
    """A failed warmup must not keep squatting on the device lease for
    the process's remaining lifetime."""
    from mxnet_tpu.serving import InferenceEngine, ModelServer
    monkeypatch.setenv("MXTPU_LEASE", "1")
    monkeypatch.setenv("MXTPU_LEASE_PATH", lease_path)
    data = mx.sym.var("data")
    sym = mx.sym.FullyConnected(data=data, num_hidden=2, name="fc")
    params = {"fc_weight": mx.nd.ones((2, 3)), "fc_bias": mx.nd.zeros((2,))}
    engine = InferenceEngine.from_symbol(sym, params, {}, {"data": (3,)},
                                         max_batch_size=4)

    def boom(*a, **k):
        raise RuntimeError("warmup boom")

    monkeypatch.setattr(engine, "warmup", boom)
    server = ModelServer(engine, num_workers=1, warmup=True)
    with pytest.raises(RuntimeError, match="warmup boom"):
        server.start()
    from mxnet_tpu.resilience.lease import held_state
    assert held_state() is None
    assert not os.path.exists(lease_path)


def test_model_server_skips_lease_on_cpu_by_default(monkeypatch,
                                                    lease_path):
    from mxnet_tpu.serving import InferenceEngine, ModelServer
    import numpy as np
    monkeypatch.delenv("MXTPU_LEASE", raising=False)
    monkeypatch.setenv("MXTPU_LEASE_PATH", lease_path)
    data = mx.sym.var("data")
    sym = mx.sym.FullyConnected(data=data, num_hidden=2, name="fc")
    params = {"fc_weight": mx.nd.ones((2, 3)), "fc_bias": mx.nd.zeros((2,))}
    engine = InferenceEngine.from_symbol(sym, params, {}, {"data": (3,)},
                                         max_batch_size=4)
    with ModelServer(engine, num_workers=1) as server:
        assert server.stats()["lease"] is None
        assert not os.path.exists(lease_path)


# -- chaos_run exercises the new sites ------------------------------------

@pytest.mark.slow
def test_chaos_run_lease_acquire_site(tmp_path):
    """tools/chaos_run.py drives the lease.acquire site end to end: a
    fatal injection makes the wrapped acquire fail CLEANLY (no hang)."""
    lease = str(tmp_path / "dev.lease")
    prog = ("import os, sys; sys.path.insert(0, %r); "
            "from mxnet_tpu.resilience.lease import DeviceLease; "
            "DeviceLease(path=%r).acquire(timeout=5)" % (ROOT, lease))
    r = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "chaos_run.py"),
         "--chaos", "lease.acquire:kind=fatal", "--timeout", "120",
         "--expect", "error", "--", sys.executable, "-c", prog],
        capture_output=True, text=True, timeout=180, cwd=ROOT)
    assert r.returncode == 0, r.stdout + r.stderr
    out = json.loads(r.stdout.splitlines()[-1])
    assert out["outcome"] == "CLEAN_ERROR" and out["ok"]


# -- tools/perf_gate.py ---------------------------------------------------

def _write_stream(path, n=5, step_time=0.01, compile_seconds=0.05,
                  batch_size=8):
    with open(path, "w") as f:
        for i in range(n):
            f.write(json.dumps({
                "source": "train", "step": i, "step_time": step_time,
                "compile_count": 1, "compile_seconds": compile_seconds,
                "batch_size": batch_size}) + "\n")


def _perf_gate(*args):
    return subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "perf_gate.py")]
        + list(args), capture_output=True, text=True, timeout=120)


def test_perf_gate_passes_healthy_stream(tmp_path):
    p = str(tmp_path / "t.jsonl")
    _write_stream(p)
    r = _perf_gate(p, "--max-step-p95-s", "0.5",
                   "--max-compile-stall-s", "10",
                   "--min-samples-per-sec", "1", "--min-steps", "5")
    assert r.returncode == 0, r.stdout + r.stderr
    verdict = json.loads(r.stdout.splitlines()[-1])
    assert verdict["ok"] and verdict["breaches"] == []
    assert verdict["checks"]["step_p95_s"]["observed"] == 0.01


def test_perf_gate_fails_on_injected_breach(tmp_path):
    p = str(tmp_path / "t.jsonl")
    _write_stream(p, step_time=1.0)       # injected step-time regression
    r = _perf_gate(p, "--max-step-p95-s", "0.1")
    assert r.returncode == 1
    verdict = json.loads(r.stdout.splitlines()[-1])
    assert verdict["breaches"] == ["step_p95_s"]
    assert "BREACH step_p95_s" in r.stderr
    # compile-stall budget breaches too
    _write_stream(p, compile_seconds=10.0)
    r = _perf_gate(p, "--max-compile-stall-s", "1.0")
    assert r.returncode == 1
    assert "compile_stall_s" in json.loads(
        r.stdout.splitlines()[-1])["breaches"]


def test_perf_gate_rejects_malformed_and_missing(tmp_path):
    bad = str(tmp_path / "bad.jsonl")
    with open(bad, "w") as f:
        f.write('{"step_time": 0.1}\nnot json\n')
    assert _perf_gate(bad, "--max-step-p95-s", "1").returncode == 2
    assert _perf_gate(str(tmp_path / "absent.jsonl"),
                      "--max-step-p95-s", "1").returncode == 2
    empty = str(tmp_path / "empty.jsonl")
    open(empty, "w").close()
    assert _perf_gate(empty, "--max-step-p95-s", "1").returncode == 2


def test_perf_gate_requires_budgets_and_enough_steps(tmp_path):
    p = str(tmp_path / "t.jsonl")
    _write_stream(p, n=2)
    assert _perf_gate(p).returncode == 2          # no budgets: no gate
    r = _perf_gate(p, "--max-step-p95-s", "1", "--min-steps", "10")
    assert r.returncode == 1                      # truncated stream
    assert "steps" in json.loads(r.stdout.splitlines()[-1])["breaches"]
