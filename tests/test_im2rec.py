"""tools/im2rec.py end-to-end: folder -> .lst -> .rec -> ImageRecordIter.

Reference: tools/im2rec.py (list + pack), consumed by
iter_image_recordio_2.cc.
"""
import os
import subprocess
import sys

import numpy as np
from PIL import Image

import mxnet_tpu as mx

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_im2rec_roundtrip(tmp_path):
    root = tmp_path / "images"
    for ci, cls in enumerate(["cat", "dog"]):
        d = root / cls
        d.mkdir(parents=True)
        for i in range(3):
            arr = np.full((10, 11, 3), ci * 100 + i, np.uint8)
            Image.fromarray(arr).save(d / ("%d.png" % i))
    prefix = str(tmp_path / "data")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    tool = os.path.join(REPO, "tools", "im2rec.py")
    r = subprocess.run([sys.executable, tool, "--list", prefix, str(root)],
                       capture_output=True, text=True, env=env, timeout=120)
    assert r.returncode == 0, r.stderr
    assert os.path.exists(prefix + ".lst")
    r = subprocess.run([sys.executable, tool, prefix, str(root),
                        "--encoding", ".png"],
                       capture_output=True, text=True, env=env, timeout=120)
    assert r.returncode == 0, r.stderr
    assert os.path.exists(prefix + ".rec")
    assert os.path.exists(prefix + ".idx")

    it = mx.io.ImageRecordIter(path_imgrec=prefix + ".rec",
                               data_shape=(3, 10, 11), batch_size=3,
                               round_batch=False, preprocess_threads=2)
    labels = []
    for b in it:
        labels.extend(b.label[0].asnumpy().tolist())
        x = b.data[0].asnumpy()
        # pixel value encodes class*100+i; label must match class
        for s in range(x.shape[0]):
            cls = int(labels[-x.shape[0] + s])
            assert abs(x[s].mean() - (cls * 100 + x[s].mean() % 100)) < 3
    assert sorted(labels) == [0.0, 0.0, 0.0, 1.0, 1.0, 1.0]
    it.close()


def test_python_chunker_fallback(tmp_path, monkeypatch):
    """ImageRecordIter must work without the native lib (portable
    _PyRecordChunker path)."""
    from mxnet_tpu import recordio as rio
    from mxnet_tpu import io_record

    path = str(tmp_path / "f.rec")
    w = rio.MXRecordIO(path, "w")
    for i in range(5):
        img = np.full((6, 6, 3), i * 20, np.uint8)
        w.write(rio.pack_img(rio.IRHeader(0, float(i), i, 0), img,
                             img_fmt=".png"))
    w.close()

    from mxnet_tpu import _native

    def broken_loader(*a, **k):
        raise _native.NativeError("forced fallback")

    monkeypatch.setattr(_native, "PrefetchLoader", broken_loader)
    it = io_record.ImageRecordIter(path_imgrec=path, data_shape=(3, 6, 6),
                                   batch_size=2, round_batch=False,
                                   preprocess_threads=1)
    got = [int(v) for b in it for v in b.label[0].asnumpy()]
    assert got == [0, 1, 2, 3]
    it.close()
