"""CustomOp bridge tests (reference: python/mxnet/operator.py:426-1101,
tests/python/unittest/test_operator.py test_custom_op)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd


class Sigmoid(mx.operator.CustomOp):
    def forward(self, is_train, req, in_data, out_data, aux):
        y = 1.0 / (1.0 + mx.nd.exp(-in_data[0]))
        self.assign(out_data[0], req[0], y)

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        y = out_data[0]
        self.assign(in_grad[0], req[0], out_grad[0] * y * (1 - y))


@mx.operator.register("sigmoid_t")
class SigmoidProp(mx.operator.CustomOpProp):
    def __init__(self):
        super().__init__(need_top_grad=True)

    def infer_shape(self, in_shape):
        return in_shape, (in_shape[0],), ()

    def create_operator(self, ctx, shapes, dtypes):
        return Sigmoid()


class ScaledFC(mx.operator.CustomOp):
    def __init__(self, scale):
        self.scale = scale

    def forward(self, is_train, req, in_data, out_data, aux):
        x, w = in_data
        self.assign(out_data[0], req[0],
                    mx.nd.dot(x, w, transpose_b=True) * self.scale)

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        x, w = in_data
        og = out_grad[0] * self.scale
        self.assign(in_grad[0], req[0], mx.nd.dot(og, w))
        self.assign(in_grad[1], req[1],
                    mx.nd.dot(og, x, transpose_a=True))


@mx.operator.register("scaled_fc_t")
class ScaledFCProp(mx.operator.CustomOpProp):
    def __init__(self, scale="1.0", num_hidden="0"):
        super().__init__(need_top_grad=True)
        self.scale = float(scale)
        self.num_hidden = int(num_hidden)

    def list_arguments(self):
        return ["data", "weight"]

    def infer_shape(self, in_shape):
        d = in_shape[0]
        return [d, [self.num_hidden, d[1]]], \
            [[d[0], self.num_hidden]], ()

    def create_operator(self, ctx, shapes, dtypes):
        return ScaledFC(self.scale)


def test_custom_nd_forward():
    x_np = np.random.RandomState(0).randn(3, 4).astype("f")
    y = mx.nd.Custom(mx.nd.array(x_np), op_type="sigmoid_t")
    assert np.allclose(y.asnumpy(), 1 / (1 + np.exp(-x_np)), atol=1e-6)


def test_custom_nd_backward():
    x_np = np.random.RandomState(0).randn(3, 4).astype("f")
    x = mx.nd.array(x_np)
    x.attach_grad()
    with autograd.record():
        y = mx.nd.Custom(x, op_type="sigmoid_t")
        loss = y.sum()
    loss.backward()
    s = 1 / (1 + np.exp(-x_np))
    assert np.allclose(x.grad.asnumpy(), s * (1 - s), atol=1e-5)


def test_custom_symbol_forward_backward():
    x_np = np.random.RandomState(0).randn(3, 4).astype("f")
    data = mx.sym.var("data")
    out = mx.sym.Custom(data, op_type="sigmoid_t", name="sig")
    ex = out.bind(mx.cpu(), {"data": mx.nd.array(x_np)},
                  args_grad={"data": mx.nd.zeros((3, 4))})
    o = ex.forward(is_train=True)
    s = 1 / (1 + np.exp(-x_np))
    assert np.allclose(o[0].asnumpy(), s, atol=1e-6)
    ex.backward([mx.nd.ones((3, 4))])
    assert np.allclose(ex.grad_dict["data"].asnumpy(), s * (1 - s),
                       atol=1e-5)


def test_custom_kwargs_and_multi_input():
    rng = np.random.RandomState(1)
    x_np = rng.randn(4, 5).astype("f")
    w_np = rng.randn(3, 5).astype("f")
    x, w = mx.nd.array(x_np), mx.nd.array(w_np)
    x.attach_grad()
    w.attach_grad()
    with autograd.record():
        y = mx.nd.Custom(x, w, op_type="scaled_fc_t", scale=2.0,
                         num_hidden=3)
        loss = y.sum()
    loss.backward()
    assert np.allclose(y.asnumpy(), 2 * x_np @ w_np.T, atol=1e-4)
    assert np.allclose(x.grad.asnumpy(),
                       2 * np.ones((4, 3)) @ w_np, atol=1e-4)
    assert np.allclose(w.grad.asnumpy(),
                       2 * np.ones((4, 3)).T @ x_np, atol=1e-4)


def test_custom_symbol_auto_weight_var():
    """Unbound prop arguments become auto-named variables that
    simple_bind can shape-infer through the prop."""
    rng = np.random.RandomState(1)
    x_np = rng.randn(4, 5).astype("f")
    w_np = rng.randn(3, 5).astype("f")
    data = mx.sym.var("data")
    out = mx.sym.Custom(data=data, op_type="scaled_fc_t", scale=1.5,
                        num_hidden=3, name="sfc")
    assert "sfc_weight" in out.list_arguments()
    ex = out.simple_bind(mx.cpu(), data=(4, 5))
    ex.arg_dict["sfc_weight"][:] = mx.nd.array(w_np)
    ex.arg_dict["data"][:] = mx.nd.array(x_np)
    o = ex.forward(is_train=True)
    assert np.allclose(o[0].asnumpy(), 1.5 * x_np @ w_np.T, atol=1e-4)
    ex.backward([mx.nd.ones((4, 3))])
    assert np.allclose(ex.grad_dict["sfc_weight"].asnumpy(),
                       1.5 * np.ones((4, 3)).T @ x_np, atol=1e-4)


def test_custom_in_module_fit():
    """Custom op inside a Module training loop learns (end-to-end through
    executor jit + pure_callback)."""
    rng = np.random.RandomState(0)
    X = rng.randn(128, 6).astype("f")
    Y = (X.sum(1) > 0).astype("f")
    data = mx.sym.var("data")
    h = mx.sym.FullyConnected(data, num_hidden=16, name="fc1")
    h = mx.sym.Custom(h, op_type="sigmoid_t", name="act")
    h = mx.sym.FullyConnected(h, num_hidden=2, name="fc2")
    net = mx.sym.SoftmaxOutput(h, name="softmax")
    it = mx.io.NDArrayIter(X, Y, batch_size=32,
                           label_name="softmax_label")
    mod = mx.mod.Module(net, label_names=["softmax_label"])
    mod.fit(it, num_epoch=40,
            optimizer_params={"learning_rate": 1.0, "momentum": 0.9})
    it.reset()
    acc = mod.score(it, mx.metric.Accuracy())[0][1]
    assert acc > 0.9, acc


def test_custom_unregistered_raises():
    with pytest.raises(mx.MXNetError):
        mx.nd.Custom(mx.nd.zeros((2,)), op_type="never_registered_xyz")


def test_prop_infer_shape_may_omit_aux():
    """The reference accepts a 2-tuple (in_shapes, out_shapes) from
    CustomOpProp.infer_shape/infer_type — the form its own tutorial
    uses (reference operator.py:732-738). Pin that a tutorial-style
    prop works end-to-end."""
    class Swish(mx.operator.CustomOp):
        def forward(self, is_train, req, in_data, out_data, aux):
            z = in_data[0].asnumpy()
            self.assign(out_data[0], req[0],
                        mx.nd.array(z / (1 + np.exp(-z))))

        def backward(self, req, out_grad, in_data, out_data, in_grad,
                     aux):
            z = in_data[0].asnumpy()
            s = 1 / (1 + np.exp(-z))
            self.assign(in_grad[0], req[0],
                        mx.nd.array(out_grad[0].asnumpy()
                                    * (s + z * s * (1 - s))))

    @mx.operator.register("tutorial_swish")
    class SwishProp(mx.operator.CustomOpProp):
        def list_arguments(self):
            return ["data"]

        def list_outputs(self):
            return ["out"]

        def infer_shape(self, in_shape):
            return in_shape, [in_shape[0]]   # NO aux element

        def create_operator(self, ctx, shapes, dtypes):
            return Swish()

    x = mx.nd.array(np.linspace(-2, 2, 12).reshape(3, 4))
    y = mx.nd.Custom(x, op_type="tutorial_swish")
    z = np.asarray(x.asnumpy(), "float64")
    np.testing.assert_allclose(y.asnumpy(),
                               z / (1 + np.exp(-z)), rtol=1e-5)
    # and through autograd
    x.attach_grad()
    with mx.autograd.record():
        out = mx.nd.Custom(x, op_type="tutorial_swish").sum()
    out.backward()
    s = 1 / (1 + np.exp(-z))
    np.testing.assert_allclose(x.grad.asnumpy(),
                               s + z * s * (1 - s), rtol=1e-4)
