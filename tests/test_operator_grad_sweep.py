"""Parametrized numeric-gradient sweep over the elementwise op families
(reference: tests/python/unittest/test_operator.py's per-op checks via
test_utils.check_numeric_gradient — the backbone of the reference's op
test strategy, SURVEY.md §4.1).

Each case: finite differences vs autograd on a small tensor drawn from
a domain where the op is smooth (away from kinks/poles).
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import sym
from mxnet_tpu.test_utils import check_numeric_gradient

# (op name, input domain (lo, hi))
UNARY_SMOOTH = [
    ("sigmoid", (-2, 2)), ("tanh", (-2, 2)), ("exp", (-1, 1)),
    ("log", (0.5, 3)), ("log2", (0.5, 3)), ("log10", (0.5, 3)),
    ("log1p", (-0.4, 2)), ("expm1", (-1, 1)), ("sqrt", (0.5, 3)),
    ("cbrt", (0.5, 3)), ("rsqrt", (0.5, 3)), ("rcbrt", (0.5, 3)),
    ("square", (-2, 2)), ("reciprocal", (0.5, 3)),
    ("sin", (-2, 2)), ("cos", (-2, 2)), ("tan", (-0.5, 0.5)),
    ("arcsin", (-0.8, 0.8)), ("arccos", (-0.8, 0.8)),
    ("arctan", (-2, 2)), ("sinh", (-1.5, 1.5)), ("cosh", (-1.5, 1.5)),
    ("arcsinh", (-2, 2)), ("arccosh", (1.5, 3)),
    ("arctanh", (-0.7, 0.7)), ("erf", (-1.5, 1.5)),
    ("gamma", (1.5, 3)), ("gammaln", (1.5, 3)),
    ("softsign", (-2, 2)),
]

REDUCE_OPS = ["sum", "mean", "prod", "nansum", "norm"]

BINARY_OPS = [
    ("elemwise_add", (-2, 2)), ("elemwise_sub", (-2, 2)),
    ("elemwise_mul", (-2, 2)), ("elemwise_div", (0.5, 2)),
    ("broadcast_add", (-2, 2)), ("broadcast_mul", (-2, 2)),
    ("broadcast_div", (0.5, 2)), ("broadcast_power", (0.5, 2)),
    ("broadcast_hypot", (0.5, 2)),
]


def _rand(shape, lo, hi, seed):
    rng = np.random.RandomState(seed)
    return mx.nd.array(rng.uniform(lo, hi, shape).astype("float32"))


@pytest.mark.parametrize("op,domain", UNARY_SMOOTH,
                         ids=[o for o, _ in UNARY_SMOOTH])
def test_unary_gradient(op, domain):
    x = sym.var("x")
    out = getattr(sym, op)(x)
    data = _rand((3, 4), *domain, seed=hash(op) % 1000)
    check_numeric_gradient(out, {"x": data}, numeric_eps=1e-3,
                           rtol=5e-2, atol=1e-3)


@pytest.mark.parametrize("op", REDUCE_OPS)
def test_reduce_gradient(op):
    x = sym.var("x")
    out = getattr(sym, op)(x, axis=1) if op != "norm" else sym.norm(x)
    data = _rand((3, 4), 0.5, 2.0, seed=len(op))
    check_numeric_gradient(out, {"x": data}, numeric_eps=1e-3,
                           rtol=5e-2, atol=1e-3)


@pytest.mark.parametrize("op,domain", BINARY_OPS,
                         ids=[o for o, _ in BINARY_OPS])
def test_binary_gradient(op, domain):
    a, b = sym.var("a"), sym.var("b")
    out = getattr(sym, op)(a, b)
    bshape = (3, 4) if not op.startswith("broadcast") else (1, 4)
    loc = {"a": _rand((3, 4), *domain, seed=1),
           "b": _rand(bshape, *domain, seed=2)}
    check_numeric_gradient(out, loc, numeric_eps=1e-3,
                           rtol=5e-2, atol=1e-3)


@pytest.mark.parametrize("act", ["relu", "sigmoid", "tanh", "softrelu",
                                 "softsign"])
def test_activation_gradient(act):
    x = sym.var("x")
    out = sym.Activation(x, act_type=act)
    # keep away from relu's kink at 0
    data = _rand((3, 4), 0.3, 2.0, seed=3)
    check_numeric_gradient(out, {"x": data}, numeric_eps=1e-3,
                           rtol=5e-2, atol=1e-3)


@pytest.mark.parametrize("op", ["softmax", "log_softmax"])
def test_softmax_gradient(op):
    x = sym.var("x")
    out = getattr(sym, op)(x, axis=-1)
    data = _rand((3, 5), -2, 2, seed=4)
    check_numeric_gradient(out, {"x": data}, numeric_eps=1e-3,
                           rtol=5e-2, atol=1e-3)


KINK_OPS = [
    # ops with kinks/selections: domains chosen so no tie/kink is near
    ("abs", (0.5, 2.0)),
    ("negative", (-2, 2)),
    ("relu", (0.3, 2.0)),
]


@pytest.mark.parametrize("op,domain", KINK_OPS,
                         ids=[o for o, _ in KINK_OPS])
def test_kink_op_gradient_away_from_kink(op, domain):
    x = sym.var("x")
    out = getattr(sym, op)(x)
    data = _rand((3, 4), *domain, seed=11)
    check_numeric_gradient(out, {"x": data}, numeric_eps=1e-3,
                           rtol=5e-2, atol=1e-3)


def test_broadcast_maximum_minimum_gradient():
    a, b = sym.var("a"), sym.var("b")
    # disjoint domains: a in (2,3), b in (0,1) — argmax never flips
    loc = {"a": _rand((3, 4), 2.0, 3.0, seed=7),
           "b": _rand((1, 4), 0.0, 1.0, seed=8)}
    for op in ("broadcast_maximum", "broadcast_minimum"):
        out = getattr(sym, op)(a, b)
        check_numeric_gradient(out, loc, numeric_eps=1e-3,
                               rtol=5e-2, atol=1e-3)


def test_clip_gradient_inside_range():
    x = sym.var("x")
    out = sym.clip(x, a_min=-10.0, a_max=10.0)
    data = _rand((3, 4), -2, 2, seed=9)
    check_numeric_gradient(out, {"x": data}, numeric_eps=1e-3,
                           rtol=5e-2, atol=1e-3)


def test_where_gradient():
    c, a, b = sym.var("c"), sym.var("a"), sym.var("b")
    out = sym.where(c, a, b)
    rng = np.random.RandomState(10)
    loc = {"c": mx.nd.array((rng.rand(3, 4) > 0.5).astype("float32")),
           "a": _rand((3, 4), -2, 2, seed=12),
           "b": _rand((3, 4), -2, 2, seed=13)}
    check_numeric_gradient(out, loc, grad_nodes=["a", "b"],
                           numeric_eps=1e-3, rtol=5e-2, atol=1e-3)


# nn ops the round-4 coverage audit exercises forward-only: give them
# numeric-gradient checks too (reference test_operator.py pattern)
def test_lrn_gradient():
    data = mx.sym.var("data")
    out = mx.sym.sum(mx.sym.LRN(data, nsize=3, alpha=0.01))
    check_numeric_gradient(out, {"data": _rand((2, 5, 4, 4), 0.5, 2, 0)
                                 .asnumpy()}, rtol=2e-2, atol=1e-2)


def test_upsampling_gradient():
    data = mx.sym.var("data")
    out = mx.sym.sum(mx.sym.square(
        mx.sym.UpSampling(data, scale=2, sample_type="nearest")))
    check_numeric_gradient(out, {"data": _rand((1, 2, 3, 3), -1, 1, 1)
                                 .asnumpy()}, rtol=2e-2, atol=1e-2)


def test_instancenorm_gradient():
    data = mx.sym.var("data")
    out = mx.sym.sum(mx.sym.square(mx.sym.InstanceNorm(
        data, mx.sym.var("gamma"), mx.sym.var("beta"))))
    check_numeric_gradient(
        out, {"data": _rand((2, 3, 4, 4), -1, 1, 2).asnumpy(),
              "gamma": np.ones((3,), "float32"),
              "beta": np.zeros((3,), "float32")},
        rtol=3e-2, atol=2e-2)


def test_l2normalization_gradient():
    data = mx.sym.var("data")
    out = mx.sym.sum(mx.sym.square(
        mx.sym.L2Normalization(data, mode="channel")))
    check_numeric_gradient(out, {"data": _rand((2, 3, 4), 0.5, 2, 3)
                                 .asnumpy()}, rtol=2e-2, atol=1e-2)


def test_smooth_l1_gradient():
    data = mx.sym.var("data")
    out = mx.sym.sum(mx.sym.smooth_l1(data, scalar=1.0))
    # away from the |x|=1 kink
    check_numeric_gradient(out, {"data": _rand((3, 4), -0.8, 0.8, 4)
                                 .asnumpy()}, rtol=2e-2, atol=1e-2)


def test_correlation_gradient():
    a, b = mx.sym.var("a"), mx.sym.var("b")
    out = mx.sym.sum(mx.sym.square(mx.sym.Correlation(
        a, b, kernel_size=1, max_displacement=1, pad_size=1)))
    loc = {"a": _rand((1, 2, 5, 5), -1, 1, 5).asnumpy(),
           "b": _rand((1, 2, 5, 5), -1, 1, 6).asnumpy()}
    check_numeric_gradient(out, loc, rtol=3e-2, atol=2e-2)


def test_deconvolution_gradient():
    data, w = mx.sym.var("data"), mx.sym.var("w")
    out = mx.sym.sum(mx.sym.square(mx.sym.Deconvolution(
        data, w, kernel=(2, 2), num_filter=2)))
    loc = {"data": _rand((1, 2, 3, 3), -1, 1, 7).asnumpy(),
           "w": _rand((2, 2, 2, 2), -1, 1, 8).asnumpy()}
    check_numeric_gradient(out, loc, rtol=3e-2, atol=2e-2)
