"""Expert parallelism (MoE over an 'ep' mesh axis).

The reference has no EP (SURVEY.md §2.3) — this is the TPU-native
upgrade; tests pin the sharded all_to_all dataflow against a
single-device oracle with identical routing semantics.
"""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

from mxnet_tpu.parallel import (make_mesh, shard_on, replicated,
                                moe_ffn, moe_ffn_dense, moe_gating,
                                ExpertParallelMoE)


def _params(rng, D=8, E=8, H=16):
    gate_w = jnp.asarray(rng.randn(D, E) * 0.5, jnp.float32)
    w1 = jnp.asarray(rng.randn(E, D, H) * 0.2, jnp.float32)
    b1 = jnp.asarray(rng.randn(E, H) * 0.1, jnp.float32)
    w2 = jnp.asarray(rng.randn(E, H, D) * 0.2, jnp.float32)
    b2 = jnp.asarray(rng.randn(E, D) * 0.1, jnp.float32)
    return gate_w, w1, b1, w2, b2


def test_gating_capacity_and_balance():
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(16, 8), jnp.float32)
    gate_w, *_ = _params(rng)
    dispatch, combine, aux = moe_gating(x, gate_w, top_k=2, capacity=3)
    # each slot holds at most one token; each token fills <= k slots
    assert dispatch.shape == (16, 8, 3)
    assert float(dispatch.sum(axis=0).max()) <= 1.0 + 1e-6
    per_token = dispatch.sum(axis=(1, 2))
    assert float(per_token.max()) <= 2 + 1e-6
    # combine weights of a kept token pair sum to 1 (normalize=True)
    full = moe_gating(x, gate_w, top_k=2, capacity=16)[1]
    s = np.asarray(full.sum(axis=(1, 2)))
    assert np.allclose(s, 1.0, atol=1e-5)
    assert float(aux) >= 1.0 - 1e-5  # balanced == 1, skew > 1


@pytest.mark.parametrize("top_k", [1, 2])
def test_moe_ffn_matches_dense_oracle(top_k):
    n = 8
    mesh = make_mesh({"ep": n})
    rng = np.random.RandomState(1)
    N, D, E = 16, 8, 8
    x = jnp.asarray(rng.randn(N, D), jnp.float32)
    gate_w, w1, b1, w2, b2 = _params(rng, D, E)
    # capacity_factor high enough that nothing is dropped on either
    # path: per-device worst case is all k*N_local picks on one expert
    cf = float(E)  # C = ceil(cf*k*N_local/E) = k*N_local
    xs = jax.device_put(x, shard_on(mesh, "ep", 0))
    out, aux = moe_ffn(xs, gate_w, w1, b1, w2, b2, mesh, "ep",
                       top_k=top_k, capacity_factor=cf)
    # oracle: shard-local routing == global routing when nothing drops
    ref, _ = moe_ffn_dense(x, gate_w, w1, b1, w2, b2, top_k=top_k)
    assert np.allclose(np.asarray(out), np.asarray(ref),
                       rtol=1e-4, atol=1e-5)
    assert np.isfinite(float(aux))


def test_moe_ffn_capacity_drops_are_partial_not_nan():
    n = 8
    mesh = make_mesh({"ep": n})
    rng = np.random.RandomState(2)
    x = jax.device_put(jnp.asarray(rng.randn(16, 8), jnp.float32),
                       shard_on(mesh, "ep", 0))
    gate_w, w1, b1, w2, b2 = _params(rng)
    out, aux = moe_ffn(x, gate_w, w1, b1, w2, b2, mesh, "ep",
                       top_k=2, capacity_factor=0.5)
    o = np.asarray(out)
    assert o.shape == (16, 8) and np.isfinite(o).all()
    assert np.isfinite(float(aux))


def test_moe_ffn_differentiable_and_jittable():
    n = 8
    mesh = make_mesh({"ep": n})
    rng = np.random.RandomState(3)
    x = jnp.asarray(rng.randn(16, 8), jnp.float32)
    gate_w, w1, b1, w2, b2 = _params(rng)

    @jax.jit
    def loss(params, xx):
        gw, a1, c1, a2, c2 = params
        out, aux = moe_ffn(xx, gw, a1, c1, a2, c2, mesh, "ep",
                           top_k=2, capacity_factor=8.0)
        return jnp.sum(out ** 2) + 0.01 * aux

    g = jax.grad(loss)((gate_w, w1, b1, w2, b2),
                       jax.device_put(x, shard_on(mesh, "ep", 0)))
    for gi in g:
        assert np.isfinite(np.asarray(gi)).all()
    # expert weights actually receive gradient
    assert float(jnp.abs(g[1]).max()) > 0


def test_expert_parallel_moe_ndarray_wrapper():
    import mxnet_tpu as mx
    n = 8
    mesh = make_mesh({"ep": n})
    rng = np.random.RandomState(4)
    gate_w, w1, b1, w2, b2 = _params(rng)
    layer = ExpertParallelMoE(mesh, capacity_factor=8.0)
    x = mx.nd.array(rng.randn(16, 8).astype("float32"))
    out, aux = layer(x, mx.nd.NDArray(gate_w), mx.nd.NDArray(w1),
                     mx.nd.NDArray(b1), mx.nd.NDArray(w2),
                     mx.nd.NDArray(b2))
    assert out.shape == (16, 8)
    ref, _ = moe_ffn_dense(jnp.asarray(x.asnumpy()), gate_w, w1, b1,
                           w2, b2, top_k=2)
    assert np.allclose(out.asnumpy(), np.asarray(ref),
                       rtol=1e-4, atol=1e-5)


def test_contrib_ring_attention_op_mesh_vs_fallback():
    import mxnet_tpu as mx
    from mxnet_tpu.parallel import use_mesh
    rng = np.random.RandomState(5)
    mk = lambda: mx.nd.array(rng.randn(1, 2, 16, 8).astype("float32"))
    q, k, v = mk(), mk(), mk()
    out_local = mx.nd.contrib.RingAttention(q, k, v, causal=True)
    mesh = make_mesh({"sp": 8})
    sh = shard_on(mesh, "sp", 2, 4)
    put = lambda a: mx.nd.NDArray(
        jax.device_put(jnp.asarray(a.asnumpy()), sh))
    with use_mesh(mesh):
        out_ring = mx.nd.contrib.RingAttention(put(q), put(k), put(v),
                                               causal=True)
    assert np.allclose(out_ring.asnumpy(), out_local.asnumpy(),
                       atol=1e-4)


def test_contrib_moe_ffn_op_mesh_vs_dense():
    import mxnet_tpu as mx
    from mxnet_tpu.parallel import use_mesh
    rng = np.random.RandomState(6)
    gate_w, w1, b1, w2, b2 = _params(rng)
    x = rng.randn(16, 8).astype("float32")
    o_dense, _ = mx.nd.contrib.MoEFFN(
        mx.nd.array(x), mx.nd.NDArray(gate_w), mx.nd.NDArray(w1),
        mx.nd.NDArray(b1), mx.nd.NDArray(w2), mx.nd.NDArray(b2),
        capacity_factor=8.0)
    mesh = make_mesh({"ep": 8})
    ep = shard_on(mesh, "ep", 0)
    pe = lambda a: mx.nd.NDArray(jax.device_put(a, ep))
    gwr = mx.nd.NDArray(jax.device_put(gate_w, replicated(mesh)))
    with use_mesh(mesh):
        o_ep, aux = mx.nd.contrib.MoEFFN(
            pe(jnp.asarray(x)), gwr, pe(w1), pe(b1), pe(w2), pe(b2),
            capacity_factor=8.0)
    assert np.allclose(o_ep.asnumpy(), o_dense.asnumpy(), atol=1e-4)
    assert np.isfinite(float(aux.asnumpy()))
