"""Parallelism tests on the 8-device virtual CPU mesh (conftest.py).

Mirrors the reference's strategy (SURVEY.md §4.5): multi-device semantics
validated without a cluster — here via xla_force_host_platform_device_count,
the way the reference runs dist kvstore tests with local processes.
"""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon
from mxnet_tpu.gluon import nn
from mxnet_tpu.parallel import (make_mesh, ShardedTrainer, ring_attention,
                                local_attention, pipeline_apply,
                                PartitionSpec, shard_on, put_sharded)


def test_make_mesh():
    mesh = make_mesh({"dp": 2, "tp": -1})
    assert mesh.shape["dp"] == 2
    assert mesh.shape["tp"] == 4


def test_sharded_trainer_dp_convergence():
    np.random.seed(0)
    X = np.random.randn(64, 10).astype("float32")
    w = np.random.randn(10, 1).astype("float32")
    Y = X @ w
    net = nn.Dense(1)
    net.initialize()
    net(mx.nd.array(X[:2]))  # materialize shapes
    mesh = make_mesh({"dp": 8})
    st = ShardedTrainer(net, lambda o, l: gluon.loss.L2Loss()(o, l),
                        "sgd", {"learning_rate": 0.2, "momentum": 0.9},
                        mesh=mesh)
    for _ in range(60):
        loss = st.step(X, Y)
    assert float(loss.asscalar()) < 1e-2
    st.copy_params_to_net()
    out = net(mx.nd.array(X)).asnumpy()
    assert np.mean((out - Y) ** 2) < 1e-2


def test_sharded_trainer_matches_single_device():
    """DP over 8 devices must equal single-device training (the
    dist_sync_kvstore.py bitwise-determinism check, tolerance-tiered)."""
    np.random.seed(1)
    X = np.random.randn(16, 6).astype("float32")
    Y = (X.sum(1, keepdims=True) > 0).astype("float32")

    def build():
        np.random.seed(42)
        net = nn.Dense(1, weight_initializer="zeros",
                       bias_initializer="zeros")
        net.initialize()
        net(mx.nd.array(X[:2]))
        return net

    losses = {}
    for name, mesh in [("single", make_mesh({"dp": 1})),
                       ("dp8", make_mesh({"dp": 8}))]:
        net = build()
        st = ShardedTrainer(net, lambda o, l: gluon.loss.L2Loss()(o, l),
                            "sgd", {"learning_rate": 0.1, "momentum": 0.0},
                            mesh=mesh)
        for _ in range(5):
            l = st.step(X, Y)
        losses[name] = float(l.asscalar())
    assert np.isclose(losses["single"], losses["dp8"], rtol=1e-5), losses


def test_sharded_trainer_tensor_parallel():
    """Dense weight split over 'tp'; XLA inserts the collectives."""
    np.random.seed(2)
    X = np.random.randn(32, 8).astype("float32")
    Y = np.random.randn(32, 4).astype("float32")
    net = nn.HybridSequential(prefix="tpnet_")
    with net.name_scope():
        net.add(nn.Dense(16, activation="relu"))
        net.add(nn.Dense(4))
    net.initialize()
    net(mx.nd.array(X[:2]))
    mesh = make_mesh({"dp": 2, "tp": 4})
    rules = [(r"dense0_weight", PartitionSpec("tp", None)),
             (r"dense0_bias", PartitionSpec("tp")),
             (r"dense1_weight", PartitionSpec(None, "tp"))]
    st = ShardedTrainer(net, lambda o, l: gluon.loss.L2Loss()(o, l),
                        "adam", {"learning_rate": 0.05},
                        mesh=mesh, param_rules=rules)
    first = float(st.step(X, Y).asscalar())
    for _ in range(50):
        loss = st.step(X, Y)
    assert float(loss.asscalar()) < first * 0.5
    # param really is sharded over tp
    w = st.params["tpnet_dense0_weight"]
    assert w.sharding.spec == PartitionSpec("tp", None)


def test_ring_attention_matches_local():
    mesh = make_mesh({"sp": 8})
    B, H, T, D = 2, 4, 32, 16
    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(B, H, T, D), jnp.float32)
    k = jnp.asarray(rng.randn(B, H, T, D), jnp.float32)
    v = jnp.asarray(rng.randn(B, H, T, D), jnp.float32)
    ref = local_attention(q, k, v)
    sh = shard_on(mesh, "sp", dim=2, ndim=4)
    qs, ks, vs = (jax.device_put(x, sh) for x in (q, k, v))
    out = ring_attention(qs, ks, vs, mesh, "sp")
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)


def test_ring_attention_causal():
    mesh = make_mesh({"sp": 4})
    B, H, T, D = 1, 2, 16, 8
    rng = np.random.RandomState(1)
    q = jnp.asarray(rng.randn(B, H, T, D), jnp.float32)
    k = jnp.asarray(rng.randn(B, H, T, D), jnp.float32)
    v = jnp.asarray(rng.randn(B, H, T, D), jnp.float32)
    ref = local_attention(q, k, v, causal=True)
    sh = shard_on(mesh, "sp", dim=2, ndim=4)
    qs, ks, vs = (jax.device_put(x, sh) for x in (q, k, v))
    out = ring_attention(qs, ks, vs, mesh, "sp", causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)


def test_pipeline_apply():
    """4-stage pipeline of affine stages == sequential application."""
    mesh = make_mesh({"pp": 4})
    n_stages, D = 4, 8
    rng = np.random.RandomState(3)
    Ws = jnp.asarray(rng.randn(n_stages, D, D) * 0.5, jnp.float32)
    bs = jnp.asarray(rng.randn(n_stages, D) * 0.1, jnp.float32)

    def stage_fn(params, x):
        W, b = params
        return jnp.tanh(x @ W + b)

    x = jnp.asarray(rng.randn(16, D), jnp.float32)
    out = pipeline_apply(stage_fn, (Ws, bs), x, mesh, "pp",
                         n_microbatches=4)
    ref = x
    for i in range(n_stages):
        ref = jnp.tanh(ref @ Ws[i] + bs[i])
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-5)


def test_dist_kvstore_single_process():
    kv = mx.kv.create("tpu_dist")
    assert kv.rank == 0 and kv.num_workers == 1
    kv.init(3, mx.nd.ones((2, 2)))
    kv.push(3, mx.nd.full((2, 2), 4.0))
    out = mx.nd.zeros((2, 2))
    kv.pull(3, out=out)
    np.testing.assert_allclose(out.asnumpy(), np.full((2, 2), 4.0))
    kv.barrier()


def test_put_sharded_batch():
    mesh = make_mesh({"dp": 8})
    x = mx.nd.ones((16, 4))
    xs = put_sharded(x, shard_on(mesh, "dp", 0, 2))
    assert xs.shape == (16, 4)


def test_step_many_matches_sequential_steps():
    # K fused steps in one scanned program == K separate step() calls
    from mxnet_tpu.gluon import nn as gnn
    from mxnet_tpu import gluon

    def build():
        m = gnn.HybridSequential()
        m.add(gnn.Conv2D(4, 3, padding=1), gnn.BatchNorm(),
              gnn.Activation("relu"), gnn.GlobalAvgPool2D(),
              gnn.Dense(10))
        m.initialize()
        m(mx.nd.zeros((1, 3, 8, 8)))
        return m

    net = build()
    loss = gluon.loss.SoftmaxCrossEntropyLoss()
    mesh = make_mesh({"dp": 8})
    rng = np.random.RandomState(0)
    x = rng.randn(16, 3, 8, 8).astype("float32")
    y = (np.arange(16) % 10).astype("float32")
    kw = dict(optimizer="sgd",
              optimizer_params={"learning_rate": 0.1, "momentum": 0.9},
              mesh=mesh)
    st1 = ShardedTrainer(net, lambda o, l: loss(o, l), **kw)
    seq = [float(st1.step(x, y).asscalar()) for _ in range(5)]
    for unroll in (1, 3):
        st2 = ShardedTrainer(net, lambda o, l: loss(o, l), **kw)
        many = st2.step_many(x, y, n_steps=5, unroll=unroll).asnumpy()
        np.testing.assert_allclose(seq, many, rtol=1e-5, atol=1e-6)
    assert st2._step_count == 5


def test_weight_update_sharding_matches_replicated():
    # ZeRO-1-style optimizer-state sharding (SURVEY 2.3 weight-update
    # sharding): same numerics, momentum rows sharded over dp
    from mxnet_tpu.gluon import nn as gnn
    from mxnet_tpu import gluon
    from jax.sharding import PartitionSpec as P

    net = gnn.HybridSequential()
    net.add(gnn.Dense(32, activation="relu"), gnn.Dense(10))
    net.initialize()
    net(mx.nd.zeros((1, 16)))
    loss = gluon.loss.SoftmaxCrossEntropyLoss()
    mesh = make_mesh({"dp": 8})
    rng = np.random.RandomState(0)
    x = rng.randn(16, 16).astype("float32")
    y = (np.arange(16) % 10).astype("float32")
    kw = dict(optimizer="adam", optimizer_params={"learning_rate": 0.01},
              mesh=mesh)
    a = ShardedTrainer(net, lambda o, l: loss(o, l), **kw)
    b = ShardedTrainer(net, lambda o, l: loss(o, l),
                       shard_optimizer_state=True, **kw)
    la = [float(a.step(x, y).asscalar()) for _ in range(3)]
    lb = [float(b.step(x, y).asscalar()) for _ in range(3)]
    np.testing.assert_allclose(la, lb, rtol=1e-5, atol=1e-6)
    # momentum for a (32,16) dense weight is actually sharded over dp
    m = b._opt_state["m"]["dense2_weight"] \
        if "dense2_weight" in b._opt_state["m"] else None
    if m is None:  # prefix numbering depends on prior tests
        key = [k for k in b._opt_state["m"] if k.endswith("_weight")][0]
        m = b._opt_state["m"][key]
    assert m.sharding.spec == P("dp"), m.sharding
    # params remain replicated for compute
    k0 = [k for k in b._params if k.endswith("_weight")][0]
    assert b._params[k0].sharding.spec == P()


def test_params_property_survives_next_step():
    # step() donates internal buffers; the public accessor must return
    # copies that stay valid afterwards
    from mxnet_tpu.gluon import nn as gnn
    from mxnet_tpu import gluon
    net = gnn.HybridSequential()
    net.add(gnn.Dense(4))
    net.initialize()
    net(mx.nd.zeros((1, 3)))
    loss = gluon.loss.L2Loss()
    st = ShardedTrainer(net, lambda o, l: loss(o, l), "sgd",
                        {"learning_rate": 0.1, "momentum": 0.9},
                        mesh=make_mesh({"dp": 8}))
    x = np.random.RandomState(0).randn(8, 3).astype("f")
    y = np.zeros((8, 4), "f")
    st.step(x, y)
    snap = st.params
    st.step(x, y)
    for v in snap.values():
        assert np.isfinite(np.asarray(v)).all()  # not deleted


def test_sgd_momentum_zero_carries_no_state_and_trains():
    from mxnet_tpu.gluon import nn as gnn
    from mxnet_tpu import gluon
    net = gnn.HybridSequential()
    net.add(gnn.Dense(8, activation="relu"), gnn.Dense(10))
    net.initialize()
    net(mx.nd.zeros((1, 4)))
    loss = gluon.loss.SoftmaxCrossEntropyLoss()
    st = ShardedTrainer(net, lambda o, l: loss(o, l), "sgd",
                        {"learning_rate": 0.2},
                        mesh=make_mesh({"dp": 8}))
    assert st._opt_state == {}
    rng = np.random.RandomState(0)
    x = rng.randn(16, 4).astype("f")
    y = (np.arange(16) % 10).astype("f")
    ls = [float(st.step(x, y).asscalar()) for _ in range(5)]
    assert ls[-1] < ls[0]


def test_batch_axis_one_with_rank1_labels():
    # TNC-layout data (batch on axis 1) alongside (B,) labels: the label
    # sharding must clamp to its own rank instead of erroring
    from mxnet_tpu.gluon import nn as gnn, HybridBlock
    from mxnet_tpu import gluon

    class MeanDense(HybridBlock):
        def __init__(self, **kw):
            super().__init__(**kw)
            with self.name_scope():
                self.out = gnn.Dense(10)

        def hybrid_forward(self, F, x):  # x: (T, B, C)
            return self.out(F.mean(x, axis=0))

    net = MeanDense()
    net.initialize()
    net(mx.nd.zeros((5, 2, 4)))
    loss = gluon.loss.SoftmaxCrossEntropyLoss()
    st = ShardedTrainer(net, lambda o, l: loss(o, l), "sgd",
                        {"learning_rate": 0.1}, batch_axis=1,
                        mesh=make_mesh({"dp": 8}))
    x = np.random.RandomState(0).randn(5, 16, 4).astype("f")
    y = (np.arange(16) % 10).astype("f")
    l = float(st.step(x, y).asscalar())
    assert np.isfinite(l)


def test_compressed_step_predict_mode_and_rng_net():
    # compressed path with (a) a BN net in predict aux_mode (no aux
    # updates emitted) and (b) a dropout net (per-shard folded RNG)
    from mxnet_tpu.gluon import nn as gnn
    from mxnet_tpu import gluon
    loss = gluon.loss.SoftmaxCrossEntropyLoss()
    gc = {"gradient_compression": {"type": "2bit", "threshold": 0.1}}
    rng = np.random.RandomState(0)
    x = rng.randn(16, 6).astype("f")
    y = (np.arange(16) % 4).astype("f")

    bn_net = gnn.HybridSequential()
    bn_net.add(gnn.Dense(8), gnn.BatchNorm(), gnn.Dense(4))
    bn_net.initialize()
    bn_net(mx.nd.zeros((1, 6)))
    st = ShardedTrainer(bn_net, lambda o, l: loss(o, l), "sgd",
                        {"learning_rate": 0.1}, aux_mode="predict",
                        mesh=make_mesh({"dp": 8}), **gc)
    assert np.isfinite(float(st.step(x, y).asscalar()))

    do_net = gnn.HybridSequential()
    do_net.add(gnn.Dense(8, activation="relu"), gnn.Dropout(0.5),
               gnn.Dense(4))
    do_net.initialize()
    do_net(mx.nd.zeros((1, 6)))
    st2 = ShardedTrainer(do_net, lambda o, l: loss(o, l), "sgd",
                         {"learning_rate": 0.1},
                         mesh=make_mesh({"dp": 8}), **gc)
    ls = [float(st2.step(x, y).asscalar()) for _ in range(3)]
    assert all(np.isfinite(v) for v in ls)


def test_batch_axis_one_rank1_labels_with_compression():
    # the compressed path's jit in_shardings must clamp too
    from mxnet_tpu.gluon import nn as gnn, HybridBlock
    from mxnet_tpu import gluon

    class MeanDense(HybridBlock):
        def __init__(self, **kw):
            super().__init__(**kw)
            with self.name_scope():
                self.out = gnn.Dense(10)

        def hybrid_forward(self, F, x):
            return self.out(F.mean(x, axis=0))

    net = MeanDense()
    net.initialize()
    net(mx.nd.zeros((5, 2, 4)))
    loss = gluon.loss.SoftmaxCrossEntropyLoss()
    st = ShardedTrainer(net, lambda o, l: loss(o, l), "sgd",
                        {"learning_rate": 0.1}, batch_axis=1,
                        mesh=make_mesh({"dp": 8}),
                        gradient_compression={"type": "2bit",
                                              "threshold": 0.1})
    x = np.random.RandomState(0).randn(5, 16, 4).astype("f")
    y = (np.arange(16) % 10).astype("f")
    assert np.isfinite(float(st.step(x, y).asscalar()))


def test_sgd_update_passes_state_through_at_zero_momentum():
    from mxnet_tpu.parallel.data_parallel import sgd_update
    import jax.numpy as jnp
    params = {"w": jnp.ones((3,))}
    grads = {"w": jnp.full((3,), 0.5)}
    state = {"w": jnp.zeros((3,))}
    new_p, new_s = sgd_update(params, grads, state, lr=0.1, momentum=0.0)
    assert new_s is state  # structure preserved for schedule callers
    np.testing.assert_allclose(np.asarray(new_p["w"]), 0.95)


def test_remat_matches_plain_step():
    """remat=True (jax.checkpoint around the traced graph) must change
    memory behavior only — identical numerics to the plain step
    (reference analog: MXNET_BACKWARD_DO_MIRROR)."""
    import numpy as np
    import jax
    from mxnet_tpu import nd, gluon
    from mxnet_tpu.parallel import make_mesh, ShardedTrainer

    rng = np.random.RandomState(0)
    X = rng.rand(16, 6).astype("float32")
    y = (X.sum(1) > 3).astype("float32")
    mesh = make_mesh({"dp": len(jax.devices())})

    def train(remat):
        import mxnet_tpu as mx
        mx.random.seed(42)  # identical init across variants
        net = gluon.nn.Sequential()
        net.add(gluon.nn.Dense(8, activation="relu"), gluon.nn.Dense(2))
        net.initialize()
        net(nd.zeros((1, 6)))
        loss = gluon.loss.SoftmaxCrossEntropyLoss()
        st = ShardedTrainer(net, lambda o, l: loss(o, l), "sgd",
                            {"learning_rate": 0.1}, mesh=mesh,
                            remat=remat)
        return [float(st.step(nd.array(X), nd.array(y)).asnumpy())
                for _ in range(4)]

    plain = train(False)
    remat = train(True)
    assert np.allclose(plain, remat, rtol=1e-5), (plain, remat)
    sel = train("dots_with_no_batch_dims_saveable")
    assert np.allclose(plain, sel, rtol=1e-5), (plain, sel)


def test_input_specs_override_matches_default():
    """input_specs shards the sequence axis of the inputs over 'sp' at
    ingest; numerics must equal the batch-default sharding."""
    import jax
    np.random.seed(0)
    B, T, D = 4, 16, 8
    X = np.random.randn(B, T, D).astype("float32")
    Y = np.random.randn(B, T, 1).astype("float32")

    net = nn.Dense(1, flatten=False)
    net.initialize()
    net(mx.nd.array(X[:1]))

    def build(input_specs=None):
        mesh = make_mesh({"dp": 2, "sp": 4})
        return ShardedTrainer(net, lambda o, l: gluon.loss.L2Loss()(o, l),
                              "sgd", {"learning_rate": 0.1}, mesh=mesh,
                              input_specs=input_specs)

    a = build()
    b = build(input_specs={"data": ("dp", "sp"),
                           "label": ("dp", "sp")})
    la = [float(a.step(X, Y).asscalar()) for _ in range(3)]
    lb = [float(b.step(X, Y).asscalar()) for _ in range(3)]
    assert np.allclose(la, lb, rtol=1e-6), (la, lb)
    # the staged input really is sequence-sharded
    sh = b._input_sharding("data", 3)
    assert "sp" in str(sh.spec)
