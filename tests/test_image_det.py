"""Detection input path: label-aware augmenters + ImageDetIter over a
real packed record file (reference: python/mxnet/image/detection.py,
src/io/iter_image_det_recordio.cc; reference tests:
tests/python/unittest/test_image.py TestImageDetIter)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, recordio
from mxnet_tpu.image_det import (DetHorizontalFlipAug, DetRandomCropAug,
                                 DetRandomPadAug, CreateDetAugmenter)

RNG = np.random.RandomState(5)


def _scene(size=32, n_obj=1):
    img = np.zeros((size, size, 3), np.uint8)
    objs = []
    for _ in range(n_obj):
        w = RNG.randint(8, 16)
        x0 = RNG.randint(0, size - w)
        y0 = RNG.randint(0, size - w)
        img[y0:y0 + w, x0:x0 + w] = RNG.randint(100, 255)
        objs.append([0, x0 / size, y0 / size, (x0 + w) / size,
                     (y0 + w) / size])
    return img, np.asarray(objs, np.float32)


def _write_rec(path, n=8, max_obj=3):
    rec = recordio.MXIndexedRecordIO(str(path) + ".idx",
                                     str(path) + ".rec", "w")
    for i in range(n):
        img, objs = _scene(n_obj=RNG.randint(1, max_obj + 1))
        label = np.concatenate([[2, 5], objs.ravel()]).astype(np.float32)
        rec.write_idx(i, recordio.pack_img(
            recordio.IRHeader(0, label, i, 0), img))
    rec.close()
    return str(path) + ".rec"


def test_flip_aug_label_math():
    img, objs = _scene()
    aug = DetHorizontalFlipAug(p=1.0)
    out, lab = aug(nd.array(img), objs)
    # x-extent mirrors, y untouched, width preserved
    assert np.allclose(lab[:, 1], 1.0 - objs[:, 3], atol=1e-6)
    assert np.allclose(lab[:, 3], 1.0 - objs[:, 1], atol=1e-6)
    assert np.allclose(lab[:, (2, 4)], objs[:, (2, 4)])
    # the image flipped too: flipping back restores it
    assert np.array_equal(np.asarray(out.asnumpy(), np.uint8)[:, ::-1],
                          img)


def test_random_crop_respects_constraints():
    img, objs = _scene(size=64, n_obj=2)
    aug = DetRandomCropAug(min_object_covered=0.5,
                           area_range=(0.3, 1.0), max_attempts=40)
    hit = False
    for _ in range(10):
        out, lab = aug(nd.array(img), objs)
        assert lab.shape[1] == 5 and lab.shape[0] >= 1
        assert (lab[:, 1:5] >= -1e-6).all() and (lab[:, 1:5] <= 1 + 1e-6).all()
        assert (lab[:, 3] > lab[:, 1]).all() and (lab[:, 4] > lab[:, 2]).all()
        if out.shape != img.shape:
            hit = True
            s = out.shape
            assert 0.3 * 64 * 64 <= s[0] * s[1] <= 64 * 64 * 1.02
    assert hit, "crop never fired in 10 tries"


def test_random_pad_shrinks_boxes():
    img, objs = _scene(size=32)
    aug = DetRandomPadAug(area_range=(2.0, 3.0), max_attempts=50)
    out, lab = aug(nd.array(img), objs)
    assert out.shape[0] >= 32 and out.shape[1] >= 32
    # areas shrink by the canvas growth factor
    def area(b):
        return (b[:, 3] - b[:, 1]) * (b[:, 4] - b[:, 2])
    growth = (out.shape[0] * out.shape[1]) / (32.0 * 32.0)
    assert np.allclose(area(lab) * growth, area(objs), rtol=0.05)


def test_create_det_augmenter_chain():
    augs = CreateDetAugmenter((3, 24, 24), rand_crop=0.5, rand_pad=0.5,
                              rand_mirror=True, brightness=0.1)
    img, objs = _scene()
    out, lab = img, objs
    out = nd.array(out)
    for a in augs:
        out, lab = a(out, lab)
    # chain always lands on the network input size
    assert tuple(out.shape) == (24, 24, 3)
    assert lab.shape[1] == 5


def test_image_det_iter_end_to_end(tmp_path):
    rec = _write_rec(tmp_path / "scenes", n=8, max_obj=3)
    it = mx.image.ImageDetIter(batch_size=4, data_shape=(3, 32, 32),
                               path_imgrec=rec, shuffle=True,
                               rand_mirror=True)
    # fixed, padded label geometry across the dataset
    assert it.provide_label[0].shape[1:] == (it.max_objects, 5)
    n_batches = 0
    for batch in it:
        x, y = batch.data[0], batch.label[0]
        assert x.shape == (4, 3, 32, 32)
        assert y.shape == (4, it.max_objects, 5)
        yn = y.asnumpy()
        # padding rows are -1; real rows have valid geometry
        real = yn[yn[:, :, 0] >= 0]
        assert real.shape[0] >= 4  # at least one object per image
        assert (real[:, 3] > real[:, 1]).all()
        n_batches += 1
    assert n_batches == 2

    # reshape to a larger padded label and iterate again
    it.reshape(label_shape=(it.max_objects + 2, 5))
    it.reset()
    b = next(iter(it))
    assert b.label[0].shape[1] == it.max_objects

    # feeds MultiBoxTarget directly (the SSD training path)
    anchors = mx.nd.contrib.MultiBoxPrior(nd.zeros((1, 8, 8, 8)),
                                          sizes=(0.3,), ratios=(1.0,))
    cls = nd.zeros((4, 2, anchors.shape[1]))
    bt, bm, ct = mx.nd.contrib.MultiBoxTarget(anchors, b.label[0], cls)
    assert np.isfinite(bt.asnumpy()).all()


def test_sync_label_shape(tmp_path):
    r1 = _write_rec(tmp_path / "a", n=4, max_obj=1)
    r2 = _write_rec(tmp_path / "b", n=4, max_obj=3)
    it1 = mx.image.ImageDetIter(batch_size=2, data_shape=(3, 32, 32),
                                path_imgrec=r1)
    it2 = mx.image.ImageDetIter(batch_size=2, data_shape=(3, 32, 32),
                                path_imgrec=r2)
    it2 = it1.sync_label_shape(it2)
    assert it1.max_objects == it2.max_objects
    assert it1.provide_label[0].shape == it2.provide_label[0].shape


def test_draw_next(tmp_path):
    rec = _write_rec(tmp_path / "d", n=2)
    it = mx.image.ImageDetIter(batch_size=1, data_shape=(3, 32, 32),
                               path_imgrec=rec)
    imgs = list(it.draw_next(color=(255, 0, 0), thickness=1))
    assert len(imgs) == 2 and imgs[0].shape == (32, 32, 3)
    assert (imgs[0] == np.array([255, 0, 0])).all(axis=-1).any()


def test_invalid_labels_raise(tmp_path):
    it_args = dict(batch_size=1, data_shape=(3, 32, 32))
    rec = recordio.MXIndexedRecordIO(str(tmp_path / "bad.idx"),
                                     str(tmp_path / "bad.rec"), "w")
    img, _ = _scene()
    # header claims obj_w=4 (< 5): must be rejected
    label = np.asarray([2, 4, 0, 0.1, 0.2, 0.3, 0.4], np.float32)
    rec.write_idx(0, recordio.pack_img(recordio.IRHeader(0, label, 0, 0),
                                       img))
    rec.close()
    with pytest.raises(mx.MXNetError, match="invalid detection label"):
        mx.image.ImageDetIter(path_imgrec=str(tmp_path / "bad.rec"),
                              **it_args)
