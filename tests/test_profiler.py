"""Profiler: Chrome-trace dump + aggregate table.

Reference: src/profiler/profiler.h:87 (chrome://tracing JSON emission),
:332 (aggregate stats), python/mxnet/profiler.py dump/dumps.
"""
import json
import os

import mxnet_tpu as mx
from mxnet_tpu import profiler


def test_dump_writes_chrome_trace(tmp_path):
    profiler.set_config(filename=str(tmp_path / "prof"))
    profiler.start()
    with profiler.Task(name="outer_task"):
        a = mx.nd.ones((32, 32))
        b = mx.nd.dot(a, a)
        (b + 1).wait_to_read()
    path = profiler.dump()
    assert os.path.exists(path)
    trace = json.load(open(path))
    events = trace["traceEvents"]
    names = {e.get("name") for e in events}
    assert "outer_task" in names
    # eager op dispatch rows recorded while profiling was on
    assert "dot" in names or "_plus_scalar" in names or "ones" in names, \
        sorted(names)[:20]
    durs = [e for e in events if e.get("ph") == "X"]
    assert durs and all("dur" in e and "ts" in e for e in durs)


def test_dumps_aggregate_table(tmp_path):
    profiler.set_config(filename=str(tmp_path / "prof2"))
    profiler.start()
    a = mx.nd.ones((4, 4))
    for _ in range(3):
        mx.nd.dot(a, a).wait_to_read()
    profiler.dump()
    table = profiler.dumps(reset=True)
    assert "Name" in table and "Calls" in table
    assert "dot" in table
    # reset cleared it
    assert "dot" not in profiler.dumps()


def test_scopes_and_markers_inactive_ok():
    # scoped objects must not crash when profiling is off
    with profiler.Frame(name="f"):
        pass
    profiler.Marker(name="m").mark()
    c = profiler.Counter(name="c")
    c.increment(); c.decrement(); c.set_value(5)
    assert c.value == 5
