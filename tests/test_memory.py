"""HBM memory ledger + goodput/MFU accounting plane (ISSUE 17,
docs/observability.md "Memory ledger" / "Goodput & MFU").

The acceptance surface:

1. attribution: the ledger's per-model total matches the engine's own
   `device_bytes()` EXACTLY (the 5% acceptance bound is trivially met
   because device_bytes reconciles the ledger cells it reports) — for
   a frozen InferenceEngine, a DecodeEngine with its KV cache, and the
   fused step's ZeRO-1 carried-state accounting;
2. OOM forensics: a chaos-injected `memory.oom` fault becomes a
   simulated RESOURCE_EXHAUSTED whose `HBMExhausted` report + stderr
   dump name the top-3 consumers, without exhausting anything real;
3. surfaces: `memory.hbm.*` / `goodput.*` Prometheus exposition
   (HELP/TYPE once per family, label cardinality bounded) and the
   `/debugz` memory+goodput sections over real HTTP;
4. goodput: per-step MFU lands non-zero on StepTimer records once a
   program charged the FLOP counter, and `perf_gate --max-hbm-mb` /
   `--min-mfu` turn the stream into a CI exit code (absent metric =
   breach, like every other budget).
"""
import json
import os
import subprocess
import sys
import urllib.request

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import optimizer as opt
from mxnet_tpu.gluon.model_zoo.gpt import GPTDecoder
from mxnet_tpu.observability import goodput, httpz, memory
from mxnet_tpu.observability import registry as obs
from mxnet_tpu.resilience import chaos
from mxnet_tpu.serving import DecodeEngine, InferenceEngine

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
NF, NCLASS = 6, 4


@pytest.fixture(autouse=True)
def _clean_plane():
    memory._reset_for_tests()
    goodput._reset_for_tests()
    chaos.configure("")
    yield
    chaos.reset()
    memory._reset_for_tests()
    goodput._reset_for_tests()


def mlp_engine(max_batch=4, name="memtest"):
    data = mx.sym.var("data")
    h = mx.sym.FullyConnected(data=data, num_hidden=8, name="fc1")
    out = mx.sym.SoftmaxOutput(data=h, name="softmax")
    rng = np.random.RandomState(5)
    params = {"fc1_weight": mx.nd.array(
                  rng.randn(8, NF).astype(np.float32)),
              "fc1_bias": mx.nd.array(np.zeros(8, np.float32))}
    return InferenceEngine.from_symbol(
        out, params, {}, {"data": (NF,)}, max_batch_size=max_batch,
        name=name)


# -- ledger core ----------------------------------------------------------

def test_ledger_set_release_totals_peak():
    memory.set_bytes("m1", "engine", "params", 4000)
    memory.set_bytes("m1", "engine", "aux", 1000)
    memory.set_bytes("m2", "decode", "kv_cache", 9000)
    assert memory.total_bytes() == 14000
    assert memory.model_bytes("m1") == 5000
    top = memory.top_consumers(2)
    assert top[0] == ("m2", "decode", "kv_cache", 9000)
    # absolute set is idempotent, not a delta
    memory.set_bytes("m1", "engine", "params", 4000)
    assert memory.total_bytes() == 14000
    memory.release("m2")
    assert memory.total_bytes() == 5000
    assert memory.model_bytes("m2") == 0
    # peak holds the high-water mark across the release
    assert memory.peak_bytes() == 14000
    snap = memory.snapshot()
    assert snap["models"]["m1"]["total_bytes"] == 5000
    assert snap["peak_bytes"] == 14000


def test_disabled_env_is_noop(monkeypatch):
    monkeypatch.setenv("MXTPU_MEMLEDGER", "0")
    memory.set_bytes("m", "s", "k", 1234)
    assert memory.total_bytes() == 0
    assert memory.snapshot()["models"] == {}


def test_headroom_from_env_override(monkeypatch):
    monkeypatch.setenv("MXTPU_HBM_BYTES", "1000000")
    memory.set_bytes("m", "engine", "params", 250000)
    # CPU has no device memory_stats, so the env override is the limit
    assert memory.headroom_bytes() == 750000


def test_record_program_working_set():
    class FakeMA:
        temp_size_in_bytes = 1 << 20
        argument_size_in_bytes = 2 << 20
        output_size_in_bytes = 3 << 20
        generated_code_size_in_bytes = 4096

    class FakeCompiled:
        def memory_analysis(self):
            return FakeMA()

    sizes = memory.record_program("prog/x", FakeCompiled())
    assert sizes == {"temp": 1 << 20, "argument": 2 << 20,
                     "output": 3 << 20, "code": 4096}
    assert memory.snapshot()["programs"]["prog/x"]["temp"] == 1 << 20
    # a backend whose executables can't answer records nothing
    class Dead:
        def memory_analysis(self):
            raise RuntimeError("unimplemented")
    assert memory.record_program("prog/dead", Dead()) is None


# -- engine / decode / trainer attribution -------------------------------

def test_engine_ledger_matches_device_bytes():
    eng = mlp_engine(name="led_eng")
    db = eng.device_bytes()
    assert db > 0
    # device_bytes reconciles the ledger cells: the acceptance's <=5%
    # bound is exact equality by construction
    assert memory.model_bytes("led_eng") == db
    by = memory.snapshot()["models"]["led_eng"]["by"]
    assert "engine/params" in by


def test_decode_ledger_matches_device_bytes():
    np.random.seed(3)
    blk = GPTDecoder(64, max_seq_len=16, num_layers=1, num_heads=2,
                     embed_dim=8)
    blk.initialize(mx.init.Xavier())
    eng = DecodeEngine(blk, max_slots=2, name="led_dec")
    db = eng.device_bytes()
    assert db > 0
    assert memory.model_bytes("led_dec") == db
    by = memory.snapshot()["models"]["led_dec"]["by"]
    # the KV cache is a first-class cell — allocated for max_slots
    # whether or not a sequence is active
    assert by["decode/kv_cache"] > 0


def test_trainer_params_registered(monkeypatch):
    from mxnet_tpu import autograd, gluon
    monkeypatch.setenv("MXTPU_FUSED_STEP", "1")
    mx.random.seed(0)
    net = gluon.nn.Dense(3)
    net.initialize()
    x = mx.nd.array(np.random.RandomState(1).randn(4, 5).astype("f"))
    y = mx.nd.array(np.random.RandomState(2).randn(4, 3).astype("f"))
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.1})
    with autograd.record():
        loss = gluon.loss.L2Loss()(net(x), y)
    loss.backward()
    tr.step(4)
    want = sum(int(p.data()._data.nbytes)
               for p in net.collect_params().values())
    by = memory.snapshot()["models"]["trainer"]["by"]
    assert by["trainer/params"] == want


def test_zero1_state_cell_accounting(monkeypatch):
    """The carried-state accounting the fused step registers under
    trainer/optimizer/zero1_state: addressable-shard bytes only (the
    1/N per-replica share), released at the flush/drop boundaries."""
    import jax.numpy as jnp
    from mxnet_tpu.parallel import fused_step as fs
    monkeypatch.setenv("MXTPU_FUSED_STEP", "1")
    upd = opt.get_updater(opt.create("sgd", learning_rate=0.1,
                                     momentum=0.9))
    ws = [mx.nd.array(np.zeros((4, 4), "f"))]
    gs = [mx.nd.array(np.ones((4, 4), "f"))]
    assert fs.try_step(upd, [0], gs, ws)
    owner = upd._fused_step_owner
    # single-process runs carry no sharded flats; inject the shape the
    # multi-process zero1 path stores and check the byte accounting
    flats = [[jnp.zeros(128, "float32")], [jnp.zeros(64, "float32")]]
    owner._state_flats["fake_sig"] = (None, flats)
    assert owner._carried_state_bytes() == (128 + 64) * 4
    memory.set_bytes("trainer", "optimizer", "zero1_state",
                     owner._carried_state_bytes())
    assert memory.model_bytes("trainer") >= (128 + 64) * 4
    owner.drop_state()           # set_states boundary: cell must drop
    by = memory.snapshot()["models"].get("trainer", {}).get("by", {})
    assert "optimizer/zero1_state" not in by


def test_gateway_eviction_releases_ledger():
    from mxnet_tpu.serving.gateway.registry import ModelRegistry
    reg = ModelRegistry(hbm_budget_mb=1024, max_models=4)
    reg.register("evict_me", lambda: mlp_engine(name="evict_me"),
                 num_workers=1, max_wait_ms=1.0)
    x = np.ones((1, NF), np.float32)
    reg.get("evict_me").infer(x, timeout=30)
    assert memory.model_bytes("evict_me") > 0
    assert reg.evict("evict_me", timeout=30)
    # an evicted model's residency must read zero, not stale
    assert memory.model_bytes("evict_me") == 0


# -- OOM forensics --------------------------------------------------------

def test_chaos_oom_forensics_names_top_consumers(capsys):
    memory.set_bytes("big", "decode", "kv_cache", 8 << 20)
    memory.set_bytes("mid", "engine", "params", 4 << 20)
    memory.set_bytes("small", "engine", "aux", 1 << 20)
    memory.set_bytes("tiny", "engine", "aux", 1 << 10)
    chaos.configure("memory.oom:p=1,kind=raise")
    before = obs.REGISTRY.get("memory.oom.events").total()
    with pytest.raises(memory.HBMExhausted) as ei:
        with memory.oom_guard("engine.infer", "big"):
            pytest.fail("guard must trip on entry")
    rep = ei.value.report
    assert rep["site"] == "engine.infer" and rep["model"] == "big"
    named = [(c["model"], c["subsystem"], c["kind"])
             for c in rep["top_consumers"]]
    assert named == [("big", "decode", "kv_cache"),
                     ("mid", "engine", "params"),
                     ("small", "engine", "aux")]
    assert obs.REGISTRY.get("memory.oom.events").total() == before + 1
    err = capsys.readouterr().err
    assert "[memory]" in err and "#1 big decode/kv_cache" in err


def test_oom_guard_converts_real_resource_exhausted():
    memory.set_bytes("m", "engine", "params", 1 << 20)
    with pytest.raises(memory.HBMExhausted) as ei:
        with memory.oom_guard("decode.step", "m"):
            raise RuntimeError(
                "RESOURCE_EXHAUSTED: Out of memory allocating "
                "1234 bytes")
    assert ei.value.report["total_bytes"] == 1 << 20
    # everything else passes through untouched
    with pytest.raises(ValueError):
        with memory.oom_guard("decode.step", "m"):
            raise ValueError("not an allocator failure")


def test_engine_infer_dispatch_is_guarded():
    eng = mlp_engine(name="oomed")
    x = np.zeros((2, NF), np.float32)
    assert eng.infer(x)            # clean path works
    chaos.configure("memory.oom:p=1,kind=raise,n=1")
    with pytest.raises(memory.HBMExhausted):
        eng.infer(x)
    chaos.reset()
    assert eng.infer(x)            # engine survives the drill


# -- goodput --------------------------------------------------------------

def test_goodput_cost_table_and_charges():
    goodput.record_cost("p1", flops=2.0e9)
    assert goodput.cost("p1")["flops"] == 2.0e9
    f0 = obs.REGISTRY.get("goodput.flops").total()
    assert goodput.note_dispatch("p1") == 2.0e9
    assert obs.REGISTRY.get("goodput.flops").total() - f0 == 2.0e9
    # unregistered programs charge nothing — the gauge stays honest
    assert goodput.note_dispatch("unknown") == 0.0
    # measured beats analytic, and never downgrades back
    class FakeCost:
        def cost_analysis(self):
            return {"flops": 5.0e9, "bytes accessed": 1.0e6}
    goodput.record_cost("p1", compiled=FakeCost())
    assert goodput.cost("p1")["flops"] == 5.0e9
    goodput.record_cost("p1", flops=1.0)
    assert goodput.cost("p1")["flops"] == 5.0e9


def test_mfu_value_clamped_and_gauged(monkeypatch):
    monkeypatch.setenv("MXTPU_PEAK_FLOPS", "1e10")
    assert goodput.mfu_value(1e9, 1.0, source="t") == \
        pytest.approx(0.1)
    assert goodput.mfu_value(1e12, 0.001, source="t") == 1.0
    g = obs.REGISTRY.get("goodput.mfu")
    assert g is not None


def test_step_record_carries_nonzero_mfu(tmp_path, monkeypatch):
    from mxnet_tpu.observability.telemetry import (StepTimer,
                                                   close_stream)
    out = tmp_path / "t.jsonl"
    monkeypatch.setenv("MXTPU_TELEMETRY", str(out))
    timer = StepTimer("goodput.test")
    timer.begin_step()
    goodput.record_cost("step_prog", flops=5.0e8)
    goodput.note_dispatch("step_prog")
    rec = timer.end_step(batch_size=2)
    close_stream()
    assert rec["step_flops"] == 5.0e8
    assert 0.0 < rec["mfu"] <= 1.0
    streamed = [json.loads(l) for l in
                out.read_text().splitlines()][-1]
    assert streamed["mfu"] == rec["mfu"]


# -- exposition + /debugz -------------------------------------------------

def test_prometheus_exposition_of_new_families():
    memory.set_bytes("m", "engine", "params", 1024)
    goodput.record_cost("p", flops=1e6)
    goodput.note_dispatch("p")
    goodput.mfu_value(1e6, 0.5, source="train")
    text = obs.REGISTRY.to_prometheus()
    for fam, kind in (("mxtpu_memory_hbm_bytes", "gauge"),
                      ("mxtpu_memory_hbm_total_bytes", "gauge"),
                      ("mxtpu_goodput_flops_total", "counter"),
                      ("mxtpu_goodput_dispatches_total", "counter"),
                      ("mxtpu_goodput_mfu", "gauge")):
        # HELP/TYPE exactly once per family
        assert text.count("# HELP %s " % fam) == 1, fam
        assert text.count("# TYPE %s %s" % (fam, kind)) == 1, fam
    assert 'mxtpu_memory_hbm_bytes{kind="params",model="m",' \
        'subsystem="engine"} 1024' in text


def test_ledger_label_cardinality_bounded(monkeypatch):
    monkeypatch.setenv("MXTPU_METRIC_MAX_LABELS", "32")
    for i in range(64):
        memory.set_bytes("model%d" % i, "engine", "params", 100)
    # past the cap new labelsets collapse into the overflow bucket
    # instead of growing without bound
    assert len(memory.HBM_BYTES._values) <= 33
    assert obs.OVERFLOW_KEY in memory.HBM_BYTES._values
    # the ledger itself stays exact — only the gauge's labels saturate
    assert memory.total_bytes() == 64 * 100


def test_debugz_memory_section_over_http():
    memory.set_bytes("served", "engine", "params", 2048)
    goodput.record_cost("prog", flops=1e6)
    srv = httpz.ObservabilityServer(port=0).start()
    try:
        dbg = json.loads(urllib.request.urlopen(
            srv.url + "/debugz", timeout=10).read().decode())
        mem = dbg["memory"]
        assert mem["enabled"] and mem["total_bytes"] >= 2048
        assert mem["models"]["served"]["by"]["engine/params"] == 2048
        assert mem["top"][0]["model"] == "served"
        gp = dbg["goodput"]
        assert gp["peak_flops"] > 0
        assert gp["costs"]["prog"]["flops"] == 1e6
    finally:
        srv.close()


# -- report + gate + drift ------------------------------------------------

def _write_stream(path, hbm_mb=100.0, mfu=0.25):
    recs = [{"ts": 1.0, "source": "train", "step": 0,
             "step_time": 0.1, "step_flops": 1e9, "mfu": mfu},
            {"ts": 2.0, "source": "train", "step": 1,
             "step_time": 0.1, "step_flops": 1e9, "mfu": mfu},
            {"ts": 3.0, "source": "memory", "event": "update",
             "model": "m", "subsystem": "engine", "kind": "params",
             "bytes": int(hbm_mb * 2**20),
             "total_bytes": int(hbm_mb * 2**20), "step_time": 0.0}]
    path.write_text("".join(json.dumps(r) + "\n" for r in recs))


def test_telemetry_report_memory_goodput_sections(tmp_path):
    sys.path.insert(0, os.path.join(ROOT, "tools"))
    try:
        from telemetry_report import (format_summary, load_records,
                                      summarize)
    finally:
        sys.path.pop(0)
    stream = tmp_path / "t.jsonl"
    _write_stream(stream, hbm_mb=64.0, mfu=0.5)
    s = summarize(load_records(str(stream)))
    assert s["hbm_peak_mb"] == pytest.approx(64.0)
    assert s["mfu_p50"] == pytest.approx(0.5)
    assert s["oom_events"] == 0
    text = format_summary(s)
    assert "memory" in text and "goodput" in text
    # memory records are excluded from headline step percentiles
    assert s["steps"] == 2


def test_perf_gate_hbm_and_mfu_budgets(tmp_path):
    gate = os.path.join(ROOT, "tools", "perf_gate.py")
    stream = tmp_path / "t.jsonl"
    _write_stream(stream, hbm_mb=100.0, mfu=0.25)

    def run(path, *budget):
        return subprocess.run(
            [sys.executable, gate, str(path)] + list(budget),
            capture_output=True, text=True)

    r = run(stream, "--max-hbm-mb", "128", "--min-mfu", "0.1")
    assert r.returncode == 0, r.stdout + r.stderr
    r = run(stream, "--max-hbm-mb", "64")
    assert r.returncode == 1 and "hbm_peak_mb" in r.stdout
    r = run(stream, "--min-mfu", "0.5")
    assert r.returncode == 1 and "mfu_p50" in r.stdout
    # a stream without the budgeted metric breaches, never passes
    bare = tmp_path / "bare.jsonl"
    bare.write_text(json.dumps(
        {"ts": 0, "source": "train", "step": 0, "step_time": 0.1})
        + "\n")
    assert run(bare, "--max-hbm-mb", "1024").returncode == 1
    assert run(bare, "--min-mfu", "0.01").returncode == 1


def test_docs_drift_clean():
    """The three code/docs contracts (metrics, perf_gate flags, chaos
    sites) hold with the new families wired in."""
    r = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "docs_drift.py")],
        capture_output=True, text=True)
    assert r.returncode == 0, r.stdout + r.stderr


def test_memledger_disabled_overhead_path(monkeypatch):
    """MXTPU_MEMLEDGER=0 short-circuits to one env read — the bench
    A/B knob. Not a timing assertion (CI noise); just that the
    disabled path really skips ledger + goodput work."""
    monkeypatch.setenv("MXTPU_MEMLEDGER", "0")
    assert not memory.enabled() and not goodput.enabled()
    memory.set_bytes("m", "s", "k", 1)
    goodput.record_cost("p", flops=1e9)
    assert memory.total_bytes() == 0
    assert goodput.cost("p") is None
