"""bench.py must stay runnable: the driver executes it on real hardware
at round end, so a CPU smoke run with tiny shapes gates bitrot."""
import json
import os
import subprocess
import sys


def test_bench_smoke_cpu():
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env.update(MXTPU_BENCH_PLATFORM="cpu", MXTPU_BENCH_BATCH="8",
               MXTPU_BENCH_IMG="32", MXTPU_BENCH_STEPS="2",
               MXTPU_BENCH_SCORE_BATCH="4", MXTPU_BENCH_UNROLL="1",
               MXTPU_BENCH_EXTRA_STEPS="2",
               MXTPU_BENCH_INCEPTION_BATCH="8",
               MXTPU_BENCH_ALEX_BATCH="8",
               # never let the in-bench budget skip extras: this test
               # asserts their presence, so skipping must be a failure
               MXTPU_BENCH_BUDGET_S="100000")
    env.pop("JAX_PLATFORMS", None)
    # ladder mode (the driver path) runs the measurement in FOUR
    # fresh-interpreter rungs (secure/score/mid/full): allow for four
    # compile rounds — the persistent compile cache may be a no-op for
    # tiny programs under its min-compile-time threshold
    r = subprocess.run([sys.executable, os.path.join(root, "bench.py")],
                       capture_output=True, text=True, timeout=5400,
                       env=env)
    assert r.returncode == 0, r.stdout[-2000:] + r.stderr[-2000:]
    line = [l for l in r.stdout.splitlines() if l.startswith("{")][-1]
    out = json.loads(line)
    assert out["metric"].startswith("resnet50_v1_train_throughput")
    assert out["value"] > 0 and out["unit"] == "img/s"
    assert "score_b4_img_s" in out["extra"]
    # the BASELINE.md secondary rows ride along (errors would be
    # reported under *_error keys — fail loudly here instead)
    for key in ("inception_v3_train_b8_img_s", "alexnet_train_b8_img_s",
                "int8_resnet50_score_b4_img_s"):
        assert key in out["extra"], out["extra"]
