"""bench.py must stay runnable: the driver executes it on real hardware
at round end, so a CPU smoke run with tiny shapes gates bitrot."""
import json
import os
import subprocess
import sys


def test_bench_smoke_cpu():
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env.update(MXTPU_BENCH_PLATFORM="cpu", MXTPU_BENCH_BATCH="8",
               MXTPU_BENCH_IMG="32", MXTPU_BENCH_STEPS="2",
               MXTPU_BENCH_SCORE_BATCH="4", MXTPU_BENCH_UNROLL="1")
    env.pop("JAX_PLATFORMS", None)
    r = subprocess.run([sys.executable, os.path.join(root, "bench.py")],
                       capture_output=True, text=True, timeout=900,
                       env=env)
    assert r.returncode == 0, r.stdout[-2000:] + r.stderr[-2000:]
    line = [l for l in r.stdout.splitlines() if l.startswith("{")][-1]
    out = json.loads(line)
    assert out["metric"].startswith("resnet50_v1_train_throughput")
    assert out["value"] > 0 and out["unit"] == "img/s"
    assert "score_b4_img_s" in out["extra"]
