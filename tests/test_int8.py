"""int8 quantization: quantized ops + end-to-end int8 resnet-18 parity.

Reference: src/operator/quantization/{quantized_conv.cc,
quantized_pooling.cc}, python/mxnet/contrib/quantization.py (naive +
entropy calibration, quantize_model).
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd


class TestQuantizedOps:
    def test_quantized_conv_matches_float_conv(self):
        rng = np.random.RandomState(0)
        x = rng.randn(2, 3, 8, 8).astype("f")
        w = rng.randn(4, 3, 3, 3).astype("f")
        qx, xmin, xmax = nd.contrib.quantize(
            nd.array(x), nd.array([x.min()]), nd.array([x.max()]))
        qw, wmin, wmax = nd.contrib.quantize(
            nd.array(w), nd.array([w.min()]), nd.array([w.max()]))
        zero = nd.zeros((1,))
        acc, omin, omax = nd.contrib.quantized_conv(
            qx, qw, nd.zeros((4,), dtype="int8"), xmin, xmax, wmin, wmax,
            zero, zero, kernel=(3, 3), num_filter=4, no_bias=True)
        assert acc.dtype == np.int32
        # dequantize the accumulator and compare against the fp32 conv
        scale = float(omax.asnumpy()[0]) / (2.0 ** 31 - 1)
        got = acc.asnumpy() * scale
        ref = nd.Convolution(nd.array(x), nd.array(w), kernel=(3, 3),
                             num_filter=4, no_bias=True).asnumpy()
        err = np.abs(got - ref).max() / (np.abs(ref).max() + 1e-9)
        assert err < 0.05, err  # int8 rounding only

    def test_quantized_pooling_exact_for_max(self):
        rng = np.random.RandomState(1)
        q = rng.randint(-127, 128, (1, 2, 6, 6)).astype(np.int8)
        mn, mx_ = nd.array([-1.0]), nd.array([1.0])
        y, omin, omax = nd.contrib.quantized_pooling(
            nd.array(q), mn, mx_, kernel=(2, 2), stride=(2, 2),
            pool_type="max")
        ref = nd.Pooling(nd.array(q.astype("f")), kernel=(2, 2),
                         stride=(2, 2), pool_type="max").asnumpy()
        np.testing.assert_array_equal(y.asnumpy().astype("f"), ref)
        assert float(omin.asnumpy()[0]) == -1.0

    def test_int8_conv_sandwich(self):
        rng = np.random.RandomState(2)
        x = rng.randn(2, 3, 8, 8).astype("f")
        w = 0.2 * rng.randn(4, 3, 3, 3).astype("f")
        amax = float(np.abs(x).max())
        y = nd._contrib_int8_conv(nd.array(x), nd.array(w),
                                  amax_data=amax, kernel=(3, 3),
                                  num_filter=4) \
            if hasattr(nd, "_contrib_int8_conv") else None
        if y is None:
            from mxnet_tpu.ndarray import invoke
            from mxnet_tpu.ops import registry
            y = invoke(registry.get("_contrib_int8_conv"),
                       [nd.array(x), nd.array(w)],
                       {"amax_data": amax, "kernel": (3, 3),
                        "num_filter": 4, "no_bias": True})[0]
        ref = nd.Convolution(nd.array(x), nd.array(w), kernel=(3, 3),
                             num_filter=4, no_bias=True).asnumpy()
        err = np.abs(y.asnumpy() - ref).max() / (np.abs(ref).max() + 1e-9)
        assert err < 0.05, err


def _calib_iter(x, batch=4):
    return mx.io.NDArrayIter(x, np.zeros((x.shape[0],), "f"),
                             batch_size=batch,
                             label_name="softmax_label")


class TestQuantizeModel:
    def _small_convnet(self):
        d = mx.sym.var("data")
        c = mx.sym.Convolution(d, kernel=(3, 3), num_filter=8, pad=(1, 1),
                               name="conv0")
        r = mx.sym.Activation(c, act_type="relu")
        p = mx.sym.Pooling(r, kernel=(2, 2), stride=(2, 2),
                           pool_type="max")
        f = mx.sym.FullyConnected(p, num_hidden=10, name="fc0")
        return mx.sym.SoftmaxOutput(f, name="softmax")

    def _params_for(self, sym, xshape):
        rng = np.random.RandomState(3)
        arg_shapes, _, aux_shapes = sym.infer_shape(
            data=xshape, softmax_label=(xshape[0],))
        args, auxs = {}, {}
        for name, shape in zip(sym.list_arguments(), arg_shapes):
            if name in ("data", "softmax_label"):
                continue
            args[name] = nd.array(0.2 * rng.randn(*shape).astype("f"))
        for name, shape in zip(sym.list_auxiliary_states(), aux_shapes):
            auxs[name] = nd.zeros(shape)
        return args, auxs

    @pytest.mark.parametrize("calib_mode", ["naive", "entropy"])
    def test_int8_forward_parity(self, calib_mode):
        rng = np.random.RandomState(4)
        sym = self._small_convnet()
        x = rng.randn(16, 3, 8, 8).astype("f")
        args, auxs = self._params_for(sym, (4, 3, 8, 8))
        qsym, qargs, qauxs = mx.contrib.quantization.quantize_model(
            sym, args, auxs, calib_data=_calib_iter(x),
            calib_mode=calib_mode, quantize_mode="full",
            excluded_sym_names=[])

        def score(s, a, au):
            ex = s.bind(None, args={**a, "data": nd.array(x[:4]),
                                    "softmax_label": nd.zeros((4,))},
                        aux_states=dict(au), grad_req="null")
            return ex.forward(is_train=False)[0].asnumpy()

        ref = score(sym, args, auxs)
        got = score(qsym, qargs, qauxs)
        # int8 parity: same argmax on (nearly) all samples
        agree = (ref.argmax(1) == got.argmax(1)).mean()
        assert agree >= 0.75, agree
        # the rewrite really lowered to int8 compute
        assert "_contrib_int8_conv" in qsym.tojson()

    def test_int8_resnet18_forward_parity(self):
        """int8 resnet-18 runs end-to-end and agrees with fp32 top-1
        (the point of the reference quantization subsystem)."""
        from mxnet_tpu.gluon.model_zoo import vision
        rng = np.random.RandomState(5)
        net = vision.resnet18_v1(classes=10)
        net.initialize()
        x = rng.randn(8, 3, 32, 32).astype("f")
        net(mx.nd.array(x))  # materialize

        data = mx.sym.var("data")
        out = net(data)
        args = {p.name: p.data() for p in net.collect_params().values()
                if p.name in out.list_arguments()}
        auxs = {p.name: p.data() for p in net.collect_params().values()
                if p.name in out.list_auxiliary_states()}

        qsym, qargs, qauxs = mx.contrib.quantization.quantize_model(
            out, args, auxs, calib_data=_calib_iter(x),
            calib_mode="naive", quantize_mode="full",
            label_names=None)

        def score(s, a, au):
            ex = s.bind(None, args={**a, "data": nd.array(x)},
                        aux_states=dict(au), grad_req="null")
            return ex.forward(is_train=False)[0].asnumpy()

        ref = score(out, args, auxs)
        got = score(qsym, qargs, qauxs)
        agree = (ref.argmax(1) == got.argmax(1)).mean()
        assert agree >= 0.75, agree

    def test_entropy_threshold_tightens_range(self):
        # heavy-tailed activations: KL threshold must clip the tail
        from mxnet_tpu.contrib.quantization import _optimal_threshold_kl
        rng = np.random.RandomState(6)
        a = np.abs(rng.randn(100000)).astype("f")
        a[:10] = 50.0  # outliers
        h, edges = np.histogram(a, bins=2048, range=(0, 50.0))
        thr = _optimal_threshold_kl(h, edges[1:])
        assert thr < 25.0, thr  # far below the outlier max
