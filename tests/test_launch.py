"""tools/launch.py: the local launcher end-to-end.

Reference: tools/launch.py local mode — here it must start N workers
with DMLC_*/JAX_* rendezvous env and reap their exit codes; the worker
is the same dist kvstore script the subprocess harness uses, now running
in env mode.
"""
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_launcher(n, extra_cmd):
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "launch.py"),
         "-n", str(n)] + extra_cmd,
        capture_output=True, text=True, timeout=240, env=env)


def test_launch_local_runs_dist_worker():
    r = _run_launcher(2, [sys.executable,
                          os.path.join(REPO, "tests",
                                       "dist_kvstore_worker.py")])
    assert r.returncode == 0, r.stdout[-3000:] + r.stderr[-2000:]
    assert "WORKER_0_OK" in r.stdout
    assert "WORKER_1_OK" in r.stdout


def test_launch_propagates_failure():
    r = _run_launcher(2, [sys.executable, "-c", "import sys; sys.exit(7)"])
    assert r.returncode == 7


def test_cleanup_flag():
    """--cleanup lists stale processes locally (and over a hostfile's
    hosts) — the reference kill-mxnet.py role. Default is list-only so
    a test-suite run can never kill an unrelated in-flight job; --kill
    opts into reaping."""
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "launch.py"),
         "--cleanup"],
        capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "kill_stale" in r.stdout or "no stale" in r.stdout
    # list mode never prints kill confirmations
    assert "-> killed" not in r.stdout
