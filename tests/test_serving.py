"""Serving subsystem tests (docs/serving.md).

Covers the ISSUE-5 acceptance surface: bit-parity of the frozen engine
against executor.forward for all three load paths (symbol+params,
Module, Gluon block), the padding-bucket compile-count bound, batcher
coalescing/timeout/deadline-rejection/shedding (including under a
chaos-injected slow `serving.infer`), graceful SIGTERM drain, and the
rebased `c_predict.Predictor` / `Module.predict` shims.
"""
import json
import os
import signal
import threading
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.observability import registry as obs
from mxnet_tpu.observability import telemetry
from mxnet_tpu.resilience import (Deadline, DeadlineExceeded,
                                  InjectedFault, chaos)
from mxnet_tpu.serving import (DynamicBatcher, InferenceEngine,
                               ModelServer, RequestRejected,
                               ServerClosed, bucket_sizes)

NF, NCLASS = 8, 4


@pytest.fixture(autouse=True)
def _clean_chaos():
    chaos.configure("")
    yield
    chaos.reset()


def mlp_symbol():
    data = mx.sym.var("data")
    h = mx.sym.FullyConnected(data=data, num_hidden=16, name="fc1")
    h = mx.sym.Activation(data=h, act_type="relu")
    h = mx.sym.FullyConnected(data=h, num_hidden=NCLASS, name="fc2")
    return mx.sym.SoftmaxOutput(data=h, name="softmax")


def mlp_params(seed=3):
    rng = np.random.RandomState(seed)

    def p(*shape):
        return mx.nd.array(rng.randn(*shape).astype(np.float32) * 0.3)

    return {"fc1_weight": p(16, NF), "fc1_bias": p(16),
            "fc2_weight": p(NCLASS, 16), "fc2_bias": p(NCLASS)}


def make_engine(max_batch=8, **kwargs):
    return InferenceEngine.from_symbol(
        mlp_symbol(), mlp_params(), {}, {"data": (NF,)},
        max_batch_size=max_batch, **kwargs)


def executor_reference(x):
    """The legacy path: full executor bind + forward(is_train=False)."""
    sym = mlp_symbol()
    args = dict(mlp_params(), data=mx.nd.array(x),
                softmax_label=mx.nd.zeros((x.shape[0],)))
    exe = sym.bind(mx.cpu(), args, grad_req="null")
    return [o.asnumpy() for o in exe.forward(is_train=False)]


def compiles_total():
    return obs.REGISTRY.get("serving.engine.compiles").total()


# -- engine ---------------------------------------------------------------
def test_bucket_sizes():
    assert bucket_sizes(1) == (1,)
    assert bucket_sizes(8) == (1, 2, 4, 8)
    assert bucket_sizes(6) == (1, 2, 4, 6)
    assert bucket_sizes(33) == (1, 2, 4, 8, 16, 32, 33)
    with pytest.raises(mx.MXNetError):
        bucket_sizes(0)


def test_engine_symbol_bit_parity():
    rng = np.random.RandomState(0)
    x = rng.randn(8, NF).astype(np.float32)
    eng = make_engine(8)
    out = eng.infer(x)
    ref = executor_reference(x)
    assert len(out) == len(ref)
    # exact bucket (no padding): byte-for-byte with the executor path
    np.testing.assert_array_equal(out[0].asnumpy(), ref[0])


def test_engine_padding_parity():
    rng = np.random.RandomState(1)
    eng = make_engine(8)
    for n in (1, 3, 5, 7):
        x = rng.randn(n, NF).astype(np.float32)
        out = eng.infer(x)[0].asnumpy()
        ref = executor_reference(x)[0]
        assert out.shape == (n, NCLASS)
        np.testing.assert_allclose(out, ref, rtol=0, atol=1e-6)


def test_engine_compile_count_bounded_by_buckets():
    rng = np.random.RandomState(2)
    eng = make_engine(8)
    before = compiles_total()
    # 8 distinct request sizes -> at most log2(8)+1 = 4 programs
    for n in range(1, 9):
        eng.infer(rng.randn(n, NF).astype(np.float32))
    assert compiles_total() - before == len(eng.buckets) == 4
    assert eng.compiled_buckets == [1, 2, 4, 8]
    # steady state: no new compiles, whatever sizes arrive
    for n in (3, 5, 8, 1, 6):
        eng.infer(rng.randn(n, NF).astype(np.float32))
    assert compiles_total() - before == 4


def test_engine_warmup_precompiles():
    eng = make_engine(4)
    before = compiles_total()
    warmed = eng.warmup()
    assert warmed == [1, 2, 4]
    assert compiles_total() - before == 3
    eng.infer(np.zeros((3, NF), np.float32))
    assert compiles_total() - before == 3   # warm: nothing new
    assert eng.warmup() == []               # idempotent


def test_engine_input_validation():
    eng = make_engine(4)
    with pytest.raises(mx.MXNetError):
        eng.infer(np.zeros((5, NF), np.float32))      # > max_batch
    with pytest.raises(mx.MXNetError):
        eng.infer(np.zeros((2, NF + 1), np.float32))  # wrong example dim
    with pytest.raises(mx.MXNetError):
        eng.infer({"bogus": np.zeros((2, NF), np.float32)})


def test_engine_donation_safe_for_device_inputs():
    # an exact-bucket jax-array input must survive the donated dispatch
    eng = make_engine(4)
    x = mx.nd.array(np.random.RandomState(3).randn(4, NF)
                    .astype(np.float32))
    first = eng.infer(x)[0].asnumpy()
    second = eng.infer(x)[0].asnumpy()     # x must still be readable
    np.testing.assert_array_equal(first, second)


def test_engine_from_module_parity():
    x = np.random.RandomState(4).randn(8, NF).astype(np.float32)
    mod = mx.mod.Module(mlp_symbol())
    mod.bind([("data", (8, NF))], for_training=False)
    mod.init_params(mx.init.Xavier())
    eng = InferenceEngine.from_module(mod)
    out = eng.infer(x)[0].asnumpy()
    os.environ["MXTPU_SERVING_ENGINE"] = "0"
    try:
        ref = mod.predict(mx.nd.array(x)).asnumpy()
    finally:
        del os.environ["MXTPU_SERVING_ENGINE"]
    np.testing.assert_array_equal(out, ref)


def test_engine_from_block_parity():
    net = mx.gluon.nn.HybridSequential()
    with net.name_scope():
        net.add(mx.gluon.nn.Dense(16, activation="relu"))
        net.add(mx.gluon.nn.Dense(NCLASS))
    net.initialize()
    x = mx.nd.array(np.random.RandomState(5).randn(8, NF)
                    .astype(np.float32))
    ref = net(x).asnumpy()
    eng = InferenceEngine.from_block(net, x)
    np.testing.assert_array_equal(eng.infer(x)[0].asnumpy(), ref)
    # padded sizes agree too
    np.testing.assert_allclose(
        eng.infer(x[:3])[0].asnumpy(), ref[:3], rtol=0, atol=1e-6)


# -- batcher --------------------------------------------------------------
def test_batcher_coalesces_to_one_batch():
    b = DynamicBatcher(["data"], max_batch_size=8, max_wait_ms=50,
                       queue_depth=16)
    for i in range(4):
        b.submit(np.full((1, NF), i, np.float32))
    batch = b.next_batch(timeout=1.0)
    assert [r.n for r in batch] == [1, 1, 1, 1]
    assert len(b) == 0


def test_batcher_splits_at_max_batch():
    b = DynamicBatcher(["data"], max_batch_size=4, max_wait_ms=1,
                       queue_depth=16)
    for _ in range(3):
        b.submit(np.zeros((3, NF), np.float32))
    first = b.next_batch(timeout=1.0)
    assert sum(r.n for r in first) == 3     # 3 + 3 > 4: next one waits
    second = b.next_batch(timeout=1.0)
    assert sum(r.n for r in second) == 3


def test_batcher_wait_window_releases_partial_batch():
    b = DynamicBatcher(["data"], max_batch_size=64, max_wait_ms=30,
                       queue_depth=16)
    t0 = time.perf_counter()
    b.submit(np.zeros((1, NF), np.float32))
    batch = b.next_batch(timeout=5.0)
    waited = time.perf_counter() - t0
    assert len(batch) == 1
    assert waited < 2.0        # released by the window, not the timeout


def test_batcher_rejects_expired_deadlines_without_computing():
    b = DynamicBatcher(["data"], max_batch_size=8, max_wait_ms=1,
                       queue_depth=16)
    doomed = b.submit(np.zeros((1, NF), np.float32),
                      deadline=Deadline(0.0, what="req"))
    live = b.submit(np.zeros((1, NF), np.float32))
    time.sleep(0.01)
    batch = b.next_batch(timeout=1.0)
    assert batch == [live] or [r is live for r in batch] == [True]
    with pytest.raises(DeadlineExceeded):
        doomed.result(timeout=1.0)
    assert b.shed == 1


def test_batcher_sheds_when_full_reject_policy():
    b = DynamicBatcher(["data"], max_batch_size=4, max_wait_ms=1,
                       queue_depth=2, shed_policy="reject")
    before = obs.REGISTRY.get("serving.shed.count").total()
    b.submit(np.zeros((1, NF), np.float32))
    b.submit(np.zeros((1, NF), np.float32))
    with pytest.raises(RequestRejected):
        b.submit(np.zeros((1, NF), np.float32))
    assert b.shed == 1
    assert obs.REGISTRY.get("serving.shed.count").total() == before + 1


def test_batcher_drop_oldest_policy():
    b = DynamicBatcher(["data"], max_batch_size=4, max_wait_ms=1,
                       queue_depth=2, shed_policy="drop_oldest")
    oldest = b.submit(np.zeros((1, NF), np.float32))
    b.submit(np.zeros((1, NF), np.float32))
    newest = b.submit(np.zeros((1, NF), np.float32))  # evicts `oldest`
    with pytest.raises(RequestRejected):
        oldest.result(timeout=1.0)
    batch = b.next_batch(timeout=1.0)
    assert newest in batch and oldest not in batch


def test_batcher_closed_rejects_submits_but_drains_queue():
    b = DynamicBatcher(["data"], max_batch_size=4, max_wait_ms=1,
                       queue_depth=8)
    queued = b.submit(np.zeros((1, NF), np.float32))
    b.close()
    with pytest.raises(ServerClosed):
        b.submit(np.zeros((1, NF), np.float32))
    batch = b.next_batch(timeout=1.0)
    assert batch == [queued]
    assert b.next_batch(timeout=0.05) is None   # closed and empty


def test_batcher_oversized_request_refused():
    b = DynamicBatcher(["data"], max_batch_size=4, max_wait_ms=1,
                       queue_depth=8)
    with pytest.raises(mx.MXNetError):
        b.submit(np.zeros((5, NF), np.float32))


# -- server ---------------------------------------------------------------
def test_server_end_to_end_parity():
    eng = make_engine(16)
    rng = np.random.RandomState(6)
    x = rng.randn(16, NF).astype(np.float32)
    ref = executor_reference(x)[0]
    with ModelServer(eng, num_workers=2, max_wait_ms=5,
                     warmup=True) as server:
        handles = [server.submit(x[i:i + 1]) for i in range(16)]
        got = np.concatenate(
            [h.result(timeout=30)[0] for h in handles], axis=0)
        stats = server.stats()
    np.testing.assert_allclose(got, ref, rtol=0, atol=1e-6)
    assert stats["served"] == 16
    assert stats["batches"] <= 16          # coalescing happened at all
    assert stats["shed"] == 0
    assert stats["compiled_buckets"] == [1, 2, 4, 8, 16]


def test_server_compiles_stay_bounded_under_mixed_sizes():
    eng = make_engine(8)
    before = compiles_total()
    rng = np.random.RandomState(7)
    with ModelServer(eng, num_workers=1, max_wait_ms=2) as server:
        handles = [server.submit(
            rng.randn(1 + (i % 5), NF).astype(np.float32))
            for i in range(20)]
        for h in handles:
            h.result(timeout=30)
    assert compiles_total() - before <= len(eng.buckets)


def test_server_under_chaos_slow_infer():
    """A chaos-slowed serving.infer backs the queue up; everything
    still completes and the site trips are visible."""
    chaos.configure("serving.infer:kind=sleep,secs=0.03")
    eng = make_engine(8)
    with ModelServer(eng, num_workers=1, max_wait_ms=2,
                     warmup=True) as server:
        handles = [server.submit(np.zeros((1, NF), np.float32))
                   for _ in range(12)]
        outs = [h.result(timeout=30) for h in handles]
    assert all(o[0].shape == (1, NCLASS) for o in outs)
    assert chaos.trip_count("serving.infer") >= 1


def test_server_chaos_fault_propagates_to_requests():
    chaos.configure("serving.infer:kind=raise,n=1")
    eng = make_engine(4)
    with ModelServer(eng, num_workers=1, max_wait_ms=1,
                     warmup=True) as server:
        h = server.submit(np.zeros((1, NF), np.float32))
        with pytest.raises(InjectedFault):
            h.result(timeout=30)
        # the injector's budget (n=1) is spent: service recovers
        h2 = server.submit(np.zeros((1, NF), np.float32))
        assert h2.result(timeout=30)[0].shape == (1, NCLASS)


def test_server_graceful_drain_on_sigterm():
    chaos.configure("serving.infer:kind=sleep,secs=0.05")
    eng = make_engine(8)
    server = ModelServer(eng, num_workers=1, max_wait_ms=1,
                         warmup=True).start()
    with server.handle_signals(signals=(signal.SIGTERM,)):
        inflight = [server.submit(np.zeros((1, NF), np.float32))
                    for _ in range(6)]
        signal.raise_signal(signal.SIGTERM)
        # accepted work FINISHES...
        outs = [h.result(timeout=30) for h in inflight]
        assert all(o[0].shape == (1, NCLASS) for o in outs)
        # ...new work is refused (drain flag set by the handler, the
        # batcher closed by the dispatcher thread)
        with pytest.raises(RequestRejected):
            for _ in range(50):
                server.submit(np.zeros((1, NF), np.float32))
                time.sleep(0.01)
    assert server.drain(timeout=30)
    assert server.stats()["draining"]


def test_server_sheds_under_sustained_overload():
    """The bounded batcher queue must stay authoritative: workers hold
    at most one backlog batch each, so overload reaches queue_depth and
    SHEDS instead of piling up in unbounded worker lists."""
    chaos.configure("serving.infer:kind=sleep,secs=0.05")
    eng = make_engine(2)
    shed_before = obs.REGISTRY.get("serving.shed.count").total()
    with ModelServer(eng, num_workers=1, max_wait_ms=1, queue_depth=2,
                     warmup=True) as server:
        rejected, handles = 0, []
        for _ in range(20):
            try:
                handles.append(
                    server.submit(np.zeros((1, NF), np.float32)))
            except RequestRejected:
                rejected += 1
        for h in handles:
            h.result(timeout=30)
    assert rejected > 0
    assert obs.REGISTRY.get("serving.shed.count").total() > shed_before


def test_server_rejects_deadline_expired_in_worker_backlog():
    """A deadline that runs out AFTER batcher dequeue (while the batch
    waits behind a slow one in the worker backlog) still rejects with
    DeadlineExceeded — never computed, never resolved late."""
    chaos.configure("serving.infer:kind=sleep,secs=0.15")
    eng = make_engine(2)
    with ModelServer(eng, num_workers=1, max_wait_ms=1,
                     warmup=True) as server:
        slow = server.submit(np.zeros((1, NF), np.float32))
        time.sleep(0.03)      # let the first batch reach the worker
        doomed = server.submit(np.zeros((1, NF), np.float32),
                               deadline=Deadline(0.05, what="req"))
        slow.result(timeout=30)
        with pytest.raises(DeadlineExceeded):
            doomed.result(timeout=30)


def test_server_stats_and_least_loaded_dispatch():
    eng = make_engine(8)
    with ModelServer(eng, num_workers=3, max_wait_ms=1) as server:
        handles = [server.submit(np.zeros((2, NF), np.float32))
                   for _ in range(9)]
        for h in handles:
            h.result(timeout=30)
        stats = server.stats()
    assert len(stats["workers"]) == 3
    assert sum(w["served_requests"] for w in stats["workers"]) == 9
    assert stats["request_latency_p50_s"] >= 0.0


def test_server_telemetry_records(tmp_path):
    path = str(tmp_path / "serving.jsonl")
    eng = make_engine(8)
    os.environ["MXTPU_TELEMETRY"] = path
    try:
        with ModelServer(eng, num_workers=1, max_wait_ms=1,
                         warmup=True) as server:
            for _ in range(5):
                server.infer(np.zeros((2, NF), np.float32), timeout=30)
    finally:
        del os.environ["MXTPU_TELEMETRY"]
        telemetry.close_stream()
    allrecs = [json.loads(l) for l in open(path) if l.strip()]
    # the stream is shared: the process's one-off cold-start record
    # (source="compile", docs/compilation.md) may ride along with the
    # per-batch serving records under test
    recs = [r for r in allrecs if r["source"] == "serving"]
    assert recs
    assert all(r["source"] in ("serving", "compile") for r in allrecs)
    assert all("step_time" in r and "fill_ratio" in r for r in recs)
    assert sum(r["requests"] for r in recs) == 5

    # the CI-gate report renders a serving section from the same file
    import importlib
    report = importlib.import_module("tools.telemetry_report")
    summary = report.summarize(report.load_records(path))
    assert summary["serving_requests"] == 5
    assert summary["serving_batches"] == len(recs)
    assert "serving_batch_p95_s" in summary
    assert "serving" in report.format_summary(summary)


# -- c_predict shim -------------------------------------------------------
def _export_checkpoint(tmp_path):
    sym = mlp_symbol()
    params = mlp_params()
    payload = {"arg:%s" % k: v for k, v in params.items()}
    sym_path = str(tmp_path / "model-symbol.json")
    params_path = str(tmp_path / "model-0000.params")
    sym.save(sym_path)
    mx.nd.save(params_path, payload)
    return sym_path, params_path


def test_predictor_bit_parity_with_executor(tmp_path):
    from mxnet_tpu.c_predict import create_predictor
    sym_path, params_path = _export_checkpoint(tmp_path)
    pred = create_predictor(sym_path, params_path,
                            {"data": (4, NF), "softmax_label": (4,)})
    x = np.random.RandomState(8).randn(4, NF).astype(np.float32)
    assert pred.set_input("data", x.tobytes())
    out = pred.forward()
    ref = executor_reference(x)
    np.testing.assert_array_equal(out[0].asnumpy(), ref[0])


def test_predictor_no_gradient_executor_and_no_aliasing(tmp_path):
    from mxnet_tpu.c_predict import create_predictor
    sym_path, params_path = _export_checkpoint(tmp_path)
    pred = create_predictor(sym_path, params_path,
                            {"data": (2, NF), "softmax_label": (2,)})
    assert not hasattr(pred, "_executor")     # engine shim, not a bind
    x = np.random.RandomState(9).randn(2, NF).astype(np.float32)
    buf = x.tobytes()
    pred.set_input("data", buf)
    first = pred.forward()[0].asnumpy()
    # forward again without set_input: same staged buffer, same answer
    # (the donated dispatch must not have consumed the staging array)
    second = pred.forward()[0].asnumpy()
    np.testing.assert_array_equal(first, second)


def test_predictor_set_input_snapshots_buffer(tmp_path):
    # MXPredSetInput copy semantics: the caller may refill one scratch
    # buffer between set_input calls; earlier inputs must not change
    from mxnet_tpu.c_predict import create_predictor
    sym_path, params_path = _export_checkpoint(tmp_path)
    pred = create_predictor(sym_path, params_path,
                            {"data": (2, NF), "softmax_label": (2,)})
    x = np.random.RandomState(20).randn(2, NF).astype(np.float32)
    scratch = bytearray(x.tobytes())
    pred.set_input("data", scratch)
    ref = pred.forward()[0].asnumpy()
    scratch[:] = b"\x00" * len(scratch)      # caller reuses the buffer
    np.testing.assert_array_equal(pred.forward()[0].asnumpy(), ref)


def test_telemetry_report_headline_excludes_serving(tmp_path):
    # a mixed train+serve stream: serving ~ms batch records must not
    # blend into the training step-time percentiles or samples/sec
    import importlib
    report = importlib.import_module("tools.telemetry_report")
    path = tmp_path / "mixed.jsonl"
    rows = [{"source": "module.fit", "step_time": 1.0, "batch_size": 64}
            for _ in range(4)]
    rows += [{"source": "serving", "step_time": 0.001, "batch_size": 8,
              "requests": 8, "fill_ratio": 1.0, "queue_depth": 0,
              "shed_total": 0} for _ in range(100)]
    path.write_text("\n".join(json.dumps(r) for r in rows) + "\n")
    s = report.summarize(report.load_records(str(path)))
    assert s["steps"] == 4
    assert s["step_time_p50_s"] == 1.0          # not diluted to ~1ms
    assert s["samples"] == 4 * 64               # serving rows excluded
    assert s["serving_batches"] == 100          # but fully reported


def test_predictor_dtype_from_bound_array(tmp_path):
    # a float16 parameter sharing the input's name binds the input as
    # float16 — set_input no longer assumes float32
    sym = mlp_symbol()
    params = mlp_params()
    from mxnet_tpu.c_predict import Predictor
    fp16_params = dict(params)
    fp16_params["data"] = mx.nd.array(
        np.zeros((2, NF), np.float16), dtype=np.float16)
    pred = Predictor(sym, fp16_params, {},
                     {"data": (2, NF), "softmax_label": (2,)})
    x16 = np.random.RandomState(10).randn(2, NF).astype(np.float16)
    assert pred.set_input("data", x16.tobytes())
    out = pred.forward()[0]
    assert out.shape == (2, NCLASS)
    with pytest.raises(mx.MXNetError):        # wrong byte count
        pred.set_input("data", x16.astype(np.float32).tobytes())


def test_predictor_independent_leading_dims_and_scalars():
    # the legacy c_predict contract: each declared input is its own
    # fixed-shape buffer — leading dims need not agree and scalar
    # shapes are legal (engine static inputs, no padding)
    from mxnet_tpu.c_predict import Predictor
    data = mx.sym.var("data")
    scale = mx.sym.var("scale")
    out = mx.sym.broadcast_mul(
        mx.sym.FullyConnected(data=data, num_hidden=NCLASS, name="fc"),
        mx.sym.reshape(scale, shape=(1, 1)))
    params = {"fc_weight": mx.nd.array(
        np.random.RandomState(16).randn(NCLASS, NF)
        .astype(np.float32)), "fc_bias": mx.nd.zeros((NCLASS,))}
    pred = Predictor(out, params, {},
                     {"data": (3, NF), "scale": (1,)})
    x = np.random.RandomState(17).randn(3, NF).astype(np.float32)
    pred.set_input("data", x.tobytes())
    pred.set_input("scale", np.float32(2.0).tobytes())
    got = pred.forward()[0].asnumpy()
    exe = out.bind(mx.cpu(), dict(params, data=mx.nd.array(x),
                                  scale=mx.nd.array([2.0])),
                   grad_req="null")
    ref = exe.forward(is_train=False)[0].asnumpy()
    np.testing.assert_array_equal(got, ref)


def test_server_per_device_replica_dispatch():
    # workers place batches + a param copy on their own device — the
    # multi-replica story the docs promise (8 virtual CPU devices here)
    import jax
    eng = make_engine(4)
    with ModelServer(eng, num_workers=2, max_wait_ms=1,
                     warmup=True) as server:
        outs = [server.submit(np.zeros((1, NF), np.float32))
                for _ in range(8)]
        for h in outs:
            h.result(timeout=30)
        stats = server.stats()
    devs = {w["device"] for w in stats["workers"]}
    assert len(devs) == min(2, len(jax.local_devices()))
    # params were replicated onto every worker device
    placed = set(eng._placed)
    worker_ids = {jax.local_devices()[i].id for i in range(2)}
    assert worker_ids <= placed or len(jax.local_devices()) == 1


def test_predictor_errors_match_api():
    from mxnet_tpu.c_predict import Predictor
    with pytest.raises(mx.MXNetError):
        # undeclared argument, no loaded param
        Predictor(mlp_symbol(), {}, {}, {"data": (2, NF)})
    pred = Predictor(mlp_symbol(), mlp_params(), {},
                     {"data": (2, NF), "softmax_label": (2,)})
    with pytest.raises(mx.MXNetError):
        pred.set_input("nope", b"\x00" * 8)


# -- Module routing -------------------------------------------------------
def test_module_predict_parity_engine_vs_legacy():
    x = np.random.RandomState(11).randn(22, NF).astype(np.float32)
    it = mx.io.NDArrayIter(x, None, batch_size=8,
                           last_batch_handle="pad")
    mod = mx.mod.Module(mlp_symbol())
    mod.bind([("data", (8, NF))], for_training=False)
    mod.init_params(mx.init.Xavier())
    out_engine = mod.predict(it).asnumpy()
    assert mod._serving_engine_obj is not None, "engine path not taken"
    os.environ["MXTPU_SERVING_ENGINE"] = "0"
    try:
        it.reset()
        out_legacy = mod.predict(it).asnumpy()
    finally:
        del os.environ["MXTPU_SERVING_ENGINE"]
    assert out_engine.shape == (22, NCLASS)
    np.testing.assert_array_equal(out_engine, out_legacy)


def test_module_env_flag_disables_engine():
    x = np.random.RandomState(12).randn(8, NF).astype(np.float32)
    mod = mx.mod.Module(mlp_symbol())
    mod.bind([("data", (8, NF))], for_training=False)
    mod.init_params(mx.init.Xavier())
    os.environ["MXTPU_SERVING_ENGINE"] = "0"
    try:
        mod.predict(mx.nd.array(x))
        assert mod._serving_engine_obj is None
    finally:
        del os.environ["MXTPU_SERVING_ENGINE"]


def test_module_training_path_untouched():
    # a for_training module never routes through the engine, even for
    # is_train=False eval forwards inside fit/score
    x, y = (np.random.RandomState(13).randn(16, NF).astype(np.float32),
            np.zeros(16, np.float32))
    it = mx.io.NDArrayIter(x, y, batch_size=8,
                           label_name="softmax_label")
    mod = mx.mod.Module(mlp_symbol())
    mod.bind([("data", (8, NF))], [("softmax_label", (8,))],
             for_training=True)
    mod.init_params(mx.init.Xavier())
    mod.score(it, "acc")
    assert mod._serving_engine_obj is None


def test_module_engine_invalidated_on_set_params():
    x = np.random.RandomState(14).randn(8, NF).astype(np.float32)
    mod = mx.mod.Module(mlp_symbol())
    mod.bind([("data", (8, NF))], for_training=False)
    mod.init_params(mx.init.Xavier())
    out1 = mod.predict(mx.nd.array(x)).asnumpy()
    assert mod._serving_engine_obj is not None
    mod.set_params(mlp_params(), {})
    assert mod._serving_engine_obj is None   # stale engine dropped
    out2 = mod.predict(mx.nd.array(x)).asnumpy()
    assert not np.array_equal(out1, out2)    # new params took effect
    np.testing.assert_array_equal(out2, executor_reference(x)[0])


def test_module_iter_predict_depads_via_engine():
    x = np.random.RandomState(15).randn(10, NF).astype(np.float32)
    it = mx.io.NDArrayIter(x, None, batch_size=8,
                           last_batch_handle="pad")
    mod = mx.mod.Module(mlp_symbol())
    mod.bind([("data", (8, NF))], for_training=False)
    mod.init_params(mx.init.Xavier())
    chunks = [outs[0].shape[0] for outs, _, _ in mod.iter_predict(it)]
    assert chunks == [8, 2]                  # tail pad sliced away
