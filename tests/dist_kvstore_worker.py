"""Worker process for test_dist_kvstore: N-process sync semantics.

Mirrors the reference's nightly dist_sync_kvstore.py (:30-34 check_diff
exact equality): every worker pushes a rank-dependent value and asserts
the pulled result equals the exact sum, across dense fp32, fp16, big,
and row_sparse-gathered keys, plus the updater path.
"""
import os
import sys

import numpy as np

# runnable as a plain user command (`tools/launch.py -n N python
# tests/dist_kvstore_worker.py`) without PYTHONPATH games
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main():
    import os
    import jax
    jax.config.update("jax_platforms", "cpu")
    if len(sys.argv) > 3:          # explicit argv mode (direct test run)
        from mxnet_tpu.parallel.kvstore_dist import _enable_cpu_collectives
        _enable_cpu_collectives()  # gloo: real cross-process CPU reduce
        coordinator, nproc, rank = (sys.argv[1], int(sys.argv[2]),
                                    int(sys.argv[3]))
        jax.distributed.initialize(coordinator_address=coordinator,
                                   num_processes=nproc, process_id=rank)
    else:                          # env mode (under tools/launch.py)
        from mxnet_tpu.parallel.kvstore_dist import init_distributed
        init_distributed()
        nproc = int(os.environ["DMLC_NUM_WORKER"])
        rank = int(os.environ["DMLC_WORKER_ID"])
    import mxnet_tpu as mx

    kv = mx.kv.create("dist_sync")
    assert kv.num_workers == nproc, kv.num_workers
    assert kv.rank == rank, (kv.rank, rank)
    nw = kv.num_workers

    # ---- dense fp32, exact equality across repeated rounds ----------
    shape = (3, 4)
    kv.init("dense", mx.nd.zeros(shape))
    for rnd in range(3):
        val = mx.nd.full(shape, rank + 1 + rnd)
        kv.push("dense", val)
        out = mx.nd.zeros(shape)
        kv.pull("dense", out=out)
        expect = sum(r + 1 + rnd for r in range(nw))
        got = out.asnumpy()
        assert (got == expect).all(), (rnd, got[0, 0], expect)

    # ---- fp16 -------------------------------------------------------
    kv.init("half", mx.nd.zeros(shape, dtype="float16"))
    kv.push("half", mx.nd.full(shape, rank + 1, dtype="float16"))
    out = mx.nd.zeros(shape, dtype="float16")
    kv.pull("half", out=out)
    expect = np.float16(sum(r + 1 for r in range(nw)))
    assert (out.asnumpy() == expect).all(), out.asnumpy()[0, 0]
    assert out.asnumpy().dtype == np.float16

    # ---- big array (exercises a second compiled reduce) -------------
    big = (129, 33)
    kv.init("big", mx.nd.zeros(big))
    kv.push("big", mx.nd.ones(big) * (rank + 1))
    out = mx.nd.zeros(big)
    kv.pull("big", out=out)
    assert (out.asnumpy() == sum(r + 1 for r in range(nw))).all()

    # ---- row_sparse pull after dense grad push ----------------------
    emb = (8, 5)
    kv.init("emb", mx.nd.zeros(emb))
    grad = np.zeros(emb, "f")
    grad[rank % 8] = rank + 1
    kv.push("emb", mx.nd.array(grad))
    out = mx.nd.zeros(emb)
    rid = mx.nd.array(np.array([rank % 8], "i"))
    kv.row_sparse_pull("emb", out=out, row_ids=rid)
    expect_row = np.zeros(5, "f")
    expect_row[:] = sum(r + 1 for r in range(nw) if r % 8 == rank % 8)
    assert np.array_equal(out.asnumpy()[rank % 8], expect_row), \
        out.asnumpy()[rank % 8]

    # ---- updater path: identical state evolution on every rank ------
    kv2_key = "w"
    kv.init(kv2_key, mx.nd.ones((4,)))
    kv.set_optimizer(mx.optimizer.SGD(learning_rate=0.1))
    kv.push(kv2_key, mx.nd.full((4,), float(rank)))
    out = mx.nd.zeros((4,))
    kv.pull(kv2_key, out=out)
    # grad sum = sum(ranks); sgd: w - 0.1 * grad (wd 0)
    expect = 1.0 - 0.1 * sum(range(nw))
    got = out.asnumpy()
    assert np.allclose(got, expect, atol=1e-6), (got, expect)

    # ---- 2-bit gradient compression over the wire -------------------
    # (reference: nightly dist_sync_kvstore.py compressed section +
    # gradient_compression.h semantics). Threshold 1.0, each worker
    # pushes 0.7 per round; the error-feedback residual makes the
    # decoded per-worker sequence [0, 1.0, 1.0] (acc 0.7 -> 1.4 -> 1.1),
    # so the pulled (stored, not accumulated) value is [0, nw, nw].
    kvc = mx.kv.create("dist_sync")
    kvc.set_gradient_compression({"type": "2bit", "threshold": 1.0})
    cshape = (64, 4)
    kvc.init("cmp", mx.nd.zeros(cshape))
    for rnd, per_worker in enumerate([0.0, 1.0, 1.0]):
        kvc.push("cmp", mx.nd.full(cshape, 0.7))
        out = mx.nd.zeros(cshape)
        kvc.pull("cmp", out=out)
        expect = per_worker * nw
        got = out.asnumpy()
        assert np.allclose(got, expect, atol=1e-5), (rnd, got[0, 0], expect)
    # bytes on the wire must be 16x smaller than the dense fp32 payload
    dense_bytes = int(np.prod(cshape)) * 4
    assert kvc.last_wire_bytes * 16 <= dense_bytes + 64, \
        (kvc.last_wire_bytes, dense_bytes)

    # ---- row_sparse push/pull WITHOUT densify -----------------------
    # (reference: kvstore_dist.h:262 / kvstore_dist_server.h
    # DataHandleRowSparse). Each worker pushes 2 rows of a 64-row table;
    # only (indices, values) cross the wire; pull gathers rows into a
    # RowSparseNDArray whose storage is 2 rows, not 64.
    from mxnet_tpu.ndarray.sparse import RowSparseNDArray
    kvs = mx.kv.create("dist_sync")  # fresh store: no updater attached
    T, D = 64, 3
    kvs.init("rsp", mx.nd.zeros((T, D)))
    my_rows = np.array([rank, (rank + 17) % T], "int32")
    vals = np.full((2, D), float(rank + 1), "float32")
    g = RowSparseNDArray(mx.nd.array(vals), mx.nd.array(my_rows), (T, D))
    kvs.push("rsp", g)
    sout = RowSparseNDArray(mx.nd.zeros((2, D)),
                            mx.nd.array(np.array([0, 0], "i")), (T, D))
    kvs.row_sparse_pull("rsp", out=sout, row_ids=mx.nd.array(my_rows))
    assert sout.data.shape == (2, D), sout.data.shape  # rows, not table
    expect0 = sum(r + 1 for r in range(nw)
                  if rank in (r % T, (r + 17) % T))
    got0 = np.asarray(sout.data._data)[0]
    assert np.allclose(got0, expect0), (rank, got0, expect0)
    # wire carried 2 rows (idx+val), not the table
    assert kvs.last_wire_bytes <= 2 * (4 + D * 4) + 64, kvs.last_wire_bytes
    assert kvs.last_wire_bytes < T * D * 4

    # ---- bucketed push_all: bit-identical parity + one collective ---
    # per bucket (ISSUE 3 acceptance). Integer-valued grads make the
    # cross-process sums exact, so "bit-identical" is associativity-
    # proof; the comparison below is still full bitwise equality.
    from mxnet_tpu.observability import registry as obs
    rng = np.random.RandomState(1234 + rank)
    bshapes = [((11,), "float32"), ((4, 7), "float32"),
               ((130,), "float32"), ((3, 5, 2), "float32"),
               ((64,), "float16"), ((9, 3), "float16")]
    kb = mx.kv.create("dist_sync")           # bucketed (default 4 MB)
    kp = mx.kv.create("dist_sync")
    kp.set_bucket_size_mb(0)                 # per-key reference path
    bkeys = ["bk%d" % i for i in range(len(bshapes))]
    bgrads = []
    for key, (shp, dt) in zip(bkeys, bshapes):
        kb.init(key, mx.nd.zeros(shp, dtype=dt))
        kp.init(key, mx.nd.zeros(shp, dtype=dt))
        bgrads.append(mx.nd.array(
            rng.randint(-4, 5, shp).astype(dt), dtype=dt))
    prios = [-i for i in range(len(bkeys))]
    ar_calls = obs.REGISTRY.get("kvstore.allreduce.calls")
    bcount = obs.REGISTRY.get("kvstore.bucket.count")
    c0, b0 = ar_calls.total(), bcount.total()
    kb.push_all(bkeys, bgrads, priorities=prios)
    bucketed_calls = ar_calls.total() - c0
    # allreduce calls per step == bucket count, not parameter count:
    # 6 tiny dense keys collapse into one bucket per dtype
    assert bucketed_calls == bcount.total() - b0, \
        (bucketed_calls, bcount.total() - b0)
    assert bucketed_calls == 2, bucketed_calls
    assert obs.REGISTRY.get("kvstore.bucket.fill_ratio").total_count() > 0
    assert obs.REGISTRY.get(
        "kvstore.bucket.pack.seconds").total_count() > 0
    c1 = ar_calls.total()
    kp.push_all(bkeys, bgrads, priorities=prios)
    assert ar_calls.total() - c1 == len(bkeys), ar_calls.total() - c1
    for key, (shp, dt) in zip(bkeys, bshapes):
        ob = mx.nd.zeros(shp, dtype=dt)
        op = mx.nd.zeros(shp, dtype=dt)
        kb.pull(key, out=ob)
        kp.pull(key, out=op)
        a, b = ob.asnumpy(), op.asnumpy()
        assert a.dtype == b.dtype and a.tobytes() == b.tobytes(), key
    print("BUCKET_PARITY_OK_%d" % rank)

    # ---- bucketed parity under 2-bit compression --------------------
    # error-feedback residuals are per key in BOTH paths, so three
    # rounds evolve identically; bucket framing must not change a bit
    kbc = mx.kv.create("dist_sync")
    kpc = mx.kv.create("dist_sync")
    kpc.set_bucket_size_mb(0)
    for s in (kbc, kpc):
        s.set_gradient_compression({"type": "2bit", "threshold": 1.0})
    cshapes = [(40,), (7, 9), (33,)]
    ckeys = ["ck%d" % i for i in range(len(cshapes))]
    for key, shp in zip(ckeys, cshapes):
        kbc.init(key, mx.nd.zeros(shp))
        kpc.init(key, mx.nd.zeros(shp))
    rngc = np.random.RandomState(77 + rank)
    cprios = [-i for i in range(len(ckeys))]
    for rnd in range(3):
        cgrads = [mx.nd.array(rngc.randint(-3, 4, shp).astype("float32"))
                  for shp in cshapes]
        cc0 = ar_calls.total()
        kbc.push_all(ckeys, cgrads, priorities=cprios)
        assert ar_calls.total() - cc0 == 1  # 3 keys, ONE fused collective
        kpc.push_all(ckeys, cgrads, priorities=cprios)
        for key, shp in zip(ckeys, cshapes):
            ob = mx.nd.zeros(shp)
            op = mx.nd.zeros(shp)
            kbc.pull(key, out=ob)
            kpc.pull(key, out=op)
            assert ob.asnumpy().tobytes() == op.asnumpy().tobytes(), \
                (rnd, key)
    print("COMPRESSED_BUCKET_PARITY_OK_%d" % rank)

    # ---- fused one-program step + ZeRO-1 over gloo ------------------
    # (ISSUE 15 acceptance): the same model/data trained three ways —
    # fused step with ZeRO-1-sharded optimizer state, fused step with
    # replicated state, and the staged bucketed path — must produce
    # bit-identical parameters on every rank, and the sharded run's
    # state must all-gather back bit-identically at save_states.
    from mxnet_tpu import gluon, autograd
    from mxnet_tpu.observability import registry as obs

    def _train(fused, zero1, tag):
        os.environ["MXTPU_FUSED_STEP"] = "1" if fused else "0"
        os.environ["MXTPU_ZERO1"] = "1" if zero1 else "0"
        mx.random.seed(7)
        net = gluon.nn.Dense(5, prefix="z1%s_" % tag)
        net.initialize()
        x0 = mx.nd.array(np.random.RandomState(1).randn(2, 9)
                         .astype("f"))
        net(x0)
        kvt = mx.kv.create("dist_sync")
        tr = gluon.Trainer(net.collect_params(), "sgd",
                           {"learning_rate": 0.05, "momentum": 0.9},
                           kvstore=kvt)
        loss_fn = gluon.loss.L2Loss()
        for s in range(3):
            r = np.random.RandomState(1000 + 10 * s + rank)
            x = mx.nd.array(r.randn(2, 9).astype("f"))
            y = mx.nd.array(r.randn(2, 5).astype("f"))
            with autograd.record():
                loss = loss_fn(net(x), y)
            loss.backward()
            tr.step(2 * nw)
        params = [p.data().asnumpy()
                  for p in net.collect_params().values()]
        states = tr._updaters[0].get_states()
        return params, states

    disp = obs.REGISTRY.counter("train.step.dispatches")
    d0 = disp.total()
    pz, sz = _train(True, True, "a")
    zero1_dispatches = disp.total() - d0
    d0 = disp.total()
    pr, sr = _train(True, False, "b")
    fused_dispatches = disp.total() - d0
    ps, ss = _train(False, False, "c")
    os.environ["MXTPU_ZERO1"] = "0"
    for a, b, c in zip(pz, pr, ps):
        assert a.tobytes() == b.tobytes(), "zero1 vs replicated drift"
        assert b.tobytes() == c.tobytes(), "fused vs staged drift"
    # sharded momentum all-gathered at get_states == replicated run's
    assert sz == sr == ss, "optimizer state drift across paths"

    # mid-run MXTPU_ZERO1 toggle: the carried sharded state must flush
    # at the knob boundary (full-signature keyed), never feed a
    # replicated program — and numerics stay bit-exact
    def _train_toggle(tag):
        os.environ["MXTPU_FUSED_STEP"] = "1"
        mx.random.seed(7)
        net = gluon.nn.Dense(5, prefix="z1%s_" % tag)
        net.initialize()
        net(mx.nd.array(np.random.RandomState(1).randn(2, 9)
                        .astype("f")))
        kvt = mx.kv.create("dist_sync")
        tr = gluon.Trainer(net.collect_params(), "sgd",
                           {"learning_rate": 0.05, "momentum": 0.9},
                           kvstore=kvt)
        loss_fn = gluon.loss.L2Loss()
        for s in range(4):
            os.environ["MXTPU_ZERO1"] = "1" if s < 2 else "0"
            r = np.random.RandomState(1000 + 10 * s + rank)
            x = mx.nd.array(r.randn(2, 9).astype("f"))
            y = mx.nd.array(r.randn(2, 5).astype("f"))
            with autograd.record():
                loss = loss_fn(net(x), y)
            loss.backward()
            tr.step(2 * nw)
        return ([p.data().asnumpy()
                 for p in net.collect_params().values()],
                tr._updaters[0].get_states())
    pt, st_t = _train_toggle("d")
    os.environ["MXTPU_ZERO1"] = "0"
    # 4 toggle steps == first 3 replicated steps + one more would need
    # a 4th reference step; instead compare against a fresh 4-step
    # replicated run
    def _train4(tag):
        os.environ["MXTPU_FUSED_STEP"] = "1"
        mx.random.seed(7)
        net = gluon.nn.Dense(5, prefix="z1%s_" % tag)
        net.initialize()
        net(mx.nd.array(np.random.RandomState(1).randn(2, 9)
                        .astype("f")))
        kvt = mx.kv.create("dist_sync")
        tr = gluon.Trainer(net.collect_params(), "sgd",
                           {"learning_rate": 0.05, "momentum": 0.9},
                           kvstore=kvt)
        loss_fn = gluon.loss.L2Loss()
        for s in range(4):
            r = np.random.RandomState(1000 + 10 * s + rank)
            x = mx.nd.array(r.randn(2, 9).astype("f"))
            y = mx.nd.array(r.randn(2, 5).astype("f"))
            with autograd.record():
                loss = loss_fn(net(x), y)
            loss.backward()
            tr.step(2 * nw)
        return ([p.data().asnumpy()
                 for p in net.collect_params().values()],
                tr._updaters[0].get_states())
    p4, s4 = _train4("e")
    for a, b in zip(pt, p4):
        assert a.tobytes() == b.tobytes(), "zero1 toggle drift"
    assert st_t == s4, "zero1 toggle state drift"
    print("ZERO1_TOGGLE_OK_%d" % rank)
    # the fused runs issued exactly ONE device program per step
    assert zero1_dispatches == 3, zero1_dispatches
    assert fused_dispatches == 3, fused_dispatches
    # the ZeRO-1 state gather was a real observed all-gather
    ag = obs.REGISTRY.get("zero1.allgather.seconds")
    assert ag is not None and ag.total_count() > 0
    print("ZERO1_PARITY_OK_%d" % rank)

    kv.barrier()
    print("WORKER_%d_OK" % rank)


if __name__ == "__main__":
    main()
